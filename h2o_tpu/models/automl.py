"""AutoML — automatic model search and ensembling.

Analog of `h2o-automl/` (10,222 LoC): `ai/h2o/automl/AutoML.java` orchestrates a
modeling plan of steps (`ModelingPlans.java:57-69` DEFAULT order: XGBoost
defaults, GLM, DRF, GBM defaults, DeepLearning, grids, then StackedEnsembles),
under time/model budgets (`WorkAllocations.java`), ranks everything on a
`Leaderboard` (`hex/leaderboard/Leaderboard.java:256` default sort: binomial →
auc, multinomial → mean_per_class_error, regression → mean_residual_deviance)
and records an `EventLog` (`ai/h2o/automl/events/`).

Step parameter presets mirror the reference's providers
(`modeling/GBMStepsProvider.java:81-123` def_1..def_5 max_depth 6/7/8/10/15,
`DRFStepsProvider` def + XRT, `DeepLearningStepsProvider` 1-3 layer presets,
`GLMStepsProvider` lambda search, `StackedEnsembleStepsProvider` best-of-family
+ all). Every base model trains with k-fold CV and kept holdout predictions so
the ensembles stack leak-free — same contract as the reference.

The executor here is a host loop: each build already saturates the mesh, so the
reference's cluster-parallel step executor collapses to sequential dispatch
with budget checks between steps.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from ..backend.jobs import Job
from ..backend.kvstore import Keyed, STORE
from ..frame.frame import Frame
from ..frame.vec import Vec
from .model_base import ModelBuilder, Parameters


# ---------------------------------------------------------------------------
# Event log (`ai/h2o/automl/events/EventLog.java`)
# ---------------------------------------------------------------------------
class EventLog:
    def __init__(self):
        self.events: list[dict] = []

    def log(self, stage: str, message: str, level: str = "Info"):
        self.events.append({"timestamp": time.time(), "level": level,
                            "stage": stage, "message": message})

    def as_frame(self) -> Frame:
        cols = {k: np.asarray([e[k] for e in self.events], dtype=object)
                for k in ("level", "stage", "message")}
        cols["timestamp"] = np.asarray(
            [e["timestamp"] for e in self.events], dtype=object)
        names = ["timestamp", "level", "stage", "message"]
        return Frame(names, [Vec(None, len(self.events), type="string",
                                 host_data=cols[n]) for n in names])

    def as_table(self):
        """`water/api/schemas3/TwoDimTableV3` shape for `/99/AutoML/{id}`."""
        from ..utils.twodimtable import TwoDimTable

        if not self.events:
            return TwoDimTable(table_header="Event Log")
        return TwoDimTable.from_dict("Event Log", {
            k: [e[k] for e in self.events]
            for k in ("timestamp", "level", "stage", "message")})


# ---------------------------------------------------------------------------
# Leaderboard (`hex/leaderboard/Leaderboard.java`)
# ---------------------------------------------------------------------------
_HIGHER_BETTER = {"auc", "aucpr", "r2", "accuracy"}


def _default_sort_metric(category: str) -> str:
    if category == "Binomial":
        return "auc"
    if category == "Multinomial":
        return "mean_per_class_error"
    return "rmse"  # stand-in for mean_residual_deviance (equal ranking for
                   # gaussian; the reference uses deviance here)


class Leaderboard:
    METRIC_COLS = {
        "Binomial": ["auc", "logloss", "aucpr", "mean_per_class_error", "rmse", "mse"],
        "Multinomial": ["mean_per_class_error", "logloss", "rmse", "mse"],
        "Regression": ["rmse", "mse", "mae", "r2"],
    }

    def __init__(self, category: str, sort_metric: str | None = None):
        self.category = category
        self.sort_metric = (sort_metric or _default_sort_metric(category)).lower()
        self.models: list = []

    def add(self, model):
        self.models.append(model)
        self.models = self.sorted()

    def _metric(self, m, name):
        mm = (m.output.cross_validation_metrics or m.output.validation_metrics
              or m.output.training_metrics)
        v = getattr(mm, name, None)
        return None if v is None or (isinstance(v, float) and np.isnan(v)) else v

    def sorted(self):
        decr = self.sort_metric in _HIGHER_BETTER
        worst = -np.inf if decr else np.inf

        def key(m):
            v = self._metric(m, self.sort_metric)
            return worst if v is None else v

        return sorted(self.models, key=key, reverse=decr)

    @property
    def leader(self):
        return self.models[0] if self.models else None

    def as_frame(self) -> Frame:
        cols: dict[str, list] = {"model_id": []}
        metric_names = self.METRIC_COLS.get(self.category,
                                            self.METRIC_COLS["Regression"])
        for n in metric_names:
            cols[n] = []
        for m in self.models:
            cols["model_id"].append(m.key)
            for n in metric_names:
                v = self._metric(m, n)
                cols[n].append(np.nan if v is None else float(v))
        vecs = [Vec(None, len(self.models), type="string",
                    host_data=np.asarray(cols["model_id"], dtype=object))]
        names = ["model_id"] + metric_names
        for n in metric_names:
            vecs.append(Vec.from_numpy(np.asarray(cols[n], dtype=np.float32)))
        return Frame(names, vecs)

    def as_table(self):
        """Leaderboard as a TwoDimTable (`/99/Leaderboards/{project}`)."""
        from ..utils.twodimtable import TwoDimTable

        metric_names = self.METRIC_COLS.get(self.category,
                                            self.METRIC_COLS["Regression"])
        ms = self.sorted()
        if not ms:
            return TwoDimTable(table_header="Leaderboard")
        cols = {"model_id": [m.key for m in ms]}
        for n in metric_names:
            cols[n] = [self._metric(m, n) for m in ms]
        return TwoDimTable.from_dict("Leaderboard", cols)


# ---------------------------------------------------------------------------
# Modeling steps (`ai/h2o/automl/modeling/*StepsProvider.java`)
# ---------------------------------------------------------------------------
@dataclass
class Step:
    algo: str              # GBM | DRF | XRT | GLM | DeepLearning | XGBoost |
                           # StackedEnsemble | grid variants
    id: str                # def_1, grid_1, best_of_family, ...
    make: Callable         # (automl) -> model(s) | None
    weight: int = 10       # relative work allocation (`WorkAllocations`)


def _default_plan() -> list[Step]:
    """The DEFAULT modeling plan order (`ModelingPlans.java:57-69`)."""
    plan: list[Step] = []
    # XGBoost defaults (our XGBoost is the retargeted histogram engine)
    for sid, over in (("def_2", dict(max_depth=10, min_rows=5)),
                      ("def_1", dict(max_depth=5, min_rows=10)),
                      ("def_3", dict(max_depth=15, min_rows=3))):
        plan.append(Step("XGBoost", sid,
                         _model_step("xgboost", dict(ntrees=50, **over))))
    plan.append(Step("GLM", "def_1", _glm_step()))
    plan.append(Step("DRF", "def_1", _model_step("drf", dict(ntrees=50))))
    # GBM def_1..def_5 (`GBMStepsProvider.java:81-123`)
    for sid, depth in (("def_1", 6), ("def_2", 7), ("def_3", 8),
                       ("def_4", 10), ("def_5", 15)):
        plan.append(Step("GBM", sid, _model_step(
            "gbm", dict(ntrees=50, max_depth=depth, sample_rate=0.8,
                        col_sample_rate_per_tree=0.8))))
    plan.append(Step("DeepLearning", "def_1", _model_step(
        "deeplearning", dict(hidden=[10, 10, 10], epochs=10))))
    plan.append(Step("XRT", "def_1", _model_step("xrt", dict(ntrees=50))))
    # one random grid over GBM (`GBMStepsProvider.java:137` search space)
    plan.append(Step("GBM", "grid_1", _gbm_grid_step(), weight=60))
    # exploitation: retrain the best GBM with learn-rate annealing
    # (`GBMStepsProvider.java:170-182` GBMExploitationStep lr_annealing)
    plan.append(Step("GBM", "lr_annealing", _gbm_exploitation_step(), weight=10))
    plan.append(Step("StackedEnsemble", "best_of_family", _se_step(True), weight=5))
    plan.append(Step("StackedEnsemble", "all", _se_step(False), weight=10))
    return plan


def _gbm_exploitation_step():
    def make(aml: "H2OAutoML"):
        best_gbm = next((m for m in aml.leaderboard.sorted()
                         if m.algo_name == "gbm"), None)
        if best_gbm is None:
            return None
        params = best_gbm.params.clone(
            learn_rate_annealing=0.99, ntrees=max(best_gbm.params.ntrees, 100),
            training_frame=aml.training_frame)
        from .gbm import GBM

        return [GBM(params).train_model()]
    return make


def _builder_for(algo: str):
    from . import deeplearning, drf, gbm, glm, xgboost

    return {
        "gbm": (gbm.GBM, gbm.GBMParameters),
        "drf": (drf.DRF, drf.DRFParameters),
        "xrt": (drf.XRT, drf.XRTParameters),
        "xgboost": (xgboost.XGBoost, xgboost.XGBoostParameters),
        "glm": (glm.GLM, glm.GLMParameters),
        "deeplearning": (deeplearning.DeepLearning,
                         deeplearning.DeepLearningParameters),
    }[algo]


def _model_step(algo: str, overrides: dict):
    def make(aml: "H2OAutoML"):
        cls, pcls = _builder_for(algo)
        valid = {f.name for f in __import__("dataclasses").fields(pcls)}
        params = pcls(**aml._common_params(),
                      **{k: v for k, v in overrides.items() if k in valid})
        return [cls(params).train_model()]
    return make


def _glm_step():
    def make(aml: "H2OAutoML"):
        from .glm import GLM, GLMParameters

        params = GLMParameters(**aml._common_params(), lambda_search=True,
                               nlambdas=10)
        return [GLM(params).train_model()]
    return make


def _gbm_grid_step():
    def make(aml: "H2OAutoML"):
        from .gbm import GBM, GBMParameters
        from .grid import GridSearch, SearchCriteria

        base = GBMParameters(**aml._common_params(), ntrees=50)
        hyper = {"max_depth": [3, 5, 7, 9, 11, 13, 15, 17],
                 "sample_rate": [0.5, 0.8, 1.0],
                 "col_sample_rate": [0.4, 0.7, 1.0],
                 "min_rows": [1, 5, 10, 15, 30]}
        budget = aml._remaining_budget()
        criteria = SearchCriteria(
            strategy="RandomDiscrete",
            max_models=max(1, (aml.max_models - aml._model_count())
                           if aml.max_models else 5),
            max_runtime_secs=budget if budget is not None else 0.0,
            seed=aml.seed if aml.seed is not None else -1)
        grid = GridSearch(GBM, base, hyper, criteria).train()
        return grid.models
    return make


def _se_step(best_of_family: bool):
    def make(aml: "H2OAutoML"):
        from .ensemble import StackedEnsemble, StackedEnsembleParameters

        bases = [m for m in aml.leaderboard.models
                 if m.algo_name != "stackedensemble"
                 and m.output.cv_holdout_predictions is not None]
        if best_of_family:
            seen, picked = set(), []
            for m in aml.leaderboard.sorted():
                if m.algo_name == "stackedensemble":
                    continue
                if m.output.cv_holdout_predictions is None:
                    continue
                if m.algo_name not in seen:
                    seen.add(m.algo_name)
                    picked.append(m)
            bases = picked
        if len(bases) < 2:
            return None
        params = StackedEnsembleParameters(
            training_frame=aml.training_frame, response_column=aml.y,
            base_models=bases, seed=aml.seed if aml.seed is not None else -1)
        return [StackedEnsemble(params).train_model()]
    return make


# ---------------------------------------------------------------------------
# The orchestrator (h2o-py `H2OAutoML` surface over `AutoML.java`)
# ---------------------------------------------------------------------------
class H2OAutoML(Keyed):
    def __init__(self, max_models: int = 0, max_runtime_secs: float = 0.0,
                 max_runtime_secs_per_model: float = 0.0, nfolds: int = 5,
                 seed: int | None = None, project_name: str | None = None,
                 include_algos: list | None = None,
                 exclude_algos: list | None = None,
                 sort_metric: str | None = None,
                 stopping_rounds: int = 3, stopping_tolerance: float = 1e-3,
                 stopping_metric: str = "AUTO",
                 keep_cross_validation_predictions: bool = True,
                 modeling_plan: list | None = None,
                 ignored_columns: list | None = None,
                 priority: str = "batch"):
        super().__init__(key=project_name, prefix="automl")
        self.priority = priority     # workload lane the plan runs under
        self.ignored_columns = list(ignored_columns or [])
        if not max_models and not max_runtime_secs:
            max_runtime_secs = 3600.0  # the reference's default total budget
        self.max_models = max_models
        self.max_runtime_secs = max_runtime_secs
        self.max_runtime_secs_per_model = max_runtime_secs_per_model
        self.nfolds = nfolds
        self.seed = seed
        self.include_algos = include_algos
        self.exclude_algos = exclude_algos or []
        self.sort_metric = sort_metric
        self.stopping_rounds = stopping_rounds
        self.stopping_tolerance = stopping_tolerance
        self.stopping_metric = stopping_metric
        self.keep_cv_preds = keep_cross_validation_predictions
        self.plan = modeling_plan or _default_plan()
        self.event_log = EventLog()
        self.leaderboard: Leaderboard | None = None
        self.training_frame: Frame | None = None
        self.y: str | None = None
        self._t0 = None
        self.job: Job | None = None
        STORE.put_keyed(self)

    # -- budget helpers ------------------------------------------------------
    def _remaining_budget(self) -> float | None:
        if not self.max_runtime_secs:
            return None
        return max(0.0, self.max_runtime_secs - (time.time() - self._t0))

    def _model_count(self) -> int:
        """Base-model count — Stacked Ensembles don't count toward max_models
        (the reference's `max_models` contract)."""
        if not self.leaderboard:
            return 0
        return sum(1 for m in self.leaderboard.models
                   if m.algo_name != "stackedensemble")

    def _budget_exhausted(self, step: "Step | None" = None) -> bool:
        is_se = step is not None and step.algo == "StackedEnsemble"
        if not is_se and self.max_models and self._model_count() >= self.max_models:
            return True
        rem = self._remaining_budget()
        return rem is not None and rem <= 0

    def _common_params(self) -> dict:
        return dict(training_frame=self.training_frame, response_column=self.y,
                    ignored_columns=list(self.ignored_columns),
                    nfolds=self.nfolds,
                    keep_cross_validation_predictions=self.keep_cv_preds,
                    fold_assignment="Modulo",  # shared folds → stackable
                    seed=self.seed if self.seed is not None else -1,
                    max_runtime_secs=self.max_runtime_secs_per_model,
                    stopping_rounds=self.stopping_rounds,
                    stopping_tolerance=self.stopping_tolerance,
                    stopping_metric=self.stopping_metric)

    def _algo_allowed(self, algo: str) -> bool:
        fam = {"XRT": "DRF"}.get(algo, algo)
        if self.include_algos is not None:
            return fam in self.include_algos or algo in self.include_algos
        return fam not in self.exclude_algos and algo not in self.exclude_algos

    # -- train (the h2o-py surface) ------------------------------------------
    def train(self, y: str | None = None, training_frame: Frame | None = None,
              job: Job | None = None, **kw) -> "H2OAutoML":
        if kw:
            raise ValueError(f"unsupported train() arguments: {sorted(kw)}")
        if training_frame is None or y is None:
            raise ValueError("y and training_frame are required")
        self.training_frame = training_frame
        self.y = y
        resp = training_frame.vec(y)
        if resp.is_categorical():
            category = "Binomial" if len(resp.domain) == 2 else "Multinomial"
        else:
            category = "Regression"
        self.leaderboard = Leaderboard(category, self.sort_metric)
        self._t0 = time.time()
        log = self.event_log
        log.log("Workflow", f"AutoML build started: {self.key}")
        # an externally supplied Job (the /99/AutoMLBuilder route's) receives
        # the per-step progress updates instead of an orphaned inner job
        if job is not None:
            job.work = float(len(self.plan))
            self.job = job
            self._run_plan(log)
        else:
            # direct-API runs get a REAL started job too: the plan loop
            # dispatches through the workload manager (priority-laned,
            # tenant-stamped, heartbeating via the per-step updates, and
            # visible in /3/Workload) instead of an orphan Job that
            # never left CREATED
            from .. import workload

            self.job = Job("AutoML", work=float(len(self.plan)))
            workload.submit(self.job, lambda: self._run_plan(log),
                            background=False,
                            cost_bytes=workload.frame_cost(training_frame),
                            priority=self.priority)
            self.job.join()      # surface a failed step loop typed
        log.log("Workflow",
                f"AutoML build done: {self._model_count()} models, "
                f"leader={self.leader.key if self.leader else None}")
        return self

    def _run_plan(self, log) -> None:
        for step in self.plan:
            if self._budget_exhausted(step):
                log.log("Workflow", f"budget exhausted; skipping {step.algo}_{step.id}")
                continue  # later SE steps may still run (don't count to max_models)
            if not self._algo_allowed(step.algo):
                log.log("ModelBuilding", f"skipping {step.algo} ({step.id}): excluded")
                continue
            label = f"{step.algo}_{step.id}"
            log.log("ModelBuilding", f"starting {label}")
            try:
                models = step.make(self)
            except Exception as e:
                log.log("ModelBuilding", f"{label} failed: {e!r}", level="Warn")
                models = None
            for m in models or []:
                self.leaderboard.add(m)
                log.log("ModelBuilding",
                        f"{label} -> {m.key} "
                        f"({self.leaderboard.sort_metric}="
                        f"{self.leaderboard._metric(m, self.leaderboard.sort_metric)})")
            self.job.update(1.0)

    # -- results -------------------------------------------------------------
    @property
    def leader(self):
        return self.leaderboard.leader if self.leaderboard else None

    def predict(self, fr: Frame) -> Frame:
        if self.leader is None:
            raise ValueError("no models trained")
        return self.leader.predict(fr)

    def get_leaderboard(self) -> Frame:
        return self.leaderboard.as_frame()
