"""Generic model — import an arbitrary MOJO as a first-class scoring model.

Analog of `hex/generic/` (GenericModel/GenericModelBuilder, 1,456 LoC): the
MOJO zip is parsed by the standalone reader (`..mojo.reader.MojoModel`) and
wrapped in the engine's `Model` interface so `predict()` / metrics work over
Frames like any trained model.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..backend.jobs import Job
from ..frame.frame import Frame
from ..frame.vec import Vec
from ..mojo.reader import MojoModel as _MojoScorer
from .model_base import Model, ModelBuilder, ModelOutput, Parameters


@dataclass
class GenericParameters(Parameters):
    path: str | None = None  # `hex/generic/GenericModelParameters` _mojo_key


class GenericModel(Model):
    algo_name = "generic"

    def __init__(self, params, output, scorer: _MojoScorer, key=None):
        self.scorer = scorer
        super().__init__(params, output, key=key)

    def predict(self, fr: Frame) -> Frame:
        raw = self.scorer.predict(fr)
        if raw.ndim == 1:  # regression value or cluster label
            return Frame(["predict"], [Vec.from_numpy(raw.astype(np.float32))])
        return self._predictions_frame(raw.astype(np.float32), fr.nrow)

    def score0(self, X):
        return self.scorer.score(np.asarray(X))


class Generic(ModelBuilder):
    """`hex/generic/Generic.java` — builds a model from a MOJO file."""

    algo_name = "generic"
    supervised = False

    def _validate(self):
        if not getattr(self.params, "path", None):
            raise ValueError("generic: 'path' to a MOJO file is required")

    def build_impl(self, job: Job) -> Model:
        path = self.params.path
        # `h2o.upload_mojo` hands the PostFile upload KEY as the path —
        # resolve it to the spooled bytes (the _mojo_key seam of
        # `hex/generic/GenericModelParameters`)
        from ..backend.kvstore import STORE
        from ..io.upload import UploadedFile

        obj = STORE.get(path) if isinstance(path, str) else None
        if isinstance(obj, UploadedFile):
            path = obj.path
        scorer = _MojoScorer.load(path)
        output = ModelOutput()
        feats = (scorer.columns[:-1] if scorer.supervised
                 else list(scorer.columns))
        output.names = feats
        output.domains = {n: scorer.domains[i] for i, n in enumerate(feats)}
        if scorer.supervised:
            output.response_domain = scorer.domains[len(scorer.columns) - 1]
            self.params.response_column = scorer.response_column
        output.model_category = scorer.category
        return GenericModel(self.params, output, scorer)


def import_mojo(path: str) -> GenericModel:
    """`h2o.import_mojo` analog."""
    return Generic(GenericParameters(path=path)).train_model()
