"""XGBoost-equivalent — the `hex/tree/xgboost` parameter surface retargeted
onto our own histogram tree engine.

The reference wraps the native xgboost4j JNI build behind a full ModelBuilder
(`h2o-extensions/xgboost/src/main/java/hex/tree/xgboost/XGBoostModel.java:67,148`
— params eta/subsample/colsample_bytree/... aliased to the H2O names, backends
auto/CPU/GPU with `tree_method=gpu_hist`). Per SURVEY.md §7.6e the TPU rebuild
does NOT wrap native XGBoost; `tree_method=hist` semantics (global quantile
binning, Newton leaf values -G/(H+λ), L1 soft-thresholding via α) are exactly
what our shared tree engine implements, so this builder is a parameter-mapping
layer over it — the same relationship the reference has between its H2O-facing
params and the rabit workers, minus the JNI.

Supported param aliases (mirroring `XGBoostV3.XGBoostParametersV3`):
  ntrees/n_estimators, eta/learn_rate, max_depth, min_child_weight/min_rows,
  subsample/sample_rate, colsample_bytree/col_sample_rate_per_tree,
  colsample_bylevel/col_sample_rate, reg_lambda, reg_alpha, max_bins,
  booster (gbtree | dart — a real DART driver with rate_drop/skip_drop/
  one_drop/normalize_type incl. multinomial + checkpoint continuation, see
  `XGBoost._build_dart` | gblinear — the penalized linear model retargeted
  onto the GLM elastic-net path, see `XGBoost._build_gblinear`), tree_method
  (ignored: always hist), backend (ignored: always TPU).
"""

from __future__ import annotations

from dataclasses import dataclass

from .gbm import GBM, GBMParameters


@dataclass
class XGBoostParameters(GBMParameters):
    """Mirrors `hex/tree/xgboost/XGBoostModel.XGBoostParameters` field names.

    xgboost-default field overrides (eta 0.3, min_child_weight 1, lambda 1)
    are declared as dataclass defaults; the xgboost-native spellings below are
    sentinel-valued aliases that, when set, overwrite their H2O-named twin and
    then reset to the sentinel — so ``dataclasses.replace`` (clone / grid
    search) re-running ``__post_init__`` is a no-op and an explicitly-passed
    H2O-named value is never clobbered.
    """

    learn_rate: float = 0.3   # xgboost default eta
    min_rows: float = 1.0     # xgboost default min_child_weight
    reg_lambda: float = 1.0   # xgboost default lambda
    reg_alpha: float = 0.0

    # DART booster knobs (`XGBoostParameters._rate_drop` et al.)
    rate_drop: float = 0.0
    skip_drop: float = 0.0
    one_drop: bool = False
    normalize_type: str = "tree"   # tree|forest

    # GBLinear booster knobs (`XGBoostParameters` gblinear section) —
    # feature_selector/top_k/updater tune xgboost's shotgun coordinate
    # descent; the GLM elastic-net retarget subsumes them (accepted, unused)
    feature_selector: str = "cyclic"
    top_k: int = 0
    updater: str | None = None

    # xgboost-native spellings; sentinel = "not set"
    n_estimators: int = 0          # alias of ntrees
    eta: float = -1.0              # alias of learn_rate
    min_child_weight: float = -1.0  # alias of min_rows
    subsample: float = -1.0        # alias of sample_rate
    colsample_bytree: float = -1.0  # alias of col_sample_rate_per_tree
    colsample_bylevel: float = -1.0  # alias of col_sample_rate
    max_bins: int = 0              # alias of nbins
    gamma: float = -1.0            # min split loss == min_split_improvement
    booster: str = "gbtree"
    tree_method: str = "hist"
    backend: str = "auto"

    def __post_init__(self):
        # resolve aliases the way the reference resolves dual-named params,
        # then park the alias back at its sentinel (idempotent re-init)
        if self.n_estimators > 0:
            self.ntrees, self.n_estimators = self.n_estimators, 0
        if self.eta >= 0:
            self.learn_rate, self.eta = self.eta, -1.0
        if self.min_child_weight >= 0:
            self.min_rows, self.min_child_weight = self.min_child_weight, -1.0
        if self.subsample > 0:
            self.sample_rate, self.subsample = self.subsample, -1.0
        if self.colsample_bytree > 0:
            self.col_sample_rate_per_tree, self.colsample_bytree = \
                self.colsample_bytree, -1.0
        if self.colsample_bylevel > 0:
            self.col_sample_rate, self.colsample_bylevel = \
                self.colsample_bylevel, -1.0
        if self.max_bins > 0:
            self.nbins, self.max_bins = self.max_bins, 0
        if self.gamma >= 0:
            self.min_split_improvement, self.gamma = self.gamma, -1.0


class XGBoost(GBM):
    algo_name = "xgboost"

    def _tree_config(self, K, nbins=None):
        import dataclasses
        cfg = super()._tree_config(K, nbins=nbins)
        return dataclasses.replace(cfg, reg_alpha=self.params.reg_alpha)

    def build_impl(self, job):
        booster = (self.params.booster or "gbtree").lower()
        if booster == "dart":
            model = self._build_dart(job)
        elif booster == "gblinear":
            model = self._build_gblinear(job)
        else:
            model = super().build_impl(job)
        # the model object is engine-native (GBM/GLM class), but the wire
        # reports the builder's algo like the reference (`XGBoostV3` schema)
        model.algo_override = "xgboost"
        return model

    def _build_gblinear(self, job):
        """booster='gblinear' (`XGBoostModel.java:56,150`): xgboost's
        L1/L2-penalized LINEAR model (shotgun coordinate descent over the
        same reg_alpha/reg_lambda penalty). TPU-native retarget: the GLM
        elastic-net path — identical objective, solved by the engine's
        sharded-Gram IRLSM/COD instead of per-feature shotgun updates.
        xgboost's penalties are ABSOLUTE (not per-row-normalized):
        alpha·λ·N = reg_alpha and (1−alpha)·λ·N = reg_lambda fixes the
        (lambda, alpha) mapping."""
        from .glm import GLM, GLMParameters

        p = self.params
        fr = p.training_frame
        nrow = max(int(fr.nrow), 1)
        l1, l2 = max(p.reg_alpha, 0.0), max(p.reg_lambda, 0.0)
        tot = l1 + l2
        lam = tot / nrow
        alpha = (l1 / tot) if tot > 0 else 0.0
        _, category, _ = self.response_info()
        family = {"Binomial": "binomial", "Multinomial": "multinomial",
                  "Regression": "gaussian"}[category]
        gp = GLMParameters(
            training_frame=fr, response_column=p.response_column,
            validation_frame=p.validation_frame,
            weights_column=p.weights_column, offset_column=p.offset_column,
            ignored_columns=list(p.ignored_columns or []),
            family=family, alpha=alpha, lambda_=lam,
            solver="COORDINATE_DESCENT" if category != "Multinomial"
            else "IRLSM",
            standardize=False,  # xgboost's linear booster fits raw features
            max_iterations=max(p.ntrees, 50),  # boosting rounds ≙ sweeps
            seed=p.seed)
        sub = GLM(gp)
        model = sub.build_impl(job)
        model.booster = "gblinear"
        return model

    def _build_dart(self, job):
        """DART booster (Rashmi & Gilad-Bachrach 2015; xgboost `booster=
        dart`): each round drops a random subset of the existing trees,
        fits the new tree against the DROPPED ensemble's residuals, then
        renormalizes so the expected prediction is unchanged —
        normalize_type="tree": new-tree weight lr/(k+lr), dropped trees
        scaled k/(k+lr); "forest": lr/(1+lr) and 1/(1+lr). skip_drop
        short-circuits a round to plain boosting; one_drop forces at least
        one dropped tree. Predictions are linear in leaf values, so the
        final forest stores each tree's leaves pre-scaled by its weight —
        scoring, MOJO export and SHAP all work unchanged.

        The engine builds each round's tree at rate 1.0 with the carried
        margin = f0 + Σ_{i∉D} w_i·tree_i; the new tree's raw contribution
        falls out of the train step (f_out − f_in), so each round costs
        |D| single-tree evaluations plus one tree build.

        Multinomial drops whole ROUNDS (all K class-trees of a round share
        one weight — xgboost's dart drops by boosting round); checkpoint
        continuation restarts the dropout trajectory over the prior's BAKED
        trees (leaves already carry their weights, so prior trees enter with
        weight 1.0 and future drop-rescales just multiply their leaves)."""
        import dataclasses
        import time as _t

        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..backend.jobs import Job  # noqa: F401  (signature parity)
        from .gbm import GBMModel, _assemble_forest, _metrics_raw
        from .model_base import ModelOutput, make_metrics
        from .tree.engine import make_train_fn, predict_forest

        # DART re-evaluates dropped trees over raw thresholds every
        # iteration (dropped_sum below) — it keeps the stacked f32 matrix
        s = self._setup_build(need_raw=True)
        p = s.p
        K = s.K
        rng = np.random.default_rng(
            p.seed if p.seed not in (-1, None) else 1234)
        cfg = s.cfg
        f0 = s.f0

        parts, weights = [], []
        prior = None
        if p.checkpoint is not None:
            prior = self._resolve_checkpoint(p.checkpoint)
            if p.ntrees <= prior.ntrees:
                raise ValueError(
                    f"checkpoint model already has {prior.ntrees} trees; "
                    f"ntrees must exceed that (got {p.ntrees})")
            for fld, ours, theirs in (
                    ("max_depth", p.max_depth, prior.cfg.max_depth),
                    ("nbins", p.nbins,
                     getattr(prior.params, "nbins", prior.cfg.nbins)),
                    ("nclasses", K, prior.cfg.nclass),
                    ("drf_mode", False, prior.cfg.drf_mode)):
                if ours != theirs:
                    raise ValueError(
                        f"checkpoint incompatible: {fld} differs "
                        f"(checkpoint={theirs}, request={ours})")
            p = self.params = dataclasses.replace(p, checkpoint=prior.key)
            # continuation trees speak the prior forest's split language
            prior_sets = bool(getattr(prior.cfg, "use_sets", False))
            if cfg.use_sets != prior_sets:
                cfg = dataclasses.replace(cfg, use_sets=prior_sets)
            pf = prior.forest
            keysx = ("feat", "thr", "nanL", "val", "gain", "catd")
            for t in range(prior.ntrees):
                parts.append(tuple(
                    (pf[k][t:t + 1] if k in pf else
                     jnp.zeros(pf["feat"][t:t + 1].shape + (1,),
                               jnp.float32)) for k in keysx))
                weights.append(1.0)
            f0 = prior.f0

        # trees build UNSCALED (engine's effective rate = cfg.learn_rate x
        # per-tree rate; DART owns the scaling via the weight vector), and
        # unclipped: max_abs_leafnode_pred caps the FINAL stored leaf, so
        # the clip applies at weight-bake time below (GBM.java:716 parity)
        cfg1 = dataclasses.replace(cfg, ntrees=1, learn_rate=1.0,
                                   max_abs_leafnode_pred=float("inf"))
        train_fn = make_train_fn(cfg1, s.grad_fn, s.mesh,
                                 cache_key=s.grad_key)
        keys = jax.random.split(
            jax.random.PRNGKey(p.seed if p.seed not in (-1, None)
                               else 1234), p.ntrees)
        one_rate = jnp.ones((1,), dtype=jnp.float32)

        lr = float(p.learn_rate)
        # f0 broadcast to the carried-margin shape ((K, R) for multinomial)
        f0b = (jnp.asarray(f0)[:, None] if K > 1
               else jnp.asarray(f0, jnp.float32))
        S = jnp.zeros_like(s.f)        # sum of w_i * raw_i over built trees
        if prior is not None:
            fprev = prior._raw_f(s.X)
            S = (fprev.T if K > 1 else fprev).astype(jnp.float32) - f0b
        history = []
        stop_series: list = []
        interval = min(p.score_tree_interval or p.ntrees, p.ntrees)
        last_scored = n_prior = len(parts)

        use_sets = cfg.use_sets

        def dropped_sum(idxs):
            """sum_{i in D} w_i * raw_i in ONE forest evaluation: stack the
            dropped trees with their weights pre-multiplied into the leaves
            — O(1) extra memory, no per-tree prediction cache. Multinomial
            trees carry a (D, K, N) class axis; the (R, K) output transposes
            onto the carried-margin layout."""
            feat = jnp.concatenate([parts[i][0] for i in idxs], axis=0)
            thr = jnp.concatenate([parts[i][1] for i in idxs], axis=0)
            nanL = jnp.concatenate([parts[i][2] for i in idxs], axis=0)
            val = jnp.concatenate(
                [jnp.asarray(parts[i][3]) * jnp.float32(weights[i])
                 for i in idxs], axis=0)
            catd = (jnp.concatenate([parts[i][5] for i in idxs], axis=0)
                    if use_sets else None)
            out = predict_forest(s.X, feat, thr, nanL, val,
                                 cfg.max_depth, catd=catd,
                                 iscat=s.iscat_dev if use_sets else None,
                                 nedges=s.nedges_dev if use_sets else None)
            return out.T if K > 1 else out

        output = ModelOutput()
        output.names = list(s.names)
        output.domains = {n: s.fr.vec(n).domain for n in s.names}
        output.response_domain = (list(s.resp_domain) if s.resp_domain
                                  else None)
        output.model_category = s.category

        def bake(parts_w):
            # bake each tree's DART weight into its stored leaf values; the
            # max_abs_leafnode_pred cap applies on the FINAL stored leaf
            # (the reference clips after the effective rate,
            # GBM.java:716-719)
            cap = float(getattr(p, "max_abs_leafnode_pred", float("inf"))
                        or float("inf"))
            out = []
            for (feat, thr, nanL, val, gain, catd), wgt in parts_w:
                v = jnp.asarray(val) * jnp.float32(wgt)
                if np.isfinite(cap):
                    v = jnp.clip(v, -cap, cap)
                out.append((feat, thr, nanL, v, gain, catd))
            return out

        for t in range(n_prior, p.ntrees):
            job.check_cancelled()
            if history and job.time_exceeded():  # keep the partial forest
                break
            dropped: list[int] = []
            if t > 0 and rng.random() >= p.skip_drop:
                dropped = [i for i in range(t)
                           if rng.random() < p.rate_drop]
                if not dropped and p.one_drop:
                    dropped = [int(rng.integers(t))]
            if dropped:
                drop_raw = dropped_sum(dropped)
                margin = f0b + S - drop_raw
            else:
                drop_raw = None
                margin = f0b + S
            f_out, _os, _oc, trees = train_fn(
                s.Xb, s.y_k, s.w, margin.astype(jnp.float32), s.edges,
                s.edge_ok, keys[t:t + 1], one_rate, s.mono, s.imat,
                s.iscat_dev, s.nedges_dev)
            raw_new = f_out - margin
            k = len(dropped)
            if k == 0:
                w_new, scale_dropped = lr, 1.0
            elif (p.normalize_type or "tree").lower() == "forest":
                w_new, scale_dropped = lr / (1.0 + lr), 1.0 / (1.0 + lr)
            else:
                w_new = lr / (k + lr)
                scale_dropped = k / (k + lr)
            if dropped:
                # S' = S + (scale-1) * sum w_i raw_i — the dropped sum is
                # already in hand, no re-evaluation
                S = S + (scale_dropped - 1.0) * drop_raw
                for i in dropped:
                    weights[i] *= scale_dropped
            S = S + w_new * raw_new
            parts.append(trees)
            weights.append(w_new)
            if (t + 1) % interval == 0 or t + 1 == p.ntrees:
                m = make_metrics(
                    s.category, s.ym,
                    _metrics_raw(s.category, s.dist, f0b + S,
                                 False, t + 1),
                    None if p.weights_column is None else s.w,
                    auc_type=p.auc_type, domain=output.response_domain)
                history.append({"timestamp": _t.time(),
                                "number_of_trees": t + 1,
                                "training_metrics": m})
                # incremental progress by trees actually elapsed since the
                # last update (the final round may be shorter than interval)
                job.update((t + 1 - last_scored) / p.ntrees)
                last_scored = t + 1
                if p.export_checkpoints_dir:
                    # in-training snapshots carry the CURRENT weights baked
                    self._export_snapshot(
                        p, output, bake(zip(parts, weights)), f0, s.dist,
                        cfg, s.is_cat, t + 1, m, cat_nedges=s.nedges_np)
                if self._should_stop(m, stop_series):
                    break

        scaled = bake(zip(parts, weights))
        output.scoring_history = history
        output.training_metrics = history[-1]["training_metrics"]
        forest = _assemble_forest(scaled)
        output.variable_importances = self._varimp(forest, s.names)
        model = GBMModel(p, output, forest, f0, s.dist, cfg, s.is_cat,
                         cat_nedges=s.nedges_np)
        if getattr(p, "calibrate_model", False):
            # same Platt step as the gbtree path — leaves are already baked,
            # so the margin the calibrator sees is the final DART margin
            model.calib = self._fit_calibration(model, s.category)
        if p.validation_frame is not None:
            output.validation_metrics = model.model_performance(
                p.validation_frame)
        return model

