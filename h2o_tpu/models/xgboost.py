"""XGBoost-equivalent — the `hex/tree/xgboost` parameter surface retargeted
onto our own histogram tree engine.

The reference wraps the native xgboost4j JNI build behind a full ModelBuilder
(`h2o-extensions/xgboost/src/main/java/hex/tree/xgboost/XGBoostModel.java:67,148`
— params eta/subsample/colsample_bytree/... aliased to the H2O names, backends
auto/CPU/GPU with `tree_method=gpu_hist`). Per SURVEY.md §7.6e the TPU rebuild
does NOT wrap native XGBoost; `tree_method=hist` semantics (global quantile
binning, Newton leaf values -G/(H+λ), L1 soft-thresholding via α) are exactly
what our shared tree engine implements, so this builder is a parameter-mapping
layer over it — the same relationship the reference has between its H2O-facing
params and the rabit workers, minus the JNI.

Supported param aliases (mirroring `XGBoostV3.XGBoostParametersV3`):
  ntrees/n_estimators, eta/learn_rate, max_depth, min_child_weight/min_rows,
  subsample/sample_rate, colsample_bytree/col_sample_rate_per_tree,
  colsample_bylevel/col_sample_rate, reg_lambda, reg_alpha, max_bins,
  booster (gbtree|dart — dart falls back to gbtree), tree_method (ignored:
  always hist), backend (ignored: always TPU).
"""

from __future__ import annotations

from dataclasses import dataclass

from .gbm import GBM, GBMParameters


@dataclass
class XGBoostParameters(GBMParameters):
    """Mirrors `hex/tree/xgboost/XGBoostModel.XGBoostParameters` field names.

    xgboost-default field overrides (eta 0.3, min_child_weight 1, lambda 1)
    are declared as dataclass defaults; the xgboost-native spellings below are
    sentinel-valued aliases that, when set, overwrite their H2O-named twin and
    then reset to the sentinel — so ``dataclasses.replace`` (clone / grid
    search) re-running ``__post_init__`` is a no-op and an explicitly-passed
    H2O-named value is never clobbered.
    """

    learn_rate: float = 0.3   # xgboost default eta
    min_rows: float = 1.0     # xgboost default min_child_weight
    reg_lambda: float = 1.0   # xgboost default lambda
    reg_alpha: float = 0.0

    # xgboost-native spellings; sentinel = "not set"
    n_estimators: int = 0          # alias of ntrees
    eta: float = -1.0              # alias of learn_rate
    min_child_weight: float = -1.0  # alias of min_rows
    subsample: float = -1.0        # alias of sample_rate
    colsample_bytree: float = -1.0  # alias of col_sample_rate_per_tree
    colsample_bylevel: float = -1.0  # alias of col_sample_rate
    max_bins: int = 0              # alias of nbins
    gamma: float = -1.0            # min split loss == min_split_improvement
    booster: str = "gbtree"
    tree_method: str = "hist"
    backend: str = "auto"

    def __post_init__(self):
        # resolve aliases the way the reference resolves dual-named params,
        # then park the alias back at its sentinel (idempotent re-init)
        if self.n_estimators > 0:
            self.ntrees, self.n_estimators = self.n_estimators, 0
        if self.eta >= 0:
            self.learn_rate, self.eta = self.eta, -1.0
        if self.min_child_weight >= 0:
            self.min_rows, self.min_child_weight = self.min_child_weight, -1.0
        if self.subsample > 0:
            self.sample_rate, self.subsample = self.subsample, -1.0
        if self.colsample_bytree > 0:
            self.col_sample_rate_per_tree, self.colsample_bytree = \
                self.colsample_bytree, -1.0
        if self.colsample_bylevel > 0:
            self.col_sample_rate, self.colsample_bylevel = \
                self.colsample_bylevel, -1.0
        if self.max_bins > 0:
            self.nbins, self.max_bins = self.max_bins, 0
        if self.gamma >= 0:
            self.min_split_improvement, self.gamma = self.gamma, -1.0


class XGBoost(GBM):
    algo_name = "xgboost"

    def _tree_config(self, K, nbins=None):
        import dataclasses
        cfg = super()._tree_config(K, nbins=nbins)
        return dataclasses.replace(cfg, reg_alpha=self.params.reg_alpha)
