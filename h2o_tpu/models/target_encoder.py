"""TargetEncoder — supervised categorical encoding.

Analog of `h2o-extensions/target-encoder/` (9,644 LoC,
`ai/h2o/targetencoding/TargetEncoderModel.java`,`TargetEncoderHelper.java`):
replace each categorical column with the (blended) per-level mean of the target,
with leakage control:

- ``None``        — encode with the full-data per-level mean.
- ``LeaveOneOut`` — each row is encoded excluding its own target
  (`(sum − yᵢ)/(cnt − 1)`).
- ``KFold``       — rows in fold f are encoded from the other folds'
  sums (`TargetEncoderModel.java:331`).

Blending (`TargetEncoderHelper.java:236-247`):
``P = 𝝺(n)·ȳ_level + (1−𝝺(n))·ȳ  with  𝝺(n) = 1/(1+exp((k−n)/f))``
(k = inflection_point, f = smoothing). Optional uniform noise in
[−noise, +noise] on training transforms (`TargetEncoderModel.java:44-47`).

TPU-native structure: per-level {sum, count} aggregation is one
``segment_sum`` per (column × fold) on device — the groupby MRTask
(`TargetEncoderHelper` group-by) collapses into a scatter-add; the encode
step is a gather + elementwise blend, fused by XLA. Multiclass targets get
one encoded column per non-baseline class (as in the reference since 3.32).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..backend.jobs import Job
from ..frame.frame import Frame
from ..frame.vec import T_CAT, Vec
from .model_base import Model, ModelBuilder, ModelOutput, Parameters

NO_FOLD = -1


@dataclass
class TargetEncoderParameters(Parameters):
    columns_to_encode: list = field(default_factory=list)  # default: all cats
    data_leakage_handling: str = "None"  # None|LeaveOneOut|KFold
    blending: bool = False
    inflection_point: float = 10.0
    smoothing: float = 20.0
    noise: float = 0.01
    keep_original_categorical_columns: bool = True


class TargetEncoderModel(Model):
    algo_name = "targetencoder"

    def __init__(self, params, output, encodings, prior, target_domain,
                 nfolds_seen, key=None):
        super().__init__(params, output, key=key)
        # encodings[col] = dict(num=(card, C), den=(card,), per-fold variants)
        self.encodings = encodings
        self.prior = prior  # (C,) per target-class prior mean (C=1 regression)
        self.target_domain = target_domain
        self.nfolds_seen = nfolds_seen

    # -- encoding table as a Frame (the reference's encoding map frames) ------
    def encoding_map_frame(self, col: str) -> Frame:
        e = self.encodings[col]
        dom = self.output.domains[col]
        cols = {col: Vec.from_numpy(np.arange(len(dom), dtype=np.float32),
                                    type=T_CAT, domain=dom),
                "numerator": Vec.from_numpy(np.asarray(e["num"][:, 0])),
                "denominator": Vec.from_numpy(np.asarray(e["den"]))}
        return Frame(list(cols), list(cols.values()))

    def _encoded_names(self, col: str) -> list[str]:
        if len(self.target_domain or []) > 2:
            return [f"{col}_{c}_te" for c in self.target_domain[1:]]
        return [f"{col}_te"]

    def transform(self, fr: Frame, as_training: bool = False,
                  noise: float | None = None) -> Frame:
        """Apply encodings. ``as_training`` honours the leakage strategy
        (LOO/KFold need the target/fold columns present); otherwise `None`
        strategy is forced (`TargetEncoderModel.java:324`)."""
        p: TargetEncoderParameters = self.params
        strategy = p.data_leakage_handling if as_training else "None"
        noise = p.noise if noise is None else noise
        if not as_training:
            noise = 0.0
        rng = np.random.default_rng(p.seed if p.seed not in (-1, None) else None)

        out = Frame(fr.names, fr.vecs)
        y = oky = fold = None
        if strategy in ("LeaveOneOut", "KFold"):
            y = self._target_matrix(fr)
            oky = ~np.isnan(fr.vec(p.response_column).to_numpy())
        if strategy == "KFold":
            fold = fr.vec(p.fold_column).to_numpy().astype(np.int64)

        for col in self.encodings:
            enc = self.encodings[col]
            v = fr.vec(col)
            codes_np = v.to_numpy()
            train_dom = self.output.domains[col]
            if v.domain != train_dom and v.domain is not None:
                remap = {l: i for i, l in enumerate(train_dom)}
                lut = np.array([remap.get(l, -1) for l in v.domain], dtype=np.float32)
                ok = ~np.isnan(codes_np)
                codes_np = np.where(ok, lut[np.where(ok, codes_np, 0).astype(np.int64)],
                                    np.nan)
                codes_np[codes_np < 0] = np.nan
            card = len(train_dom)
            ok = ~np.isnan(codes_np)
            codes = np.where(ok, codes_np, card).astype(np.int64)  # card = NA slot

            num, den = np.asarray(enc["num"]), np.asarray(enc["den"])
            if strategy == "KFold" and enc.get("fold_num") is not None:
                f_num, f_den = enc["fold_num"], enc["fold_den"]  # (F, card, C),(F, card)
                fidx = np.clip(fold, 0, f_num.shape[0] - 1)
                # per-row out-of-fold sums: total − own fold
                row_num = num[codes] - f_num[fidx, codes]
                row_den = den[codes] - f_den[fidx, codes]
            elif strategy == "LeaveOneOut":
                sub = ok & oky  # only rows that contributed to the sums
                row_num = num[codes] - np.where(sub[:, None], np.nan_to_num(y), 0.0)
                row_den = den[codes] - sub.astype(np.float64)
            else:
                row_num = num[codes]
                row_den = den[codes]

            row_den = row_den[:, None]  # (n, 1) against row_num's (n, C)
            with np.errstate(invalid="ignore", divide="ignore"):
                post = row_num / np.maximum(row_den, 1e-300)
            k, f, use_b = self.params.inflection_point, self.params.smoothing, \
                self.params.blending
            prior = self.prior
            if use_b:
                lam = 1.0 / (1.0 + np.exp(np.clip((k - row_den) / max(f, 1e-12),
                                                  -60, 60)))
                val = lam * post + (1.0 - lam) * prior[None, :]
            else:
                val = post
            val = np.where(row_den > 0, val, prior[None, :])
            # NA level gets the prior unless it was seen in training (NA slot
            # is part of the table, matching the reference's NA-as-level).
            if noise and noise > 0:
                val = val + rng.uniform(-noise, noise, size=val.shape)
            names = self._encoded_names(col)
            for j, name in enumerate(names):
                out.add(name, Vec.from_numpy(val[:, j].astype(np.float32)))
            if not self.params.keep_original_categorical_columns:
                out.remove(col)
        return out

    def transform_training(self, fr: Frame) -> Frame:
        return self.transform(fr, as_training=True)

    def _target_matrix(self, fr: Frame) -> np.ndarray:
        yv = fr.vec(self.params.response_column)
        y = yv.to_numpy()
        if self.target_domain and len(self.target_domain) > 2:
            C = len(self.target_domain) - 1
            out = np.zeros((len(y), C))
            for c in range(C):
                out[:, c] = (y == c + 1).astype(np.float64)
            return out
        if self.target_domain:
            return (y == 1).astype(np.float64)[:, None]
        return y.astype(np.float64)[:, None]

    def score0(self, X):
        raise NotImplementedError("TargetEncoder transforms frames; use transform()")

    def predict(self, fr: Frame) -> Frame:
        return self.transform(fr, as_training=False)


class TargetEncoder(ModelBuilder):
    algo_name = "targetencoder"
    supports_cv = False  # fold_column feeds the KFold leakage strategy

    def build_impl(self, job: Job) -> TargetEncoderModel:
        p: TargetEncoderParameters = self.params
        fr = p.training_frame
        cols = list(p.columns_to_encode) or [
            n for n in fr.names
            if fr.vec(n).is_categorical() and n not in
            (p.response_column, p.fold_column)]
        if p.data_leakage_handling == "KFold" and not p.fold_column:
            raise ValueError("KFold leakage handling requires fold_column")

        yv = fr.vec(p.response_column)
        target_domain = list(yv.domain) if yv.is_categorical() else None
        y_np = yv.to_numpy()
        if target_domain and len(target_domain) > 2:
            C = len(target_domain) - 1
            Y = np.stack([(y_np == c + 1).astype(np.float64) for c in range(C)],
                         axis=1)
        elif target_domain:
            Y = (y_np == 1).astype(np.float64)[:, None]
        else:
            Y = y_np.astype(np.float64)[:, None]
        ok_y = ~np.isnan(y_np)
        prior = Y[ok_y].mean(axis=0)

        fold = None
        nfolds = 0
        if p.data_leakage_handling == "KFold":
            fold = fr.vec(p.fold_column).to_numpy().astype(np.int64)
            nfolds = int(fold.max()) + 1

        encodings = {}
        doms = {}
        for col in cols:
            v = fr.vec(col)
            if not v.is_categorical():
                continue
            dom = list(v.domain)
            card = len(dom)
            codes_np = v.to_numpy()
            okc = ~np.isnan(codes_np) & ok_y
            codes = np.where(okc, codes_np, card).astype(np.int64)
            # device scatter-add: per-level target sums + counts in one pass
            seg = jnp.asarray(codes)
            Yd = jnp.asarray(np.where(okc[:, None], Y, 0.0))
            num = jax.ops.segment_sum(Yd, seg, num_segments=card + 1)
            den = jax.ops.segment_sum(jnp.asarray(okc.astype(np.float64)), seg,
                                      num_segments=card + 1)
            enc = {"num": np.asarray(num), "den": np.asarray(den)}
            if nfolds:
                f_num = np.zeros((nfolds, card + 1, Y.shape[1]))
                f_den = np.zeros((nfolds, card + 1))
                seg2 = jnp.asarray(codes + np.clip(fold, 0, nfolds - 1) * (card + 1))
                fn = jax.ops.segment_sum(Yd, seg2, num_segments=nfolds * (card + 1))
                fd = jax.ops.segment_sum(jnp.asarray(okc.astype(np.float64)), seg2,
                                         num_segments=nfolds * (card + 1))
                enc["fold_num"] = np.asarray(fn).reshape(nfolds, card + 1, -1)
                enc["fold_den"] = np.asarray(fd).reshape(nfolds, card + 1)
            else:
                enc["fold_num"] = enc["fold_den"] = None
            encodings[col] = enc
            doms[col] = dom

        out = ModelOutput()
        out.model_category = "TargetEncoder"
        out.names = list(encodings)
        out.domains = doms
        out.response_domain = target_domain
        return TargetEncoderModel(p, out, encodings, prior, target_domain, nfolds)
