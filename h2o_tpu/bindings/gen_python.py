"""Generate Python estimator classes from live schema metadata.

Reference: `h2o-bindings/bin/gen_python.py` — the reference introspects a
running server's `/3/Metadata/schemas` and emits `h2o-py/h2o/estimators/*`;
here the same loop reads `/3/ModelBuilders/{algo}` parameter metadata
(served by `models/registry.param_metadata`) and emits one
`H2O<Algo>Estimator` subclass per registered algorithm, with typed keyword
arguments, defaults, and docstrings. Run either against a live server
(`--url`) or in-process off the registry (no server needed):

    python -m h2o_tpu.bindings.gen_python --dest /tmp/estimators
"""

from __future__ import annotations

import keyword
import os


_HEADER = '''"""Generated estimator bindings — do not edit by hand.

Produced by h2o_tpu.bindings.gen_python (the `h2o-bindings/bin/gen_python.py`
analog) from live /3/ModelBuilders parameter metadata.
"""

from h2o_tpu.api.client import H2OEstimator


'''

_CLASS_TMPL = '''class H2O{cls}Estimator(H2OEstimator):
    """{doc}

    Parameters (from /3/ModelBuilders/{algo} schema metadata):
{params_doc}
    """

    algo = "{algo}"

    def __init__(self{sig}):
        kwargs = {{k: v for k, v in locals().items()
                  if k not in ("self", "__class__") and v is not None}}
        H2OEstimator.__init__(self, **kwargs)


'''


def _camel(algo: str) -> str:
    special = {"gbm": "GradientBoosting", "drf": "RandomForest",
               "glm": "GeneralizedLinear", "xgboost": "XGBoost",
               "kmeans": "KMeans", "pca": "PrincipalComponentAnalysis",
               "svd": "SingularValueDecomposition",
               "glrm": "GeneralizedLowRank", "coxph": "CoxProportionalHazards",
               "naivebayes": "NaiveBayes", "deeplearning": "DeepLearning",
               "isolationforest": "IsolationForest",
               "extendedisolationforest": "ExtendedIsolationForest",
               "upliftdrf": "UpliftRandomForest",
               "targetencoder": "TargetEncoder",
               "stackedensemble": "StackedEnsemble",
               "rulefit": "RuleFit", "psvm": "SupportVectorMachine",
               "gam": "GeneralizedAdditive", "anovaglm": "ANOVAGLM",
               "modelselection": "ModelSelection", "isotonicregression":
               "IsotonicRegression", "decisiontree": "DecisionTree",
               "adaboost": "AdaBoost", "word2vec": "Word2vec",
               "aggregator": "Aggregator", "infogram": "Infogram",
               "generic": "Generic"}
    return special.get(algo, algo.capitalize())


def _pydefault(v):
    if isinstance(v, float):
        # repr(inf) is the bare name `inf` — not valid source
        if v != v:
            return 'float("nan")'
        if v == float("inf"):
            return 'float("inf")'
        if v == float("-inf"):
            return 'float("-inf")'
    return repr(v)


def _fetch_metadata(url: str | None):
    """[(algo, [ {name,type,default_value}, ... ]), ...]"""
    if url:
        import json
        import urllib.request

        def get(path):
            with urllib.request.urlopen(url.rstrip("/") + path,
                                        timeout=60) as r:
                return json.loads(r.read().decode())

        algos = sorted(get("/3/ModelBuilders")["model_builders"])
        return [(a, get(f"/3/ModelBuilders/{a}")["parameters"])
                for a in algos]
    from ..models import registry

    return [(a, registry.param_metadata(a))
            for a in sorted(registry.algo_names())]


def generate_source(url: str | None = None) -> str:
    """The generated module text (one estimator class per algo)."""
    out = [_HEADER]
    for algo, params in _fetch_metadata(url):
        names, sig_parts, doc_lines = set(), [], []
        for prm in params:
            name = prm["name"]
            if name in ("training_frame", "validation_frame") \
                    or keyword.iskeyword(name) or not name.isidentifier():
                continue
            if name in names:
                continue
            names.add(name)
            default = prm.get("default_value")
            if isinstance(default, str) and default.startswith("<"):
                default = None
            sig_parts.append(f"{name}={_pydefault(default)}")
            doc_lines.append(f"        {name}: {prm.get('type', 'Any')} "
                             f"(default {default!r})")
        sig = ""
        if sig_parts:
            # one kwarg per line keeps the generated file reviewable
            joined = ",\n                 ".join(sig_parts)
            sig = f", *,\n                 {joined}"
        out.append(_CLASS_TMPL.format(
            cls=_camel(algo), algo=algo,
            doc=f"Builder for the '{algo}' algorithm.",
            params_doc="\n".join(doc_lines) or "        (none)",
            sig=sig))
    return "".join(out)


def generate(dest_dir: str, url: str | None = None) -> str:
    """Write `estimators_gen.py` under dest_dir; returns the file path."""
    os.makedirs(dest_dir, exist_ok=True)
    path = os.path.join(dest_dir, "estimators_gen.py")
    with open(path, "w") as f:
        f.write(generate_source(url))
    return path


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--dest", default="./bindings_out")
    ap.add_argument("--url", default=None,
                    help="live server to introspect (default: in-process)")
    a = ap.parse_args()
    print(generate(a.dest, a.url))
