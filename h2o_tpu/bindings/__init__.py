"""Client-bindings codegen — `h2o-bindings/` analog (generator producing
estimator classes from live schema metadata, `h2o-bindings/bin/gen_python.py`)."""
