"""Vec — a distributed column held in TPU HBM.

Reference: `water/fvec/Vec.java` (1,783 LoC) — a chunked, compressed, typed
distributed column with lazy rollup stats. The TPU-native design (SURVEY.md §7.2):

- storage is ONE row-sharded ``jax.Array`` (float32), padded to an equal-shard
  length; padding rows and missing values are both NaN. This replaces the 21
  per-chunk compression codecs (`water/fvec/C*.java`) — a deliberate divergence:
  HBM arrays want fixed-width vectorizable layouts, and bf16/int8 casts at the
  point of use recover the bandwidth that byte-packing bought on the JVM.
- the chunk layout / ESPC machinery (`fvec/Vec.java:152-166`) becomes "equal
  padded shards + global nrow"; per-row masks are derived on device.
- types mirror the reference (`fvec/Vec.java:12-103`): numeric, int, categorical
  (int codes + host-side domain), time (ms since epoch), string (host-side —
  variable-length data has no business in HBM).
- rollups (min/max/mean/sigma/NA-count/zero-count) are computed lazily by one
  fused device reduction and cached until the data version changes — mirroring
  `water/fvec/RollupStats.java` (572 LoC) without the volatile-task dance.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..backend.kvstore import Keyed, make_key
from ..parallel import mesh as meshmod

T_NUM = "real"
T_INT = "int"
T_CAT = "enum"
T_TIME = "time"
T_STR = "string"
T_UUID = "uuid"
T_BAD = "bad"  # all-NA column

NUMERIC_TYPES = (T_NUM, T_INT, T_CAT, T_TIME, T_BAD)


class Rollups:
    """Cached summary stats — analog of `water/fvec/RollupStats.java`."""

    __slots__ = ("mins", "maxs", "mean", "sigma", "nacnt", "zerocnt", "nrow", "is_int")

    def __init__(self, mins, maxs, mean, sigma, nacnt, zerocnt, nrow, is_int):
        self.mins = mins
        self.maxs = maxs
        self.mean = mean
        self.sigma = sigma
        self.nacnt = nacnt
        self.zerocnt = zerocnt
        self.nrow = nrow
        self.is_int = is_int


@jax.jit
def _rollup_kernel(data: jax.Array):
    """Fused rollup pass; NaN rows (NA + padding) drop out.

    Variance is computed centered (two reductions inside one XLA program) —
    the E[x²]−mean² shortcut cancels catastrophically in f32 for columns with
    large mean relative to spread (time columns, IDs). The reference computes
    rollups in double (`water/fvec/RollupStats.java`); centering buys the same
    robustness without f64 on the MXU.
    """
    ok = ~jnp.isnan(data)
    x = jnp.where(ok, data, 0.0)
    n = jnp.sum(ok)
    mean = jnp.sum(x) / jnp.maximum(n, 1)
    d = jnp.where(ok, data - mean, 0.0)
    var = jnp.sum(d * d) / jnp.maximum(n, 1)
    return dict(
        mins=jnp.min(jnp.where(ok, data, jnp.inf)),
        maxs=jnp.max(jnp.where(ok, data, -jnp.inf)),
        mean=mean,
        var=jnp.maximum(var, 0.0),
        n=n,
        zerocnt=jnp.sum(ok & (data == 0.0)),
        isint=jnp.all(jnp.where(ok, data == jnp.floor(data), True)),
    )


@jax.jit
def _rollup_kernel_cols(X: jax.Array):
    """Batched rollups over a (plen, C) column stack — identical math to
    `_rollup_kernel`, one program + ONE host transfer for C columns (the
    fix for ~1.3 s of tunnel round-trip PER COLUMN on an 11M-row frame).
    Production now dispatches `_rollup_mr_map` through the MRTask driver;
    this fused kernel stays as the bit-level parity ORACLE the telemetry
    tests pin the mr path against (tests/test_telemetry.py) — change the
    rollup math in both places or that test fails."""
    ok = ~jnp.isnan(X)
    x = jnp.where(ok, X, 0.0)
    n = jnp.sum(ok, axis=0)
    mean = jnp.sum(x, axis=0) / jnp.maximum(n, 1)
    d = jnp.where(ok, X - mean[None, :], 0.0)
    var = jnp.sum(d * d, axis=0) / jnp.maximum(n, 1)
    return dict(
        mins=jnp.min(jnp.where(ok, X, jnp.inf), axis=0),
        maxs=jnp.max(jnp.where(ok, X, -jnp.inf), axis=0),
        mean=mean,
        var=jnp.maximum(var, 0.0),
        n=n,
        zerocnt=jnp.sum(ok & (X == 0.0), axis=0),
        isint=jnp.all(jnp.where(ok, X == jnp.floor(X), True), axis=0),
    )


def _rollup_mr_map(cols, rows):
    """Per-shard rollup partials for the MRTask driver — the batched rollup
    pass as an actual map/reduce: the same centered-variance math as
    ``_rollup_kernel_cols`` (global mean via an INTERNAL ``psum`` — DrJAX's
    map-with-collectives shape), partial sums/mins/maxs combined by the
    driver's named monoids. NaN rows (NA + mesh padding) drop out of every
    reduction, exactly like the fused kernel. Module-level so the driver's
    per-map_fn program cache engages across frames."""
    from ..parallel.mesh import ROWS

    X = jnp.stack(cols, axis=1)  # (shard_rows, C)
    ok = ~jnp.isnan(X)
    x = jnp.where(ok, X, 0.0)
    n_part = jnp.sum(ok, axis=0)
    n = jax.lax.psum(n_part, ROWS)
    mean = jax.lax.psum(jnp.sum(x, axis=0), ROWS) / jnp.maximum(n, 1)
    d = jnp.where(ok, X - mean[None, :], 0.0)
    return {
        "n": n_part,
        "sum": jnp.sum(x, axis=0),
        "varsum": jnp.sum(d * d, axis=0),
        "mins": jnp.min(jnp.where(ok, X, jnp.inf), axis=0),
        "maxs": jnp.max(jnp.where(ok, X, -jnp.inf), axis=0),
        "zerocnt": jnp.sum(ok & (X == 0.0), axis=0),
        "isint": jnp.min(jnp.where(ok, X == jnp.floor(X),
                                   True).astype(jnp.int32), axis=0),
    }


#: the per-output monoids `_rollup_mr_map` reduces under
_ROLLUP_REDUCE = {"n": "sum", "sum": "sum", "varsum": "sum", "mins": "min",
                  "maxs": "max", "zerocnt": "sum", "isint": "min"}


def _rollups_from_scalars(nrow: int, r: dict) -> "Rollups":
    n = int(r["n"])
    var = float(r["var"]) * (n / max(n - 1, 1))  # sample variance
    return Rollups(
        mins=float(r["mins"]) if n else np.nan,
        maxs=float(r["maxs"]) if n else np.nan,
        mean=float(r["mean"]) if n else np.nan,
        sigma=float(np.sqrt(var)) if n else np.nan,
        nacnt=nrow - n,
        zerocnt=int(r["zerocnt"]),
        nrow=nrow,
        is_int=bool(r["isint"]),
    )


class Vec(Keyed):
    def __init__(
        self,
        data: jax.Array,
        nrow: int,
        type: str = T_NUM,
        domain: list[str] | None = None,
        key: str | None = None,
        host_data: np.ndarray | None = None,
        exact_data: np.ndarray | None = None,
    ):
        super().__init__(key=key, prefix="vec")
        import threading

        self._lock = threading.RLock()  # guards _data/_spill_path transitions
        self._data = data  # padded, row-sharded float32 (None for string vecs)
        self._spill_path: str | None = None  # Cleaner "ice" file when spilled
        self._last_access = 0
        self.nrow = int(nrow)
        self.type = type
        self.domain = domain  # categorical level names (host-side)
        self.host_data = host_data  # for T_STR/T_UUID: numpy object array
        self.exact_data = exact_data  # exact int64/f64 copy when f32 is lossy
        self._rollups: Rollups | None = None
        self._version = 0
        if data is not None:
            from ..backend.memory import CLEANER

            self._last_access = CLEANER.touch(self)
            CLEANER.track(self, data.size * data.dtype.itemsize)

    @property
    def data(self):
        """The device column. Spilled Vecs rehydrate transparently (the
        Cleaner's swap-in path, `water/Cleaner.java` lazy reload role).
        Thread-safe: concurrent readers and Cleaner sweeps serialize on the
        per-Vec lock, and the rehydrating access is excluded from the sweep
        it may trigger — the getter never returns None for a numeric vec."""
        from ..backend.memory import CLEANER

        with self._lock:
            if self._data is None and self._spill_path is not None:
                from ..utils import telemetry

                host = np.load(self._spill_path)
                self._data = self._rehydrate_put(host)
                CLEANER._remove_ice(self._spill_path)
                self._spill_path = None
                self._last_access = CLEANER.touch(self)
                nbytes = self._data.size * self._data.dtype.itemsize
                CLEANER.track(self, nbytes)
                telemetry.inc("cleaner.rehydrate.count")
                telemetry.inc("cleaner.rehydrate.bytes", nbytes)
            elif self._data is not None:
                self._last_access = CLEANER.touch(self)
            return self._data

    def _rehydrate_put(self, host: np.ndarray):
        """Spilled payload -> device, surviving a device OOM: when HBM is
        so contended the reload itself RESOURCE_EXHAUSTs, emergency-spill
        every other unpinned resident and retry once — losing one LRU round
        beats killing the job mid-scoring (the failure mode the
        ``cleaner.rehydrate`` failpoint injects on demand)."""
        import jax

        from ..backend.memory import CLEANER
        from ..utils import failpoints

        def put():
            failpoints.hit("cleaner.rehydrate")
            return jax.device_put(host, self._put_sharding())

        try:
            return put()
        except Exception as e:  # noqa: BLE001 — OOM-classified right below
            if "RESOURCE_EXHAUSTED" not in str(e):
                raise
            from ..utils.log import warn

            freed = CLEANER.emergency_sweep(
                exclude=getattr(self, "_cleaner_token", None))
            warn(f"device OOM rehydrating {self.key}: emergency-spilled "
                 f"{freed} bytes, retrying")
            try:
                return put()  # a still-armed injection fails this too
            except Exception as e2:  # noqa: BLE001 — typed below
                if "RESOURCE_EXHAUSTED" in str(e2):
                    # OOM that survived the spill-everything sweep: the
                    # process genuinely cannot fit this buffer — the
                    # flight recorder's canonical terminal event (no-op
                    # unless H2O_TPU_FLIGHT_DIR is set)
                    from ..utils import flightrec

                    flightrec.dump("device-oom", e2)
                raise

    @data.setter
    def data(self, value):
        from ..backend.memory import CLEANER

        with self._lock:
            old = self._data
            old_path = self._spill_path
            self._data = value
            self._spill_path = None
            if old is not None or old_path is not None:
                CLEANER.note_freed(
                    self, 0 if old is None else old.size * old.dtype.itemsize,
                    old_path)
            if value is not None:
                self._last_access = CLEANER.touch(self)
                CLEANER.track(self, value.size * value.dtype.itemsize)

    def _put_sharding(self):
        """Sharding for (re)hydrating this Vec's device payload. Row-sharded
        by default; coded chunk payloads whose leading axis is not the row
        axis (const/sparse codecs, `frame/chunks.py`) override this."""
        from ..parallel.mesh import default_mesh, row_sharding

        return row_sharding(default_mesh())

    # -- construction --------------------------------------------------------
    @staticmethod
    def from_numpy(arr: np.ndarray, type: str | None = None,
                   domain: list[str] | None = None, mesh=None) -> "Vec":
        """Build a row-sharded Vec from host data (the ingest endpoint)."""
        arr = np.asarray(arr)
        nrow = arr.shape[0]
        if arr.dtype == object or arr.dtype.kind in "US":
            return Vec(None, nrow, type=T_STR, host_data=np.asarray(arr, dtype=object))
        plen = meshmod.padded_len(nrow, mesh)
        buf = np.full(plen, np.nan, dtype=np.float32)
        f32 = arr.astype(np.float32)
        buf[:nrow] = f32
        if type is None:
            if domain is not None:
                type = T_CAT
            elif arr.dtype.kind in "iu" or (arr.dtype.kind == "b"):
                type = T_INT
            else:
                type = T_NUM
        # f32 is lossy above 2^24 (big int ids, ms-since-epoch times). Device
        # compute stays f32 (MXU wants it) but the exact values are retained
        # host-side so the logical column (to_numpy/at/export) is never corrupted
        # — the f32 HBM copy is then a compute projection, like the reference's
        # scaled-decimal codecs are a storage projection (`fvec/C2SChunk.java`).
        exact = None
        if arr.dtype.kind in "iuf" and arr.dtype.itemsize > 4 and nrow:
            back = f32.astype(arr.dtype) if arr.dtype.kind in "iu" else f32.astype(np.float64)
            with np.errstate(invalid="ignore"):
                lossy = ~np.isclose(back, arr, rtol=0, atol=0, equal_nan=True)
            if lossy.any():
                exact = arr.copy()
        data = jax.device_put(buf, meshmod.row_sharding(mesh))
        return Vec(data, nrow, type=type, domain=domain, exact_data=exact)

    @staticmethod
    def from_device(data: jax.Array, nrow: int, type: str = T_NUM,
                    domain: list[str] | None = None) -> "Vec":
        return Vec(data, nrow, type=type, domain=domain)

    # -- basic props ---------------------------------------------------------
    def __len__(self) -> int:
        return self.nrow

    @property
    def plen(self) -> int:
        return self.nrow if self.data is None else self.data.shape[0]

    def is_numeric(self) -> bool:
        return self.type in (T_NUM, T_INT)

    def is_categorical(self) -> bool:
        return self.type == T_CAT

    def is_string(self) -> bool:
        return self.type == T_STR

    def cardinality(self) -> int:
        return len(self.domain) if self.domain else -1

    # -- data access ---------------------------------------------------------
    def to_numpy(self) -> np.ndarray:
        """Gather the logical column to host (NA as NaN)."""
        if self.data is None:
            return self.host_data
        if self.exact_data is not None:
            return self.exact_data
        return np.asarray(self.data)[: self.nrow]

    def at(self, i: int):
        """Single-element read — `Chunk.at()` analog; O(1) but host-syncing."""
        if not -self.nrow <= i < self.nrow:
            raise IndexError(f"row {i} out of range for Vec of {self.nrow} rows")
        if i < 0:
            i += self.nrow
        if self.data is None:
            return self.host_data[i]
        if self.exact_data is not None:
            return self.exact_data[i]
        return float(self.data[i])

    def modified(self) -> None:
        """Invalidate cached rollups after an in-place-style update."""
        self._rollups = None
        self._version += 1

    # -- rollups -------------------------------------------------------------
    def rollups(self) -> Rollups:
        if self._rollups is None:
            if self.data is None:
                nacnt = int(sum(1 for v in self.host_data if v is None))
                self._rollups = Rollups(np.nan, np.nan, np.nan, np.nan,
                                        nacnt, 0, self.nrow, False)
            else:
                r = jax.device_get(_rollup_kernel(self.data))
                self._rollups = _rollups_from_scalars(self.nrow, r)
        return self._rollups

    def mean(self) -> float:
        return self.rollups().mean

    def sigma(self) -> float:
        return self.rollups().sigma

    def min(self) -> float:
        return self.rollups().mins

    def max(self) -> float:
        return self.rollups().maxs

    def nacnt(self) -> int:
        return self.rollups().nacnt

    # -- transforms ----------------------------------------------------------
    def with_data(self, data: jax.Array, type: str | None = None,
                  domain: Any = "__same__") -> "Vec":
        return Vec(data, self.nrow, type=type or self.type,
                   domain=self.domain if domain == "__same__" else domain)

    def astype_cat(self, domain: list[str]) -> "Vec":
        return Vec(self.data, self.nrow, type=T_CAT, domain=domain)

    def __repr__(self) -> str:
        dom = f", card={len(self.domain)}" if self.domain else ""
        return f"Vec({self.key}, nrow={self.nrow}, type={self.type}{dom})"
