"""Distributed frame layer: Vec (sharded column), Frame (named columns),
and the compressed columnar chunk store (coded columns + binned views)."""

from .chunks import BinnedView, ChunkMeta, CodedVec, compress_frame
from .frame import Frame
from .vec import Vec

__all__ = ["Frame", "Vec", "BinnedView", "ChunkMeta", "CodedVec",
           "compress_frame"]
