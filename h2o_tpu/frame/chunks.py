"""Compressed columnar chunk store — the `water/fvec/C*Chunk` codec family
rebuilt for HBM.

The reference keeps every column as compressed chunks: constant runs
(`C0DChunk`), byte/short offset-scale codes (`C1Chunk`/`C2SChunk`),
sparse-zero runs (`CXIChunk`) — 21 codecs picked per chunk at parse time.
The seed design here deliberately dropped all of that for flat f32 arrays
(vec.py's "fixed-width vectorizable layouts" note), which is why an
Airlines-116M×31 expanded matrix is ~14 GB of f32 on one v5e chip. This
module brings the codec idea back in TPU-native form:

- **Coded columns** (`CodedVec`): ONE device array of codes per column plus
  host-side affine metadata. Codecs: ``const`` (one value), ``int8``/
  ``int16`` offset-scale (``value = offset + code·scale``, top code = NA),
  ``cat8``/``cat16`` (categorical level ids — same wire format, labelled for
  introspection), ``sparse0`` (row-index + f32-bit pairs for mostly-zero
  columns), and ``raw`` passthrough. Every codec is **verified bit-exact at
  encode time against the real device decode kernel** (NaN-aware); a column
  no codec reproduces exactly stays raw f32. Decoding is a per-access
  temporary — the f32 view never becomes resident state.
- **Cleaner residency**: coded bytes register with `backend/memory.py`'s
  Cleaner exactly like raw Vec buffers (CodedVec IS a Vec), so
  ``hbm_budget_bytes()`` stays honest while chunk views are alive, and
  coded columns spill/rehydrate under budget pressure like any other
  column (`tests/test_chunks.py` pins the eviction cycle).
- **Binned views** (`BinnedView`): the training-matrix analog of XGBoost's
  ELLPACK page — per-column quantile-bin codes packed into one
  device-resident (plen, F) int8 (int16 when any feature needs > 127 bins)
  matrix, built COLUMN BY COLUMN from Vec data + precomputed edges, so the
  raw f32 matrix is never stacked. The tree engine consumes it directly:
  blocks upcast to int32 inside the histogram scan body (VMEM-granular, no
  HBM-wide relayout — `models/tree/engine.py`), which is what makes int8
  storage a win where the always-int8 one-hot measured 5x slower
  (`binning.bin_matrix`'s historical note).

``H2O_TPU_BINNED_STORE=0`` disables the binned training path in the tree
builders (`models/gbm.py`); the chunk codecs themselves are opt-in via
``compress_frame`` / ``Frame.compress()``.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel import mesh as meshmod
from .vec import Rollups, T_CAT, T_NUM, Vec

#: code-space caps: the top code of each width is the NA sentinel
_CAP8, _NA8 = 254, 255
_CAP16, _NA16 = 65534, 65535

_INT_KINDS = ("int8", "int16", "cat8", "cat16")


@dataclass(frozen=True)
class ChunkMeta:
    """Host-side decode metadata — the `Chunk` subclass header analog."""

    kind: str            # const | int8 | int16 | cat8 | cat16 | sparse0 | raw
    nrow: int            # logical rows (padding rows beyond this are NaN)
    plen: int            # padded device length
    offset: float = 0.0  # int codecs: value = offset + code * scale (f32)
    scale: float = 1.0
    na_code: int = 0     # int codecs: the NA/padding sentinel code
    value: float = float("nan")  # const codecs: the single value
    is_int: bool = False         # decoded values all integral (encode-time)
    zero_code: int = -1          # int codecs: code decoding to 0.0 (-1: none)


# ---------------------------------------------------------------------------
# Decode kernels — one jitted program per (kind, shape family); the affine
# params ride as operands so ingesting many columns never multiplies compiles.
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("plen",))
def _decode_const(value, nrow, plen: int):
    i = jnp.arange(plen)
    return jnp.where(i < nrow, jnp.asarray(value, jnp.float32), jnp.nan)


@jax.jit
def _decode_intcode(codes, offset, scale, na_code):
    out = offset + codes.astype(jnp.float32) * scale
    return jnp.where(codes == na_code, jnp.nan, out)


@functools.partial(jax.jit, static_argnames=("plen",))
def _decode_sparse(packed, plen: int):
    vals = jax.lax.bitcast_convert_type(packed[1], jnp.float32)
    return jnp.zeros((plen,), jnp.float32).at[packed[0]].set(vals,
                                                             mode="drop")


def decode_chunk(coded: jax.Array, meta: ChunkMeta) -> jax.Array:
    """Coded device array + meta -> the f32 logical column (padding = NaN)."""
    k = meta.kind
    if k == "raw":
        return coded
    if k == "const":
        return _decode_const(np.float32(meta.value), np.int32(meta.nrow),
                             meta.plen)
    if k in _INT_KINDS:
        return _decode_intcode(coded, np.float32(meta.offset),
                               np.float32(meta.scale),
                               np.asarray(meta.na_code, coded.dtype))
    if k == "sparse0":
        return _decode_sparse(coded, meta.plen)
    raise ValueError(f"unknown chunk kind '{k}'")


# ---------------------------------------------------------------------------
# Encode (host-side; ingest/compress time)
# ---------------------------------------------------------------------------
def _bits_equal(a: np.ndarray, b: np.ndarray) -> bool:
    """f32 bit-equality with all NaNs identified (any-payload NaN == NaN)."""
    a = np.ascontiguousarray(a, np.float32)
    b = np.ascontiguousarray(b, np.float32)
    same = a.view(np.int32) == b.view(np.int32)
    return bool(np.all(same | (np.isnan(a) & np.isnan(b))))


def _int_candidate(vals, nan_mask, nrow, plen, cap, na_code, dtype, kind,
                   is_int):
    """Offset-scale integer codes covering every distinct value exactly, or
    None. The affine params are chosen so the f32 decode arithmetic
    reproduces the original f32 bits (verified by the caller)."""
    finite = vals[~nan_mask]
    u = np.unique(finite.astype(np.float64))
    if u.size == 0:
        return None
    offset = float(u[0])
    if u.size == 1:
        scale = 1.0
    else:
        scale = float(np.min(np.diff(u)))
        if scale <= 0:
            return None
    q = (u - offset) / scale
    qr = np.round(q)
    if np.max(np.abs(q - qr)) > 1e-6 or qr[-1] > cap:
        return None
    codes = np.full(plen, na_code, dtype=dtype)
    c = np.round((vals[~nan_mask].astype(np.float64) - offset) / scale)
    if c.min() < 0 or c.max() > cap:
        return None
    codes[~nan_mask] = c.astype(dtype)
    # host-side replica of the decode arithmetic as a cheap pre-filter; the
    # device kernel itself re-verifies in from_vec (fma-safe)
    dec = np.float32(offset) + codes.astype(np.float32) * np.float32(scale)
    dec = np.where(codes == na_code, np.float32(np.nan), dec)
    if not _bits_equal(dec, vals):
        return None
    zq = np.round((0.0 - offset) / scale)
    zero_code = int(zq) if (0 <= zq <= cap and
                            np.float32(offset)
                            + np.float32(zq) * np.float32(scale) == 0.0) \
        else -1
    meta = ChunkMeta(kind=kind, nrow=nrow, plen=plen, offset=offset,
                     scale=scale, na_code=na_code, is_int=is_int,
                     zero_code=zero_code)
    return codes, meta


def encode_column(host: np.ndarray, nrow: int,
                  is_cat: bool = False) -> tuple[np.ndarray, ChunkMeta]:
    """Pick the narrowest codec that reproduces ``host`` (a padded (plen,)
    f32 column, NaN = NA/padding) bit-exactly. Preference order is by coded
    bytes: const, int8 (1 B/row), sparse when it beats 2 B/row, int16,
    sparse when it beats 4 B/row, raw."""
    vals = np.ascontiguousarray(host, np.float32)
    plen = vals.shape[0]
    nan_mask = np.isnan(vals)
    is_int = bool(np.all(vals[~nan_mask] == np.floor(vals[~nan_mask]))) \
        if (~nan_mask).any() else False

    if nan_mask[:nrow].all():          # all-NA logical column
        return (np.zeros(1, np.int8),
                ChunkMeta(kind="const", nrow=nrow, plen=plen,
                          value=float("nan")))
    bits = vals.view(np.int32)
    if not nan_mask[:nrow].any() and np.all(bits[:nrow] == bits[0]):
        return (np.zeros(1, np.int8),
                ChunkMeta(kind="const", nrow=nrow, plen=plen,
                          value=float(vals[0]), is_int=is_int))

    # sparse payload: rows whose BITS are nonzero (keeps -0.0 and every NaN,
    # padding tail included) as (row, f32-bits) int32 pairs
    nz = np.nonzero(bits)[0]
    sparse_bytes = 8 * max(nz.size, 1)

    def sparse_pack():
        packed = np.stack([nz.astype(np.int32), bits[nz]], axis=0)
        return packed, ChunkMeta(kind="sparse0", nrow=nrow, plen=plen,
                                 is_int=is_int)

    cand = _int_candidate(vals, nan_mask, nrow, plen, _CAP8, _NA8, np.uint8,
                          "cat8" if is_cat else "int8", is_int)
    if cand is not None:
        return cand
    if sparse_bytes < 2 * plen:
        return sparse_pack()
    cand = _int_candidate(vals, nan_mask, nrow, plen, _CAP16, _NA16,
                          np.uint16, "cat16" if is_cat else "int16", is_int)
    if cand is not None:
        return cand
    if sparse_bytes < 4 * plen:
        return sparse_pack()
    return vals, ChunkMeta(kind="raw", nrow=nrow, plen=plen, is_int=is_int)


# ---------------------------------------------------------------------------
# Rollups from codes (lossless stats without decoding)
# ---------------------------------------------------------------------------
@jax.jit
def _code_rollup_kernel(codes, na_code, zero_code):
    ok = codes != na_code
    cf = codes.astype(jnp.float32)
    n = jnp.sum(ok)
    mean = jnp.sum(jnp.where(ok, cf, 0.0)) / jnp.maximum(n, 1)
    d = jnp.where(ok, cf - mean, 0.0)
    return dict(
        n=n,
        mean=mean,
        var=jnp.maximum(jnp.sum(d * d) / jnp.maximum(n, 1), 0.0),
        cmin=jnp.min(jnp.where(ok, cf, jnp.inf)),
        cmax=jnp.max(jnp.where(ok, cf, -jnp.inf)),
        zerocnt=jnp.sum(ok & (codes == zero_code)),
    )


@jax.jit
def _code_rollup_kernel_cols(codes, na_codes, zero_codes):
    """Batched code-space rollups over a (plen, C) code stack — one program
    + ONE host transfer for C coded columns (the `_rollup_kernel_cols` role:
    the per-column eager path costs a device round trip PER COLUMN on
    remote-tunnel transports). na/zero codes ride as int32 so uint8/uint16
    stacks compare without reinterpreting -1 sentinels."""
    ok = codes.astype(jnp.int32) != na_codes[None, :]
    cf = codes.astype(jnp.float32)
    n = jnp.sum(ok, axis=0)
    mean = jnp.sum(jnp.where(ok, cf, 0.0), axis=0) / jnp.maximum(n, 1)
    d = jnp.where(ok, cf - mean[None, :], 0.0)
    return dict(
        n=n,
        mean=mean,
        var=jnp.maximum(jnp.sum(d * d, axis=0) / jnp.maximum(n, 1), 0.0),
        cmin=jnp.min(jnp.where(ok, cf, jnp.inf), axis=0),
        cmax=jnp.max(jnp.where(ok, cf, -jnp.inf), axis=0),
        zerocnt=jnp.sum(
            ok & (codes.astype(jnp.int32) == zero_codes[None, :]), axis=0),
    )


def _rollups_from_code_stats(meta: ChunkMeta, r: dict, nrow: int) -> Rollups:
    """Affine-map code-space stats back to value space (f32 min/max match
    the decode arithmetic; sample-variance correction as in vec.py)."""
    n = int(r["n"])
    var = (float(meta.scale) ** 2) * float(r["var"]) * (n / max(n - 1, 1))
    dec = lambda c: float(np.float32(meta.offset)
                          + np.float32(c) * np.float32(meta.scale))
    return Rollups(
        mins=dec(r["cmin"]) if n else np.nan,
        maxs=dec(r["cmax"]) if n else np.nan,
        mean=float(meta.offset + meta.scale * float(r["mean"])) if n
        else np.nan,
        sigma=float(np.sqrt(var)) if n else np.nan,
        nacnt=nrow - n,
        zerocnt=int(r["zerocnt"]),
        nrow=nrow,
        is_int=meta.is_int)


def batch_code_rollups(vecs) -> list:
    """Fill missing rollups for CodedVecs without decoding: consts resolve
    host-side, int-coded columns batch into ONE device program per
    (plen, dtype) stack. Returns the vecs it could NOT serve (sparse/raw —
    they join the caller's decode-path batch)."""
    rest: list = []
    by_shape: dict = {}
    for v in vecs:
        if v._rollups is not None:
            continue
        m = getattr(v, "meta", None)
        if m is None:
            rest.append(v)
        elif m.kind == "const":
            v.rollups_from_codes()
        elif m.kind in _INT_KINDS:
            by_shape.setdefault((m.plen, np.dtype(v._code_dtype()).name),
                                []).append(v)
        else:
            rest.append(v)
    for group in by_shape.values():
        if len(group) == 1:
            group[0].rollups_from_codes()
            continue
        codes = jnp.stack([v.coded for v in group], axis=1)
        na = jnp.asarray([v.meta.na_code for v in group], jnp.int32)
        zero = jnp.asarray([v.meta.zero_code for v in group], jnp.int32)
        r = jax.device_get(_code_rollup_kernel_cols(codes, na, zero))
        for i, v in enumerate(group):
            v._rollups = _rollups_from_code_stats(
                v.meta, {k: r[k][i] for k in r}, v.nrow)
    return rest


class CodedVec(Vec):
    """A Vec whose device-resident state is the CODED column.

    ``.data`` decodes on access (a per-call f32 temporary — never resident);
    ``.coded`` is the tracked device array the Cleaner budgets, spills and
    rehydrates. Rollups come straight off the codes for const/int codecs
    (min/max/NA/zero counts are lossless in code space; mean/sigma are the
    same centered f32 reduction the raw kernel runs, on codes then
    affine-mapped)."""

    def __init__(self, coded, meta: ChunkMeta, nrow: int, type: str = T_NUM,
                 domain=None, exact_data=None, key=None):
        self.meta = meta
        super().__init__(coded, nrow, type=type, domain=domain,
                         exact_data=exact_data, key=key)

    # -- storage access ------------------------------------------------------
    @property
    def plen(self) -> int:
        # the padded length is decode metadata — never launch a full-column
        # decode (the base property reads self.data) to answer a shape query
        return self.meta.plen

    @property
    def coded(self) -> jax.Array:
        """The coded device array (touches the LRU clock; rehydrates)."""
        return Vec.data.fget(self)

    @property
    def data(self) -> jax.Array:
        return decode_chunk(Vec.data.fget(self), self.meta)

    @data.setter
    def data(self, value):
        # overwriting with a plain device column degrades the codec to raw
        # passthrough — the coded ledger entry is swapped for the new bytes,
        # and plen must track the NEW buffer (Vec's contract is the live
        # device shape; a stale meta.plen would mis-group this vec in
        # ensure_rollups' same-plen stacks)
        self.meta = replace(self.meta, kind="raw",
                            plen=(self.meta.plen if value is None
                                  else int(value.shape[0])))
        Vec.data.fset(self, value)

    def _put_sharding(self):
        if self.meta.kind in ("const", "sparse0"):
            # (1,) / (2, nnz) payloads don't row-shard; replicate on reload
            return meshmod.replicated(meshmod.default_mesh())
        return super()._put_sharding()

    def coded_nbytes(self) -> int:
        """Device-resident coded bytes (0 while spilled)."""
        c = self._data
        return 0 if c is None else c.size * c.dtype.itemsize

    # -- rollups -------------------------------------------------------------
    def rollups_from_codes(self) -> bool:
        """Compute + cache rollups without decoding when the codec allows;
        False sends the caller down the decode path (sparse0/raw)."""
        if self._rollups is not None:
            return True
        m = self.meta
        if m.kind == "const":
            if np.isnan(m.value):
                self._rollups = Rollups(np.nan, np.nan, np.nan, np.nan,
                                        self.nrow, 0, self.nrow, False)
            else:
                self._rollups = Rollups(
                    m.value, m.value, m.value, 0.0, 0,
                    self.nrow if m.value == 0.0 else 0, self.nrow, m.is_int)
            return True
        if m.kind in _INT_KINDS:
            r = jax.device_get(_code_rollup_kernel(
                self.coded, np.asarray(m.na_code, self._code_dtype()),
                np.int32(m.zero_code)))
            self._rollups = _rollups_from_code_stats(m, r, self.nrow)
            return True
        return False

    def _code_dtype(self):
        c = self._data
        return c.dtype if c is not None else \
            (np.uint8 if self.meta.kind in ("int8", "cat8") else np.uint16)

    def rollups(self) -> Rollups:
        if self._rollups is None and not self.rollups_from_codes():
            return super().rollups()
        return self._rollups

    # -- construction --------------------------------------------------------
    @staticmethod
    def from_vec(vec: Vec) -> Vec:
        """Compress one column. Returns ``vec`` unchanged when it has no
        device data (strings), is already coded, or no codec wins (raw).
        The chosen codec is re-verified against the DEVICE decode kernel
        (host pre-verification can't see backend fma fusion); a mismatch
        falls back to the raw column."""
        if isinstance(vec, CodedVec) or vec.data is None:
            return vec
        host = np.asarray(Vec.data.fget(vec))
        coded_np, meta = encode_column(host, vec.nrow,
                                       is_cat=vec.is_categorical())
        if meta.kind == "raw":
            return vec
        mesh = meshmod.default_mesh()
        sharding = (meshmod.replicated(mesh)
                    if meta.kind in ("const", "sparse0")
                    else meshmod.row_sharding(mesh))
        coded = jax.device_put(coded_np, sharding)
        if not _bits_equal(np.asarray(decode_chunk(coded, meta)), host):
            return vec
        return CodedVec(coded, meta, vec.nrow, type=vec.type,
                        domain=vec.domain, exact_data=vec.exact_data)

    def __repr__(self) -> str:
        return (f"CodedVec({self.key}, nrow={self.nrow}, type={self.type}, "
                f"codec={self.meta.kind})")


def compress_frame(fr):
    """A new Frame with every compressible column coded (`Frame.compress`)."""
    from ..backend.kvstore import STORE
    from .frame import Frame

    out = Frame(list(fr.names), [CodedVec.from_vec(v) for v in fr.vecs])
    STORE.put_keyed(out)
    return out


# ---------------------------------------------------------------------------
# BinnedView — device-resident int8/int16 binned training matrix
# ---------------------------------------------------------------------------
@jax.jit
def _stack_codes(*cols):
    return jnp.stack(cols, axis=1)


class BinnedView(Vec):
    """The packed (plen, F) bin-code matrix the tree engine trains on.

    Subclassing Vec buys the whole residency protocol for free: the coded
    bytes are Cleaner-tracked, so a model-building pass at the HBM edge
    budgets honestly against the live binned view (`hbm_budget_bytes()`
    subtracts it). The view is PINNED (never spilled): its consumer — the
    jitted train loop — holds the device buffer for the view's whole
    lifetime, so a sweep could only pay a multi-GB ice write and corrupt
    the ledger without freeing a byte of HBM."""

    def __init__(self, matrix, edges_np: np.ndarray, names=None):
        self.edges_np = edges_np
        self.col_names = list(names) if names is not None else None
        self._pinned = True  # before track(): the registering sweep must
                             # already see the pin
        super().__init__(matrix, matrix.shape[0], type="binned")

    @property
    def matrix(self) -> jax.Array:
        """The (plen, F) code matrix (touches the LRU clock; rehydrates)."""
        return Vec.data.fget(self)

    @property
    def nbytes(self) -> int:
        m = self._data
        return 0 if m is None else m.size * m.dtype.itemsize

    @staticmethod
    def code_dtype(nbins_tot: int):
        """Narrowest signed dtype holding codes 0..nbins_tot (NA bucket)."""
        if nbins_tot <= np.iinfo(np.int8).max:
            return jnp.int8
        if nbins_tot <= np.iinfo(np.int16).max:
            return jnp.int16
        return jnp.int32

    @staticmethod
    def build(cols, edges_np: np.ndarray, names=None) -> "BinnedView":
        """Bin column-by-column against ``edges_np`` ((F, W) NaN-padded cut
        rows) and pack into one narrow-dtype matrix. ``cols`` may be Vecs
        (CodedVecs decode one column at a time) or device arrays; the raw
        f32 matrix is never materialized — peak transient footprint is the
        per-column code vectors plus the packed matrix (2 coded bytes/cell
        at int8), vs 8 f32+int32 bytes/cell on the stacked path."""
        from ..models.tree.binning import _coldata, bin_column

        cols = list(cols)
        assert len(cols) == edges_np.shape[0], "one edge row per column"
        dtype = BinnedView.code_dtype(edges_np.shape[1] + 1)
        edges_dev = jnp.asarray(np.ascontiguousarray(edges_np, np.float32))
        codes = [bin_column(_coldata(c), edges_dev[f], dtype=dtype)
                 for f, c in enumerate(cols)]
        # EXPLICIT row_sharding for the packed matrix: the per-column code
        # vectors are row-sharded, but the stacked result's placement is
        # otherwise whatever GSPMD picked — the training-matrix layout the
        # per-chip HBM budget depends on must be policy, not inference
        # (each device holds exactly its plen/n_shards row slice, which the
        # shard_map trainer consumes without any relayout)
        matrix = jax.device_put(_stack_codes(*codes),
                                meshmod.row_sharding(meshmod.default_mesh()))
        return BinnedView(matrix, edges_np, names=names)

    def __repr__(self) -> str:
        shape = None if self._data is None else tuple(self._data.shape)
        return f"BinnedView({self.key}, shape={shape})"
