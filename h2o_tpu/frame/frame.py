"""Frame — a named collection of Vecs (distributed columns).

Reference: `water/fvec/Frame.java` (2,017 LoC). A Frame is column-oriented: an
ordered map name -> Vec, all with the same row count. Unlike the reference, the
columns here are row-sharded JAX arrays in HBM (see vec.py); all per-column chunk
alignment concerns (`VectorGroup`, `water/Key.java:108-120`) vanish because every
Vec uses the same padded sharding, so shard i of every column covers the same
global rows — the property MRTask's aligned-chunk map relied on.

Also provides the dense-matrix materialization used by model builders (the
`hex/DataInfo` handoff): ``as_matrix`` stacks selected numeric columns into an
(nrow_padded, ncol) float32 array, still row-sharded.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..backend.kvstore import Keyed, STORE
from ..parallel import mesh as meshmod
from .vec import T_CAT, T_NUM, Vec


@jax.jit
def _stack_cols(*cols):
    return jnp.stack(cols, axis=1)


class Frame(Keyed):
    def __init__(self, names: Sequence[str] | None = None,
                 vecs: Sequence[Vec] | None = None, key: str | None = None):
        super().__init__(key=key, prefix="frame")
        self._names: list[str] = list(names or [])
        self._vecs: list[Vec] = list(vecs or [])
        assert len(self._names) == len(self._vecs)
        if self._vecs:
            nr = self._vecs[0].nrow
            assert all(v.nrow == nr for v in self._vecs), "column row counts differ"

    # -- construction --------------------------------------------------------
    @staticmethod
    def from_dict(cols: dict, mesh=None, key: str | None = None) -> "Frame":
        names, vecs = [], []
        for name, col in cols.items():
            names.append(str(name))
            if isinstance(col, Vec):
                vecs.append(col)
            else:
                col = np.asarray(col)
                vecs.append(Vec.from_numpy(col, mesh=mesh))
        fr = Frame(names, vecs, key=key)
        STORE.put_keyed(fr)
        return fr

    @staticmethod
    def from_pandas(df, mesh=None, key: str | None = None) -> "Frame":
        names, vecs = [], []
        import pandas.api.types as pdt

        for name in df.columns:
            s = df[name]
            if not (pdt.is_numeric_dtype(s) or pdt.is_bool_dtype(s)
                    or pdt.is_datetime64_any_dtype(s)):
                if isinstance(s.dtype, __import__("pandas").CategoricalDtype):
                    codes = s.cat.codes.to_numpy().astype(np.float32)
                    codes[codes < 0] = np.nan
                    vecs.append(Vec.from_numpy(codes, type=T_CAT, mesh=mesh,
                                               domain=[str(x) for x in s.cat.categories]))
                else:
                    uniq, codes = _factorize(s.to_numpy())
                    vecs.append(Vec.from_numpy(codes, type=T_CAT, domain=uniq, mesh=mesh))
            elif pdt.is_datetime64_any_dtype(s):
                ms = s.astype("int64").to_numpy().astype(np.float64) / 1e6
                ms[s.isna().to_numpy()] = np.nan
                vecs.append(Vec.from_numpy(ms.astype(np.float32), type="time", mesh=mesh))
            else:
                vecs.append(Vec.from_numpy(s.to_numpy(dtype=np.float32, na_value=np.nan),
                                           mesh=mesh))
            names.append(str(name))
        fr = Frame(names, vecs, key=key)
        STORE.put_keyed(fr)
        return fr

    # -- shape / lookup ------------------------------------------------------
    @property
    def nrow(self) -> int:
        return self._vecs[0].nrow if self._vecs else 0

    @property
    def ncol(self) -> int:
        return len(self._vecs)

    @property
    def names(self) -> list[str]:
        return list(self._names)

    @property
    def vecs(self) -> list[Vec]:
        return list(self._vecs)

    def vec(self, name_or_idx) -> Vec:
        if isinstance(name_or_idx, int):
            return self._vecs[name_or_idx]
        return self._vecs[self._names.index(name_or_idx)]

    def __getitem__(self, sel):
        if isinstance(sel, str):
            return self.vec(sel)
        if isinstance(sel, (list, tuple)):
            return self.subframe(sel)
        return self._vecs[sel]

    def find(self, name: str) -> int:
        return self._names.index(name) if name in self._names else -1

    # -- mutation (builds new frames; Vecs are immutable-ish) ----------------
    def add(self, name: str, vec: Vec) -> "Frame":
        if self._vecs:
            assert vec.nrow == self.nrow
        self._names.append(name)
        self._vecs.append(vec)
        return self

    def remove(self, name: str) -> Vec:
        i = self._names.index(name)
        self._names.pop(i)
        return self._vecs.pop(i)

    def replace(self, name: str, vec: Vec) -> "Frame":
        i = self._names.index(name)
        self._vecs[i] = vec
        return self

    def subframe(self, names: Iterable[str]) -> "Frame":
        names = list(names)
        return Frame(names, [self.vec(n) for n in names])

    def rename(self, old: str, new: str) -> "Frame":
        self._names[self._names.index(old)] = new
        return self

    def take(self, idx) -> "Frame":
        """Row subset by integer index array — the shared helper behind
        split_frame, CV fold slicing, and rapids row selection."""
        import numpy as _np

        idx = _np.asarray(idx)
        cols = {}
        for name in self._names:
            v = self.vec(name)
            if v.is_string():
                cols[name] = Vec(None, len(idx), type=v.type,
                                 host_data=v.host_data[idx])
            else:
                cols[name] = Vec.from_numpy(v.to_numpy()[idx], type=v.type,
                                            domain=v.domain)
        return Frame(list(cols), list(cols.values()))

    def concat_rows(self, *others: "Frame") -> "Frame":
        """Row-wise concatenation (rbind) preserving types/domains of self."""
        import numpy as _np

        cols = {}
        for n in self._names:
            v0 = self.vec(n)
            if v0.is_string():
                parts = [self.vec(n).host_data] + [o.vec(n).host_data
                                                   for o in others]
                cols[n] = Vec(None, sum(len(p) for p in parts), type=v0.type,
                              host_data=_np.concatenate(parts))
            else:
                parts = [self.vec(n).to_numpy()] + [o.vec(n).to_numpy()
                                                    for o in others]
                cols[n] = Vec.from_numpy(_np.concatenate(parts), type=v0.type,
                                         domain=v0.domain)
        return Frame(list(cols), list(cols.values()))

    # -- device materialization ----------------------------------------------
    def as_matrix(self, names: Sequence[str] | None = None) -> jax.Array:
        """Stack columns into a row-sharded (plen, ncol) float32 matrix.

        One jitted program per column count: the eager jnp.stack emitted
        several chunked-concatenate XLA programs, each paying ~1 s of cold
        compile+load through the device tunnel."""
        names = list(names) if names is not None else self._names
        cols = [self.vec(n) for n in names]
        assert all(c.data is not None for c in cols), "string cols can't go to HBM"
        return _stack_cols(*[c.data for c in cols])

    def ensure_rollups(self, names: Sequence[str] | None = None) -> None:
        """Compute every missing column rollup in batched fused programs —
        ONE device round-trip per ~2^28-cell block instead of one per column
        (29 serial per-column rollups measured 38 s of an 11M-row cold train
        through the device tunnel; this is the builders' pre-pass).

        The batch dispatches through the MRTask driver (`mr_reduce`), so
        every frame's first rollup touch shows up in /3/Metrics and the
        timeline as a real map/reduce with payload bytes and phase walls —
        the RollupStats MRTask, accounted like one."""
        from .vec import _ROLLUP_REDUCE, _rollup_mr_map, _rollups_from_scalars

        todo = [self.vec(n) for n in (names if names is not None
                                      else self._names)]
        todo = [v for v in todo if v._rollups is None
                and (v._data is not None or v._spill_path is not None)]
        coded = [v for v in todo if hasattr(v, "rollups_from_codes")]
        if coded:
            # coded columns batch in code space — one program per
            # (plen, dtype) stack, never decoding (`chunks.py`); sparse/raw
            # codecs come back and ride the decode-path batch below
            from .chunks import batch_code_rollups

            rest = set(map(id, batch_code_rollups(coded)))
            todo = [v for v in todo
                    if not hasattr(v, "rollups_from_codes")
                    or id(v) in rest]
        if len(todo) <= 1:
            return
        from ..backend.memory import hbm_budget_bytes

        # the (plen, C) stack is a fresh device copy: cap it at 1/8 of the
        # live HBM budget (f32 cells), keeping the historical 2^28-cell
        # (1 GiB) block when no accelerator budget is resolvable
        budget = hbm_budget_bytes()
        cell_cap = (budget // 32) if budget else (1 << 28)
        by_plen: dict[int, list] = {}
        for v in todo:
            by_plen.setdefault(v.plen, []).append(v)
        for plen, group in by_plen.items():
            block = max(1, cell_cap // max(plen, 1))
            for s0 in range(0, len(group), block):
                sub = group[s0:s0 + block]
                import jax
                import numpy as np

                from ..parallel.mrtask import mr_reduce

                r = jax.device_get(mr_reduce(
                    _rollup_mr_map, [v.data for v in sub],
                    nrow=max(v.nrow for v in sub),
                    reduce=_ROLLUP_REDUCE))
                for i, v in enumerate(sub):
                    n = int(r["n"][i])
                    scalars = {
                        "n": n,
                        "mean": r["sum"][i] / max(n, 1),
                        "var": max(float(r["varsum"][i]) / max(n, 1), 0.0),
                        "mins": r["mins"][i], "maxs": r["maxs"][i],
                        "zerocnt": r["zerocnt"][i],
                        "isint": bool(np.asarray(r["isint"][i])),
                    }
                    v._rollups = _rollups_from_scalars(v.nrow, scalars)

    def compress(self) -> "Frame":
        """Compressed-chunk copy of this frame: every column re-encoded with
        the narrowest bit-exact codec (`frame/chunks.py`) — the C1/C2-style
        coded storage the reference parses straight into. Columns no codec
        reproduces exactly stay raw f32 (shared, not copied)."""
        from .chunks import compress_frame

        return compress_frame(self)

    # -- host views ----------------------------------------------------------
    def to_pandas(self):
        import pandas as pd

        out = {}
        for name, v in zip(self._names, self._vecs):
            col = v.to_numpy()
            if v.type == T_CAT and v.domain is not None:
                codes = np.where(np.isnan(col), -1, col).astype(np.int64)
                out[name] = pd.Categorical.from_codes(
                    codes, categories=[str(d) for d in v.domain])
            else:
                out[name] = col
        return pd.DataFrame(out)

    def head(self, n: int = 10):
        return self.to_pandas().head(n)

    def types(self) -> dict[str, str]:
        return dict(zip(self._names, (v.type for v in self._vecs)))

    def remove_impl(self, store) -> None:
        for v in self._vecs:
            store.remove(v.key, cascade=False)

    def __repr__(self) -> str:
        return f"Frame({self.key}, {self.nrow}x{self.ncol} {self._names[:8]}{'...' if self.ncol > 8 else ''})"


def _factorize(arr: np.ndarray):
    """String column -> (sorted domain, float codes w/ NaN for NA).

    The host-side analog of distributed categorical interning
    (`water/parser/ParseDataset.java:502-601`): levels are collected, sorted
    lexicographically (H2O domain order), and values re-coded against the
    sorted domain.
    """
    mask = np.array([x is None or (isinstance(x, float) and np.isnan(x)) for x in arr],
                    dtype=bool)
    vals = np.asarray([("" if m else str(x)) for x, m in zip(arr, mask)])
    uniq = sorted(set(vals[~mask]))
    lookup = {u: i for i, u in enumerate(uniq)}
    codes = np.array([lookup.get(v, -1) for v in vals], dtype=np.float32)
    codes[mask] = np.nan
    return uniq, codes
