"""Frame splitters — analog of `hex/splitframe/` (ShuffleSplitFrame.java,
SplitFrame.java) and the h2o-py `H2OFrame.split_frame` surface.

H2O's split is probabilistic, not exact: each row draws a uniform and lands in
the first split whose cumulative ratio exceeds it (`ShuffleSplitFrame`'s
per-chunk random assignment). ``split_frame`` reproduces that; ``split_exact``
gives deterministic contiguous-shuffled splits for tests.
"""

from __future__ import annotations

import numpy as np

from .frame import Frame


def split_frame(fr: Frame, ratios=(0.75,), seed: int | None = None) -> list[Frame]:
    """Random split by per-row uniform draw; len(ratios)+1 frames, the last
    takes the remainder (h2o-py semantics: ratios must sum to < 1)."""
    ratios = list(ratios)
    if sum(ratios) >= 1.0:
        raise ValueError("ratios must sum to less than 1.0")
    rng = np.random.default_rng(None if seed in (None, -1) else seed)
    u = rng.random(fr.nrow)
    bounds = np.cumsum(ratios + [1.0 - sum(ratios)])
    which = np.searchsorted(bounds, u, side="right")
    which = np.minimum(which, len(bounds) - 1)
    return [fr.take(np.where(which == k)[0]) for k in range(len(bounds))]


def split_exact(fr: Frame, ratios=(0.75,), seed: int | None = None) -> list[Frame]:
    """Deterministic row-count splits after a shuffle."""
    ratios = list(ratios)
    rng = np.random.default_rng(None if seed in (None, -1) else seed)
    perm = rng.permutation(fr.nrow)
    counts = [int(r * fr.nrow) for r in ratios]
    counts.append(fr.nrow - sum(counts))
    out, s = [], 0
    for c in counts:
        out.append(fr.take(np.sort(perm[s:s + c])))
        s += c
    return out
