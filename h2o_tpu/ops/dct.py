"""Discrete cosine transform of frame rows — `water/util/MathUtils.DCT`
(`POST /99/DCTTransformer`).

Each row is a W[×H[×D]] signal laid out across the frame's columns; the
orthonormal DCT-II (JTransforms' ``DoubleDCT_1D.forward(a, true)`` scaling:
factor √(2/N) with the DC term divided by √2) is applied along every
dimension, the inverse being the orthonormal DCT-III. On device this is a
dense matmul per axis — exactly the MXU's shape, unlike the reference's
per-row JTransforms loop."""

from __future__ import annotations

import numpy as np


def _dct_matrix(n: int) -> np.ndarray:
    """Orthonormal DCT-II matrix C (forward y = C @ x; inverse x = Cᵀ @ y)."""
    k = np.arange(n)[:, None]
    i = np.arange(n)[None, :]
    C = np.sqrt(2.0 / n) * np.cos(np.pi * (i + 0.5) * k / n)
    C[0] /= np.sqrt(2.0)
    return C


def dct_frame(X: np.ndarray, width: int, height: int = 1, depth: int = 1,
              inverse: bool = False) -> np.ndarray:
    """(R, W*H*D) row-major signals → transformed, same shape.

    Matches `MathUtils.DCT.initCheck`: dimensions must multiply to the
    column count, values must be finite."""
    import jax.numpy as jnp

    if width < 1 or height < 1 or depth < 1:
        raise ValueError("dimensions must be >= 1")
    if width * height * depth != X.shape[1]:
        raise ValueError("dimensions WxHxD must match the # columns "
                         f"of the frame ({X.shape[1]})")
    if np.isnan(X).any():
        raise ValueError("DCT can not be computed on rows with missing "
                         "values")
    R = X.shape[0]
    sig = jnp.asarray(X, dtype=jnp.float32).reshape(R, width, height, depth)
    for axis, n in ((1, width), (2, height), (3, depth)):
        if n == 1:
            continue
        C = jnp.asarray(_dct_matrix(n), dtype=jnp.float32)
        M = C.T if inverse else C
        sig = jnp.moveaxis(
            jnp.tensordot(sig, M, axes=[[axis], [1]]), -1, axis)
    return np.asarray(sig.reshape(R, -1))
