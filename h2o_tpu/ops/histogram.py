"""Pallas TPU kernel: fused score+bin histogram accumulation.

The GBM hot loop (`hex/tree/ScoreBuildHistogram2.java:16-62`) accumulates, per
tree level, a {w, g, h} histogram over (feature, leaf, bin). The XLA path
(`models/tree/engine.py:_build_level_hist`) expresses this as two one-hot
expansions feeding one einsum per row-block inside a `lax.scan`.

This kernel is the same contraction — lhs (rows, n_lv·V) = leaf-one-hot ⊗
channel values, rhs (rows, F·B) = bin-one-hot, accumulated as an MXU matmul
into a VMEM-resident (n_lv·V, F·B) histogram — but with both one-hots
materialized ONLY in VMEM per row-tile and the accumulator pinned in VMEM
across the whole row grid (the analog of ScoreBuildHistogram2's private
per-thread histograms, merged for free because the TPU grid is sequential).
Nothing but the final histogram touches HBM.

MXU shape note: for V=3 channels the lhs sublane extent is n_lv·3 ≤ 96 up to
depth 5, so the systolic array is well utilized; the bin one-hot's lane extent
F·B is a multiple of 128 only by padding, which the compiler handles.

Outside shard_map callers psum the result over the `rows` mesh axis exactly as
the XLA path does — the kernel is shard-local.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..parallel import mesh as meshmod


def _row_tile(rl: int, want: int) -> int:
    if rl % want == 0:
        return want
    b = 1
    while b * 2 <= want and rl % (b * 2) == 0:
        b *= 2
    return b if rl % b == 0 else rl


def _hist_kernel(xb_ref, lc_ref, vals_ref, out_ref, *, n_lv: int, B: int):
    """All shapes stay 2-D (Mosaic rejects minor-dim reshapes): the ⊗ and
    one-hot expansions are built with iota arithmetic + tiny selection-matrix
    matmuls instead of reshape/tile."""
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)

    xb = xb_ref[:]                     # (TR, F) int32 bin ids
    lc = lc_ref[:]                     # (TR, 1) int32 local leaf ids
    vals = vals_ref[:]                 # (TR, V) f32 channels (masked upstream)
    TR, F = xb.shape
    V = vals.shape[1]
    M = n_lv * V

    # lhs (TR, M): column c ≙ (leaf n = c//V, channel v = c%V);
    # value = (lc == n) * vals[:, v]
    c_m = jax.lax.broadcasted_iota(jnp.int32, (TR, M), 1)
    n_oh = (lc == c_m // V).astype(jnp.float32)               # (TR, M)
    sel_v = (jax.lax.broadcasted_iota(jnp.int32, (V, M), 1) % V
             == jax.lax.broadcasted_iota(jnp.int32, (V, M), 0)
             ).astype(jnp.float32)                            # (V, M) const
    # HIGHEST: vals are real f32 gradients/hessians — a default bf16 multiply
    # would round every histogram contribution by 2^-9 (engine.py's hi/lo
    # trick is the fast path; this opt-in kernel favors exactness)
    vals_exp = jnp.dot(vals, sel_v, precision=jax.lax.Precision.HIGHEST,
                       preferred_element_type=jnp.float32)
    lhs = n_oh * vals_exp

    # rhs (TR, F*B): column c ≙ (feature f = c//B, bin b = c%B);
    # value = (xb[:, f] == b)
    FB = F * B
    sel_f = (jax.lax.broadcasted_iota(jnp.int32, (F, FB), 1) // B
             == jax.lax.broadcasted_iota(jnp.int32, (F, FB), 0)
             ).astype(jnp.float32)                            # (F, FB) const
    xb_exp = jnp.dot(xb.astype(jnp.float32), sel_f,
                     precision=jax.lax.Precision.HIGHEST,  # bin ids must stay
                     preferred_element_type=jnp.float32)   # ==-exact (TR, FB)
    b_m = (jax.lax.broadcasted_iota(jnp.int32, (TR, FB), 1) % B
           ).astype(jnp.float32)
    rhs = (xb_exp == b_m).astype(jnp.float32)

    out_ref[:] += jax.lax.dot_general(
        lhs, rhs, (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,  # lhs holds real f32 channels
        preferred_element_type=jnp.float32)                   # (M, F*B)


@functools.partial(jax.jit, static_argnames=("n_lv", "B", "tile", "interpret"))
def _hist_call(Xb, lc, vals, n_lv: int, B: int, tile: int, interpret: bool):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    Rl, F = Xb.shape
    V = vals.shape[1]
    TR = _row_tile(Rl, tile)
    grid = (Rl // TR,)

    kernel = functools.partial(_hist_kernel, n_lv=n_lv, B=B)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TR, F), lambda i: (i, 0)),
            pl.BlockSpec((TR, 1), lambda i: (i, 0)),
            pl.BlockSpec((TR, V), lambda i: (i, 0)),
        ],
        # accumulator: same block every grid step → stays resident in VMEM
        out_specs=pl.BlockSpec((n_lv * V, F * B), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_lv * V, F * B), jnp.float32),
        interpret=interpret,
    )(Xb, lc.reshape(-1, 1), vals)
    # rows are (leaf-major, channel-minor): (n_lv*V, F*B) → (F, n_lv, B, V)
    return out.reshape(n_lv, V, F, B).transpose(2, 0, 3, 1)


def use_pallas_default() -> bool:
    """Measured on v5e: the whole-shard one-hot einsum (XLA fuses it into a
    single MXU contraction) beats this kernel once per-row gathers were
    eliminated from routing (engine.py) — the kernel's per-grid-step overhead
    dominates at histogram sizes. Kept opt-in (TreeConfig.use_pallas=True)
    as the substrate for deeper-tree / wider-bin configs where the one-hot
    HBM materialization starts to matter."""
    return False


def build_level_hist_pallas(Xb, node, vals, offset: int, n_lv: int, B: int,
                            tile: int = 2048, interpret: bool | None = None):
    """Drop-in for engine._build_level_hist's accumulation (pre-psum).

    Xb (Rl, F) int32; node (Rl,) int32 global ids; vals (Rl, V) f32.
    Rows outside [offset, offset+n_lv) contribute nothing.
    """
    if interpret is None:
        # compile via Mosaic on TPU; emulate elsewhere (CPU test mesh)
        interpret = jax.default_backend() != "tpu" 
    local = node - offset
    active = (local >= 0) & (local < n_lv)
    lc = jnp.where(active, local, n_lv)  # n_lv = dead leaf id, matches no iota
    v = jnp.where(active[:, None], vals, 0.0)
    return _hist_call(Xb, lc.astype(jnp.int32), v, n_lv, B, tile, interpret)
