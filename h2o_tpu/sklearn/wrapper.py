"""sklearn BaseEstimator bridge for h2o_tpu model builders.

Reference: `h2o-py/h2o/sklearn/wrapper.py` (`H2OtoSklearnEstimator`,
`BaseSklearnEstimator`) — params round-trip through get_params/set_params so
`sklearn.base.clone`, pipelines, and grid search work; fit/predict convert
between numpy/pandas and engine Frames.
"""

from __future__ import annotations

import numpy as np

from ..frame.frame import Frame
from ..frame.vec import T_CAT, Vec


try:  # sklearn's bases provide __sklearn_tags__ for pipeline/CV integration
    from sklearn.base import BaseEstimator as _SkBase
    from sklearn.base import ClassifierMixin as _SkClassifier
    from sklearn.base import RegressorMixin as _SkRegressor
except ImportError:  # pragma: no cover — adapters still importable bare
    _SkBase = object

    class _SkClassifier:  # noqa: N801
        pass

    class _SkRegressor:  # noqa: N801
        pass


class _BaseAdapter(_SkBase):
    """Minimal BaseEstimator contract (get_params/set_params via a params
    dict, so **kwargs __init__ stays clone-compatible)."""

    _algo = None  # registry name

    def __init__(self, **params):
        self._params = dict(params)
        self._model = None
        self._classes = None

    # sklearn plumbing --------------------------------------------------------
    def get_params(self, deep=True):
        return dict(self._params)

    def set_params(self, **params):
        self._params.update(params)
        return self

    @classmethod
    def _get_param_names(cls):
        return []

    def __sklearn_clone__(self):
        return type(self)(**self.get_params())

    def _repr_mimebundle_(self, **kw):  # keep sklearn's html repr quiet
        return {"text/plain": repr(self)}

    def __repr__(self):
        return f"{type(self).__name__}({self._params})"

    # data conversion ---------------------------------------------------------
    @staticmethod
    def _to_frame(X, y=None, classification=False):
        cols = {}
        if hasattr(X, "columns"):  # pandas
            names = [str(c) for c in X.columns]
            X = np.asarray(X, dtype=np.float32)
        else:
            X = np.asarray(X, dtype=np.float32)
            names = [f"x{j}" for j in range(X.shape[1])]
        for j, n in enumerate(names):
            cols[n] = X[:, j]
        fr = Frame.from_dict(cols)
        classes = None
        if y is not None:
            y = np.asarray(y)
            if classification:
                classes, codes = np.unique(y, return_inverse=True)
                fr.add("__response__", Vec.from_numpy(
                    codes.astype(np.float32), type=T_CAT,
                    domain=[str(c) for c in classes]))
            else:
                fr.add("__response__",
                       Vec.from_numpy(np.asarray(y, dtype=np.float32)))
        return fr, names, classes

    def _train(self, fr, extra=None):
        from ..models import registry

        entry = registry.lookup(self._algo)
        algo_cls, params_cls = entry[0], entry[1]
        kw = dict(self._params)
        kw.update(extra or {})
        kw["training_frame"] = fr
        self._model = algo_cls(params_cls(**kw)).train_model()
        # trailing-underscore attrs mark the estimator fitted for sklearn's
        # check_is_fitted (wrapper.py sets the same convention)
        self.fitted_ = True
        self.n_features_in_ = fr.ncol - (1 if "__response__" in fr.names
                                         else 0)
        return self

    def _predict_frame(self, X):
        fr, _, _ = self._to_frame(X)
        return self._model.predict(fr)


class H2ORegressorMixin(_SkRegressor):
    _estimator_type = "regressor"

    def fit(self, X, y):
        fr, self._names, _ = self._to_frame(X, y, classification=False)
        return self._train(fr, {"response_column": "__response__"})

    def predict(self, X):
        return self._predict_frame(X).vec(0).to_numpy().astype(np.float64)

    def score(self, X, y):
        pred = self.predict(X)
        y = np.asarray(y, dtype=np.float64)
        ss_res = float(((y - pred) ** 2).sum())
        ss_tot = float(((y - y.mean()) ** 2).sum())
        return 1.0 - ss_res / max(ss_tot, 1e-300)


class H2OClassifierMixin(_SkClassifier):
    _estimator_type = "classifier"

    def fit(self, X, y):
        fr, self._names, self._classes = self._to_frame(
            X, y, classification=True)
        return self._train(fr, {"response_column": "__response__"})

    @property
    def classes_(self):
        return self._classes

    def predict(self, X):
        out = self._predict_frame(X)
        labels = out.vec(0).to_numpy().astype(np.int64)
        return self._classes[labels]

    def predict_proba(self, X):
        out = self._predict_frame(X)
        K = len(self._classes)
        return np.stack([out.vec(1 + k).to_numpy() for k in range(K)],
                        axis=1).astype(np.float64)

    def score(self, X, y):
        return float(np.mean(self.predict(X) == np.asarray(y)))


class _UnsupervisedMixin:
    """KMeans / IsolationForest style: fit(X) with no response."""

    def fit(self, X, y=None):
        fr, self._names, _ = self._to_frame(X)
        return self._train(fr)

    def predict(self, X):
        out = self._predict_frame(X)
        return out.vec(0).to_numpy().astype(np.float64)

    def transform(self, X):
        out = self._predict_frame(X)
        return np.stack([out.vec(j).to_numpy() for j in range(out.ncol)],
                        axis=1).astype(np.float64)

    def fit_transform(self, X, y=None):
        return self.fit(X).transform(X)


# algo → (ClassifierName, RegressorName) or (single wrapper name, mixin)
_SUPERVISED = {
    "gbm": "H2OGradientBoosting",
    "drf": "H2ORandomForest",
    "glm": "H2OGeneralizedLinear",
    "deeplearning": "H2ODeepLearning",
    "xgboost": "H2OXGBoost",
    "naivebayes": "H2ONaiveBayes",  # classifier only
    "adaboost": "H2OAdaBoost",      # classifier only
}
_CLASSIFIER_ONLY = {"naivebayes", "adaboost"}
_UNSUPERVISED = {
    "kmeans": "H2OKMeansEstimator",
    "isolationforest": "H2OIsolationForestEstimator",
    "extendedisolationforest": "H2OExtendedIsolationForestEstimator",
    "pca": "H2OPCAEstimator",
}


def make_sklearn_classes() -> dict:
    """Generate the adapter classes (the reference's module-import-time
    `_algo_to_classes` generation loop)."""
    out = {}
    for algo, stem in _SUPERVISED.items():
        out[f"{stem}Classifier"] = type(
            f"{stem}Classifier", (H2OClassifierMixin, _BaseAdapter),
            {"_algo": algo})
        if algo not in _CLASSIFIER_ONLY:
            out[f"{stem}Regressor"] = type(
                f"{stem}Regressor", (H2ORegressorMixin, _BaseAdapter),
                {"_algo": algo})
    for algo, name in _UNSUPERVISED.items():
        out[name] = type(name, (_UnsupervisedMixin, _BaseAdapter),
                         {"_algo": algo})
    return out
