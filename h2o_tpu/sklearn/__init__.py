"""scikit-learn adapters — `h2o-py/h2o/sklearn/` analog.

The reference generates `H2O<Algo>Classifier` / `H2O<Algo>Regressor` classes
wrapping each estimator behind sklearn's fit/predict/get_params contract
(`h2o-py/h2o/sklearn/__init__.py` `_algo_to_classes`, `wrapper.py`
`H2OtoSklearnEstimator`). Same shape here: thin BaseEstimator wrappers that
convert numpy/pandas X, y into engine Frames and train in-process on the
device mesh (no REST hop — the adapter is a library-boundary surface).
"""

from .wrapper import (H2OClassifierMixin, H2ORegressorMixin,
                      make_sklearn_classes)

_GENERATED = make_sklearn_classes()
globals().update(_GENERATED)

__all__ = sorted(_GENERATED) + ["H2OClassifierMixin", "H2ORegressorMixin",
                                "make_sklearn_classes"]
