"""Flow — the notebook IDE (`h2o-web` analog, served at /flow).

The reference ships h2o-flow: a CoffeeScript notebook whose cells hold Flow
routines (`importFiles`, `buildModel`, `getFrames`, …) that expand into REST
calls, with notebooks saved into NodePersistentStorage under the "notebook"
category and an assist menu that inserts template cells
(`h2o-web/README.md:1-20`).

This is the same shape without a build step: one static page, vanilla JS.

- **Cells**: each holds a Flow expression (`routine arg` — args are JSON) or
  markdown (`md:` prefix). Shift+Enter runs a cell and renders its result
  (tables for frames/models/jobs, JSON otherwise); cells insert/delete/move.
- **Routines** (the Flow language core, same names as the reference):
  `assist`, `importFiles ["path"]`, `setupParse {...}`, `parseFiles {...}`,
  `getFrames`, `getFrameSummary "id"`, `getFrameData "id"`, `splitFrame
  {...}`, `getModels`, `getModel "id"`, `buildModel "algo", {params}`,
  `predict {model:, frame:}`, `getJobs`, `getJob "id"`, `rapids "(expr)"`,
  `deleteFrame "id"`, `deleteModel "id"`.
- **Notebooks**: save/load/list through `/3/NodePersistentStorage/notebook`
  exactly like the reference's Flow persistence.
- **Assist**: inserts ready-to-edit template cells for the common verbs.
"""

FLOW_HTML = r"""<!doctype html><html><head><title>h2o_tpu flow</title><style>
body{font-family:-apple-system,'Segoe UI',sans-serif;margin:0;background:#f4f4f2;color:#222}
#top{background:#1b1b1b;color:#eee;padding:.5em 1em;display:flex;gap:1em;align-items:center}
#top b{color:#ffd24d}#top button,#top input{font-size:.85em}
#cloudinfo{margin-left:auto;color:#9c9;font-size:.8em}
#nb{max-width:1000px;margin:1em auto;padding:0 1em}
.cell{background:#fff;border:1px solid #ddd;border-left:4px solid #ccc;margin:.6em 0;border-radius:2px}
.cell.active{border-left-color:#ffd24d;box-shadow:0 1px 4px rgba(0,0,0,.12)}
.cellbar{display:flex;gap:.3em;padding:2px 6px;background:#fafafa;border-bottom:1px solid #eee}
.cellbar button{border:none;background:none;cursor:pointer;color:#888;font-size:.8em;padding:1px 5px}
.cellbar button:hover{color:#000}
textarea{width:100%;border:none;resize:vertical;font-family:ui-monospace,monospace;
 font-size:.95em;padding:.5em .7em;box-sizing:border-box;min-height:2.2em;outline:none;background:#fffef8}
.out{padding:.4em .8em;border-top:1px dashed #eee;overflow-x:auto}
.out table{border-collapse:collapse;font-size:.85em;font-family:ui-monospace,monospace}
.out td,.out th{border:1px solid #ddd;padding:2px 8px;text-align:left}
.out th{background:#f0f0ea}
.err{color:#b00020;font-family:ui-monospace,monospace;white-space:pre-wrap}
.md{padding:.6em .9em}
pre{margin:.3em 0;font-size:.85em;white-space:pre-wrap}
progress{width:160px;height:10px}
#assist{position:fixed;right:1em;top:3.2em;background:#fff;border:1px solid #ccc;
 padding:.6em;border-radius:3px;box-shadow:0 2px 8px rgba(0,0,0,.15);display:none}
#assist a{display:block;padding:2px 4px;color:#06c;cursor:pointer;font-size:.9em}
a{color:#06c;cursor:pointer}
.badge{font-size:.7em;color:#999;padding-left:.5em}
</style></head><body>
<div id=top>
 <b>H2O Flow</b>
 <button onclick="newCellBelow(-1)">+ cell</button>
 <button onclick="runAll()">&#9654; run all</button>
 <button onclick="toggleAssist()">assist</button>
 <input id=nbname placeholder="notebook name" size=16>
 <button onclick="saveNotebook()">save</button>
 <button onclick="listNotebooks()">open&hellip;</button>
 <span id=savemsg class=badge></span>
 <span id=cloudinfo>connecting&hellip;</span>
</div>
<div id=assist></div>
<div id=nb></div>
<script>
'use strict';
async function J(u, opts){const r = await fetch(u, opts);
 let body; const ct = r.headers.get('content-type')||'';
 if(ct.includes('json')) body = await r.json(); else body = await r.text();
 if(!r.ok) throw new Error((body && body.msg) || r.statusText);
 return body}
function el(tag, attrs, kids){const e = document.createElement(tag);
 Object.assign(e, attrs||{}); (kids||[]).forEach(k=>e.appendChild(k)); return e}
function txt(s){return document.createTextNode(s)}

/* ------------------------------------------------------------------ cells */
let cells = [];   // {input, outEl, taEl, wrapEl}
let activeIdx = -1;
const nb = document.getElementById('nb');

function renderCells(){
 nb.replaceChildren();
 cells.forEach((c, i)=>{
  const ta = el('textarea', {value: c.input, spellcheck: false});
  ta.rows = Math.max(1, c.input.split('\n').length);
  ta.onfocus = ()=>setActive(i);
  ta.oninput = ()=>{c.input = ta.value;
   ta.rows = Math.max(1, ta.value.split('\n').length)};
  ta.onkeydown = (ev)=>{ if(ev.key === 'Enter' && ev.shiftKey){
    ev.preventDefault(); runCell(i);} };
  const bar = el('div', {className:'cellbar'}, [
   el('button', {textContent:'▶ run', onclick:()=>runCell(i)}),
   el('button', {textContent:'+ below', onclick:()=>newCellBelow(i)}),
   el('button', {textContent:'↑', onclick:()=>moveCell(i,-1)}),
   el('button', {textContent:'↓', onclick:()=>moveCell(i, 1)}),
   el('button', {textContent:'✕', onclick:()=>deleteCell(i)}),
  ]);
  // reuse the LIVE output node (innerHTML round-trips would drop the
  // onclick handlers on result links)
  let out = c.outEl;
  if(!out){out = el('div', {className:'out'}); out.style.display = 'none';}
  const wrap = el('div', {className:'cell' + (i===activeIdx?' active':'')},
                  [bar, ta, out]);
  c.taEl = ta; c.outEl = out; c.wrapEl = wrap;
  nb.appendChild(wrap);
 });
 if(!cells.length) newCellBelow(-1);
}
function setActive(i){activeIdx = i;
 cells.forEach((c,k)=>c.wrapEl && c.wrapEl.classList.toggle('active', k===i))}
function newCellBelow(i, input){cells.splice(i+1, 0, {input: input||''});
 renderCells(); setActive(i+1);
 if(cells[i+1].taEl) cells[i+1].taEl.focus()}
function deleteCell(i){cells.splice(i,1); renderCells()}
function moveCell(i,d){const k=i+d; if(k<0||k>=cells.length) return;
 const t=cells[i]; cells[i]=cells[k]; cells[k]=t; renderCells()}
async function runAll(){for(let i=0;i<cells.length;i++) await runCell(i)}

function setOut(i, node){const c = cells[i];
 c.outEl.style.display=''; c.outEl.replaceChildren(node)}
function setErr(i, msg){setOut(i, el('div',{className:'err',textContent:msg}))}

/* ------------------------------------------------------- result rendering */
function table(heads, rows, links){
 const t = el('table');
 t.appendChild(el('tr', {}, heads.map(h=>el('th',{textContent:h}))));
 rows.forEach((r, ri)=>{
  t.appendChild(el('tr', {}, r.map((v, ci)=>{
   const td = el('td');
   if(links && links[ci]){const a = el('a',{textContent: v==null?'':v});
    a.onclick = ()=>links[ci](r, ri); td.appendChild(a);}
   else td.textContent = v==null?'':v;
   return td})))});
 return t}
function jsonOut(o){return el('pre',{textContent: JSON.stringify(o,null,1)
  .slice(0, 20000)})}

/* ------------------------------------------------------ the Flow language */
function parseCell(src){
 src = src.trim();
 if(src.startsWith('md:')) return {md: src.slice(3)};
 const m = src.match(/^([A-Za-z_][A-Za-z0-9_]*)\s*([\s\S]*)$/);
 if(!m) throw new Error('cannot parse cell; expected: routine [json args]');
 let rest = m[2].trim();
 const args = [];
 // split top-level comma-separated JSON values: "gbm", {...} — quote-aware
 // so commas/brackets inside string args survive
 let depth = 0, cur = '', inq = null, prevc = '';
 for(const ch of rest){
  if(inq){ cur += ch; if(ch === inq && prevc !== String.fromCharCode(92))
    inq = null; prevc = ch; continue; }
  if(ch === String.fromCharCode(34) || ch === String.fromCharCode(39))
   inq = ch;
  if('[{'.includes(ch)) depth++;
  if(']}'.includes(ch)) depth--;
  if(ch === ',' && depth === 0){args.push(cur); cur='';} else cur += ch;
  prevc = ch;
 }
 if(cur.trim()) args.push(cur);
 return {routine: m[1], args: args.map(a=>{
  a = a.trim();
  try{return JSON.parse(a)}catch(e){
   const q = a.charAt(0);
   if((q === String.fromCharCode(34) || q === String.fromCharCode(39))
      && a.endsWith(q)) return a.slice(1, -1);
   return a}})};
}

async function pollJob(jobjson, onTick){
 let key = jobjson.job && jobjson.job.key ? jobjson.job.key.name : null;
 if(!key) return jobjson.job || jobjson;
 for(;;){
  const jj = (await J('/3/Jobs/'+encodeURIComponent(key))).jobs[0];
  if(onTick) onTick(jj);
  if(jj.status === 'DONE') return jj;
  if(jj.status === 'FAILED' || jj.status === 'CANCELLED')
   throw new Error(jj.status + ': ' + (jj.exception||''));
  await new Promise(res=>setTimeout(res, 250));
 }
}

const ROUTINES = {
 async assist(){ return el('div', {}, Object.keys(TEMPLATES).map(k=>{
   const a = el('a',{textContent:k});
   a.onclick=()=>newCellBelow(activeIdx, TEMPLATES[k]); return a;})) },

 async importFiles(i, paths){
  if(typeof paths === 'string') paths = [paths];
  const out = [];
  for(const p of paths){
   const imp = await J('/3/ImportFiles?path='+encodeURIComponent(p));
   const fs = imp.files||[];
   for(const f of fs) out.push(f);
  }
  return el('div', {}, [txt('imported: '+out.join(', ')),
   el('div',{},[el('a',{textContent:'↳ setupParse',
    onclick:()=>newCellBelow(activeIdx,
     'setupParse {"source_frames": '+JSON.stringify(out)+'}')})])]);
 },

 async setupParse(i, spec){
  const s = await J('/3/ParseSetup', {method:'POST',
   headers:{'Content-Type':'application/json'}, body: JSON.stringify(spec)});
  const tmpl = {source_frames: spec.source_frames,
                destination_frame: s.destination_frame};
  return el('div', {}, [
   table(['destination','columns','parse type'],
         [[s.destination_frame, (s.column_names||[]).length,
           s.parse_type||'CSV']]),
   el('a',{textContent:'↳ parseFiles', onclick:()=>newCellBelow(
     activeIdx, 'parseFiles '+JSON.stringify(tmpl))})]);
 },

 async parseFiles(i, spec){
  const job = await J('/3/Parse', {method:'POST',
   headers:{'Content-Type':'application/json'}, body: JSON.stringify(spec)});
  const done = await pollJob(job, jj=>setOut(i,
   el('div',{},[txt('parsing '), el('progress',{max:1,value:jj.progress})])));
  return el('div', {}, [txt('frame '),
   el('a',{textContent: done.dest.name,
    onclick:()=>newCellBelow(activeIdx,
     'getFrameSummary "'+done.dest.name+'"')})]);
 },

 async getFrames(){
  const f = await J('/3/Frames');
  return table(['frame', 'rows', 'columns'],
   f.frames.map(x=>[x.frame_id.name, x.rows, x.num_columns]),
   [(r)=>newCellBelow(activeIdx, 'getFrameSummary "'+r[0]+'"')]);
 },

 async getFrameSummary(i, id){
  const f = await J('/3/Frames/'+encodeURIComponent(id)+'/summary');
  const fr = f.frames[0];
  return el('div', {}, [
   el('b',{textContent: id+' — '+fr.rows+' rows × '+
           fr.num_columns+' cols'}),
   table(['column','type','mean','sigma','missing'],
    fr.columns.map(c=>[c.label, c.type, fmt(c.mean), fmt(c.sigma),
                       c.missing_count]))]);
 },

 async getFrameData(i, id){
  const f = await J('/3/Frames/'+encodeURIComponent(id)+'?row_count=10');
  const fr = f.frames[0];
  const heads = fr.columns.map(c=>c.label);
  const n = Math.min(10, fr.rows);
  const rows = [];
  for(let r=0;r<n;r++) rows.push(fr.columns.map(c=>{
   const d = c.data || c.string_data; let v = d ? d[r] : null;
   if(v != null && c.domain && c.type==='enum') v = c.domain[v];
   return fmt(v)}));
  return table(heads, rows);
 },

 async splitFrame(i, spec){
  const res = await J('/3/SplitFrame', {method:'POST',
   headers:{'Content-Type':'application/json'}, body: JSON.stringify(spec)});
  return jsonOut(res.destination_frames);
 },

 async buildModel(i, algo, params){
  const job = await J('/3/ModelBuilders/'+encodeURIComponent(algo),
   {method:'POST', headers:{'Content-Type':'application/json'},
    body: JSON.stringify(params)});
  const done = await pollJob(job, jj=>setOut(i,
   el('div',{},[txt('training '+algo+' '),
    el('progress',{max:1,value:jj.progress})])));
  return el('div', {}, [txt('model '),
   el('a',{textContent: done.dest.name, onclick:()=>newCellBelow(
     activeIdx, 'getModel "'+done.dest.name+'"')})]);
 },

 async getModels(){
  const m = await J('/3/Models');
  return table(['model','algo','category'],
   m.models.map(x=>[x.model_id.name, x.algo,
    (x.output||{}).model_category]),
   [(r)=>newCellBelow(activeIdx, 'getModel "'+r[0]+'"')]);
 },

 async getModel(i, id){
  const res = await J('/3/Models/'+encodeURIComponent(id));
  const m = res.models[0];
  const out = m.output || {};
  const kids = [el('b',{textContent: id+' ('+m.algo+', '+
                        out.model_category+')'})];
  const tm = out.training_metrics || {};
  const rows = Object.entries(tm).filter(kv=>typeof kv[1] === 'number')
    .map(kv=>[kv[0], fmt(kv[1])]);
  if(rows.length) kids.push(table(['metric','training'], rows));
  const vi = out.variable_importances;
  if(vi && vi.variable){
   const vrows = vi.variable.map((v, k)=>[v, fmt(vi.scaled_importance[k]),
                                          fmt(vi.percentage[k])]);
   kids.push(el('b',{textContent:'variable importances'}));
   kids.push(table(['variable','scaled','percentage'], vrows));
  }
  kids.push(el('a',{textContent:'↳ predict', onclick:()=>newCellBelow(
    activeIdx, 'predict {"model": "'+id+'", "frame": "<frame-id>"}')}));
  return el('div', {}, kids);
 },

 async predict(i, spec){
  const res = await J('/3/Predictions/models/'+
   encodeURIComponent(spec.model)+'/frames/'+
   encodeURIComponent(spec.frame), {method:'POST'});
  const out = res.predictions_frame.name;
  return el('div', {}, [txt('predictions '),
   el('a',{textContent: out,
    onclick:()=>newCellBelow(activeIdx, 'getFrameData "'+out+'"')})]);
 },

 async getJobs(){
  const jbs = await J('/3/Jobs');
  return table(['job','description','status','progress'],
   jbs.jobs.map(x=>[x.key.name, x.description, x.status,
                    fmt(x.progress)]));
 },

 async getJob(i, id){
  return jsonOut((await J('/3/Jobs/'+encodeURIComponent(id))).jobs[0]);
 },

 async rapids(i, expr){
  const res = await J('/99/Rapids', {method:'POST',
   headers:{'Content-Type':'application/json'},
   body: JSON.stringify({ast: expr})});
  if(res.key) return el('div',{},[txt('frame '),
   el('a',{textContent:res.key.name, onclick:()=>newCellBelow(
     activeIdx, 'getFrameData "'+res.key.name+'"')})]);
  return jsonOut(res.scalar !== null && res.scalar !== undefined ?
                 res.scalar : (res.values || res.string || res));
 },

 async deleteFrame(i, id){
  await J('/3/Frames/'+encodeURIComponent(id), {method:'DELETE'});
  return txt('deleted '+id);
 },
 async deleteModel(i, id){
  await J('/3/Models/'+encodeURIComponent(id), {method:'DELETE'});
  return txt('deleted '+id);
 },
};

function fmt(v){return typeof v === 'number' && isFinite(v) ?
 (Number.isInteger(v)? v : v.toPrecision(5)) : v}

const TEMPLATES = {
 'import files':   'importFiles ["/path/to/data.csv"]',
 'list frames':    'getFrames',
 'inspect frame':  'getFrameSummary "<frame-id>"',
 'peek rows':      'getFrameData "<frame-id>"',
 'split frame':    'splitFrame {"dataset": "<frame-id>", "ratios": [0.75]}',
 'build model':    'buildModel "gbm", {"training_frame": "<frame-id>", ' +
                   '"response_column": "<y>", "ntrees": 20}',
 'list models':    'getModels',
 'predict':        'predict {"model": "<model-id>", "frame": "<frame-id>"}',
 'rapids':         'rapids "(mean (cols <frame-id> 0) true)"',
 'jobs':           'getJobs',
 'markdown':       'md: ## notes',
};

async function runCell(i){
 const c = cells[i]; setActive(i);
 let parsed;
 try{parsed = parseCell(c.input)}catch(e){return setErr(i, e.message)}
 if(parsed.md !== undefined){
  const d = el('div',{className:'md'});
  d.innerHTML = mdLite(parsed.md); return setOut(i, d)}
 const fn = ROUTINES[parsed.routine];
 if(!fn) return setErr(i, 'unknown routine "'+parsed.routine+
                          '" — try assist');
 try{ setOut(i, txt('…'));
  const node = await fn(i, ...parsed.args);
  setOut(i, node || txt('ok'));
 }catch(e){ setErr(i, String(e.message||e)) }
}
function mdLite(s){return s.split(/\n/).map(l=>{
 if(l.startsWith('## ')) return '<h3>'+esc(l.slice(3))+'</h3>';
 if(l.startsWith('# '))  return '<h2>'+esc(l.slice(2))+'</h2>';
 return '<p>'+esc(l)+'</p>'}).join('')}
function esc(s){return s.replace(/[&<>]/g,
 c=>({'&':'&amp;','<':'&lt;','>':'&gt;'}[c]))}

/* -------------------------------------------------- notebook save / load */
async function saveNotebook(){
 const name = document.getElementById('nbname').value.trim() || 'unnamed';
 const flowObj = {version: 1, cells: cells.map(c=>({input: c.input}))};
 await J('/3/NodePersistentStorage/notebook/'+encodeURIComponent(name),
  {method:'POST', headers:{'Content-Type':'application/json'},
   body: JSON.stringify({value: JSON.stringify(flowObj)})});
 document.getElementById('savemsg').textContent =
  'saved '+name+' @ '+new Date().toLocaleTimeString();
}
async function listNotebooks(){
 const res = await J('/3/NodePersistentStorage/notebook');
 const names = (res.entries||[]).map(e=>e.name||e);
 const box = document.getElementById('assist');
 box.replaceChildren(el('b',{textContent:'notebooks'}),
  ...names.map(n=>{const a = el('a',{textContent:n});
   a.onclick=()=>{loadNotebook(n); box.style.display='none'}; return a}),
  names.length?txt(''):txt(' (none saved)'));
 box.style.display='block';
}
async function loadNotebook(name){
 const raw = await J('/3/NodePersistentStorage/notebook/'+
                     encodeURIComponent(name));
 const obj = typeof raw === 'string' ? JSON.parse(raw) : raw;
 cells = (obj.cells||[]).map(c=>({input: c.input}));
 document.getElementById('nbname').value = name;
 renderCells();
}
function toggleAssist(){
 const box = document.getElementById('assist');
 if(box.style.display === 'block'){box.style.display='none'; return}
 box.replaceChildren(el('b',{textContent:'assist'}),
  ...Object.keys(TEMPLATES).map(k=>{const a = el('a',{textContent:k});
   a.onclick=()=>{newCellBelow(activeIdx, TEMPLATES[k]);
    box.style.display='none'}; return a}));
 box.style.display='block';
}

/* ------------------------------------------------------------------ boot */
(async function(){
 try{
  const c = await J('/3/Cloud');
  document.getElementById('cloudinfo').textContent =
   c.cloud_name+' · '+c.cloud_size+' node · '+(c.version||'tpu');
 }catch(e){
  document.getElementById('cloudinfo').textContent = 'cloud unreachable';
 }
 cells = [{input: 'assist'}, {input: 'getFrames'}];
 renderCells();
})();
</script></body></html>"""
