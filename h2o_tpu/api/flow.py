"""Minimal interactive Flow — the `h2o-web` notebook's working core.

One static HTML page (no build step) over the JSON API: list/inspect
frames, import a file, launch a training run with live job progress, and
inspect the resulting model's metrics. The reference ships a full
CoffeeScript notebook IDE (`h2o-web/README.md:1-20`); this covers the
quickstart's browser flow end-to-end against the same REST routes.
"""

FLOW_HTML = """<!doctype html><html><head><title>h2o_tpu flow</title><style>
body{font-family:monospace;margin:1.5em;background:#fafafa;color:#222}
h1{color:#333;margin-bottom:0}h2{color:#444;border-bottom:1px solid #ddd}
table{border-collapse:collapse;margin:.6em 0}td,th{border:1px solid #ccc;
padding:3px 9px;text-align:left}th{background:#eee}
a{color:#06c;cursor:pointer;text-decoration:underline}
input,select{font-family:monospace;margin:2px;padding:2px 4px}
button{font-family:monospace;padding:3px 10px;cursor:pointer}
#detail{background:#fff;border:1px solid #ccc;padding:.8em;margin:.8em 0}
.err{color:#b00}.ok{color:#080}#jobstate{font-weight:bold}
small{color:#777}</style></head><body>
<h1>h2o_tpu</h1><div id=cloud><small>connecting…</small></div>

<h2>Import</h2>
<form id=importform onsubmit="return doImport(event)">
<input id=importpath size=60 placeholder="/path/or/uri/to/data.csv">
<button>Import &amp; parse</button> <span id=importmsg></span></form>

<h2>Frames</h2><table id=frames></table>

<h2>Train</h2>
<form id=trainform onsubmit="return doTrain(event)">
algo <select id=algo></select>
frame <select id=trframe></select>
response <select id=trresp></select>
params <input id=trparams size=32 placeholder='{"ntrees": 20}'>
<button>Train</button>
<div>job <span id=jobkey>—</span> <span id=jobstate></span>
<progress id=jobbar max=1 value=0></progress> <span id=jobmsg></span></div>
</form>

<h2>Models</h2><table id=models></table>
<h2>Jobs</h2><table id=jobs></table>
<div id=detail><small>click a frame or model key to inspect it</small></div>

<script>
async function j(u, opts){const r = await fetch(u, opts);
 const body = await r.json();
 if(!r.ok) throw new Error(body.msg || r.statusText); return body}
function row(cells, links){const tr = document.createElement('tr');
 cells.forEach(function(c, i){const td = document.createElement('td');
  if(links && links[i]){const a = document.createElement('a');
   a.textContent = c==null?'':String(c); a.onclick = links[i];
   td.appendChild(a)}
  else td.textContent = c==null?'':String(c);
  tr.appendChild(td)}); return tr}
function fill(id, head, rows){const t = document.getElementById(id);
 t.replaceChildren(); const hr = document.createElement('tr');
 head.forEach(function(h){const th = document.createElement('th');
  th.textContent = h; hr.appendChild(th)}); t.appendChild(hr);
 rows.forEach(function(r){t.appendChild(r)})}
function opt(sel, vals, keep){const s = document.getElementById(sel);
 const cur = s.value; s.replaceChildren();
 vals.forEach(function(v){const o = document.createElement('option');
  o.value = o.textContent = v; s.appendChild(o)});
 if(keep && vals.indexOf(cur) >= 0) s.value = cur}

async function inspectFrame(fid){
 const fr = (await j('/3/Frames/' + encodeURIComponent(fid)
   + '/summary')).frames[0];
 const d = document.getElementById('detail');
 d.replaceChildren();
 d.insertAdjacentHTML('beforeend',
  '<b></b> — ' + fr.rows + ' rows × ' + fr.num_columns + ' cols');
 d.querySelector('b').textContent = fid;
 const t = document.createElement('table');
 const hr = document.createElement('tr');
 ['column','type','min','mean','max','missing'].forEach(function(h){
  const th = document.createElement('th'); th.textContent = h;
  hr.appendChild(th)}); t.appendChild(hr);
 fr.columns.forEach(function(c){
  t.appendChild(row([c.label, c.type,
   c.mins && c.mins.length ? c.mins[0] : '',
   c.mean == null ? '' : Number(c.mean).toFixed(4),
   c.maxs && c.maxs.length ? c.maxs[0] : '', c.missing_count]))});
 d.appendChild(t)}

async function inspectModel(mid){
 const m = (await j('/3/Models/' + encodeURIComponent(mid))).models[0];
 const d = document.getElementById('detail');
 d.replaceChildren();
 d.insertAdjacentHTML('beforeend', '<b></b> — ' + m.algo + ' ('
   + m.output.model_category + ')');
 d.querySelector('b').textContent = mid;
 const tm = m.output.training_metrics || {};
 const t = document.createElement('table');
 const hr = document.createElement('tr');
 ['metric','value'].forEach(function(h){const th =
  document.createElement('th'); th.textContent = h; hr.appendChild(th)});
 t.appendChild(hr);
 Object.keys(tm).forEach(function(k){
  if(typeof tm[k] === 'number')
   t.appendChild(row([k, Number(tm[k]).toFixed(6)]))});
 d.appendChild(t)}

async function loadRespCols(fid){
 // columns of the SELECTED frame only — the listing stays O(frames)
 const d = await j('/3/Frames/' + encodeURIComponent(fid) + '/columns');
 opt('trresp', d.frames[0].columns.map(function(c){return c.label}), true)}

async function refresh(){
 try{
  const c = await j('/3/Cloud');
  document.getElementById('cloud').textContent = 'cloud ' + c.cloud_name
    + ' v' + c.version + ' — ' + c.nodes[0].num_cpus
    + ' device(s), backend ' + c.nodes[0].backend;
  const fr = await j('/3/Frames');
  fill('frames', ['key','rows','cols'], fr.frames.map(function(f){
   const fid = f.frame_id.name;
   return row([fid, f.rows, f.num_columns],
              [function(){inspectFrame(fid)}, null, null])}));
  const hadSel = document.getElementById('trframe').value;
  opt('trframe', fr.frames.map(function(f){return f.frame_id.name}), true);
  const sel = document.getElementById('trframe').value;
  if(sel && sel !== hadSel) await loadRespCols(sel);
  const mo = await j('/3/Models');
  fill('models', ['key','algo','category'], mo.models.map(function(m){
   const mid = m.model_id.name;
   return row([mid, m.algo, m.output.model_category],
              [function(){inspectModel(mid)}, null, null])}));
  const jb = await j('/3/Jobs');
  fill('jobs', ['key','description','status','progress'],
   jb.jobs.map(function(x){return row([x.key.name, x.description,
    x.status, (100 * x.progress).toFixed(0) + '%'])}));
 }catch(e){document.getElementById('cloud').textContent =
   'error: ' + e.message}}

async function doImport(ev){
 ev.preventDefault();
 const msg = document.getElementById('importmsg');
 try{
  const path = document.getElementById('importpath').value;
  const imp = await j('/3/ImportFiles?path=' + encodeURIComponent(path));
  if(imp.fails.length) throw new Error('not found: ' + imp.fails[0]);
  const setup = await j('/3/ParseSetup', {method:'POST',
   headers:{'Content-Type':'application/json'},
   body: JSON.stringify({source_frames: imp.files})});
  const parse = await j('/3/Parse', {method:'POST',
   headers:{'Content-Type':'application/json'},
   body: JSON.stringify({source_frames: imp.files,
                         destination_frame: setup.destination_frame})});
  await pollJob(parse.job.key.name);
  msg.className = 'ok'; msg.textContent = 'parsed → '
    + setup.destination_frame;
  refresh();
 }catch(e){msg.className = 'err'; msg.textContent = e.message}
 return false}

async function pollJob(key){
 for(;;){
  const jj = (await j('/3/Jobs/' + encodeURIComponent(key))).jobs[0];
  document.getElementById('jobkey').textContent = key;
  document.getElementById('jobstate').textContent = jj.status;
  document.getElementById('jobbar').value = jj.progress;
  if(jj.status === 'DONE') return jj;
  if(jj.status === 'FAILED') throw new Error(jj.exception || 'job failed');
  if(jj.status === 'CANCELLED') throw new Error('job cancelled');
  await new Promise(function(res){setTimeout(res, 300)})}}

async function doTrain(ev){
 ev.preventDefault();
 const msg = document.getElementById('jobmsg');
 msg.textContent = ''; msg.className = '';
 try{
  const algo = document.getElementById('algo').value;
  const body = JSON.parse(
    document.getElementById('trparams').value || '{}');
  body.training_frame = document.getElementById('trframe').value;
  body.response_column = document.getElementById('trresp').value;
  const resp = await j('/3/ModelBuilders/' + algo, {method:'POST',
   headers:{'Content-Type':'application/json'},
   body: JSON.stringify(body)});
  const done = await pollJob(resp.job.key.name);
  msg.className = 'ok';
  msg.textContent = 'model → ' + done.dest.name;
  refresh(); inspectModel(done.dest.name);
 }catch(e){msg.className = 'err'; msg.textContent = e.message}
 return false}

async function boot(){
 try{const mb = await j('/3/ModelBuilders');
  opt('algo', mb.model_builders ? Object.keys(mb.model_builders)
      : mb.algos || []);
 }catch(e){}
 document.getElementById('trframe').onchange = function(){
  loadRespCols(document.getElementById('trframe').value)};
 refresh(); setInterval(refresh, 3000)}
boot();
</script></body></html>"""
