"""Client explanation module — the `h2o-py/h2o/explanation/_explain.py`
surface (3,614 LoC in the reference) rebuilt on this client.

Public registry (matching `h2o/explanation/__init__.py` `__all__` plus the
per-model methods `register_explain_methods` installs):

- ``explain(models, frame, ...)`` / ``explain_row(models, frame, row_index)``
  — orchestrators returning an :class:`H2OExplanation` ordered dict of
  headers, descriptions, figures and tables (`_explain.py:3080,3364`).
- ``shap_summary_plot`` / ``shap_explain_row_plot`` — TreeSHAP beeswarm and
  per-row contribution bars off ``predict_contributions``
  (`_explain.py:616,765`).
- ``pd_plot`` / ``ice_plot`` / ``pd_multi_plot`` — partial dependence and
  individual conditional expectation off the `/3/PartialDependence` route
  (row_index sweeps are server-side, one predict per curve)
  (`_explain.py:1411,1751,1485`).
- ``varimp`` / ``varimp_heatmap`` — consolidated variable-importance matrix
  and its clustered heatmap (`_explain.py:2171,2095`).
- ``model_correlation`` / ``model_correlation_heatmap`` — prediction
  correlation across models (`_explain.py:2315,2222`).
- ``residual_analysis_plot`` — fitted vs residual with zero line
  (`_explain.py:2364`).
- ``learning_curve_plot`` — metric vs iteration from the scoring history
  (`_explain.py:2452`).

Figures come back wrapped by ``decorate_plot_result`` so ``res.figure()``
returns the matplotlib Figure (the `h2o/plot/_plot_result.py` contract the
explain pyunits assert on). Everything is Agg-safe: matplotlib is imported
lazily and never requires a display.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np


# ---------------------------------------------------------------------------
# display primitives (`_explain.py:56,78,179`)
# ---------------------------------------------------------------------------
class Header:
    """A rendered section header (repr-friendly for notebooks/terminals)."""

    def __init__(self, content: str, level: int = 1):
        self.content = content
        self.level = level

    def __repr__(self):
        return "\n\n{} {}\n".format("#" * self.level, self.content)


class Description:
    """Canned explanation descriptions (`_explain.py` DESCRIPTIONS map)."""

    DESCRIPTIONS = {
        "leaderboard": "Leaderboard shows models with their metrics.",
        "confusion_matrix": "Confusion matrix shows a predicted class vs "
                            "an actual class.",
        "residual_analysis": "Residual Analysis plots the fitted values vs "
                             "residuals on a test dataset.",
        "learning_curve": "Learning curve plot shows the loss function/metric "
                          "dependent on number of iterations or trees for "
                          "tree-based algorithms.",
        "variable_importance": "The variable importance plot shows the "
                               "relative importance of the most important "
                               "variables in the model.",
        "varimp_heatmap": "Variable importance heatmap shows variable "
                          "importance across multiple models.",
        "model_correlation_heatmap": "This plot shows the correlation "
                                     "between the predictions of the models.",
        "shap_summary": "SHAP summary plot shows the contribution of the "
                        "features for each instance (row of data).",
        "pdp": "Partial dependence plot (PDP) gives a graphical depiction "
               "of the marginal effect of a variable on the response.",
        "ice": "An Individual Conditional Expectation (ICE) plot gives a "
               "graphical depiction of the marginal effect of a variable "
               "on the response for a single row.",
        "shap_explain_row": "SHAP explanation shows contribution of "
                            "features for a given instance.",
    }

    def __init__(self, for_what: str):
        self.content = self.DESCRIPTIONS.get(for_what, "")

    def __repr__(self):
        return self.content


class H2OExplanation(OrderedDict):
    """Ordered container of explanation artifacts (`_explain.py:179`)."""


# ---------------------------------------------------------------------------
# figure wrapping (`h2o/plot/_plot_result.py` contract)
# ---------------------------------------------------------------------------
class _MObject(object):
    pass


def decorate_plot_result(res=None, figure=None):
    """Attach a ``.figure()`` accessor to any result object."""

    class _MTuple(tuple):
        pass

    class _MList(list):
        pass

    class _MDict(dict):
        pass

    class _MStr(str):
        pass

    if res is None:
        dec = _MObject()
    elif isinstance(res, tuple):
        dec = _MTuple(res)
    elif isinstance(res, list):
        dec = _MList(res)
    elif isinstance(res, dict):
        dec = _MDict(res)
    elif isinstance(res, str):
        dec = _MStr(res)
    else:
        dec = res
    dec.figure = lambda: figure
    dec._is_decorated_plot_result = True
    return dec


def _plt():
    """Lazy matplotlib import, headless-safe."""
    import matplotlib

    if matplotlib.get_backend().lower() not in ("agg", "module://ipykernel"
                                                ".pylab.backend_inline"):
        import os

        if not os.environ.get("DISPLAY"):
            matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def _figure(figsize):
    """(fig, ax) WITHOUT registering in pyplot's global figure registry —
    explain() opens a figure per section per model, and registry-held
    figures are never GC-able (matplotlib's >20-figures warning); canvas
    attached so fig.savefig works headlessly."""
    _plt()  # backend selection only
    from matplotlib.backends.backend_agg import FigureCanvasAgg
    from matplotlib.figure import Figure

    fig = Figure(figsize=figsize)
    FigureCanvasAgg(fig)
    return fig, fig.add_subplot(111)


# ---------------------------------------------------------------------------
# model introspection helpers
# ---------------------------------------------------------------------------
def _get_algorithm(model, treat_xrt_as_algorithm=False):
    algo = (getattr(model, "_schema", None) or {}).get("algo", "")
    return algo or "unknown"


def _get_xy(model):
    """(x, y) the model was trained on: the output feature names minus the
    special columns (`_explain.py:1913`)."""
    out = (model._schema or {}).get("output", {})
    names = list(out.get("names") or [])
    parms = {}
    try:
        parms = model.parms
    except Exception:
        pass

    def actual(k):
        v = (parms.get(k) or {})
        v = v.get("actual_value") if isinstance(v, dict) else v
        return v

    y = (model._schema or {}).get("response_column_name") or actual(
        "response_column")
    special = {y}
    for k in ("weights_column", "offset_column", "fold_column"):
        special.add(actual(k))
    x = [n for n in names if n not in special]
    return x, y


def _has_varimp(model) -> bool:
    return bool(((model._schema or {}).get("output") or {})
                .get("variable_importances"))


def _shorten_model_ids(model_ids):
    """Drop the longest common AutoML suffix chatter while keeping ids
    unique (`_explain.py:518`)."""
    import re

    shortened = [re.sub(r"(_AutoML_[\d_]+)", "", mid) for mid in model_ids]
    if len(set(shortened)) == len(set(model_ids)):
        return shortened
    return list(model_ids)


def _is_automl(obj) -> bool:
    return hasattr(obj, "_leaderboard_json") or (
        type(obj).__name__ == "H2OAutoML")


def _model_ids_of(models):
    """Normalize any supported 'models' argument to a list of model ids."""
    from . import client as _c

    if _is_automl(models):
        return [m["name"] for m in models._leaderboard_json["models"]]
    if isinstance(models, _c.H2OFrame):
        col = models["model_id"]
        return [str(v) for v in
                col.as_data_frame()["model_id"].tolist()]
    if isinstance(models, (list, tuple)):
        return [m if isinstance(m, str) else m.model_id for m in models]
    return [models if isinstance(models, str) else models.model_id]


def _get_models(models):
    """Resolve to live H2OModelClient objects."""
    from . import client as _c

    return [m if hasattr(m, "_schema") and not isinstance(m, str)
            else _c.get_model(m) for m in _model_ids_of(models)]


def _first_of_family(models):
    """Keep the best (first-listed) model per algorithm family
    (`_explain.py:551`)."""
    seen = set()
    out = []
    for m in models:
        algo = _get_algorithm(m)
        if algo not in seen:
            seen.add(algo)
            out.append(m)
    return out


def _consolidate_varimps(model) -> dict:
    """Map the model's varimp onto its training features, summing encoded
    sub-columns (one-hot `col.level`, interactions) back onto base columns
    and normalizing to percentages (`_explain.py:1944`)."""
    x, _ = _get_xy(model)
    vi = model.varimp() or {}
    variables = list(vi.get("variable") or [])
    pcts = list(vi.get("percentage") or [])
    out = {col: 0.0 for col in x}
    for var, pct in zip(variables, pcts):
        if var in out:
            out[var] += float(pct)
        else:
            base = var.split(".")[0]
            if base in out:
                out[base] += float(pct)
    tot = sum(out.values())
    if tot > 0:
        out = {k: v / tot for k, v in out.items()}
    return out


def _calculate_clustering_indices(matrix: np.ndarray):
    """Leaf order of an average-linkage clustering over rows — numpy-only
    (the reference implements its own, `_explain.py:2060`)."""
    n = matrix.shape[0]
    if n <= 2:
        return list(range(n))
    clusters = [[i] for i in range(n)]

    def dist(a, b):
        da = matrix[a].mean(axis=0)
        db = matrix[b].mean(axis=0)
        return float(np.linalg.norm(da - db))

    while len(clusters) > 1:
        best = (0, 1, float("inf"))
        for i in range(len(clusters)):
            for j in range(i + 1, len(clusters)):
                d = dist(clusters[i], clusters[j])
                if d < best[2]:
                    best = (i, j, d)
        i, j, _d = best
        clusters[i] = clusters[i] + clusters[j]
        del clusters[j]
    return clusters[0]


def _is_classification(model) -> bool:
    cat = ((model._schema or {}).get("output") or {}).get("model_category")
    return cat in ("Binomial", "Multinomial")


def _is_binomial(model) -> bool:
    return ((model._schema or {}).get("output") or {}).get(
        "model_category") == "Binomial"


def _is_tree_model(model) -> bool:
    return _get_algorithm(model) in ("gbm", "drf", "xrt", "xgboost",
                                     "isolationforest")


def _frame_df(frame):
    return frame.as_data_frame()


def _as_float(col):
    """Pandas column -> float ndarray; non-numeric (string/categorical,
    including Arrow-backed dtypes) fall back to sorted-level codes."""
    try:
        return col.to_numpy(dtype=float)
    except (ValueError, TypeError):
        codes = {v: i for i, v in enumerate(sorted(set(col.dropna())))}
        return col.map(codes).to_numpy(dtype=float)


# ---------------------------------------------------------------------------
# varimp + heatmaps
# ---------------------------------------------------------------------------
def varimp(models, num_of_features=20, cluster=True, use_pandas=True):
    """The varimp-heatmap matrix (`_explain.py:2171`): models x features of
    consolidated percentage importances."""
    models = [m for m in _get_models(models) if _has_varimp(m)]
    if not models:
        raise RuntimeError("No model with variable importance")
    varimps = [_consolidate_varimps(m) for m in models]
    # feature axis = ordered union over models (models may use different
    # predictor subsets)
    x = []
    for m in models:
        for col in _get_xy(m)[0]:
            if col not in x:
                x.append(col)
    M = np.array([[vi.get(col, 0.0) for col in x] for vi in varimps])
    if num_of_features is not None and M.shape[1] > num_of_features:
        # argsort twice: ranks, not a permutation (a bare argsort keeps
        # arbitrary features)
        ranks = np.amax(M, axis=0).argsort().argsort()
        mask = (ranks.max() - ranks) < num_of_features
        M = M[:, mask]
        x = [c for c, keep in zip(x, mask) if keep]
    model_ids = _shorten_model_ids([m.model_id for m in models])
    if cluster and len(models) > 2:
        order = _calculate_clustering_indices(M.T)
        x = [x[i] for i in order]
        M = M[:, order]
        order = _calculate_clustering_indices(M)
        model_ids = [model_ids[i] for i in order]
        M = M[order, :]
    M = M.T  # features x models, like the reference's heatmap layout
    if use_pandas:
        import pandas as pd

        return pd.DataFrame(M, columns=model_ids, index=x)
    return M, model_ids, x


def varimp_heatmap(models, top_n=None, num_of_features=20, figsize=(16, 9),
                   cluster=True, colormap="RdYlBu_r", save_plot_path=None):
    """Clustered variable-importance heatmap across models
    (`_explain.py:2095`)."""
    plt = _plt()
    M, model_ids, x = varimp(models, num_of_features=num_of_features,
                             cluster=cluster, use_pandas=False)
    fig, ax = _figure(figsize)
    im = ax.imshow(M, aspect="auto", cmap=colormap, vmin=0, vmax=1)
    ax.set_xticks(range(len(model_ids)))
    ax.set_xticklabels(model_ids, rotation=45, ha="right")
    ax.set_yticks(range(len(x)))
    ax.set_yticklabels(x)
    fig.colorbar(im, ax=ax, label="Variable Importance (percentage)")
    ax.set_title("Variable Importance Heatmap")
    fig.tight_layout()
    if save_plot_path is not None:
        fig.savefig(save_plot_path)
    return decorate_plot_result(figure=fig)


def _prediction_column(model, frame):
    """The raw per-row 'predict' column (labels stay labels; the shared
    encoding happens across models in model_correlation)."""
    return _frame_df(model.predict(frame))["predict"]


def model_correlation(models, frame, top_n=None, use_pandas=True):
    """Pairwise correlation of model predictions on ``frame``
    (`_explain.py:2315`). Classifier labels encode through ONE shared
    level map across all models — per-model sorted-set codes would compress
    differently when a model never predicts some label."""
    models = _get_models(models)
    model_ids = _shorten_model_ids([m.model_id for m in models])
    cols = [_prediction_column(m, frame) for m in models]
    numeric = []
    try:
        numeric = [c.to_numpy(dtype=float) for c in cols]
    except (ValueError, TypeError):
        levels = sorted({v for c in cols for v in c.dropna()},
                        key=str)
        codes = {v: i for i, v in enumerate(levels)}
        numeric = [c.map(codes).to_numpy(dtype=float) for c in cols]
    preds = np.stack(numeric)
    C = np.corrcoef(preds)
    if use_pandas:
        import pandas as pd

        return pd.DataFrame(C, columns=model_ids, index=model_ids)
    return C, model_ids


def model_correlation_heatmap(models, frame, top_n=None, figsize=(13, 13),
                              cluster_models=True, triangular=True,
                              colormap="RdYlBu_r", save_plot_path=None):
    """Prediction-correlation heatmap (`_explain.py:2222`)."""
    plt = _plt()
    C, model_ids = model_correlation(models, frame, use_pandas=False)
    if cluster_models and len(model_ids) > 2:
        order = _calculate_clustering_indices(C)
        C = C[np.ix_(order, order)]
        model_ids = [model_ids[i] for i in order]
    D = C.copy()
    if triangular:
        D[np.triu_indices_from(D, k=1)] = np.nan
    fig, ax = _figure(figsize)
    im = ax.imshow(D, cmap=colormap, vmin=-1, vmax=1)
    ax.set_xticks(range(len(model_ids)))
    ax.set_xticklabels(model_ids, rotation=45, ha="right")
    ax.set_yticks(range(len(model_ids)))
    ax.set_yticklabels(model_ids)
    fig.colorbar(im, ax=ax, label="Correlation")
    ax.set_title("Model Correlation")
    fig.tight_layout()
    if save_plot_path is not None:
        fig.savefig(save_plot_path)
    return decorate_plot_result(figure=fig)


# ---------------------------------------------------------------------------
# SHAP plots
# ---------------------------------------------------------------------------
def _contributions(model, frame, samples=None):
    contrib = model.predict_contributions(frame)
    df = _frame_df(contrib)
    X = _frame_df(frame)
    if samples is not None and len(df) > samples:
        idx = np.random.default_rng(42).choice(len(df), samples,
                                               replace=False)
        df = df.iloc[idx].reset_index(drop=True)
        X = X.iloc[idx].reset_index(drop=True)
    return df, X


def shap_summary_plot(model, frame, columns=None, top_n_features=20,
                      samples=1000, colorize_factors=True, alpha=1,
                      colormap=None, figsize=(12, 12), jitter=0.35,
                      save_plot_path=None):
    """TreeSHAP beeswarm: per-feature contribution scatter colored by the
    normalized feature value (`_explain.py:616`)."""
    plt = _plt()
    df, X = _contributions(model, frame, samples)
    phi_cols = [c for c in df.columns if c != "BiasTerm"]
    if columns is not None:
        phi_cols = [c for c in phi_cols if c in set(columns)]
    order = np.argsort([-float(np.abs(df[c]).mean()) for c in phi_cols])
    phi_cols = [phi_cols[i] for i in order[:top_n_features]]
    phi_cols = phi_cols[::-1]  # most important on top
    rng = np.random.default_rng(7)
    fig, ax = _figure(figsize)
    cmap = plt.get_cmap(colormap or "RdYlBu_r")
    for yi, col in enumerate(phi_cols):
        phi = df[col].to_numpy(dtype=float)
        if col in X.columns:
            vals = _as_float(X[col])
            lo, hi = np.nanmin(vals), np.nanmax(vals)
            norm = (vals - lo) / (hi - lo) if hi > lo else np.full_like(
                vals, 0.5)
            colors = cmap(np.nan_to_num(norm, nan=0.5))
        else:
            colors = None
        ys = yi + rng.uniform(-jitter, jitter, len(phi))
        ax.scatter(phi, ys, c=colors, s=8, alpha=alpha)
    ax.set_yticks(range(len(phi_cols)))
    ax.set_yticklabels(phi_cols)
    ax.axvline(0, color="grey", lw=0.8)
    ax.set_xlabel("SHAP value (contribution)")
    ax.set_title("SHAP Summary plot for \"{}\"".format(model.model_id))
    fig.tight_layout()
    if save_plot_path is not None:
        fig.savefig(save_plot_path)
    return decorate_plot_result(figure=fig)


def shap_explain_row_plot(model, frame, row_index, columns=None,
                          top_n_features=10, figsize=(16, 9),
                          plot_type="barplot", contribution_type="both",
                          save_plot_path=None):
    """Per-row contribution bar plot (`_explain.py:765`)."""
    plt = _plt()
    row = frame[int(row_index), :]
    df, _X = _contributions(model, row)
    phi = {c: float(df[c].iloc[0]) for c in df.columns if c != "BiasTerm"}
    if columns is not None:
        phi = {c: v for c, v in phi.items() if c in set(columns)}
    items = sorted(phi.items(), key=lambda kv: abs(kv[1]),
                   reverse=True)[:top_n_features]
    if contribution_type == "positive":
        items = [kv for kv in items if kv[1] > 0]
    elif contribution_type == "negative":
        items = [kv for kv in items if kv[1] < 0]
    items = items[::-1]
    fig, ax = _figure(figsize)
    names = [k for k, _ in items]
    vals = [v for _, v in items]
    ax.barh(range(len(items)), vals,
            color=["#b3ddf2" if v >= 0 else "#f5c8c8" for v in vals])
    ax.set_yticks(range(len(items)))
    ax.set_yticklabels(names)
    ax.axvline(0, color="grey", lw=0.8)
    ax.set_xlabel("SHAP contribution")
    ax.set_title("SHAP explanation for \"{}\" on row {}".format(
        model.model_id, row_index))
    fig.tight_layout()
    if save_plot_path is not None:
        fig.savefig(save_plot_path)
    return decorate_plot_result(figure=fig)


# ---------------------------------------------------------------------------
# PD / ICE
# ---------------------------------------------------------------------------
def _pd_table(model, frame, column, nbins=20, row_index=-1, target=None):
    """(values, means, stddevs) off the /3/PartialDependence route."""
    tables = model.partial_plot(frame, cols=[column], nbins=nbins,
                                row_index=row_index, targets=target)
    t = tables[0]
    cols = {c["name"]: i for i, c in enumerate(t["columns"])}
    data = t["data"]
    values = data[cols[column]]
    means = np.array(data[cols["mean_response"]], dtype=float)
    stds = np.array(data[cols["stddev_response"]], dtype=float)
    return values, means, stds


def _is_factor(frame, column) -> bool:
    return frame.types.get(column) == "enum"


def pd_plot(model, frame, column, row_index=None, target=None,
            max_levels=30, figsize=(16, 9), colormap="Dark2", nbins=100,
            show_rug=True, save_plot_path=None, binary_response_scale=None,
            grouping_column=None, output_graphing_data=False,
            grouping_variables=None, **_kw):
    """Partial dependence of one column; with ``row_index`` the row's ICE
    curve is drawn alongside (`_explain.py:1411`)."""
    plt = _plt()
    if frame.types.get(column) == "string":
        raise ValueError("String columns are not supported!")
    factor = _is_factor(frame, column)
    eff_bins = nbins if not factor else max_levels
    fig, ax = _figure(figsize)
    vals, means, stds = _pd_table(model, frame, column, nbins=eff_bins,
                                  target=target)
    xs = np.arange(len(vals)) if factor else np.array(vals, dtype=float)
    if factor:
        ax.errorbar(xs, means, yerr=stds, fmt="o", capsize=3,
                    label="Partial dependence")
        ax.set_xticks(xs)
        ax.set_xticklabels(vals, rotation=45, ha="right")
    else:
        ax.plot(xs, means, label="Partial dependence")
        ax.fill_between(xs, means - stds, means + stds, alpha=0.2)
    if row_index is not None:
        v2, m2, _s2 = _pd_table(model, frame, column, nbins=eff_bins,
                                row_index=int(row_index), target=target)
        x2 = np.arange(len(v2)) if factor else np.array(v2, dtype=float)
        ax.plot(x2, m2, "--", color="C1",
                label="ICE (row {})".format(row_index))
    ax.set_xlabel(column)
    ax.set_ylabel("Mean response")
    ax.set_title("Partial Dependence plot for \"{}\"{}".format(
        column, " (row {})".format(row_index) if row_index is not None
        else ""))
    ax.legend()
    fig.tight_layout()
    if save_plot_path is not None:
        fig.savefig(save_plot_path)
    return decorate_plot_result(figure=fig)


def ice_plot(model, frame, column, target=None, max_levels=30,
             figsize=(16, 9), colormap="plasma", save_plot_path=None,
             show_pdp=True, binary_response_scale=None, centered=False,
             grouping_column=None, output_graphing_data=False, nbins=100,
             show_rug=True, **_kw):
    """ICE curves at the response deciles + the PDP mean
    (`_explain.py:1751`: one curve per decile of the response)."""
    plt = _plt()
    if frame.types.get(column) == "string":
        raise ValueError("String columns are not supported!")
    factor = _is_factor(frame, column)
    eff_bins = nbins if not factor else max_levels
    _x, y = _get_xy(model)
    df = _frame_df(frame)
    fig, ax = _figure(figsize)
    cmap = plt.get_cmap(colormap)
    # rows at the response deciles (the reference picks percentile rows)
    if y in df.columns and df[y].dtype != object:
        order = np.argsort(df[y].to_numpy())
    else:
        order = np.arange(len(df))
    deciles = [int(q * (len(order) - 1) / 10) for q in range(11)]
    rows = [int(order[i]) for i in deciles]
    ice0 = None
    for qi, ri in enumerate(rows):
        vals, means, _ = _pd_table(model, frame, column, nbins=eff_bins,
                                   row_index=ri, target=target)
        xs = np.arange(len(vals)) if factor else np.array(vals, dtype=float)
        curve = means - means[0] if centered else means
        if ice0 is None:
            ice0 = (xs, vals)
        ax.plot(xs, curve, color=cmap(qi / 10.0), lw=1,
                label="{}th percentile".format(qi * 10))
    if show_pdp:
        _v, pmeans, _s = _pd_table(model, frame, column, nbins=eff_bins,
                                   target=target)
        xs = ice0[0]
        ax.plot(xs, pmeans - pmeans[0] if centered else pmeans,
                color="k", lw=3, linestyle="dotted",
                label="Partial dependence")
    if factor:
        ax.set_xticks(ice0[0])
        ax.set_xticklabels(ice0[1], rotation=45, ha="right")
    ax.set_xlabel(column)
    ax.set_ylabel("Response")
    ax.set_title("ICE plot for \"{}\"".format(column))
    ax.legend(fontsize=7)
    fig.tight_layout()
    if save_plot_path is not None:
        fig.savefig(save_plot_path)
    return decorate_plot_result(figure=fig)


def pd_multi_plot(models, frame, column, best_of_family=True, row_index=None,
                  target=None, max_levels=30, figsize=(16, 9),
                  colormap="Dark2", markers=["o", "v", "s", "P", "*", "D",
                                             "X", "^", "<", ">", "."],
                  nbins=100, show_rug=True, save_plot_path=None, **_kw):
    """One PD curve per model on a shared axis (`_explain.py:1485`)."""
    plt = _plt()
    if frame.types.get(column) == "string":
        raise ValueError("String columns are not supported!")
    models = _get_models(models)
    if best_of_family:
        models = _first_of_family(models)
    factor = _is_factor(frame, column)
    eff_bins = nbins if not factor else max_levels
    model_ids = _shorten_model_ids([m.model_id for m in models])
    fig, ax = _figure(figsize)
    cmap = plt.get_cmap(colormap)
    ticks = None
    for i, (m, mid) in enumerate(zip(models, model_ids)):
        vals, means, _ = _pd_table(
            m, frame, column, nbins=eff_bins,
            row_index=-1 if row_index is None else int(row_index),
            target=target)
        xs = np.arange(len(vals)) if factor else np.array(vals, dtype=float)
        ticks = (xs, vals)
        marker = markers[i % len(markers)]
        ax.plot(xs, means, label=mid, color=cmap(i % 8),
                marker=marker if factor else None)
    if factor and ticks is not None:
        ax.set_xticks(ticks[0])
        ax.set_xticklabels(ticks[1], rotation=45, ha="right")
    ax.set_xlabel(column)
    ax.set_ylabel("Mean response")
    ax.set_title("Partial dependence plot for \"{}\"".format(column))
    ax.legend(fontsize=8)
    fig.tight_layout()
    if save_plot_path is not None:
        fig.savefig(save_plot_path)
    return decorate_plot_result(figure=fig)


# ---------------------------------------------------------------------------
# residuals + learning curve
# ---------------------------------------------------------------------------
def residual_analysis_plot(model, frame, figsize=(16, 9),
                           save_plot_path=None):
    """Fitted vs residual scatter with the zero line (`_explain.py:2364`)."""
    plt = _plt()
    _x, y = _get_xy(model)
    pred = _frame_df(model.predict(frame))["predict"].to_numpy(dtype=float)
    actual = _frame_df(frame[y]).iloc[:, 0].to_numpy(dtype=float)
    resid = actual - pred
    fig, ax = _figure(figsize)
    ax.scatter(pred, resid, s=8, alpha=0.6)
    ax.axhline(0, color="k", lw=1)
    ax.set_xlabel("Fitted")
    ax.set_ylabel("Residuals")
    ax.set_title("Residual Analysis for \"{}\"".format(model.model_id))
    fig.tight_layout()
    if save_plot_path is not None:
        fig.savefig(save_plot_path)
    return decorate_plot_result(figure=fig)


_METRIC_AUTO = {
    "Binomial": "logloss", "Multinomial": "logloss",
    "Regression": "deviance",
}


def learning_curve_plot(model, metric="AUTO", cv_ribbon=None, cv_lines=None,
                        figsize=(16, 9), colormap=None, save_plot_path=None):
    """Metric vs iteration/tree count from the scoring history
    (`_explain.py:2452`)."""
    plt = _plt()
    sh = model.scoring_history(use_pandas=False)
    if not sh:
        raise ValueError(
            "Model {} has no scoring history".format(model.model_id))
    cat = ((model._schema or {}).get("output") or {}).get("model_category")
    metric = (metric or "AUTO").lower()
    if metric == "auto":
        metric = _METRIC_AUTO.get(cat, "rmse")
    # iteration axis: trees for tree models, iterations/epochs otherwise
    for xkey in ("number_of_trees", "iterations", "iteration", "epochs"):
        if xkey in sh and any(v is not None for v in sh[xkey]):
            break
    else:
        xkey = None
    n = max(len(v) for v in sh.values())
    xs = (np.array([v if v is not None else np.nan for v in sh[xkey]],
                   dtype=float) if xkey else np.arange(n, dtype=float))
    fig, ax = _figure(figsize)
    plotted = False
    # GLM/GAM histories store the metric unprefixed ('deviance' per lambda
    # step); treat a bare column as the training series
    if "training_{}".format(metric) not in sh and metric in sh:
        sh = dict(sh)
        sh["training_{}".format(metric)] = sh[metric]
    for prefix, style in (("training", "-"), ("validation", "--")):
        key = "{}_{}".format(prefix, metric)
        if key not in sh:
            continue
        ys = np.array([v if v is not None else np.nan for v in sh[key]],
                      dtype=float)
        if np.all(np.isnan(ys)):
            continue
        ax.plot(xs[:len(ys)], ys, style, marker="o", markersize=3,
                label="Training" if prefix == "training" else "Validation")
        plotted = True
    if not plotted:
        raise ValueError(
            "Metric '{}' is not present in the scoring history of {} "
            "(columns: {})".format(metric, model.model_id,
                                   sorted(sh.keys())))
    ax.set_xlabel(xkey or "iteration")
    ax.set_ylabel(metric)
    ax.set_title("Learning Curve for \"{}\"".format(model.model_id))
    ax.legend()
    fig.tight_layout()
    if save_plot_path is not None:
        fig.savefig(save_plot_path)
    return decorate_plot_result(figure=fig)


# ---------------------------------------------------------------------------
# orchestrators
# ---------------------------------------------------------------------------
def _display(obj):
    return obj


_ALL_EXPLANATIONS = ["leaderboard", "confusion_matrix", "residual_analysis",
                     "learning_curve", "varimp", "varimp_heatmap",
                     "model_correlation_heatmap", "shap_summary", "pdp",
                     "ice", "shap_explain_row"]


def _select(include, exclude):
    if include in (None, "ALL", ["ALL"]):
        chosen = list(_ALL_EXPLANATIONS)
    else:
        chosen = [include] if isinstance(include, str) else list(include)
    for e in (exclude or []):
        if e in chosen:
            chosen.remove(e)
    return chosen


def _varimp_plot_single(model, figsize, num_of_features=10):
    plt = _plt()
    vi = model.varimp() or {}
    variables = list(vi.get("variable") or [])[:num_of_features][::-1]
    scaled = list(vi.get("scaled_importance") or [])[:num_of_features][::-1]
    fig, ax = _figure(figsize)
    ax.barh(range(len(variables)), scaled, color="#1F77B4")
    ax.set_yticks(range(len(variables)))
    ax.set_yticklabels(variables)
    ax.set_title("Variable Importance for \"{}\"".format(model.model_id))
    ax.set_xlabel("Scaled importance")
    fig.tight_layout()
    return decorate_plot_result(figure=fig)


def _top_features(models, n):
    """Union of each model's top-n varimp features, ranked."""
    scores: dict = {}
    for m in models:
        if not _has_varimp(m):
            continue
        for col, pct in _consolidate_varimps(m).items():
            scores[col] = scores.get(col, 0.0) + pct
    return [c for c, _v in sorted(scores.items(), key=lambda kv: -kv[1])][:n]


def explain(models, frame, columns=None, top_n_features=5,
            include_explanations="ALL", exclude_explanations=[],
            plot_overrides={}, figsize=(16, 9), render=True,
            qualitative_colormap="Dark2", sequential_colormap="RdYlBu_r",
            background_frame=None):
    """Generate the standard model-explanation dashboard
    (`_explain.py:3080`): global explanations for one model or a set of
    models (AutoML / leaderboard slice / list)."""
    models_list = _get_models(models)
    multiple = len(models_list) > 1
    leader = models_list[0]
    classification = _is_classification(leader)
    chosen = _select(include_explanations, exclude_explanations)
    if columns is not None:
        pd_cols = list(columns)
    else:
        pd_cols = _top_features(models_list, top_n_features)
        if not pd_cols:
            pd_cols = _get_xy(leader)[0][:top_n_features]
    result = H2OExplanation()
    if multiple and "leaderboard" in chosen and _is_automl(models):
        result["leaderboard"] = H2OExplanation()
        result["leaderboard"]["header"] = _display(Header("Leaderboard"))
        result["leaderboard"]["description"] = _display(
            Description("leaderboard"))
        result["leaderboard"]["data"] = _display(models.leaderboard)
    if classification and "confusion_matrix" in chosen:
        result["confusion_matrix"] = H2OExplanation()
        result["confusion_matrix"]["header"] = _display(
            Header("Confusion Matrix"))
        result["confusion_matrix"]["description"] = _display(
            Description("confusion_matrix"))
        result["confusion_matrix"]["subexplanations"] = sub = H2OExplanation()
        for m in (models_list if not multiple else
                  _first_of_family(models_list)):
            try:
                sub[m.model_id] = _display(m.confusion_matrix())
            except Exception:
                pass
    if not classification and "residual_analysis" in chosen and not multiple:
        result["residual_analysis"] = H2OExplanation()
        result["residual_analysis"]["header"] = _display(
            Header("Residual Analysis"))
        result["residual_analysis"]["description"] = _display(
            Description("residual_analysis"))
        result["residual_analysis"]["plots"] = _display(
            residual_analysis_plot(leader, frame, figsize=figsize))
    if "learning_curve" in chosen:
        result["learning_curve"] = H2OExplanation()
        result["learning_curve"]["header"] = _display(
            Header("Learning Curve Plot"))
        result["learning_curve"]["description"] = _display(
            Description("learning_curve"))
        result["learning_curve"]["plots"] = plots = H2OExplanation()
        for m in models_list:
            try:
                plots[m.model_id] = _display(
                    learning_curve_plot(m, figsize=figsize))
            except Exception:
                pass
    if multiple and "varimp_heatmap" in chosen:
        try:
            result["varimp_heatmap"] = H2OExplanation()
            result["varimp_heatmap"]["header"] = _display(
                Header("Variable Importance Heatmap"))
            result["varimp_heatmap"]["description"] = _display(
                Description("varimp_heatmap"))
            result["varimp_heatmap"]["plots"] = _display(varimp_heatmap(
                models_list, figsize=figsize,
                colormap=sequential_colormap))
        except RuntimeError:
            del result["varimp_heatmap"]
    if multiple and "model_correlation_heatmap" in chosen:
        result["model_correlation_heatmap"] = H2OExplanation()
        result["model_correlation_heatmap"]["header"] = _display(
            Header("Model Correlation"))
        result["model_correlation_heatmap"]["description"] = _display(
            Description("model_correlation_heatmap"))
        result["model_correlation_heatmap"]["plots"] = _display(
            model_correlation_heatmap(
                models_list, frame, figsize=figsize,
                colormap=sequential_colormap))
    if not multiple and "varimp" in chosen and _has_varimp(leader):
        result["varimp"] = H2OExplanation()
        result["varimp"]["header"] = _display(
            Header("Variable Importance"))
        result["varimp"]["description"] = _display(
            Description("variable_importance"))
        result["varimp"]["plots"] = _display(
            _varimp_plot_single(leader, figsize))
    if "shap_summary" in chosen and not multiple and _is_tree_model(leader):
        try:
            result["shap_summary"] = H2OExplanation()
            result["shap_summary"]["header"] = _display(
                Header("SHAP Summary"))
            result["shap_summary"]["description"] = _display(
                Description("shap_summary"))
            result["shap_summary"]["plots"] = _display(shap_summary_plot(
                leader, frame, **plot_overrides.get("shap_summary_plot",
                                                    {})))
        except Exception:
            result.pop("shap_summary", None)
    if "pdp" in chosen:
        result["pdp"] = H2OExplanation()
        result["pdp"]["header"] = _display(
            Header("Partial Dependence Plots"))
        result["pdp"]["description"] = _display(Description("pdp"))
        result["pdp"]["plots"] = plots = H2OExplanation()
        for col in pd_cols:
            try:
                if multiple:
                    plots[col] = _display(pd_multi_plot(
                        models_list, frame, col, figsize=figsize,
                        colormap=qualitative_colormap))
                else:
                    plots[col] = _display(pd_plot(
                        leader, frame, col, figsize=figsize,
                        colormap=qualitative_colormap))
            except ValueError:
                pass
    if "ice" in chosen and not multiple:
        result["ice"] = H2OExplanation()
        result["ice"]["header"] = _display(Header("ICE Plots"))
        result["ice"]["description"] = _display(Description("ice"))
        result["ice"]["plots"] = plots = H2OExplanation()
        for col in pd_cols:
            try:
                plots[col] = _display(ice_plot(leader, frame, col,
                                               figsize=figsize))
            except ValueError:
                pass
    return result


def explain_row(models, frame, row_index, columns=None, top_n_features=5,
                include_explanations="ALL", exclude_explanations=[],
                plot_overrides={}, qualitative_colormap="Dark2",
                figsize=(16, 9), render=True, background_frame=None):
    """Generate per-row explanations (`_explain.py:3364`): SHAP row plot +
    per-column ICE curves."""
    models_list = _get_models(models)
    multiple = len(models_list) > 1
    leader = models_list[0]
    chosen = _select(include_explanations, exclude_explanations)
    if columns is not None:
        pd_cols = list(columns)
    else:
        pd_cols = _top_features(models_list, top_n_features)
        if not pd_cols:
            pd_cols = _get_xy(leader)[0][:top_n_features]
    result = H2OExplanation()
    if "shap_explain_row" in chosen and not multiple \
            and _is_tree_model(leader):
        try:
            result["shap_explain_row"] = H2OExplanation()
            result["shap_explain_row"]["header"] = _display(
                Header("SHAP Explanation"))
            result["shap_explain_row"]["description"] = _display(
                Description("shap_explain_row"))
            result["shap_explain_row"]["plots"] = _display(
                shap_explain_row_plot(leader, frame, row_index,
                                      figsize=figsize))
        except Exception:
            result.pop("shap_explain_row", None)
    result["ice"] = H2OExplanation()
    result["ice"]["header"] = _display(
        Header("Individual Conditional Expectation"))
    result["ice"]["description"] = _display(Description("ice"))
    result["ice"]["plots"] = plots = H2OExplanation()
    for col in pd_cols:
        try:
            if multiple:
                plots[col] = _display(pd_multi_plot(
                    models_list, frame, col, row_index=row_index,
                    figsize=figsize, colormap=qualitative_colormap))
            else:
                plots[col] = _display(pd_plot(
                    leader, frame, col, row_index=row_index,
                    figsize=figsize, colormap=qualitative_colormap))
        except ValueError:
            pass
    return result


# ---------------------------------------------------------------------------
# registration (`h2o/explanation/__init__.py` register_explain_methods)
# ---------------------------------------------------------------------------
def register_explain_methods():
    """Install the explanation verbs on the client classes the way the
    reference installs them on ModelBase / H2OAutoMLBaseMixin."""
    from . import client as _c

    _c.H2OModelClient.explain = explain
    _c.H2OModelClient.explain_row = explain_row
    _c.H2OModelClient.shap_summary_plot = shap_summary_plot
    _c.H2OModelClient.shap_explain_row_plot = shap_explain_row_plot
    _c.H2OModelClient.pd_plot = pd_plot
    _c.H2OModelClient.ice_plot = ice_plot
    _c.H2OModelClient.residual_analysis_plot = residual_analysis_plot
    _c.H2OModelClient.learning_curve_plot = learning_curve_plot

    _c.H2OAutoML.explain = explain
    _c.H2OAutoML.explain_row = explain_row
    _c.H2OAutoML.pd_multi_plot = pd_multi_plot
    _c.H2OAutoML.varimp_heatmap = varimp_heatmap
    _c.H2OAutoML.model_correlation_heatmap = model_correlation_heatmap
    _c.H2OAutoML.model_correlation = model_correlation
    _c.H2OAutoML.varimp = varimp


def _corrected_variance(accuracy, total):
    """Literal `_explain.py:3519` formula (parity beats plausibility — the
    reference subtracts the MEAN standard error inside the var call, which
    collapses to a scalar shift):
    max(0, var(accuracy - mean(accuracy*(1-accuracy)/total)))."""
    accuracy = np.asarray(accuracy, float)
    total = np.asarray(total, float)
    se = np.mean(accuracy * (1 - accuracy) / total)
    return float(max(0.0, np.var(accuracy - se)))


def disparate_analysis(models, frame, protected_columns, reference,
                       favorable_class, air_metric="selectedRatio",
                       alpha=0.05):
    """Aggregate intersectional fairness across models
    (`_explain.py:3527`): per model, overall metrics plus AIR
    min/mean/median/max, the coverage-weighted AIR (cair), the same
    aggregates over SIGNIFICANT groups (p < alpha), and p-value aggregates.
    Returns a pandas DataFrame ranked like a leaderboard."""
    import pandas as pd

    models = _get_models(models)
    rows = []
    for m in models:
        fm = m.fairness_metrics(frame, protected_columns, reference,
                                favorable_class)
        ov = fm["overview"].as_data_frame()
        col = "AIR_{}".format(air_metric)
        if col not in ov.columns:
            raise ValueError(
                "Metric {} is not present in the result of "
                "model.fairness_metrics. Please specify one of {}.".format(
                    air_metric, ", ".join(
                        c[4:] for c in ov.columns if c.startswith("AIR"))))
        air = ov[col].to_numpy(dtype=float)
        pv = ov["p.value"].to_numpy(dtype=float)
        sig = air[pv < alpha]

        def agg(fn, arr):
            return float(fn(arr)) if len(arr) else float("nan")

        perf = m.model_performance(frame)
        row = {"model_id": m.model_id,
               "auc": perf.get("AUC"), "logloss": perf.get("logloss"),
               "num_of_features": len(_get_xy(m)[0]),
               "var": float(np.var(ov["accuracy"])),
               "corrected_var": _corrected_variance(ov["accuracy"],
                                                    ov["total"]),
               "air_min": agg(np.min, air), "air_mean": agg(np.mean, air),
               "air_median": agg(np.median, air),
               "air_max": agg(np.max, air),
               "cair": float(np.sum(ov["relativeSize"].to_numpy() * air)),
               "significant_air_min": agg(np.min, sig),
               "significant_air_mean": agg(np.mean, sig),
               "significant_air_median": agg(np.median, sig),
               "significant_air_max": agg(np.max, sig),
               "p.value_min": agg(np.min, pv),
               "p.value_mean": agg(np.mean, pv),
               "p.value_median": agg(np.median, pv),
               "p.value_max": agg(np.max, pv)}
        rows.append(row)
    df = pd.DataFrame(rows)
    # leaderboard contract: best model first (the reference cbinds onto
    # make_leaderboard, ranked by the default metric)
    if df["auc"].notna().any():
        df = df.sort_values("auc", ascending=False)
    elif df["logloss"].notna().any():
        df = df.sort_values("logloss", ascending=True)
    return df.reset_index(drop=True)


def _calculate_pareto_front(x, y, top=True, left=True):
    """Indices on the Pareto front of (x, y) (`_explain.py:2726`): sort by
    the y-objective first so equal-x points keep only their best, then a
    strictly-improving scan along x."""
    x = np.asarray(x, float)
    y = np.asarray(y, float)
    yy = y if top else -y
    # secondary sort: best y first within equal x (stable composition)
    order = np.argsort(-yy, kind="stable")
    order = order[np.argsort(x[order] if left else -x[order],
                             kind="stable")]
    best = -np.inf
    keep = []
    for i in order:
        if yy[i] > best:
            best = yy[i]
            keep.append(i)
    return np.asarray(keep, dtype=int)


def pareto_front(frame, x_metric, y_metric, optimum="top left",
                 title=None, color_col=None, figsize=(16, 9),
                 save_plot_path=None):
    """Scatter every row of ``frame`` (a leaderboard-like pandas DataFrame
    or H2OFrame) in metric space and draw its Pareto front
    (`_explain.py:2757`)."""
    plt = _plt()
    opt = (optimum or "").lower()
    if opt not in ("top left", "top right", "bottom left", "bottom right"):
        raise ValueError("optimum must be one of 'top left', 'top right', "
                         f"'bottom left', 'bottom right' (got {optimum!r})")
    df = frame.as_data_frame() if hasattr(frame, "as_data_frame") else frame
    x = df[x_metric].to_numpy(dtype=float)
    y = df[y_metric].to_numpy(dtype=float)
    top = "top" in opt
    left = "left" in opt
    front = _calculate_pareto_front(x, y, top=top, left=left)
    fig, ax = _figure(figsize)
    if color_col is not None and color_col in df.columns:
        cmap = plt.get_cmap("Dark2")
        levels = sorted(map(str, set(df[color_col])))
        cidx = {v: k for k, v in enumerate(levels)}
        for lv in levels:
            sel = df[color_col].astype(str) == lv
            ax.scatter(x[sel.to_numpy()], y[sel.to_numpy()], s=18,
                       color=cmap(cidx[lv] % 8), label=lv)
    else:
        ax.scatter(x, y, s=18, color="#888", label="all")
    fo = front[np.argsort(x[front])]
    ax.plot(x[fo], y[fo], "-o", color="#d62728", label="Pareto front")
    ax.set_xlabel(x_metric)
    ax.set_ylabel(y_metric)
    ax.set_title(title or "Pareto front ({})".format(optimum))
    ax.legend()
    fig.tight_layout()
    if save_plot_path is not None:
        fig.savefig(save_plot_path)
    return decorate_plot_result(res=df.iloc[front], figure=fig)


__all__ = ["explain", "explain_row", "varimp_heatmap",
           "model_correlation_heatmap", "pd_multi_plot", "varimp",
           "model_correlation", "shap_summary_plot",
           "shap_explain_row_plot", "pd_plot", "ice_plot",
           "residual_analysis_plot", "learning_curve_plot",
           "disparate_analysis", "pareto_front",
           "H2OExplanation", "decorate_plot_result",
           "register_explain_methods"]
