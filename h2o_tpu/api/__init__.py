"""REST API + client — the L9/L10 layers.

``h2o_tpu.api`` doubles as the `h2o` module surface: ``import h2o_tpu.api as
h2o; h2o.init(); h2o.import_file(...)`` mirrors the h2o-py entry points
(`h2o-py/h2o/h2o.py`), backed by the in-process REST server (server.py).
"""

from .client import (H2OConnection, H2OConnectionError, H2OEstimator,
                     H2OFrame, H2OGroupBy, H2OModelClient, assign,
                     cluster_status, connect, connection, deep_copy,
                     export_file, get_frame, get_model, get_timezone,
                     as_list, download_model, import_file, init, interaction,
                     list_timezones, load_model, ls, rapids, remove,
                     import_mojo, save_model, set_timezone, shutdown,
                     upload_custom_metric, upload_file, upload_frame,
                     upload_model, upload_mojo)
from .client import H2OGenericEstimator
from .client import (H2OAdaBoostEstimator, H2OANOVAGLMEstimator,
                     H2OAggregatorEstimator,
                     H2OCoxProportionalHazardsEstimator,
                     H2ODecisionTreeEstimator, H2ODeepLearningEstimator,
                     H2OExtendedIsolationForestEstimator,
                     H2OGeneralizedAdditiveEstimator,
                     H2OGeneralizedLinearEstimator,
                     H2OGeneralizedLowRankEstimator,
                     H2OGradientBoostingEstimator, H2OInfogram,
                     H2OIsolationForestEstimator,
                     H2OIsotonicRegressionEstimator, H2OKMeansEstimator,
                     H2OModelSelectionEstimator, H2ONaiveBayesEstimator,
                     H2OPrincipalComponentAnalysisEstimator,
                     H2ORandomForestEstimator, H2ORuleFitEstimator,
                     H2OSingularValueDecompositionEstimator,
                     H2OStackedEnsembleEstimator,
                     H2OSupportVectorMachineEstimator,
                     H2OTargetEncoderEstimator,
                     H2OUpliftRandomForestEstimator, H2OWord2vecEstimator,
                     H2OXGBoostEstimator)
from .client import (H2OServingOverloadError, H2OServingTimeoutError,
                     register_serving, score_rows, serving_stats,
                     unregister_serving, create_route, route_score,
                     route_stats, delete_route, serving_control,
                     programs, fleet_metrics, profiler_capture,
                     flight_bundles, flight_bundle, health, slow_traces)
from .client import H2OAutoML, H2OGridSearch, load_grid, save_grid
from .client import (create_frame, download_csv, insert_missing_values,
                     log_and_echo, remove_all, split_frame_rest)
from .server import H2OServer
from . import explanation
from .explanation import (explain, explain_row, varimp_heatmap,
                          model_correlation_heatmap, pd_multi_plot, varimp,
                          model_correlation, disparate_analysis,
                          pareto_front)

explanation.register_explain_methods()

__all__ = [n for n in dir() if not n.startswith("_")]
