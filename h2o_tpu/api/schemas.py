"""Schema v3 DTO builders — the wire shapes of `water/api/schemas3/`.

The reference reflectively copies impl fields into versioned Schema objects
(`water/api/Schema.java:23-45`); here each builder function produces the JSON
dict for one schema class, keeping the reference's field names (frame_id,
column `data`/`domain`, job `status`/`progress`, model `algo`/`output`) so a
schema-v3 client recognises the payloads.
"""

from __future__ import annotations

import math

import numpy as np

from ..backend.jobs import Job
from ..frame.frame import Frame


def _clean(x):
    """JSON-safe scalar (NaN/inf → None, numpy → python)."""
    if isinstance(x, (np.floating, float)):
        x = float(x)
        return None if (math.isnan(x) or math.isinf(x)) else x
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.bool_,)):
        return bool(x)
    if isinstance(x, np.ndarray):
        return [_clean(v) for v in x.tolist()]
    if isinstance(x, dict):
        return {k: _clean(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_clean(v) for v in x]
    return x


def key_schema(key: str, type_: str = "Key") -> dict:
    return {"name": key, "type": type_, "URL": None}


def col_summary(name: str, vec, npreview: int = 0) -> dict:
    """`water/api/schemas3/FrameV3.ColV3`."""
    out = {"label": name, "type": vec.type, "missing_count": None,
           "domain": vec.domain, "domain_cardinality": vec.cardinality(),
           "mean": None, "sigma": None, "mins": [], "maxs": [], "data": None}
    if vec.data is not None:
        r = vec.rollups()
        out.update(missing_count=int(r.nacnt), mean=_clean(r.mean),
                   sigma=_clean(r.sigma), mins=[_clean(r.mins)],
                   maxs=[_clean(r.maxs)])
    else:
        out["missing_count"] = int(sum(1 for x in vec.host_data if x is None))
    if npreview:
        if vec.is_string():
            out["string_data"] = [None if x is None else str(x)
                                  for x in vec.host_data[:npreview]]
        else:
            out["data"] = _clean(vec.to_numpy()[:npreview])
    return out


def frame_schema(fr: Frame, npreview: int = 0) -> dict:
    """`water/api/schemas3/FrameV3` (summary form)."""
    return {
        "frame_id": key_schema(fr.key, "Key<Frame>"),
        "rows": fr.nrow,
        "num_columns": fr.ncol,
        "byte_size": sum((v.plen * 4) for v in fr.vecs if v.data is not None),
        "is_text": False,
        "columns": [col_summary(n, fr.vec(n), npreview) for n in fr.names],
    }


def frame_base(fr: Frame) -> dict:
    return {"frame_id": key_schema(fr.key, "Key<Frame>"), "rows": fr.nrow,
            "num_columns": fr.ncol}


def job_schema(job: Job) -> dict:
    """`water/api/schemas3/JobV3` (status names match `water/Job.java`)."""
    return {
        "key": key_schema(job.key, "Key<Job>"),
        "description": job.description,
        "status": job.status,
        "progress": _clean(job.progress),
        "progress_msg": job.progress_msg,
        "start_time": int(job.start_time * 1000),
        "msec": int(((job.end_time or job.start_time) - job.start_time) * 1000),
        "dest": key_schema(job.dest_key) if job.dest_key else None,
        "exception": None if job.exception is None else repr(job.exception),
        "stacktrace": job.traceback,
        "tenant": job.tenant,
        "priority": job.priority,
    }


def table_schema(t) -> dict | None:
    """`water/api/schemas3/TwoDimTableV3` (compact form)."""
    if t is None:
        return None
    return {"name": t.table_header, "description": t.description,
            "columns": [{"name": h, "type": ty}
                        for h, ty in zip(t.col_header, t.col_types)],
            "data": _clean([[r[i] for r in t.cell_values]
                            for i in range(len(t.col_header))])}


def scoring_history_schema(history) -> dict | None:
    """`ScoringHistoryV3` analog: one column-oriented table over the model's
    scoring snapshots — iteration markers kept verbatim, the per-snapshot
    metrics object flattened to `training_<metric>` columns (the column
    names h2o-py's learning_curve_plot reads off the wire)."""
    if not history:
        return None
    cols: dict[str, list] = {}
    metric_keys = ("rmse", "mse", "mae", "logloss", "auc", "pr_auc",
                   "mean_per_class_error", "r2", "residual_deviance",
                   "null_deviance")
    for i, h in enumerate(history):
        row: dict = {}
        for k, v in h.items():
            if isinstance(v, (int, float, str)) or v is None:
                row[k] = _clean(v)  # NaN/inf -> null (strict-JSON clients)
        for prefix, mobj in (("training", h.get("training_metrics")),
                             ("validation", h.get("validation_metrics"))):
            if mobj is None:
                continue
            for mk in metric_keys:
                v = getattr(mobj, mk, None)
                if v is not None:
                    row[f"{prefix}_{mk}"] = _clean(v)
            dev = getattr(mobj, "mean_residual_deviance",
                          getattr(mobj, "mse", None))
            if dev is not None:
                row[f"{prefix}_deviance"] = _clean(dev)
        for k in set(cols) | set(row):
            cols.setdefault(k, [None] * i).append(row.get(k))
    return cols


def metrics_schema(m) -> dict | None:
    if m is None:
        return None
    out = {}
    for f in ("mse", "rmse", "mae", "r2", "auc", "pr_auc", "logloss",
              "mean_per_class_error", "ks", "null_deviance",
              "residual_deviance", "aic", "gini"):
        v = getattr(m, f, None)
        if v is not None:
            # wire casing follows the reference schemas exactly
            # (ModelMetricsBaseV3.java:50 RMSE/MSE, BinomialV3 AUC/Gini/AIC)
            wire = {"auc": "AUC", "aic": "AIC", "mse": "MSE",
                    "rmse": "RMSE", "gini": "Gini"}.get(f, f)
            out[wire] = _clean(v)
    cmn = getattr(m, "custom_metric_name", None)
    if cmn is not None:
        out["custom_metric_name"] = cmn
        out["custom_metric_value"] = _clean(
            getattr(m, "custom_metric_value", None))
    cm = getattr(m, "confusion_matrix", None)
    if cm is not None:
        out["cm"] = {"table": _clean(cm)}
    for f in ("gains_lift_table", "max_criteria_and_metric_scores"):
        t = getattr(m, f, None)
        if t is not None:
            out[f] = table_schema(t)
    if hasattr(m, "tot_withinss"):
        # `ModelMetricsClusteringV3`: SS decomposition + per-cluster stats;
        # combined CV metrics carry centroid_stats = null (the reference
        # cannot pool per-cluster rows across folds)
        out["totss"] = _clean(m.totss)
        out["tot_withinss"] = _clean(m.tot_withinss)
        out["betweenss"] = _clean(m.betweenss)
        if getattr(m, "sizes", None) is not None and not getattr(
                m, "_cv_combined", False):
            out["centroid_stats"] = {
                "name": "Centroid Statistics",
                "columns": [{"name": "centroid", "type": "int"},
                            {"name": "size", "type": "double"},
                            {"name": "within_cluster_sum_of_squares",
                             "type": "double"}],
                "data": [
                    list(range(1, len(np.asarray(m.sizes)) + 1)),
                    _clean(np.asarray(m.sizes)),
                    _clean(np.asarray(m.withinss))]}
        else:
            out["centroid_stats"] = None
    ts = getattr(m, "thresholds_and_metric_scores", None)
    if ts is not None:
        # downsample the 1024-bin per-threshold arrays (stride 8 → 128 rows):
        # the full resolution lives on the model; the wire payload only feeds
        # client-side threshold lookups, where 1/128 granularity suffices
        out["thresholds_and_metric_scores"] = {
            k: _clean(np.asarray(v)[::8]) for k, v in ts.items()}
    return out


def serving_model_schema(info: dict) -> dict:
    """Wire shape of a serving registration (`POST /3/Serving/models/{id}`):
    the ServedModel.info() dict plus a key ref, JSON-cleaned."""
    out = _clean(dict(info))
    out["serving_model_id"] = key_schema(info["model_id"],
                                         "Key<ServingModel>")
    return out


def serving_stats_schema(stats: dict) -> dict:
    """Wire shape of `GET /3/Serving/stats`: {model_id: snapshot} from
    `serving/stats.py`, JSON-cleaned (NaN-free percentiles)."""
    return {"models": _clean(stats)}


def serving_route_schema(stats: dict) -> dict:
    """Wire shape of a serving route (`/3/Serving/routes/{endpoint}`):
    the Route.stats() dict — endpoint, seed, request count, per-variant
    weights/counters/divergence — JSON-cleaned."""
    return _clean(dict(stats))


def model_schema(model) -> dict:
    """`water/api/schemas3/ModelSchemaV3` (summary form)."""
    o = model.output
    out = {
        "model_id": key_schema(model.key, "Key<Model>"),
        "algo": getattr(model, "algo_override", None) or model.algo_name,
        "algo_full_name": getattr(model, "algo_override", None)
        or model.algo_name,
        "response_column_name": getattr(model.params, "response_column", None),
        "output": {
            "model_category": o.model_category,
            "names": o.names,
            "domains": _clean([o.domains.get(n) for n in o.names]),
            "response_domain": o.response_domain,
            "training_metrics": metrics_schema(o.training_metrics),
            "validation_metrics": metrics_schema(o.validation_metrics),
            "cross_validation_metrics": metrics_schema(o.cross_validation_metrics),
            "cross_validation_models": (
                [key_schema(m.key, "Key<Model>") for m in o.cv_models]
                if getattr(o, "cv_models", None) else None),
            "variable_importances": _clean(o.variable_importances),
            "scoring_history_length": len(o.scoring_history),
            "scoring_history": scoring_history_schema(o.scoring_history),
            "run_time_ms": o.run_time_ms,
        },
    }
    if getattr(o, "num_iterations", None) is not None:
        out["output"]["num_iterations"] = int(o.num_iterations)
    import dataclasses as _dc

    if _dc.is_dataclass(model.params):
        # `ModelSchemaV3.parameters` — actual vs default values per param
        # (h2o-py `model.parms[name]['actual_value']` reads these)
        plist = []
        for fld in _dc.fields(model.params):
            v = getattr(model.params, fld.name)
            if isinstance(v, Frame) or hasattr(v, "vecs"):
                v = {"name": getattr(v, "key", None)}
            default = None if fld.default is _dc.MISSING else fld.default
            if fld.default_factory is not _dc.MISSING:  # type: ignore
                default = fld.default_factory()
            plist.append({"name": fld.name, "label": fld.name,
                          "actual_value": _clean(v),
                          "default_value": _clean(default)})
        out["parameters"] = plist

    def _frame_ref(frobj):
        return {"name": frobj.key, "type": "Key<Frame>",
                "URL": f"/3/Frames/{frobj.key}"}

    if getattr(o, "cv_holdout_predictions", None) is not None \
            and o.cv_holdout_predictions.key:
        out["output"]["cross_validation_holdout_predictions_frame_id"] = \
            _frame_ref(o.cv_holdout_predictions)
    if getattr(o, "cv_fold_predictions", None):
        out["output"]["cross_validation_predictions"] = [
            _frame_ref(f) for f in o.cv_fold_predictions if f.key]
    if getattr(o, "cv_fold_assignment", None) is not None:
        out["output"]["cross_validation_fold_assignment_frame_id"] = \
            _frame_ref(o.cv_fold_assignment)
    if getattr(o, "weights_keys", None):
        # `DeepLearningModelOutputV3.weights/biases` — frame key refs with
        # the /3/Frames URL h2o-py's model.weights() splits apart
        out["output"]["weights"] = [
            {"name": k, "type": "Key<Frame>", "URL": f"/3/Frames/{k}"}
            for k in o.weights_keys]
        out["output"]["biases"] = [
            {"name": k, "type": "Key<Frame>", "URL": f"/3/Frames/{k}"}
            for k in o.biases_keys]
    if hasattr(model, "centers"):  # clustering: KMeansModelOutputV3.centers
        import numpy as _np

        c = _np.asarray(model.centers)
        # centers live in the EXPANDED feature space (one-hot categoricals),
        # which may be wider than the input names
        cnames = list(o.names) if len(o.names) == c.shape[1] else \
            [f"C{j + 1}" for j in range(c.shape[1])]
        out["output"]["centers"] = {
            "name": "Cluster means",
            "columns": [{"name": n, "type": "double"} for n in cnames],
            "data": _clean([c[:, j].tolist() for j in range(c.shape[1])])}
    if hasattr(model, "coef"):  # GLM-family: `hex/schemas/GLMModelV3`
        try:
            coefs = model.coef()
        except Exception as e:  # keep /3/Models listing alive, but visibly
            from ..utils.log import warn

            warn(f"coefficients_table for {model.key}: {e!r}")
            coefs = None
        flat = coefs and not any(isinstance(v, dict) for v in coefs.values())
        if flat:  # multinomial's {class: {coef: v}} ships per-class instead
            tbl = {"names": list(coefs), "coefficients": _clean(
                list(coefs.values()))}
            if hasattr(model, "coef_norm"):
                tbl["standardized_coefficients"] = _clean(
                    list(model.coef_norm().values()))
            for attr, col in (("std_errs", "std_errs"),
                              ("z_values", "z_values"),
                              ("p_values", "p_values")):
                d = getattr(model, attr, None)
                if d:
                    tbl[col] = _clean([d.get(n) for n in coefs])
            out["output"]["coefficients_table"] = tbl
        elif coefs:
            # `coefficients_table_multinomials_with_class_names` role: one
            # coefficient list per response class (`GLMModelV3.java:33`)
            any_class = next(iter(coefs.values()))
            out["output"]["coefficients_table_multinomial"] = {
                "names": list(any_class),
                "classes": list(coefs),
                "coefficients": [
                    _clean([coefs[k].get(n) for n in any_class])
                    for k in coefs]}
        disp = getattr(model, "dispersion_estimated", None)
        if disp is not None:
            out["output"]["dispersion"] = _clean(disp)
    return out


# ---------------------------------------------------------------------------
# fleet observability plane (PR 13)
# ---------------------------------------------------------------------------
def program_schema(pid: str, rec: dict) -> dict:
    """One `/3/Programs` entry, JSON-cleaned: the wire shape tools parse
    (the bench sidecar's program block uses the compact subset of these
    field names, so a consumer learns ONE schema)."""
    return {"program_id": pid, "kind": rec.get("kind"),
            "name": rec.get("name"), "labels": _clean(rec.get("labels")),
            "flops": _clean(rec.get("flops")),
            "bytes_accessed": _clean(rec.get("bytes_accessed")),
            "memory": _clean(rec.get("memory")),
            "dispatch_count": rec.get("dispatch_count"),
            "wall": _clean(rec.get("wall")),
            "achieved_flops_per_s": _clean(rec.get(
                "achieved_flops_per_s")),
            "roofline_fraction": _clean(rec.get("roofline_fraction")),
            "registered_ms": rec.get("registered_ms")}


def programs_schema(snapshot: dict, peak_flops) -> dict:
    """The full `GET /3/Programs` payload."""
    return {"programs": {pid: program_schema(pid, rec)
                         for pid, rec in snapshot.items()},
            "count": len(snapshot),
            "peak_flops_per_s": _clean(peak_flops)}


# ---------------------------------------------------------------------------
# causal observability plane (PR 15)
# ---------------------------------------------------------------------------
def health_schema(snap: dict) -> dict:
    """The `GET /3/Health` payload (utils/health.py snapshot), JSON-
    cleaned. ``ready``/``live`` and the typed ``degraded`` reasons are
    the contract autoscalers and rollout gates switch on; ``checks`` and
    the per-SLO ``slo`` burn block carry the supporting numbers."""
    return _clean(dict(snap))


def workload_schema(snap: dict) -> dict:
    """The `GET /3/Workload` payload (workload/manager.py snapshot),
    JSON-cleaned. ``tenants`` carries weights/quotas/counters, ``entries``
    the per-job scheduler state (QUEUED/RUNNING/PARKED/FINISHED) and
    ``slots``/``seed`` the dispatch configuration."""
    return _clean(dict(snap))


def slow_traces_schema(traces: list, total: int) -> dict:
    """The `GET /3/SlowTraces` payload: the tail-capture ring (each entry
    = SLO verdict + full span tree + program dispatch walls), plus the
    ever-captured total so a poller can detect rotation."""
    return {"slow_traces": _clean(list(traces)),
            "count": len(traces),
            "total_captured": int(total)}
