"""h2o-py-compatible client — the L10 surface (`h2o-py/h2o/h2o.py`,
`frame.py`, `estimators/`) speaking this package's REST API.

Mirrors the reference's client architecture: a connection object wrapping the
versioned JSON endpoints (`h2o-py/h2o/backend/connection.py:249,431`), module
functions (``init/connect/import_file/get_frame/remove/rapids/shutdown``), an
``H2OFrame`` handle whose operations compile to Rapids expressions posted to
`/99/Rapids` lazily (`h2o-py/h2o/expr.py:27-44` ExprNode DAG: frame ops hold
a pending expression, nested ops fuse, and one `(tmp= ...)` materializes on
first identity/data access), and estimator classes over
`/3/ModelBuilders/{algo}` (`h2o-py/h2o/estimators/`).

``init()`` with no running server boots an in-process `H2OServer` — the analog
of h2o.init() spawning a local JVM (`h2o-py/h2o/h2o.py:287`).
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
import urllib.parse
import uuid

from ..utils.retry import RetryBudgetExceeded, retry_after_verdict

_conn = None


class H2OConnectionError(Exception):
    """REST-level failure. Carries ``status`` (HTTP code), ``headers`` and
    the parsed error ``payload`` when the server replied at all — typed
    helpers (serving's 429/408 mapping) key off those."""

    status: int | None = None
    headers: dict | None = None
    payload: dict | None = None


class H2OServingOverloadError(H2OConnectionError):
    """`POST /3/Serving/score` hit a full queue (HTTP 429): back off for
    ``retry_after_s`` (the server's Retry-After drain estimate)."""

    retry_after_s: float = 0.0


class H2OServingTimeoutError(H2OConnectionError):
    """`POST /3/Serving/score` missed its deadline while queued (408)."""


class H2ORetriesExhaustedError(H2OConnectionError, RetryBudgetExceeded):
    """The client's automatic transient retry gave up. Dual-typed on
    purpose: still an ``H2OConnectionError`` (every existing handler —
    e.g. ``remove()``'s frames-vs-models fallback — keeps working) AND a
    ``RetryBudgetExceeded`` (attempts/elapsed/cause attached). The
    transport fields mirror the FINAL underlying error."""

    def __init__(self, description: str, attempts: int, elapsed_s: float,
                 last: BaseException):
        RetryBudgetExceeded.__init__(self, description, attempts,
                                     elapsed_s, last)
        self.status = getattr(last, "status", None)
        self.headers = getattr(last, "headers", None)
        self.payload = getattr(last, "payload", None)
        self.no_server = getattr(last, "no_server", False)


class H2OConnection:
    """REST transport — `h2o-py/h2o/backend/connection.py` analog.

    The wire is POOLED: one persistent `http.client.HTTPConnection` per
    CLIENT THREAD (the server is HTTP/1.1 keep-alive; re-dialing TCP +
    rebuilding a urllib opener per request capped the wire at ~450 req/s
    while the batcher behind it does 37×). A stale pooled socket — server
    restarted, idle timeout, half-closed keep-alive — redials ONCE
    transparently when the request body is replayable; everything else
    keeps the pre-pool semantics: typed errors, Retry-After retries,
    streamed uploads/downloads. ``H2O_TPU_CLIENT_KEEPALIVE=0`` reverts to
    one connection per request (the serving_wire bench baseline)."""

    def __init__(self, url: str, username: str | None = None,
                 password: str | None = None,
                 verify_ssl_certificates: bool = True,
                 cacert: str | None = None):
        self.url = url.rstrip("/")
        self.session_id: str | None = None
        self.requests_count = 0  # h2o-py connection counter (lazy-op tests)
        self.connected = True
        self._pool = threading.local()  # .conn: this thread's keep-alive
        self._auth = None
        self._ssl_ctx = None
        if url.startswith("https"):
            import ssl

            self._ssl_ctx = ssl.create_default_context(cafile=cacert)
            if not verify_ssl_certificates:
                self._ssl_ctx.check_hostname = False
                self._ssl_ctx.verify_mode = ssl.CERT_NONE
        if username is not None:
            import base64

            self._auth = "Basic " + base64.b64encode(
                f"{username}:{password or ''}".encode()).decode()

    def request(self, method: str, path: str, data: dict | None = None,
                params: dict | None = None, raw: bool = False,
                filename: str | None = None,
                save_to: str | None = None,
                retry: bool | None = None) -> dict | str:
        """``raw=True`` returns the response body as text (non-JSON
        endpoints like DownloadDataset) through the same auth/SSL path.
        ``filename`` streams that local file as the request body (the h2o-py
        connection's file-upload mode — http.client reads file objects in
        8KB blocks, so large pushes never materialize in memory).
        ``save_to`` streams a binary response body to that local path and
        returns the path (the h2o-py save_to download mode).

        Transient-failure policy (`utils/retry.py`): idempotent methods
        (GET/HEAD/DELETE, no upload body) retry connection-level failures
        and 429/503 — honoring a server Retry-After — with jittered
        backoff, giving up with the typed ``RetryBudgetExceeded``.
        Non-idempotent requests never retry automatically (a replayed POST
        could double-train a model); ``retry`` overrides either way."""
        pathq = path
        if params:
            pathq += "?" + urllib.parse.urlencode(params)
        headers = {}
        if self._auth:
            headers["Authorization"] = self._auth
        if filename is not None:
            headers["Content-Type"] = "application/octet-stream"
        elif data is not None:
            headers["Content-Type"] = "application/json"

        def _attempt():
            # the body is built PER ATTEMPT: a retried upload must stream a
            # fresh file handle, never re-send the consumed one (which
            # http.client would transmit as an empty body)
            body = None
            if filename is not None:
                body = open(filename, "rb")
                headers["Content-Length"] = str(os.path.getsize(filename))
            elif data is not None:
                body = json.dumps(data).encode()
            try:
                return self._send(method, pathq, body, headers, raw,
                                  save_to)
            finally:
                if filename is not None and body is not None:
                    body.close()

        self.requests_count += 1
        if retry is None:
            retry = method in ("GET", "HEAD", "DELETE") and filename is None
        if not retry:
            return _attempt()
        from ..utils.retry import retry_call

        try:
            return retry_call(_attempt, retryable=_transient_rest,
                              description=f"{method} {path}")
        except RetryBudgetExceeded as e:
            raise H2ORetriesExhaustedError(
                f"{method} {path}", e.attempts, e.elapsed_s,
                e.last) from e.last

    # -- pooled transport ---------------------------------------------------
    def _new_conn(self) -> http.client.HTTPConnection:
        u = urllib.parse.urlsplit(self.url)
        if u.scheme == "https":
            return http.client.HTTPSConnection(
                u.hostname, u.port, timeout=600, context=self._ssl_ctx)
        return http.client.HTTPConnection(u.hostname, u.port, timeout=600)

    @staticmethod
    def _rewind(body) -> bool:
        """True when ``body`` can be re-sent on a redial (None/bytes
        always; a file only if it seeks back to 0)."""
        if body is None or isinstance(body, (bytes, bytearray)):
            return True
        try:
            body.seek(0)
            return True
        except (AttributeError, OSError):
            return False

    def _send(self, method: str, pathq: str, body, headers: dict,
              raw: bool, save_to: str | None):
        from ..utils import failpoints, knobs, telemetry

        failpoints.hit("client.request")
        keepalive = knobs.get_bool("H2O_TPU_CLIENT_KEEPALIVE")
        conn = getattr(self._pool, "conn", None) if keepalive else None
        pooled = conn is not None
        hdrs = dict(headers)
        # wire trace propagation: a request issued inside an open span
        # carries its W3C-style traceparent so the server roots the
        # request span under THIS caller's trace id — client→REST→job→
        # chunk spans merge into one Perfetto session across processes.
        # Outside any span no header is sent (no trace to continue).
        tp = telemetry.current_traceparent()
        if tp is not None:
            hdrs.setdefault("traceparent", tp)
        # tenant attribution: the H2O_TPU_TENANT knob stamps every request
        # so the server-side workload manager books admission, fair-share
        # tickets and per-tenant metrics against this client's tenant.
        tenant = knobs.get_str("H2O_TPU_TENANT")
        if tenant:
            hdrs.setdefault("X-H2O-TPU-Tenant", tenant)
        if not keepalive:
            hdrs["Connection"] = "close"
        try:
            if conn is None:
                conn = self._new_conn()
            try:
                conn.request(method, pathq, body=body, headers=hdrs)
                resp = conn.getresponse()
            except (http.client.HTTPException, OSError):
                # a POOLED socket gone stale (server restart, keep-alive
                # timeout, half-close — RemoteDisconnected, resets, EBADF)
                # redials ONCE on a fresh connection — transparent
                # reconnect, not a retry-policy attempt; a FRESH
                # connection failing is a real transport error
                conn.close()
                if keepalive:
                    self._pool.conn = None
                if not pooled or not self._rewind(body):
                    raise
                pooled = False
                conn = self._new_conn()
                conn.request(method, pathq, body=body, headers=hdrs)
                resp = conn.getresponse()
        except (http.client.HTTPException, OSError) as e:
            try:
                conn.close()
            except Exception:   # noqa: BLE001 — already broken
                pass
            if keepalive:
                self._pool.conn = None
            err = H2OConnectionError(f"no H2O server at {self.url}: {e}")
            err.no_server = True  # distinguishes "nothing listening" from
            raise err             # HTTP-level failures like 401
        try:
            return self._read_response(resp, raw, save_to)
        finally:
            # the body was fully read either way — the socket is clean for
            # the next request on this thread
            if keepalive:
                self._pool.conn = conn
            else:
                conn.close()

    def _read_response(self, resp, raw: bool, save_to: str | None):
        status = resp.status
        rheaders = {k: v for k, v in resp.getheaders()}
        if status >= 400:
            body = resp.read().decode(errors="replace")
            payload = None
            msg = f"HTTP Error {status}: {resp.reason}"
            try:
                payload = json.loads(body)
                if isinstance(payload, dict):
                    msg = payload.get("msg", msg)
            except ValueError:
                pass
            err = H2OConnectionError(msg)
            err.status = status
            err.headers = rheaders
            err.payload = payload if isinstance(payload, dict) else None
            raise err
        if save_to is not None:
            with open(save_to, "wb") as out:
                while True:
                    chunk = resp.read(1 << 20)
                    if not chunk:
                        break
                    out.write(chunk)
            return save_to
        text = resp.read().decode()
        return text if raw else json.loads(text)

    # session for rapids temp management
    def session(self) -> str:
        if self.session_id is None:
            self.session_id = self.request("POST", "/3/InitID")["session_key"]
        return self.session_id


def _transient_rest(e: BaseException):
    """Retry classifier for the REST transport: connection-level failures
    back off exponentially; 429/503 honor the server's Retry-After when it
    sent one (returning the float delegates the delay to retry_call)."""
    if isinstance(e, ConnectionError):
        return True  # failpoint-injected / OS-level resets before the wire
    if not isinstance(e, H2OConnectionError):
        return False
    if getattr(e, "no_server", False):
        return True
    if e.status in (429, 503):
        return retry_after_verdict((e.headers or {}).get("Retry-After"))
    return False


def connection() -> H2OConnection:
    if _conn is None:
        raise H2OConnectionError("not connected; call h2o.init() first")
    return _conn


# ---------------------------------------------------------------------------
# module surface (`h2o-py/h2o/h2o.py`)
# ---------------------------------------------------------------------------
def init(url: str | None = None, port: int = 54321, name: str = "h2o_tpu",
         strict_version_check: bool = False, username: str | None = None,
         password: str | None = None, hash_login: dict | str | None = None,
         verify_ssl_certificates: bool = True, cacert: str | None = None,
         **kw):
    """Connect to a running server, else boot one in-process
    (`h2o-py/h2o/h2o.py:137` connect-or-spawn). `username`/`password` send
    basic auth; `hash_login` configures it on a freshly booted server;
    `verify_ssl_certificates`/`cacert` control https trust."""
    global _conn
    if url is None:
        url = f"http://127.0.0.1:{port}"
    try:
        _conn = H2OConnection(url, username, password,
                              verify_ssl_certificates, cacert)
        # probe without retry: "nothing listening" here means "boot one
        # in-process", and that fallback must stay instant
        _conn.request("GET", "/3/Cloud", retry=False)
        return _conn
    except H2OConnectionError as e:
        if not getattr(e, "no_server", False):
            # a server IS listening but refused us (401, 5xx…) — surface it
            # rather than silently booting a fresh empty cluster beside it
            raise
    from ..utils import compile_cache
    from .server import H2OServer

    # boot-an-in-process-server path: this process will compile — arm the
    # knob-gated persistent XLA compile cache with the cloud (idempotent;
    # deploy_entry's server mode arms it the same way)
    compile_cache.ensure()
    server = H2OServer(port=port, name=name, hash_login=hash_login).start()
    _conn = H2OConnection(server.url, username, password,
                          verify_ssl_certificates, cacert)
    _conn._server = server  # keep alive / allow shutdown
    cluster_status()
    return _conn


def connect(url: str, username: str | None = None,
            password: str | None = None,
            verify_ssl_certificates: bool = True, cacert: str | None = None,
            **kw):
    global _conn
    _conn = H2OConnection(url, username, password,
                          verify_ssl_certificates, cacert)
    _conn.request("GET", "/3/Cloud")
    return _conn


def cluster_status() -> dict:
    return connection().request("GET", "/3/Cloud")


def shutdown(prompt: bool = False):
    global _conn
    connection().request("POST", "/3/Shutdown")
    _conn = None


def _poll_job(job_json: dict) -> dict:
    key = job_json["job"]["key"]["name"]
    while True:
        j = connection().request("GET", f"/3/Jobs/{key}")["jobs"][0]
        if j["status"] == "DONE":
            return j
        if j["status"] == "FAILED":
            raise RuntimeError(f"job failed: {j.get('exception')}\n"
                               f"{j.get('stacktrace', '')}")
        if j["status"] == "CANCELLED":
            raise RuntimeError(f"job {key} was cancelled")
        time.sleep(0.05)


def import_file(path: str, destination_frame: str | None = None) -> "H2OFrame":
    """`h2o.import_file` — ImportFiles → ParseSetup → Parse → poll job."""
    c = connection()
    imp = c.request("GET", "/3/ImportFiles", params={"path": path})
    if imp["fails"]:
        raise FileNotFoundError(f"import failed for {imp['fails']}")
    setup = c.request("POST", "/3/ParseSetup",
                      data={"source_frames": imp["files"]})
    dest = destination_frame or setup["destination_frame"]
    job = c.request("POST", "/3/Parse",
                    data={"source_frames": imp["files"],
                          "destination_frame": dest})
    done = _poll_job(job)
    return H2OFrame._by_id(done["dest"]["name"])


def _python_obj_to_pandas(obj, column_names=None, header=0):
    """h2o-py `H2OFrame(python_obj)` conversion semantics (`h2o-py/h2o/
    frame.py` _upload_python_object): a flat list/tuple is ONE column; a
    list/tuple of lists/tuples is a list of ROWS (jagged rows NA-pad to the
    widest); a dict maps names -> columns (jagged columns NA-pad); numpy
    1-D is one column, 2-D is rows; pandas passes through. Unnamed columns
    become C1..Cn."""
    import numpy as _np
    import pandas as pd

    if isinstance(obj, pd.DataFrame):
        df = obj.copy()
    elif isinstance(obj, dict):
        cols = {k: list(v) if hasattr(v, "__iter__")
                and not isinstance(v, str) else [v]
                for k, v in obj.items()}
        depth = max(len(v) for v in cols.values())
        cols = {k: v + [_np.nan] * (depth - len(v))
                for k, v in cols.items()}
        df = pd.DataFrame(cols)
    else:
        if isinstance(obj, _np.ndarray):
            rows = obj.tolist()
        elif isinstance(obj, (list, tuple)):
            rows = list(obj)
        else:
            rows = [obj]  # single scalar
        if rows and not any(isinstance(r, (list, tuple)) for r in rows):
            rows = [[r] for r in rows]  # flat sequence = one column
        if header == 1 and rows and column_names is None:
            # h2o-py header=1: the first row IS the column names
            column_names = [str(c) for c in rows[0]]
            rows = rows[1:]
        width = max((len(r) for r in rows), default=0)
        rows = [list(r) + [_np.nan] * (width - len(r)) for r in rows]
        names = list(column_names) if column_names else \
            [f"C{i+1}" for i in range(width)]
        df = pd.DataFrame(rows, columns=names)
    if column_names:
        df.columns = list(column_names)
    elif not isinstance(obj, dict):
        # positional pandas columns (0, 1, ...) get h2o's C1..Cn names
        df.columns = [f"C{i+1}" if isinstance(c, int) else str(c)
                      for i, c in enumerate(df.columns)]
    for c in df.columns:  # h2o uploads booleans as 0/1 numerics
        if df[c].dtype == bool:
            df[c] = df[c].astype(int)
    return df


def upload_frame(python_obj, destination_frame: str | None = None,
                 column_names=None, column_types=None,
                 na_strings=None, header: int = 0) -> "H2OFrame":
    """Build a frame from a python object via a CSV push through
    `POST /3/PostFile` — the h2o.H2OFrame(python_obj) upload path."""
    import os
    import tempfile

    df = _python_obj_to_pandas(python_obj, column_names=column_names,
                               header=header)
    fd, tmp = tempfile.mkstemp(suffix=".csv")
    os.close(fd)
    try:
        # QUOTE_NONNUMERIC like h2o-py's uploader: strings (incl. empty
        # ones) ride quoted so a lone "" row isn't dropped as a blank line;
        # the parser maps quoted "" to NA for numerics but keeps it as the
        # empty string for string/enum columns unless na_strings says so
        import csv as _csv

        df.to_csv(tmp, index=False, quoting=_csv.QUOTE_NONNUMERIC)
        parse_kw = {"column_names": list(df.columns), "check_header": 1}
        if column_types is not None:
            if isinstance(column_types, dict):
                parse_kw["column_types"] = {str(k): v for k, v
                                            in column_types.items()}
            else:
                parse_kw["column_types"] = list(column_types)
        if na_strings is not None:
            parse_kw["na_strings"] = list(na_strings)
        return upload_file(tmp, destination_frame=destination_frame,
                           **parse_kw)
    finally:
        os.unlink(tmp)


def upload_file(path: str, destination_frame: str | None = None,
                **parse_kw) -> "H2OFrame":
    """`h2o.upload_file` (`h2o-py/h2o/h2o.py:341`): push a LOCAL file to the
    server — `POST /3/PostFile` → ParseSetup → Parse on the raw upload key.
    The push streams in 8KB blocks; nothing loads into client memory."""
    c = connection()
    if path.startswith("~"):
        path = os.path.expanduser(path)
    ret = c.request("POST", "/3/PostFile",
                    params={"filename": os.path.basename(path)},
                    filename=path)
    rawkey = ret["destination_frame"]
    setup = c.request("POST", "/3/ParseSetup",
                      data={"source_frames": [rawkey]})
    dest = destination_frame or setup["destination_frame"]
    job = c.request("POST", "/3/Parse",
                    data={"source_frames": [rawkey],
                          "destination_frame": dest, **parse_kw})
    done = _poll_job(job)
    return H2OFrame._by_id(done["dest"]["name"])


def _model_id_of(model) -> str:
    return model if isinstance(model, str) else model.model_id


def save_model(model, path: str = "", force: bool = False,
               filename: str | None = None) -> str:
    """`h2o.save_model` (`h2o-py/h2o/h2o.py:1490`): the SERVER saves the
    binary model to ``path`` — `GET /99/Models.bin/{id}?dir=...`."""
    mid = _model_id_of(model)
    filename = filename or mid
    full = os.path.join(os.getcwd() if path == "" else path, filename)
    return connection().request(
        "GET", f"/99/Models.bin/{urllib.parse.quote(mid)}",
        params={"dir": full, "force": str(bool(force)).lower()})["dir"]


def load_model(path: str) -> "H2OModelClient":
    """`h2o.load_model` (`h2o-py/h2o/h2o.py:1578`): the SERVER loads a
    binary model from ``path`` — `POST /99/Models.bin`."""
    res = connection().request("POST", "/99/Models.bin",
                               data={"dir": path})
    return get_model(res["models"][0]["model_id"]["name"])


def download_model(model, path: str = "",
                   filename: str | None = None) -> str:
    """`h2o.download_model` (`h2o-py/h2o/h2o.py:1527`): stream the binary
    model to the CLIENT machine — `GET /3/Models.fetch.bin/{id}`."""
    mid = _model_id_of(model)
    filename = filename or mid
    full = os.path.join(os.getcwd() if path == "" else path, filename)
    return connection().request(
        "GET", f"/3/Models.fetch.bin/{urllib.parse.quote(mid)}",
        save_to=full)


def upload_custom_metric(func, func_file: str = "metrics.py",
                         func_name: str | None = None,
                         class_name: str | None = None) -> str:
    """`h2o.upload_custom_metric` (`h2o-py/h2o/h2o.py:2165`): push a metric
    class (a class object or its source string) to the server as a zipped
    module via PostFile; returns the ``python:{key}={module}.{Class}``
    reference any model's ``custom_metric_func`` can name. The class must
    implement ``map(pred, act, w, o, model)``, ``reduce(l, r)`` and
    ``metric(l)`` — the CMetricFunc triple."""
    import inspect
    import tempfile
    import zipfile

    if not func_file.endswith(".py"):
        raise ValueError("func_file must end with .py")
    module = func_file[:-3]
    if isinstance(func, str):
        if class_name is None:
            raise ValueError("class_name is required when func is a source "
                             "string")
        code = func
        derived = f"metrics_{class_name}"
        path = f"{module}.{class_name}"
    else:
        if not inspect.isclass(func):
            raise TypeError("func must be a class or a source string")
        for method in ("map", "reduce", "metric"):
            if method not in func.__dict__:
                raise ValueError(f"the class must define `{method}`")
        import textwrap

        code = textwrap.dedent(inspect.getsource(func))
        derived = f"metrics_{func.__name__}"
        path = f"{module}.{func.__name__}"
    key = func_name or derived
    fd, tmp = tempfile.mkstemp(suffix=".zip")
    os.close(fd)
    try:
        with zipfile.ZipFile(tmp, "w") as zf:
            zf.writestr(func_file, code)
        connection().request("POST", "/3/PostFile",
                             params={"destination_frame": key,
                                     "filename": f"{key}.zip"},
                             filename=tmp)
    finally:
        os.unlink(tmp)
    return f"python:{key}={path}"


def import_mojo(path: str) -> "H2OModelClient":
    """`h2o.import_mojo`: load a SERVER-side MOJO zip as a Generic model
    (`hex/generic/Generic` — first-class import)."""
    est = H2OGenericEstimator(path=path)
    est.train(training_frame=None)
    return est._model


def upload_mojo(path: str) -> "H2OModelClient":
    """`h2o.upload_mojo` (`h2o-py/h2o/h2o.py:2375`): push a CLIENT-side
    MOJO through PostFile, then import it as a Generic model."""
    c = connection()
    resp = c.request("POST", "/3/PostFile",
                     params={"filename": os.path.basename(path)},
                     filename=path)
    key = resp["destination_frame"]
    # the upload key resolves to its spool path server-side via Parse's
    # upload seam; Generic takes a filesystem path, so ask the server
    # where the bytes landed through the ImportFiles echo
    est = H2OGenericEstimator(path=key)
    est.train(training_frame=None)
    return est._model


def upload_model(path: str) -> "H2OModelClient":
    """`h2o.upload_model` (`h2o-py/h2o/h2o.py:1563`): push a CLIENT-side
    binary model to the server — PostFile.bin then Models.upload.bin."""
    c = connection()
    response = c.request("POST", "/3/PostFile.bin", filename=path)
    frame_key = response["destination_frame"]
    res = c.request("POST", "/99/Models.upload.bin",
                    data={"dir": frame_key})
    return get_model(res["models"][0]["model_id"]["name"])


def ls() -> list[str]:
    frames = connection().request("GET", "/3/Frames")["frames"]
    return [f["frame_id"]["name"] for f in frames]


def get_frame(frame_id: str) -> "H2OFrame":
    return H2OFrame._by_id(frame_id)


def get_model(model_id: str) -> "H2OModelClient":
    j = connection().request("GET", f"/3/Models/{urllib.parse.quote(model_id)}")
    return H2OModelClient(model_id, j["models"][0])


def remove(key):
    """`h2o.remove`: accepts a key string, an H2OFrame/model handle, or a
    list of either (h2o-py signature)."""
    if isinstance(key, (list, tuple)):
        for k in key:
            remove(k)
        return
    if not isinstance(key, str):
        key = getattr(key, "frame_id", None) or getattr(key, "model_id", key)
    c = connection()
    try:
        c.request("DELETE", f"/3/Frames/{urllib.parse.quote(key)}")
    except H2OConnectionError:
        c.request("DELETE", f"/3/Models/{urllib.parse.quote(key)}")


def as_list(frame: "H2OFrame", use_pandas: bool = True, header: bool = True):
    """`h2o.as_list` (`h2o-py/h2o/h2o.py`)."""
    return frame.as_data_frame(use_pandas=use_pandas, header=header)


def remove_all():
    """`h2o.remove_all` — `DELETE /3/DKV` (RemoveAllHandler)."""
    connection().request("DELETE", "/3/DKV")


def create_frame(rows: int = 10000, cols: int = 10, seed: int = -1,
                 real_fraction: float | None = None,
                 categorical_fraction: float | None = None,
                 integer_fraction: float | None = None,
                 binary_fraction: float | None = None,
                 time_fraction: float | None = None,
                 string_fraction: float | None = None,
                 missing_fraction: float = 0.0, factors: int = 100,
                 has_response: bool = False, response_factors: int = 2,
                 frame_id: str | None = None, **kw) -> "H2OFrame":
    """`h2o.create_frame` — `POST /3/CreateFrame` (CreateFrameHandler).

    Unset fractions share the remainder by the reference client's weights
    (real .5, cat .2, int .2, bin .1, time/string 0 — `h2o.py:1807-1837`),
    so `string_fraction=1.0` yields a pure string frame like h2o-py."""
    frcs = [real_fraction, categorical_fraction, integer_fraction,
            binary_fraction, time_fraction, string_fraction]
    wgts = [0.5, 0.2, 0.2, 0.1, 0.0, 0.0]
    explicit = sum(0 if f is None else f for f in frcs)
    if explicit >= 1 + 1e-10:
        raise ValueError("column-type fractions must add up to <= 1")
    if explicit < 1 - 1e-10:
        remainder = 1 - explicit
        sum_w = sum(wgts[i] for i in range(6) if frcs[i] is None)
        for i in range(6):
            if frcs[i] is not None:
                continue
            frcs[i] = remainder if sum_w == 0 else \
                remainder * wgts[i] / sum_w
            remainder -= frcs[i]
            sum_w -= wgts[i]
    frcs = [0.0 if f is None else f for f in frcs]
    body = dict(rows=rows, cols=cols, seed=seed,
                real_fraction=frcs[0],
                categorical_fraction=frcs[1],
                integer_fraction=frcs[2],
                binary_fraction=frcs[3],
                time_fraction=frcs[4],
                string_fraction=frcs[5],
                missing_fraction=missing_fraction, factors=factors,
                has_response=str(bool(has_response)).lower(),
                response_factors=response_factors, **kw)
    # always send a unique dest (h2o-py sends py_tmp_key when unset) — two
    # back-to-back create_frame calls must not overwrite each other under
    # the server's shared default key
    body["dest"] = frame_id or f"py_createframe_{uuid.uuid4().hex[:12]}"
    j = connection().request("POST", "/3/CreateFrame", data=body)
    return H2OFrame._by_id(j["key"]["name"])


def split_frame_rest(frame: "H2OFrame", ratios=(0.75,), seed: int = -1,
                     destination_frames=None) -> list["H2OFrame"]:
    """Server-side split — `POST /3/SplitFrame` (SplitFrameHandler); the
    H2OFrame.split_frame method is the rapids path, this is the REST one."""
    body = {"dataset": frame.frame_id,
            "ratios": list(ratios), "seed": seed}
    if destination_frames:
        body["destination_frames"] = list(destination_frames)
    j = connection().request("POST", "/3/SplitFrame", data=body)
    return [H2OFrame._by_id(d["name"]) for d in j["destination_frames"]]


def insert_missing_values(frame: "H2OFrame", fraction: float = 0.1,
                          seed: int = -1) -> "H2OFrame":
    """`h2o.insert_missing_values` — `POST /3/MissingInserter`."""
    connection().request("POST", "/3/MissingInserter",
                         data={"dataset": frame.frame_id,
                               "fraction": fraction, "seed": seed})
    return H2OFrame._by_id(frame.frame_id)


def download_csv(frame: "H2OFrame") -> str:
    """`h2o.download_csv` body — `GET /3/DownloadDataset` (raw CSV)."""
    return connection().request("GET", "/3/DownloadDataset",
                                params={"frame_id": frame.frame_id}, raw=True)


def log_and_echo(message: str) -> None:
    """`h2o.log_and_echo` — `POST /3/LogAndEcho`."""
    connection().request("POST", "/3/LogAndEcho", data={"message": message})


def rapids(expr: str) -> dict:
    c = connection()
    return c.request("POST", "/99/Rapids",
                     data={"ast": expr, "session_id": c.session()})


# ---------------------------------------------------------------------------
# online scoring (`/3/Serving/...` — the h2o_tpu/serving/ runtime)
# ---------------------------------------------------------------------------
def register_serving(model=None, serving_id: str | None = None,
                     mojo_file: str | None = None, **options) -> dict:
    """Register a model for online scoring and warm up its bucket scorers
    (`POST /3/Serving/models/{id}`). ``model`` is an in-STORE model (client
    handle, estimator, or key string); alternatively ``mojo_file`` names a
    MOJO zip on the server's filesystem (or a PostFile upload key).
    ``options`` forwards the serving overrides (buckets, max_batch,
    max_wait_us, queue_depth, deadline_ms, stats_window, strict_levels).
    Returns the registration info (buckets, warmup_compiles, ...)."""
    data = dict(options)
    if mojo_file is not None:
        data["mojo_file"] = mojo_file
        sid = serving_id or os.path.basename(mojo_file).rsplit(".", 1)[0]
    else:
        if model is None:
            raise ValueError("register_serving needs a model or a mojo_file")
        data["model_id"] = _model_id_of(model)
        sid = serving_id or data["model_id"]
    return connection().request(
        "POST", f"/3/Serving/models/{urllib.parse.quote(sid)}", data=data)


def score_rows(serving_id: str, rows, deadline_ms=None,
               retries: int = 0) -> list:
    """Score one row dict or a list of them through the micro-batched
    runtime (`POST /3/Serving/score`); returns one typed prediction dict
    per row. Raises `H2OServingOverloadError` (queue full, carries
    ``retry_after_s``) and `H2OServingTimeoutError` (deadline expired) so
    callers can back off / retry instead of parsing status codes.

    ``retries > 0`` does the backing off for you (`utils/retry.py`):
    overloads sleep exactly the server's Retry-After drain estimate and
    re-submit, up to ``retries`` extra attempts / the retry wall-clock
    budget — scoring is read-only, so replaying the POST is safe. The
    typed give-up is ``RetryBudgetExceeded`` with the final overload as
    ``__cause__``. Default 0 keeps the raw backpressure signal."""
    if retries > 0:
        from ..utils.retry import retry_call

        def _overloaded(e):
            if isinstance(e, H2OServingOverloadError):
                return max(float(e.retry_after_s), 0.001)
            return False

        return retry_call(
            lambda: score_rows(serving_id, rows, deadline_ms=deadline_ms),
            retryable=_overloaded, attempts=retries + 1,
            description=f"score_rows({serving_id})")
    if isinstance(rows, dict):
        rows = [rows]
    data: dict = {"model_id": serving_id, "rows": list(rows)}
    if deadline_ms is not None:
        data["deadline_ms"] = deadline_ms
    try:
        resp = connection().request("POST", "/3/Serving/score", data=data)
    except H2OConnectionError as e:
        if e.status == 429:
            err = H2OServingOverloadError(str(e))
            err.status, err.headers, err.payload = (e.status, e.headers,
                                                    e.payload)
            err.retry_after_s = float(
                (e.payload or {}).get("retry_after_s")
                or (e.headers or {}).get("Retry-After") or 0.0)
            raise err from None
        if e.status == 408:
            err = H2OServingTimeoutError(str(e))
            err.status, err.headers, err.payload = (e.status, e.headers,
                                                    e.payload)
            raise err from None
        raise
    return resp["predictions"]


def serving_stats(serving_id: str | None = None) -> dict:
    """`GET /3/Serving/stats[/{id}]` → {model_id: snapshot} (p50/p95/p99
    latency, rows/s, mean batch occupancy, queue depth, counters)."""
    path = "/3/Serving/stats"
    if serving_id is not None:
        path += f"/{urllib.parse.quote(serving_id)}"
    return connection().request("GET", path)["models"]


def unregister_serving(serving_id: str) -> dict:
    """`DELETE /3/Serving/models/{id}` — stop the model's batcher."""
    return connection().request(
        "DELETE", f"/3/Serving/models/{urllib.parse.quote(serving_id)}")


def create_route(endpoint: str, variants, seed: int | None = None) -> dict:
    """Map a logical serving endpoint onto weighted model variants
    (`POST /3/Serving/routes/{endpoint}`). ``variants`` is a list of
    ``{"model_id": ..., "weight": ..., "shadow": bool}`` dicts — or the
    ``{model_id: weight}`` shorthand. The split is deterministic in the
    route ``seed`` (fixed seed = replayable variant sequence); shadow
    variants score every request off the response path and feed the
    divergence stats in `route_stats`."""
    if isinstance(variants, dict):
        variants = [{"model_id": k, "weight": v}
                    for k, v in variants.items()]
    data: dict = {"variants": list(variants)}
    if seed is not None:
        data["seed"] = int(seed)
    return connection().request(
        "POST", f"/3/Serving/routes/{urllib.parse.quote(endpoint)}",
        data=data)


def route_score(endpoint: str, rows, deadline_ms=None,
                retries: int = 0) -> list:
    """Score through a routed endpoint (`POST /3/Serving/score` with
    ``endpoint``): the router picks the serving variant per request —
    champion/canary split — and shadow variants see the same rows without
    touching the response. Same typed 429/408 surface (and ``retries``
    semantics) as `score_rows`."""
    if retries > 0:
        from ..utils.retry import retry_call

        def _overloaded(e):
            if isinstance(e, H2OServingOverloadError):
                return max(float(e.retry_after_s), 0.001)
            return False

        return retry_call(
            lambda: route_score(endpoint, rows, deadline_ms=deadline_ms),
            retryable=_overloaded, attempts=retries + 1,
            description=f"route_score({endpoint})")
    if isinstance(rows, dict):
        rows = [rows]
    data: dict = {"endpoint": endpoint, "rows": list(rows)}
    if deadline_ms is not None:
        data["deadline_ms"] = deadline_ms
    try:
        resp = connection().request("POST", "/3/Serving/score", data=data)
    except H2OConnectionError as e:
        if e.status == 429:
            err = H2OServingOverloadError(str(e))
            err.status, err.headers, err.payload = (e.status, e.headers,
                                                    e.payload)
            err.retry_after_s = float(
                (e.payload or {}).get("retry_after_s")
                or (e.headers or {}).get("Retry-After") or 0.0)
            raise err from None
        if e.status == 408:
            err = H2OServingTimeoutError(str(e))
            err.status, err.headers, err.payload = (e.status, e.headers,
                                                    e.payload)
            raise err from None
        raise
    return resp["predictions"]


def route_stats(endpoint: str | None = None) -> dict:
    """`GET /3/Serving/routes[/{endpoint}]` — request counts, per-variant
    weights/serve counts, shadow rows and prediction-delta divergence."""
    if endpoint is not None:
        return connection().request(
            "GET", f"/3/Serving/routes/{urllib.parse.quote(endpoint)}")
    return connection().request("GET", "/3/Serving/routes")


def delete_route(endpoint: str) -> dict:
    """`DELETE /3/Serving/routes/{endpoint}`."""
    return connection().request(
        "DELETE", f"/3/Serving/routes/{urllib.parse.quote(endpoint)}")


def serving_control() -> dict:
    """`GET /3/Serving/control` — fleet quota, placements, routes."""
    return connection().request("GET", "/3/Serving/control")


# ---------------------------------------------------------------------------
# fleet observability plane (PR 13 — programs / fleet metrics / profiler
# capture / flight recorder)
# ---------------------------------------------------------------------------
def programs() -> dict:
    """`GET /3/Programs` → {program_id: record}: per compiled program,
    XLA cost-model flops / bytes accessed, memory assignment, measured
    dispatch walls and the roofline fraction (null off-TPU)."""
    return connection().request("GET", "/3/Programs")["programs"]


def fleet_metrics(force: bool = False) -> dict:
    """`GET /3/Metrics?fleet=1` — the merged multi-process view: counters
    summed, gauges max'd, histogram quantiles count-weight-merged, every
    value also labeled per process. ``force`` bypasses the
    H2O_TPU_FLEET_INTERVAL_MS scrape cache."""
    path = "/3/Metrics?fleet=1" + ("&force=1" if force else "")
    return connection().request("GET", path)["fleet"]


def profiler_capture(ms: int = 1000) -> str:
    """`POST /3/Profiler/capture?ms=N` — bounded live jax.profiler device
    capture on the server process; returns the capture directory (load it
    in Perfetto / tensorboard-profile)."""
    return connection().request(
        "POST", f"/3/Profiler/capture?ms={int(ms)}")["dir"]


def flight_bundles() -> dict:
    """`GET /3/Flight` — flight-recorder state: armed?, dir, bundles."""
    return connection().request("GET", "/3/Flight")


def flight_bundle(name: str) -> dict:
    """`GET /3/Flight/{name}` — one diagnostics bundle's full content."""
    return connection().request(
        "GET", f"/3/Flight/{urllib.parse.quote(name)}")["bundle"]


# ---------------------------------------------------------------------------
# causal observability plane (PR 15 — health / SLO burn / slow traces)
# ---------------------------------------------------------------------------
def health() -> dict:
    """`GET /3/Health` — liveness/readiness with typed degradation
    reasons (device visibility, Cleaner headroom vs the reservation
    ledger, serving queue saturation, job heartbeats, watchdog trips,
    SLO burn). ``ready`` is the poll target for autoscalers and rollout
    gates; ``degraded`` names exactly what is wrong."""
    return connection().request("GET", "/3/Health")


def workload() -> dict:
    """`GET /3/Workload` — the multi-tenant workload manager snapshot:
    tenants (weights, quota fractions, preempt/shed/reject counters),
    scheduler entries with their QUEUED/RUNNING/PARKED/FINISHED state,
    and the dispatch configuration (slots, seed)."""
    return connection().request("GET", "/3/Workload")


def workload_configure(tenant: str, weight: float | None = None,
                       quota_fraction: float | None = None) -> dict:
    """`POST /3/Workload` — configure a tenant's fair-share weight
    (lottery tickets relative to other tenants) and/or HBM quota
    fraction (share of the reservation ledger admission debits against).
    Returns the refreshed workload snapshot."""
    body: dict = {"tenant": tenant}
    if weight is not None:
        body["weight"] = float(weight)
    if quota_fraction is not None:
        body["quota_fraction"] = float(quota_fraction)
    return connection().request("POST", "/3/Workload", data=body)


def slow_traces(limit: int | None = None) -> list:
    """`GET /3/SlowTraces` — the tail-based capture ring: full span trees
    (+ program dispatch walls) of requests that breached their SLO p99
    target, newest last."""
    path = "/3/SlowTraces" + (f"?limit={int(limit)}" if limit else "")
    return connection().request("GET", path)["slow_traces"]


# ---------------------------------------------------------------------------
# H2OFrame handle (`h2o-py/h2o/frame.py` + the lazy `h2o-py/h2o/expr.py`
# ExprNode DAG: frame-producing ops build a pending rapids expression and
# only materialize — one `(tmp= name expr)` POST — when the frame's identity
# or data is actually needed. Nested ops fuse into a single server round-trip
# the way h2o-py's expression DAG does.)
# ---------------------------------------------------------------------------
import itertools as _itertools

_TMP_COUNTER = _itertools.count(1)  # atomic under the GIL (worker threads)


class H2OFrame:
    def __init__(self, python_obj=None, destination_frame: str | None = None,
                 header: int = 0, column_names=None, column_types=None,
                 na_strings=None):
        self._pending: str | None = None  # un-materialized rapids expression
        self._inlined = False  # pending expr already embedded somewhere once
        if python_obj is not None:
            other = upload_frame(python_obj, destination_frame,
                                 column_names=column_names,
                                 column_types=column_types,
                                 na_strings=na_strings, header=header)
            self._id = other.frame_id
            self._schema = other._schema
        else:
            self._id = None
            self._schema = None

    @classmethod
    def from_python(cls, python_obj, destination_frame=None, header=0,
                    separator=",", column_names=None, column_types=None,
                    na_strings=None) -> "H2OFrame":
        """`H2OFrame.from_python` (`h2o-py/h2o/frame.py:155`)."""
        return cls(python_obj, destination_frame=destination_frame,
                   header=header, column_names=column_names,
                   column_types=column_types, na_strings=na_strings)

    @classmethod
    def _by_id(cls, frame_id: str) -> "H2OFrame":
        fr = cls()
        fr._id = frame_id
        return fr

    @classmethod
    def _lazy(cls, expr: str) -> "H2OFrame":
        fr = cls()
        fr._pending = expr
        return fr

    @property
    def frame_id(self) -> str:
        """Materializes a pending expression on first identity access
        (h2o-py `ExprNode._eager_frame`)."""
        if self._id is None and self._pending is not None:
            name = f"py_{next(_TMP_COUNTER)}_{os.getpid()}"
            rapids(f"(tmp= {name} {self._pending})")
            self._id = name
            self._pending = None
        return self._id

    @frame_id.setter
    def frame_id(self, value: str):
        self._id = value
        self._pending = None

    def _ref(self) -> str:
        """Expression fragment for embedding in a larger rapids expression.

        A pending expression inlines ONCE (fusion, no round-trip); any later
        reference materializes and embeds the key instead — this caps the
        expression-string growth of reused/self-referencing lazy frames
        (h2o-py's ExprNode caches evaluated nodes for the same reason) and
        keeps repeated scalar reductions from re-evaluating the chain."""
        if self._id is None and self._pending is not None and \
                not self._inlined:
            self._inlined = True
            return self._pending
        return self.frame_id

    def _fr(self, expr: str) -> "H2OFrame":
        return H2OFrame._lazy(expr)

    # -- metadata ------------------------------------------------------------
    def _summary(self) -> dict:
        if self._schema is None:
            self._schema = connection().request(
                "GET", f"/3/Frames/{urllib.parse.quote(self.frame_id)}/summary"
            )["frames"][0]
        return self._schema

    def refresh(self):
        self._schema = None

    _meta: dict | None = None  # local dims/names/types for lazy frames

    @property
    def nrow(self) -> int:
        if self._id is None and self._meta:
            return self._meta["rows"]
        return self._summary()["rows"]

    @property
    def ncol(self) -> int:
        if self._id is None and self._meta:
            return self._meta["cols"]
        return self._summary()["num_columns"]

    @property
    def columns(self) -> list[str]:
        if self._id is None and self._meta:
            return list(self._meta["names"])
        return [c["label"] for c in self._summary()["columns"]]

    @property
    def names(self) -> list[str]:
        return self.columns

    @names.setter
    def names(self, value: list[str]) -> None:
        # h2o-py allows `fr.names = [...]` as a rename-in-place
        self.set_names(list(value))

    @property
    def types(self) -> dict:
        if self._id is None and self._meta:
            return dict(self._meta["types"])
        return {c["label"]: c["type"] for c in self._summary()["columns"]}

    def __len__(self):
        return self.nrow

    # -- rapids-backed ops ---------------------------------------------------
    def _exec(self, expr: str) -> "H2OFrame | float | str":
        res = rapids(expr)
        if res.get("key"):
            return H2OFrame._by_id(res["key"]["name"])
        if res.get("scalar") is not None:
            return res["scalar"]
        if res.get("values") is not None:
            return res["values"]
        return res.get("string")

    def _quoted(self) -> str:
        return self.frame_id

    def _slice_bounds(self, sl: slice, n: int) -> tuple[int, int]:
        start = 0 if sl.start is None else sl.start
        stop = n if sl.stop is None else min(sl.stop, n)
        if sl.step not in (None, 1):
            raise ValueError("h2o frame slices are contiguous (step 1)")
        return start, stop

    def _col_expr(self, ref: str, sel) -> str:
        if isinstance(sel, str):
            return f"(cols {ref} '{sel}')"
        if isinstance(sel, int):
            return f"(cols {ref} {sel})"
        if isinstance(sel, slice):
            a, b = self._slice_bounds(sel, self.ncol)
            return f"(cols {ref} [{a}:{b}])"
        if isinstance(sel, list):
            inner = " ".join(f"'{s}'" if isinstance(s, str) else str(s)
                             for s in sel)
            return f"(cols {ref} [{inner}])"
        raise TypeError(f"bad column selector {sel!r}")

    def __getitem__(self, sel):
        if isinstance(sel, tuple) and len(sel) == 2:
            rows, cols = sel
            scalar = isinstance(rows, int) and isinstance(cols, (int, str))
            # column select first (never changes row identity), rows second
            ref = self._ref()
            expr = ref if (isinstance(cols, slice) and cols == slice(None)) \
                else self._col_expr(ref, cols)
            if isinstance(rows, int):
                expr = f"(rows {expr} [{rows}:{rows + 1}])"
            elif isinstance(rows, slice):
                if rows != slice(None):
                    a, b = self._slice_bounds(rows, self.nrow)
                    expr = f"(rows {expr} [{a}:{b}])"
            elif isinstance(rows, list):
                inner = " ".join(str(int(r)) for r in rows)
                expr = f"(rows {expr} [{inner}])"
            elif isinstance(rows, H2OFrame):
                expr = f"(rows {expr} (cols {rows._ref()} 0))"
            else:
                raise TypeError(f"bad row selector {rows!r}")
            out = self._fr(expr)
            return out._scalar() if scalar else out
        if isinstance(sel, (str, int, slice, list)):
            return self._fr(self._col_expr(self._ref(), sel))
        if isinstance(sel, H2OFrame):  # boolean mask frame
            return self._fr(f"(rows {self._ref()} (cols {sel._ref()} 0))")
        raise TypeError(f"bad selector {sel!r}")

    def _scalar(self):
        """The single cell of a 1x1 frame as a python value (the h2o-py
        `flatten()` read used by `fr[r, c]`)."""
        j = connection().request(
            "GET", f"/3/Frames/{urllib.parse.quote(self.frame_id)}",
            params={"row_count": 1})["frames"][0]
        c = j["columns"][0]
        if c.get("string_data") is not None:
            return c["string_data"][0]
        v = (c["data"] or [None])[0]
        if v is None:
            return float("nan")
        if c["domain"]:
            return c["domain"][int(v)]
        return v

    @staticmethod
    def _src_expr(value) -> str:
        if isinstance(value, H2OFrame):
            return value.frame_id
        if isinstance(value, str):
            return f"'{value}'"
        if value is None:
            return "NA"
        return repr(float(value))

    def __setitem__(self, sel, value):
        """Column/slice update: `(append ...)` for a new column, `(:= ...)`
        rectangle assign otherwise (h2o-py `H2OFrame.__setitem__` →
        `AstAppend`/`AstRectangleAssign`). The result is bound to a FRESH key
        and this handle rebinds to it — outstanding lazy frames built from
        the old key keep seeing the pre-mutation data, matching h2o-py's
        immutable ExprNode-DAG semantics."""
        src = self._src_expr(value)
        fid = self.frame_id
        if isinstance(sel, tuple) and len(sel) == 2:
            rowsel, colsel = sel
            rows = (f"(cols {rowsel.frame_id} 0)"
                    if isinstance(rowsel, H2OFrame) else
                    "[]" if rowsel is None else
                    f"[{' '.join(str(int(r)) for r in rowsel)}]"
                    if isinstance(rowsel, (list, tuple)) else
                    str(int(rowsel)))
            cols = (f"'{colsel}'" if isinstance(colsel, str)
                    else str(int(colsel)))
            expr = f"(:= {fid} {src} {cols} {rows})"
        elif isinstance(sel, str) and sel not in self.columns:
            expr = f"(append {fid} {src} '{sel}')"
        else:
            col = sel if not isinstance(sel, str) else f"'{sel}'"
            expr = f"(:= {fid} {src} {col} [])"
        name = f"py_{next(_TMP_COUNTER)}_{os.getpid()}"
        rapids(f"(tmp= {name} {expr})")
        self._id = name
        self._pending = None
        self.refresh()

    def _binop(self, op, other, reverse=False):
        rhs = other._ref() if isinstance(other, H2OFrame) else repr(float(other))
        lhs = self._ref()
        if reverse:
            lhs, rhs = rhs, lhs
        return self._fr(f"({op} {lhs} {rhs})")

    def __add__(self, o):
        return self._binop("+", o)

    def __radd__(self, o):
        return self._binop("+", o, True)

    def __sub__(self, o):
        return self._binop("-", o)

    def __mul__(self, o):
        return self._binop("*", o)

    def __truediv__(self, o):
        return self._binop("/", o)

    def __gt__(self, o):
        return self._binop(">", o)

    def __ge__(self, o):
        return self._binop(">=", o)

    def __lt__(self, o):
        return self._binop("<", o)

    def __le__(self, o):
        return self._binop("<=", o)

    def __eq__(self, o):  # noqa: comparing frames builds a frame, like h2o-py
        return self._binop("==", o)

    def __ne__(self, o):
        return self._binop("!=", o)

    __hash__ = None

    def __and__(self, o):
        return self._binop("&", o)

    def __or__(self, o):
        return self._binop("|", o)

    def __pow__(self, o):
        return self._binop("^", o)

    def __rpow__(self, o):
        return self._binop("^", o, reverse=True)

    def __mod__(self, o):
        return self._binop("%", o)

    def __rmod__(self, o):
        return self._binop("%", o, reverse=True)

    def __floordiv__(self, o):
        return self._binop("intDiv", o)

    def __rtruediv__(self, o):
        return self._binop("/", o, reverse=True)

    def __rsub__(self, o):
        return self._binop("-", o, reverse=True)

    def __abs__(self):
        return self._unop("abs")

    def __neg__(self):
        return self._binop("*", -1)

    def __invert__(self):
        return self._unop("not")

    # unary math surface (h2o-py H2OFrame.cos/log/... — each compiles to
    # the matching rapids prim lazily)
    def _unop(self, op) -> "H2OFrame":
        return self._fr(f"({op} {self._ref()})")

    def cos(self): return self._unop("cos")          # noqa: E704
    def sin(self): return self._unop("sin")          # noqa: E704
    def tan(self): return self._unop("tan")          # noqa: E704
    def acos(self): return self._unop("acos")        # noqa: E704
    def asin(self): return self._unop("asin")        # noqa: E704
    def atan(self): return self._unop("atan")        # noqa: E704
    def cosh(self): return self._unop("cosh")        # noqa: E704
    def sinh(self): return self._unop("sinh")        # noqa: E704
    def tanh(self): return self._unop("tanh")        # noqa: E704
    def exp(self): return self._unop("exp")          # noqa: E704
    def expm1(self): return self._unop("expm1")      # noqa: E704
    def log(self): return self._unop("log")          # noqa: E704
    def log1p(self): return self._unop("log1p")      # noqa: E704
    def log2(self): return self._unop("log2")        # noqa: E704
    def log10(self): return self._unop("log10")      # noqa: E704
    def sqrt(self): return self._unop("sqrt")        # noqa: E704
    def abs(self): return self._unop("abs")          # noqa: E704
    def floor(self): return self._unop("floor")      # noqa: E704
    def ceil(self): return self._unop("ceiling")     # noqa: E704
    def trunc(self): return self._unop("trunc")      # noqa: E704
    def sign(self): return self._unop("sign")        # noqa: E704
    def gamma(self): return self._unop("gamma")      # noqa: E704
    def lgamma(self): return self._unop("lgamma")    # noqa: E704
    def digamma(self): return self._unop("digamma")  # noqa: E704
    def trigamma(self): return self._unop("trigamma")  # noqa: E704
    def logical_negation(self): return self._unop("not")  # noqa: E704

    def mean(self, na_rm=True):
        return self._exec(f"(mean {self._ref()} {'true' if na_rm else 'false'})")

    def sum(self, na_rm=True):
        return self._exec(f"(sum {self._ref()} {'true' if na_rm else 'false'})")

    def min(self):
        return self._exec(f"(min {self._ref()} true)")

    def max(self):
        return self._exec(f"(max {self._ref()} true)")

    def sd(self):
        return self._exec(f"(sd {self._ref()} true)")

    def prod(self, na_rm=True):
        return self._exec(f"(prod {self._ref()} "
                          f"{'true' if na_rm else 'false'})")

    def all(self) -> bool:
        return bool(self._exec(f"(all {self._ref()} true)"))

    def any(self) -> bool:
        return bool(self._exec(f"(any {self._ref()} true)"))

    def cumsum(self, axis=0): return self._unop("cumsum")    # noqa: E704
    def cumprod(self, axis=0): return self._unop("cumprod")  # noqa: E704
    def cummin(self, axis=0): return self._unop("cummin")    # noqa: E704
    def cummax(self, axis=0): return self._unop("cummax")    # noqa: E704

    @property
    def dim(self) -> list:
        return [self.nrow, self.ncol]

    @property
    def shape(self) -> tuple:
        return (self.nrow, self.ncol)

    def __iter__(self):
        return (self[i] for i in range(self.ncol))

    def show(self, use_pandas=False):
        print(self)

    def summary(self, return_data=False):
        """Per-column summary print (`H2OFrame.summary`)."""
        if return_data:
            return self._summary()
        self.describe()

    def insert_missing_values(self, fraction=0.1, seed=None) -> "H2OFrame":
        """In-place NA injection — `POST /3/MissingInserter` on this frame's
        key (h2o-py's method of the same name mutates server-side too)."""
        insert_missing_values(self, fraction=fraction,
                              seed=-1 if seed is None else seed)
        self.refresh()
        return self

    def flatten(self):
        return self._scalar()

    def asfactor(self) -> "H2OFrame":
        return self._fr(f"(as.factor {self._ref()})")

    def asnumeric(self) -> "H2OFrame":
        return self._fr(f"(as.numeric {self._ref()})")

    def unique(self) -> "H2OFrame":
        return self._fr(f"(unique {self._ref()})")

    def table(self) -> "H2OFrame":
        return self._fr(f"(table {self._ref()})")

    def cbind(self, other: "H2OFrame") -> "H2OFrame":
        return self._fr(f"(cbind {self._ref()} {other._ref()})")

    def rbind(self, other: "H2OFrame") -> "H2OFrame":
        return self._fr(f"(rbind {self._ref()} {other._ref()})")

    def skewness(self, na_rm=True):
        return self._exec(f"(skewness {self._ref()} true)")

    def kurtosis(self, na_rm=True):
        return self._exec(f"(kurtosis {self._ref()} true)")

    def cor(self, other: "H2OFrame" = None):
        if other is None:
            fid = self.frame_id  # one evaluation, embedded twice by key
            return self._exec(f"(cor {fid} {fid} 'everything' 'Pearson')")
        return self._exec(f"(cor {self._ref()} {other._ref()} "
                          f"'everything' 'Pearson')")

    def quantile(self, prob=(0.01, 0.1, 0.25, 0.333, 0.5, 0.667, 0.75, 0.9,
                             0.99)) -> "H2OFrame":
        ps = " ".join(str(p) for p in prob)
        return self._fr(f"(quantile {self._ref()} [{ps}] 'interpolate' _)")

    def impute(self, column=-1, method="mean"):
        return self._exec(f"(h2o.impute {self.frame_id} {column} '{method}' "
                          f"'interpolate' [] _ _)")

    def scale(self, center=True, scale=True) -> "H2OFrame":
        c = "true" if center else "false"
        s = "true" if scale else "false"
        return self._fr(f"(scale {self._ref()} {c} {s})")

    def na_omit(self) -> "H2OFrame":
        return self._fr(f"(na.omit {self._ref()})")

    def fillna(self, method="forward", axis=0, maxlen=1) -> "H2OFrame":
        return self._fr(f"(h2o.fillna {self._ref()} '{method}' {axis} "
                        f"{maxlen})")

    def match(self, table, nomatch=None) -> "H2OFrame":
        items = " ".join(f"'{t}'" if isinstance(t, str) else str(t)
                         for t in table)
        nm = "_" if nomatch is None else str(nomatch)
        return self._fr(f"(match {self._ref()} [{items}] {nm} 1)")

    def cut(self, breaks, labels=None, include_lowest=False,
            right=True) -> "H2OFrame":
        bs = " ".join(str(b) for b in breaks)
        lb = "_" if not labels else \
            "[" + " ".join(f"'{l}'" for l in labels) + "]"
        il = "true" if include_lowest else "false"
        r = "true" if right else "false"
        return self._fr(f"(cut {self._ref()} [{bs}] {lb} {il} {r} 3)")

    def difflag1(self) -> "H2OFrame":
        return self._fr(f"(difflag1 {self._ref()})")

    def kfold_column(self, n_folds=3, seed=-1) -> "H2OFrame":
        return self._exec(f"(kfold_column {self.frame_id} {n_folds} {seed})")

    def stratified_kfold_column(self, n_folds=3, seed=-1) -> "H2OFrame":
        return self._exec(
            f"(stratified_kfold_column {self.frame_id} {n_folds} {seed})")

    def stratified_split(self, test_frac=0.2, seed=-1) -> "H2OFrame":
        return self._exec(f"(h2o.random_stratified_split {self.frame_id} "
                          f"{test_frac} {seed})")

    def levels(self):
        return self._exec(f"(levels {self.frame_id})")

    def relevel(self, y: str) -> "H2OFrame":
        return self._exec(f"(relevel {self.frame_id} '{y}')")

    def pivot(self, index: str, column: str, value: str) -> "H2OFrame":
        return self._exec(f"(pivot {self.frame_id} '{index}' '{column}' "
                          f"'{value}')")

    def melt(self, id_vars, value_vars=None, var_name="variable",
             value_name="value", skipna=False) -> "H2OFrame":
        ids = " ".join(f"'{c}'" for c in id_vars)
        vv = "_" if not value_vars else \
            "[" + " ".join(f"'{c}'" for c in value_vars) + "]"
        sk = "true" if skipna else "false"
        return self._exec(f"(melt {self.frame_id} [{ids}] {vv} '{var_name}' "
                          f"'{value_name}' {sk})")

    def transpose(self) -> "H2OFrame":
        return self._exec(f"(t {self.frame_id})")

    def mult(self, other: "H2OFrame") -> "H2OFrame":
        return self._exec(f"(x*y {self.frame_id} {other.frame_id})")

    def topn(self, column=0, nPercent=10, grabTopN=-1) -> "H2OFrame":
        """grabTopN=-1 → top values; any other value → bottom (h2o-py
        `topNBottomN` convention routes both through this prim)."""
        bottom = "0" if grabTopN == -1 else "1"
        return self._exec(f"(topn {self.frame_id} {column} {nPercent} "
                          f"{bottom})")

    def mode(self):
        return self._exec(f"(mode {self.frame_id})")

    def hist(self, breaks="sturges") -> "H2OFrame":
        b = (f"'{breaks}'" if isinstance(breaks, str) else
             "[" + " ".join(map(str, breaks)) + "]"
             if isinstance(breaks, (list, tuple)) else str(breaks))
        return self._exec(f"(hist {self.frame_id} {b})")

    def distance(self, y: "H2OFrame", measure="l2") -> "H2OFrame":
        return self._exec(f"(distance {self.frame_id} {y.frame_id} "
                          f"'{measure}')")

    def drop_duplicates(self, columns, keep="first") -> "H2OFrame":
        cols = " ".join(f"'{c}'" if isinstance(c, str) else str(c)
                        for c in columns)
        return self._exec(f"(dropdup {self.frame_id} [{cols}] '{keep}')")

    def mad(self, combine_method="interpolate", constant=1.4826):
        return self._exec(f"(h2o.mad {self.frame_id} '{combine_method}' "
                          f"{constant})")

    def nlevels(self):
        return self._exec(f"(nlevels {self.frame_id})")

    def anyfactor(self):
        return bool(self._exec(f"(any.factor {self.frame_id})"))

    def isna(self) -> "H2OFrame":
        out = self._fr(f"(is.na {self._ref()})")
        # h2o-py's ExprNode knows the result's dims/names/types locally —
        # reading them must not force evaluation (pyunit_isna pins this)
        out._meta = {"rows": self.nrow, "cols": self.ncol,
                     "names": [f"isNA({n})" for n in self.columns],
                     "types": {f"isNA({n})": "int" for n in self.columns}}
        return out

    def columns_by_type(self, coltype="numeric"):
        return self._exec(f"(columnsByType {self.frame_id} '{coltype}')")

    def set_level(self, level: str) -> "H2OFrame":
        return self._exec(f"(setLevel {self.frame_id} '{level}')")

    def append_levels(self, levels) -> "H2OFrame":
        lv = " ".join(f"'{l}'" for l in levels)
        return self._exec(f"(appendLevels {self.frame_id} [{lv}])")

    def relevel_by_frequency(self, top_n=-1) -> "H2OFrame":
        return self._exec(f"(relevel.by.freq {self.frame_id} {top_n})")

    def as_date(self, format: str) -> "H2OFrame":
        return self._exec(f"(as.Date {self.frame_id} '{format}')")

    def week(self) -> "H2OFrame":
        return self._exec(f"(week {self.frame_id})")

    def isax(self, num_words, max_cardinality, optimize_card=False):
        oc = "1" if optimize_card else "0"
        return self._exec(f"(isax {self.frame_id} {num_words} "
                          f"{max_cardinality} {oc})")

    def apply(self, fun: str, axis=0) -> "H2OFrame":
        """Apply a reducer over rows (axis=1) or columns (axis=0). `fun` is
        a reducer name ('mean', 'sum', …) or a raw rapids lambda string."""
        lam = fun if fun.lstrip().startswith("{") else f"{{x . ({fun} x)}}"
        margin = 1 if axis == 1 else 2
        return self._exec(f"(apply {self.frame_id} {margin} {lam})")

    def entropy(self) -> "H2OFrame":
        return self._exec(f"(entropy {self.frame_id})")

    @property
    def nrows(self) -> int:
        return self.nrow

    @property
    def ncols(self) -> int:
        return self.ncol

    def merge(self, other: "H2OFrame", all_x: bool = False,
              all_y: bool = False, by_x=None, by_y=None,
              method: str = "auto") -> "H2OFrame":
        """`H2OFrame.merge` — `(merge x y all_x all_y [bx] [by])`
        (AstMerge); by_x/by_y are column names or indices."""
        ax = "TRUE" if all_x else "FALSE"
        ay = "TRUE" if all_y else "FALSE"

        def idxs(fr, by):
            if by is None:
                return "[]"
            cols = by if isinstance(by, list) else [by]
            return "[" + " ".join(
                str(fr.columns.index(c) if isinstance(c, str) else int(c))
                for c in cols) + "]"

        return self._fr(f"(merge {self._ref()} {other._ref()} {ax} {ay} "
                        f"{idxs(self, by_x)} {idxs(other, by_y)} "
                        f"'{method}')")

    def sort(self, by, ascending=None) -> "H2OFrame":
        cols = by if isinstance(by, list) else [by]
        inner = " ".join(f"'{c}'" if isinstance(c, str) else str(c)
                         for c in cols)
        if ascending is None:
            return self._fr(f"(sort {self._ref()} [{inner}])")
        asc = ascending if isinstance(ascending, list) else [ascending]
        flags = " ".join("1" if a else "0" for a in asc)
        return self._fr(f"(sort {self._ref()} [{inner}] [{flags}])")

    def strdistance(self, y: "H2OFrame", measure: str = "lv",
                    compare_empty: bool = True) -> "H2OFrame":
        """`H2OFrame.strdistance` — `(strDistance x y measure ce)`."""
        ce = "true" if compare_empty else "false"
        return self._fr(f"(strDistance {self._ref()} {y._ref()} "
                        f"'{measure}' {ce})")

    def strsplit(self, pattern: str) -> "H2OFrame":
        return self._exec(f"(strsplit {self.frame_id} '{pattern}')")

    def countmatches(self, pattern) -> "H2OFrame":
        pats = pattern if isinstance(pattern, list) else [pattern]
        items = " ".join(f"'{p}'" for p in pats)
        return self._exec(f"(countmatches {self.frame_id} [{items}])")

    def tokenize(self, split=" ") -> "H2OFrame":
        return self._exec(f"(tokenize {self.frame_id} '{split}')")

    def runif(self, seed=-1) -> "H2OFrame":
        return self._exec(f"(h2o.runif {self.frame_id} {seed})")

    def split_frame(self, ratios=(0.75,), seed=-1) -> list["H2OFrame"]:
        """Random frame split (h2o-py `split_frame`): cumulative ratio
        buckets over one uniform column."""
        r = self.runif(seed=seed)
        out = []
        lo = 0.0
        bounds = list(ratios) + [None]
        for frac in bounds:
            hi = lo + frac if frac is not None else 1.0
            mask = (r >= lo) & (r < hi) if frac is not None else (r >= lo)
            out.append(self[mask])
            lo = hi
        return out

    def drop(self, col) -> "H2OFrame":
        """Remove column(s) by name/index (h2o-py `drop`)."""
        cols = [col] if isinstance(col, (str, int)) else list(col)
        have = self.columns
        names = [c if isinstance(c, str) else have[c] for c in cols]
        missing = [n for n in names if n not in have]
        if missing:
            raise ValueError(f"drop: column(s) {missing} not in frame")
        keep = [n for n in have if n not in names]
        return self[keep]

    def ascharacter(self) -> "H2OFrame":
        return self._exec(f"(ascharacter {self.frame_id})")

    def group_by(self, by) -> "H2OGroupBy":
        """h2o-py GroupBy builder: chain aggregates, then `.get_frame()`."""
        return H2OGroupBy(self, [by] if isinstance(by, str) else list(by))

    def set_names(self, names: list[str]) -> "H2OFrame":
        """Rename columns in place (h2o-py semantics: the handle keeps
        pointing at the renamed frame)."""
        inner = " ".join(f"'{n}'" for n in names)
        idx = " ".join(str(i) for i in range(len(names)))
        out = self._exec(f"(colnames= {self.frame_id} [{idx}] [{inner}])")
        self.frame_id = out.frame_id
        self._schema = None
        return self

    # -- materialization -----------------------------------------------------
    def as_data_frame(self, use_pandas: bool = True, header: bool = True,
                      rows: int | None = None):
        """pandas DataFrame, or with ``use_pandas=False`` the h2o-py wire
        shape: a list of ROWS of strings, ``header`` controlling whether the
        first row is the column names (`h2o-py/h2o/frame.py as_data_frame`)."""
        j = connection().request(
            "GET", f"/3/Frames/{urllib.parse.quote(self.frame_id)}",
            params={"row_count": rows if rows is not None else self.nrow}
        )["frames"][0]
        cols = {}
        for c in j["columns"]:
            if c.get("string_data") is not None:
                cols[c["label"]] = c["string_data"]
            elif c["domain"]:
                dom = c["domain"]
                cols[c["label"]] = [None if v is None else dom[int(v)]
                                    for v in (c["data"] or [])]
            else:
                cols[c["label"]] = c["data"] or []
        if use_pandas:
            import pandas as pd

            return pd.DataFrame(cols)

        def cell(v):
            if v is None or (isinstance(v, float) and v != v):
                return ""
            if isinstance(v, float) and v == int(v):
                return str(int(v))
            return str(v)

        names = list(cols)
        out = [[cell(v) for v in row] for row in zip(*cols.values())]
        return ([names] + out) if header else out

    def describe(self, chunk_summary=False):
        """Print the per-column summary table (`H2OFrame.describe`)."""
        summ = self._summary()
        print(f"Rows:{summ['rows']}  Cols:{summ['num_columns']}")
        def first(v):  # schema emits mins/maxs as 1-element lists
            return v[0] if isinstance(v, list) and v else (
                None if isinstance(v, list) else v)

        rows = []
        for c in summ["columns"]:
            rows.append([c["label"], c["type"], first(c.get("mins")),
                         first(c.get("maxs")), c.get("mean"), c.get("sigma"),
                         c.get("missing_count")])
        try:
            import pandas as pd

            df = pd.DataFrame(rows, columns=["column", "type", "min", "max",
                                             "mean", "sigma", "missing"])
            print(df.to_string(index=False))
        except ImportError:
            for r in rows:
                print(r)
        return self

    def head(self, rows=10):
        # only the first `rows` rows cross the wire (server-side preview cap)
        return self.as_data_frame(rows=rows)

    def __repr__(self):
        return f"H2OFrame({self.frame_id}, {self.nrow}x{self.ncol})"


# ---------------------------------------------------------------------------
# estimators (`h2o-py/h2o/estimators/*` — thin generated layer)
# ---------------------------------------------------------------------------
class H2OGroupBy:
    """`h2o-py/h2o/group_by.py` surface over the rapids GB prim."""

    def __init__(self, fr: H2OFrame, by: list[str]):
        self._fr = fr
        self._by = by
        self._aggs: list[tuple[str, str, str]] = []

    def _add(self, agg, col, na):
        cols = [col] if isinstance(col, str) else list(col)
        for c in cols:
            self._aggs.append((agg, c, na))
        return self

    def sum(self, col, na="all"):
        return self._add("sum", col, na)

    def mean(self, col, na="all"):
        return self._add("mean", col, na)

    def min(self, col, na="all"):
        return self._add("min", col, na)

    def max(self, col, na="all"):
        return self._add("max", col, na)

    def sd(self, col, na="all"):
        return self._add("sd", col, na)

    def var(self, col, na="all"):
        return self._add("var", col, na)

    def count(self, na="all"):
        self._aggs.append(("nrow", self._by[0], na))
        return self

    def get_frame(self) -> H2OFrame:
        by = " ".join(f"'{c}'" for c in self._by)
        aggs = " ".join(f"'{a}' '{c}' '{na}'" for a, c, na in self._aggs)
        return self._fr._exec(f"(GB {self._fr.frame_id} [{by}] {aggs})")


def deep_copy(frame: H2OFrame, destination_frame: str) -> H2OFrame:
    """`h2o.deep_copy`: server-side materialized copy under a new key."""
    idx = " ".join(str(i) for i in range(frame.ncol))
    rapids(f"(tmp= {destination_frame} (cols {frame._ref()} [{idx}]))")
    return H2OFrame._by_id(destination_frame)


def assign(frame: H2OFrame, destination_frame: str) -> H2OFrame:
    """`h2o.assign`: bind the frame's data under a new key and rebind this
    handle to it. The old key stays alive so other handles and pending lazy
    expressions that captured it keep working (the client's snapshot
    contract — see __setitem__)."""
    idx = " ".join(str(i) for i in range(frame.ncol))
    rapids(f"(tmp= {destination_frame} (cols {frame._ref()} [{idx}]))")
    frame.frame_id = destination_frame
    frame.refresh()
    return frame


def list_timezones() -> "H2OFrame":
    return H2OFrame._lazy("(listTimeZones)")


def get_timezone() -> str:
    fr = H2OFrame._lazy("(getTimeZone)")
    return fr.as_data_frame().iloc[0, 0]


def set_timezone(tz: str) -> None:
    rapids(f"(setTimeZone '{tz}')")


def interaction(frame: H2OFrame, factors, pairwise=False, max_factors=100,
                min_occurrence=1, destination_frame=None) -> H2OFrame:
    """`h2o.interaction`: combined categorical columns from factor tuples."""
    items = " ".join(f"'{f}'" if isinstance(f, str) else str(f)
                     for f in factors)
    pw = "true" if pairwise else "false"
    return frame._exec(f"(interaction {frame.frame_id} [{items}] {pw} "
                       f"{max_factors} {min_occurrence})")


def export_file(frame: H2OFrame, path: str, force: bool = False) -> None:
    """`h2o.export_file`: write a frame to CSV/parquet server-side."""
    connection().request(
        "POST", f"/3/Frames/{urllib.parse.quote(frame.frame_id)}/export",
        params={"path": path, "force": "true" if force else "false"})


class _ClientMetrics(dict):
    """Metrics payload with h2o-py `ModelMetrics` getter methods, still a
    plain dict of the ModelMetricsBaseV3 wire fields."""

    def auc(self): return self.get("AUC")                    # noqa: E704
    def aucpr(self): return self.get("pr_auc")               # noqa: E704
    def mse(self): return self.get("MSE")                    # noqa: E704
    def rmse(self): return self.get("RMSE")                  # noqa: E704
    def mae(self): return self.get("mae")                    # noqa: E704
    def logloss(self): return self.get("logloss")            # noqa: E704
    def gini(self): return self.get("Gini")                  # noqa: E704
    def r2(self): return self.get("r2")                      # noqa: E704
    def null_deviance(self): return self.get("null_deviance")        # noqa: E704,E501
    def residual_deviance(self): return self.get("residual_deviance")  # noqa: E704,E501
    def mean_residual_deviance(self):                        # noqa: E704
        return self.get("mean_residual_deviance")

    def show(self):
        print(self)


class H2OModelClient:
    """Client handle on a trained server-side model."""

    def __init__(self, model_id: str, schema: dict):
        self.model_id = schema["model_id"]["name"] if schema else model_id
        self._schema = schema

    @property
    def key(self):
        return self.model_id

    def predict(self, frame: H2OFrame, **params) -> H2OFrame:
        j = connection().request(
            "POST",
            f"/3/Predictions/models/{urllib.parse.quote(self.model_id)}"
            f"/frames/{urllib.parse.quote(frame.frame_id)}", params=params)
        return H2OFrame._by_id(j["predictions_frame"]["name"])

    def predict_contributions(self, frame: H2OFrame) -> H2OFrame:
        return self.predict(frame, predict_contributions="true")

    def predict_leaf_node_assignment(self, frame: H2OFrame,
                                     type="Path") -> H2OFrame:
        return self.predict(frame, leaf_node_assignment="true",
                            leaf_node_assignment_type=type)

    def staged_predict_proba(self, frame: H2OFrame) -> H2OFrame:
        return self.predict(frame, predict_staged_proba="true")

    # -- GLM-family coefficient surface (`h2o-py` model.coef()) --------------
    def _coef_table(self) -> dict:
        tbl = ((self._schema or {}).get("output") or {}).get(
            "coefficients_table")
        if tbl is None:
            raise ValueError(f"model {self.model_id} has no coefficients")
        return tbl

    def coef(self) -> dict:
        t = self._coef_table()
        return dict(zip(t["names"], t["coefficients"]))

    def coef_norm(self) -> dict:
        t = self._coef_table()
        return dict(zip(t["names"], t["standardized_coefficients"]))

    def _coef_stat(self, col) -> dict:
        t = self._coef_table()
        if col not in t:
            raise ValueError(f"train with compute_p_values=True for {col}")
        return dict(zip(t["names"], t[col]))

    def std_errs(self) -> dict:
        return self._coef_stat("std_errs")

    def z_values(self) -> dict:
        return self._coef_stat("z_values")

    def p_values(self) -> dict:
        return self._coef_stat("p_values")

    def dispersion(self):
        return ((self._schema or {}).get("output") or {}).get("dispersion")

    def _metrics(self, kind="training_metrics") -> dict:
        return (self._schema or {}).get("output", {}).get(kind) or {}

    def model_performance(self, test_data: "H2OFrame | None" = None,
                          train=False, valid=False, xval=False) -> dict:
        """Recompute metrics on a frame — `GET /3/ModelMetrics/models/{m}/
        frames/{f}` (ModelMetricsHandler score-and-fetch); without a frame,
        the stored training/validation/xval metrics (h2o-py signature)."""
        if test_data is None:
            kind = ("cross_validation_metrics" if xval else
                    "validation_metrics" if valid else "training_metrics")
            return _ClientMetrics(self._metrics(kind))
        j = connection().request(
            "GET",
            f"/3/ModelMetrics/models/{urllib.parse.quote(self.model_id)}"
            f"/frames/{urllib.parse.quote(test_data.frame_id)}")
        return _ClientMetrics(j["model_metrics"][0])

    @property
    def _id(self) -> str:
        return self.model_id

    @property
    def _model_json(self) -> dict:
        return self._schema

    def show(self):
        print(self)

    def summary(self):
        return ((self._schema or {}).get("output") or {}).get("model_summary")

    @property
    def parms(self) -> dict:
        """h2o-py `ModelBase.parms`: {name: {actual_value, default_value}}
        off the model schema's parameters list."""
        return {p["name"]: p
                for p in (self._schema or {}).get("parameters", [])}

    @property
    def actual_params(self) -> dict:
        return {k: v.get("actual_value") for k, v in self.parms.items()}

    def cross_validation_models(self) -> list:
        """The N fold models (`ModelBase.cross_validation_models`)."""
        refs = ((self._schema or {}).get("output") or {}).get(
            "cross_validation_models") or []
        return [get_model(r["name"]) for r in refs]

    def cross_validation_fold_assignment(self) -> "H2OFrame":
        ref = ((self._schema or {}).get("output") or {}).get(
            "cross_validation_fold_assignment_frame_id")
        if not ref:
            raise ValueError("no fold assignment kept (train with "
                             "keep_cross_validation_fold_assignment=True)")
        return get_frame(ref["name"])

    def cross_validation_holdout_predictions(self) -> "H2OFrame":
        ref = ((self._schema or {}).get("output") or {}).get(
            "cross_validation_holdout_predictions_frame_id")
        if not ref:
            raise ValueError("no holdout predictions kept (train with "
                             "keep_cross_validation_predictions=True)")
        return get_frame(ref["name"])

    def cross_validation_predictions(self) -> list:
        refs = ((self._schema or {}).get("output") or {}).get(
            "cross_validation_predictions") or []
        return [get_frame(r["name"]) for r in refs]

    def to_frame(self) -> "H2OFrame":
        """Word2vec embeddings as a [Word, V1..VD] frame
        (`H2OWordEmbeddingModel.to_frame` → rapids word2vec.to.frame)."""
        j = rapids(f"(word2vec.to.frame {self.model_id})")
        return H2OFrame._by_id(j["key"]["name"])

    def weights(self, matrix_id: int = 0) -> "H2OFrame":
        """Layer weight frame (units_out × units_in) —
        `ModelBase.weights`, served when export_weights_and_biases=True."""
        refs = ((self._schema or {}).get("output") or {}).get("weights")
        if not refs:
            raise ValueError("no exported weights (train with "
                             "export_weights_and_biases=True)")
        return get_frame(refs[matrix_id]["name"])

    def biases(self, vector_id: int = 0) -> "H2OFrame":
        refs = ((self._schema or {}).get("output") or {}).get("biases")
        if not refs:
            raise ValueError("no exported biases (train with "
                             "export_weights_and_biases=True)")
        return get_frame(refs[vector_id]["name"])

    def num_iterations(self):
        """Clustering/GLM iteration count (`ModelBase.num_iterations`)."""
        return ((self._schema or {}).get("output") or {}).get(
            "num_iterations")

    def centers(self):
        """Cluster means in row-major form (`H2OClusteringModel.centers`)."""
        c = ((self._schema or {}).get("output") or {}).get("centers")
        if not c:
            return None
        cols = c["data"]
        return [[col[i] for col in cols] for i in range(len(cols[0]))]

    def auc(self, train=True, valid=False, xval=False):
        kind = ("cross_validation_metrics" if xval else
                "validation_metrics" if valid else "training_metrics")
        return self._metrics(kind).get("AUC")

    def rmse(self, train=True, valid=False, xval=False):
        kind = ("cross_validation_metrics" if xval else
                "validation_metrics" if valid else "training_metrics")
        return self._metrics(kind).get("RMSE")

    def logloss(self, **kw):
        return self._metrics().get("logloss")

    def aucpr(self, **kw):
        return self._metrics().get("pr_auc")

    def kolmogorov_smirnov(self, **kw):
        return self._metrics().get("ks")

    def gini(self, **kw):
        return self._metrics().get("Gini")

    def confusion_matrix(self, **kw):
        cm = self._metrics().get("cm")
        return cm and cm.get("table")

    def gains_lift(self, **kw):
        return self._metrics().get("gains_lift_table")

    def F1(self, thresholds=None, **kw):
        ts = self._metrics().get("thresholds_and_metric_scores") or {}
        return list(zip(ts.get("thresholds", []), ts.get("f1", [])))

    def find_threshold_by_max_metric(self, metric: str, **kw):
        t = self._metrics().get("max_criteria_and_metric_scores")
        if not t:
            return None
        names = t["data"][0]
        return t["data"][1][names.index(f"max {metric}")]

    def varimp(self, use_pandas=False):
        vi = (self._schema or {}).get("output", {}).get("variable_importances")
        if vi and use_pandas:
            import pandas as pd

            return pd.DataFrame(vi)
        return vi

    def partial_plot(self, frame: H2OFrame, cols=None, nbins: int = 20,
                     plot: bool = False, row_index: int = -1, targets=None):
        """Partial dependence tables (h2o-py `partial_plot` data surface);
        ``row_index >= 0`` returns the row's ICE curve instead."""
        params = {"model_id": self.model_id, "frame_id": frame.frame_id,
                  "nbins": nbins, "row_index": row_index}
        if cols:
            params["cols"] = ",".join(cols)
        if targets:
            params["targets"] = ",".join(
                [targets] if isinstance(targets, str) else list(targets))
        j = connection().request("POST", "/3/PartialDependence", params=params)
        return j["partial_dependence_data"]

    def fairness_metrics(self, frame: "H2OFrame", protected_columns,
                         reference, favorable_class) -> dict:
        """Intersectional fairness metrics (`h2o-py fairness_metrics` /
        the `fairnessMetrics` rapids prim): dict of H2OFrames keyed
        'overview' + per-group threshold tables."""
        def _sl(xs):
            return "[" + " ".join(f'"{x}"' for x in xs) + "]"

        expr = (f'(fairnessMetrics "{self.model_id}" {frame.frame_id} '
                f"{_sl(protected_columns)} "
                f"{_sl(reference) if reference else '[]'} "
                f'"{favorable_class}")')
        j = rapids(expr)
        return {name: H2OFrame._by_id(f["key"]["name"])
                for name, f in zip(j["map_keys"]["string"], j["frames"])}

    def scoring_history(self, use_pandas: bool = True):
        """The model's scoring-history table (`model.scoring_history()`)."""
        sh = ((self._schema or {}).get("output") or {}).get("scoring_history")
        if sh and use_pandas:
            import pandas as pd

            return pd.DataFrame(sh)
        return sh

    def permutation_importance(self, frame: H2OFrame, metric: str = "AUTO",
                               n_repeats: int = 1, seed: int = -1):
        j = connection().request(
            "POST", "/3/PermutationVarImp",
            params={"model_id": self.model_id, "frame_id": frame.frame_id,
                    "metric": metric, "n_repeats": n_repeats, "seed": seed})
        return j["permutation_varimp"]

    def download_mojo(self, path: str = ".") -> str:
        j = connection().request(
            "GET", f"/3/Models/{urllib.parse.quote(self.model_id)}/mojo",
            params={"dir": path})
        return j["dir"]

    def __repr__(self):
        return f"H2OModelClient({self.model_id})"


def _train_body(params: dict, x, y, training_frame, validation_frame,
                kw: dict) -> dict:
    """Assemble the training POST body shared by estimators and grid search:
    frame-valued params ride the wire as their keys (the server resolves them
    back to Frames), and ``x`` maps to ignored_columns h2o-py-style (names or
    integer indices)."""
    body = dict(params)
    body.update(kw)
    body = {k: (v.frame_id if isinstance(v, H2OFrame) else v)
            for k, v in body.items()}
    if training_frame is not None:
        body["training_frame"] = training_frame.frame_id
    if validation_frame is not None:
        body["validation_frame"] = validation_frame.frame_id
    frame_for_names = training_frame if training_frame is not None \
        else validation_frame
    if y is not None:
        if isinstance(y, int):  # h2o-py accepts a column index for y
            y = frame_for_names.columns[y]
        body["response_column"] = y
    if x is not None:
        all_cols = frame_for_names.columns
        keep = {all_cols[c] if isinstance(c, int) else c for c in x}
        body["ignored_columns"] = [c for c in all_cols
                                   if c not in keep and c != y]
    return body


#: algo -> parameter-name set for client-side validation (None = unknown,
#: keep trying). Module-level by ALGO, never a class attribute: a class-
#: level cache would leak through inheritance and poison every subclass.
_VALID_PARAMS: dict = {}


def _valid_param_names(algo: str) -> set | None:
    """Per-algo parameter names — the client-side validation surface
    h2o-py's generated estimators carry (`estimator_base.py` rejects
    unknown kwargs locally). Connected clients read the server's
    `/3/ModelBuilders/{algo}` metadata (no local modeling-stack import);
    in-process sessions fall back to the registry. None when neither is
    reachable — validation is skipped, the server still rejects."""
    if algo in _VALID_PARAMS:
        return _VALID_PARAMS[algo]
    names = None
    if _conn is not None:
        try:
            meta = _conn.request("GET", f"/3/ModelBuilders/{algo}")
            names = {p["name"] for p in meta.get("parameters", [])}
        except Exception:
            names = None
    if names is None:
        try:
            from ..models import registry

            entry = registry.lookup(algo)
            if entry is not None:
                import dataclasses

                names = {f.name for f in dataclasses.fields(entry[1])}
        except Exception:
            names = None
    if names:
        _VALID_PARAMS[algo] = names
    return names


class H2OEstimator:
    """Base estimator: collects kwargs, posts to /3/ModelBuilders/{algo},
    polls the job, exposes the trained model. Unknown keyword arguments
    fail at CONSTRUCTION with the valid-names list, like h2o-py's
    generated per-algo estimators."""

    algo = None

    def __init__(self, **params):
        cls = type(self)
        valid = _valid_param_names(self.algo) if self.algo else None
        if valid:
            unknown = sorted(k for k in params if k not in valid)
            if unknown:
                import difflib

                hints = {
                    k: difflib.get_close_matches(k, valid, n=1)
                    for k in unknown}
                hint_txt = "; ".join(
                    f"'{k}'" + (f" (did you mean '{m[0]}'?)" if m else "")
                    for k, m in hints.items())
                raise TypeError(
                    f"{cls.__name__} got unknown parameter(s) {hint_txt}. "
                    f"Valid parameters: {sorted(valid)}")
        self._params = params
        self._model: H2OModelClient | None = None

    def train(self, x=None, y=None, training_frame: H2OFrame | None = None,
              validation_frame: H2OFrame | None = None, **kw):
        body = _train_body(dict(self._params), x, y, training_frame,
                           validation_frame, kw)
        job = connection().request("POST", f"/3/ModelBuilders/{self.algo}",
                                   data=body)
        done = _poll_job(job)
        self._model = get_model(done["dest"]["name"])
        return self

    # delegate model accessors
    def __getattr__(self, name):
        if self._model is None:
            raise AttributeError(f"train() first (no model for {name})")
        return getattr(self._model, name)

    @property
    def model_id(self):
        return self._model.model_id if self._model else None


def _estimator(algo: str, clsname: str) -> type:
    return type(clsname, (H2OEstimator,), {"algo": algo})


# the h2o-py estimator names (`h2o-py/h2o/estimators/__init__.py`)
H2OGradientBoostingEstimator = _estimator("gbm", "H2OGradientBoostingEstimator")
H2ORandomForestEstimator = _estimator("drf", "H2ORandomForestEstimator")
H2OXGBoostEstimator = _estimator("xgboost", "H2OXGBoostEstimator")
H2OGeneralizedLinearEstimator = _estimator("glm", "H2OGeneralizedLinearEstimator")
H2OGeneralizedAdditiveEstimator = _estimator("gam", "H2OGeneralizedAdditiveEstimator")
H2ODeepLearningEstimator = _estimator("deeplearning", "H2ODeepLearningEstimator")
H2OKMeansEstimator = _estimator("kmeans", "H2OKMeansEstimator")
H2OPrincipalComponentAnalysisEstimator = _estimator("pca", "H2OPrincipalComponentAnalysisEstimator")
H2OSingularValueDecompositionEstimator = _estimator("svd", "H2OSingularValueDecompositionEstimator")
H2OGeneralizedLowRankEstimator = _estimator("glrm", "H2OGeneralizedLowRankEstimator")
H2ONaiveBayesEstimator = _estimator("naivebayes", "H2ONaiveBayesEstimator")
H2OIsolationForestEstimator = _estimator("isolationforest", "H2OIsolationForestEstimator")
H2OExtendedIsolationForestEstimator = _estimator("extendedisolationforest", "H2OExtendedIsolationForestEstimator")
H2OCoxProportionalHazardsEstimator = _estimator("coxph", "H2OCoxProportionalHazardsEstimator")
H2OIsotonicRegressionEstimator = _estimator("isotonicregression", "H2OIsotonicRegressionEstimator")
H2OStackedEnsembleEstimator = _estimator("stackedensemble", "H2OStackedEnsembleEstimator")
H2ORuleFitEstimator = _estimator("rulefit", "H2ORuleFitEstimator")
H2OSupportVectorMachineEstimator = _estimator("psvm", "H2OSupportVectorMachineEstimator")
H2OWord2vecEstimator = _estimator("word2vec", "H2OWord2vecEstimator")
H2OUpliftRandomForestEstimator = _estimator("upliftdrf", "H2OUpliftRandomForestEstimator")
H2ODecisionTreeEstimator = _estimator("decisiontree", "H2ODecisionTreeEstimator")
H2OAdaBoostEstimator = _estimator("adaboost", "H2OAdaBoostEstimator")
H2OANOVAGLMEstimator = _estimator("anovaglm", "H2OANOVAGLMEstimator")
H2OModelSelectionEstimator = _estimator("modelselection", "H2OModelSelectionEstimator")
H2OTargetEncoderEstimator = _estimator("targetencoder", "H2OTargetEncoderEstimator")
H2OAggregatorEstimator = _estimator("aggregator", "H2OAggregatorEstimator")
H2OInfogram = _estimator("infogram", "H2OInfogram")
H2OGenericEstimator = _estimator("generic", "H2OGenericEstimator")


# ---------------------------------------------------------------------------
# Grid search over REST (`h2o-py/h2o/grid/grid_search.py` surface over
# `POST /99/Grid/{algo}` + `GET /99/Grids/{id}`)
# ---------------------------------------------------------------------------
class H2OGridSearch:
    """h2o-py-compatible grid search: wraps an estimator (instance or class),
    posts the hyper space, polls the job, exposes ranked models."""

    def __init__(self, model, hyper_params: dict, grid_id: str | None = None,
                 search_criteria: dict | None = None, parallelism: int = 1):
        self.model = model() if isinstance(model, type) else model
        #: estimator class the contained models present as — h2o-py's grid
        #: yields estimator instances (`for m in grid: isinstance(m, Est)`)
        self._est_cls = model if isinstance(model, type) else type(model)
        self.hyper_params = hyper_params
        self.grid_id = grid_id
        self.search_criteria = search_criteria or {}
        self.parallelism = parallelism
        self._grid_json: dict | None = None

    def train(self, x=None, y=None, training_frame: "H2OFrame | None" = None,
              validation_frame: "H2OFrame | None" = None, **kw):
        import json as _json

        body = _train_body(dict(getattr(self.model, "_params", {})),
                           x, y, training_frame, validation_frame, kw)
        body["hyper_parameters"] = _json.dumps(self.hyper_params)
        if self.search_criteria:
            body["search_criteria"] = _json.dumps(self.search_criteria)
        if self.grid_id:
            body["grid_id"] = self.grid_id
        if self.parallelism != 1:
            body["parallelism"] = self.parallelism
        job = connection().request("POST", f"/99/Grid/{self.model.algo}",
                                   data=body)
        done = _poll_job(job)
        self.grid_id = done["dest"]["name"]
        self._fetch()
        return self

    def _fetch(self, sort_by: str | None = None, decreasing: bool | None = None):
        params = {}
        if sort_by:
            params["sort_by"] = sort_by
        if decreasing is not None:
            params["decreasing"] = str(bool(decreasing)).lower()
        self._grid_json = connection().request(
            "GET", f"/99/Grids/{urllib.parse.quote(self.grid_id)}",
            params=params)
        return self._grid_json

    @property
    def model_ids(self) -> list:
        return [k["name"] for k in self._grid_json["model_ids"]]

    @property
    def models(self) -> list:
        out = []
        for mid in self.model_ids:
            est = self._est_cls()
            est._model = get_model(mid)
            out.append(est)
        return out

    def __iter__(self):
        return iter(self.models)

    def __len__(self) -> int:
        return len(self.model_ids)

    def __getitem__(self, i):
        # one REST fetch for one model, not len(grid) of them
        est = self._est_cls()
        est._model = get_model(self.model_ids[i])
        return est

    def get_grid(self, sort_by: str | None = None, decreasing: bool = False):
        self._fetch(sort_by, decreasing)
        return self

    def summary_table(self):
        return self._grid_json.get("summary_table")

    @property
    def failure_details(self) -> list:
        return self._grid_json.get("failure_details", [])


def save_grid(grid: "H2OGridSearch", grid_directory: str) -> str:
    """`h2o.save_grid` — `POST /3/Grid.bin/{grid_id}/export`."""
    connection().request(
        "POST", f"/3/Grid.bin/{urllib.parse.quote(grid.grid_id)}/export",
        data={"grid_directory": grid_directory})
    return grid_directory


def load_grid(grid_directory: str) -> "H2OGridSearch":
    """`h2o.load_grid` — `POST /3/Grid.bin/import`. The rebuilt handle keeps
    the original algo (from the server-side manifest), so it can continue
    training with a fresh hyper space."""
    j = connection().request("POST", "/3/Grid.bin/import",
                             data={"grid_path": grid_directory})
    gid = j["name"]
    detail = connection().request(
        "GET", f"/99/Grids/{urllib.parse.quote(gid)}")
    est = H2OEstimator()
    est.algo = detail.get("algo")
    gs = H2OGridSearch(model=est, hyper_params={}, grid_id=gid)
    gs._grid_json = detail
    return gs


# ---------------------------------------------------------------------------
# AutoML over REST (`h2o-py/h2o/automl/_estimator.py` surface over
# `POST /99/AutoMLBuilder` + `GET /99/Leaderboards/{project}`)
# ---------------------------------------------------------------------------
class H2OAutoML:
    def __init__(self, max_models: int = 0, max_runtime_secs: float = 0.0,
                 max_runtime_secs_per_model: float = 0.0, nfolds: int = 5,
                 seed: int | None = None, project_name: str | None = None,
                 include_algos: list | None = None,
                 exclude_algos: list | None = None,
                 sort_metric: str | None = None,
                 stopping_rounds: int = 3, stopping_tolerance: float = 1e-3,
                 stopping_metric: str = "AUTO"):
        self.build_control = {
            "project_name": project_name,
            "nfolds": nfolds,
            "stopping_criteria": {
                "max_models": max_models,
                "max_runtime_secs": max_runtime_secs,
                "max_runtime_secs_per_model": max_runtime_secs_per_model,
                "seed": -1 if seed is None else seed,
                "stopping_rounds": stopping_rounds,
                "stopping_tolerance": stopping_tolerance,
                "stopping_metric": stopping_metric,
            },
        }
        self.build_models = {"include_algos": include_algos,
                             "exclude_algos": exclude_algos}
        self.sort_metric = sort_metric
        self.project_name = project_name
        self._leaderboard_json: dict | None = None

    def train(self, x=None, y=None,
              training_frame: "H2OFrame | None" = None, **kw):
        if kw:
            raise ValueError(
                f"unsupported H2OAutoML.train() arguments: {sorted(kw)} — "
                "supported: x, y, training_frame")
        spec = {"training_frame": training_frame.frame_id,
                "response_column": y}
        if self.sort_metric:
            spec["sort_metric"] = self.sort_metric
        if x is not None:
            all_cols = training_frame.columns
            keep = {all_cols[c] if isinstance(c, int) else c for c in x}
            spec["ignored_columns"] = [c for c in all_cols
                                       if c not in keep and c != y]
        resp = connection().request("POST", "/99/AutoMLBuilder", data={
            "input_spec": spec,
            "build_control": self.build_control,
            "build_models": self.build_models,
        })
        self.project_name = resp["build_control"]["project_name"]
        _poll_job(resp)
        self._fetch()
        return self

    def _fetch(self):
        self._leaderboard_json = connection().request(
            "GET", f"/99/Leaderboards/{urllib.parse.quote(self.project_name)}")
        return self._leaderboard_json

    @property
    def leaderboard(self):
        return self._leaderboard_json["table"]

    @property
    def leader(self) -> "H2OModelClient":
        models = self._leaderboard_json["models"]
        if not models:
            raise ValueError("no models trained")
        return get_model(models[0]["name"])

    def predict(self, test_data: "H2OFrame"):
        return self.leader.predict(test_data)

    def event_log(self) -> dict:
        j = connection().request(
            "GET", f"/99/AutoML/{urllib.parse.quote(self.project_name)}")
        return j["event_log_table"]
