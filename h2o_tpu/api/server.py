"""REST API server — analog of `water/api/RequestServer.java` (:56,80,157).

Routes follow the reference's versioned URL scheme (`/3/...`, `/99/Rapids`;
128 endpoints registered in `water/api/RegisterV3Api.java` — the subset here
covers the paths the Python client actually drives: cloud status, import/
parse, frames, model builders, models, predictions, jobs, rapids, logs,
timeline, shutdown). Built on the stdlib ThreadingHTTPServer: the control
plane is host-side Python; all bulk compute the handlers trigger runs on the
device mesh (SURVEY.md §2.5 — REST/job control on host CPUs, data plane on
ICI).

Request/response bodies are schema-v3-shaped JSON (see schemas.py). Errors
return the reference's H2OErrorV3 shape with http status codes
(`water/api/RequestServer.java` error handling).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import traceback
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import __version__
from ..backend.jobs import Job
from ..backend.kvstore import STORE, make_key
from ..frame.frame import Frame
from ..frame.vec import Vec
from ..models import registry
from ..rapids.exec import Rapids, Session
from ..workload import WorkloadAdmissionError
from . import schemas

_SESSIONS: dict[str, Session] = {}
#: `/3/SessionProperties` store, keyed (session_key, property) —
#: `water/rapids/Session` attributes in the reference
_SESSION_PROPS: dict[tuple[str, str], str | None] = {}
#: on-frame metric recomputes, keyed (model_id, frame_id) — the reference
#: keeps ModelMetrics objects in the DKV under a model×frame checksum key;
#: the listing/fetch/DELETE ModelMetrics routes operate on this. Guarded by
#: a lock: ThreadingHTTPServer serves requests concurrently.
_METRICS_CACHE: dict[tuple[str, str], object] = {}
_METRICS_LOCK = threading.Lock()


def _metrics_cache_items() -> list:
    """Live cache entries; entries whose model or frame died since caching
    are purged here so a long-lived server can't accumulate garbage."""
    with _METRICS_LOCK:
        items = list(_METRICS_CACHE.items())
        dead = [(m, f) for (m, f), _ in items
                if STORE.get(m) is None or STORE.get(f) is None]
        for k in dead:
            del _METRICS_CACHE[k]
        return [(k, v) for k, v in items if k not in dead]


class H2OServer:
    """Server lifecycle — `water/H2O.main` + Jetty boot analog."""

    def __init__(self, port: int = 54321, name: str = "h2o_tpu",
                 hash_login: dict | str | None = None,
                 ssl_certfile: str | None = None,
                 ssl_keyfile: str | None = None,
                 auth_check=None, negotiate_auth=None):
        """`hash_login`: {user: sha256-hex-or-plain} dict or a realm file of
        `user:sha256hex` lines — the `-hash_login` basic-auth analog
        (`h2o-security`, `water/webserver/H2OHttpViewImpl` auth hook).
        `auth_check`: a callable `(user, password) -> bool` verifying Basic
        credentials against an external directory — pass
        `h2o_tpu.utils.ldap.LdapAuth(...)` for the `-ldap_login` role (the
        pluggable seam JAAS login modules fill in the reference).
        `ssl_certfile`/`ssl_keyfile` terminate TLS on the REST socket — the
        `-jks`/https role of `water/network/SSLSocketChannelFactory`."""
        if sum(x is not None and x != {} for x in
               (auth_check, hash_login, negotiate_auth)) > 1:
            raise ValueError("hash_login, auth_check and negotiate_auth are "
                             "mutually exclusive — one mechanism owns the "
                             "port, like the reference's login-module flags")
        self.auth_check = auth_check
        #: SPNEGO acceptor (`utils/krb.py` SpnegoAuth) — the `-spnego_login`
        #: role; when set, 401s advertise `WWW-Authenticate: Negotiate`
        self.negotiate_auth = negotiate_auth
        self.port = port
        self.name = name
        self.started_at = time.time()
        #: last REST activity stamp — `/3/SteamMetrics` idle_millis source
        self.last_activity = self.started_at
        #: `POST /3/CloudLock` reason (`water/Paxos.lockCloud`); the cloud
        #: here is born locked (single controller) so this is bookkeeping
        self.locked_reason: str | None = "new cloud"
        self.httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.ssl_certfile = ssl_certfile
        self.ssl_keyfile = ssl_keyfile
        if isinstance(hash_login, str):
            creds = {}
            with open(hash_login) as f:
                for line in f:
                    if ":" in line:
                        u, _, h = line.strip().partition(":")
                        creds[u] = h
            hash_login = creds
        self.hash_login = hash_login

    def check_auth(self, header: str | None) -> bool:
        if self.negotiate_auth is not None:
            return self.negotiate_auth.check_header(header) is not None
        if not self.hash_login and self.auth_check is None:
            return True
        if not header or not header.startswith("Basic "):
            return False
        import base64
        import hashlib
        import hmac
        import re

        try:
            user, _, pw = base64.b64decode(
                header[6:]).decode().partition(":")
        except Exception:
            return False
        if self.auth_check is not None:
            return bool(self.auth_check(user, pw))
        expect = self.hash_login.get(user)
        if expect is None:
            return False
        # a 64-hex entry is a stored sha256 — compare digests only, so the
        # realm file never doubles as a usable credential (no pass-the-hash)
        if re.fullmatch(r"[0-9a-f]{64}", expect):
            digest = hashlib.sha256(pw.encode()).hexdigest()
            return hmac.compare_digest(digest, expect)
        return hmac.compare_digest(pw, expect)

    def start(self) -> "H2OServer":
        handler = _make_handler(self)
        # port scan upward like the reference (`NetworkInit` baseport search)
        last_err = None
        for port in range(self.port, self.port + 20):
            try:
                self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
                self.port = port
                break
            except OSError as e:
                last_err = e
        if self.httpd is None:
            raise last_err
        if self.ssl_certfile:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.ssl_certfile, self.ssl_keyfile)
            self.httpd.socket = ctx.wrap_socket(self.httpd.socket,
                                                server_side=True)
        # the acceptor owns no spans and serves EVERY request's context —
        # carrying the boot thread's trace into it would fabricate
        # causality
        self._thread = threading.Thread(  # graftlint: disable=thread-without-trace-context
            target=self.httpd.serve_forever, daemon=True, name="h2o-rest")
        self._thread.start()
        # arm the watchdog supervisor with the server (idempotent; no-op
        # unless H2O_TPU_WATCHDOG_MS > 0) — hung jobs / stalled dispatch /
        # Cleaner thrash / queue stalls become typed events + bundles
        from ..utils import watchdog

        watchdog.ensure_started()
        return self

    def stop(self):
        if self.httpd:
            self.httpd.shutdown()
            self.httpd.server_close()
            self.httpd = None
        if self._thread is not None:
            # serve_forever returns once shutdown() lands; drain the
            # acceptor thread so stop() means STOPPED (graftlint
            # unjoined-thread GL17-server-thread)
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def url(self) -> str:
        scheme = "https" if self.ssl_certfile else "http"
        return f"{scheme}://127.0.0.1:{self.port}"


def _truthy(v) -> bool:
    return str(v).lower() in ("true", "1", "yes")


def _err(status: int, msg: str, **extra) -> tuple[int, dict]:
    return status, {"__meta": {"schema_type": "H2OError"},
                    "error_url": "", "msg": msg, "dev_msg": msg,
                    "http_status": status, "exception_msg": msg, **extra}


def _export_path(p: dict, default_name: str, what: str,
                 force_default: bool = False):
    """Shared dir-export contract (Models.bin / Models.mojo / Models json /
    frame export all follow `ModelsHandler`'s): strip file://, append
    `default_name` when the target is a directory, refuse an existing file
    unless force. Returns (path, None) on success or (None, error reply)."""
    path = p.get("dir", "")
    if not path:
        return None, _err(400, f"{what}: dir is required")
    if path.startswith("file://"):
        path = path[len("file://"):]
    if "://" not in path:  # remote URIs ride the Persist SPI untouched
        if os.path.isdir(path) or path.endswith(os.sep):
            os.makedirs(path, exist_ok=True)
            path = os.path.join(path, default_name)
        force = _truthy(p["force"]) if "force" in p else force_default
        if not force and os.path.exists(path):
            return None, _err(400, f"{what}: {path} exists (use force)")
    return path, None


_FRAME_PARAMS = ("training_frame", "validation_frame", "blending_frame",
                 "calibration_frame", "pre_trained")


def _resolve_params(params_cls, body: dict, extra_names=()) -> dict:
    """Validate body keys against the Parameters dataclass (the reference's
    412 on unknown params) and resolve frame keys to Frames. Shared by the
    ModelBuilders and Grid routes so frame-param handling can't drift."""
    import dataclasses

    valid = {f.name for f in dataclasses.fields(params_cls)}
    unknown = [k for k in body if k not in valid] + \
              [k for k in extra_names if k not in valid]
    if unknown:
        raise ValueError(f"unknown parameter(s) {unknown} for this algorithm")
    return {k: (STORE.get(v) if k in _FRAME_PARAMS else v)
            for k, v in body.items()}


def _jobs_of(algo_cls, params_cls, body: dict) -> tuple[int, dict]:
    kwargs = _resolve_params(params_cls, body)
    builder = algo_cls(params_cls(**kwargs))
    job = builder.train(background=True)
    return 200, {"job": schemas.job_schema(job),
                 "key": schemas.key_schema(job.key)}


def _make_handler(server: H2OServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route to our logger, not stderr
            from ..utils.log import debug

            debug(f"REST {self.address_string()} {fmt % args}")

        # -- plumbing --------------------------------------------------------
        def _reply(self, status: int, payload: dict):
            filename = None
            extra_headers = payload.pop("__headers__", None)
            if "__html__" in payload:
                data = payload["__html__"].encode()
                ctype = "text/html; charset=utf-8"
            elif "__raw__" in payload:
                # non-JSON bodies (DownloadDataset's CSV, Models.fetch.bin)
                data = payload["__raw__"]
                if isinstance(data, str):
                    data = data.encode()
                ctype = payload.get("__ctype__", "text/plain")
                filename = payload.get("__filename__")
            else:
                data = json.dumps(payload).encode()
                ctype = "application/json"
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            for hk, hv in (extra_headers or {}).items():
                # route-supplied headers (Serving's Retry-After); values are
                # server-generated numbers/tokens, never client echoes
                self.send_header(hk, str(hv))
            if filename:
                # frame keys are client-controlled; anything outside a safe
                # charset could malform the header or inject CR/LF
                safe = re.sub(r"[^A-Za-z0-9._-]", "_", str(filename))
                self.send_header("Content-Disposition",
                                 f'attachment; filename="{safe}"')
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            if not getattr(self, "_suppress_body", False):
                self.wfile.write(data)

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(n).decode() if n else ""
            if not raw:
                return {}
            ctype = self.headers.get("Content-Type", "")
            if "json" in ctype:
                return json.loads(raw)
            return {k: v[0] if len(v) == 1 else v
                    for k, v in urllib.parse.parse_qs(raw).items()}

        def _route(self, method: str):
            # handler instances persist across keep-alive requests; a HEAD
            # must not leave the suppress-body flag set for the next request
            head_only = getattr(self, "_head_only", False)
            self._head_only = False
            self._suppress_body = head_only
            if not server.check_auth(self.headers.get("Authorization")):
                # drain any request body first — a keep-alive client will
                # reuse this socket for the re-authed retry, and leftover
                # body bytes would corrupt its next request line
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    self.rfile.read(n)
                self.send_response(401)
                challenge = ("Negotiate" if server.negotiate_auth is not None
                             else 'Basic realm="h2o_tpu"')
                self.send_header("WWW-Authenticate", challenge)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            parsed = urllib.parse.urlparse(self.path)
            parts = [p for p in parsed.path.split("/") if p]
            query = {k: v[0] if len(v) == 1 else v
                     for k, v in urllib.parse.parse_qs(parsed.query).items()}
            # monitoring polls don't count as activity for SteamMetrics'
            # idle clock (`water/api/SteamMetricsHandler` semantics);
            # Health rides the same exclusion — a 1s readiness prober
            # must not look like user traffic or cycle the timeline ring.
            # Timeline too: since the ?since= cursor exists precisely for
            # frequent polling, a timeline poll recording a timeline
            # event would FEED the ring it drains — every pull returns
            # the event of the previous pull and the cursor never
            # catches up (observed driving the cursor loop end-to-end)
            head = parts[1] if len(parts) > 1 else (parts[0] if parts else "")
            is_monitor_poll = head in ("Cloud", "Ping", "Jobs",
                                       "SteamMetrics", "Sample", "Health",
                                       "Timeline", "Workload")
            if not is_monitor_poll:
                server.last_activity = time.time()
            if method == "POST" and parts and \
                    parts[-1] in ("PostFile", "PostFile.bin"):
                # binary body — must not go through the text _body() path
                try:
                    status, payload = _post_file(self, query)
                except Exception as e:  # noqa: BLE001
                    status, payload = _err(500, repr(e),
                                           stacktrace=traceback.format_exc())
                self._reply(status, payload)
                return
            import contextlib

            from ..utils import slowtrace, telemetry

            t_route = time.perf_counter()
            # wire trace propagation: an incoming W3C-style traceparent
            # (attached by api/client.py _send) roots this request's span
            # under the REMOTE caller's trace — same trace id reused,
            # remote parent recorded — so client→REST→job→chunk spans
            # merge into one Perfetto session across processes. Non-
            # monitor requests also ride the tail-based slow-request
            # capture: the request span tree persists when the wall
            # breaches the rest.request SLO p99 (GET /3/SlowTraces).
            tp_header = self.headers.get("traceparent")
            capture = (contextlib.nullcontext() if is_monitor_poll
                       else slowtrace.request(
                           "rest.request", f"{method} {parsed.path}",
                           endpoint=head, remote=int(bool(tp_header))))
            with telemetry.remote_context(tp_header), capture as _cap:
                try:
                    from ..utils import failpoints

                    # read the body BEFORE the failpoint (or any other
                    # early reply) can short-circuit routing: on a
                    # keep-alive connection, unread body bytes would be
                    # parsed as the NEXT request's start line — a
                    # wire-protocol desync the pooled client turns from
                    # latent to immediate
                    body = (self._body() if method in ("POST", "PUT")
                            else {})
                    failpoints.hit("rest.route")
                    # tenant identity rides the request: every Job/quota
                    # decision under this route sees the caller's tenant
                    # (X-H2O-TPU-Tenant, attached by api/client.py) and
                    # requested priority lane
                    from ..workload import tenants as _tenants

                    with _tenants.request_scope(
                            self.headers.get("X-H2O-TPU-Tenant"),
                            self.headers.get("X-H2O-TPU-Priority")):
                        status, payload = route(server, method, parts,
                                                query, body)
                except failpoints.InjectedHTTPError as e:
                    # deterministic flaky-server injection: reply the
                    # injected status; 429/503 carry Retry-After so client
                    # retry paths can be driven end-to-end over a socket
                    status, payload = _err(e.status, str(e))
                    if e.status in (429, 503):
                        payload["__headers__"] = {
                            "Retry-After": f"{e.retry_after_s:g}"}
                except WorkloadAdmissionError as e:
                    # over-quota tenant submission — ONE central mapping
                    # covers every submitting route (model builds, grids,
                    # AutoML): retryable-later, other tenants untouched
                    status, payload = _err(
                        429, str(e), error_type="quota_rejected",
                        tenant=e.tenant,
                        retry_after_s=round(e.retry_after_s, 3),
                        cost_bytes=e.cost_bytes,
                        quota_bytes=e.quota_bytes)
                    payload["__headers__"] = {
                        "Retry-After": max(1, int(np.ceil(e.retry_after_s)))}
                except KeyError as e:
                    status, payload = _err(404, str(e))
                except (ValueError, TypeError) as e:
                    status, payload = _err(400, str(e))
                except Exception as e:  # noqa: BLE001 — as H2OError
                    status, payload = _err(500, repr(e),
                                           stacktrace=traceback.format_exc())
                    from ..utils.log import err as _log_err

                    # a 500 that only ever reached the wire was invisible
                    # to /3/Logs — now the ring keeps it
                    _log_err(f"{method} {parsed.path} -> 500: {e!r}")
                if _cap is not None and status >= 500:
                    # a 500 reply IS an SLO error even though no exception
                    # unwinds this handler
                    _cap.note_error()
            # every routed request lands in the registry (the reference
            # TimeLine records every RPC packet; the REST control plane is
            # this repo's packet stream) — but monitoring polls stay OUT of
            # the ring: a client polling /3/Jobs at 50ms would cycle the
            # 4096-event ring and evict the very training spans the
            # endpoint exists to show
            telemetry.inc("rest.request.count")
            if status >= 500:
                telemetry.inc("rest.error.count")
            telemetry.observe("rest.request.seconds",
                              time.perf_counter() - t_route)
            if not is_monitor_poll:
                from ..utils import timeline as _timeline

                _timeline.record("rest", f"{method} {parsed.path}",
                                 status=status)
            self._reply(status, payload)

        def do_GET(self):
            self._route("GET")

        def do_POST(self):
            self._route("POST")

        def do_DELETE(self):
            self._route("DELETE")

        def do_HEAD(self):
            # `HEAD /3/Cloud` (RegisterV3Api) — same handler as GET, no body
            self._head_only = True
            self._route("GET")

    return Handler


# ---------------------------------------------------------------------------
# routing table (`RequestServer.java:157` route registration)
# ---------------------------------------------------------------------------
def _post_file(handler, query: dict) -> tuple[int, dict]:
    """`POST /3/PostFile[.bin]` (`water/api/PostFileServlet.java:14`): spool
    the pushed bytes server-side and register them in the DKV under
    ``destination_frame``. A raw body streams to disk in 1MB chunks; a
    multipart/form-data body (what h2o-py's requests layer sends) is parsed
    with the stdlib email machinery."""
    from ..backend.kvstore import STORE, make_key
    from ..io import upload

    n = int(handler.headers.get("Content-Length") or 0)
    ctype = handler.headers.get("Content-Type", "")
    dest = query.get("destination_frame") or make_key("upload")
    fname = query.get("filename", "")
    # stream the request body to disk first — a multi-GB push (raw OR
    # multipart) must never materialize in server memory
    path, total = upload.spool_stream(handler.rfile, n)
    if ctype.startswith("multipart/"):
        raw = path
        try:
            path, total, part_name = upload.extract_multipart(raw, ctype)
        finally:
            os.unlink(raw)
        fname = fname or part_name
    with open(path, "rb") as fh:
        head = fh.read(8)
    suffix = upload.guess_suffix(fname, dest, head=head)
    if suffix != ".bin":
        os.replace(path, path[:-len(".bin")] + suffix)
        path = path[:-len(".bin")] + suffix
    uf = upload.UploadedFile(dest, path, total,
                             name=fname or os.path.basename(path))
    STORE.put(dest, uf)
    return 200, {"destination_frame": dest, "total_bytes": total}


#: ParseV3 wire type names → Vec type strings (`water/parser/ParseSetup`)
_PARSE_TYPES = {"numeric": "real", "real": "real", "double": "real",
                "float": "real", "int": "int", "enum": "enum",
                "categorical": "enum", "factor": "enum", "string": "string",
                "time": "time", "uuid": "string", "unknown": "real"}


def _parse_setup_of(p: dict):
    """Build a ParseSetup from a ParseV3 request body (`water/api/
    ParseHandler` applies the client's overrides the same way); returns None
    when the body carries no overrides so guessing stays in charge."""
    from ..io.parser import ParseSetup

    names = p.get("column_names") or None
    if isinstance(names, str):
        names = json.loads(names) if names.startswith("[") else [names]
    ctypes = p.get("column_types") or None
    if isinstance(ctypes, str):
        ctypes = json.loads(ctypes) if ctypes.startswith("[") else [ctypes]
    nas = p.get("na_strings") or None
    if isinstance(nas, str):
        nas = json.loads(nas) if nas.startswith("[") else [nas]
    check_header = p.get("check_header")
    sep = p.get("separator")
    if not any(v is not None for v in (names, ctypes, nas, check_header,
                                       sep)):
        return None
    tmap = None
    if isinstance(ctypes, dict):
        tmap = {k: _PARSE_TYPES.get(str(v).lower(), "real")
                for k, v in ctypes.items()}
    elif ctypes is not None and names:
        tmap = {n: _PARSE_TYPES.get(str(t).lower(), "real")
                for n, t in zip(names, ctypes) if t is not None}
    header = None
    if check_header is not None:
        check_header = int(check_header)
        header = True if check_header == 1 else \
            False if check_header == -1 else None
    if isinstance(sep, int):
        sep = chr(sep)
    return ParseSetup(separator=sep, header=header, column_names=names,
                      column_types=tmap, na_strings=nas)


def _csv_head_preview(path: str, setup) -> tuple[list, list]:
    """(column names, guessed types) from the first lines of a CSV — the
    ParseSetup preview. Transparent for gz/zip heads via pyarrow streams."""
    import csv as _csv
    import io as _io

    try:
        if path.endswith(".gz"):
            import pyarrow as pa

            with pa.input_stream(path, compression="gzip") as st:
                head = st.read(1 << 16)
        elif path.endswith(".zip"):
            import zipfile as _zipfile

            with _zipfile.ZipFile(path) as zf:
                with zf.open(zf.namelist()[0]) as st:
                    head = st.read(1 << 16)
        else:
            with open(path, "rb") as fh:
                head = fh.read(1 << 16)
    except Exception:  # noqa: BLE001 — preview is best-effort
        return None, None
    lines = head.decode("utf-8", errors="replace").splitlines()
    rows = list(_csv.reader(_io.StringIO("\n".join(lines[:50])),
                            delimiter=setup.separator or ","))
    rows = [r for r in rows if r]
    if not rows:
        return None, None
    ncol = len(rows[0])
    names = rows[0] if setup.header else [f"C{i+1}" for i in range(ncol)]
    data = rows[1:] if setup.header else rows
    types = []
    for j in range(ncol):
        vals = [r[j] for r in data[:30] if j < len(r) and r[j].strip()]
        numeric = vals and all(_is_float(v) for v in vals)
        types.append("Numeric" if numeric else "Enum")
    return names, types


def _is_float(tok: str) -> bool:
    try:
        float(tok)
        return True
    except ValueError:
        return tok.strip().upper() in ("NA", "NAN", "")


def _resolve_upload(source: str) -> tuple[str, str]:
    """A Parse source may be a filesystem path OR the key of a PostFile
    upload; returns (path to read, display name whose extension drives
    parse-type guessing)."""
    from ..backend.kvstore import STORE
    from ..io.upload import UploadedFile

    obj = STORE.get(source)
    if isinstance(obj, UploadedFile):
        return obj.path, obj.name
    return source, source


def _maybe_decrypt(path: str, name: str, p: dict) -> tuple[str, str, str | None]:
    """When the request names a decrypt_tool (`ParseSetupV3.decrypt_tool`),
    run the source bytes through it into a temp file the parser reads —
    `water/parser/DecryptionTool.decryptionOf` in the reference's 2-pass
    parse. The plaintext name drops a trailing .aes/.enc so extension-based
    type guessing sees the real format. The third return is the temp path
    when one was created — the CALLER must unlink it after parsing so
    plaintext never outlives the request."""
    tool_id = p.get("decrypt_tool") or p.get("decrypt_tool_id")
    if not tool_id:
        return path, name, None
    from ..io.crypto import DecryptionTool

    tool = STORE.get(tool_id)
    if not isinstance(tool, DecryptionTool):
        raise KeyError(f"decrypt tool {tool_id!r} not found")
    import tempfile

    with open(path, "rb") as fh:
        plain = tool.decrypt(fh.read())
    for ext in (".aes", ".enc", ".encrypted"):
        if name.endswith(ext):
            name = name[:-len(ext)]
            break
    suffix = os.path.splitext(name)[1] or ".csv"
    tf = tempfile.NamedTemporaryFile(suffix=suffix, delete=False)
    tf.write(plain)
    tf.close()
    return tf.name, name, tf.name


def _serving_route(method: str, rest: list[str], p: dict) -> tuple[int, dict]:
    """`/3/Serving/...` — the online scoring runtime (`h2o_tpu/serving/`).

    - ``POST /3/Serving/models/{id}``: register + warm up (in-STORE model
      key via ``model_id``, or a MOJO zip/dir via ``mojo_file`` — a local
      path or a PostFile upload key).
    - ``DELETE /3/Serving/models/{id}``: unregister, stop its batcher.
    - ``POST /3/Serving/score``: row-dict scoring (``row`` or ``rows``);
      queue-full → 429 + Retry-After, deadline expiry → 408 — typed,
      never hanging.
    - ``GET /3/Serving/stats[/{id}]``: latency percentiles, throughput,
      batch occupancy, queue depth, recompile/rejection counters.
    - ``POST /3/Serving/routes/{endpoint}``: map a logical endpoint onto
      weighted model variants (canary split + shadow traffic);
      ``GET /3/Serving/routes[/{endpoint}]`` surfaces per-variant
      divergence stats, ``DELETE`` drops the route.
    - ``GET /3/Serving/control``: fleet quota, placements, routes.
    """
    from .. import serving
    from ..serving.errors import (AdmissionError, DeadlineExceededError,
                                  ModelNotRegisteredError, QueueFullError,
                                  RouteNotFoundError, ServingShutdownError,
                                  UnsupportedModelError)

    rt = serving.get_runtime()
    sub = rest[1] if len(rest) > 1 else ""

    if sub == "models" and len(rest) >= 3:
        sid = urllib.parse.unquote(rest[2])
        if method == "GET":
            try:
                return 200, schemas.serving_model_schema(rt.model(sid).info())
            except ModelNotRegisteredError as e:
                return _err(404, str(e))
        if method == "DELETE":
            try:
                rt.unregister(sid)
            except ModelNotRegisteredError as e:
                return _err(404, str(e))
            return 200, {"model_id": sid, "unregistered": True}
        if method == "POST":
            overrides = {k: p[k] for k in
                         ("buckets", "max_batch", "max_wait_us",
                          "queue_depth", "deadline_ms", "stats_window",
                          "priority", "replicas")
                         if p.get(k) not in (None, "")}
            if isinstance(overrides.get("buckets"), str):
                overrides["buckets"] = [
                    int(t) for t in overrides["buckets"].split(",")
                    if t.strip()]
            strict = _truthy(p.get("strict_levels"))
            try:
                if p.get("mojo_file"):
                    path, _name = _resolve_upload(str(p["mojo_file"]))
                    if not os.path.exists(path):
                        return _err(404, f"no MOJO at '{p['mojo_file']}'")
                    info = rt.register_mojo(path, sid, overrides=overrides,
                                            strict_levels=strict)
                else:
                    mid = p.get("model_id") or sid
                    model = STORE.get(mid)
                    if model is None:
                        return _err(404, f"model {mid} not found")
                    info = rt.register_model(model, sid,
                                             overrides=overrides,
                                             strict_levels=strict)
            except (UnsupportedModelError, NotImplementedError) as e:
                # NotImplementedError: a model's score_raw declares the
                # matrix path unsupported at trace time (GLM interactions)
                # — a client-input problem, not a server fault
                return _err(400, str(e), error_type="unsupported_model")
            except ValueError as e:
                return _err(400, str(e))
            except AdmissionError as e:
                # over-quota (or placement-OOM) — retryable-later, and
                # co-registered models are untouched by construction
                status, payload = _err(
                    429, str(e), error_type="admission_rejected",
                    retry_after_s=round(e.retry_after_s, 3),
                    cost_bytes=e.cost_bytes,
                    budget_bytes=e.budget_bytes)
                payload["__headers__"] = {
                    "Retry-After": max(1, int(np.ceil(e.retry_after_s)))}
                return status, payload
            return 200, schemas.serving_model_schema(info)

    if sub == "score" and method == "POST":
        sid = p.get("model_id", "")
        endpoint = p.get("endpoint", "")
        rows = p.get("rows")
        if rows is None:
            row = p.get("row")
            rows = [row] if row is not None else None
        if not rows or not all(isinstance(r, dict) for r in rows):
            return _err(400, "score needs 'row' (dict) or 'rows' "
                             "(list of dicts)")
        deadline_ms = p.get("deadline_ms")
        deadline_ms = (None if deadline_ms in (None, "")
                       else float(deadline_ms))
        served_by = sid
        try:
            if endpoint:
                # routed scoring: the router picks the serving variant
                # (weighted deterministic split) and feeds shadow traffic
                preds, served_by = rt.router.score(endpoint, rows,
                                                   deadline_ms=deadline_ms)
            else:
                preds = rt.score(sid, rows, deadline_ms=deadline_ms)
        except (ModelNotRegisteredError, RouteNotFoundError) as e:
            return _err(404, str(e))
        except ServingShutdownError as e:
            # raced a DELETE / re-registration: the looked-up lane died
            # under the request — retryable conflict, not a server fault
            return _err(409, str(e), error_type="model_shutdown")
        except (QueueFullError, AdmissionError) as e:
            # AdmissionError here: a cold model's lazy re-placement lost
            # the quota race — same retryable-later shape as queue-full
            status, payload = _err(
                429, str(e),
                error_type=("queue_full" if isinstance(e, QueueFullError)
                            else "admission_rejected"),
                retry_after_s=round(e.retry_after_s, 3))
            payload["__headers__"] = {
                "Retry-After": max(1, int(np.ceil(e.retry_after_s)))}
            return status, payload
        except DeadlineExceededError as e:
            return _err(408, str(e), error_type="deadline_exceeded")
        out = {"model_id": served_by, "predictions": preds,
               "count": len(preds)}
        if endpoint:
            out["endpoint"] = endpoint
        return 200, out

    if sub == "stats" and method == "GET":
        if len(rest) > 2:
            sid = urllib.parse.unquote(rest[2])
            try:
                snap = rt.stats(sid)
            except ModelNotRegisteredError as e:
                return _err(404, str(e))
            return 200, schemas.serving_stats_schema({sid: snap})
        return 200, schemas.serving_stats_schema(rt.stats())

    if sub == "models" and method == "GET":
        infos = []
        for mid in rt.model_ids():
            try:
                infos.append(rt.model(mid).info())
            except ModelNotRegisteredError:
                pass  # unregistered between the listing and the lookup
        return 200, {"models": infos}

    if sub == "routes":
        if len(rest) > 2:
            endpoint = urllib.parse.unquote(rest[2])
            if method == "POST":
                variants = p.get("variants")
                if isinstance(variants, dict):
                    # {model_id: weight} shorthand
                    variants = [{"model_id": k, "weight": v}
                                for k, v in variants.items()]
                if not isinstance(variants, list):
                    return _err(400, "route needs 'variants': a list of "
                                     "{model_id, weight[, shadow]} dicts")
                seed = p.get("seed")
                try:
                    st = rt.router.create_route(
                        endpoint, variants,
                        seed=None if seed in (None, "") else int(seed))
                except ModelNotRegisteredError as e:
                    return _err(404, str(e))
                except ValueError as e:
                    return _err(400, str(e))
                return 200, schemas.serving_route_schema(st)
            if method == "DELETE":
                try:
                    rt.router.delete_route(endpoint)
                except RouteNotFoundError as e:
                    return _err(404, str(e))
                return 200, {"endpoint": endpoint, "deleted": True}
            if method == "GET":
                try:
                    return 200, schemas.serving_route_schema(
                        rt.router.stats(endpoint))
                except RouteNotFoundError as e:
                    return _err(404, str(e))
        if method == "GET":
            st = rt.router.stats()
            return 200, {"routes": [schemas.serving_route_schema(r)
                                    for r in st["routes"]]}

    if sub == "control" and method == "GET":
        return 200, schemas._clean(rt.control_snapshot())

    return _err(404, f"no serving route for {method} "
                     f"/{'/'.join(['3'] + rest)}")


def route(server: H2OServer, method: str, parts: list[str], query: dict,
          body: dict) -> tuple[int, dict]:
    if not parts or parts[0] in ("flow", "index.html"):
        # minimal interactive Flow over the JSON API: import/parse, frame
        # and model inspection, train form with live job progress (the
        # reference serves the h2o-flow notebook IDE here, `h2o-web/`)
        from .flow import FLOW_HTML

        return 200, {"__html__": FLOW_HTML}
    ver, rest = parts[0], parts[1:]
    if ver not in ("3", "99", "4"):
        return _err(404, f"unknown api version {ver}")
    p = dict(query)
    p.update(body)

    if not rest:
        return _err(404, "no route")
    head = rest[0]

    # -- cloud / about / shutdown -------------------------------------------
    if head in ("Cloud", "Sample"):
        # `GET /99/Sample` registers CloudHandler.status too
        # (`RegisterV3Api.java:495` — "example of an experimental endpoint")
        import jax

        from ..backend.memory import CLEANER, hbm_stats

        mem = hbm_stats() or {}
        return 200, {
            "version": __version__, "cloud_name": server.name,
            "cloud_size": 1, "cloud_healthy": True, "consensus": True,
            "locked": True,
            "nodes": [{"h2o": server.url, "healthy": True,
                       "num_cpus": len(jax.devices()),
                       "backend": jax.default_backend(),
                       # the free_mem/swap fields of NodeV3 — HBM here
                       # (0 is a REAL value at full utilization, not null)
                       "free_mem": (mem["bytes_limit"] - mem["bytes_in_use"]
                                    if "bytes_limit" in mem
                                    and "bytes_in_use" in mem else None),
                       "max_mem": mem.get("bytes_limit"),
                       "tracked_hbm_bytes": CLEANER.tracked_bytes(),
                       "swap_count": CLEANER.spills}],
        }
    if head == "About":
        return 200, {"entries": [{"name": "Build version", "value": __version__},
                                 {"name": "Backend", "value": "jax/tpu"}]}
    if head == "Shutdown" and method == "POST":
        # detached teardown thread — the process is ending, there is no
        # trace to continue
        threading.Thread(target=server.stop,  # graftlint: disable=thread-without-trace-context
                         daemon=True).start()
        return 200, {}

    # -- import / parse ------------------------------------------------------
    if head in ("ImportFiles", "ImportFilesMulti"):
        # `POST /3/ImportFilesMulti` (`ImportFilesHandler.importFilesMulti`)
        # is the same resolution over a `paths` array
        paths_in = ([p.get("path", "")] if head == "ImportFiles"
                    else p.get("paths") or [])
        if isinstance(paths_in, str):
            paths_in = [s.strip(" '\"") for s in
                        paths_in.strip("[]").split(",") if s.strip(" '\"")]
        import glob as _glob

        hits, fails = [], []
        for path in paths_in:
            if "://" in path:  # URI schemes resolve through the Persist SPI
                from ..io.persist import localize

                try:
                    hits.append(localize(path))
                except (OSError, ValueError, NotImplementedError):
                    fails.append(path)
            elif any(c in path for c in "*?["):
                got = sorted(_glob.glob(path))
                hits.extend(got)
                if not got:
                    fails.append(path)
            elif os.path.exists(path):
                hits.append(path)
            else:
                fails.append(path)
        return 200, {"files": hits, "destination_frames": hits,
                     "fails": fails, "dels": []}
    if head == "ParseSetup" and method == "POST":
        from ..io.parser import guess_setup

        paths = p.get("source_frames", [])
        if isinstance(paths, str):
            paths = [paths]
        paths = [s.strip('"') for s in paths]
        path0, name0, tmp0 = _maybe_decrypt(*_resolve_upload(paths[0]), p)
        try:
            setup = guess_setup(path0)
            ext = name0.rsplit(".", 1)[-1].lower()
            from ..io.parser import BINARY_FORMAT_EXTS

            if setup.column_names is None and \
                    "." + ext not in BINARY_FORMAT_EXTS:
                # sample the head for names/types the way ParseSetupHandler's
                # preview pass does (`water/parser/ParseSetup.java` guessSetup)
                names, types = _csv_head_preview(path0, setup)
                setup.column_names = names
                if setup.column_types is None:
                    setup.column_types = types
        finally:
            if tmp0:  # decrypted plaintext must not outlive the request
                os.unlink(tmp0)
        ptype = {"parquet": "PARQUET", "pq": "PARQUET", "orc": "ORC",
                 "xls": "XLS", "xlsx": "XLSX",
                 "svm": "SVMLight", "svmlight": "SVMLight"}.get(ext, "CSV")
        return 200, {
            "source_frames": [schemas.key_schema(s) for s in paths],
            "parse_type": ptype,
            "separator": ord(setup.separator or ","),
            "check_header": 1 if setup.header else -1,
            "column_names": setup.column_names,
            "column_types": setup.column_types,
            "number_columns": len(setup.column_names or []),
            "destination_frame": _dest_name(paths[0]),
        }
    if head == "Parse" and method == "POST":
        from ..io.parser import ParseSetup, parse_file

        paths = p.get("source_frames", [])
        if isinstance(paths, str):
            paths = [paths]
        paths = [s.strip('"') for s in paths]
        dest = p.get("destination_frame") or _dest_name(paths[0])
        job = Job(f"Parse {paths[0]}", work=1.0)
        # sources may be PostFile upload keys; resolve to their spool files
        # (and through the decrypt tool when the request names one)
        resolved = [_maybe_decrypt(*_resolve_upload(s), p) for s in paths]
        srcs = [r[0] for r in resolved]
        temps = [r[2] for r in resolved if r[2]]
        setup = _parse_setup_of(p)

        def run():
            try:
                fr = parse_file(srcs[0], setup=setup, dest_key=dest)
                if paths[1:]:  # multi-file import: rbind the remaining files
                    # the client's ParseV3 overrides apply to EVERY source
                    rest_frames = [parse_file(q, setup=setup)
                                   for q in srcs[1:]]
                    fr = fr.concat_rows(*rest_frames)
                    fr.key = dest
                    STORE.put(dest, fr)
            finally:
                for t in temps:  # decrypted plaintext dies with the parse
                    if os.path.exists(t):
                        os.unlink(t)
            from ..io.upload import UploadedFile

            for s in paths:  # delete_on_done: uploads are spent after parse
                if isinstance(STORE.get(s), UploadedFile):
                    STORE.remove(s)
            job.dest_key = fr.key
            return fr

        job.start(run, background=True)
        return 200, {"job": schemas.job_schema(job)}

    # -- frames --------------------------------------------------------------
    if head == "Frames":
        if method == "GET" and not rest[1:]:
            frames = STORE.values(Frame)
            return 200, {"frames": [schemas.frame_base(f) for f in frames]}
        if method == "DELETE" and not rest[1:]:
            # `DELETE /3/Frames` — remove ALL frames (`FramesHandler.deleteAll`)
            for k in STORE.keys(Frame):
                STORE.remove(k)
            return 200, {}
        if method == "POST" and rest[1:] and rest[1] == "load":
            # `POST /3/Frames/load` — binary frame import
            # (`water/fvec/persist/FramePersist.loadFrom`)
            from ..backend import persist

            d = p.get("dir", "")
            if not d:
                return _err(400, "Frames/load: dir is required")
            fr2 = persist.load_frame(d)
            job = Job(f"Load frame {fr2.key}", work=1.0)
            job.start(lambda: fr2, background=False)
            return 200, {"job": schemas.job_schema(job),
                         "frame_id": schemas.key_schema(fr2.key, "Key<Frame>")}
        fid = urllib.parse.unquote(rest[1]) if rest[1:] else None
        fr = STORE.get(fid)
        if not isinstance(fr, Frame):
            return _err(404, f"frame {fid} not found")
        if method == "DELETE":
            STORE.remove(fid)
            return 200, {}
        if rest[2:] and rest[2] == "save" and method == "POST":
            # `POST /3/Frames/{id}/save` — binary frame export
            # (`water/fvec/persist/FramePersist.saveTo`)
            from ..backend import persist

            d = p.get("dir", "")
            if not d:
                return _err(400, "Frames/save: dir is required")
            if not _truthy(p.get("force", True)) and os.path.exists(d):
                return _err(400, f"Frames/save: {d} exists (use force)")
            out = persist.save_frame(fr, d)
            job = Job(f"Save frame {fid}", work=1.0)
            job.start(lambda: out, background=False)
            return 200, {"job": schemas.job_schema(job), "dir": out}
        if rest[2:] and rest[2] == "light":
            # `GET /3/Frames/{id}/light` (`FramesHandler.fetchLight`) —
            # names/types only, no rollups and no row preview
            return 200, {"frames": [{
                "frame_id": schemas.key_schema(fr.key, "Key<Frame>"),
                "rows": fr.nrow, "num_columns": fr.ncol,
                "column_names": list(fr.names),
                "column_types": [fr.vec(n).type for n in fr.names]}]}
        if rest[2:] and rest[2] == "export" and \
                method == "GET" and rest[3:]:
            # `GET /3/Frames/{id}/export/{path}/overwrite/{force}`
            p["path"] = urllib.parse.unquote(rest[3])
            p["force"] = rest[5] if rest[5:] else "true"
            method = "POST"  # fall through to the POST export body below
        if rest[2:] and rest[2] == "export" and method == "POST":
            # `water/api/FramesHandler.export` — CSV/parquet by extension
            path = p.get("path", "")
            if not path:
                return _err(400, "export: path is required")
            if path.startswith("file://"):
                path = path[len("file://"):]
            remote = "://" in path
            if not remote and not _truthy(p.get("force")) \
                    and os.path.exists(path):
                return _err(400, f"export: {path} exists (use force)")
            df = fr.to_pandas()
            local = path
            if remote:  # s3://... / gs://... ride the Persist store SPI
                import tempfile as _tf

                suffix = os.path.splitext(path)[1] or ".csv"
                tf = _tf.NamedTemporaryFile(suffix=suffix, delete=False)
                tf.close()
                local = tf.name
            if path.endswith((".parquet", ".pq")):
                df.to_parquet(local)
            else:
                df.to_csv(local, index=False)
            if remote:
                from ..io.persist import store as _store

                try:
                    _store(path, local)
                finally:
                    os.unlink(local)
            return 200, {"job": {"status": "DONE", "dest": path}}
        if rest[2:] and rest[2] == "summary":
            return 200, {"frames": [schemas.frame_schema(fr, npreview=0)]}
        if rest[2:] and rest[2] == "columns":
            if rest[3:]:
                # `GET /3/Frames/{id}/columns/{column}[/domain|/summary]`
                col = urllib.parse.unquote(rest[3])
                if col not in fr.names:
                    return _err(404, f"column {col} not found in {fid}")
                v = fr.vec(col)
                if rest[4:] and rest[4] == "domain":
                    # `FramesHandler.columnDomain` — levels + per-level counts
                    if v.domain is None:
                        return 200, {"domain": [None], "map_keys": None,
                                     "num_levels": [0]}
                    codes = v.to_numpy()
                    counts = np.bincount(
                        codes[~np.isnan(codes)].astype(np.int64),
                        minlength=len(v.domain))
                    return 200, {"domain": [list(v.domain)],
                                 "map_keys": {"string": list(v.domain)},
                                 "num_levels": [int(len(v.domain))],
                                 "counts": [counts.tolist()]}
                summary = schemas.col_summary(col, v, npreview=0)
                if rest[4:] and rest[4] == "summary" and not v.is_string() \
                        and v.data is not None:
                    # `FramesHandler.columnSummary` — histogram + percentiles
                    x = v.to_numpy()
                    x = x[~np.isnan(x)]
                    if x.size:
                        counts, edges = np.histogram(x, bins=20)
                        summary["histogram_bins"] = counts.tolist()
                        summary["histogram_base"] = float(edges[0])
                        summary["histogram_stride"] = float(
                            edges[1] - edges[0])
                        probs = [0.001, 0.01, 0.1, 0.25, 0.333, 0.5,
                                 0.667, 0.75, 0.9, 0.99, 0.999]
                        summary["percentiles"] = np.quantile(
                            x, probs).tolist()
                        summary["default_percentiles"] = probs
                return 200, {"frames": [{
                    "frame_id": schemas.key_schema(fr.key, "Key<Frame>"),
                    "rows": fr.nrow, "num_columns": fr.ncol,
                    "columns": [summary]}]}
            # columns-only payload, no row preview (`FramesHandler.columns`)
            full = schemas.frame_schema(fr, npreview=0)
            return 200, {"frames": [{
                "frame_id": full["frame_id"],
                "rows": full["rows"],
                "num_columns": full["num_columns"],
                "columns": full["columns"]}]}
        n = int(p.get("row_count", 10) or 10)
        return 200, {"frames": [schemas.frame_schema(fr, npreview=n)]}

    # -- model builders ------------------------------------------------------
    if head == "ModelBuilders":
        if method == "GET" and not rest[1:]:
            return 200, {"model_builders": {
                a: {"algo": a, "visibility": "Stable"}
                for a in registry.algo_names()}}
        algo = rest[1]
        entry = registry.lookup(algo)
        if entry is None:
            return _err(404, f"unknown algorithm {algo}")
        if rest[2:] and rest[2] == "model_id" and method == "POST":
            # `POST /3/ModelBuilders/{algo}/model_id`
            # (`ModelBuildersHandler.calcModelId`) — a fresh unique id
            return 200, {"model_id": schemas.key_schema(
                make_key(f"{algo.upper()}_model"), "Key<Model>")}
        if rest[2:] and rest[2] == "parameters" and method == "POST":
            # validation-only pass (`ModelBuilderHandler.validate_parameters`
            # — POST /3/ModelBuilders/{algo}/parameters): construct the
            # builder, report messages, train NOTHING
            messages = []
            try:
                kwargs = _resolve_params(entry[1], p)
                entry[0](entry[1](**kwargs))
            except (ValueError, TypeError, KeyError) as e:
                messages.append({"message_type": "ERRR", "field_name": "",
                                 "message": str(e)})
            return 200, {"algo": algo,
                         "parameters": registry.param_metadata(algo),
                         "messages": messages,
                         "error_count": len(messages)}
        if method == "POST":
            return _jobs_of(entry[0], entry[1], p)
        return 200, {"algo": algo,
                     "parameters": registry.param_metadata(algo)}

    if head == "Word2VecSynonyms":
        # `hex/api/Word2VecHandler.findSynonyms` (Word2VecSynonymsV3)
        m = STORE.get(p.get("model", ""))
        if m is None:
            return _err(404, f"model {p.get('model')} not found")
        count = int(p.get("count", 10) or 10)
        syn = m.find_synonyms(p.get("word", ""), count=count)
        return 200, {"model": schemas.key_schema(m.key, "Key<Model>"),
                     "word": p.get("word", ""), "count": count,
                     "synonyms": list(syn.keys()),
                     "scores": [float(v) for v in syn.values()]}

    if head == "Capabilities":
        # `water/api/CapabilitiesHandler` — core/API/algo extension listing
        core = [{"name": n, "extension_type": "core"}
                for n in ("Algos", "AutoML", "TargetEncoder", "Infogram",
                          "MOJO", "Grid", "SegmentModels")]
        rest_caps = [{"name": "API v3", "extension_type": "rest"},
                     {"name": "Rapids", "extension_type": "rest"}]
        which = rest[1].lower() if rest[1:] else "all"
        caps = {"core": core, "api": rest_caps}.get(which, core + rest_caps)
        return 200, {"capabilities": caps}

    # -- models --------------------------------------------------------------
    if head == "Models":
        from ..models.model_base import Model

        if method == "GET" and not rest[1:]:
            return 200, {"models": [schemas.model_schema(m)
                                    for m in STORE.values(Model)]}
        if method == "DELETE" and not rest[1:]:
            # `DELETE /3/Models` — remove ALL models (`ModelsHandler.deleteAll`)
            for k in STORE.keys(Model):
                STORE.remove(k)
            return 200, {}
        mid = urllib.parse.unquote(rest[1]) if rest[1:] else None
        m = STORE.get(mid)
        if m is None:
            return _err(404, f"model {mid} not found")
        if method == "DELETE":
            STORE.remove(mid)
            return 200, {}
        if rest[2:] and rest[2] == "json":
            # `GET /99/Models/{id}/json` (`ModelsHandler.exportModelDetails`)
            # — full model detail; with dir=, also written server-side
            payload = schemas.model_schema(m)
            if p.get("dir"):
                path, err = _export_path(p, f"{mid}.json", "Models/json")
                if err:
                    return err
                with open(path, "w") as fh:
                    json.dump(payload, fh)
                return 200, {"dir": path, "models": [payload]}
            return 200, {"models": [payload]}
        if rest[2:] and rest[2] == "mojo":
            # force_default True: the download path always rewrites its temp
            # target (the `fetchMojo` contract h2o-py download_mojo relies on)
            path, err = _export_path({**p, "dir": p.get("dir") or "."},
                                     f"{mid}.zip", "Models/mojo",
                                     force_default=True)
            if err:
                return err
            return 200, {"dir": m.save_mojo(path)}
        return 200, {"models": [schemas.model_schema(m)]}

    # -- binary model persistence over the wire ------------------------------
    # (`water/api/ModelsHandler` importModel/exportModel + fetchBinaryModel;
    #  client verbs h2o.save_model/load_model/upload_model, h2o.py:1490-1602)
    if head == "Models.bin":
        from ..backend import persist

        if method == "GET" and rest[1:]:
            mid = urllib.parse.unquote(rest[1])
            m = STORE.get(mid)
            if m is None:
                return _err(404, f"model {mid} not found")
            path, err = _export_path(p, mid, "Models.bin")
            if err:
                return err
            return 200, {"dir": persist.save_model(m, path)}
        if method == "POST":
            path = p.get("dir", "")
            if not path:
                return _err(400, "Models.bin: dir is required")
            m = persist.load_model(path)
            return 200, {"models": [schemas.model_schema(m)]}
        return _err(404, "Models.bin: GET /{id}?dir= or POST with dir")
    if head == "Models.fetch.bin" and method == "GET" and rest[1:]:
        from ..backend import persist

        mid = urllib.parse.unquote(rest[1])
        m = STORE.get(mid)
        if m is None:
            return _err(404, f"model {mid} not found")
        return 200, {"__raw__": persist.model_bytes(m),
                     "__ctype__": "application/octet-stream",
                     "__filename__": mid}
    if head == "Models.java" and method == "GET" and rest[1:]:
        # `GET /3/Models.java/{id}[/preview]` (`ModelsHandler.fetchJavaCode`
        # / `fetchPreview`) — the POJO source as a java file download
        from ..mojo.pojo import pojo_source

        mid = urllib.parse.unquote(rest[1])
        m = STORE.get(mid)
        if m is None:
            return _err(404, f"model {mid} not found")
        cls_name = re.sub(r"[^A-Za-z0-9_]", "_", mid)
        if not cls_name or not (cls_name[0].isalpha() or cls_name[0] == "_"):
            # `JCodeGen.toJavaId`: a leading digit is not a Java identifier
            cls_name = "_" + cls_name
        try:
            src = pojo_source(m, class_name=cls_name)
        except NotImplementedError as e:
            return _err(400, str(e))
        if rest[2:] and rest[2] == "preview":
            # the reference truncates the preview to its first kilobytes
            lines = src.splitlines()
            src = "\n".join(lines[:1000])
            if len(lines) > 1000:
                src += "\n// ... truncated preview ..."
        return 200, {"__raw__": src, "__ctype__": "text/x-java",
                     "__filename__": f"{cls_name}.java"}
    if head == "Models.mojo" and method == "GET" and rest[1:]:
        # `GET /99/Models.mojo/{id}?dir=` (`ModelsHandler.exportMojo`) —
        # server-side MOJO export, returns the written path
        mid = urllib.parse.unquote(rest[1])
        m = STORE.get(mid)
        if m is None:
            return _err(404, f"model {mid} not found")
        path, err = _export_path(p, f"{mid}.zip", "Models.mojo")
        if err:
            return err
        return 200, {"dir": m.save_mojo(path)}
    if head == "Models.upload.bin" and method == "POST":
        from ..backend import persist
        from ..io.upload import UploadedFile

        src = p.get("dir", "")
        uf = STORE.get(src)
        if not isinstance(uf, UploadedFile):
            return _err(404, f"Models.upload.bin: no uploaded file '{src}'")
        m = persist.load_model(uf.path)
        STORE.remove(src)
        return 200, {"models": [schemas.model_schema(m)]}

    # -- online scoring (`h2o_tpu/serving/` runtime) -------------------------
    if head == "Serving":
        return _serving_route(method, rest, p)

    # -- predictions ---------------------------------------------------------
    if head == "Predictions" and method == "POST":
        # /3/Predictions/models/{model}/frames/{frame}
        mid = urllib.parse.unquote(rest[2])
        fid = urllib.parse.unquote(rest[4])
        model, fr = STORE.get(mid), STORE.get(fid)
        if model is None:
            return _err(404, f"model {mid} not found")
        if fr is None:
            return _err(404, f"frame {fid} not found")
        if _truthy(p.get("predict_contributions")):
            def score_fn():
                return model.predict_contributions(fr)
        elif _truthy(p.get("leaf_node_assignment")):
            def score_fn():
                return model.predict_leaf_node_assignment(
                    fr, type=p.get("leaf_node_assignment_type") or "Path")
        elif _truthy(p.get("predict_staged_proba")):
            def score_fn():
                return model.staged_predict_proba(fr)
        else:
            def score_fn():
                return model.predict(fr)
        dest = p.get("predictions_frame") or f"predictions_{mid}_{fid}"
        if ver == "4":
            # `POST /4/Predictions/...` — the async registration: EVERY
            # scoring mode runs in a background job the client polls
            # (water/api/RegisterV3Api's /4 route contract)
            job = Job(f"Prediction {mid} on {fid}", work=1.0)

            def run_predict():
                out = score_fn()
                out.key = dest
                STORE.put(dest, out)
                job.dest_key = dest
                return out

            job.start(run_predict, background=True)
            return 200, {"job": schemas.job_schema(job),
                         "predictions_frame": schemas.key_schema(dest)}
        pred = score_fn()
        pred.key = dest
        STORE.put(dest, pred)
        return 200, {"predictions_frame": schemas.key_schema(dest),
                     "model_metrics": [{}]}

    if head == "PartialDependence" and method == "POST":
        # `water/api/ModelMetricsHandler` PDP route (synchronous here: the
        # reference runs it as a job; ours is one batched rescore per bin)
        model = STORE.get(p.get("model_id", ""))
        fr = STORE.get(p.get("frame_id", ""))
        if model is None or fr is None:
            return _err(404, "model or frame not found")
        cols = p.get("cols")
        cols = cols.split(",") if isinstance(cols, str) and cols else None
        targets = p.get("targets")
        targets = targets.split(",") if isinstance(targets, str) and targets \
            else None
        tables = model.partial_dependence(
            fr, cols, nbins=int(p.get("nbins", 20) or 20),
            weight_column=p.get("weight_column") or None, targets=targets,
            row_index=int(p["row_index"]) if p.get("row_index")
            is not None else -1)
        dest = p.get("destination_key") or make_key("PartialDependence")
        payload = {"destination_key": schemas.key_schema(dest),
                   "partial_dependence_data":
                   [schemas.table_schema(t) for t in tables]}
        # keep the result fetchable by key — `GET /3/PartialDependence/{id}`
        STORE.put(dest, payload)
        return 200, payload
    if head == "PartialDependence" and method == "GET" and rest[1:]:
        # `ModelMetricsHandler.fetchPartialDependenceData`
        dest = urllib.parse.unquote(rest[1])
        payload = STORE.get(dest)
        if not isinstance(payload, dict) \
                or "partial_dependence_data" not in payload:
            return _err(404, f"no partial dependence result {dest}")
        return 200, payload

    if head == "PermutationVarImp" and method == "POST":
        model = STORE.get(p.get("model_id", ""))
        fr = STORE.get(p.get("frame_id", ""))
        if model is None or fr is None:
            return _err(404, "model or frame not found")
        t = model.permutation_importance(
            fr, metric=p.get("metric", "AUTO") or "AUTO",
            n_repeats=int(p.get("n_repeats", 1) or 1),
            seed=int(p.get("seed", -1) or -1))
        return 200, {"permutation_varimp": schemas.table_schema(t)}

    # -- model metrics (`water/api/ModelMetricsHandler`) --------------------
    if head == "ModelMetrics":
        from ..models.model_base import Model

        def _mm_entry(mid2, fid2, mm2):
            return {"model": schemas.key_schema(mid2),
                    "frame": schemas.key_schema(fid2) if fid2 else None,
                    **(schemas.metrics_schema(mm2) or {})}

        def _training_entries(models):
            return [
                _mm_entry(m.key,
                          (m.params.training_frame.key
                           if getattr(m.params, "training_frame", None)
                           is not None else None),
                          m.output.training_metrics)
                for m in models if m.output.training_metrics is not None]

        if not rest[1:]:
            if method == "DELETE":  # `DELETE /3/ModelMetrics` — drop cache
                with _METRICS_LOCK:
                    _METRICS_CACHE.clear()
                return 200, {}
            # listing: training metrics + every cached on-frame recompute
            return 200, {"model_metrics": _training_entries(
                STORE.values(Model)) + [
                _mm_entry(m2, f2, mm) for (m2, f2), mm
                in _metrics_cache_items()]}

        # `/3/ModelMetrics/predictions_frame/{p}/actuals_frame/{a}` — build
        # metrics from a predictions frame + an actuals frame with no model
        # (`ModelMetricsHandler.make`, h2o-py `h2o.make_metrics`)
        if rest[1] == "predictions_frame" and method == "POST":
            return _make_metrics_route(rest, p)

        # resolve the {models,frames} path pair in either order
        mid = fid = None
        seg = rest[1:]
        while seg:
            kind, val = seg[0], (seg[1] if seg[1:] else None)
            if val is None:
                break
            if kind == "models":
                mid = urllib.parse.unquote(val)
            elif kind == "frames":
                fid = urllib.parse.unquote(val)
            else:
                return _err(404, f"ModelMetrics: unknown segment {kind}")
            seg = seg[2:]
        if method == "DELETE":
            # scoped cache invalidation (`ModelMetricsHandler.delete`) —
            # runs BEFORE existence checks: entries for already-deleted
            # models/frames must stay deletable
            with _METRICS_LOCK:
                for k in [k for k in _METRICS_CACHE
                          if (mid is None or k[0] == mid)
                          and (fid is None or k[1] == fid)]:
                    del _METRICS_CACHE[k]
            return 200, {}
        model = STORE.get(mid) if mid else None
        if mid and model is None:
            return _err(404, f"model {mid} not found")
        if fid and not isinstance(STORE.get(fid), Frame):
            return _err(404, f"frame {fid} not found")
        if mid and fid:
            # always recompute: the model or frame may have been replaced
            # under the same key since the last score (the reference's
            # checksum-keyed DKV entry invalidates on replacement)
            if method == "POST" and p.get("predictions_frame"):
                # one scoring pass serves both outputs (BigScore semantics)
                pred, mm = model.score_with_metrics(STORE.get(fid))
                pred.key = str(p["predictions_frame"])
                STORE.put(pred.key, pred)
            else:
                mm = model.model_performance(STORE.get(fid))
            with _METRICS_LOCK:
                _METRICS_CACHE[(mid, fid)] = mm
            return 200, {"model_metrics": [_mm_entry(mid, fid, mm)]}
        if mid:  # all metrics known for one model
            entries = _training_entries([model]) + [
                _mm_entry(m2, f2, mm) for (m2, f2), mm
                in _metrics_cache_items() if m2 == mid]
            return 200, {"model_metrics": entries}
        # all metrics computed on one frame
        return 200, {"model_metrics": [
            _mm_entry(m2, f2, mm) for (m2, f2), mm
            in _metrics_cache_items() if f2 == fid]}

    # -- frame factory / munging routes -------------------------------------
    if head == "CreateFrame" and method == "POST":
        # `water/api/CreateFrameHandler` — synthetic random frame
        rows = int(p.get("rows", 10000) or 10000)
        cols = int(p.get("cols", 10) or 10)
        seed = int(p.get("seed", -1) or -1)
        rng = np.random.default_rng(None if seed in (-1, None) else seed)
        cat_frac = float(p.get("categorical_fraction", 0.2) or 0)
        int_frac = float(p.get("integer_fraction", 0.2) or 0)
        bin_frac = float(p.get("binary_fraction", 0.1) or 0)
        if cat_frac + int_frac + bin_frac > 1.0 + 1e-9:
            return _err(400, "categorical_fraction + integer_fraction + "
                             "binary_fraction must not exceed 1")
        miss_frac = float(p.get("missing_fraction", 0.0) or 0)
        factors = int(p.get("factors", 100) or 100)
        real_range = float(p.get("real_range", 100.0) or 100.0)
        int_range = int(p.get("integer_range", 100) or 100)
        from ..frame.vec import T_CAT, T_STR, T_TIME, Vec as _Vec

        str_frac = float(p.get("string_fraction", 0.0) or 0)
        time_frac = float(p.get("time_fraction", 0.0) or 0)
        real_frac = (float(p["real_fraction"])
                     if p.get("real_fraction") not in (None, "") else None)
        if (cat_frac + int_frac + bin_frac + str_frac + time_frac
                + (real_frac or 0.0)) > 1.0 + 1e-9:
            return _err(400, "column-type fractions must not exceed 1")
        # +0.1 before the floor absorbs 0.2999999997-style client rounding
        # (`OriginalCreateFrameRecipe.buildRecipe`'s comment)
        n_cat = int(cols * cat_frac + 0.1)
        n_int = int(cols * int_frac + 0.1)
        n_bin = int(cols * bin_frac + 0.1)
        n_str = int(cols * str_frac + 0.1)
        n_time = int(cols * time_frac + 0.1)
        if real_frac is not None:
            n_real = int(cols * real_frac + 0.1)
            # explicit fractions must account for every column
            total = n_cat + n_int + n_bin + n_str + n_time + n_real
            if total != cols:
                n_real += cols - total  # rounding slack goes to reals
        else:
            n_real = max(cols - n_cat - n_int - n_bin - n_str - n_time, 0)
        fr2 = Frame([], [])
        ci = 0
        _alpha = np.array(list("abcdefghijklmnopqrstuvwxyz"))
        for _ in range(n_str):
            lens = rng.integers(4, 9, rows)
            words = np.array(
                ["".join(rng.choice(_alpha, size=ln)) for ln in lens],
                dtype=object)
            words[rng.random(rows) < miss_frac] = None
            fr2.add(f"C{ci + 1}", _Vec(None, rows, type=T_STR,
                                       host_data=words)); ci += 1
        for _ in range(n_time):
            t = rng.integers(1_400_000_000_000, 1_700_000_000_000,
                             rows).astype(np.float64)
            t[rng.random(rows) < miss_frac] = np.nan
            fr2.add(f"C{ci + 1}", _Vec.from_numpy(t, type=T_TIME)); ci += 1
        for _ in range(n_real):
            x = rng.uniform(-real_range, real_range, rows).astype(np.float32)
            x[rng.random(rows) < miss_frac] = np.nan
            fr2.add(f"C{ci + 1}", _Vec.from_numpy(x)); ci += 1
        for _ in range(n_int):
            x = rng.integers(-int_range, int_range + 1, rows).astype(np.float32)
            x[rng.random(rows) < miss_frac] = np.nan
            fr2.add(f"C{ci + 1}", _Vec.from_numpy(x)); ci += 1
        for _ in range(n_bin):
            x = (rng.random(rows) < 0.5).astype(np.float32)
            x[rng.random(rows) < miss_frac] = np.nan
            fr2.add(f"C{ci + 1}", _Vec.from_numpy(x)); ci += 1
        for _ in range(n_cat):
            codes = rng.integers(0, factors, rows).astype(np.float32)
            codes[rng.random(rows) < miss_frac] = np.nan
            fr2.add(f"C{ci + 1}", _Vec.from_numpy(
                codes, type=T_CAT,
                domain=[f"c{ci}.l{j}" for j in range(factors)])); ci += 1
        if _truthy(p.get("has_response")):
            rf = int(p.get("response_factors", 2) or 2)
            if rf <= 1:
                y = rng.normal(size=rows).astype(np.float32)
                fr2.add("response", _Vec.from_numpy(y))
            else:
                y = rng.integers(0, rf, rows).astype(np.float32)
                fr2.add("response", _Vec.from_numpy(
                    y, type=T_CAT, domain=[f"r{j}" for j in range(rf)]))
        dest = p.get("dest") or p.get("destination_frame") or "createdFrame"
        fr2.key = dest
        STORE.put(dest, fr2)
        return 200, {"key": schemas.key_schema(dest),
                     "job": {"status": "DONE",
                             "dest": schemas.key_schema(dest)}}

    if head == "SplitFrame" and method == "POST":
        # `water/api/SplitFrameHandler`
        from ..frame.split import split_frame

        fid = p.get("dataset", "")
        fr2 = STORE.get(fid)
        if not isinstance(fr2, Frame):
            return _err(404, f"frame {fid} not found")
        ratios = p.get("ratios") or [0.75]
        if isinstance(ratios, str):
            ratios = [float(r) for r in ratios.strip("[]").split(",") if r]
        seed = int(p.get("seed", -1) or -1)
        parts = split_frame(fr2, ratios=tuple(float(r) for r in ratios),
                            seed=None if seed == -1 else seed)
        dests = p.get("destination_frames") or [
            f"{fid}_part{i}" for i in range(len(parts))]
        if isinstance(dests, str):
            dests = [d.strip(" '\"") for d in dests.strip("[]").split(",")]
        if len(dests) < len(parts):
            return _err(400, f"destination_frames has {len(dests)} names "
                             f"but the split produces {len(parts)} parts")
        for part, dest in zip(parts, dests):
            part.key = dest
            STORE.put(dest, part)
        return 200, {"destination_frames": [schemas.key_schema(d)
                                            for d in dests[:len(parts)]],
                     "job": {"status": "DONE"}}

    if head == "Interaction" and method == "POST":
        # `water/api/InteractionHandler` — combined categorical columns
        from ..rapids import advmath

        fid = p.get("source_frame") or p.get("dataset") or ""
        fr2 = STORE.get(fid)
        if not isinstance(fr2, Frame):
            return _err(404, f"frame {fid} not found")
        factors = p.get("factor_columns") or p.get("factors") or []
        if isinstance(factors, str):
            factors = [f.strip(" '\"") for f in factors.strip("[]").split(",")]
        out = advmath.interaction(
            fr2, factors, _truthy(p.get("pairwise")),
            int(p.get("max_factors", 100) or 100),
            int(p.get("min_occurrence", 1) or 1))
        dest = p.get("dest") or f"{fid}_interaction"
        out.key = dest
        STORE.put(dest, out)
        return 200, {"dest": schemas.key_schema(dest),
                     "job": {"status": "DONE"}}

    if head == "MissingInserter" and method == "POST":
        # `water/api/MissingInserterHandler` — corrupt a frame with NAs
        fid = p.get("dataset", "")
        fr2 = STORE.get(fid)
        if not isinstance(fr2, Frame):
            return _err(404, f"frame {fid} not found")
        frac = float(p["fraction"]) if p.get("fraction") not in (None, "") \
            else 0.1
        seed = int(p["seed"]) if p.get("seed") not in (None, "") else -1
        rng = np.random.default_rng(None if seed == -1 else seed)
        from ..frame.vec import Vec as _Vec

        for name in fr2.names:
            v = fr2.vec(name)
            if v.is_string():
                continue
            # keep float64: from_numpy detects f32-lossy values (time/int64
            # columns) and retains the exact sidecar — an astype(f32) here
            # would corrupt every row, not just the NA-inserted ones
            x = v.to_numpy().astype(np.float64)
            x[rng.random(len(x)) < frac] = np.nan
            fr2.replace(name, _Vec.from_numpy(x, type=v.type,
                                              domain=v.domain))
        return 200, {"job": {"status": "DONE",
                             "dest": schemas.key_schema(fid)}}

    if head in ("DownloadDataset", "DownloadDataset.bin"):
        # `water/api/DownloadDataHandler` — raw CSV body, not JSON; the .bin
        # registration (`RegisterV3Api`) streams the same CSV for big frames
        fid = p.get("frame_id", "")
        fr2 = STORE.get(fid)
        if not isinstance(fr2, Frame):
            return _err(404, f"frame {fid} not found")
        csv = fr2.to_pandas().to_csv(
            index=False, header=not _truthy(p.get("hex_string")))
        return 200, {"__raw__": csv, "__ctype__": "text/csv",
                     "__filename__": f"{fid}.csv"}

    if head == "Tree":
        # `hex/schemas/TreeV3` + `water/api/TreeHandler` — inspect one tree
        model = STORE.get(p.get("model", ""))
        if model is None or not hasattr(model, "forest"):
            return _err(404, "tree model not found")
        forest = model.forest
        if not isinstance(forest, dict) or not all(
                k in forest for k in ("feat", "thr", "val", "nanL")):
            return _err(400, f"model {model.key} does not store inspectable "
                             f"feat/thr/val trees (algo "
                             f"{getattr(model, 'algo_name', '?')})")
        t = int(p.get("tree_number", 0) or 0)
        feat = np.asarray(model.forest["feat"])
        if not (0 <= t < feat.shape[0]):
            return _err(400, f"tree_number {t} out of range "
                             f"[0, {feat.shape[0]})")
        if feat.ndim == 3:  # multinomial: per-class trees
            dom = model.output.response_domain or []
            cls_name = p.get("tree_class") or (dom[0] if dom else "0")
            k = dom.index(cls_name) if cls_name in dom else int(cls_name)
            sel = (t, k)
        else:
            cls_name = None
            sel = (t,)
        ft = feat[sel]
        thr = np.asarray(model.forest["thr"])[sel]
        val = np.asarray(model.forest["val"])[sel]
        nanl = np.asarray(model.forest["nanL"])[sel]
        N = ft.shape[0]
        names = model.output.names
        lefts = np.where(np.arange(N) * 2 + 1 < N,
                         np.arange(N) * 2 + 1, -1)
        rights = np.where(np.arange(N) * 2 + 2 < N,
                          np.arange(N) * 2 + 2, -1)
        is_leaf = ft < 0
        lefts[is_leaf] = -1
        rights[is_leaf] = -1
        # categorical set-split nodes report their LEFT level set
        # (`TreeHandler` fills levels from the split bitset)
        levels = [None] * N
        if (getattr(getattr(model, "cfg", None), "use_sets", False)
                and "catd" in forest):
            catd = np.asarray(forest["catd"])[sel]
            iscat = np.asarray(model.is_cat)
            ne = np.asarray(model.cat_nedges, dtype=np.int64)
            for j in range(N):
                f = int(ft[j])
                if f < 0 or not iscat[f]:
                    continue
                dom = model.output.domains.get(names[f]) or []
                lv = [d for li, d in enumerate(dom)
                      if catd[j, min(li, int(ne[f]))] <= 0.5]
                levels[j] = lv
        return 200, {
            "model_id": schemas.key_schema(str(model.key)),
            "tree_number": t,
            "tree_class": cls_name,
            "left_children": lefts.tolist(),
            "right_children": rights.tolist(),
            "features": [None if f < 0 else names[int(f)] for f in ft],
            "thresholds": [None if l or levels[i] is not None else float(x)
                           for i, (l, x) in enumerate(zip(is_leaf, thr))],
            "levels": levels,
            "predictions": [float(x) if l else None
                            for l, x in zip(is_leaf, val)],
            "nas": ["L" if nl else "R" for nl in nanl],
            "root_node_id": 0,
        }

    # -- key management / misc ----------------------------------------------
    if head == "DKV" and method == "DELETE":
        if rest[1:]:
            STORE.remove(urllib.parse.unquote(rest[1]))
            return 200, {}
        for k in STORE.keys():  # `removeAll` (`water/api/RemoveAllHandler`)
            STORE.remove(k, cascade=False)
        return 200, {}
    if head == "GarbageCollect" and method == "POST":
        import gc as _gc

        _gc.collect()
        return 200, {}
    if head == "LogAndEcho" and method == "POST":
        from ..utils.log import info as _log_info

        msg = p.get("message", "") or ""
        _log_info(f"LogAndEcho: {msg}")
        return 200, {"message": msg}
    if head == "Ping":
        import time as _time

        return 200, {"cloud_uptime_millis": int(
            (_time.time() - server.started_at) * 1000), "cloud_healthy": True}
    if head == "KillMinus3":
        # `GET /3/KillMinus3` (`water/util/JStackCollectorTask`) — the JVM
        # analog logs all stack traces to stdout; log the controller's here
        import sys
        import traceback as tb

        from ..utils.log import info as _log_info

        names = {t.ident: t.name for t in threading.enumerate()}
        for tid, frame in sys._current_frames().items():
            _log_info(f"KillMinus3 thread [{names.get(tid, tid)}]:\n"
                      + "".join(tb.format_stack(frame)))
        return 200, {}
    if head == "CloudLock" and method == "POST":
        # `water/api/CloudLockHandler` → Paxos.lockCloud(reason)
        from ..utils.log import info as _log_info

        reason = "requested via REST api." + (
            f" Reason: {p['reason']}" if p.get("reason") else "")
        server.locked_reason = reason
        _log_info(f"Cloud locked: {reason}")
        return 200, {"reason": p.get("reason")}
    if head == "UnlockKeys" and method == "POST":
        # `water/api/UnlockKeysHandler` → UnlockTask over all nodes; keys
        # here carry no write-locks (single controller), so this releases
        # nothing but keeps the verb for clients that call it defensively
        return 200, {}
    if head == "SessionProperties":
        # `RapidsHandler.{get,set}SessionProperty` (RegisterV3Api:483-487)
        sid = p.get("session_key", "default")
        key = p.get("key", "")
        if not key:
            return _err(400, "SessionProperties: key is required")
        if method == "POST":
            _SESSION_PROPS[(sid, key)] = p.get("value")
            return 200, {"session_key": sid, "key": key,
                         "value": p.get("value")}
        return 200, {"session_key": sid, "key": key,
                     "value": _SESSION_PROPS.get((sid, key))}
    if head == "SteamMetrics":
        # `water/api/SteamMetricsHandler` — cluster idle time for Steam's
        # auto-suspend decision
        import time as _time

        from ..backend.jobs import any_running

        idle = 0 if any_running() else int(
            (_time.time() - server.last_activity) * 1000)
        return 200, {"version": 1, "idle_millis": idle}
    if head == "Find":
        # `water/api/FindHandler` — scan forward from `row` for `match`,
        # reporting the previous and next hit row indices
        fr2 = STORE.get(p.get("key", ""))
        if not isinstance(fr2, Frame):
            return _err(404, f"frame {p.get('key')} not found")
        names = [p["column"]] if p.get("column") else list(fr2.names)
        if p.get("column") and p["column"] not in fr2.names:
            return _err(404, f"column {p['column']} not found")
        row = int(p.get("row", 0) or 0)
        match = p.get("match")
        prev_hit, next_hit = -1, -1
        for name in names:
            v = fr2.vec(name)
            if v.is_string():
                vals = np.asarray([x == match for x in v.host_data])
            elif v.domain is not None:
                if match not in v.domain:
                    if len(names) == 1:
                        return _err(404, f"level {match!r} not found in "
                                         f"column {name}")
                    continue
                vals = fr2.vec(name).to_numpy() == v.domain.index(match)
            else:
                try:
                    target = float("nan") if match is None else float(match)
                except (TypeError, ValueError):
                    if len(names) == 1:
                        return _err(400, f"column {name} is numeric and the "
                                         f"find pattern is not: {match!r}")
                    continue
                x = v.to_numpy()
                vals = np.isnan(x) if np.isnan(target) else (x == target)
            hits = np.flatnonzero(vals)
            before = hits[hits < row]
            after = hits[hits >= row]
            if before.size:
                prev_hit = max(prev_hit, int(before[-1]))
            if after.size:
                next_hit = int(after[0]) if next_hit < 0 \
                    else min(next_hit, int(after[0]))
        return 200, {"prev": prev_hit, "next": next_hit}
    if head == "FrameChunks":
        # `water/api/FrameChunksHandler` — chunk layout of a frame; chunks
        # here are the row-shards of the device mesh
        fid2 = urllib.parse.unquote(rest[1]) if rest[1:] else ""
        fr2 = STORE.get(fid2)
        if not isinstance(fr2, Frame):
            return _err(404, f"frame {fid2} not found")
        shards = 1
        if fr2.ncol and fr2.vecs[0].data is not None:
            try:
                shards = len(fr2.vecs[0].data.sharding.device_set)
            except (AttributeError, TypeError):
                shards = 1
        per = -(-fr2.nrow // shards)  # even padded shards (the ESPC analog)
        counts = [min(per, fr2.nrow - i * per) for i in range(shards)]
        return 200, {"frame_id": schemas.key_schema(fid2, "Key<Frame>"),
                     "chunks": [{"chunk_id": i, "row_count": max(c, 0),
                                 "node_idx": i % shards}
                                for i, c in enumerate(counts)]}

    # -- grid search (`POST /99/Grid/{algo}`, `GET /99/Grids[/{id}]`,
    #    `POST /3/Grid.bin/import`, `POST /3/Grid.bin/{id}/export` —
    #    `water/api/GridSearchHandler`/`GridsHandler`/`GridImportExportHandler`)
    if head == "Grid" and method == "POST" and rest[1:]:
        import json as _json

        from ..models.grid import GridSearch, SearchCriteria

        algo = rest[1]
        entry = registry.lookup(algo)
        if entry is None:
            return _err(404, f"unknown algorithm {algo}")
        algo_cls, params_cls = entry
        body2 = dict(p)
        hp = body2.pop("hyper_parameters", None) or {}
        if isinstance(hp, str):
            hp = _json.loads(hp)
        sc = body2.pop("search_criteria", None) or {}
        if isinstance(sc, str):
            sc = _json.loads(sc)
        grid_id = body2.pop("grid_id", None)
        parallelism = int(body2.pop("parallelism", 1) or 1)
        recovery_dir = body2.pop("recovery_dir", None)
        try:
            kwargs = _resolve_params(params_cls, body2, extra_names=list(hp))
        except ValueError as e:
            return _err(412, str(e))
        gs = GridSearch(algo_cls, params_cls(**kwargs), hp,
                        SearchCriteria(**sc), recovery_dir=recovery_dir,
                        parallelism=parallelism, grid_id=grid_id)
        job = gs.train(background=True)
        return 200, {"job": schemas.job_schema(job),
                     "key": schemas.key_schema(job.dest_key)}
    if head == "Grids":
        from ..models.grid import Grid

        if not rest[1:]:
            return 200, {"grids": [{"grid_id": schemas.key_schema(g.key)}
                                   for g in STORE.values(Grid)]}
        gid = urllib.parse.unquote(rest[1])
        g = STORE.get(gid)
        if not isinstance(g, Grid):
            return _err(404, f"grid {gid} not found")
        if method == "DELETE":
            # cascade like the reference's grid remove: the contained models
            # die with the grid (h2o.remove(grid) contract)
            for m in list(g.models):
                STORE.remove(m.key)
            STORE.remove(gid)
            return 200, {}
        by = p.get("sort_by") or None
        decr = _truthy(p["decreasing"]) if "decreasing" in p else None
        ms = g.sorted_models(by, decr)
        return 200, {
            "grid_id": schemas.key_schema(g.key),
            "algo": g.builder_cls.algo_name,
            "model_ids": [schemas.key_schema(m.key) for m in ms],
            "hyper_names": list(g.hyper_params),
            "failure_details": [f["error"] for f in g.failures],
            "failed_raw_params": [f["params"] for f in g.failures],
            "summary_table": schemas.table_schema(g.summary_table(by)),
        }
    # -- tree interaction statistics ----------------------------------------
    if head == "FeatureInteraction" and method == "POST":
        # `ModelsHandler.makeFeatureInteraction` (hex/FeatureInteractions,
        # the xgbfi algorithm)
        from ..models.interactions import feature_interactions_tables

        m = STORE.get(p.get("model_id", ""))
        if m is None:
            return _err(404, f"model {p.get('model_id')} not found")
        if not hasattr(m, "forest") or not hasattr(m, "_ensure_covers"):
            return _err(400, f"{getattr(m, 'algo_name', '?')} does not "
                             "support feature interactions calculation")
        tables = feature_interactions_tables(
            m, int(p.get("max_interaction_depth", 100) or 100),
            int(p.get("max_tree_depth", 100) or 100),
            int(p.get("max_deepening", -1) if p.get("max_deepening")
                not in (None, "") else -1))
        return 200, {"feature_interaction":
                     [schemas.table_schema(t) for t in tables]}
    if head == "FriedmansPopescusH" and method == "POST":
        # `ModelsHandler.makeFriedmansPopescusH` (hex/tree/FriedmanPopescusH)
        from ..models.interactions import friedman_popescu_h

        m = STORE.get(p.get("model_id", ""))
        fr2 = STORE.get(p.get("frame", "") or p.get("frame_id", ""))
        if m is None or not isinstance(fr2, Frame):
            return _err(404, "model or frame not found")
        if not hasattr(m, "forest") or not hasattr(m, "_ensure_covers"):
            return _err(400, f"{getattr(m, 'algo_name', '?')} does not "
                             "support Friedman Popescus H calculation")
        variables = p.get("variables") or []
        if isinstance(variables, str):
            variables = [v.strip(" '\"") for v in
                         variables.strip("[]").split(",") if v.strip(" '\"")]
        h = friedman_popescu_h(m, fr2, variables)
        return 200, {"h": None if np.isnan(h) else float(h)}
    if head == "SignificantRules" and method == "POST":
        # `ModelsHandler.makeSignificantRulesTable` (RuleFit)
        m = STORE.get(p.get("model_id", ""))
        if m is None:
            return _err(404, f"model {p.get('model_id')} not found")
        if not hasattr(m, "rule_importance"):
            return _err(400, f"{getattr(m, 'algo_name', '?')} does not "
                             "support significant rules collection")
        from ..utils.twodimtable import TwoDimTable

        rows = m.rule_importance()
        t = TwoDimTable.from_dict("Significant Rules", {
            "variable": [r["rule"] for r in rows],
            "coefficient": [float(r["coefficient"]) for r in rows],
            "support": [float(r["support"]) for r in rows]})
        return 200, {"significant_rules_table": schemas.table_schema(t)}

    # -- tabulate / DCT / SQL import ----------------------------------------
    if head == "Tabulate" and method == "POST":
        # `water/api/TabulateHandler` → `water/util/Tabulate`
        from ..rapids.advmath import tabulate as _tabulate

        fr2 = STORE.get(p.get("dataset", ""))
        if not isinstance(fr2, Frame):
            return _err(404, f"frame {p.get('dataset')} not found")
        count_t, resp_t = _tabulate(
            fr2, p.get("predictor", ""), p.get("response", ""),
            weight=p.get("weight") or None,
            nbins_predictor=int(p.get("nbins_predictor", 20) or 20),
            nbins_response=int(p.get("nbins_response", 10) or 10))
        return 200, {"count_table": schemas.table_schema(count_t),
                     "response_table": schemas.table_schema(resp_t)}
    if head == "DCTTransformer" and method == "POST":
        # `water/api/DCTTransformerHandler` → MathUtils.DCT, on the MXU
        from ..frame.vec import Vec as _Vec
        from ..ops.dct import dct_frame

        fr2 = STORE.get(p.get("dataset", ""))
        if not isinstance(fr2, Frame):
            return _err(404, f"frame {p.get('dataset')} not found")
        dims = p.get("dimensions") or []
        if isinstance(dims, str):
            dims = [int(d) for d in dims.strip("[]").split(",") if d.strip()]
        if len(dims) != 3:
            return _err(400, "Need 3 dimensions (width/height/depth): "
                             "WxHxD (1D: Wx1x1, 2D: WxHx1, 3D: WxHxD)")
        X = np.stack([fr2.vec(n).to_numpy() for n in fr2.names], axis=1)
        Y = dct_frame(X, dims[0], dims[1], dims[2],
                      inverse=_truthy(p.get("inverse")))
        dest = p.get("destination_frame") or f"{p.get('dataset')}_dct"
        out = Frame([f"C{i + 1}" for i in range(Y.shape[1])],
                    [_Vec.from_numpy(Y[:, i]) for i in range(Y.shape[1])],
                    key=dest)
        STORE.put_keyed(out)
        return 200, {"key": schemas.key_schema(dest, "Key<Frame>"),
                     "job": {"status": "DONE",
                             "dest": schemas.key_schema(dest)}}
    if head == "ImportSQLTable" and method == "POST":
        # `water/jdbc/SQLManager` (`POST /99/ImportSQLTable`)
        from ..io.sqlimport import import_sql

        fr2 = import_sql(
            p.get("connection_url", ""), table=p.get("table", "") or "",
            select_query=p.get("select_query", "") or "",
            columns=p.get("columns", "*") or "*")
        job = Job(f"ImportSQLTable {fr2.key}", work=1.0)
        job.dest_key = fr2.key
        job.start(lambda: fr2, background=False)
        return 200, {"job": schemas.job_schema(job),
                     "destination_frame": schemas.key_schema(fr2.key)}
    if head in ("ImportHiveTable", "SaveToHiveTable") and method == "POST":
        # `water/hive/HiveTableImporter` — needs a live Hive metastore;
        # gate unless one is configured (the reference fails identically
        # without a Hive cluster on the classpath)
        from ..utils.knobs import raw as _knob_raw

        if not _knob_raw("H2O_TPU_HIVE_JDBC"):
            return _err(501, f"{head}: no Hive metastore configured "
                             "(set H2O_TPU_HIVE_JDBC to a reachable "
                             "HiveServer2 JDBC url)")
        return _err(501, f"{head}: Hive JDBC transport not implemented "
                         "in this build")
    if head == "ParseSVMLight" and method == "POST":
        # `POST /3/ParseSVMLight` (`ParseHandler.parseSVMLight`) — force the
        # SVMLight reader regardless of the source's extension
        from ..io.parser import _parse_svmlight

        paths = p.get("source_frames") or p.get("source_keys") or []
        if isinstance(paths, str):
            paths = [paths]
        paths = [s.strip('"') for s in paths]
        if not paths:
            return _err(400, "ParseSVMLight: source_frames is required")
        dest = p.get("destination_frame") or _dest_name(paths[0])
        src = _resolve_upload(paths[0])[0]
        job = Job(f"ParseSVMLight {paths[0]}", work=1.0)

        def run_svm():
            fr3 = _parse_svmlight(src, dest_key=dest)
            job.dest_key = fr3.key
            return fr3

        job.start(run_svm, background=True)
        return 200, {"job": schemas.job_schema(job),
                     "destination_frame": schemas.key_schema(dest)}

    # -- decryption setup ----------------------------------------------------
    if head == "DecryptionSetup" and method == "POST":
        # `water/api/DecryptionSetupHandler` → DecryptionTool; the keystore
        # is an uploaded key file (PostFile) or a server-side path
        from ..io.crypto import DecryptionTool, parse_key_material
        from ..io.upload import UploadedFile

        ks = p.get("keystore_id", "")
        obj = STORE.get(ks)
        if isinstance(obj, UploadedFile):
            with open(obj.path, "rb") as fh:
                raw = fh.read()
        elif ks and os.path.exists(ks):
            with open(ks, "rb") as fh:
                raw = fh.read()
        else:
            return _err(404, f"DecryptionSetup: keystore {ks!r} not found "
                             "(upload the key via PostFile first)")
        secret = parse_key_material(raw, p.get("keystore_type", "raw"))
        key = p.get("decrypt_tool_id") or make_key("decrypt_tool")
        tool = DecryptionTool(key, secret,
                              p.get("cipher_spec", "AES/CBC/PKCS5Padding"))
        STORE.put(key, tool)
        return 200, {"decrypt_tool_id": schemas.key_schema(key),
                     "decrypt_impl": "GenericDecryptionTool",
                     "cipher_spec": tool.cipher_spec}

    # -- node persistent storage --------------------------------------------
    if head == "NodePersistentStorage":
        from ..backend.nps import NPS

        if rest[1:] and rest[1] == "configured":
            return 200, {"configured": NPS.configured()}
        if rest[1:] and rest[1] == "categories":
            # /categories/{cat}/exists | /categories/{cat}/names/{n}/exists
            cat = rest[2] if rest[2:] else ""
            if rest[3:] and rest[3] == "names" and rest[4:]:
                return 200, {"exists": NPS.exists(cat, rest[4])}
            return 200, {"exists": NPS.exists(cat)}
        cat = rest[1] if rest[1:] else ""
        if not cat:
            return _err(404, "NodePersistentStorage: category required")
        name = rest[2] if rest[2:] else None
        if method == "GET" and name:
            return 200, {"__raw__": NPS.get(cat, name),
                         "__ctype__": "application/octet-stream",
                         "__filename__": name}
        if method == "GET":
            return 200, {"entries": NPS.list(cat)}
        if method == "POST":
            if name is None:
                import uuid

                name = str(uuid.uuid4())
            NPS.put(cat, name, p.get("value", ""))
            return 200, {"category": cat, "name": name}
        if method == "DELETE" and name:
            NPS.delete(cat, name)
            return 200, {}
        return _err(404, "NodePersistentStorage: bad request")

    # -- assembly (`POST /99/Assembly`, `GET /99/Assembly.java/...`) --------
    if head == "Assembly" and method == "POST":
        from .assembly_server import Assembly, parse_steps

        fr2 = STORE.get(p.get("frame", ""))
        if not isinstance(fr2, Frame):
            return _err(404, f"frame {p.get('frame')} not found")
        steps = parse_steps(p.get("steps"))
        asm = Assembly(steps)
        result = asm.fit(fr2)
        if result is fr2:  # empty pipeline: never rebind the input's key
            result = Frame(list(fr2.names), list(fr2.vecs))
        result.key = make_key("assembly_result")
        STORE.put_keyed(result)
        STORE.put(asm.key, asm)
        return 200, {"assembly": schemas.key_schema(asm.key, "Key<Assembly>"),
                     "result": schemas.key_schema(result.key, "Key<Frame>")}
    if head == "Assembly.java" and method == "GET" and rest[2:]:
        from .assembly_server import Assembly

        asm = STORE.get(urllib.parse.unquote(rest[1]))
        if not isinstance(asm, Assembly):
            return _err(404, f"assembly {rest[1]} not found")
        pojo_name = urllib.parse.unquote(rest[2])
        if pojo_name.endswith(".java"):
            pojo_name = pojo_name[:-5]
        return 200, {"__raw__": asm.to_java(pojo_name),
                     "__ctype__": "text/x-java",
                     "__filename__": f"{pojo_name}.java"}

    if head == "Recovery" and method == "POST" and rest[1:] \
            and rest[1] == "resume":
        # `POST /3/Recovery/resume` (`water/api/RecoveryHandler`, the
        # `-auto_recovery_dir` restart protocol): resume every incomplete
        # grid found under the recovery dir, skipping finished models
        from ..models.grid import GridSearch

        d = p.get("recovery_dir", "")
        if not d or not os.path.isdir(d):
            return _err(404, f"Recovery: no recovery dir at {d!r}")
        gs = GridSearch.resume(d)
        job = gs.train(background=True)
        return 200, {"job": schemas.job_schema(job),
                     "grid_id": schemas.key_schema(job.dest_key)}

    if head == "Grid.bin" and method == "POST":
        from ..models.grid import Grid, export_grid, import_grid

        if rest[1:] and rest[1] == "import":
            d = p.get("grid_path") or p.get("grid_directory") or ""
            if not os.path.isdir(d):
                return _err(404, f"no grid export at {d}")
            g = import_grid(d)
            return 200, {"name": g.key, "grid_id": schemas.key_schema(g.key)}
        if rest[2:] and rest[2] == "export":
            gid = urllib.parse.unquote(rest[1])
            g = STORE.get(gid)
            if not isinstance(g, Grid):
                return _err(404, f"grid {gid} not found")
            d = p.get("grid_directory") or p.get("grid_path") or ""
            if not d:
                return _err(400, "grid_directory is required")
            export_grid(g, d)
            return 200, {"grid_directory": d}
        return _err(404, "Grid.bin: use /import or /{grid_id}/export")

    # -- AutoML (`POST /99/AutoMLBuilder`, `GET /99/AutoML/{id}`,
    #    `GET /99/Leaderboards[/{project}]` — h2o-automl REST surface)
    if head == "AutoMLBuilder" and method == "POST":
        from ..models.automl import H2OAutoML as _AutoML

        spec = p.get("input_spec") or {}
        ctrl = p.get("build_control") or {}
        bm = p.get("build_models") or {}
        fr = STORE.get(spec.get("training_frame", ""))
        y = spec.get("response_column")
        if isinstance(y, dict):  # h2o-py sends {column_name: y}
            y = y.get("column_name")
        if fr is None or not y:
            return _err(404, "input_spec.training_frame and "
                             "input_spec.response_column are required")
        crit = ctrl.get("stopping_criteria") or {}
        aml = _AutoML(
            max_models=int(crit.get("max_models", 0) or 0),
            max_runtime_secs=float(crit.get("max_runtime_secs", 0) or 0),
            max_runtime_secs_per_model=float(
                crit.get("max_runtime_secs_per_model", 0) or 0),
            nfolds=int(ctrl.get("nfolds", 5) or 5),
            seed=(None if crit.get("seed") in (None, -1) else int(crit["seed"])),
            project_name=ctrl.get("project_name") or None,
            include_algos=bm.get("include_algos") or None,
            exclude_algos=bm.get("exclude_algos") or None,
            sort_metric=(spec.get("sort_metric") or None),
            stopping_rounds=int(crit.get("stopping_rounds", 3) or 3),
            stopping_tolerance=float(crit.get("stopping_tolerance", 1e-3)
                                     or 1e-3),
            stopping_metric=crit.get("stopping_metric", "AUTO") or "AUTO",
            ignored_columns=spec.get("ignored_columns") or None)
        job = Job("AutoML", work=1.0)
        job.dest_key = aml.key

        def run_automl():
            aml.train(y=y, training_frame=fr, job=job)
            return aml

        # managed dispatch: the AutoML run is tenant-stamped from the
        # request scope, lane-classed, and quota-checked like any build
        from .. import workload as _workload

        _workload.submit(job, run_automl, background=True,
                         cost_bytes=_workload.frame_cost(fr),
                         priority=aml.priority)
        return 200, {"job": schemas.job_schema(job),
                     "build_control": {"project_name": aml.key}}
    if head == "AutoML" and rest[1:]:
        from ..models.automl import H2OAutoML as _AutoML

        aml = STORE.get(urllib.parse.unquote(rest[1]))
        if not isinstance(aml, _AutoML):
            return _err(404, f"automl {rest[1]} not found")
        lb = aml.leaderboard
        return 200, {
            "automl_id": {"name": aml.key},
            "project_name": aml.key,
            "leader": schemas.key_schema(aml.leader.key) if aml.leader else None,
            "leaderboard_table": schemas.table_schema(
                lb.as_table()) if lb else None,
            "event_log_table": schemas.table_schema(aml.event_log.as_table()),
        }
    if head == "Leaderboards":
        from ..models.automl import H2OAutoML as _AutoML

        if not rest[1:]:
            return 200, {"projects": [a.key for a in STORE.values(_AutoML)]}
        aml = STORE.get(urllib.parse.unquote(rest[1]))
        if not isinstance(aml, _AutoML) or aml.leaderboard is None:
            return _err(404, f"no leaderboard for {rest[1]}")
        lb = aml.leaderboard
        return 200, {
            "project_name": aml.key,
            "table": schemas.table_schema(lb.as_table()),
            "models": [schemas.key_schema(m.key) for m in lb.sorted()],
            "sort_metric": lb.sort_metric,
        }

    # -- jobs ----------------------------------------------------------------
    if head == "Jobs":
        if rest[1:]:
            jid = urllib.parse.unquote(rest[1])
            job = STORE.get(jid)
            if not isinstance(job, Job):
                return _err(404, f"job {jid} not found")
            if rest[2:] and rest[2] == "cancel" and method == "POST":
                job.stop()
                return 200, {}
            return 200, {"jobs": [schemas.job_schema(job)]}
        return 200, {"jobs": [schemas.job_schema(j)
                              for j in STORE.values(Job)]}

    # -- rapids (`/99/Rapids`) ----------------------------------------------
    if head == "Rapids" and method == "GET" and rest[1:] \
            and rest[1] == "help":
        # `GET /99/Rapids/help` (`RapidsHandler.genHelp`) — the language's
        # registered primitives
        from ..rapids.exec import _PRIMS

        return 200, {"syntax": [
            {"name": n, "is_abstract": False} for n in sorted(_PRIMS)]}
    if head == "Rapids" and method == "POST":
        ast = p.get("ast", "")
        sid = p.get("session_id", "default")
        session = _SESSIONS.setdefault(sid, Session(sid))
        result = Rapids(session).exec(ast)
        return 200, _rapids_result(result)
    if head == "InitID":
        if method == "DELETE":
            sid = rest[1] if rest[1:] else "default"
            s = _SESSIONS.pop(sid, None)
            if s:
                s.end()
            for k in [k for k in _SESSION_PROPS if k[0] == sid]:
                del _SESSION_PROPS[k]  # props die with their session
            return 200, {}
        sid = f"_sid_{np.random.randint(1 << 30)}"
        _SESSIONS[sid] = Session(sid)
        return 200, {"session_key": sid}

    if head == "Typeahead":
        # `GET /3/Typeahead/files?src=...&limit=N` — path completion for the
        # import UI (`water/api/TypeaheadHandler`)
        src = p.get("src", "") or ""
        limit = int(p.get("limit", 100) or 100)
        import glob as _glob

        # escape glob metacharacters: src is a literal path prefix, not a
        # pattern ('/data/run[1]/' must match itself); non-positive limit
        # means unlimited (the H2O -1 convention)
        matches = sorted(_glob.glob(_glob.escape(src) + "*"))
        if limit > 0:
            matches = matches[:limit]
        return 200, {"src": src, "matches": matches}

    # -- observability -------------------------------------------------------
    if head == "JStack":
        # thread dumps — `water/api/JStackHandler` analog for the controller
        import sys
        import traceback as tb

        frames = sys._current_frames()
        threads = {t.ident: t.name for t in threading.enumerate()}
        traces = []
        for tid, frame in frames.items():
            traces.append({
                "thread": threads.get(tid, str(tid)),
                "stack": "".join(tb.format_stack(frame))})
        return 200, {"traces": traces}
    if head == "Logs":
        from ..utils.log import get_buffer, get_records

        limit = int(p.get("limit", 2000) or 0) or None
        if rest[1:] and rest[1] == "nodes" and len(rest) >= 5:
            # `GET /3/Logs/nodes/{nodeidx}/files/{name}`
            # (`water/api/LogsHandler`) — one controller, so every nodeidx
            # serves the same ring; `name` picks the per-level file, a
            # STRUCTURED level filter on the typed ring (friendly
            # spellings resolve via log._LEVEL_ALIASES); unknown names
            # serve the unfiltered ring, the old behavior
            from ..utils.log import _LEVEL_ALIASES

            want = (rest[4] if rest[4].upper() in _LEVEL_ALIASES else None)
            lines = get_buffer(limit=limit, level=want)
            return 200, {"log": "\n".join(lines),
                         "name": rest[4], "nodeidx": int(rest[2])}
        return 200, {"log": "\n".join(get_buffer(limit=limit)),
                     "records": get_records(limit=limit,
                                            level=p.get("level") or None)}
    if head == "Timeline":
        from ..utils import timeline as tl

        # full typed events (seq/ns/ms/kind/what + kind-specific detail),
        # newest-biased cap so a full 4096-event ring doesn't make every
        # poll serialize megabytes (`?limit=N`, `?kind=span` filter).
        # `?since=<seq>` is the incremental cursor: only events with a
        # larger seq return, OLDEST-first under `limit` (a >limit gap
        # drains losslessly across pulls — resume from `next_since`,
        # which echoes `since` when nothing new arrived); detect ring
        # overwrite by comparing the first returned seq against since+1
        limit = int(p.get("limit", 1000) or 0)
        # an EXPLICIT ?since= — including 0 (bootstrap from the start) —
        # selects cursor mode; absent = the newest-biased human view
        since_raw = p.get("since")
        since = (int(since_raw) if since_raw not in (None, "")
                 else None)
        events = tl.snapshot(limit=limit or None,
                             kind=p.get("kind") or None,
                             since=since)
        return 200, {"events": events,
                     "since": since,
                     "next_since": (events[-1]["seq"] if events
                                    else (since or 0)),
                     "total_recorded": tl.total_recorded(),
                     "capacity": tl.capacity()}
    if head == "Health":
        # liveness/readiness with typed degradation reasons — the signal
        # the autoscaling loop polls (utils/health.py); excluded from the
        # timeline ring like the monitoring polls above
        from ..utils import health as _health

        snap = _health.snapshot()
        return 200, schemas.health_schema(snap)
    if head == "Workload":
        # the multi-tenant scheduler surface (h2o_tpu/workload/): GET =
        # tenants/quotas/lanes/per-tenant burn + every live entry; POST =
        # configure a tenant's fair-share weight / quota fraction
        from .. import workload as _workload

        if method == "POST":
            name = p.get("tenant") or ""
            if not name:
                return _err(400, "POST /3/Workload needs 'tenant'")
            w = p.get("weight")
            q = p.get("quota_fraction")
            _workload.tenants.configure(
                name,
                weight=None if w in (None, "") else float(w),
                quota_fraction=None if q in (None, "") else float(q))
        return 200, schemas.workload_schema(_workload.snapshot())
    if head == "SlowTraces":
        # the tail-based capture ring (utils/slowtrace.py): full span
        # trees + program dispatch walls of requests that breached their
        # SLO p99 target
        from ..utils import slowtrace as _slowtrace

        if method == "DELETE":
            _slowtrace.clear()
            return 200, {}
        limit = int(p.get("limit", 0) or 0)
        return 200, schemas.slow_traces_schema(
            _slowtrace.snapshot(limit=limit or None),
            _slowtrace.total_captured())
    if head == "Metrics":
        # the unified telemetry registry — JSON by default, Prometheus
        # text exposition via ?format=prometheus (scrape-ready), and the
        # MERGED multi-process view via ?fleet=1 (utils/fleetobs.py
        # scrapes H2O_TPU_FLEET_PEERS + the spool dir and merges with
        # per-process labels — the observability substrate the
        # multi-process serving tier and multi-HOST ingest assume)
        from ..utils import telemetry

        from ..utils import slo as _slo

        # refresh the slo.worst_burn gauge before ANY metrics serve: a
        # Prometheus scraper that never polls /3/Health must still read
        # a current burn, not whatever the last health poll left behind
        _slo.burn_snapshot()
        if _truthy(p.get("fleet")):
            from ..utils import fleetobs

            return 200, {"fleet": fleetobs.collect(
                force=_truthy(p.get("force")))}
        if (p.get("format") or "").lower() in ("prometheus", "text"):
            return 200, {"__raw__": telemetry.prometheus(),
                         "__ctype__": "text/plain; version=0.0.4"}
        return 200, {"metrics": telemetry.snapshot(),
                     "trace_path": telemetry.trace_path(),
                     "pid": os.getpid(), "name": server.name,
                     "ts_ms": int(time.time() * 1000)}
    if head == "Programs":
        # the program cost registry (utils/programs.py): per compiled
        # program, XLA cost_analysis flops/bytes + memory_analysis
        # figures, measured dispatch walls, achieved FLOP/s and the
        # roofline fraction (null off-TPU — see README caveats)
        from ..utils import programs as _programs
        from .schemas import programs_schema

        payload = programs_schema(_programs.snapshot(),
                                  _programs.device_peak_flops())
        payload["ts_ms"] = int(time.time() * 1000)
        return 200, payload
    if head == "Flight":
        # flight-recorder bundles (utils/flightrec.py): listing, or one
        # bundle's full content by name
        from ..utils import flightrec as _flight

        if rest[1:]:
            return 200, {"bundle": _flight.read_bundle(rest[1])}
        return 200, {"dir": _flight.flight_dir(),
                     "armed": _flight.enabled(),
                     "bundles": _flight.list_bundles()}
    if head == "Profiler" and rest[1:] and rest[1] == "capture":
        # POST /3/Profiler/capture?ms=N — bounded LIVE device capture on
        # this process (the tool the real-v5e campaign points at a serving
        # replica); returns the capture directory. 400 when a session is
        # already running or ms is out of range.
        if method != "POST":
            return _err(405, "capture requires POST")
        from ..utils import telemetry as _telemetry

        ms = int(p.get("ms", 1000))
        path = _telemetry.capture(ms, out_dir=p.get("dir") or None)
        return 200, {"dir": path, "ms": ms}
    if head == "Profiler":
        # `water/api/ProfilerHandler`: cluster stack-sample aggregation; here
        # the controller process is sampled for `depth` rounds
        import sys
        import time as _time
        from collections import Counter

        depth = int(p.get("depth", 10))
        counts: Counter = Counter()
        for _ in range(max(depth, 1)):
            for frame in sys._current_frames().values():
                stack = []
                f = frame
                while f is not None and len(stack) < 20:
                    stack.append(f"{f.f_code.co_filename}:{f.f_lineno} "
                                 f"{f.f_code.co_name}")
                    f = f.f_back
                counts["\n".join(stack)] += 1
            _time.sleep(0.005)
        nodes = [{"node_name": server.name,
                  "entries": [{"stacktrace": s, "count": c}
                              for s, c in counts.most_common(50)]}]
        # per-task phase aggregation (`MRTask.profile()` records rolled up
        # process-wide) — the view that says WHERE task time went, next to
        # the stack samples that say where threads are right now
        from ..utils.profile import aggregate_snapshot

        return 200, {"nodes": nodes, "task_profiles": aggregate_snapshot()}
    if head == "WaterMeterCpuTicks":
        # `water/api/WaterMeterCpuTicksHandler` — /proc/stat per-core ticks
        ticks = []
        try:
            with open("/proc/stat") as f:
                for line in f:
                    if line.startswith("cpu") and line[3:4].isdigit():
                        vals = [int(x) for x in line.split()[1:5]]
                        ticks.append(vals)  # user, nice, sys, idle
        except OSError:
            pass
        return 200, {"cpu_ticks": ticks}
    if head == "WaterMeterIo":
        # `water/api/WaterMeterIoHandler` — process I/O counters
        io = {}
        try:
            with open("/proc/self/io") as f:
                for line in f:
                    k, _, v = line.partition(":")
                    io[k.strip()] = int(v)
        except OSError:
            pass
        return 200, {"persist_stats": [{
            "backend": "ice", "store_count": 0,
            "load_bytes": io.get("read_bytes", 0),
            "store_bytes": io.get("write_bytes", 0)}]}
    if head == "NetworkTest":
        from ..utils.devicebench import network_test

        return 200, network_test()
    if head == "Metadata":
        # `/3/Metadata/endpoints` + `/3/Metadata/schemas` — the
        # schema-metadata surface that drives client codegen
        # (`water/api/MetadataHandler`, consumed by h2o-bindings)
        sub = rest[1] if rest[1:] else "endpoints"
        if sub == "endpoints":
            if rest[2:]:
                # `GET /3/Metadata/endpoints/{path}` — one route, by index
                # or by url fragment (`MetadataHandler.fetchRoute`)
                which = urllib.parse.unquote("/".join(rest[2:]))
                if which.isdigit() and int(which) < len(_ROUTES_DOC):
                    return 200, {"routes": [_ROUTES_DOC[int(which)]]}
                hits = [r for r in _ROUTES_DOC
                        if which in r["url_pattern"]]
                if not hits:
                    return _err(404, f"no endpoint matching {which}")
                return 200, {"routes": hits}
            return 200, {"routes": _ROUTES_DOC}
        if sub == "schemas":
            # reference schema-class naming (`hex/schemas/*V3`): acronym
            # algos keep their acronym, the rest camel-case
            special = {"gbm": "GBM", "drf": "DRF", "glm": "GLM",
                       "gam": "GAM", "psvm": "PSVM", "svd": "SVD",
                       "pca": "PCA", "glrm": "GLRM", "coxph": "CoxPH",
                       "anovaglm": "ANOVAGLM", "dt": "DT",
                       "kmeans": "KMeans", "deeplearning": "DeepLearning",
                       "naivebayes": "NaiveBayes",
                       "isolationforest": "IsolationForest",
                       "extendedisolationforest": "ExtendedIsolationForest",
                       "upliftdrf": "UpliftDRF",
                       "targetencoder": "TargetEncoder",
                       "stackedensemble": "StackedEnsemble",
                       "rulefit": "RuleFit", "isotonic": "IsotonicRegression",
                       "modelselection": "ModelSelection",
                       "adaboost": "AdaBoost", "word2vec": "Word2Vec",
                       "aggregator": "Aggregator", "infogram": "Infogram",
                       "generic": "Generic", "xgboost": "XGBoost"}
            names = sorted(
                {f"{special.get(a, a.capitalize())}ParametersV3"
                 for a in registry.algo_names()}
                | {"CloudV3", "FramesV3", "FrameV3", "JobsV3", "JobV3",
                   "ModelsV3", "ModelSchemaV3", "ModelBuildersV3",
                   "RapidsSchemaV3", "ImportFilesV3", "ParseV3",
                   "ParseSetupV3", "InitIDV3", "ShutdownV3", "LogsV3",
                   "TimelineV3", "MetricsV3", "ProfilerV3", "NetworkTestV3",
                   "HealthV3", "SlowTracesV3",
                   "PartialDependenceV3", "PermutationVarImpV3",
                   "TwoDimTableV3", "KeyV3", "H2OErrorV3"})
            if rest[2:]:
                # `GET /3/Metadata/schemas/{schemaname}`
                want = urllib.parse.unquote(rest[2])
                if want not in names:
                    return _err(404, f"unknown schema {want}")
                return 200, {"schemas": [{"name": want, "version": 3}]}
            return 200, {"schemas": [{"name": n, "version": 3}
                                     for n in names]}
        if sub == "schemaclasses" and rest[2:]:
            # `GET /3/Metadata/schemaclasses/{classname}` — the schema-class
            # view is the schema view here (no Java class layer)
            want = urllib.parse.unquote(rest[2])
            return 200, {"schemas": [{"name": want, "version": 3}]}
        return _err(404, f"unknown metadata view {sub}")

    return _err(404, f"no route for {method} /{'/'.join(parts)}")


_ROUTES_DOC = [
    {"http_method": m, "url_pattern": u, "summary": s}
    for m, u, s in [
        ("GET", "/3/Cloud", "cluster status"),
        ("HEAD", "/3/Cloud", "cluster liveness, headers only"),
        ("GET", "/99/Sample", "cluster status (experimental alias)"),
        ("GET", "/3/About", "version info"),
        ("GET", "/3/KillMinus3", "log all stack traces"),
        ("POST", "/3/CloudLock", "lock the cloud with a reason"),
        ("POST", "/3/UnlockKeys", "unlock all write-locked keys"),
        ("GET", "/3/SessionProperties", "read a session property"),
        ("POST", "/3/SessionProperties", "set a session property"),
        ("GET", "/3/SteamMetrics", "cluster idle time for Steam"),
        ("POST", "/3/Shutdown", "shut the cluster down"),
        ("GET", "/3/ImportFiles", "import files by path/URI"),
        ("POST", "/3/PostFile", "upload raw bytes for parsing"),
        ("POST", "/3/PostFile.bin", "upload a binary artifact"),
        ("GET", "/99/Models.bin/{id}", "save a binary model server-side"),
        ("POST", "/99/Models.bin", "load a binary model server-side"),
        ("GET", "/3/Models.fetch.bin/{id}", "download a binary model"),
        ("POST", "/99/Models.upload.bin", "import an uploaded binary model"),
        ("POST", "/3/ParseSetup", "guess parse setup"),
        ("POST", "/3/Parse", "parse files into a Frame"),
        ("GET", "/3/Frames", "list frames"),
        ("GET", "/3/Frames/{id}", "frame detail with row preview"),
        ("GET", "/3/Frames/{id}/summary", "frame summary with column stats"),
        ("GET", "/3/Frames/{id}/light", "frame names/types only"),
        ("GET", "/3/Frames/{id}/columns", "frame columns"),
        ("GET", "/3/Frames/{id}/columns/{column}", "one column's stats"),
        ("GET", "/3/Frames/{id}/columns/{column}/domain",
         "categorical levels + counts"),
        ("GET", "/3/Frames/{id}/columns/{column}/summary",
         "column histogram + percentiles"),
        ("GET", "/3/FrameChunks/{id}", "chunk/shard layout of a frame"),
        ("POST", "/3/Frames/{id}/export", "export a frame to csv/parquet"),
        ("GET", "/3/Frames/{id}/export/{path}/overwrite/{force}",
         "export a frame (GET form)"),
        ("POST", "/3/Frames/{id}/save", "save a frame in binary form"),
        ("POST", "/3/Frames/load", "load a binary-saved frame"),
        ("DELETE", "/3/Frames/{id}", "remove a frame"),
        ("DELETE", "/3/Frames", "remove all frames"),
        ("GET", "/3/Find", "find a value in a frame"),
        ("POST", "/3/ImportFilesMulti", "import many paths/patterns"),
        ("GET", "/3/ModelBuilders", "list algorithms"),
        ("GET", "/3/ModelBuilders/{algo}", "algorithm parameter metadata"),
        ("POST", "/3/ModelBuilders/{algo}", "launch a training job"),
        ("POST", "/3/ModelBuilders/{algo}/parameters",
         "validate parameters without training"),
        ("GET", "/3/Word2VecSynonyms", "nearest words in a w2v embedding"),
        ("GET", "/3/Capabilities", "core + REST extension listing"),
        ("GET", "/3/Models", "list models"),
        ("GET", "/3/Models/{id}", "model detail"),
        ("GET", "/3/Models/{id}/mojo", "export MOJO"),
        ("GET", "/3/Models.java/{id}", "POJO scoring source"),
        ("GET", "/3/Models.java/{id}/preview", "POJO source preview"),
        ("GET", "/99/Models.mojo/{id}", "export MOJO server-side"),
        ("GET", "/99/Models/{id}/json", "model detail as exportable JSON"),
        ("POST", "/3/ModelBuilders/{algo}/model_id", "fresh unique model id"),
        ("DELETE", "/3/Models/{id}", "remove a model"),
        ("DELETE", "/3/Models", "remove all models"),
        ("POST", "/3/Serving/models/{id}",
         "register + warm up a model (or MOJO) for online scoring"),
        ("DELETE", "/3/Serving/models/{id}", "unregister a served model"),
        ("POST", "/3/Serving/score",
         "micro-batched row-dict scoring (429/408 on overload/deadline)"),
        ("GET", "/3/Serving/stats",
         "serving latency/throughput/occupancy/queue stats"),
        ("POST", "/3/Serving/routes/{endpoint}",
         "map an endpoint onto weighted variants (canary + shadow)"),
        ("GET", "/3/Serving/routes",
         "route table with per-variant divergence stats"),
        ("DELETE", "/3/Serving/routes/{endpoint}", "drop a route"),
        ("GET", "/3/Serving/control",
         "fleet placement/quota snapshot (admission control plane)"),
        ("POST", "/3/Predictions/models/{m}/frames/{f}", "score a frame"),
        ("POST", "/4/Predictions/models/{m}/frames/{f}",
         "score a frame asynchronously (job)"),
        ("POST", "/3/PartialDependence", "partial dependence"),
        ("GET", "/3/PartialDependence/{name}",
         "fetch a stored partial dependence result"),
        ("POST", "/3/Recovery/resume", "resume grids from a recovery dir"),
        ("POST", "/3/PermutationVarImp", "permutation importance"),
        ("GET", "/3/Jobs", "list jobs"),
        ("GET", "/3/Jobs/{id}", "poll a job"),
        ("POST", "/3/Jobs/{id}/cancel", "cancel a job"),
        ("POST", "/99/Rapids", "execute a rapids expression"),
        ("GET", "/99/Rapids/help", "rapids language primitives"),
        ("POST", "/3/InitID", "open a session"),
        ("GET", "/3/InitID", "open a session"),
        ("DELETE", "/3/InitID", "end a session"),
        ("GET", "/3/JStack", "thread stack dump"),
        ("GET", "/3/Logs", "node log ring"),
        ("GET", "/3/Logs/nodes/{nodeidx}/files/{name}",
         "one node's log file, filtered by level"),
        ("GET", "/3/Timeline",
         "typed event timeline ring (limit/kind; ?since=<seq> is the "
         "incremental poll cursor)"),
        ("GET", "/3/Health",
         "liveness/readiness with typed degradation reasons + SLO burn "
         "(devices, Cleaner headroom, serving queues, job heartbeats, "
         "watchdog trips)"),
        ("GET", "/3/Workload",
         "multi-tenant workload manager snapshot: tenants (weights, "
         "quotas, preempt/shed counters), scheduler entries and dispatch "
         "configuration"),
        ("POST", "/3/Workload",
         "configure a tenant: weight (fair-share tickets) and "
         "quota_fraction (share of the HBM reservation ledger)"),
        ("GET", "/3/SlowTraces",
         "tail-based slow-request capture ring: span trees + program "
         "dispatch walls of SLO p99 breachers"),
        ("DELETE", "/3/SlowTraces", "clear the slow-trace ring"),
        ("GET", "/3/Metrics",
         "unified telemetry registry (JSON; ?format=prometheus; "
         "?fleet=1 merges peer processes with per-process labels)"),
        ("GET", "/3/Programs",
         "program cost registry: per-executable XLA flops/bytes/memory, "
         "measured walls, roofline fraction"),
        ("GET", "/3/Flight", "flight-recorder bundle listing"),
        ("GET", "/3/Flight/{name}", "one flight bundle's content"),
        ("POST", "/3/Profiler/capture",
         "bounded live jax.profiler device capture (?ms=N)"),
        ("GET", "/3/Profiler", "stack samples + task phase aggregation"),
        ("GET", "/3/WaterMeterCpuTicks/{node}", "cpu tick counters"),
        ("GET", "/3/WaterMeterIo", "io counters"),
        ("GET", "/3/WaterMeterIo/{nodeidx}", "one node's io counters"),
        ("GET", "/3/NetworkTest", "device microbenchmarks"),
        ("GET", "/3/Typeahead/files", "path completion for import"),
        ("GET", "/3/Metadata/endpoints", "this listing"),
        ("GET", "/3/Metadata/endpoints/{path}", "one endpoint's doc"),
        ("GET", "/3/Metadata/schemas", "schema catalog"),
        ("GET", "/3/Metadata/schemas/{schemaname}", "one schema's doc"),
        ("GET", "/3/Metadata/schemaclasses/{classname}",
         "one schema class's doc"),
        ("GET", "/3/ModelMetrics", "list stored model metrics"),
        ("DELETE", "/3/ModelMetrics", "drop all cached metrics"),
        ("GET", "/3/ModelMetrics/models/{m}", "all metrics of one model"),
        ("DELETE", "/3/ModelMetrics/models/{m}",
         "drop one model's cached metrics"),
        ("GET", "/3/ModelMetrics/frames/{f}", "metrics computed on a frame"),
        ("DELETE", "/3/ModelMetrics/frames/{f}",
         "drop one frame's cached metrics"),
        ("GET", "/3/ModelMetrics/models/{m}/frames/{f}",
         "compute metrics of a model on a frame"),
        ("POST", "/3/ModelMetrics/models/{m}/frames/{f}",
         "recompute metrics, optionally storing predictions"),
        ("DELETE", "/3/ModelMetrics/models/{m}/frames/{f}",
         "drop one cached model-on-frame metric"),
        ("GET", "/3/ModelMetrics/frames/{f}/models/{m}",
         "metrics of a model on a frame (frame-first form)"),
        ("DELETE", "/3/ModelMetrics/frames/{f}/models/{m}",
         "drop one cached metric (frame-first form)"),
        ("POST",
         "/3/ModelMetrics/predictions_frame/{p}/actuals_frame/{a}",
         "make metrics from a predictions frame + actuals"),
        ("POST", "/3/CreateFrame", "synthesize a random frame"),
        ("POST", "/3/SplitFrame", "random-split a frame"),
        ("POST", "/3/Interaction", "combined categorical interaction columns"),
        ("POST", "/3/MissingInserter", "inject NAs into a frame"),
        ("GET", "/3/DownloadDataset", "frame as raw CSV"),
        ("GET", "/3/DownloadDataset.bin", "frame as raw CSV (streaming)"),
        ("GET", "/3/Tree", "inspect one tree of a tree model"),
        ("DELETE", "/3/DKV/{key}", "remove one key"),
        ("DELETE", "/3/DKV", "remove all keys"),
        ("POST", "/3/GarbageCollect", "force a gc cycle"),
        ("POST", "/3/LogAndEcho", "log and echo a message"),
        ("GET", "/3/Ping", "liveness + uptime"),
        ("POST", "/99/Grid/{algo}", "launch a grid search"),
        ("GET", "/99/Grids", "list grids"),
        ("GET", "/99/Grids/{id}", "grid detail with ranked models"),
        ("DELETE", "/99/Grids/{id}", "remove a grid"),
        ("POST", "/3/Grid.bin/import", "import an exported grid"),
        ("POST", "/3/Grid.bin/{id}/export", "export a grid and its models"),
        ("POST", "/3/FeatureInteraction",
         "xgbfi feature-interaction tables for a tree model"),
        ("POST", "/3/FriedmansPopescusH",
         "Friedman-Popescu H interaction statistic"),
        ("POST", "/3/SignificantRules", "RuleFit rule-importance table"),
        ("POST", "/99/Tabulate", "co-occurrence tabulation of two columns"),
        ("POST", "/99/DCTTransformer", "row-wise discrete cosine transform"),
        ("POST", "/99/ImportSQLTable", "import a SQL table (sqlite3)"),
        ("POST", "/3/ImportHiveTable", "import a Hive table (gated)"),
        ("POST", "/3/SaveToHiveTable", "export to a Hive table (gated)"),
        ("POST", "/3/ParseSVMLight", "parse SVMLight files directly"),
        ("POST", "/3/DecryptionSetup", "register an AES decryption tool"),
        ("GET", "/3/NodePersistentStorage/configured", "NPS availability"),
        ("GET", "/3/NodePersistentStorage/categories/{category}/exists",
         "category existence"),
        ("GET", "/3/NodePersistentStorage/categories/{category}"
                "/names/{name}/exists", "entry existence"),
        ("GET", "/3/NodePersistentStorage/{category}", "list a category"),
        ("GET", "/3/NodePersistentStorage/{category}/{name}",
         "fetch an entry"),
        ("POST", "/3/NodePersistentStorage/{category}",
         "store under a fresh uuid name"),
        ("POST", "/3/NodePersistentStorage/{category}/{name}",
         "store under a name"),
        ("DELETE", "/3/NodePersistentStorage/{category}/{name}",
         "delete an entry"),
        ("POST", "/99/Assembly", "fit a munging pipeline"),
        ("GET", "/99/Assembly.java/{assembly_id}/{file_name}",
         "pipeline as a self-contained Java class"),
        ("POST", "/99/AutoMLBuilder", "launch an AutoML run"),
        ("GET", "/99/AutoML/{id}", "AutoML run detail + event log"),
        ("GET", "/99/Leaderboards", "list AutoML projects"),
        ("GET", "/99/Leaderboards/{project}", "project leaderboard"),
    ]
]


def _make_metrics_route(rest: list[str], p: dict) -> tuple[int, dict]:
    """`POST /3/ModelMetrics/predictions_frame/{p}/actuals_frame/{a}`
    (`ModelMetricsHandler.make`, `ModelMetricsBinomial.make` et al.): build
    metrics straight from a predictions frame and an actuals column, no
    model involved. `domain` (class labels) picks the category: absent →
    regression (1 prediction column), 2 labels → binomial (1 column of
    class-1 probabilities), >2 → multinomial (k probability columns)."""
    import jax.numpy as jnp

    from ..models import metrics as M

    pid = urllib.parse.unquote(rest[2])
    if not (rest[3:] and rest[3] == "actuals_frame" and rest[4:]):
        return _err(404, "ModelMetrics make: "
                         "/predictions_frame/{p}/actuals_frame/{a}")
    aid = urllib.parse.unquote(rest[4])
    pred, act = STORE.get(pid), STORE.get(aid)
    if not isinstance(pred, Frame):
        return _err(404, f"predictions frame {pid} not found")
    if not isinstance(act, Frame):
        return _err(404, f"actuals frame {aid} not found")
    domain = p.get("domain")
    if isinstance(domain, str) and domain:
        domain = [d.strip(" '\"") for d in domain.strip("[]").split(",")]
    av = act.vecs[0]
    y = av.to_numpy()
    if domain:
        if av.domain and list(av.domain) != list(domain):
            # actuals encoded against their own domain: remap to the
            # caller's label order (the reference adapts via the domain)
            remap = {lv: i for i, lv in enumerate(domain)}
            codes = np.array([remap.get(av.domain[int(c)], -1)
                              if not np.isnan(c) else np.nan for c in y])
            y = codes
        if len(domain) == 2:
            if pred.ncol != 1:
                return _err(400, "binomial make: predictions_frame must "
                                 "have exactly one class-1 probability "
                                 "column")
            mm = M.make_binomial_metrics(jnp.asarray(y),
                                         jnp.asarray(pred.vecs[0].to_numpy()))
        else:
            if pred.ncol != len(domain):
                return _err(400, f"multinomial make: predictions_frame must "
                                 f"have {len(domain)} probability columns")
            probs = np.stack([v.to_numpy() for v in pred.vecs], axis=1)
            mm = M.make_multinomial_metrics(jnp.asarray(y),
                                            jnp.asarray(probs))
    else:
        if pred.ncol != 1:
            return _err(400, "regression make (domain=null): "
                             "predictions_frame must have exactly 1 column")
        mm = M.make_regression_metrics(jnp.asarray(y),
                                       jnp.asarray(pred.vecs[0].to_numpy()))
    return 200, {"model_metrics": [schemas.metrics_schema(mm) or {}]}


def _dest_name(path: str) -> str:

    base = os.path.basename(path)
    for ext in (".csv", ".gz", ".zip", ".parquet"):
        base = base.replace(ext, "")
    return base.replace(".", "_") + ".hex"


def _rapids_result(result) -> dict:
    """ValFrame/ValNum/ValStr serialization (`water/rapids/val/*`)."""
    if isinstance(result, dict) and result and all(
            isinstance(v, Frame) for v in result.values()):
        # ValMapFrame (`RapidsMapFrameV3`): named frames, DKV-published
        frames = []
        for v in result.values():
            STORE.put_keyed(v)
            frames.append({"key": schemas.key_schema(v.key)})
        return {"key": None, "string": None, "scalar": None,
                "map_keys": {"string": list(result.keys())},
                "frames": frames}
    if isinstance(result, Frame):
        STORE.put_keyed(result)
        return {"key": schemas.key_schema(result.key),
                "string": None, "scalar": None}
    if isinstance(result, Vec):
        fr = Frame([result.key or "C1"], [result])
        STORE.put_keyed(fr)
        return {"key": schemas.key_schema(fr.key),
                "string": None, "scalar": None}
    if isinstance(result, str):
        return {"key": None, "string": result, "scalar": None}
    if isinstance(result, (list, tuple)):
        return {"key": None, "string": None, "scalar": None,
                "values": schemas._clean(list(result))}
    if result is None:
        return {"key": None, "string": None, "scalar": None}
    return {"key": None, "string": None, "scalar": schemas._clean(result)}
