"""Server-side Assembly — `water/api/AssemblyHandler` + the
`water/rapids/transforms/*` munging pipeline behind `POST /99/Assembly` and
`GET /99/Assembly.java/{assembly_id}/{pojo_name}`.

The wire format is h2o-py's `H2OAssembly.fit`: a JSON-ish list of step
strings ``name__Class__ast__inplace__names`` where the rapids ast uses the
literal frame-id placeholder ``dummy`` (`H2OColOp.FRAME_ID_PLACEHOLDER`).
Fitting substitutes the target frame, executes each step's ast through a
rapids session, and applies the reference's inplace/append semantics;
`to_java` renders the fitted pipeline as one self-contained Java class
(`Transform.genClassImpl` role — structural Java, validated by tests the
same way the model POJOs are, there being no JVM in the image)."""

from __future__ import annotations

import re

import numpy as np

from ..backend.kvstore import STORE, Keyed, make_key
from ..frame.frame import Frame

#: rapids unary op → java.lang.Math expression template
_JAVA_UNOPS = {
    "cos": "Math.cos(%s)", "sin": "Math.sin(%s)", "tan": "Math.tan(%s)",
    "acos": "Math.acos(%s)", "asin": "Math.asin(%s)",
    "atan": "Math.atan(%s)", "cosh": "Math.cosh(%s)",
    "sinh": "Math.sinh(%s)", "tanh": "Math.tanh(%s)",
    "abs": "Math.abs(%s)", "sqrt": "Math.sqrt(%s)",
    "log": "Math.log(%s)", "log10": "Math.log10(%s)",
    "log2": "(Math.log(%s)/Math.log(2))",
    "log1p": "Math.log1p(%s)", "exp": "Math.exp(%s)",
    "expm1": "Math.expm1(%s)", "floor": "Math.floor(%s)",
    "ceiling": "Math.ceil(%s)", "sign": "Math.signum(%s)",
}
_JAVA_BINOPS = {"+": "+", "-": "-", "*": "*", "/": "/",
                "<": "<", "<=": "<=", ">": ">", ">=": ">=",
                "==": "==", "!=": "!="}


class AssemblyStep:
    def __init__(self, spec: str):
        parts = spec.split("__")
        if len(parts) != 5:
            raise ValueError(f"malformed assembly step {spec!r} "
                             "(name__class__ast__inplace__names)")
        self.name, self.cls, self.ast, inplace, names = parts
        self.inplace = inplace in ("True", "true")
        self.new_names = None if names == "|" else names.split("|")
        m = re.search(r"\(cols(?:_py)? dummy ['\"]([^'\"]+)['\"]\)",
                      self.ast)
        self.old_col = m.group(1) if m else None
        #: rapids operator at the head of the ast, e.g. cos/+/strlen
        m2 = re.match(r"\(\s*([^\s()]+)", self.ast)
        self.op = m2.group(1) if m2 else None


class Assembly(Keyed):
    """Fitted pipeline, keyed in the DKV (`water/rapids/Assembly`)."""

    def __init__(self, steps: list[AssemblyStep], key: str | None = None):
        super().__init__(key or make_key("assembly"))
        self.steps = steps
        self.scaler_stats: dict[int, tuple[list, list, list]] = {}

    # -- fit ------------------------------------------------------------------
    def fit(self, fr: Frame) -> Frame:
        from ..frame.vec import Vec
        from ..rapids.exec import Rapids, Session

        session = Session(f"{self.key}_fit")
        try:
            cur = fr
            for si, step in enumerate(self.steps):
                if step.cls == "H2OColSelect":
                    cols = re.findall(r"['\"]([^'\"]+)['\"]",
                                      step.ast.split("dummy", 1)[1])
                    cur = Frame(list(cols), [cur.vec(c) for c in cols])
                elif step.cls == "H2OScaler":
                    means, sds = [], []
                    vecs, names = [], []
                    for n in cur.names:
                        v = cur.vec(n)
                        r = v.rollups()
                        means.append(float(r.mean))
                        sds.append(float(r.sigma) or 1.0)
                        x = (v.to_numpy().astype(np.float64) - means[-1]) \
                            / (sds[-1] or 1.0)
                        vecs.append(Vec.from_numpy(x))
                        names.append(n)
                    self.scaler_stats[si] = (list(names), means, sds)
                    cur = Frame(names, vecs)
                elif step.cls in ("H2OColOp", "H2OBinaryOp"):
                    # bind a shallow COPY under the temp key — `cur` may
                    # still be the caller's STORE-resident frame, whose .key
                    # must never be rebound by a fit
                    tmp_key = make_key("assembly_in")
                    tmp_fr = Frame(list(cur.names), list(cur.vecs),
                                   key=tmp_key)
                    STORE.put(tmp_key, tmp_fr)
                    try:
                        ast = step.ast.replace("dummy", tmp_key)
                        res = Rapids(session).exec(ast)
                    finally:
                        STORE.remove(tmp_key, cascade=False)
                    if not isinstance(res, Frame):
                        from ..frame.vec import Vec as _V

                        if isinstance(res, _V):
                            res = Frame(["C1"], [res])
                        else:
                            raise ValueError(
                                f"step {step.name}: ast returned "
                                f"{type(res).__name__}, expected a column")
                    if step.inplace and step.old_col:
                        cur = Frame(list(cur.names),
                                    [res.vecs[0] if n == step.old_col
                                     else cur.vec(n) for n in cur.names])
                    else:
                        add = list(step.new_names or
                                   [f"{step.name}" for _ in res.names])
                        names = list(cur.names) + add[:len(res.names)]
                        cur = Frame(names, list(cur.vecs) + list(res.vecs))
                else:
                    raise ValueError(
                        f"unknown assembly transform {step.cls!r} "
                        "(H2OColSelect|H2OColOp|H2OBinaryOp|H2OScaler)")
            return cur
        finally:
            session.end()

    # -- codegen --------------------------------------------------------------
    def to_java(self, class_name: str) -> str:
        """One self-contained class: `Map<String,Object> transform(row)`
        chaining every step (the AssemblyHandler download surface)."""
        cls = re.sub(r"[^A-Za-z0-9_]", "_", class_name) or "Assembly"
        if not (cls[0].isalpha() or cls[0] == "_"):
            cls = "_" + cls
        body = []
        for si, step in enumerate(self.steps):
            if step.cls == "H2OColSelect":
                cols = re.findall(r"['\"]([^'\"]+)['\"]",
                                  step.ast.split("dummy", 1)[1])
                quoted = ", ".join(f'"{c}"' for c in cols)
                body.append(f"    row.keySet().retainAll("
                            f"java.util.Arrays.asList({quoted}));"
                            f" // {step.name}")
            elif step.cls == "H2OScaler":
                # explicit per-column statements: HashMap keySet() iteration
                # order is unspecified, so positional means_/sds_ indexing
                # would scale columns with the wrong statistics
                names, means, sds = self.scaler_stats.get(si, ([], [], []))
                for cn, m, sd in zip(names, means, sds):
                    body.append(f"    row.put(\"{cn}\", ((Double) "
                                f"row.get(\"{cn}\") - {m!r}) / {sd!r});"
                                f" // {step.name}")
            else:
                col = step.old_col or "C1"
                expr = f"(Double) row.get(\"{col}\")"
                if step.op in _JAVA_UNOPS:
                    expr = _JAVA_UNOPS[step.op] % expr
                elif step.op in _JAVA_BINOPS:
                    rhs = re.search(r"\)\s*([-0-9.eE]+)\s*\)?\s*$", step.ast)
                    rv = rhs.group(1) if rhs else "0"
                    jop = _JAVA_BINOPS[step.op]
                    cmp = f"({expr} {jop} {rv})"
                    expr = f"{cmp} ? 1.0 : 0.0" \
                        if jop in ("<", "<=", ">", ">=", "==", "!=") else cmp
                else:
                    expr = f"{expr} /* unmapped rapids op: {step.op} */"
                target = col if step.inplace else \
                    (step.new_names[0] if step.new_names else step.name)
                body.append(f"    row.put(\"{target}\", {expr});"
                            f" // {step.name}")
        lines = "\n".join(body)
        return (
            "import java.util.Map;\n\n"
            f"public class {cls} {{\n"
            "  public static Map<String, Object> transform("
            "Map<String, Object> row) {\n"
            f"{lines}\n"
            "    return row;\n"
            "  }\n"
            "}\n")


def parse_steps(steps_param) -> list[AssemblyStep]:
    """Decode the `steps` request param: either a JSON array or the
    stringified `["step","step"]` h2o-py sends (double-quoted items, inner
    quotes already flattened to single quotes by the client)."""
    if isinstance(steps_param, str):
        items = re.findall(r'"([^"]*)"', steps_param)
        if not items:
            raise ValueError("Assembly: no steps found in request")
    else:
        items = list(steps_param or [])
    return [AssemblyStep(s) for s in items]
