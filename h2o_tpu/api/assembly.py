"""H2OAssembly — multi-step frame munging pipelines.

Reference: `h2o-py/h2o/assembly.py` (H2OAssembly) +
`h2o-py/h2o/transforms/preprocessing.py` (H2OColSelect/H2OColOp/H2OBinaryOp).
Steps apply in order against the running frame; `fit` returns the munged
H2OFrame. Persistence is JSON (`save`/`load`) — the reference's
`to_pojo` Java-codegen export is out of scope (documented divergence: offline
munging replays through this client instead of a generated Java class).
"""

from __future__ import annotations

import json

from .client import H2OFrame


class H2OColSelect:
    """Keep only the named columns (`preprocessing.H2OColSelect`)."""

    def __init__(self, cols):
        self.cols = [cols] if isinstance(cols, str) else list(cols)

    def fit_transform(self, fr: H2OFrame) -> H2OFrame:
        return fr[self.cols]

    def to_spec(self) -> dict:
        return {"type": "H2OColSelect", "cols": self.cols}


class H2OColOp:
    """Apply a unary H2OFrame op to one column (`preprocessing.H2OColOp`).

    ``op`` is an H2OFrame method or its name (e.g. ``H2OFrame.cos`` /
    ``"cos"``); ``inplace`` replaces the column, else appends ``<col>0``.
    """

    def __init__(self, op, col: str, inplace: bool = True, new_col_name=None,
                 **params):
        self.op_name = op if isinstance(op, str) else op.__name__
        self.col = col
        self.inplace = inplace
        self.new_col_name = new_col_name
        self.params = params

    def fit_transform(self, fr: H2OFrame) -> H2OFrame:
        method = getattr(H2OFrame, self.op_name)
        out = method(fr[self.col], **self.params)
        if not self.inplace:
            name = self.new_col_name or f"{self.col}0"
            return fr.cbind(out.set_names([name]))
        # replace preserving column order
        cols = list(fr.columns)
        res = fr.drop(self.col).cbind(out.set_names([self.col]))
        return res[cols]

    def to_spec(self) -> dict:
        return {"type": "H2OColOp", "op": self.op_name, "col": self.col,
                "inplace": self.inplace, "new_col_name": self.new_col_name,
                "params": self.params}


class H2OBinaryOp:
    """Binary op between a column and a scalar or another column
    (`preprocessing.H2OBinaryOp`)."""

    def __init__(self, op: str, col: str, right=None, inplace: bool = True,
                 new_col_name=None):
        self.op = op  # "+", "-", "*", "/", ">", ...
        self.col = col
        self.right = right  # scalar or column name
        self.inplace = inplace
        self.new_col_name = new_col_name

    def fit_transform(self, fr: H2OFrame) -> H2OFrame:
        lhs = fr[self.col]
        rhs = fr[self.right] if isinstance(self.right, str) and \
            self.right in fr.columns else self.right
        out = lhs._binop(self.op, rhs)
        if not self.inplace:
            name = self.new_col_name or f"{self.col}0"
            return fr.cbind(out.set_names([name]))
        cols = list(fr.columns)
        res = fr.drop(self.col).cbind(out.set_names([self.col]))
        return res[cols]

    def to_spec(self) -> dict:
        return {"type": "H2OBinaryOp", "op": self.op, "col": self.col,
                "right": self.right, "inplace": self.inplace,
                "new_col_name": self.new_col_name}


_STEP_TYPES = {"H2OColSelect": H2OColSelect, "H2OColOp": H2OColOp,
               "H2OBinaryOp": H2OBinaryOp}


class H2OAssembly:
    """Ordered munging pipeline (`h2o-py/h2o/assembly.py`)."""

    def __init__(self, steps):
        self.steps = list(steps)  # [(name, transform), ...]
        self.fitted = False

    def fit(self, fr: H2OFrame) -> H2OFrame:
        for _, step in self.steps:
            fr = step.fit_transform(fr)
        self.fitted = True
        return fr

    # JSON persistence (the reference round-trips assemblies via POJO export;
    # here the declarative spec itself is the artifact)
    def save(self, path: str) -> str:
        spec = [{"name": n, **t.to_spec()} for n, t in self.steps]
        with open(path, "w") as f:
            json.dump({"steps": spec}, f, indent=1)
        return path

    @staticmethod
    def load(path: str) -> "H2OAssembly":
        with open(path) as f:
            spec = json.load(f)
        steps = []
        for st in spec["steps"]:
            cls = _STEP_TYPES[st["type"]]
            kw = {k: v for k, v in st.items() if k not in ("name", "type")}
            if cls is H2OColOp:
                steps.append((st["name"], H2OColOp(
                    kw["op"], kw["col"], kw["inplace"], kw["new_col_name"],
                    **(kw.get("params") or {}))))
            elif cls is H2OColSelect:
                steps.append((st["name"], H2OColSelect(kw["cols"])))
            else:
                steps.append((st["name"], H2OBinaryOp(
                    kw["op"], kw["col"], kw["right"], kw["inplace"],
                    kw["new_col_name"])))
        return H2OAssembly(steps)
