"""Job tracking — analog of `water/Job.java` (565 LoC).

The reference Job is a keyed, DKV-resident progress/cancel handle polled by
clients via `/3/Jobs` (`water/Job.java:199-224`). Here a Job wraps a Python
worker thread; progress is a float in [0,1] updated by the running builder, stop
requests are cooperative (builders poll ``stop_requested`` between iterations —
the same contract as `Job.stop_requested()` in the reference).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable

from .kvstore import Keyed, STORE


class JobCancelled(Exception):
    pass


class JobPreempted(Exception):
    """Cooperative chunk-boundary preemption (h2o_tpu/workload/): the
    training loop observed a preempt request at a safe boundary, force-
    checkpointed its state and unwound. Unlike a cancel, the work is NOT
    lost — ``recovery_dir`` names the checkpoint ``resume_training``
    replays to a bit-equal model once the job is re-admitted."""

    def __init__(self, job_key: str, recovery_dir: str | None):
        self.recovery_dir = recovery_dir
        super().__init__(
            f"{job_key} preempted at a chunk boundary"
            + (f" (state parked in {recovery_dir})" if recovery_dir else ""))


class JobTimeoutError(Exception):
    """Typed wall-clock expiry: raised by ``Job.join(timeout=...)`` when the
    wait runs out, and by ``Job.check_max_runtime()`` when the
    ``max_runtime_secs`` budget expires before a builder has any partial
    result worth keeping. Carries the numbers callers used to have to parse
    out of message text: ``elapsed_s`` spent vs ``budget_s`` allowed."""

    def __init__(self, what: str, elapsed_s: float, budget_s: float):
        self.elapsed_s = elapsed_s
        self.budget_s = budget_s
        super().__init__(
            f"{what}: {elapsed_s:.1f}s elapsed of a {budget_s:.1f}s budget")


#: long-lived server hygiene: XLA's compiler accumulates per-program state
#: across hundreds of distinct trainings and the CPU backend has been
#: observed to destabilize under it (the test suite resets per module —
#: `tests/conftest.py`; a server process needs the same bound). After every
#: H2O_TPU_CLEAR_CACHES_EVERY finished jobs (default 64, 0 disables) the
#: NEXT job boundary drops XLA's compilation caches — compiled programs are
#: re-derivable, so the only cost is a recompile on reuse.
_jobs_finished = 0
_jobs_lock = threading.Lock()


def _note_job_finished() -> None:
    global _jobs_finished
    from ..utils.knobs import raw

    # set-but-empty means DISABLED (int("" or 0) == 0 historically) — raw
    # keeps the unset/empty distinction get_int deliberately collapses
    every = int(raw("H2O_TPU_CLEAR_CACHES_EVERY", 64) or 0)
    if every <= 0:
        return
    with _jobs_lock:
        _jobs_finished += 1
        due = _jobs_finished % every == 0
    if due:
        import gc

        import jax

        # the AOT train-step executables are held DIRECTLY (not through a
        # jit cache), so jax.clear_caches() alone cannot release them —
        # drop the dict first or the per-program XLA state this bound
        # exists for re-accumulates through the AOT path. sys.modules
        # lookup, not an import: a process that never trained trees has
        # nothing to clear and must not pull the models stack in here.
        import sys as _sys

        gbm_mod = _sys.modules.get("h2o_tpu.models.gbm")
        if gbm_mod is not None:
            gbm_mod._AOT_STEP_CACHE.clear()
        # the sharded merge-expand programs are likewise held directly,
        # keyed by data-dependent output sizes — a long server joining
        # ever-different frames would otherwise accumulate executables
        merge_mod = _sys.modules.get("h2o_tpu.rapids.merge")
        if merge_mod is not None:
            merge_mod._EXPAND_PROGS.clear()
        # Tracked program wrappers (utils/programs.py) hold their compiled
        # executables directly too — same invisibility to jax.clear_caches
        # as the AOT caches above; records (pure numbers) survive, the
        # executables recompile on next dispatch
        prog_mod = _sys.modules.get("h2o_tpu.utils.programs")
        if prog_mod is not None:
            prog_mod.clear_compiled()
        gc.collect()
        jax.clear_caches()
        from ..utils.log import info

        info(f"cleared XLA compilation caches after {_jobs_finished} jobs "
             "(H2O_TPU_CLEAR_CACHES_EVERY)")


class Job(Keyed):
    CREATED = "CREATED"
    RUNNING = "RUNNING"
    DONE = "DONE"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"
    PREEMPTED = "PREEMPTED"

    #: priority classes, strongest first — the reference's H2O.submitTask
    #: priority queues collapsed to four lanes. The workload manager's
    #: lottery weights and arrival-preemption both key off the ordinal.
    PRIORITIES = ("realtime", "interactive", "batch", "background")

    def __init__(self, description: str = "", work: float = 1.0, dest_key: str | None = None):
        from ..utils import sanitizer

        super().__init__(prefix="job")
        self.description = description
        self.dest_key = dest_key
        # status/result/exception/progress are written by the worker
        # thread and read by pollers (REST /3/Jobs, join, progress) — one
        # lock makes every transition atomic and publishes result+status
        # together (graftlint unguarded-shared-field GL14-job-state)
        self._lock = sanitizer.make_lock("Job._state")
        self.status = Job.CREATED
        self.exception: BaseException | None = None
        self.traceback: str | None = None
        self._work_total = max(work, 1e-12)
        self._worked = 0.0
        self.progress_msg = ""
        self.start_time = 0.0
        self.end_time = 0.0
        self._stop_requested = False
        self._thread: threading.Thread | None = None
        self.result: Any = None
        #: workload-manager identity (h2o_tpu/workload/): which tenant
        #: owns this job and which priority lane it dispatches under.
        #: Stamped at submit; "default"/"batch" for legacy direct starts.
        self.tenant = "default"
        self.priority = "batch"
        #: True once the builder armed auto-recovery — only then can a
        #: preempt request be honored without losing work
        self.preemptible = False
        self._preempt_requested = False
        #: checkpoint dir captured when a preemption lands (the resume
        #: handle /3/Jobs pollers and the manager read back)
        self.preempt_dir: str | None = None
        #: last progress heartbeat (wall clock) — refreshed by update()
        #: and check_cancelled(), i.e. at every chunk/epoch boundary; the
        #: watchdog's hung-job detector and /3/Health's job check read it
        self.last_beat = time.time()
        STORE.put_keyed(self)

    def beat(self) -> None:
        """Mark forward progress (hung-job watchdog heartbeat)."""
        with self._lock:
            self.last_beat = time.time()

    # -- lifecycle -----------------------------------------------------------
    def start(self, fn: Callable[[], Any], background: bool = True) -> "Job":
        def _run():
            # job transitions are timeline events, like the reference's
            # TimeLine records of task start/finish packets. State writes
            # happen under the lock; the builder fn and the timeline
            # emits run OUTSIDE it (fn holds the lock for nothing, and
            # blocking-under-lock stays clean).
            from ..utils import timeline

            with self._lock:
                self.status = Job.RUNNING
                self.start_time = self.last_beat = time.time()
            timeline.record("job", "start", job=str(self.key),
                            desc=self.description)
            try:
                result = fn()
                with self._lock:
                    self.result = result
                    self.status = (Job.CANCELLED if self._stop_requested
                                   else Job.DONE)
            except JobCancelled:
                with self._lock:
                    self.status = Job.CANCELLED
            except JobPreempted as e:
                # not a failure: the boundary checkpointed, the workload
                # manager parks the entry and resumes it bit-equal later
                with self._lock:
                    self.preempt_dir = e.recovery_dir
                    self.status = Job.PREEMPTED
            except BaseException as e:  # noqa: BLE001 - mirror of Job exception capture
                with self._lock:
                    self.exception = e
                    self.traceback = traceback.format_exc()
                    self.status = Job.FAILED
            finally:
                with self._lock:
                    self.end_time = time.time()
                    status = self.status
                    run_s = round(self.end_time - self.start_time, 3)
                timeline.record("job", status, job=str(self.key),
                                run_s=run_s)
                _note_job_finished()

        if background:
            from ..utils import telemetry

            # the worker thread adopts the SUBMITTER's span context
            # (captured here, in the REST handler / caller thread), so a
            # background training job's spans nest under the request that
            # started it instead of minting an orphan trace id
            self._thread = threading.Thread(
                target=telemetry.carry_context(_run), daemon=True,
                name=self.key)
            self._thread.start()
        else:
            _run()
        return self

    def join(self, timeout: float | None = None) -> Any:
        """Wait for the job. A bounded wait that runs out raises the typed
        ``JobTimeoutError`` (the job keeps running — this is the WAIT's
        budget) instead of the old behavior of silently returning None with
        the job still live."""
        if self._thread is not None:
            self._thread.join(timeout)
            if timeout is not None and self._thread.is_alive():
                with self._lock:
                    status = self.status
                raise JobTimeoutError(
                    f"join on {self.key} ({self.description!r}) timed out "
                    f"with the job still {status}",
                    elapsed_s=self.run_time, budget_s=timeout)
        with self._lock:  # status+exception+result publish atomically
            status, exc, result = self.status, self.exception, self.result
        if status == Job.FAILED and exc is not None:
            raise exc
        return result

    # -- progress / cancel ---------------------------------------------------
    @property
    def progress(self) -> float:
        with self._lock:
            if self.status == Job.DONE:
                return 1.0
            return min(1.0, self._worked / self._work_total)

    def update(self, worked: float, msg: str = "") -> None:
        with self._lock:
            self._worked += worked
            self.last_beat = time.time()
            if msg:
                self.progress_msg = msg

    def stop(self) -> None:
        """Request cooperative cancellation (`Job.stop_requested` contract)."""
        with self._lock:
            self._stop_requested = True

    deadline: float | None = None     # wall-clock expiry (max_runtime_secs)
    max_runtime_s: float | None = None  # the armed budget, for typed errors

    def set_max_runtime(self, secs: float) -> None:
        """Arm the per-model time budget (`Model.Parameters.max_runtime_secs`
        — the reference stops training and keeps the partial model)."""
        if secs and secs > 0:
            self.max_runtime_s = float(secs)
            self.deadline = time.time() + secs

    def time_exceeded(self) -> bool:
        """Iterative builders poll this between iterations and BREAK (keeping
        the partial model), unlike check_cancelled which unwinds."""
        return self.deadline is not None and time.time() > self.deadline

    def check_max_runtime(self) -> None:
        """The typed sibling of ``time_exceeded`` for call sites with NO
        partial result to keep: an expired budget raises ``JobTimeoutError``
        (elapsed vs budget attached) instead of letting the build run
        arbitrarily past its contract before the first keepable iteration."""
        if self.time_exceeded():
            raise JobTimeoutError(
                f"job {self.key} ({self.description!r}) exceeded "
                f"max_runtime_secs before producing a keepable result",
                elapsed_s=self.run_time,
                budget_s=self.max_runtime_s or 0.0)

    @property
    def stop_requested(self) -> bool:
        with self._lock:
            return self._stop_requested

    # -- preemption (h2o_tpu/workload/) --------------------------------------
    def request_preempt(self) -> None:
        """Ask the running builder to yield at its NEXT chunk/epoch
        boundary (model_base._recovery_tick polls this). Cooperative like
        stop(), but the builder checkpoints and raises ``JobPreempted``
        instead of discarding work. A no-op on non-preemptible jobs —
        the boundary poll ignores the flag when no recovery is armed."""
        with self._lock:
            self._preempt_requested = True

    @property
    def preempt_requested(self) -> bool:
        with self._lock:
            return self._preempt_requested

    def clear_preempt(self) -> None:
        with self._lock:
            self._preempt_requested = False

    def check_cancelled(self) -> None:
        """Builders call this between iterations; raises to unwind the
        driver. Doubling as the heartbeat: reaching a cancellation poll
        IS forward progress, so every chunk/epoch boundary refreshes
        ``last_beat`` without a second instrumentation site."""
        self.beat()
        if self.stop_requested:
            raise JobCancelled(self.key)

    @property
    def run_time(self) -> float:
        with self._lock:
            end = self.end_time or time.time()
            return end - self.start_time if self.start_time else 0.0

    def is_running(self) -> bool:
        with self._lock:
            return self.status in (Job.CREATED, Job.RUNNING)


def any_running() -> bool:
    """True when any job is live — `/3/SteamMetrics` reports zero idle time
    while the cluster is working (`water/api/SteamMetricsHandler`)."""
    from .kvstore import STORE

    return any(j.is_running() for j in STORE.values(Job))
