"""HBM memory manager — the `water.Cleaner` / MemoryManager analog.

The reference runs a background Cleaner that, under memory pressure, swaps
least-recently-used values out of the K/V store to disk ("ice") and drops
cached POJOs (`water/Cleaner.java`, `water/MemoryManager.java`). The TPU
analog: bulk data lives in HBM as sharded `jax.Array`s hanging off Vecs, so
the Cleaner tracks every device-resident Vec (weakly), and when tracked bytes
exceed the budget it spills the coldest Vecs' device buffers to disk; the Vec
rehydrates transparently on next `.data` access (`frame/vec.py`).

Budget resolution order:
- ``H2O_TPU_HBM_LIMIT_BYTES`` env (tests pin this for determinism),
- ``jax.local_devices()[0].memory_stats()['bytes_limit']`` × 0.85 when the
  backend reports it (real TPUs do; the CPU test backend does not) — resolved
  once and cached,
- otherwise unlimited (the Cleaner only observes).

Accounting is a running counter (track/spill/rehydrate/GC adjust it), not a
per-call scan; spill files are removed on rehydrate, on overwrite, and by a
weakref finalizer when a spilled Vec is garbage-collected.
"""

from __future__ import annotations

import os
import tempfile
import threading
import weakref

import numpy as np

_UNRESOLVED = object()


def hbm_stats() -> dict | None:
    """Per-device memory stats when the backend exposes them (TPU does)."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    return dict(stats) if stats else None


def _vec_nbytes(arr) -> int:
    return 0 if arr is None else arr.size * arr.dtype.itemsize


class Cleaner:
    def __init__(self):
        import itertools

        self._vecs: "weakref.WeakValueDictionary[int, object]" = \
            weakref.WeakValueDictionary()
        self._lock = threading.RLock()
        # atomic in CPython — Vec.data reads must not contend on a lock
        self._clock = itertools.count(1)
        self._resident_bytes = 0
        self._sizes: dict[int, int] = {}  # id(vec) -> its resident bytes
        self._stats_limit = _UNRESOLVED  # memory_stats-based limit, cached
        self.spill_dir = None            # lazy tempdir
        self.spills = 0                  # observability (`/3/Cloud` swap ctr)

    # -- budget ---------------------------------------------------------------
    def limit_bytes(self) -> int | None:
        env = os.environ.get("H2O_TPU_HBM_LIMIT_BYTES")
        if env:
            return int(env)
        if self._stats_limit is _UNRESOLVED:
            stats = hbm_stats()
            self._stats_limit = (int(stats["bytes_limit"] * 0.85)
                                 if stats and stats.get("bytes_limit")
                                 else None)
        return self._stats_limit

    # -- tracking -------------------------------------------------------------
    def touch(self, vec) -> int:
        """Record an access; returns the new LRU clock stamp (lock-free)."""
        return next(self._clock)

    def track(self, vec, nbytes: int) -> None:
        """Register a newly device-resident Vec (construction / rehydrate /
        setter). The caller holds the vec's own lock if one exists."""
        vid = id(vec)
        with self._lock:
            if vid not in self._vecs:
                self._vecs[vid] = vec
                weakref.finalize(vec, self._on_dead, vid,
                                 getattr(vec, "key", None))
            self._resident_bytes += nbytes
            self._sizes[vid] = self._sizes.get(vid, 0) + nbytes
        self.maybe_sweep(exclude=vid)

    def note_freed(self, vec, nbytes: int,
                   spill_path: str | None = None) -> None:
        """A device buffer went away outside a sweep (setter overwrite)."""
        with self._lock:
            self._resident_bytes -= nbytes
            vid = id(vec)
            if vid in self._sizes:
                self._sizes[vid] -= nbytes
        if spill_path:
            self._remove_ice(spill_path)

    def _on_dead(self, vid, key):
        # a spilled vec's ice file dies with it, and whatever bytes it still
        # held resident leave the counter — otherwise churned temporaries
        # drift the counter upward and every construction pays a recount
        with self._lock:
            self._resident_bytes -= self._sizes.pop(vid, 0)
        if key and self.spill_dir:
            self._remove_ice(os.path.join(self.spill_dir, f"{key}.npy"))

    @staticmethod
    def _remove_ice(path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass

    def tracked_bytes(self) -> int:
        with self._lock:
            return max(self._resident_bytes, 0)

    def _recount(self) -> tuple[int, dict]:
        """Exact resync against live vecs, DEDUPED by device buffer: several
        Vecs may wrap the same jax array (as_factor views etc.) — it holds
        HBM once and spilling one alias frees nothing. Returns (total bytes,
        {buffer id: alias count}); corrects drift from GC'd arrays."""
        with self._lock:
            vecs = list(self._vecs.values())
            seen: dict = {}
            total = 0
            sizes: dict[int, int] = {}
            for v in vecs:
                arr = getattr(v, "_data", None)
                if arr is None:
                    continue
                bid = id(arr)
                if bid not in seen:
                    total += _vec_nbytes(arr)
                seen[bid] = seen.get(bid, 0) + 1
                sizes[id(v)] = _vec_nbytes(arr)
            self._resident_bytes = total
            self._sizes = sizes
            return total, seen

    # -- the sweep (Cleaner.run's store_clean pass) ---------------------------
    def maybe_sweep(self, exclude: int | None = None) -> int:
        limit = self.limit_bytes()
        if limit is None:
            return 0
        if self.tracked_bytes() <= limit:
            return 0
        used, aliases = self._recount()
        if used <= limit:
            return 0
        with self._lock:
            vecs = sorted((v for v in self._vecs.values()
                           if getattr(v, "_data", None) is not None
                           and id(v) != exclude
                           # spilling an aliased buffer frees no HBM
                           and aliases.get(id(v._data), 1) == 1),
                          key=lambda v: getattr(v, "_last_access", 0))
        freed = 0
        for v in vecs:
            if used - freed <= limit:
                break
            freed += self._spill(v)
        return freed

    def _spill(self, vec) -> int:
        # non-blocking: a vec whose lock is held is in active use — skip it
        # (this also prevents lock-order inversion against a rehydrating
        # reader that holds its vec lock while sweeping others)
        if not vec._lock.acquire(blocking=False):
            return 0
        try:
            return self._spill_locked(vec)
        finally:
            vec._lock.release()

    def _spill_locked(self, vec) -> int:
        arr = vec._data
        if arr is None:
            return 0
        nbytes = _vec_nbytes(arr)
        if self.spill_dir is None:
            self.spill_dir = tempfile.mkdtemp(prefix="h2o_tpu_ice_")
        path = os.path.join(self.spill_dir, f"{vec.key}.npy")
        np.save(path, np.asarray(arr))  # device -> host -> ice
        vec._spill_path = path
        vec._data = None                # HBM buffer becomes collectable
        with self._lock:
            self._resident_bytes -= nbytes
            vid = id(vec)
            if vid in self._sizes:
                self._sizes[vid] -= nbytes
            self.spills += 1
        return nbytes


#: process-global Cleaner (the `H2O.CLEANER` role)
CLEANER = Cleaner()
