"""HBM memory manager — the `water.Cleaner` / MemoryManager analog.

The reference runs a background Cleaner that, under memory pressure, swaps
least-recently-used values out of the K/V store to disk ("ice") and drops
cached POJOs (`water/Cleaner.java`, `water/MemoryManager.java`). The TPU
analog: bulk data lives in HBM as sharded `jax.Array`s hanging off Vecs, so
the Cleaner tracks every device-resident Vec (weakly), and when tracked bytes
exceed the budget it spills the coldest Vecs' device buffers to disk; the Vec
rehydrates transparently on next `.data` access (`frame/vec.py`).

Budget resolution order:
- ``H2O_TPU_HBM_LIMIT_BYTES`` env (tests pin this for determinism),
- ``jax.local_devices()[0].memory_stats()['bytes_limit']`` × 0.85 when the
  backend reports it (real TPUs do; the CPU test backend does not) — resolved
  once and cached,
- on a TPU backend whose transport hides memory_stats: the chip's physical
  HBM from a ``device_kind`` lookup table × 0.85 (``device_hbm_bytes``),
- otherwise unlimited (the Cleaner only observes).

``hbm_budget_bytes()`` exposes the same resolution (sans the TPU-only
last-resort) to compute planners — the tree engine's histogram row blocks,
the binning sketch's column blocks, and the frame rollup batcher all size
their intermediates from it instead of hardcoded constants.

Accounting is a running counter (track/spill/rehydrate/GC adjust it), not a
per-call scan; spill files are removed on rehydrate, on overwrite, and by a
weakref finalizer when a spilled Vec is garbage-collected.

The tracked protocol is duck-typed on Vec's fields (``_data``, ``_lock``,
``_spill_path``, ``_last_access``, ``key``), so the chunk store's coded
columns and binned views (`frame/chunks.py` CodedVec/BinnedView — Vec
subclasses whose ``_data`` holds CODES, not f32) ride the same ledger:
their coded bytes debit ``hbm_budget_bytes()`` while alive, and they
spill/rehydrate by LRU like raw columns (reload sharding comes from
``Vec._put_sharding`` — const/sparse payloads replicate).
"""

from __future__ import annotations

import os
import tempfile
import threading
import weakref

import numpy as np

from ..utils import knobs, telemetry
from ..utils.sanitizer import guarded_by

_UNRESOLVED = object()


def hbm_stats() -> dict | None:
    """Per-device memory stats when the backend exposes them (TPU does)."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    return dict(stats) if stats else None


#: per-device HBM by device_kind substring (GiB), most specific first — the
#: fallback when the transport hides memory_stats. v2/v3 devices are cores
#: (8/16 GiB each); v4+ are chips.
_KIND_HBM_GIB = (("v6 lite", 32), ("v6e", 32), ("v5 lite", 16), ("v5e", 16),
                 ("v5p", 95), ("v5", 95), ("v4", 32), ("v3", 16), ("v2", 8))

_HW_BYTES = _UNRESOLVED  # cached device_hbm_bytes result


def device_hbm_bytes() -> int | None:
    """Physical per-device HBM: ``memory_stats()['bytes_limit']`` when the
    backend reports it, else a ``device_kind`` table lookup (remote device
    tunnels hide memory_stats but still name the chip), else None (CPU and
    unknown accelerators)."""
    global _HW_BYTES
    if _HW_BYTES is not _UNRESOLVED:
        return _HW_BYTES
    stats = hbm_stats()
    if stats and stats.get("bytes_limit"):
        _HW_BYTES = int(stats["bytes_limit"])
        return _HW_BYTES
    import jax

    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        kind = ""
    _HW_BYTES = next((gib << 30 for tag, gib in _KIND_HBM_GIB if tag in kind),
                     None)
    return _HW_BYTES


def hbm_budget_bytes() -> int | None:
    """LIVE HBM planning budget for compute intermediates: 85% of physical
    (the Cleaner's headroom) minus the bytes the Cleaner currently tracks as
    device-resident and minus outstanding serving reservations
    (:func:`reserve_bytes`), floored at 1/16 of physical so planners always
    get a workable (if small) budget under pressure.
    ``H2O_TPU_HBM_LIMIT_BYTES`` pins the PRE-reservation value exactly (no
    residency adjustment — tests mock budgets with it); None when no
    accelerator budget is resolvable (planners fall back to their own
    conservative defaults)."""
    env = knobs.raw("H2O_TPU_HBM_LIMIT_BYTES")
    if env and int(env) > 0:  # 0 = backend resolution (optargs contract)
        return int(env)
    hw = device_hbm_bytes()
    if not hw:
        return None
    return max(int(hw * 0.85) - CLEANER.tracked_bytes() - reserved_bytes(),
               hw >> 4)


# ---------------------------------------------------------------------------
# reservation ledger — the serving control plane's quota hook
# ---------------------------------------------------------------------------
#: owner -> bytes reserved out of the shared HBM pool. The serving control
#: plane (serving/control.py) reserves each PLACED model's estimated
#: residency here, so training planners (hbm_budget_bytes) and the Cleaner's
#: sweep threshold (Cleaner.limit_bytes) both see serving occupancy through
#: the ONE existing accounting instead of a parallel serving-only ledger.
_RESERVATIONS: dict[str, int] = {}
_RES_LOCK = threading.Lock()


def reserve_bytes(owner: str, nbytes: int) -> None:
    """Reserve ``nbytes`` of the shared HBM pool under ``owner`` (replacing
    any prior reservation for the same owner)."""
    with _RES_LOCK:
        _RESERVATIONS[owner] = max(int(nbytes), 0)


def release_bytes(owner: str) -> int:
    """Drop ``owner``'s reservation; returns the bytes freed (0 if none)."""
    with _RES_LOCK:
        return _RESERVATIONS.pop(owner, 0)


def reserved_bytes() -> int:
    """Total outstanding reservations (0 when serving placed nothing)."""
    with _RES_LOCK:
        return sum(_RESERVATIONS.values())


def _vec_nbytes(arr) -> int:
    return 0 if arr is None else arr.size * arr.dtype.itemsize


def _arr_device_bytes(arr) -> dict:
    """Per-DEVICE footprint — the distinction the process-global counter
    cannot see and a per-chip HBM budget lives or dies by. Shared with the
    bench accounting via the one mesh-layer implementation."""
    from ..parallel.mesh import device_nbytes

    return device_nbytes(arr)


class Cleaner:
    def __init__(self):
        import itertools

        from ..utils.sanitizer import make_lock

        self._vecs: "weakref.WeakValueDictionary[int, object]" = \
            weakref.WeakValueDictionary()
        self._lock = make_lock("Cleaner._lock", rlock=True)
        # atomic in CPython — Vec.data reads must not contend on a lock
        self._clock = itertools.count(1)
        # ledger keys are per-vec monotonic tokens, NOT id(vec): CPython can
        # reuse a dead vec's address for a new vec before the old finalizer
        # fires, and a stale _on_dead must never pop the new vec's bytes
        self._token_ctr = itertools.count(1)
        self._resident_bytes = 0
        self._sizes: dict[int, int] = {}  # vec token -> its resident bytes
        # per-DEVICE residency (the multi-chip split of the live-bytes
        # gauge): token -> {device label -> bytes}, plus the live totals
        # and process-lifetime peaks the prometheus provider exposes as
        # h2o_tpu_cleaner_device_live_bytes{device="..."}
        self._dev_by_tok: dict[int, dict] = {}
        self._dev_live: dict[str, int] = {}
        self._dev_peak: dict[str, int] = {}
        self._stats_limit = _UNRESOLVED  # memory_stats-based limit, cached
        self.spill_dir = None            # lazy tempdir
        self.spills = 0                  # observability (`/3/Cloud` swap ctr)

    # -- budget ---------------------------------------------------------------
    def limit_bytes(self) -> int | None:
        """Sweep threshold for tracked Vec residency: the resolved HBM
        budget minus outstanding serving reservations (floored at 1/16 of
        the base so a quota-heavy fleet can't drive the Cleaner into a
        spill storm) — frames yield HBM to placed serving models through
        the same ledger planners read."""
        base = self._base_limit_bytes()
        if base is None:
            return None
        res = reserved_bytes()
        return max(base - res, base >> 4) if res else base

    def _base_limit_bytes(self) -> int | None:
        env = knobs.raw("H2O_TPU_HBM_LIMIT_BYTES")
        if env and int(env) > 0:  # 0 = backend resolution (optargs contract)
            telemetry.set_gauge("cleaner.hbm.limit.bytes", int(env))
            return int(env)
        if self._stats_limit is not _UNRESOLVED:
            # resolved: lock-free read of an immutable value — planner
            # budget queries must not contend with a sweep holding the
            # ledger lock
            return self._stats_limit
        with self._lock:
            # first resolve under the ledger lock: serving admission and a
            # training planner racing it must not both resolve (and
            # double-emit the gauge)
            return self._resolve_stats_limit_locked()

    @guarded_by("_lock")
    def _resolve_stats_limit_locked(self) -> int | None:
        if self._stats_limit is _UNRESOLVED:
            stats = hbm_stats()
            limit = (int(stats["bytes_limit"] * 0.85)
                     if stats and stats.get("bytes_limit") else None)
            if limit is None:
                import jax

                if jax.default_backend() == "tpu":
                    # some transports (remote device tunnels) hide
                    # memory_stats; derive the budget from the chip's
                    # device_kind (a v5p must not spill at a v5e budget),
                    # keeping the smallest current-generation chip (v5e:
                    # 16 GiB) only as the last resort for unknown kinds
                    hw = device_hbm_bytes() or 16 * (1 << 30)
                    limit = int(hw * 0.85)
            self._stats_limit = limit
            telemetry.set_gauge("cleaner.hbm.limit.bytes", limit or 0)
        return self._stats_limit

    # -- tracking -------------------------------------------------------------
    def touch(self, vec) -> int:
        """Record an access; returns the new LRU clock stamp (lock-free)."""
        return next(self._clock)

    def _token(self, vec) -> int:
        tok = getattr(vec, "_cleaner_token", None)
        if tok is None:
            tok = next(self._token_ctr)
            vec._cleaner_token = tok
        return tok

    def track(self, vec, nbytes: int) -> None:
        """Register a newly device-resident Vec (construction / rehydrate /
        setter). The caller holds the vec's own lock if one exists."""
        with self._lock:
            tok = self._token(vec)
            if tok not in self._vecs:
                self._vecs[tok] = vec
                weakref.finalize(vec, self._on_dead, tok,
                                 getattr(vec, "key", None))
            self._resident_bytes += nbytes
            self._sizes[tok] = self._sizes.get(tok, 0) + nbytes
            dm = _arr_device_bytes(getattr(vec, "_data", None))
            if dm:
                per = self._dev_by_tok.setdefault(tok, {})
                for d, b in dm.items():
                    per[d] = per.get(d, 0) + b
                    live = self._dev_live.get(d, 0) + b
                    self._dev_live[d] = live
                    if live > self._dev_peak.get(d, 0):
                        self._dev_peak[d] = live
            telemetry.set_gauge("cleaner.hbm.live.bytes",
                                max(self._resident_bytes, 0))
        self.maybe_sweep(exclude=tok)

    def note_freed(self, vec, nbytes: int,
                   spill_path: str | None = None) -> None:
        """A device buffer went away outside a sweep (setter overwrite)."""
        self._debit(vec, nbytes)
        if spill_path:
            self._remove_ice(spill_path)

    def _debit(self, vec, nbytes: int) -> None:
        with self._lock:
            self._resident_bytes -= nbytes
            tok = getattr(vec, "_cleaner_token", None)
            if tok in self._sizes:
                before = self._sizes[tok]
                after = max(before - nbytes, 0)
                self._sizes[tok] = after
                self._dev_release(tok, after / before if before > 0 else 0.0)
            telemetry.set_gauge("cleaner.hbm.live.bytes",
                                max(self._resident_bytes, 0))

    @guarded_by("_lock")
    def _dev_release(self, tok, keep_frac: float) -> None:
        """Scale a token's per-device residency by ``keep_frac`` (0 drops
        it) and debit the live per-device totals. Lock held by caller
        (asserted under H2O_TPU_SANITIZE=guards)."""
        per = self._dev_by_tok.get(tok)
        if per is None:
            return
        if keep_frac <= 0:
            self._dev_by_tok.pop(tok, None)
            for d, b in per.items():
                self._dev_live[d] = max(self._dev_live.get(d, 0) - b, 0)
            return
        for d, b in list(per.items()):
            kept = int(b * keep_frac)
            per[d] = kept
            self._dev_live[d] = max(self._dev_live.get(d, 0) - (b - kept), 0)

    def _on_dead(self, tok, key):
        # a spilled vec's ice file dies with it, and whatever bytes it still
        # held resident leave the counter — otherwise churned temporaries
        # drift the counter upward and every construction pays a recount
        with self._lock:
            self._resident_bytes -= self._sizes.pop(tok, 0)
            self._dev_release(tok, 0.0)
            telemetry.set_gauge("cleaner.hbm.live.bytes",
                                max(self._resident_bytes, 0))
        if key and self.spill_dir:
            self._remove_ice(os.path.join(self.spill_dir, f"{key}.npy"))

    @staticmethod
    def _remove_ice(path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass

    def tracked_bytes(self) -> int:
        with self._lock:
            return max(self._resident_bytes, 0)

    def device_bytes(self) -> dict:
        """Live tracked bytes PER DEVICE ({device label: bytes}) — the
        multi-chip residency split (a replicated array books its full
        nbytes on every device; a row-sharded one ~1/n_shards each)."""
        with self._lock:
            return {d: b for d, b in self._dev_live.items() if b > 0}

    def device_peak_bytes(self) -> dict:
        """Process-lifetime per-device residency peaks (the per-chip HBM
        watermark the bench `sharded` leg and /3/Metrics report)."""
        with self._lock:
            return dict(self._dev_peak)

    def _recount(self) -> tuple[int, dict]:
        """Exact resync against live vecs, DEDUPED by device buffer: several
        Vecs may wrap the same jax array (as_factor views etc.) — it holds
        HBM once and spilling one alias frees nothing. Returns (total bytes,
        {buffer id: alias count}); corrects drift from GC'd arrays."""
        with self._lock:
            vecs = [v for v in self._vecs.values()
                    if getattr(v, "_data", None) is not None]
            seen: dict = {}
            total = 0
            for v in vecs:
                bid = id(v._data)
                if bid not in seen:
                    total += _vec_nbytes(v._data)
                seen[bid] = seen.get(bid, 0) + 1
            # a shared buffer's bytes are SPLIT across its alias tokens so the
            # per-token ledger sums to _resident_bytes: when one alias dies,
            # _on_dead debits only its share, not the whole still-live buffer
            sizes: dict[int, int] = {}
            dev_by_tok: dict[int, dict] = {}
            dev_live: dict[str, int] = {}
            for v in vecs:
                tok = self._token(v)
                aliases = seen[id(v._data)]
                sizes[tok] = _vec_nbytes(v._data) // aliases
                dm = {d: b // aliases
                      for d, b in _arr_device_bytes(v._data).items()}
                dev_by_tok[tok] = dm
                for d, b in dm.items():
                    dev_live[d] = dev_live.get(d, 0) + b
            self._resident_bytes = total
            self._sizes = sizes
            self._dev_by_tok = dev_by_tok
            self._dev_live = dev_live
            for d, b in dev_live.items():
                if b > self._dev_peak.get(d, 0):
                    self._dev_peak[d] = b
            return total, seen

    # -- the sweep (Cleaner.run's store_clean pass) ---------------------------
    def emergency_sweep(self, exclude: int | None = None) -> int:
        """Spill EVERYTHING spillable except ``exclude`` — the rehydrate
        path's response to a device OOM (`frame/vec.py`): free the maximum
        HBM regardless of budget, so the failed device_put can retry."""
        from ..utils import timeline

        telemetry.inc("cleaner.emergency_sweep.count")
        freed = self.maybe_sweep(exclude=exclude, target_bytes=0)
        timeline.record("cleaner", "emergency_sweep", freed_bytes=freed)
        return freed

    def maybe_sweep(self, exclude: int | None = None,
                    target_bytes: int | None = None) -> int:
        from ..utils import sanitizer

        limit = self.limit_bytes() if target_bytes is None else target_bytes
        if limit is None:
            return 0
        if self.tracked_bytes() <= limit:
            return 0
        used, aliases = self._recount()
        if used <= limit:
            return 0
        with self._lock:
            vecs = sorted((v for v in self._vecs.values()
                           if getattr(v, "_data", None) is not None
                           and getattr(v, "_cleaner_token", None) != exclude
                           # pinned views (a BinnedView mid-train) stay: the
                           # trainer holds the buffer anyway, so spilling
                           # would debit the ledger while freeing no HBM
                           and not getattr(v, "_pinned", False)
                           # spilling an aliased buffer frees no HBM
                           and aliases.get(id(v._data), 1) == 1),
                          key=lambda v: getattr(v, "_last_access", 0))
        freed = 0
        # H2O_TPU_SANITIZE=transfers: the sweep's only sanctioned
        # device->host move is the spill's explicit device_get — an
        # implicit conversion anywhere in the loop raises typed
        with sanitizer.transfer_scope("cleaner.sweep"):
            for v in vecs:
                if used - freed <= limit:
                    break
                freed += self._spill(v)
        return freed

    def _spill(self, vec) -> int:
        # non-blocking: a vec whose lock is held is in active use — skip it
        # (this also prevents lock-order inversion against a rehydrating
        # reader that holds its vec lock while sweeping others)
        if not vec._lock.acquire(blocking=False):
            return 0
        try:
            return self._spill_locked(vec)
        finally:
            vec._lock.release()

    def _spill_locked(self, vec) -> int:
        import jax

        from ..utils import failpoints

        failpoints.hit("cleaner.spill")
        arr = vec._data
        if arr is None:
            return 0
        nbytes = _vec_nbytes(arr)
        if self.spill_dir is None:
            self.spill_dir = tempfile.mkdtemp(prefix="h2o_tpu_ice_")
        path = os.path.join(self.spill_dir, f"{vec.key}.npy")
        # EXPLICIT device->host fetch (not np.asarray): the spill is a
        # declared sync point, so it stays silent under the sweep's
        # transfer guard and the graftlint host-transfer-in-hot-path rule
        np.save(path, jax.device_get(arr))  # device -> host -> ice
        vec._spill_path = path
        vec._data = None                # HBM buffer becomes collectable
        self._debit(vec, nbytes)
        with self._lock:
            self.spills += 1
        telemetry.inc("cleaner.spill.count")
        telemetry.inc("cleaner.spill.bytes", nbytes)
        return nbytes


#: process-global Cleaner (the `H2O.CLEANER` role)
CLEANER = Cleaner()


def _prometheus_device_lines() -> list:
    """Per-device label dimension for the Prometheus exposition (the PR 6
    residual unblocked by multi-chip sharding): the process-global
    ``h2o_tpu_cleaner_hbm_live_bytes`` stays in the registry — one
    accounting — and these ``{device="..."}`` families split it per chip,
    straight off the Cleaner's per-device ledger (the serving per-model
    provider pattern)."""
    live = CLEANER.device_bytes()
    peak = CLEANER.device_peak_bytes()
    if not live and not peak:
        return []
    esc = telemetry.prom_label_escape
    lines = [
        "# HELP h2o_tpu_cleaner_device_live_bytes tracked device-resident "
        "bytes per device (replicated arrays count on every device)",
        "# TYPE h2o_tpu_cleaner_device_live_bytes gauge",
    ]
    for d, b in sorted(live.items()):
        lines.append(
            f'h2o_tpu_cleaner_device_live_bytes{{device="{esc(d)}"}} {b:g}')
    lines += [
        "# HELP h2o_tpu_cleaner_device_peak_bytes process-lifetime peak "
        "of tracked bytes per device (the per-chip HBM watermark)",
        "# TYPE h2o_tpu_cleaner_device_peak_bytes gauge",
    ]
    for d, b in sorted(peak.items()):
        lines.append(
            f'h2o_tpu_cleaner_device_peak_bytes{{device="{esc(d)}"}} {b:g}')
    return lines


telemetry.add_prometheus_provider(_prometheus_device_lines)


def base_hbm_limit_bytes() -> int | None:
    """The resolved HBM budget BEFORE reservation subtraction — the number
    the serving control plane takes its quota fraction of (taking it from
    the post-reservation limit would shrink serving's own quota as serving
    places models: a feedback loop, not an accounting)."""
    return CLEANER._base_limit_bytes()
