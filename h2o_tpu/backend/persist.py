"""Persistence: binary model export/import, frame save/load, recovery dirs.

Analog of the reference's checkpoint/persist layer (SURVEY.md §5.4):
- binary model export/import (`hex/Model.java` exportBinaryModel /
  `water/api/ModelsHandler` importModel),
- frame save/load (`water/fvec/persist/FramePersist.java`),
- the auto-recovery directory protocol used by grid search
  (`hex/faulttolerance/Recovery.java:20-40`).

Formats are host-side and self-contained: frames go to one ``.npz`` (columns)
plus a JSON sidecar (names/types/domains); models are pickles whose device
arrays were pulled back to numpy and whose attached Frames (training/validation
— cluster state, not model state) are stripped, mirroring the reference, which
also persists models without their training data. A persisted model scores
after load in a fresh process: jnp ops consume the numpy arrays directly.
"""

from __future__ import annotations

import json
import os
import pickle

import jax
import numpy as np


# ---------------------------------------------------------------------------
# frames (`water/fvec/persist/FramePersist.java`)
# ---------------------------------------------------------------------------
def save_frame(fr, path: str) -> str:
    """Write a Frame to ``path`` (.npz + .json sidecar). Returns path."""
    from ..frame.vec import T_STR

    base = path[:-4] if path.endswith(".npz") else path
    arrays, meta = {}, {"names": fr.names, "nrow": fr.nrow, "cols": []}
    for i, name in enumerate(fr.names):
        v = fr.vec(name)
        cmeta = {"name": name, "type": v.type, "domain": v.domain}
        if v.is_string():
            arrays[f"c{i}"] = np.asarray(
                ["" if x is None else str(x) for x in v.host_data])
            arrays[f"c{i}_na"] = np.asarray(
                [x is None for x in v.host_data], dtype=bool)
        else:
            arrays[f"c{i}"] = v.to_numpy()
        meta["cols"].append(cmeta)
    np.savez_compressed(base + ".npz", **arrays)
    with open(base + ".json", "w") as f:
        json.dump(meta, f)
    return base + ".npz"


def load_frame(path: str, key: str | None = None):
    from ..frame.frame import Frame
    from ..frame.vec import T_STR, Vec

    base = path[:-4] if path.endswith(".npz") else path
    with open(base + ".json") as f:
        meta = json.load(f)
    data = np.load(base + ".npz", allow_pickle=False)
    vecs = []
    for i, cmeta in enumerate(meta["cols"]):
        arr = data[f"c{i}"]
        if cmeta["type"] == T_STR:
            na = data[f"c{i}_na"]
            host = np.asarray([None if na[j] else str(x)
                               for j, x in enumerate(arr)], dtype=object)
            vecs.append(Vec(None, meta["nrow"], type=T_STR, host_data=host))
        else:
            vecs.append(Vec.from_numpy(arr, type=cmeta["type"],
                                       domain=cmeta["domain"]))
    fr = Frame(meta["names"], vecs, key=key)
    from .kvstore import STORE

    STORE.put_keyed(fr)
    return fr


# ---------------------------------------------------------------------------
# models (binary export/import)
# ---------------------------------------------------------------------------
def _to_host(obj):
    """Recursively pull jax.Arrays back to numpy so pickles are portable."""
    if isinstance(obj, jax.Array):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        # NamedTuple (optax optimizer states): construct positionally
        return type(obj)(*(_to_host(v) for v in obj))
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_host(v) for v in obj)
    return obj


def model_bytes(model) -> bytes:
    """The binary model export as in-memory bytes (Models.fetch.bin)."""
    return pickle.dumps(_model_payload(model))


def _model_payload(model) -> dict:
    if hasattr(model, "_ensure_covers"):
        # Tree models compute SHAP node covers lazily from the attached
        # training frame; the export strips frames, so materialize covers now
        # (best effort — a model whose frame is already gone exports without
        # them, and SHAP raises its imported-without-node-weights error).
        try:
            model._ensure_covers()
        except ValueError:
            pass
    state = dict(model.__dict__)
    params = state.get("params")
    if params is not None:
        import dataclasses

        from ..frame.frame import Frame

        reps = {f.name: getattr(params, f.name).key
                for f in dataclasses.fields(params)
                if isinstance(getattr(params, f.name), Frame)}
        params = dataclasses.replace(
            params, **{k: None for k in reps})
        state["params"] = params
        state["__frame_keys__"] = reps
    state = _to_host(state)
    return {"class_module": type(model).__module__,
            "class_name": type(model).__name__,
            "state": state}


def save_model(model, path: str) -> str:
    """Binary model export. Frames on the params are replaced by their keys."""
    payload = _model_payload(model)
    if path.startswith("file://"):
        path = path[len("file://"):]
    if "://" in path:
        # cloud destinations ride the Persist store SPI (s3://, gs://)
        import tempfile

        from ..io.persist import store as _persist_store

        tf = tempfile.NamedTemporaryFile(suffix=".bin", delete=False)
        try:
            pickle.dump(payload, tf)
            tf.close()
            _persist_store(path, tf.name)
        finally:
            tf.close()
            os.unlink(tf.name)
        return path
    with open(path, "wb") as f:
        pickle.dump(payload, f)
    return path


#: modules a model pickle may legitimately reference. The binary format is
#: pickle, and Models.upload.bin puts it on the wire — an unrestricted
#: pickle.load would hand any client arbitrary code execution via __reduce__
#: (the reference's Iced deserializer is not exec-capable, so the wire route
#: must not be either). Everything outside this list fails to load.
_SAFE_BUILTINS = frozenset({
    "object", "dict", "list", "tuple", "set", "frozenset", "bytearray",
    "complex", "range", "slice", "bool", "int", "float", "str", "bytes",
    "NoneType",
})


class _ModelUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        root = module.split(".", 1)[0]
        if root in ("h2o_tpu", "numpy", "collections", "datetime"):
            return super().find_class(module, name)
        if root == "optax":
            # optimizer-state NamedTuples ride DL checkpoints (ADADELTA
            # accumulators) — plain tuple containers whose construction
            # executes no code. Everything else in optax (transform
            # factories, partials, tree utilities) is callable machinery a
            # crafted REDUCE could invoke, so the resolved object must BE a
            # NamedTuple class, not merely live in the package.
            cls = super().find_class(module, name)
            if (isinstance(cls, type) and issubclass(cls, tuple)
                    and hasattr(cls, "_fields")):
                return cls
            raise pickle.UnpicklingError(
                f"model file references optax object {module}.{name}, which "
                "is not an optimizer-state NamedTuple — refusing to load")
        if module == "builtins" and name in _SAFE_BUILTINS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"model file references {module}.{name}, which is outside the "
            "model-state allowlist — refusing to load")


def load_model(path: str):
    """Binary model import — registers the model back into the store.
    Cloud URIs (s3://, gs://) localize through the Persist SPI first.
    Deserialization is allowlisted (see _ModelUnpickler): a crafted file
    cannot reach os/subprocess/eval through __reduce__."""
    import importlib

    if "://" in path:
        from ..io.persist import localize

        path = localize(path)
    with open(path, "rb") as f:
        payload = _ModelUnpickler(f).load()
    if payload["class_module"].split(".", 1)[0] != "h2o_tpu":
        raise ValueError("model class must live in h2o_tpu")
    cls = getattr(importlib.import_module(payload["class_module"]),
                  payload["class_name"])
    model = object.__new__(cls)
    state = payload["state"]
    state.pop("__frame_keys__", None)
    model.__dict__.update(state)
    from .kvstore import STORE

    STORE.put_keyed(model)
    return model


# ---------------------------------------------------------------------------
# atomic writes (the checkpoint layer's durability primitive)
# ---------------------------------------------------------------------------
def _fsync_dir(path: str) -> None:
    """Durably record a rename in the containing directory (POSIX: the
    rename itself is atomic, but only a dir fsync makes it survive power
    loss). Best-effort on filesystems that refuse O_RDONLY dir fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes,
                       failpoint: str = "persist.checkpoint") -> None:
    """write-temp + fsync + rename: a reader (or a resuming process) sees
    either the complete previous content or the complete new content,
    never a torn write — the invariant every kill-at-any-instant resume
    test leans on. The ``persist.checkpoint`` failpoint (``persist.shard``
    for per-device shard-state writes, so the two kill windows count
    independently) sits between the durable temp write and the rename,
    the exact window a preemption would hit."""
    from ..utils import failpoints

    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    failpoints.hit(failpoint)
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


def _replace_durable(tmp: str, path: str) -> None:
    """Atomically promote an already-written temp file (fsync + rename +
    dir fsync) — the rename half of :func:`atomic_write_bytes` for writers
    that produce their temp file through another API (np.savez, pickle)."""
    with open(tmp, "rb+") as f:
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


# ---------------------------------------------------------------------------
# auto-recovery dir (`hex/faulttolerance/Recovery.java`)
# ---------------------------------------------------------------------------
class Recovery:
    """Persists a grid search's progress so a fresh process can auto-resume,
    skipping already-trained models — the reference's `-auto_recovery_dir`
    protocol (exercised by `test_grid_auto_recover.py:50`)."""

    MANIFEST = "recovery.json"

    def __init__(self, dir: str):
        self.dir = dir
        os.makedirs(dir, exist_ok=True)

    def _manifest_path(self) -> str:
        return os.path.join(self.dir, self.MANIFEST)

    def read(self) -> dict | None:
        if not os.path.exists(self._manifest_path()):
            return None
        with open(self._manifest_path()) as f:
            return json.load(f)

    def write(self, manifest: dict) -> None:
        atomic_write_bytes(self._manifest_path(),
                           json.dumps(manifest).encode())

    def save_training_frame(self, fr) -> None:
        p = os.path.join(self.dir, "training_frame.npz")
        if not os.path.exists(p):
            save_frame(fr, p)

    def load_training_frame(self):
        return load_frame(os.path.join(self.dir, "training_frame.npz"))

    def model_path(self, i: int) -> str:
        return os.path.join(self.dir, f"model_{i}.bin")


# ---------------------------------------------------------------------------
# shard-aware state splitting (multi-chip checkpoints)
# ---------------------------------------------------------------------------
def _is_partitioned(arr) -> bool:
    """True for a jax array actually SPLIT across >1 device (replicated
    multi-device arrays reassemble from any one copy and pickle whole)."""
    if not isinstance(arr, jax.Array):
        return False
    try:
        shards = arr.addressable_shards
    except Exception:  # noqa: BLE001 — host-only arrays
        return False
    if len(shards) <= 1:
        return False
    idx = {tuple((sl.start, sl.stop) for sl in s.index) for s in shards}
    return len(idx) > 1


def _split_state_shards(state):
    """Walk an iteration-state pytree and pull every PARTITIONED device
    array out into per-device payloads: the state that remains pickles
    small (markers + replicated arrays), and each device's row shard is
    written by "its" shard file — the multi-host protocol shape (every
    worker writes its shard, the coordinator commits the manifest) run
    single-process over the addressable mesh.

    Returns (state-with-markers, [per-shard payload dict, ...]); payloads
    are empty when nothing is partitioned (single-device meshes — the
    historic one-pickle layout, bit-identical)."""
    payloads: list[dict] = []
    dev_slot: dict = {}
    counter = [0]

    def slot(dev) -> int:
        if dev not in dev_slot:
            dev_slot[dev] = len(dev_slot)
            payloads.append({})
        return dev_slot[dev]

    def walk(obj):
        if _is_partitioned(obj):
            aid = counter[0]
            counter[0] += 1
            seen_idx = set()
            for s in sorted(obj.addressable_shards,
                            key=lambda s: str(s.device)):
                idx = tuple((sl.start, sl.stop) for sl in s.index)
                if idx in seen_idx:
                    continue  # replica copies of the same piece
                seen_idx.add(idx)
                payloads[slot(s.device)].setdefault(aid, []).append(
                    (idx, np.asarray(s.data)))
            return {"__h2o_sharded__": aid, "shape": tuple(obj.shape),
                    "dtype": str(obj.dtype)}
        if isinstance(obj, dict):
            return {k: walk(v) for k, v in obj.items()}
        if isinstance(obj, tuple) and hasattr(obj, "_fields"):
            return type(obj)(*(walk(v) for v in obj))
        if isinstance(obj, (list, tuple)):
            return type(obj)(walk(v) for v in obj)
        return obj

    out = walk(state)
    return out, payloads


def _join_state_shards(state, shard_payloads: list):
    """Inverse of :func:`_split_state_shards`: markers -> reassembled numpy
    arrays (each piece written back at its recorded index — bit-equal to
    the original device array's host pull)."""
    merged: dict = {}
    for payload in shard_payloads:
        for aid, pieces in payload.items():
            merged.setdefault(aid, []).extend(pieces)

    def walk(obj):
        if isinstance(obj, dict) and "__h2o_sharded__" in obj:
            aid = obj["__h2o_sharded__"]
            out = np.empty(tuple(obj["shape"]), dtype=np.dtype(obj["dtype"]))
            covered = 0
            for idx, piece in merged.get(aid, []):
                out[tuple(slice(a, b) for a, b in idx)] = piece
                covered += piece.size
            # pieces are disjoint by construction (replica-deduped at
            # split), so full coverage ⇔ their sizes sum to the array's —
            # anything less would hand training uninitialized memory
            if covered != out.size:
                raise ValueError(
                    f"checkpoint shard payloads cover {covered} of "
                    f"{out.size} elements for state array {aid} — the "
                    f"recovery dir is missing or torn shard files")
            return out
        if isinstance(obj, dict):
            return {k: walk(v) for k, v in obj.items()}
        if isinstance(obj, tuple) and hasattr(obj, "_fields"):
            return type(obj)(*(walk(v) for v in obj))
        if isinstance(obj, (list, tuple)):
            return type(obj)(walk(v) for v in obj)
        return obj

    return walk(state)


# ---------------------------------------------------------------------------
# in-training auto-checkpoints (preemption-proof training)
# ---------------------------------------------------------------------------
class TrainingRecovery:
    """Periodic atomic checkpointing of a RUNNING training job, so a TPU
    preemption / OOM / kill loses at most one checkpoint interval instead
    of the whole forest — the piece the reference's `-auto_recovery_dir`
    protocol had for grids but never for a single model's iterations.

    Layout under ``dir`` (all writes go through :func:`atomic_write_bytes`,
    so a kill at ANY instant leaves a resumable directory):

    - ``recovery.json``       — manifest: builder class, frame fields,
      progress, ``completed`` flag (written last on success)
    - ``params.pkl``          — builder Parameters, Frames stripped to keys
    - ``frame_<field>.npz``   — every Frame the params referenced
    - ``train_state.pkl``     — the builder's iteration state (exact device
      arrays pulled to numpy): restoring it and replaying the remaining
      iterations is BIT-EQUAL to the uninterrupted run, because the RNG
      streams are indexed by global iteration, not by process history

    ``interval_s`` (default: the ``H2O_TPU_CHECKPOINT_SECS`` knob) is a
    wall-clock floor between state writes; 0 checkpoints at every boundary
    the builder offers (how the kill-at-every-interval tests drive it).
    ``writes``/``write_s`` account the overhead the bench `recovery` leg
    reports against total train wall.
    """

    STATE = "train_state.pkl"
    PARAMS = "params.pkl"

    def __init__(self, dir: str, interval_s: float | None = None):
        from ..utils import knobs

        self.rec = Recovery(dir)
        self.dir = dir
        self.interval_s = (knobs.get_int("H2O_TPU_CHECKPOINT_SECS")
                           if interval_s is None else float(interval_s))
        self.writes = 0
        self.write_s = 0.0
        self._last_write = 0.0

    # -- arming (once, before training mutates anything) ---------------------
    def init_for(self, builder) -> bool:
        """Persist the builder's identity + params + frames. Returns False
        (recovery disarmed) when the params hold something unpicklable
        (in-process UDF callables) — a training job must never die for its
        checkpoint insurance."""
        import dataclasses
        import time

        from ..frame.frame import Frame

        from ..models.model_base import Model

        p = builder.params
        frame_fields = [f.name for f in dataclasses.fields(p)
                        if isinstance(getattr(p, f.name), Frame)]
        stripped = {}
        model_fields = []
        for f in dataclasses.fields(p):
            v = getattr(p, f.name)
            if isinstance(v, Frame):
                stripped[f.name] = None
            elif isinstance(v, Model):
                # prior models (checkpoint continuations) are SAVED into the
                # dir: a resume in a fresh process has no STORE to resolve a
                # bare key against
                stripped[f.name] = v.key
                model_fields.append(f.name)
            elif hasattr(v, "key") and not isinstance(v, (str, bytes)):
                stripped[f.name] = v.key  # other keyed refs ride as keys
        params = dataclasses.replace(p, **stripped)
        try:
            params_bytes = pickle.dumps(params)
        except Exception as e:  # noqa: BLE001 — degrade, don't kill the job
            from ..utils.log import warn

            warn(f"auto-recovery disabled: params not picklable ({e!r})")
            return False
        for fname in frame_fields:
            # always overwrite (a reused dir must never resume a NEW job on
            # a PREVIOUS job's frame) — but ATOMICALLY: a re-init on a dir
            # whose manifest is still live (a resume killed before its first
            # checkpoint re-runs init_for) must never leave a torn .npz
            # behind a manifest that points at it
            final = os.path.join(self.dir, f"frame_{fname}.npz")
            tmp = os.path.join(self.dir, f"frame_{fname}.tmp.npz")
            save_frame(getattr(p, fname), tmp)
            _replace_durable(tmp, final)
            _replace_durable(tmp[:-4] + ".json", final[:-4] + ".json")
        try:
            for fname in model_fields:
                final = os.path.join(self.dir, f"model_{fname}.bin")
                save_model(getattr(p, fname), final + ".tmp")
                _replace_durable(final + ".tmp", final)
        except Exception as e:  # noqa: BLE001 — degrade, don't kill the job
            from ..utils.log import warn

            warn(f"auto-recovery disabled: prior model not savable ({e!r})")
            return False
        atomic_write_bytes(os.path.join(self.dir, self.PARAMS), params_bytes)
        manifest = self.rec.read() or {}
        manifest.update({
            "kind": "training",
            "builder_module": type(builder).__module__,
            "builder_name": type(builder).__name__,
            "algo": getattr(builder, "algo_name", "base"),
            "frame_fields": frame_fields,
            "model_fields": model_fields,
            "params_path": self.PARAMS,
            "state_path": None,
            "completed": False,
            "checkpoints": 0,
            "started": time.time(),
        })
        self.rec.write(manifest)
        self._last_write = time.monotonic()
        return True

    # -- the periodic write ---------------------------------------------------
    def due(self) -> bool:
        import time

        return (time.monotonic() - self._last_write) >= self.interval_s

    def save_state(self, state: dict, progress: dict | None = None) -> None:
        """Atomically persist the iteration state, then the manifest (state
        first: a kill between the two leaves the previous manifest pointing
        at the previous complete state — never a dangling reference).

        SHARD-AWARE: state arrays that are actually partitioned across the
        mesh (the carried ``f``/OOB vectors of a multi-chip train) are
        pulled per DEVICE and written as generation-numbered per-shard
        files (``train_state.g<G>.shard<i>.pkl``) BEFORE the main state —
        each device's shard write is its own atomic rename, the
        ``persist.shard`` failpoint fires before each one, and the
        manifest (recording the generation + shard count) commits LAST, so
        a kill anywhere inside the fan-out leaves the previous generation
        fully referenced and the half-written one invisible. Previous-
        generation shard files are reaped only after the commit."""
        import time

        from ..utils import failpoints

        t0 = time.monotonic()
        split, payloads = _split_state_shards(state)
        manifest = self.rec.read() or {}
        committed = int(manifest.get("checkpoints", 0))
        # the new generation must exceed every generation ON DISK, not just
        # the committed count: after a kill during the manifest write the
        # state file already references an uncommitted generation whose
        # files a recycled number would overwrite mid-fanout
        gen = max([committed] + self._shard_gens_on_disk()) + 1
        for i, payload in enumerate(payloads):
            atomic_write_bytes(
                os.path.join(self.dir, f"train_state.g{gen}.shard{i}.pkl"),
                pickle.dumps(payload), failpoint="persist.shard")
        if payloads:
            # the state is SELF-describing: it records which shard
            # generation it was written with, and load() resolves shard
            # files from the state, never the manifest — a kill between
            # this write and the manifest commit must not let a stale
            # manifest join generation G's skeleton with G-1's shards
            # (every gen-G shard file is durably on disk before this
            # write, and reaping runs only after the commit)
            split = dict(split)
            split["__ckpt_gen__"] = gen
            split["__ckpt_shards__"] = len(payloads)
        atomic_write_bytes(os.path.join(self.dir, self.STATE),
                           pickle.dumps(_to_host(split)))
        manifest["state_path"] = self.STATE
        manifest["checkpoints"] = committed + 1
        manifest["state_gen"] = gen if payloads else None
        manifest["state_shards"] = len(payloads)
        if progress:
            manifest["progress"] = progress
        self.rec.write(manifest)
        self._reap_shard_files(keep_gen=gen)
        self.writes += 1
        self.write_s += time.monotonic() - t0
        self._last_write = time.monotonic()
        failpoints.hit("train.checkpoint")

    def _shard_files(self):
        """(generation, filename) of every shard file in the dir — the one
        scan generation numbering and reaping both read."""
        import re

        pat = re.compile(r"train_state\.g(\d+)\.shard\d+\.pkl$")
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        return [(int(m.group(1)), name)
                for m, name in ((pat.match(n), n) for n in names) if m]

    def _shard_gens_on_disk(self) -> list:
        return [gen for gen, _ in self._shard_files()]

    def _reap_shard_files(self, keep_gen: int) -> None:
        """Drop shard files of superseded generations (post-commit only —
        the previous generation must survive until the new state, which is
        durably renamed by now, references the new one). Best-effort: a
        leftover file costs disk, never correctness (resume reads only the
        generation the state itself records)."""
        for gen, name in self._shard_files():
            if gen != keep_gen:
                try:
                    os.remove(os.path.join(self.dir, name))
                except OSError:
                    pass

    def mark_completed(self, model_key: str | None = None) -> None:
        manifest = self.rec.read() or {}
        manifest["completed"] = True
        if model_key:
            manifest["model_key"] = model_key
        self.rec.write(manifest)

    # -- the resume side -------------------------------------------------------
    @staticmethod
    def load(dir: str):
        """(builder_cls, params-with-frames, state-or-None, manifest) from a
        recovery dir. State unpickling goes through the same allowlisted
        unpickler as model imports — a crafted recovery dir cannot reach
        __reduce__ gadgets."""
        import importlib

        rec = Recovery(dir)
        manifest = rec.read()
        if manifest is None or manifest.get("kind") != "training":
            raise ValueError(f"no training recovery manifest in {dir}")
        builder_cls = getattr(
            importlib.import_module(manifest["builder_module"]),
            manifest["builder_name"])
        with open(os.path.join(dir, manifest["params_path"]), "rb") as fh:
            params = _ModelUnpickler(fh).load()
        import dataclasses

        updates = {}
        for fname in manifest.get("frame_fields", []):
            updates[fname] = load_frame(
                os.path.join(dir, f"frame_{fname}.npz"))
        for fname in manifest.get("model_fields", []):
            # load_model also re-registers the prior under its key in STORE,
            # so key-based resolution (gbm._resolve_checkpoint) works in a
            # fresh process
            updates[fname] = load_model(
                os.path.join(dir, f"model_{fname}.bin"))
        params = dataclasses.replace(params, **updates)
        state = None
        if manifest.get("state_path"):
            with open(os.path.join(dir, manifest["state_path"]), "rb") as fh:
                state = _ModelUnpickler(fh).load()
            # shard generation + count come from the STATE itself (written
            # atomically after its shard files), not the manifest — the
            # manifest may be one commit behind after a kill in the window
            # between the state write and the manifest write
            gen = state.pop("__ckpt_gen__", None) \
                if isinstance(state, dict) else None
            nshards = int(state.pop("__ckpt_shards__", 0) or 0) \
                if isinstance(state, dict) else 0
            if gen is not None and nshards:
                payloads = []
                for i in range(nshards):
                    p = os.path.join(dir,
                                     f"train_state.g{gen}.shard{i}.pkl")
                    with open(p, "rb") as fh:
                        payloads.append(_ModelUnpickler(fh).load())
                state = _join_state_shards(state, payloads)
        return builder_cls, params, state, manifest
