"""Persistence: binary model export/import, frame save/load, recovery dirs.

Analog of the reference's checkpoint/persist layer (SURVEY.md §5.4):
- binary model export/import (`hex/Model.java` exportBinaryModel /
  `water/api/ModelsHandler` importModel),
- frame save/load (`water/fvec/persist/FramePersist.java`),
- the auto-recovery directory protocol used by grid search
  (`hex/faulttolerance/Recovery.java:20-40`).

Formats are host-side and self-contained: frames go to one ``.npz`` (columns)
plus a JSON sidecar (names/types/domains); models are pickles whose device
arrays were pulled back to numpy and whose attached Frames (training/validation
— cluster state, not model state) are stripped, mirroring the reference, which
also persists models without their training data. A persisted model scores
after load in a fresh process: jnp ops consume the numpy arrays directly.
"""

from __future__ import annotations

import json
import os
import pickle

import jax
import numpy as np


# ---------------------------------------------------------------------------
# frames (`water/fvec/persist/FramePersist.java`)
# ---------------------------------------------------------------------------
def save_frame(fr, path: str) -> str:
    """Write a Frame to ``path`` (.npz + .json sidecar). Returns path."""
    from ..frame.vec import T_STR

    base = path[:-4] if path.endswith(".npz") else path
    arrays, meta = {}, {"names": fr.names, "nrow": fr.nrow, "cols": []}
    for i, name in enumerate(fr.names):
        v = fr.vec(name)
        cmeta = {"name": name, "type": v.type, "domain": v.domain}
        if v.is_string():
            arrays[f"c{i}"] = np.asarray(
                ["" if x is None else str(x) for x in v.host_data])
            arrays[f"c{i}_na"] = np.asarray(
                [x is None for x in v.host_data], dtype=bool)
        else:
            arrays[f"c{i}"] = v.to_numpy()
        meta["cols"].append(cmeta)
    np.savez_compressed(base + ".npz", **arrays)
    with open(base + ".json", "w") as f:
        json.dump(meta, f)
    return base + ".npz"


def load_frame(path: str, key: str | None = None):
    from ..frame.frame import Frame
    from ..frame.vec import T_STR, Vec

    base = path[:-4] if path.endswith(".npz") else path
    with open(base + ".json") as f:
        meta = json.load(f)
    data = np.load(base + ".npz", allow_pickle=False)
    vecs = []
    for i, cmeta in enumerate(meta["cols"]):
        arr = data[f"c{i}"]
        if cmeta["type"] == T_STR:
            na = data[f"c{i}_na"]
            host = np.asarray([None if na[j] else str(x)
                               for j, x in enumerate(arr)], dtype=object)
            vecs.append(Vec(None, meta["nrow"], type=T_STR, host_data=host))
        else:
            vecs.append(Vec.from_numpy(arr, type=cmeta["type"],
                                       domain=cmeta["domain"]))
    fr = Frame(meta["names"], vecs, key=key)
    from .kvstore import STORE

    STORE.put_keyed(fr)
    return fr


# ---------------------------------------------------------------------------
# models (binary export/import)
# ---------------------------------------------------------------------------
def _to_host(obj):
    """Recursively pull jax.Arrays back to numpy so pickles are portable."""
    if isinstance(obj, jax.Array):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        # NamedTuple (optax optimizer states): construct positionally
        return type(obj)(*(_to_host(v) for v in obj))
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_host(v) for v in obj)
    return obj


def model_bytes(model) -> bytes:
    """The binary model export as in-memory bytes (Models.fetch.bin)."""
    return pickle.dumps(_model_payload(model))


def _model_payload(model) -> dict:
    if hasattr(model, "_ensure_covers"):
        # Tree models compute SHAP node covers lazily from the attached
        # training frame; the export strips frames, so materialize covers now
        # (best effort — a model whose frame is already gone exports without
        # them, and SHAP raises its imported-without-node-weights error).
        try:
            model._ensure_covers()
        except ValueError:
            pass
    state = dict(model.__dict__)
    params = state.get("params")
    if params is not None:
        import dataclasses

        from ..frame.frame import Frame

        reps = {f.name: getattr(params, f.name).key
                for f in dataclasses.fields(params)
                if isinstance(getattr(params, f.name), Frame)}
        params = dataclasses.replace(
            params, **{k: None for k in reps})
        state["params"] = params
        state["__frame_keys__"] = reps
    state = _to_host(state)
    return {"class_module": type(model).__module__,
            "class_name": type(model).__name__,
            "state": state}


def save_model(model, path: str) -> str:
    """Binary model export. Frames on the params are replaced by their keys."""
    payload = _model_payload(model)
    if path.startswith("file://"):
        path = path[len("file://"):]
    if "://" in path:
        # cloud destinations ride the Persist store SPI (s3://, gs://)
        import tempfile

        from ..io.persist import store as _persist_store

        tf = tempfile.NamedTemporaryFile(suffix=".bin", delete=False)
        try:
            pickle.dump(payload, tf)
            tf.close()
            _persist_store(path, tf.name)
        finally:
            tf.close()
            os.unlink(tf.name)
        return path
    with open(path, "wb") as f:
        pickle.dump(payload, f)
    return path


#: modules a model pickle may legitimately reference. The binary format is
#: pickle, and Models.upload.bin puts it on the wire — an unrestricted
#: pickle.load would hand any client arbitrary code execution via __reduce__
#: (the reference's Iced deserializer is not exec-capable, so the wire route
#: must not be either). Everything outside this list fails to load.
_SAFE_BUILTINS = frozenset({
    "object", "dict", "list", "tuple", "set", "frozenset", "bytearray",
    "complex", "range", "slice", "bool", "int", "float", "str", "bytes",
    "NoneType",
})


class _ModelUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        root = module.split(".", 1)[0]
        if root in ("h2o_tpu", "numpy", "collections", "datetime"):
            return super().find_class(module, name)
        if root == "optax":
            # optimizer-state NamedTuples ride DL checkpoints (ADADELTA
            # accumulators) — plain tuple containers whose construction
            # executes no code. Everything else in optax (transform
            # factories, partials, tree utilities) is callable machinery a
            # crafted REDUCE could invoke, so the resolved object must BE a
            # NamedTuple class, not merely live in the package.
            cls = super().find_class(module, name)
            if (isinstance(cls, type) and issubclass(cls, tuple)
                    and hasattr(cls, "_fields")):
                return cls
            raise pickle.UnpicklingError(
                f"model file references optax object {module}.{name}, which "
                "is not an optimizer-state NamedTuple — refusing to load")
        if module == "builtins" and name in _SAFE_BUILTINS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"model file references {module}.{name}, which is outside the "
            "model-state allowlist — refusing to load")


def load_model(path: str):
    """Binary model import — registers the model back into the store.
    Cloud URIs (s3://, gs://) localize through the Persist SPI first.
    Deserialization is allowlisted (see _ModelUnpickler): a crafted file
    cannot reach os/subprocess/eval through __reduce__."""
    import importlib

    if "://" in path:
        from ..io.persist import localize

        path = localize(path)
    with open(path, "rb") as f:
        payload = _ModelUnpickler(f).load()
    if payload["class_module"].split(".", 1)[0] != "h2o_tpu":
        raise ValueError("model class must live in h2o_tpu")
    cls = getattr(importlib.import_module(payload["class_module"]),
                  payload["class_name"])
    model = object.__new__(cls)
    state = payload["state"]
    state.pop("__frame_keys__", None)
    model.__dict__.update(state)
    from .kvstore import STORE

    STORE.put_keyed(model)
    return model


# ---------------------------------------------------------------------------
# auto-recovery dir (`hex/faulttolerance/Recovery.java`)
# ---------------------------------------------------------------------------
class Recovery:
    """Persists a grid search's progress so a fresh process can auto-resume,
    skipping already-trained models — the reference's `-auto_recovery_dir`
    protocol (exercised by `test_grid_auto_recover.py:50`)."""

    MANIFEST = "recovery.json"

    def __init__(self, dir: str):
        self.dir = dir
        os.makedirs(dir, exist_ok=True)

    def _manifest_path(self) -> str:
        return os.path.join(self.dir, self.MANIFEST)

    def read(self) -> dict | None:
        if not os.path.exists(self._manifest_path()):
            return None
        with open(self._manifest_path()) as f:
            return json.load(f)

    def write(self, manifest: dict) -> None:
        tmp = self._manifest_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, self._manifest_path())  # atomic wrt crashes

    def save_training_frame(self, fr) -> None:
        p = os.path.join(self.dir, "training_frame.npz")
        if not os.path.exists(p):
            save_frame(fr, p)

    def load_training_frame(self):
        return load_frame(os.path.join(self.dir, "training_frame.npz"))

    def model_path(self, i: int) -> str:
        return os.path.join(self.dir, f"model_{i}.bin")
