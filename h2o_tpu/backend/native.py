"""Native runtime loader — builds and binds the C++ components.

The reference's performance-critical host paths are JVM code (radix
sort/merge `water/rapids/RadixOrder.java` + `BinaryMerge.java`, CSV tokenizer
`water/parser/CsvParser.java`); ours are C++ (native/*.cpp), compiled on first
use with the in-image toolchain (g++ -O3) into a cached shared library and
bound via ctypes (no pybind11 in the image). Every native entry point has a
numpy fallback, so the package works even where a compiler is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False

_SRC_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_CACHE = os.path.join(_SRC_DIR, "build")


def _build() -> str | None:
    src = os.path.join(_SRC_DIR, "radix.cpp")
    try:
        if not os.path.exists(src):
            return None
        os.makedirs(_CACHE, exist_ok=True)
        # content-hashed artifact name: a stale or foreign .so (different
        # source, different machine — -march=native is not portable) never
        # gets picked up; rebuilds happen exactly when the source changes
        import hashlib

        with open(src, "rb") as f:
            tag = hashlib.sha256(f.read()).hexdigest()[:12]
        out = os.path.join(_CACHE, f"libh2otpu-{tag}.so")
        if os.path.exists(out):
            return out
        cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
               "-pthread", src, "-o", out]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return out
    except (subprocess.SubprocessError, OSError) as e:
        # covers missing compiler, read-only installs, and build errors —
        # the numpy fallback below keeps every caller working
        from ..utils.log import warn

        warn(f"native build failed ({e}); using numpy fallbacks")
        return None


def lib() -> ctypes.CDLL | None:
    """The loaded native library, or None when unavailable."""
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        path = _build()
        if path is None:
            return None
        try:
            L = ctypes.CDLL(path)
        except OSError as e:  # incompatible binary → numpy fallback
            from ..utils.log import warn

            warn(f"native library load failed ({e}); using numpy fallbacks")
            return None
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        L.h2otpu_radix_argsort_u64.argtypes = [u64p, ctypes.c_int64, i64p,
                                               ctypes.c_int]
        L.h2otpu_radix_refine_u64.argtypes = [u64p, ctypes.c_int64, i64p,
                                              ctypes.c_int]
        L.h2otpu_gather_u64.argtypes = [u64p, i64p, ctypes.c_int64, u64p,
                                        ctypes.c_int]
        _LIB = L
        return _LIB


def _as_sortable_u64(col: np.ndarray, ascending: bool = True,
                     na_first: bool = True) -> np.ndarray:
    """Map a float64 column to order-preserving uint64 keys.

    IEEE-754 trick: flip the sign bit for non-negatives, all bits for
    negatives. NaN maps to an extreme so NA ordering is explicit (H2O sorts
    NAs first ascending)."""
    col = np.ascontiguousarray(col, dtype=np.float64)
    bits = col.view(np.uint64).copy()
    neg = bits >> 63 != 0
    bits[neg] = ~bits[neg]
    bits[~neg] |= np.uint64(1) << np.uint64(63)
    nan = np.isnan(col)
    bits[nan] = 0 if na_first == ascending else np.uint64(0xFFFFFFFFFFFFFFFF)
    if not ascending:
        bits = np.uint64(0xFFFFFFFFFFFFFFFF) - bits
    return bits


def radix_lexsort(columns: list[np.ndarray], ascending: list[bool] | None = None,
                  na_first: bool = True, nthreads: int = 0) -> np.ndarray:
    """Stable multi-column argsort; columns[0] is the PRIMARY key (unlike
    np.lexsort). Native parallel radix when available, np.lexsort fallback."""
    n = len(columns[0])
    ascending = ascending or [True] * len(columns)
    L = lib()
    if L is None or n < (1 << 15):  # small inputs: numpy is fine
        # same u64 key transform as the native path, so the two paths produce
        # IDENTICAL permutations (incl. NaN-vs-±inf ordering and na_first)
        keys = [_as_sortable_u64(np.asarray(c, dtype=np.float64), asc, na_first)
                for c, asc in zip(columns, ascending)]
        return np.lexsort(list(reversed(keys)))

    order = np.empty(n, dtype=np.int64)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    # least-significant column first; each refine is stable on prior order
    first = True
    for col, asc in zip(reversed(columns), reversed(ascending)):
        keys = _as_sortable_u64(np.asarray(col, dtype=np.float64), asc, na_first)
        kp = keys.ctypes.data_as(u64p)
        op = order.ctypes.data_as(i64p)
        if first:
            L.h2otpu_radix_argsort_u64(kp, n, op, nthreads)
            first = False
        else:
            L.h2otpu_radix_refine_u64(kp, n, op, nthreads)
    return order
