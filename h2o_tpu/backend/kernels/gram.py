"""Fused weighted Gram accumulation — the GLMIterationTask inner loop.

One call computes XᵀWX (and optionally XᵀWz) in ONE pass over row blocks:
the (R, P) weighted design never materializes — each block's X·W product
lives only for its own contraction — and both the matrix and the vector
accumulate in the same pass, which is exactly `hex/glm/GLMTask.java:35-37`'s
one-MRTask contract. Consumers: `glm._make_irls_kernel` (IRLS driver),
`pca._gram_kernel` (GramSVD), and RuleFit's streaming IRLS shares
`_block_contrib` inside its design-building scan.

Backends mirror kernels/hist.py: ``xla`` is the blocked ``lax.scan``
(default on CPU, the parity oracle), ``pallas`` fuses the same per-block
math into one ``pl.pallas_call`` with the (P, P) accumulator VMEM-resident
across the grid (interpreted off-TPU). Identical block math + identical
ascending block order ⇒ bit-equal outputs at production block shapes
(single or gemm-sized blocks under the default budget) — pinned by
tests/test_kernels.py down to the end-to-end IRLS coefficients. The one
measured caveat: at deliberately tiny forced blocks XLA may pick a
different reduction strategy for the fused scan than the interpreted
kernel, so the forced-multiblock boundary is pinned at tight closeness
rather than bitness (the default budget never produces such blocks).

The W/z row vectors arrive precomputed (they are O(R) elementwise — the
IRLS step builds them from eta in the same jitted program); the fusion
here covers the O(R·P²) part that dominates.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import hist_backend, interpret_mode, pow2_block_rows

#: transient-cell budget per block: blocks sized so the (rb, P) weighted
#: product stays ~128 MB of f32 (gemm-sized, never HBM-relevant); designs
#: under the budget run as ONE block — i.e. exactly the historic fused
#: einsum, byte-for-byte — and only frame-scale designs split
_BLOCK_CELLS = 1 << 25


def block_contrib(xb, wb, zb):
    """One row block's (ΔG, Δb): Xᵀ(W∘X) and Xᵀ(W∘z). ``zb=None`` skips
    the vector (PCA's unweighted-response Gram). Public: RuleFit's
    streaming IRLS calls this inside its own design-building scan — the
    design block exists only in-scan there, so the fusion point is the
    shared math, not a second pass."""
    XW = xb * wb[:, None]
    dG = jnp.einsum("rp,rq->pq", XW, xb)
    if zb is None:
        return dG, None
    return dG, XW.T @ zb


_block_contrib = block_contrib


def _xla_gram(X, W, z, rb):
    R, P = X.shape
    nblk = R // rb
    has_z = z is not None

    def body(carry, blk):
        G, b = carry
        xb, wb, zb = blk if has_z else (*blk, None)
        dG, db = _block_contrib(xb, wb, zb)
        return (G + dG, b + db if has_z else b), None

    init = (jnp.zeros((P, P), jnp.float32),
            jnp.zeros((P,), jnp.float32) if has_z else 0.0)
    xs = (X.reshape(nblk, rb, P), W.reshape(nblk, rb))
    if has_z:
        xs = xs + (z.reshape(nblk, rb),)
    (G, b), _ = jax.lax.scan(body, init, xs)
    return G, (b if has_z else None)


def _pallas_gram(X, W, z, rb):
    R, P = X.shape
    nblk = R // rb
    has_z = z is not None

    def kernel(x_ref, w_ref, *refs):
        i = pl.program_id(0)
        if has_z:
            z_ref, g_ref, b_ref = refs
            zb = z_ref[..., 0]
        else:
            (g_ref,) = refs
            zb = None
        dG, db = _block_contrib(x_ref[...], w_ref[..., 0], zb)

        @pl.when(i == 0)
        def _():
            g_ref[...] = dG
            if has_z:
                b_ref[...] = db[None, :]

        @pl.when(i != 0)
        def _():
            g_ref[...] = g_ref[...] + dG
            if has_z:
                b_ref[...] = b_ref[...] + db[None, :]

    in_specs = [pl.BlockSpec((rb, P), lambda i: (i, 0)),
                pl.BlockSpec((rb, 1), lambda i: (i, 0))]
    args = [X, W[:, None]]
    out_specs = [pl.BlockSpec((P, P), lambda i: (0, 0))]
    out_shape = [jax.ShapeDtypeStruct((P, P), jnp.float32)]
    if has_z:
        in_specs.append(pl.BlockSpec((rb, 1), lambda i: (i, 0)))
        args.append(z[:, None])
        out_specs.append(pl.BlockSpec((1, P), lambda i: (0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((1, P), jnp.float32))
    out = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        interpret=interpret_mode(),
    )(*args)
    if has_z:
        return out[0], out[1][0]
    return out[0], None


def gram_accumulate(X, W, z=None, *, block: int | None = None,
                    backend: str | None = None):
    """(G, b) = (XᵀWX, XᵀWz) in one blocked pass; ``b`` is None when ``z``
    is. ``W`` is the per-row weight vector (a 0/1 mask for PCA — note the
    contraction applies W once, i.e. Xᵀ·diag(W)·X; mask callers rely on
    0²=0, 1²=1). Backend routed per kernels package policy; read at trace
    time, so jit-caching callers must key on `hist_backend()`.

    Blocks are balanced — nblk = ceil(R·P / cell budget), rb = ceil(R /
    nblk) — and rows pad with zeros up to nblk·rb when R doesn't divide
    (unlike the engine's power-of-two frame padding, GLM designs arrive at
    arbitrary lengths; a pow2-divisor fallback once produced 16-row blocks
    at R=50000, turning the gemm into 3125 dispatch-bound slivers).
    Zero-weight zero-value rows contribute exact +0.0 products, so the
    padded sum is bit-identical on both backends; designs under the
    budget run as a single block, which IS the historic fused einsum."""
    R, P = X.shape
    cells = block * P if block else _BLOCK_CELLS
    nblk = max(1, -(-R * P // max(cells, 1)))
    rb = -(-R // nblk)
    pad = nblk * rb - R
    if pad:
        X = jnp.concatenate(
            [X, jnp.zeros((pad, X.shape[1]), X.dtype)], axis=0)
        W = jnp.concatenate([W, jnp.zeros((pad,), W.dtype)])
        if z is not None:
            z = jnp.concatenate([z, jnp.zeros((pad,), z.dtype)])
    bk = backend or hist_backend()
    fn = _pallas_gram if bk == "pallas" else _xla_gram
    return fn(X, W, z, rb)
