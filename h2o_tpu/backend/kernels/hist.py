"""Fused level-histogram kernel — the ScoreBuildHistogram2 inner loop.

One call accumulates, per shard, the (F, n_lv, B, V) channel histograms of
one tree level from the chunk store's int8/int16 bin codes. Two backends
share the per-block math (see the package docstring for the parity
contract):

- ``xla``: ``lax.scan`` over row blocks — the pre-kernels production path.
- ``pallas``: ONE ``pl.pallas_call`` with the grid over row blocks; each
  step DMAs a (rb, F) code block + (rb,) node ids + (rb, V) channel values
  into VMEM, upcasts the sub-int32 codes there (the PR 2 discipline — the
  narrow dtype exists only as an HBM storage format), and adds the block's
  contribution into the VMEM-resident accumulator. The GPU tree-boosting
  kernels (Booster / XGBoost gpu_hist) do exactly this with shared-memory
  atomics; TPUs have no scatter unit, so the in-VMEM accumulate is
  expressed as compare-mask contractions riding the MXU — the engine's
  standard no-gather idiom, now fused into a single kernel instead of a
  chain of HLO ops with HBM-visible intermediates.

Width-bucketed ``groups`` (engine.plan_hist_groups) are first-class: the
per-group column gather is hoisted out of the block loop (Pallas kernels
cannot close over constant index arrays, and the narrow coded gather is
cheap), each group accumulates at its own width, and ``mode="segsum"``
groups keep their segment-sum formulation on the xla path while the kernel
uses the same op under interpret — parity pinned either way. The caller
(engine._build_level_hist) owns the psum and the grouped scatter-back;
nothing in here touches a mesh axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import hist_backend, interpret_mode, pow2_block_rows


# ---------------------------------------------------------------------------
# shared per-block contributions — the ONE definition both backends execute
# ---------------------------------------------------------------------------
def _node_outer(l, vv, n_lv: int):
    """(rb, n_lv, V) per-row channel values routed to the row's node slot —
    an outer product against the node one-hot (exact: one 1.0 per row)."""
    n_oh = jax.nn.one_hot(l, n_lv, dtype=jnp.float32)
    return jnp.einsum("rn,rv->rnv", n_oh, vv)


def _flat_contrib(xb, l, vv, n_lv: int, nbins_tot: int):
    """One row block's (F, n_lv, B, V) contribution, flat bin space."""
    # int8/int16 binned views upcast HERE, one block at a time in VMEM /
    # in-scan: the accumulate below always sees int32 (graftlint
    # narrow-int-accumulate pins the hazard), while HBM keeps 1-2 B/cell.
    xb = xb.astype(jnp.int32)
    a = _node_outer(l, vv, n_lv)
    b_oh = jax.nn.one_hot(xb, nbins_tot, dtype=jnp.float32)   # (rb, F, B)
    return jnp.einsum("rnv,rfb->fnbv", a, b_oh)


def _one_group_contrib(xg, a, l, vv, Bg: int, mode: str, n_lv: int,
                       na_global: int, segsum_ok: bool = True):
    """One width bucket's (Fg, n_lv, Bg, V) block contribution. ``xg`` is
    the group's already-gathered code block; the group NA bucket is its
    last slot (global NA remaps here, scatter-back restores it).

    ``segsum_ok`` gates the segment-sum formulation: the xla path and the
    INTERPRETED pallas path use it (and stay bit-equal to each other), but
    a Mosaic-COMPILED kernel body must not — segment_sum is a scatter, and
    the TPU has no scatter unit to lower it onto, so on-chip the narrow
    groups fall back to the compare-mask contraction (value-equivalent;
    on-chip parity vs the on-chip xla path is the ROADMAP's real-v5e
    measurement)."""
    xg = xg.astype(jnp.int32)
    Fg = xg.shape[1]
    xg = jnp.where(xg == na_global, Bg - 1, xg)
    if mode == "segsum" and segsum_ok:
        # narrow-bin path: at Bg ≪ the 128-lane MXU tile the one-hot
        # matmul is degenerate (mostly-padding tiles); a flat segment-sum
        # over (feature, node, bin) keys accumulates the same cells with
        # no one-hot at all (and in pure f32 adds — the matmul path rounds
        # each contribution through bf16 on TPU, so this path is the
        # *more* exact of the two). broadcasted_iota, not arange: a Pallas
        # kernel body may not close over constant arrays.
        fi = jax.lax.broadcasted_iota(jnp.int32, (1, Fg), 1)
        seg = (fi * n_lv + l[:, None]) * Bg + xg              # (rb, Fg)
        data = jnp.broadcast_to(vv[:, None, :],
                                (xg.shape[0], Fg, vv.shape[1]))
        h = jax.ops.segment_sum(
            data.reshape(-1, vv.shape[1]), seg.reshape(-1),
            num_segments=Fg * n_lv * Bg)
        return h.reshape(Fg, n_lv, Bg, vv.shape[1])
    b_oh = jax.nn.one_hot(xg, Bg, dtype=jnp.float32)
    return jnp.einsum("rnv,rfb->fnbv", a, b_oh)


def _group_contrib(xgs, l, vv, groups, n_lv: int, na_global: int,
                   segsum_ok: bool = True):
    a = _node_outer(l, vv, n_lv)   # shared across onehot groups — exact
    return tuple(
        _one_group_contrib(xg, a, l, vv, Bg, mode, n_lv, na_global,
                           segsum_ok=segsum_ok)
        for xg, (_idxs, Bg, mode) in zip(xgs, groups))


# ---------------------------------------------------------------------------
# xla backend — blocked lax.scan (the oracle)
# ---------------------------------------------------------------------------
def _xla_flat(Xb, lc, vv, n_lv, nbins_tot, rb):
    Rl, F = Xb.shape
    V = vv.shape[1]
    nblk = Rl // rb

    def body(acc, blk):
        xb, l, v = blk
        return acc + _flat_contrib(xb, l, v, n_lv, nbins_tot), None

    init = jnp.zeros((F, n_lv, nbins_tot, V), dtype=jnp.float32)
    hist, _ = jax.lax.scan(body, init, (Xb.reshape(nblk, rb, F),
                                        lc.reshape(nblk, rb),
                                        vv.reshape(nblk, rb, V)))
    return hist


def _xla_grouped(xgs, lc, vv, groups, n_lv, na_global, rb):
    Rl = lc.shape[0]
    V = vv.shape[1]
    nblk = Rl // rb
    xgs_r = [xg.reshape(nblk, rb, xg.shape[1]) for xg in xgs]

    def body(accs, blk):
        l, v, *xg = blk
        cs = _group_contrib(xg, l, v, groups, n_lv, na_global)
        return tuple(a + c for a, c in zip(accs, cs)), None

    init = tuple(jnp.zeros((len(idxs), n_lv, Bg, V), jnp.float32)
                 for idxs, Bg, _mode in groups)
    hists, _ = jax.lax.scan(body, init, (lc.reshape(nblk, rb),
                                         vv.reshape(nblk, rb, V), *xgs_r))
    return hists


# ---------------------------------------------------------------------------
# pallas backend — one fused kernel, grid over row blocks
# ---------------------------------------------------------------------------
def _accum_out(out_ref, contrib):
    """Zero-on-first-step accumulate into a grid-revisited VMEM output."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[...] = contrib

    @pl.when(i != 0)
    def _():
        out_ref[...] = out_ref[...] + contrib


def _pallas_flat(Xb, lc, vv, n_lv, nbins_tot, rb):
    Rl, F = Xb.shape
    V = vv.shape[1]
    nblk = Rl // rb

    def kernel(xb_ref, l_ref, v_ref, out_ref):
        _accum_out(out_ref, _flat_contrib(xb_ref[...], l_ref[..., 0],
                                          v_ref[...], n_lv, nbins_tot))

    return pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[pl.BlockSpec((rb, F), lambda i: (i, 0)),
                  pl.BlockSpec((rb, 1), lambda i: (i, 0)),
                  pl.BlockSpec((rb, V), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((F, n_lv, nbins_tot, V),
                               lambda i: (0, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, n_lv, nbins_tot, V),
                                       jnp.float32),
        interpret=interpret_mode(),
    )(Xb, lc[:, None], vv)


def _pallas_grouped(xgs, lc, vv, groups, n_lv, na_global, rb):
    Rl = lc.shape[0]
    V = vv.shape[1]
    nblk = Rl // rb
    ng = len(groups)
    interp = interpret_mode()
    shapes = tuple(jax.ShapeDtypeStruct((len(idxs), n_lv, Bg, V),
                                        jnp.float32)
                   for idxs, Bg, _mode in groups)

    def kernel(l_ref, v_ref, *refs):
        xg_refs, out_refs = refs[:ng], refs[ng:]
        cs = _group_contrib([x[...] for x in xg_refs], l_ref[..., 0],
                            v_ref[...], groups, n_lv, na_global,
                            segsum_ok=interp)  # no scatter through Mosaic
        for o, c in zip(out_refs, cs):
            _accum_out(o, c)

    return pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[pl.BlockSpec((rb, 1), lambda i: (i, 0)),
                  pl.BlockSpec((rb, V), lambda i: (i, 0))]
                 + [pl.BlockSpec((rb, xg.shape[1]), lambda i: (i, 0))
                    for xg in xgs],
        out_specs=tuple(pl.BlockSpec(s.shape, lambda i: (0, 0, 0, 0))
                        for s in shapes),
        out_shape=shapes,
        interpret=interpret_mode(),
    )(lc[:, None], vv, *xgs)


# ---------------------------------------------------------------------------
# public entry — what engine._build_level_hist calls
# ---------------------------------------------------------------------------
def level_hist_one_group(xg, lc, vv, *, Bg: int, mode: str, n_lv: int,
                         nbins_tot: int, block: int,
                         backend: str | None = None):
    """ONE width bucket accumulated in its own scan — the async-psum shape:
    the caller issues this group's psum immediately after, BEFORE tracing
    the next group's scan, so on a real ICI the collective overlaps the
    next bucket's local accumulation. Bit-parity with the joint-scan path
    is by construction: the per-block contribution is the same
    `_one_group_contrib` over the same block contents in the same ascending
    block order (the shared node outer product is recomputed per scan but
    is an exact outer product — identical values either way)."""
    rb = pow2_block_rows(lc.shape[0], block)
    bk = backend or hist_backend()
    groups1 = ((tuple(range(xg.shape[1])), Bg, mode),)
    fn = _pallas_grouped if bk == "pallas" else _xla_grouped
    return fn([xg], lc, vv, groups1, n_lv, nbins_tot - 1, rb)[0]


def streamed_route_hist(Xb, node, vals, route_fn, *, offset: int, n_lv: int,
                        nbins_tot: int, block: int, groups=None):
    """Fused route→accumulate single pass — the double-buffered column-block
    stream of the pipelined level program (``H2O_TPU_PIPELINE``).

    The synchronous level program walks the row blocks TWICE per level: once
    to route rows off the previous level's splits, once to accumulate the
    new level's histogram. This pass decodes each (rb, F) block once:
    ``route_fn`` (the previous level's routing, closure from the engine)
    advances the block's node ids, the level window localizes them, and the
    block's histogram contribution accumulates immediately — while the scan
    machinery is already streaming the NEXT block's codes in (XLA pipelines
    the decode/upcast of block i+1 against block i's contraction; on TPU
    the Mosaic grid does the same with VMEM DMA double-buffering). Returns
    ``(hists, node)`` with ``hists`` a tuple of per-group accumulators (one
    flat accumulator when ``groups`` is None) and ``node`` the advanced
    (Rl,) ids. No collectives — the caller psums, exactly like
    `level_hist_blocks`.

    Bit-parity with the two-pass shape is by construction: routing is
    integer/boolean work (any formulation that picks the same children is
    exact), and the histogram contributions are the same `_flat_contrib` /
    `_group_contrib` over the same block contents in the same block order.
    ``route_fn=None`` (level 0) skips the routing half."""
    Rl = Xb.shape[0]
    V = vals.shape[1]
    rb = pow2_block_rows(Rl, block)
    nblk = Rl // rb

    def body(accs, blk):
        xb, nd, v = blk
        if route_fn is not None:
            nd = route_fn(xb, nd)
        local = nd - offset
        active = (local >= 0) & (local < n_lv)
        lc = jnp.clip(local, 0, n_lv - 1)
        vz = jnp.where(active[:, None], v, 0.0)
        if groups is None:
            cs = (_flat_contrib(xb, lc, vz, n_lv, nbins_tot),)
        else:
            xgs = [xb[:, list(idxs)] for idxs, _Bg, _mode in groups]
            cs = _group_contrib(xgs, lc, vz, groups, n_lv, nbins_tot - 1)
        return tuple(a + c for a, c in zip(accs, cs)), nd

    if groups is None:
        init = (jnp.zeros((Xb.shape[1], n_lv, nbins_tot, V), jnp.float32),)
    else:
        init = tuple(jnp.zeros((len(idxs), n_lv, Bg, V), jnp.float32)
                     for idxs, Bg, _mode in groups)
    accs, node_b = jax.lax.scan(
        body, init, (Xb.reshape(nblk, rb, Xb.shape[1]),
                     node.reshape(nblk, rb),
                     vals.reshape(nblk, rb, V)))
    return accs, node_b.reshape(Rl)


def level_hist_blocks(Xb, lc, vv, *, n_lv: int, nbins_tot: int, block: int,
                      groups=None, backend: str | None = None):
    """Per-shard level-histogram accumulation over row blocks.

    ``Xb`` (Rl, F) int8/int16/int32 bin codes; ``lc`` (Rl,) int32 LOCAL
    node ids already clipped to [0, n_lv); ``vv`` (Rl, V) f32 channel
    values already zeroed for inactive rows. Flat (``groups=None``)
    returns the (F, n_lv, nbins_tot, V) accumulator; grouped returns one
    (Fg, n_lv, Bg, V) accumulator per normalized group, each group's NA
    bucket in its LAST slot. No collectives — the caller psums.
    """
    rb = pow2_block_rows(Xb.shape[0], block)
    bk = backend or hist_backend()
    if groups is None:
        fn = _pallas_flat if bk == "pallas" else _xla_flat
        return fn(Xb, lc, vv, n_lv, nbins_tot, rb)
    na_global = nbins_tot - 1
    # the per-group column gather hoists out of the block loop: values are
    # identical either way (int codes gather exactly), the Pallas kernel
    # cannot close over the constant index arrays, and the gathered narrow
    # views total exactly Xb's bytes
    xgs = [Xb[:, list(idxs)] for idxs, _Bg, _mode in groups]
    fn = _pallas_grouped if bk == "pallas" else _xla_grouped
    return fn(xgs, lc, vv, groups, n_lv, na_global, rb)
