"""Kernels layer — the two inner loops PAPER.md's north star names as
Pallas targets, behind one backend switch.

Every accumulation-heavy hot loop in this repo bottoms out in one of two
shapes: the GBM/DRF level-histogram scan (bin codes → per-(feature, node,
bin) channel sums) and the GLM/PCA weighted Gram (XᵀWX + XᵀWz). This
package owns BOTH implementations of each:

- **xla** — the blocked ``lax.scan`` formulation (the pre-kernels
  production path, verbatim). Default on CPU, and the bit-parity ORACLE
  everywhere: the Pallas path must reproduce it bit-for-bit.
- **pallas** — the same per-block math compiled as ONE fused
  ``pl.pallas_call``: codes stream HBM→VMEM a row block at a time, the
  sub-int32 upcast and the accumulate happen in VMEM, and no per-block
  one-hot/segment intermediate ever round-trips through HBM. On this
  container's CPU mesh the kernel runs under ``interpret=True`` (the
  Mosaic interpreter executes the identical jaxpr, which is what makes
  bit-parity checkable without a chip); on a real TPU backend it compiles
  through Mosaic.

Parity is BY CONSTRUCTION, not by tolerance: both backends call the same
block-contribution functions (`hist._flat_contrib` / `hist._group_contrib`
/ `gram._block_contrib`) and accumulate blocks in the same ascending
order, so the only thing that can diverge is the execution engine — and
the tests/test_kernels.py suite pins that it doesn't (forests, histograms
and Gram matrices bit-equal across ``H2O_TPU_HIST_KERNEL=pallas|xla``).

Backend selection (``H2O_TPU_HIST_KERNEL``):

=========  ================================================================
 value      meaning
=========  ================================================================
 ``auto``   (default) pallas on real TPU backends, xla everywhere else
 ``xla``    force the scan formulation (also the oracle in parity tests)
 ``pallas`` force the fused kernel; interpreted off-TPU
=========  ================================================================

graftlint rule 12 (``direct-pallas-call``) pins this package as the only
sanctioned ``pl.pallas_call`` site — kernels grown elsewhere would dodge
the oracle contract and the interpret routing.
"""

from __future__ import annotations


def pow2_block_rows(rl: int, want: int) -> int:
    """Largest power-of-two divisor of ``rl`` up to ``want`` (``rl`` itself
    when none divides) — the row-block sizer every blocked accumulation in
    this package (and the engine's scans) shares."""
    if rl % want == 0:
        return want
    b = 1
    while b * 2 <= want and rl % (b * 2) == 0:
        b *= 2
    return b if rl % b == 0 else rl


def hist_backend() -> str:
    """Resolved kernels backend: ``"pallas"`` or ``"xla"``.

    Read at TRACE time — callers that cache jitted programs must fold this
    into their cache key (``engine.make_train_fn`` does)."""
    from ...utils.knobs import get_str

    v = (get_str("H2O_TPU_HIST_KERNEL") or "auto").strip().lower()
    if v == "auto":
        import jax

        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if v not in ("pallas", "xla"):
        raise ValueError(
            f"H2O_TPU_HIST_KERNEL={v!r} — expected pallas, xla or auto")
    return v


def interpret_mode() -> bool:
    """True when ``pl.pallas_call`` must run interpreted (no Mosaic
    compiler for this backend) — every non-TPU backend, including the CPU
    mesh this container trains on."""
    import jax

    return jax.default_backend() != "tpu"


from . import gram, hist  # noqa: E402  (cycle-free: leaf modules)

__all__ = ["gram", "hist", "hist_backend", "interpret_mode",
           "pow2_block_rows"]
