"""Node-persistent storage — `water/init/NodePersistentStorage` behind the
`/3/NodePersistentStorage` REST family (Flow saves notebooks through it).

A category/name → bytes store rooted in an on-disk directory (the reference
roots it at `-flow_dir`/ice; here `H2O_TPU_NPS_DIR` or `<ice>/nps`). Names
and categories are restricted to a safe charset so a REST caller can never
path-escape the root."""

from __future__ import annotations

import os
import re

_SAFE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")


class NodePersistentStorage:
    def __init__(self, root: str | None = None):
        from ..utils.knobs import raw

        self.root = root or raw("H2O_TPU_NPS_DIR") or \
            os.path.join(raw("H2O_TPU_ICE_DIR", "/tmp/h2o_tpu"), "nps")

    def configured(self) -> bool:
        return True  # always rooted (the reference is unconfigured only
        # when no flow_dir could be determined)

    def _dir(self, category: str, name: str | None = None) -> str:
        if not _SAFE.match(category or ""):
            raise ValueError(f"bad category {category!r}")
        if name is not None and not _SAFE.match(name):
            raise ValueError(f"bad name {name!r}")
        p = os.path.join(self.root, category)
        return p if name is None else os.path.join(p, name)

    def put(self, category: str, name: str, value: str | bytes) -> None:
        path = self._dir(category, name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        data = value.encode() if isinstance(value, str) else value
        # dot-prefixed temp name lives OUTSIDE the entry namespace (_SAFE
        # requires a leading alphanumeric) — it can never collide with or
        # destroy a legitimate entry
        tmp = os.path.join(os.path.dirname(path), f".tmp-{name}")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic wrt concurrent readers

    def get(self, category: str, name: str) -> bytes:
        path = self._dir(category, name)
        if not os.path.exists(path):
            raise KeyError(f"no NPS entry {category}/{name}")
        with open(path, "rb") as f:
            return f.read()

    def exists(self, category: str, name: str | None = None) -> bool:
        return os.path.exists(self._dir(category, name))

    def delete(self, category: str, name: str) -> None:
        path = self._dir(category, name)
        if os.path.exists(path):
            os.unlink(path)

    def list(self, category: str) -> list[dict]:
        d = self._dir(category)
        if not os.path.isdir(d):
            return []
        out = []
        for name in sorted(os.listdir(d)):
            if name.startswith("."):  # in-flight temp files
                continue
            st = os.stat(os.path.join(d, name))
            out.append({"category": category, "name": name,
                        "size": st.st_size,
                        "timestamp_millis": int(st.st_mtime * 1000)})
        return out


NPS = NodePersistentStorage()
