"""Keyed object store — the control-plane analog of H2O's DKV.

The reference implements a distributed K/V store with home-node hashing, caching
reads, MESI-like invalidation and CAS transactions (`water/DKV.java:52-222`,
`water/Key.java:12-120`, `water/Atomic.java:10-40`) because *every* node can run
control logic. The TPU rebuild is single-controller: the Python process drives the
mesh, bulk data lives in HBM as sharded jax.Arrays, and only light-weight control
objects (frames, models, jobs) need a keyed registry. We therefore keep the Key /
put / get / remove *semantics* (names, types, lifecycle, listing) and drop the
distributed mechanism — consistency comes free from living in one process.

Thread-safety matters (jobs run on worker threads), so all mutation is under a
lock; ``put_if_match`` mirrors `DKV.DputIfMatch` CAS semantics.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable

_counter = itertools.count()


def make_key(prefix: str = "obj") -> str:
    """Generate a unique key name (analog of `water/Key.java` make())."""
    return f"{prefix}_{next(_counter):06d}"


class Keyed:
    """Base for objects that live in the store under a key.

    Mirrors `water/Keyed.java`: the object knows its own key and can remove
    itself (subclasses override ``remove_impl`` to drop dependent keys).
    """

    def __init__(self, key: str | None = None, prefix: str | None = None):
        self.key = key or make_key(prefix or type(self).__name__.lower())

    def remove_impl(self, store: "KVStore") -> None:  # pragma: no cover - default no-op
        pass


class KVStore:
    def __init__(self) -> None:
        self._store: dict[str, Any] = {}
        self._lock = threading.RLock()

    def put(self, key: str, value: Any) -> Any:
        with self._lock:
            self._store[key] = value
        return value

    def put_keyed(self, value: Keyed) -> Keyed:
        return self.put(value.key, value)

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._store.get(key, default)

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._store

    def remove(self, key: str, cascade: bool = True) -> Any:
        with self._lock:
            val = self._store.pop(key, None)
        if cascade and isinstance(val, Keyed):
            val.remove_impl(self)
        return val

    def put_if_match(self, key: str, new: Any, expected: Any) -> Any:
        """CAS put: only store ``new`` if the current value is ``expected``.

        Returns the value now in the store (analog of `water/DKV.java`
        DputIfMatch which returns the witnessed old value).
        """
        with self._lock:
            cur = self._store.get(key)
            if cur is expected or cur == expected:
                self._store[key] = new
                return new
            return cur

    def keys(self, of_type: type | None = None) -> list[str]:
        with self._lock:
            if of_type is None:
                return list(self._store)
            return [k for k, v in self._store.items() if isinstance(v, of_type)]

    def values(self, of_type: type | None = None) -> list[Any]:
        with self._lock:
            vals = list(self._store.values())
        if of_type is not None:
            vals = [v for v in vals if isinstance(v, of_type)]
        return vals

    def clear(self) -> None:
        with self._lock:
            self._store.clear()

    def snapshot(self) -> frozenset:
        """Current key set — the `CheckKeysTask` view the leak rule diffs."""
        with self._lock:
            return frozenset(self._store)


class leak_check:
    """Context manager asserting an operation leaves no new keys behind —
    the `water/junit/rules/CheckLeakedKeysRule.java:20-35` analog for the
    single-controller store. Keys the caller DID mean to create are passed
    out by returning them from the block and listing them in ``expect``
    (a callable evaluated at exit, or an iterable of keys)."""

    def __init__(self, store: KVStore | None = None, expect=()):
        self.store = store or STORE
        self.expect = expect

    def __enter__(self):
        self._before = self.store.snapshot()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        expected = self.expect() if callable(self.expect) else self.expect
        leaked = self.store.snapshot() - self._before - frozenset(expected)
        if leaked:
            raise AssertionError(
                f"leaked keys: {sorted(leaked)} — operations must remove "
                f"their temporaries (CheckLeakedKeysRule)")
        return False


#: Process-global store (the analog of `H2O.STORE`).
STORE = KVStore()
