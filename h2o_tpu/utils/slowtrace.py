"""Tail-based slow-request capture — keep the WHOLE story of the worst
requests, drop everything else.

Sampling every request's span tree would drown the process in its own
observability; sampling none means the p99.9 outlier that matters is
gone by the time anyone asks. Tail-based capture is the standard answer
(OpenTelemetry tail sampling, Dapper's slow-trace stores): hold each
request's finished span tree briefly, persist it ONLY when the request
breached its SLO p99 target — the exact requests a latency post-mortem
needs, at a cost proportional to how badly things are going.

Mechanics: :func:`request` wraps a request boundary in a ``ring=False``
telemetry span rooted on a :class:`~h2o_tpu.utils.telemetry.SpanSink`
(the bounded subtree collector; spans from carry_context'd worker
threads land in it too). On exit the wall is compared against the
request's declared SLO (`utils/slo.py` ``objective().p99_ms``); breaches
— and any request slower than the ``H2O_TPU_SLOWTRACE_MIN_MS`` floor —
persist a bundle into a bounded in-process ring (newest wins,
``H2O_TPU_SLOWTRACE_KEEP``): the span tree, the SLO verdict, and the
program-dispatch walls snapshot (`utils/programs.py` — WHAT was
dispatching while this request crawled). ``GET /3/SlowTraces`` serves
the ring; ``h2o.slow_traces()`` is the client helper.

The capture also feeds the SLO error/latency windows via ``slo.note`` —
one boundary instrumentation, both consumers.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from collections import deque

from . import knobs, slo, telemetry


class _Handle:
    """What :func:`request` yields: the caller marks failures on it
    (``error = True`` / ``note_error()``) before the block exits — a 500
    reply is an SLO error even though no exception unwinds the server's
    route handler."""

    __slots__ = ("error",)

    def __init__(self):
        self.error = False

    def note_error(self) -> None:
        self.error = True


_RING: deque = deque()
_LOCK = threading.Lock()
_SEQ = 0


def _keep() -> int:
    return max(knobs.get_int("H2O_TPU_SLOWTRACE_KEEP"), 1)


def _min_ms() -> float:
    return float(knobs.get_int("H2O_TPU_SLOWTRACE_MIN_MS"))


def _program_walls() -> list[dict]:
    """Compact dispatch-wall view of the program registry at capture time
    — lazily resolved and failure-proof (a control-plane process with no
    compiled programs must still capture slow requests)."""
    mod = sys.modules.get("h2o_tpu.utils.programs")
    if mod is None:
        return []
    try:
        out = []
        for pid, rec in sorted(mod.snapshot().items()):
            wall = rec.get("wall") or {}
            if not wall.get("count"):
                continue
            out.append({"program": pid, "name": rec.get("name"),
                        "dispatches": wall.get("count"),
                        "p50_s": wall.get("p50_s"),
                        "max_s": wall.get("max_s")})
        return out
    except Exception:  # noqa: BLE001 — diagnostics must not fail requests
        return []


@contextlib.contextmanager
def request(slo_name: str, what: str, **attrs):
    """Wrap one request against SLO ``slo_name``: opens the capture-root
    span (``ring=False`` — request-rate spans must not cycle the timeline
    ring), feeds the SLO window on exit, and persists the span tree when
    the request breached its p99 target. An exception unwinding through
    counts as an error AND propagates untouched."""
    sink = telemetry.SpanSink()
    handle = _Handle()
    sp = None
    t0 = time.perf_counter()
    try:
        with telemetry.span(slo_name, ring=False, sink=sink,
                            what=what, **attrs) as sp:
            try:
                yield handle
            except BaseException:
                handle.error = True
                raise
    finally:
        dur_ms = (time.perf_counter() - t0) * 1000.0
        slo.note(slo_name, dur_ms / 1000.0, error=handle.error)
        try:
            target = slo.objective(slo_name).p99_ms
        except KeyError:
            target = None
        if (telemetry.enabled() and sp is not None and target is not None
                and dur_ms > max(target, _min_ms())):
            _capture(slo_name, what, sp, dur_ms, target, handle.error,
                     sink.close())


def _capture(slo_name, what, sp, dur_ms, target_ms, error, tree) -> None:
    global _SEQ
    rec = {
        "slo": slo_name, "what": what,
        "trace": sp.trace_id,
        "dur_ms": round(dur_ms, 3),
        "p99_target_ms": target_ms,
        "error": bool(error),
        "ts_ms": int(time.time() * 1000),
        "pid": os.getpid(),
        "spans": tree,
        "program_walls": _program_walls(),
    }
    with _LOCK:
        _SEQ += 1
        rec["seq"] = _SEQ
        _RING.append(rec)
        keep = _keep()
        while len(_RING) > keep:
            _RING.popleft()
    telemetry.inc("slowtrace.captured.count")
    from . import timeline

    timeline.record("slowtrace", what, slo=slo_name,
                    dur_ms=rec["dur_ms"], trace=sp.trace_id)


def snapshot(limit: int | None = None) -> list[dict]:
    """Captured bundles, oldest first — the `GET /3/SlowTraces` payload
    (``limit`` keeps the newest N)."""
    with _LOCK:
        out = list(_RING)
    if limit is not None and limit > 0:
        out = out[-limit:]
    return out


def total_captured() -> int:
    with _LOCK:
        return _SEQ


def clear() -> None:
    """Empty the ring (test isolation / `DELETE /3/SlowTraces`)."""
    with _LOCK:
        _RING.clear()
