"""Persistent XLA compilation cache switch — shared by the server entry
point and bench.py.

On accelerator backends the cache is pure win (the standard TPU deployment
practice): a fresh server/bench process replays its compiles from disk in
seconds instead of paying the ~25-70 s cold-start the first full-length
train otherwise costs. CPU stays opt-in because jax 0.9.0's CPU executable
serializer segfaulted once mid-suite (tests/conftest.py history).
``H2O_TPU_COMPILE_CACHE`` overrides the location; '0' disables."""

from __future__ import annotations

import os


def enable(default_dir: str | None = None) -> str | None:
    import jax

    from .knobs import raw

    loc = raw("H2O_TPU_COMPILE_CACHE")
    if loc == "0":
        return None
    if not loc:  # unset OR empty (a bare env entry must not makedirs(''))
        if jax.default_backend() == "cpu":
            return None
        loc = default_dir or os.path.expanduser("~/.cache/h2o_tpu_xla")
    os.makedirs(loc, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", loc)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return loc
