"""Persistent XLA compilation cache switch — shared by the server entry
point, cluster init, the first `train_model` of a process, and bench.py.

On accelerator backends the cache is pure win (the standard TPU deployment
practice): a fresh server/bench process replays its compiles from disk in
seconds instead of paying the ~25-70 s cold-start the first full-length
train otherwise costs. CPU stays opt-in because jax 0.9.0's CPU executable
serializer segfaulted once mid-suite (tests/conftest.py history) — setting
``H2O_TPU_COMPILE_CACHE`` to a directory opts in explicitly on any
backend; '0' disables everywhere.

:func:`ensure` is the idempotent wiring point: `model_base.train` calls it
before a job's first dispatch and `api.client.init` / `parallel.cluster
.init_cluster` call it at cluster formation, so ANY process with the knob
set gets the cache without touching `deploy_entry` — closing the ROADMAP
cold-start item (BENCH_r03/r04 measured 49-94 s cold vs 10.5 s warm; the
bench ``cold_start`` leg keeps the delta on the record)."""

from __future__ import annotations

import os

_ENSURED = False
_LOC: str | None = None


def enable(default_dir: str | None = None) -> str | None:
    import jax

    from .knobs import raw

    loc = raw("H2O_TPU_COMPILE_CACHE")
    if loc == "0":
        return None
    if not loc:  # unset OR empty (a bare env entry must not makedirs(''))
        if jax.default_backend() == "cpu":
            return None
        loc = default_dir or os.path.expanduser("~/.cache/h2o_tpu_xla")
    os.makedirs(loc, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", loc)
    # cache EVERYTHING: the cold-start gap is the sum of dozens of small
    # programs (PR 6's acceptance run counted 32 for one GBM leg), and a
    # time floor would leave every sub-threshold program recompiling in
    # the "warm" process — the bench cold_start leg pins uncached ≤ 2
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return loc


def ensure(default_dir: str | None = None) -> str | None:
    """Enable once per process (knob-gated; no-op thereafter). Returns the
    active cache dir, or None when the cache is off for this process.

    The cache is an optimization, never a gate: an unwritable dir (bad
    knob value, read-only home on an accelerator container) degrades to
    running without the cache, exactly like gbm.py's AOT fallback — a
    training job must not die for its warm-start insurance."""
    global _ENSURED, _LOC
    if _ENSURED:
        return _LOC
    _ENSURED = True
    try:
        _LOC = enable(default_dir)
    except OSError as e:
        from .log import warn

        warn(f"persistent compile cache disabled: cache dir unusable "
             f"({e!r})")
        _LOC = None
        return None
    if _LOC:
        from .log import info

        info(f"persistent XLA compile cache at {_LOC}")
    return _LOC
