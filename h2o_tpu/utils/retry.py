"""Typed transient-failure handling — shared retry/backoff policy.

One policy object serves every caller that talks to something flaky: the
Python client (connection errors and 429 Retry-After from the serving
surface, `api/client.py`), the remote readers (`io/hdfs.py`, `io/cloud.py`),
and anything a failpoint makes flaky on purpose. The shape mirrors the
reference's retry discipline (S3 persist retries, client connection
re-attempts) but with the semantics pinned:

- **jittered exponential backoff** — delay_i = base * 2^i capped at max;
  with jitter on (default), the actual sleep is uniform in (0, delay_i]
  ("full jitter" — the fleet must not thunder back in lockstep). Tests pin
  determinism by turning jitter off (`H2O_TPU_RETRY_JITTER=0`).
- **server-directed delays win** — a retryable error carrying an explicit
  delay (Retry-After from a 429) sleeps exactly that, not the backoff.
- **bounded by attempts AND wall-clock budget** — whichever is hit first
  raises :class:`RetryBudgetExceeded`, a TYPED give-up carrying the attempt
  count, elapsed seconds, and the last underlying error as ``__cause__`` —
  callers branch on the type, not on message text.
- **non-retryable errors re-raise immediately**, untouched.

Defaults come from the knob registry (`H2O_TPU_RETRY_*`); every call site
may override per-call.
"""

from __future__ import annotations

import random
import time
from typing import Callable

from . import knobs


class RetryBudgetExceeded(Exception):
    """Typed give-up: the operation stayed transiently broken past the
    retry budget. ``attempts`` tried, ``elapsed_s`` spent, ``last`` (also
    ``__cause__``) is the final underlying error."""

    def __init__(self, description: str, attempts: int, elapsed_s: float,
                 last: BaseException):
        self.attempts = attempts
        self.elapsed_s = elapsed_s
        self.last = last
        super().__init__(
            f"{description or 'operation'} still failing after "
            f"{attempts} attempts over {elapsed_s:.2f}s: {last!r}")


def backoff_s(attempt: int, base_s: float, max_s: float,
              jitter: bool, rng: random.Random | None = None) -> float:
    """Delay before retry number ``attempt`` (0-based): full-jitter
    exponential, deterministic cap sequence with jitter off."""
    d = min(base_s * (2.0 ** attempt), max_s)
    if not jitter:
        return d
    return (rng or random).uniform(0.0, d) or d * 0.01


def retry_after_verdict(value) -> "bool | float":
    """Turn a raw Retry-After header value into a `retry_call` verdict:
    the parsed float delegates the exact delay to the server; a missing or
    malformed header falls back to our own backoff (True). The ONE copy of
    this policy — both HTTP classifiers (`transient_http` here, the REST
    client's `_transient_rest`) route through it."""
    if value is None:
        return True
    try:
        return float(value)
    except (TypeError, ValueError):
        return True


def transient_http(e: BaseException):
    """Shared classifier for the stdlib-urllib remote readers (`io/hdfs.py`,
    `io/cloud.py`): connection-level failures and throttling/5xx statuses
    are transient; everything else (403, 404, parse errors) re-raises
    untouched. HTTPError is checked first — it subclasses URLError, and a
    404 must not be retried just because of its ancestry. A Retry-After
    header on a 429/503 delegates the exact delay."""
    import urllib.error

    if isinstance(e, urllib.error.HTTPError):
        if e.code not in (429, 500, 502, 503, 504):
            return False
        return retry_after_verdict(
            e.headers.get("Retry-After") if e.headers else None)
    return isinstance(e, (urllib.error.URLError, ConnectionError,
                          TimeoutError))


def retry_call(fn: Callable, *, retryable, description: str = "",
               attempts: int | None = None, budget_s: float | None = None,
               base_s: float | None = None, max_s: float | None = None,
               jitter: bool | None = None,
               on_retry: Callable | None = None,
               sleep: Callable[[float], None] = time.sleep):
    """Run ``fn()`` retrying transient failures; returns its result.

    ``retryable`` is either a tuple of exception types, or a predicate
    ``exc -> bool | float`` — False means not transient (re-raise), True
    means back off, a float means "the server told us when" (sleep exactly
    that many seconds, e.g. Retry-After). ``on_retry(exc, attempt, delay)``
    observes each scheduled retry (logging / stats).
    """
    attempts = knobs.get_int("H2O_TPU_RETRY_ATTEMPTS") \
        if attempts is None else int(attempts)
    budget_s = knobs.get_int("H2O_TPU_RETRY_BUDGET_MS") / 1000.0 \
        if budget_s is None else float(budget_s)
    base_s = knobs.get_int("H2O_TPU_RETRY_BASE_MS") / 1000.0 \
        if base_s is None else float(base_s)
    max_s = knobs.get_int("H2O_TPU_RETRY_MAX_MS") / 1000.0 \
        if max_s is None else float(max_s)
    jitter = knobs.get_bool("H2O_TPU_RETRY_JITTER") \
        if jitter is None else bool(jitter)

    if isinstance(retryable, (tuple, type)):
        types = retryable if isinstance(retryable, tuple) else (retryable,)

        def _classify(exc):  # noqa: ANN001
            return isinstance(exc, types)
    else:
        _classify = retryable

    t0 = time.monotonic()
    tried = 0
    while True:
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classified right below
            verdict = _classify(e)
            if verdict is False or verdict is None:
                raise
            tried += 1
            elapsed = time.monotonic() - t0
            if tried >= attempts or elapsed >= budget_s:
                raise RetryBudgetExceeded(description, tried, elapsed,
                                          e) from e
            if isinstance(verdict, bool):
                delay = backoff_s(tried - 1, base_s, max_s, jitter)
            else:
                # server-directed delay wins EXACTLY (max_s caps only our
                # own backoff) — the budget clip below still bounds it
                delay = float(verdict)
            # never sleep past the budget — give up ON TIME, typed
            delay = min(delay, max(budget_s - elapsed, 0.0))
            from . import telemetry

            telemetry.inc("retry.attempt.count")
            if on_retry is not None:
                on_retry(e, tried, delay)
            if delay > 0:
                sleep(delay)
