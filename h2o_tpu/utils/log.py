"""Logging facade — analog of `water/util/Log.java` (SURVEY.md §2.1 Logging).

The reference buffers log lines until the log directory is known, writes
per-level files, and serves the buffer cluster-wide via `/3/Logs`
(`water/api/LogsHandler.java`). Here: a ring buffer (most recent N lines) on
top of the stdlib logging module; `/3/Logs` reads the buffer.
"""

from __future__ import annotations

import logging
import time
from collections import deque

_LOGGER = logging.getLogger("h2o_tpu")
_BUFFER: deque[str] = deque(maxlen=10_000)

_LEVELS = {"TRACE": 5, "DEBUG": logging.DEBUG, "INFO": logging.INFO,
           "WARN": logging.WARNING, "ERRR": logging.ERROR,
           "FATAL": logging.CRITICAL}


def _emit(level: str, msg: str):
    line = (f"{time.strftime('%m-%d %H:%M:%S')} {level.ljust(5)} "
            f"h2o_tpu: {msg}")
    _BUFFER.append(line)
    _LOGGER.log(_LEVELS.get(level, logging.INFO), msg)


def trace(msg: str):
    _emit("TRACE", msg)


def debug(msg: str):
    _emit("DEBUG", msg)


def info(msg: str):
    _emit("INFO", msg)


def warn(msg: str):
    _emit("WARN", msg)


def err(msg: str):
    _emit("ERRR", msg)


def get_buffer() -> list[str]:
    """Most recent log lines — the `/3/Logs` payload."""
    return list(_BUFFER)


def set_level(level: str):
    _LOGGER.setLevel(_LEVELS.get(level.upper(), logging.INFO))
