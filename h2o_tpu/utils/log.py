"""Logging facade — analog of `water/util/Log.java` (SURVEY.md §2.1 Logging).

The reference buffers log lines until the log directory is known, writes
per-level files, and serves the buffer cluster-wide via `/3/Logs`
(`water/api/LogsHandler.java`). Here: one BOUNDED in-process ring of typed
records (seq, wall stamp, level, message) behind the stdlib logging module.

Routing is unified through the ``h2o_tpu`` logger: the facade functions
(``info``/``warn``/...) emit stdlib records, and a ``RingHandler`` attached
once at import captures them — so BARE ``logging.getLogger("h2o_tpu.x")``
calls from any module land in the same ring the facade feeds, and `/3/Logs`
serves both. A WARNING-level stderr handler preserves the old console
visibility for warnings and errors (``set_level`` tunes it); the ring
itself always records every level, like the reference's always-on buffer.
"""

from __future__ import annotations

import itertools
import logging
import sys
import time
from collections import deque

_LOGGER = logging.getLogger("h2o_tpu")

_LEVELS = {"TRACE": 5, "DEBUG": logging.DEBUG, "INFO": logging.INFO,
           "WARN": logging.WARNING, "ERRR": logging.ERROR,
           "FATAL": logging.CRITICAL}
_NAMES = {v: k for k, v in _LEVELS.items()}

#: (seq, epoch seconds, 5-char level, message) — deque append is atomic,
#: the ring is bounded, and /3/Logs serializes at most `limit` entries
_BUFFER: deque = deque(maxlen=10_000)
_SEQ = itertools.count(1)


def _level_name(levelno: int) -> str:
    """Nearest declared level at or below the record's (stdlib loggers can
    emit any integer)."""
    best = "TRACE"
    for name, no in _LEVELS.items():
        if levelno >= no:
            best = name
    return best


class RingHandler(logging.Handler):
    """Captures every record under the ``h2o_tpu`` logger namespace into
    the ring — the seam that routes bare stdlib ``logging`` calls through
    this facade instead of losing them to the root logger."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — a broken format must not recurse
            msg = str(record.msg)
        _BUFFER.append((next(_SEQ), record.created,
                        _level_name(record.levelno), msg))


_RING_HANDLER = RingHandler(level=0)
_STDERR_HANDLER = logging.StreamHandler(sys.stderr)
_STDERR_HANDLER.setLevel(logging.WARNING)
_STDERR_HANDLER.setFormatter(logging.Formatter("h2o_tpu %(levelname)s: "
                                               "%(message)s"))
if not any(isinstance(h, RingHandler) for h in _LOGGER.handlers):
    _LOGGER.addHandler(_RING_HANDLER)
    _LOGGER.addHandler(_STDERR_HANDLER)
_LOGGER.setLevel(5)       # the ring records everything, always
_LOGGER.propagate = False  # our handlers own delivery — no double lines


def _emit(level: str, msg: str):
    _LOGGER.log(_LEVELS.get(level, logging.INFO), msg)


def trace(msg: str):
    _emit("TRACE", msg)


def debug(msg: str):
    _emit("DEBUG", msg)


def info(msg: str):
    _emit("INFO", msg)


def warn(msg: str):
    _emit("WARN", msg)


def err(msg: str):
    _emit("ERRR", msg)


def _format(rec: tuple) -> str:
    seq, created, level, msg = rec
    stamp = time.strftime("%m-%d %H:%M:%S", time.localtime(created))
    return f"{stamp} {level.ljust(5)} h2o_tpu: {msg}"


#: friendly spellings accepted anywhere a level filter is taken (`?level=
#: error` must not silently return nothing because the internal code is
#: the 5-char ERRR)
_LEVEL_ALIASES = {"TRACE": "TRACE", "DEBUG": "DEBUG", "INFO": "INFO",
                  "WARN": "WARN", "WARNING": "WARN", "ERR": "ERRR",
                  "ERRR": "ERRR", "ERROR": "ERRR", "FATAL": "FATAL",
                  "CRITICAL": "FATAL"}


def _select(limit: int | None, level: str | None) -> list[tuple]:
    """The ONE copy of the ring's filter semantics — both the formatted
    and the typed `/3/Logs` views read through it, so they cannot
    diverge."""
    recs = list(_BUFFER)
    if level is not None:
        want = _LEVEL_ALIASES.get(level.upper(), level.upper())
        recs = [r for r in recs if r[2] == want]
    if limit is not None and limit > 0:
        recs = recs[-limit:]
    return recs


def get_records(limit: int | None = None,
                level: str | None = None) -> list[dict]:
    """Typed recent records (newest last). ``level`` filters to exactly
    that declared level (the reference's per-level log files; friendly
    spellings like "error" accepted); ``limit`` keeps the most recent N
    after filtering."""
    return [{"seq": r[0], "ms": int(r[1] * 1000), "level": r[2],
             "msg": r[3]} for r in _select(limit, level)]


def get_buffer(limit: int | None = None,
               level: str | None = None) -> list[str]:
    """Most recent log lines, formatted — the `/3/Logs` payload."""
    return [_format(r) for r in _select(limit, level)]


def set_level(level: str):
    """Console verbosity (stderr handler) — the ring records every level
    regardless, like the reference's always-on buffer."""
    _STDERR_HANDLER.setLevel(_LEVELS.get(level.upper(), logging.INFO))
