"""Fleet observability — cross-process metric/trace aggregation.

The reference's diagnostic surface is CLUSTER-wide by construction: any
node answers `/3/Logs`/`/3/Timeline` for every node, because the water
cloud gossips state. This repo's processes are symmetric but isolated —
PR 6's registry is process-global, so the multi-process serving tier,
multi-HOST ingest workers and bench subprocesses each kept a private
view. This module is the merge point: a PULL-based collector that
scrapes N peer processes' `/3/Metrics` snapshots (plus a shared spool
directory for processes with no HTTP surface), merges them with
per-process labels, and concatenates per-process chrome-trace files into
one Perfetto session.

Sources, in merge order:

- **self** — this process's registry, read directly;
- **peers** — ``H2O_TPU_FLEET_PEERS`` (comma ``host:port`` or full URLs),
  each scraped as ``GET <peer>/3/Metrics`` with a bounded per-peer
  timeout (a dead replica bounds, never blocks, the view);
- **spool** — ``H2O_TPU_FLEET_SPOOL``: ``*.json`` snapshot files dropped
  by :func:`write_spool` from processes that serve no REST port (bench
  subprocesses, multihost workers).

Merge semantics (documented here because they are the contract the
serving tier and multi-HOST items build on):

- counters: SUM across processes + per-process values;
- gauges: per-process values + the max (``peak`` merged as max too);
- histograms: count/sum SUM; quantiles merged as the COUNT-WEIGHTED mean
  of per-process quantiles plus the max across processes — approximate
  by nature (true quantile merge needs mergeable sketches; the ROADMAP
  Rapids item grows those), and labeled as such in the payload.

Trace merge: every span event already carries its ``pid``, so one
Perfetto session is literally the concatenation of each process's event
list — ``merge_traces`` reads every ``trace_*.trace.json`` in a
directory (tolerating torn tails via ``telemetry.read_trace``) and
writes one well-formed JSON array.

This module is also a sanctioned ``jax.profiler`` site for graftlint
rule 19 (`unscoped-profiler-capture`) — it holds no capture today, but
a fleet-coordinated capture (start on every peer, pull the sessions)
lands here, not in ad-hoc scripts.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

from . import knobs, telemetry


def peers() -> list[str]:
    """Normalized peer scrape URLs from ``H2O_TPU_FLEET_PEERS``."""
    raw = knobs.get_str("H2O_TPU_FLEET_PEERS")
    out = []
    for tok in filter(None, (t.strip() for t in raw.split(","))):
        if not tok.startswith("http"):
            tok = f"http://{tok}"
        out.append(tok.rstrip("/"))
    return out


def _scrape_one(url: str, timeout_s: float) -> dict:
    """One peer's /3/Metrics snapshot, or a typed failure entry."""
    telemetry.inc("fleet.scrape.count")
    try:
        with urllib.request.urlopen(f"{url}/3/Metrics",
                                    timeout=timeout_s) as r:
            payload = json.loads(r.read().decode())
        return {"source": url, "ok": True,
                "pid": payload.get("pid"),
                "name": payload.get("name"),
                "ts_ms": payload.get("ts_ms"),
                "metrics": payload.get("metrics", {})}
    except Exception as e:  # noqa: BLE001 — a dead peer is a data point
        return {"source": url, "ok": False, "error": repr(e)}


def _spool_dir() -> str | None:
    return knobs.get_str("H2O_TPU_FLEET_SPOOL") or None


def write_spool(label: str | None = None) -> str | None:
    """Drop this process's snapshot into the shared spool (atomic rename
    — a concurrent collector never reads a torn file). The seam bench
    subprocesses and multihost workers use to join the fleet view without
    serving a port. No-op (None) when no spool is configured."""
    d = _spool_dir()
    if d is None:
        return None
    os.makedirs(d, exist_ok=True)
    rec = {"source": f"spool:{label or os.getpid()}", "ok": True,
           "pid": os.getpid(), "name": label or f"pid{os.getpid()}",
           "ts_ms": int(time.time() * 1000),
           "metrics": telemetry.snapshot()}
    path = os.path.join(d, f"{label or os.getpid()}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(rec, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def _read_spool() -> list[dict]:
    d = _spool_dir()
    if d is None or not os.path.isdir(d):
        return []
    max_age_s = knobs.get_int("H2O_TPU_FLEET_SPOOL_MAX_AGE_MS") / 1000.0
    out = []
    now = time.time()
    for fn in sorted(os.listdir(d)):
        if not fn.endswith(".json"):
            continue
        path = os.path.join(d, fn)
        try:
            age_s = now - os.stat(path).st_mtime
            if max_age_s > 0 and age_s > max_age_s:
                # a snapshot a dead process left behind must not merge as
                # live data forever — surfaced, not summed
                out.append({"source": f"spool:{fn}", "ok": False,
                            "error": f"stale spool snapshot "
                                     f"(age {age_s:.0f}s)"})
                continue
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            out.append({"source": f"spool:{fn}", "ok": False,
                        "error": repr(e)})
            continue
        if not isinstance(rec, dict):
            # a stray JSON array/scalar (e.g. a merged trace file dropped
            # into the same shared dir) must degrade to a typed entry,
            # not 500 the fleet endpoint
            out.append({"source": f"spool:{fn}", "ok": False,
                        "error": f"spool snapshot is "
                                 f"{type(rec).__name__}, expected object"})
            continue
        rec.setdefault("source", f"spool:{fn}")
        rec.setdefault("ok", True)
        out.append(rec)
    return out


def _label(proc: dict) -> str:
    pid = proc.get("pid")
    src = proc.get("source", "?")
    return f"{pid}@{src}" if pid is not None else src


_QKEYS = ("p50", "p95", "p99")


def _merge(procs: list[dict]) -> dict:
    metrics: dict[str, dict] = {}
    for proc in procs:
        if not proc.get("ok"):
            continue
        lbl = _label(proc)
        for name, rec in (proc.get("metrics") or {}).items():
            kind = rec.get("kind")
            m = metrics.setdefault(
                name, {"kind": kind, "per_process": {}})
            if kind == "counter":
                v = rec.get("value", 0) or 0
                m["per_process"][lbl] = v
                m["value"] = m.get("value", 0) + v
            elif kind == "gauge":
                v = rec.get("value", 0)
                pp = {"value": v}
                if "peak" in rec:
                    pp["peak"] = rec["peak"]
                    m["peak"] = max(m.get("peak", rec["peak"]), rec["peak"])
                m["per_process"][lbl] = pp
                m["max"] = max(m.get("max", v), v) if v is not None \
                    else m.get("max")
            else:  # histogram
                cnt = rec.get("count", 0) or 0
                m["per_process"][lbl] = {
                    k: rec.get(k) for k in ("count", "sum") + _QKEYS}
                m["count"] = m.get("count", 0) + cnt
                m["sum"] = round(m.get("sum", 0.0)
                                 + (rec.get("sum", 0.0) or 0.0), 6)
                for q in _QKEYS:
                    qv = rec.get(q)
                    if qv is None or not cnt:
                        continue
                    wsum, w = m.get(f"_{q}", (0.0, 0))
                    m[f"_{q}"] = (wsum + qv * cnt, w + cnt)
                    m[f"{q}_max"] = max(m.get(f"{q}_max", qv), qv)
    for m in metrics.values():
        if m.get("kind") == "histogram":
            for q in _QKEYS:
                acc = m.pop(f"_{q}", None)
                if acc and acc[1]:
                    # count-weighted mean of per-process quantiles — an
                    # APPROXIMATION (flagged below); the max is exact
                    m[q] = round(acc[0] / acc[1], 6)
            m["quantile_merge"] = "count-weighted mean (approximate)"
    return metrics


# one cached merge per process (H2O_TPU_FLEET_INTERVAL_MS window): a
# dashboard polling ?fleet=1 at 1s must not multiply every peer's scrape
# load by every poller
_CACHE_LOCK = threading.Lock()
_CACHE: dict = {"at": 0.0, "view": None}


def collect(force: bool = False) -> dict:
    """The merged fleet view behind ``GET /3/Metrics?fleet=1``."""
    interval_s = knobs.get_int("H2O_TPU_FLEET_INTERVAL_MS") / 1000.0
    with _CACHE_LOCK:
        if (not force and _CACHE["view"] is not None
                and time.monotonic() - _CACHE["at"] < interval_s):
            return _CACHE["view"]
    t0 = time.perf_counter()
    timeout_s = max(knobs.get_int("H2O_TPU_FLEET_TIMEOUT_MS"), 1) / 1000.0
    procs = [{"source": "self", "ok": True, "pid": os.getpid(),
              "ts_ms": int(time.time() * 1000),
              "metrics": telemetry.snapshot()}]
    plist = peers()
    if plist:
        # bounded parallel scrape: total wall ~= slowest peer, not the sum
        import concurrent.futures as cf

        with cf.ThreadPoolExecutor(max_workers=min(len(plist), 16)) as ex:
            # pool threads adopt the collector's span context so scrape
            # telemetry lands under the caller's trace (carry_context —
            # executor submits don't propagate contextvars)
            procs.extend(ex.map(
                telemetry.carry_context(
                    lambda u: _scrape_one(u, timeout_s)), plist))
    procs.extend(_read_spool())
    # dedup by pid, first entry wins (merge order: self > peers > spool):
    # a peer list that includes this process's own port, or a process
    # that both serves /3/Metrics and writes a spool snapshot, must not
    # have its counters SUMmed twice. (Caveat: pids can collide across
    # hosts — rare, and the alternative is the guaranteed double-count
    # of the uniform-peer-list deployment; the skipped entry stays
    # visible in `processes` with the reason.)
    seen_pids: set = set()
    for p in procs:
        pid = p.get("pid")
        if not p.get("ok") or pid is None:
            continue
        if pid in seen_pids:
            p["ok"] = False
            p["error"] = (f"duplicate pid {pid} — already merged from "
                          f"another source")
        else:
            seen_pids.add(pid)
    view = {
        "processes": [{k: p.get(k) for k in
                       ("source", "ok", "pid", "name", "ts_ms", "error")
                       if k in p} for p in procs],
        "live": sum(1 for p in procs if p.get("ok")),
        "metrics": _merge(procs),
        "ts_ms": int(time.time() * 1000),
    }
    telemetry.observe("fleet.scrape.seconds", time.perf_counter() - t0)
    with _CACHE_LOCK:
        _CACHE["at"] = time.monotonic()
        _CACHE["view"] = view
    return view


def invalidate_cache() -> None:
    """Drop the cached merge (tests / topology changes)."""
    with _CACHE_LOCK:
        _CACHE["at"] = 0.0
        _CACHE["view"] = None


def merge_traces(trace_dir: str, out_path: str | None = None,
                 extra_dirs=()) -> str:
    """Concatenate every per-process ``trace_*.trace.json`` in
    ``trace_dir`` (and any ``extra_dirs`` — processes that exported into
    their own directories merge into the same Perfetto session) into one
    well-formed chrome-trace array. Events keep their ``pid``, so
    Perfetto renders one track group per process — and since PR 15's
    wire/thread propagation, one REQUEST's spans carry one trace id
    across all of them. Returns the merged file's path."""
    events: list[dict] = []
    for d in (trace_dir, *extra_dirs):
        for fn in sorted(os.listdir(d)):
            if fn.startswith("trace_") and fn.endswith(".trace.json"):
                events.extend(telemetry.read_trace(os.path.join(d, fn)))
    events.sort(key=lambda e: e.get("ts", 0))
    out_path = out_path or os.path.join(trace_dir, "trace_merged.json")
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(events, f)
    os.replace(tmp, out_path)
    return out_path
