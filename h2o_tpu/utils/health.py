"""`GET /3/Health` — liveness/readiness with TYPED degradation reasons.

The autoscaling loop, the promote/rollback gate, and a multi-process
router's health-checker all need the same answer: "is this process fit
to take work, and if not, exactly why". The reference's `/3/Cloud`
``cloud_healthy`` is a bare boolean; this endpoint decomposes it into
checks over state the subsystems already publish, each reporting
``ok`` + a machine-readable degradation reason:

- **devices** — the mesh backend answers and has visible devices (a
  TPU runtime that lost its chips serves nothing);
- **cleaner-headroom** — Cleaner live bytes + the serving reservation
  ledger against the resolved HBM budget: headroom under
  ``H2O_TPU_HEALTH_HEADROOM_PCT`` means the next placement/rehydrate
  likely sweeps or OOMs;
- **serving-queue** — any served model's live queue past
  ``H2O_TPU_HEALTH_QUEUE_PCT`` of its bounded depth (the router should
  spray elsewhere BEFORE submits start bouncing 429);
- **job-heartbeat** — a RUNNING job with a stale progress beat (the
  watchdog's hung-job signal, evaluated inline so health works with the
  watchdog disarmed);
- **watchdog** — recent watchdog trips that haven't aged out;
- **slo-burn** — any declared SLO burning its budget faster than
  ``H2O_TPU_HEALTH_BURN_MAX``.

``ready`` is the AND of every check; ``live`` is the fact the handler
answered. Health polls are excluded from the timeline ring exactly like
the PR 6 monitoring polls — a 1-second readiness prober must not evict
the training spans the timeline exists to show.
"""

from __future__ import annotations

import os
import sys
import time

from . import knobs, slo, telemetry, watchdog


def _pct(name: str) -> float:
    return max(knobs.get_int(name), 0) / 100.0


def _check_devices() -> dict:
    jax = sys.modules.get("jax")
    if jax is None:
        # a control-plane process that never touched the backend is not
        # degraded — it has no device work to be unfit for
        return {"ok": True, "note": "backend not initialized"}
    try:
        devs = jax.devices()
    except Exception as e:  # noqa: BLE001 — a dead backend IS the finding
        return {"ok": False, "reason": "no-devices", "error": repr(e)}
    if not devs:
        return {"ok": False, "reason": "no-devices", "devices": 0}
    return {"ok": True, "devices": len(devs),
            "backend": jax.default_backend()}


def _check_cleaner() -> dict:
    mem = sys.modules.get("h2o_tpu.backend.memory")
    if mem is None:
        return {"ok": True, "note": "backend.memory not loaded"}
    try:
        live = mem.CLEANER.tracked_bytes()
        reserved = mem.reserved_bytes()
        limit = mem.CLEANER.limit_bytes()
    except Exception as e:  # noqa: BLE001
        return {"ok": False, "reason": "cleaner-unreadable",
                "error": repr(e)}
    out = {"live_bytes": live, "reserved_bytes": reserved,
           "limit_bytes": limit}
    if not limit:
        out["ok"] = True            # unlimited budget: headroom undefined
        return out
    # the Cleaner sweep threshold already subtracts reservations; health
    # judges the SAME accounting: committed = tracked + reserved vs the
    # base budget the reservations were debited from
    base = limit + reserved
    headroom = max(base - live - reserved, 0) / base
    out["headroom_fraction"] = round(headroom, 4)
    if headroom < _pct("H2O_TPU_HEALTH_HEADROOM_PCT"):
        out["ok"] = False
        out["reason"] = "cleaner-headroom"
    else:
        out["ok"] = True
    return out


def _check_serving() -> dict:
    rt_mod = sys.modules.get("h2o_tpu.serving.runtime")
    rt = getattr(rt_mod, "_RUNTIME", None) if rt_mod else None
    if rt is None:
        return {"ok": True, "note": "serving runtime not started"}
    saturation = _pct("H2O_TPU_HEALTH_QUEUE_PCT")
    hot = []
    with rt._lock:
        models = dict(rt._models)
    for mid, served in models.items():
        cap = served.cfg["queue_depth"] * max(len(served.replicas.replicas),
                                              1)
        depth = served.depth
        if cap and depth / cap >= saturation:
            hot.append({"model": mid, "depth": depth, "capacity": cap})
    if hot:
        return {"ok": False, "reason": "serving-queue-saturation",
                "models": hot}
    return {"ok": True, "models": len(models)}


def _check_jobs() -> dict:
    # the ONE hung-job rule (watchdog.stale_running_jobs) — evaluated
    # inline so the check works with the watchdog supervisor disarmed
    stale = watchdog.stale_running_jobs()
    if stale:
        return {"ok": False, "reason": "job-heartbeat", "jobs": stale}
    return {"ok": True}


def _check_watchdog() -> dict:
    dog = watchdog.instance()
    if dog is None:
        return {"ok": True, "note": "watchdog disarmed"}
    trips = dog.recent_trips()
    if trips:
        return {"ok": False, "reason": "watchdog-trip", "trips": trips}
    return {"ok": True, "sweeps": dog._sweeps}


def _check_slo(burns: dict) -> dict:
    max_burn = max(knobs.get_int("H2O_TPU_HEALTH_BURN_MAX"), 1)
    burning = {name: rec["burn"] for name, rec in burns.items()
               if (rec["burn"] or 0) > max_burn}
    if burning:
        return {"ok": False, "reason": "slo-burn", "burning": burning,
                "max_burn": max_burn}
    return {"ok": True, "max_burn": max_burn}


def snapshot() -> dict:
    """The full `GET /3/Health` payload. ``ready`` = every check ok;
    ``degraded`` lists the typed reasons (stable strings a poller can
    switch on) with their check details."""
    telemetry.inc("health.poll.count")
    burns = slo.burn_snapshot()
    checks = {
        "devices": _check_devices(),
        "cleaner": _check_cleaner(),
        "serving": _check_serving(),
        "jobs": _check_jobs(),
        "watchdog": _check_watchdog(),
        "slo": _check_slo(burns),
    }
    degraded = [{"check": name, "reason": rec.get("reason", name),
                 **{k: v for k, v in rec.items()
                    if k not in ("ok", "reason")}}
                for name, rec in checks.items() if not rec.get("ok", True)]
    return {
        "live": True,
        "ready": not degraded,
        "degraded": degraded,
        "checks": checks,
        "slo": burns,
        "pid": os.getpid(),
        "ts_ms": int(time.time() * 1000),
    }
