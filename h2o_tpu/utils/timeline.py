"""Timeline — per-process event ring buffer. Analog of `water/TimeLine.java`
(:12-40): a lock-free ring of the last 2048 events served at `/3/Timeline`.

The reference records every RPC packet send/recv. The TPU-native equivalents
are control-plane events: mr_task dispatches, job transitions, REST requests,
device transfers. Recording is cheap (deque append) and always on, like the
reference's always-on ring.
"""

from __future__ import annotations

import threading
import time
from collections import deque

_RING: deque[dict] = deque(maxlen=2048)
_LOCK = threading.Lock()


def record(kind: str, what: str, **detail):
    """Append one event; ns timestamps mirror TimeLine's nanotime entries."""
    ev = {"ns": time.perf_counter_ns(), "ms": int(time.time() * 1000),
          "kind": kind, "what": what}
    if detail:
        ev.update(detail)
    with _LOCK:
        _RING.append(ev)


def snapshot() -> list[dict]:
    """Ordered copy of the ring — the TimelineSnapshot/`/3/Timeline` payload."""
    with _LOCK:
        return sorted(_RING, key=lambda e: e["ns"])


def clear():
    with _LOCK:
        _RING.clear()
