"""Timeline — per-process event ring buffer. Analog of `water/TimeLine.java`
(:12-40): a lock-guarded ring of the most recent events served at
`/3/Timeline`.

The reference records every RPC packet send/recv. The TPU-native equivalents
are control-plane events: mr_task dispatch spans, job transitions, REST
requests, Cleaner spills/sweeps, failpoint fires. Recording is cheap (deque
append) and always on, like the reference's always-on ring.

Every event is TYPED: ``seq`` (monotone insertion order — the sort key;
``perf_counter_ns`` ties are possible on coarse clocks and wall ``ms`` can
step backwards under NTP), ``ns``/``ms`` stamps, ``kind``, ``what``, plus
kind-specific detail keys flat on the event (a span's ``dur_us``/``trace``,
a spill's ``bytes``, ...). ``snapshot(limit=...)`` caps how much the REST
path serializes; ``total_recorded()`` tells a poller how many events ever
happened so it can report drops. Ring capacity comes from the
``H2O_TPU_TIMELINE_EVENTS`` knob (read once at import).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

from . import knobs


def _capacity() -> int:
    return max(knobs.get_int("H2O_TPU_TIMELINE_EVENTS"), 64)


_RING: deque = deque(maxlen=_capacity())
_LOCK = threading.Lock()
_SEQ = itertools.count(1)
_LAST_SEQ = 0


def record(kind: str, what: str, **detail):
    """Append one typed event; ns timestamps mirror TimeLine's nanotime
    entries, seq pins the order even across clock ties. Honors the
    ``H2O_TPU_METRICS_ENABLED`` master switch (one gate for every direct
    call site — jobs, REST, Cleaner, failpoints, compiles — matching the
    telemetry registry's contract)."""
    if not knobs.get_bool("H2O_TPU_METRICS_ENABLED"):
        return
    global _LAST_SEQ
    ev = {"seq": 0, "ns": time.perf_counter_ns(),
          "ms": int(time.time() * 1000), "kind": kind, "what": what}
    if detail:
        ev.update(detail)
    with _LOCK:
        ev["seq"] = _LAST_SEQ = next(_SEQ)
        _RING.append(ev)


def snapshot(limit: int | None = None, kind: str | None = None,
             since: int | None = None) -> list[dict]:
    """Ordered copy of the ring — the `/3/Timeline` payload. ``limit`` keeps
    only the most recent N events (serialization cost cap for the REST
    path); ``kind`` filters by event kind first; ``since`` keeps only
    events with a LARGER seq — the incremental poll cursor (seq is the
    monotone sort key, so `?since=<last seen seq>` resumes exactly where
    the previous pull stopped instead of re-serializing the whole ring).

    The bias of ``limit`` FLIPS with the cursor: a plain snapshot keeps
    the NEWEST N (a human asking "what just happened"), but a cursored
    pull keeps the OLDEST N past the cursor — a catch-up poller advances
    its cursor to the last returned seq and re-polls, so a >N-event gap
    drains losslessly over several pulls instead of silently dropping
    its middle."""
    with _LOCK:
        # seq assignment and append share the lock above, so the deque is
        # already seq-ordered — no sort needed
        evs = list(_RING)
    # since=0 IS a cursor (a collector bootstrapping from the beginning,
    # oldest-first); None means no cursor (the newest-biased human view)
    cursored = since is not None
    if cursored and since > 0:
        # the ring is ordered: bisect by seq instead of filtering 4096
        # events per poll (the cursor exists to make polling cheap)
        import bisect

        idx = bisect.bisect_right([e["seq"] for e in evs], since)
        evs = evs[idx:]
    if kind is not None:
        evs = [e for e in evs if e["kind"] == kind]
    if limit is not None and limit > 0:
        evs = evs[:limit] if cursored else evs[-limit:]
    return evs


def total_recorded() -> int:
    """Events ever recorded (ring evictions included) — lets a /3/Timeline
    poller compute how many events it missed between polls."""
    with _LOCK:
        return _LAST_SEQ


def capacity() -> int:
    return _RING.maxlen or 0


def clear():
    with _LOCK:
        _RING.clear()
