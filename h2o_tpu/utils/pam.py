"""PAM authentication — the `h2o-jaas-pam` analog (`de/codedo/jaas/
PamLoginModule.java`), straight onto ``libpam`` through ctypes (no JAAS, no
python-pam dependency: the conversation callback supplies the password and
``pam_authenticate`` + ``pam_acct_mgmt`` decide).

Usage: ``H2OServer(auth_check=PamAuth(service="login"))`` — the same
pluggable Basic-auth seam LDAP uses (`utils/ldap.py`).
"""

from __future__ import annotations

import ctypes
import ctypes.util

PAM_SUCCESS = 0
PAM_PROMPT_ECHO_OFF = 1
PAM_PROMPT_ECHO_ON = 2


class _PamMessage(ctypes.Structure):
    _fields_ = [("msg_style", ctypes.c_int), ("msg", ctypes.c_char_p)]


class _PamResponse(ctypes.Structure):
    _fields_ = [("resp", ctypes.c_char_p), ("resp_retcode", ctypes.c_int)]


#: int conv(int num_msg, const struct pam_message **msg,
#:          struct pam_response **resp, void *appdata) — Linux-PAM passes an
#: array of POINTERS to messages (msg[i] is message i)
_CONV_FUNC = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int,
    ctypes.POINTER(ctypes.POINTER(_PamMessage)),
    ctypes.POINTER(ctypes.POINTER(_PamResponse)), ctypes.c_void_p)


class _PamConv(ctypes.Structure):
    _fields_ = [("conv", _CONV_FUNC), ("appdata_ptr", ctypes.c_void_p)]


def _load_libpam():
    name = ctypes.util.find_library("pam") or "libpam.so.0"
    lib = ctypes.CDLL(name)
    lib.pam_start.restype = ctypes.c_int
    lib.pam_start.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                              ctypes.POINTER(_PamConv),
                              ctypes.POINTER(ctypes.c_void_p)]
    lib.pam_authenticate.restype = ctypes.c_int
    lib.pam_authenticate.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.pam_acct_mgmt.restype = ctypes.c_int
    lib.pam_acct_mgmt.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.pam_end.restype = ctypes.c_int
    lib.pam_end.argtypes = [ctypes.c_void_p, ctypes.c_int]
    return lib


_LIBC = ctypes.CDLL(None)
_LIBC.malloc.restype = ctypes.c_void_p  # default int truncates on 64-bit
_LIBC.malloc.argtypes = [ctypes.c_size_t]


def make_conv(password: str) -> _PamConv:
    """The PAM conversation: answer every echo-off/echo-on prompt with the
    password (the PamLoginModule does exactly this for its two-prompt
    flow). Responses must be malloc'd — pam frees them."""
    pw = password.encode()

    def conv(n_msg, msgs, resp_out, appdata):
        # pam frees the response array AND each resp string: allocate both
        # with libc malloc, never python-managed memory
        arr = _LIBC.malloc(n_msg * ctypes.sizeof(_PamResponse))
        if not arr:
            return 5  # PAM_BUF_ERR
        ctypes.memset(arr, 0, n_msg * ctypes.sizeof(_PamResponse))
        responses = ctypes.cast(arr, ctypes.POINTER(_PamResponse))
        for i in range(n_msg):
            style = msgs[i].contents.msg_style
            if style in (PAM_PROMPT_ECHO_OFF, PAM_PROMPT_ECHO_ON):
                buf = _LIBC.malloc(len(pw) + 1)
                ctypes.memmove(buf, pw + b"\0", len(pw) + 1)
                responses[i].resp = ctypes.cast(buf, ctypes.c_char_p)
        resp_out[0] = responses
        return PAM_SUCCESS

    cb = _CONV_FUNC(conv)
    out = _PamConv(cb, None)
    out._cb_ref = cb  # the struct stores only the C pointer: keep the
    out._fn_ref = conv  # python objects alive for the pam handle's lifetime
    return out


class PamAuth:
    """``(user, password) -> bool`` against a PAM service stack."""

    def __init__(self, service: str = "login"):
        self.service = service
        self._lib = _load_libpam()

    def __call__(self, user: str, password: str) -> bool:
        if not user or "\0" in user or "\0" in password:
            return False
        conv = make_conv(password)
        handle = ctypes.c_void_p()
        rc = self._lib.pam_start(self.service.encode(), user.encode(),
                                 ctypes.byref(conv), ctypes.byref(handle))
        if rc != PAM_SUCCESS:
            return False
        try:
            rc = self._lib.pam_authenticate(handle, 0)
            if rc != PAM_SUCCESS:
                return False
            return self._lib.pam_acct_mgmt(handle, 0) == PAM_SUCCESS
        finally:
            self._lib.pam_end(handle, rc)
