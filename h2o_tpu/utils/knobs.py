"""Central registry of every ``H2O_TPU_*`` environment knob.

The reference keeps its expert properties behind one reflective surface
(`water/H2O.OptArgs` + `sys.ai.h2o.*` system properties); this repo grew the
same knobs ad hoc — `os.environ.get("H2O_TPU_...")` scattered through the
runtime, each with its own inline default and no single place a reader (or a
linter) can ask "what knobs exist and what do they do". This module is that
place: every knob is declared HERE with its name, type, default, and one-line
docstring, and graftlint's ``unregistered-knob`` rule fails the build on any
literal ``H2O_TPU_*`` environment read whose name is not declared below
(`tools/graftlint/rules.py` parses this file's AST — no import needed).

Reads stay dynamic: accessors consult ``os.environ`` at call time, so tests
that monkeypatch the environment keep working, and `utils/optargs.py`'s
"CLI > env > default, exported back to env" contract is untouched — this
registry documents and types the env surface, it does not cache it.

Accessors:

- ``raw(name, default=None)``  — exact ``os.environ.get`` semantics (string
  or the given default), plus the registration check. The graftlint
  ``--fix`` rewrite targets this: ``os.environ.get("H2O_TPU_X", d)`` →
  ``knobs.raw("H2O_TPU_X", d)`` is behavior-preserving.
- ``get_str/get_int/get_bool(name, default=...)`` — typed reads falling back
  to the REGISTERED default when the variable is unset/empty (an explicit
  ``default=`` overrides the registered one).

Every accessor raises ``KeyError`` for an undeclared name, so a new knob
cannot ship without a registry line (the same invariant the linter enforces
statically).
"""

from __future__ import annotations

import dataclasses
import os

_MISSING = object()

#: strings that read as False for bool knobs (superset of the historic
#: per-site spellings: BINNED_STORE used {0,false,off}, ALLOW_WIRE_UDF
#: {0,false}). Set-but-EMPTY is handled as UNSET, not falsy — a stale
#: `export VAR=` line must not silently flip BINNED_STORE/ALLOW_WIRE_UDF
#: off (their pre-registry reads defaulted "" to on).
_FALSY = ("0", "false", "off", "no")


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    kind: str           # "str" | "int" | "bool"
    default: object
    doc: str


KNOBS: dict[str, Knob] = {}


def _knob(name: str, kind: str, default, doc: str) -> None:
    KNOBS[name] = Knob(name, kind, default, doc)


# -- launcher / runtime (mirrors utils/optargs.py, the CLI surface) ---------
_knob("H2O_TPU_REST_PORT", "int", 54321,
      "REST API port (optargs --port)")
_knob("H2O_TPU_DRIVER", "str", "",
      "python module run as the multi-host SPMD driver instead of REST")
_knob("H2O_TPU_ASSISTED_CLUSTERING", "bool", False,
      "start the clustering sidecar API before touching any JAX backend")
_knob("H2O_TPU_ASSISTED_CLUSTERING_API_PORT", "int", 8080,
      "port for the assisted-clustering sidecar API")
_knob("H2O_TPU_PROCESS_ID", "int", 0,
      "this process's rank when joining an assisted-clustering cloud")
_knob("H2O_TPU_ICE_DIR", "str", "",
      "spill directory for the HBM Cleaner and NodePersistentStorage")
_knob("H2O_TPU_NPS_DIR", "str", "",
      "NodePersistentStorage root (default: <ice>/nps)")

# -- memory / frames --------------------------------------------------------
_knob("H2O_TPU_HBM_LIMIT_BYTES", "int", 0,
      "pin the Cleaner/planner HBM budget exactly (0/unset = backend "
      "resolution: memory_stats -> device_kind table -> unlimited)")
_knob("H2O_TPU_MAX_FRAME_BYTES", "int", 12 * 1024 ** 3,
      "refuse parses whose f32 frame would exceed this (FrameSizeMonitor)")
_knob("H2O_TPU_BINNED_STORE", "bool", True,
      "train trees from the chunk store's int8/int16 binned view instead "
      "of the stacked f32 matrix (frame/chunks.py); 0 reverts")
_knob("H2O_TPU_ROW_SHARDS", "int", 0,
      "row shards of the lazily-built default mesh (parallel/mesh.py): "
      "how many devices split the data-parallel 'rows' axis; 0/unset = "
      "all devices (the historic default). Read ONCE at mesh "
      "construction — set it before any frame is placed (the bench "
      "'sharded' leg runs each value in its own subprocess)")
_knob("H2O_TPU_SHARDED_MERGE", "bool", True,
      "run the rapids merge expansion phase-2 sharded over the mesh rows "
      "axis (explicit per-shard delta-scatter+cumsum fills inside "
      "shard_map, rapids/merge.py); 0 reverts to the replicated oracle "
      "the sharded path is bit-parity-pinned against")

# -- engine knobs -----------------------------------------------------------
_knob("H2O_TPU_EXACT_BIN_ROWS", "int", 16384,
      "rows at or below which tree binning may use exact small-data cuts")
_knob("H2O_TPU_HIST_SEG_WIDTH", "int", 8,
      "bin widths at/below this accumulate via segment-sum instead of the "
      "one-hot matmul in the histogram scan (0 disables the path); also "
      "bounds the widest VMEM accumulator slab a narrow group hands the "
      "pallas hist kernel (backend/kernels/hist.py)")
_knob("H2O_TPU_PIPELINE", "bool", True,
      "async pipelined GBM/DRF level program: route->hist fused into one "
      "streamed pass per row block, gather-formulated routing, cadence "
      "scoring fused into the chunk step, donated margin carry. "
      "BIT-equal to the synchronous oracle; 0 reverts to the two-pass "
      "level program (models/tree/engine.py)")
_knob("H2O_TPU_ASYNC_PSUM", "bool", True,
      "overlapped per-level histogram reduction: each width bucket's ICI "
      "psum is issued before the next bucket's local scan so the "
      "collective hides under compute; 0 reverts to the PR 10 shape "
      "(one joint scan, psums after). Bit-equal either way")
_knob("H2O_TPU_GOSS", "str", "",
      "GOSS-style gradient-based row sampling for GBM, 'a,b' fractions "
      "(e.g. 0.2,0.1): per shard the top-a rows by |gradient| plus a "
      "uniform b of the rest (amplified by (1-a)/b) feed the histogram "
      "and leaf passes. Deterministic under the train seed; changes the "
      "forest (a sampler, not an oracle-parity mode); empty = off")
_knob("H2O_TPU_HIST_KERNEL", "str", "auto",
      "kernels-layer backend for the level-histogram and Gram "
      "accumulations (backend/kernels/): 'pallas' = fused pl.pallas_call "
      "(interpreted off-TPU), 'xla' = the blocked lax.scan oracle, "
      "'auto' = pallas on real TPU backends, xla elsewhere")
_knob("H2O_TPU_CLEAR_CACHES_EVERY", "int", 64,
      "drop live XLA executables every N models (long-server hygiene; "
      "0 = never)")
_knob("H2O_TPU_PDP_BATCH_ROWS", "int", 2_000_000,
      "row budget per batched partial-dependence predict")
_knob("H2O_TPU_COMPILE_CACHE", "str", "",
      "persistent XLA compile cache dir ('0' disables; empty = backend "
      "default: on for accelerators, off for CPU)")

# -- serving (h2o_tpu/serving/ online scoring runtime) ----------------------
_knob("H2O_TPU_SERVING_BUCKETS", "str", "1,4,16,64,128,256,512",
      "comma list of padded-batch bucket sizes the serving scorer "
      "AOT-compiles at registration; requests pad up to the smallest "
      "bucket that fits, larger batches chunk through the biggest. "
      "Dense x2 steps at the top where scoring is linear in rows and "
      "padding waste costs real milliseconds; x4 at the bottom where "
      "dispatch overhead dominates (measured: a 119-row batch scores "
      "3.6 ms at bucket 128 vs 20 ms padded to 512)")
_knob("H2O_TPU_SERVING_MAX_BATCH", "int", 512,
      "most rows the micro-batcher coalesces into one device call "
      "(effectively clamped to the largest compiled bucket)")
_knob("H2O_TPU_SERVING_MAX_WAIT_US", "int", 2000,
      "how long the micro-batch worker holds the first queued request "
      "open for coalescing before dispatching a partial batch (0 = "
      "dispatch immediately, no coalescing window)")
_knob("H2O_TPU_SERVING_QUEUE_DEPTH", "int", 1024,
      "bounded request-queue depth per served model; submits beyond it "
      "are rejected with QueueFullError (REST: 429 + Retry-After)")
_knob("H2O_TPU_SERVING_DEADLINE_MS", "int", 1000,
      "default per-request deadline; a request still queued past it "
      "raises DeadlineExceededError (REST: 408); 0 = no deadline")
_knob("H2O_TPU_SERVING_STATS_WINDOW", "int", 2048,
      "ring-buffer length of the per-model latency/throughput window "
      "behind GET /3/Serving/stats")

# -- serving control plane (h2o_tpu/serving/control.py + router.py) ----------
_knob("H2O_TPU_SERVING_QUOTA_FRACTION", "str", "0.35",
      "fraction of the resolved Cleaner HBM budget the serving fleet may "
      "reserve for placed models (admission rejects registrations beyond "
      "it with 429 + Retry-After); models place unlimited when no budget "
      "resolves (CPU without H2O_TPU_HBM_LIMIT_BYTES)")
_knob("H2O_TPU_SERVING_PRIORITY", "str", "hot",
      "default placement priority class for registrations that don't pass "
      "one: 'hot' pins residency (never evicted while registered), 'cold' "
      "is evictable under quota pressure and lazily re-placed — paying "
      "its bucket compiles again — on first hit")
_knob("H2O_TPU_SERVING_REPLICAS", "int", 1,
      "default replica scorers per registration; replicas are placed "
      "round-robin across mesh devices and dispatched least-loaded by "
      "live batcher queue depth")
_knob("H2O_TPU_SERVING_ROUTE_SEED", "int", 42,
      "default seed for a route's deterministic weighted split when the "
      "route doesn't carry its own (same seed + same request order = "
      "exactly the same variant sequence)")
_knob("H2O_TPU_SERVING_SHADOW", "bool", True,
      "master switch for shadow traffic: 0 skips off-response-path "
      "shadow scoring (and divergence stats) even on routes that "
      "configure shadow variants")
_knob("H2O_TPU_CLIENT_KEEPALIVE", "bool", True,
      "pool one persistent HTTP connection per client thread "
      "(api/client.py), auto-reconnecting on a stale socket; 0 reverts "
      "to one connection per request (the serving_wire bench baseline)")

# -- runtime sanitizers (utils/sanitizer.py) ---------------------------------
_knob("H2O_TPU_SANITIZE", "str", "",
      "comma list of runtime sanitizer modes (utils/sanitizer.py): "
      "'locks' = instrumented lock wrappers that track per-thread "
      "acquisition stacks + the global lock-order graph and raise a "
      "typed LockOrderViolation on an OBSERVED inversion; 'guards' = "
      "@guarded_by('_lock') assertions on lock-protected methods; "
      "'transfers' = jax transfer guards scoped over the hot sections "
      "(train chunk dispatch, MRTask dispatch, serving score path, "
      "Cleaner sweep) raising a typed TransferGuardViolation on an "
      "implicit device->host conversion; 'recompiles' = any uncached "
      "XLA compile inside a declared-steady section (GBM post-first-"
      "boundary, serving post-registration) raises a typed "
      "SteadyStateCompileError. locks are consulted at construction — "
      "build the runtime after setting it; empty = zero overhead")

# -- fault tolerance (failpoints / auto-checkpoints / retry) ----------------
_knob("H2O_TPU_FAILPOINTS", "str", "",
      "comma list of site:spec deterministic fault injections "
      "(utils/failpoints.py — spec grammar: action[(arg)][*N|@K], action "
      "in raise|sleep|http); empty = nothing armed")
_knob("H2O_TPU_CHECKPOINT_SECS", "int", 600,
      "wall-clock seconds between auto-recovery checkpoints while a "
      "training job with auto_recovery_dir runs; 0 = checkpoint at every "
      "iteration boundary (the kill-resume parity tests pin this)")
_knob("H2O_TPU_AUTO_RECOVERY_DIR", "str", "",
      "base auto-recovery directory armed for every training job whose "
      "params leave auto_recovery_dir unset (preemption-proof by default "
      "on preemptible pools); each job checkpoints into its own "
      "<algo>_<pid>_<jobkey> subdirectory so overlapping jobs never "
      "clobber each other — resume_training takes that subdir; empty = off")
_knob("H2O_TPU_RETRY_ATTEMPTS", "int", 4,
      "total tries (first call + retries) utils/retry.py allows before "
      "raising the typed RetryBudgetExceeded")
_knob("H2O_TPU_RETRY_BASE_MS", "int", 100,
      "first-retry backoff in ms; doubles per retry (full jitter unless "
      "H2O_TPU_RETRY_JITTER=0)")
_knob("H2O_TPU_RETRY_MAX_MS", "int", 5000,
      "backoff cap in ms — also caps server-directed Retry-After sleeps")
_knob("H2O_TPU_RETRY_BUDGET_MS", "int", 20000,
      "wall-clock retry budget in ms; exceeded -> RetryBudgetExceeded "
      "even with attempts left")
_knob("H2O_TPU_RETRY_JITTER", "bool", True,
      "0 pins backoff to the deterministic cap sequence (tests); default "
      "full jitter so a fleet never thunders back in lockstep")

# -- observability (utils/telemetry.py + timeline.py) ------------------------
_knob("H2O_TPU_METRICS_ENABLED", "bool", True,
      "master switch for the telemetry registry + span/timeline/trace "
      "recording, including every direct timeline.record site (always-on "
      "by default, like the reference's TimeLine ring; 0 skips the writes "
      "but keeps name validation)")
_knob("H2O_TPU_METRICS_HIST_WINDOW", "int", 1024,
      "observations kept per histogram metric (read at import) — "
      "percentiles in /3/Metrics describe this recent window, memory "
      "stays bounded")
_knob("H2O_TPU_TIMELINE_EVENTS", "int", 4096,
      "capacity of the /3/Timeline event ring (read at import; the "
      "reference's TimeLine keeps 2048)")
_knob("H2O_TPU_TRACE_DIR", "str", "",
      "directory for per-process chrome-tracing span exports "
      "(trace_<pid>.trace.json, loadable in Perfetto); empty = off")

# -- fleet observability plane (programs / profiler / fleetobs / flightrec) --
_knob("H2O_TPU_PROFILE_DIR", "str", "",
      "arm span-scoped jax.profiler device capture: training jobs wrap "
      "their root span in a bounded profiler session written under this "
      "directory, and the live span stack mirrors into TraceAnnotations "
      "so XLA ops nest under the telemetry span names in Perfetto "
      "(utils/telemetry.py device_profile — the only sanctioned capture "
      "site, graftlint rule unscoped-profiler-capture); empty = off")
_knob("H2O_TPU_FLIGHT_DIR", "str", "",
      "crash flight-recorder bundle directory (utils/flightrec.py): "
      "typed terminal events (device OOM after emergency sweep, "
      "LockOrderViolation, unhandled train/serving crash, the armed "
      "flightrec.dump drill failpoint) write an atomic diagnostics "
      "bundle — metrics/timeline/logs/thread-dump/Cleaner ledger/program "
      "registry/knobs — here; empty = off")
_knob("H2O_TPU_FLIGHT_MAX_BUNDLES", "int", 32,
      "most flight bundles kept in H2O_TPU_FLIGHT_DIR before the oldest "
      "are reaped (a crash storm must not fill the disk)")
_knob("H2O_TPU_FLEET_PEERS", "str", "",
      "comma list of peer-process /3/Metrics endpoints "
      "(host:port or full http:// URLs) the fleet collector scrapes for "
      "GET /3/Metrics?fleet=1 (utils/fleetobs.py); empty = self (+ spool)")
_knob("H2O_TPU_FLEET_SPOOL", "str", "",
      "shared spool directory where non-HTTP processes (bench "
      "subprocesses, batch workers) drop metric snapshots "
      "(fleetobs.write_spool) for the fleet merge; empty = off")
_knob("H2O_TPU_FLEET_TIMEOUT_MS", "int", 500,
      "per-peer scrape timeout for the fleet collector — one slow/dead "
      "replica bounds, not blocks, the merged view")
_knob("H2O_TPU_FLEET_SPOOL_MAX_AGE_MS", "int", 900_000,
      "spool snapshots older than this (file mtime) are reported stale "
      "instead of merged — a dead process's last snapshot must not sum "
      "into the fleet totals forever (0 = no cutoff)")
_knob("H2O_TPU_FLEET_INTERVAL_MS", "int", 0,
      "minimum ms between live fleet scrapes; within the window "
      "GET /3/Metrics?fleet=1 serves the cached merge (0 = scrape on "
      "every request)")

# -- causal observability plane (slo / watchdog / slowtrace / health) --------
_knob("H2O_TPU_SLO", "str", "",
      "per-deployment SLO overrides (utils/slo.py) as comma-separated "
      "'<slo>.p99_ms=<ms>' / '<slo>.error_budget=<frac>' pairs, e.g. "
      "'serving.score.p99_ms=50,rest.request.error_budget=0.05'; "
      "undeclared SLO names raise KeyError (the knobs discipline); "
      "empty = the declared defaults")
_knob("H2O_TPU_SLO_WINDOW_S", "int", 300,
      "rolling window (seconds) the SLO error-burn rate is computed "
      "over; latency burn rides the telemetry histogram rings' own "
      "H2O_TPU_METRICS_HIST_WINDOW observation window")
_knob("H2O_TPU_SLOWTRACE_KEEP", "int", 64,
      "slow-request capture ring size (utils/slowtrace.py): how many "
      "SLO-p99-breaching requests keep their full span tree + program "
      "dispatch walls behind GET /3/SlowTraces (newest win)")
_knob("H2O_TPU_SLOWTRACE_MIN_MS", "int", 0,
      "floor for slow-request capture: requests faster than this never "
      "persist even when their SLO p99 target is lower (a deliberately "
      "tight test SLO must not flood the ring in production); 0 = the "
      "SLO targets alone decide")
_knob("H2O_TPU_WATCHDOG_MS", "int", 0,
      "watchdog supervisor sweep interval (utils/watchdog.py): every "
      "interval one thread checks for hung jobs, stalled MRTask "
      "dispatch, Cleaner spill/rehydrate thrash and serving queue "
      "stalls, each trip landing a typed timeline event + Prometheus "
      "gauge + proactive flight bundle; 0 = disarmed (no thread)")
_knob("H2O_TPU_WATCHDOG_JOB_BUDGET_MS", "int", 120_000,
      "a RUNNING job whose progress heartbeat (Job.beat — fed by every "
      "update/check_cancelled at chunk/epoch boundaries) is older than "
      "this trips the hung-job detector")
_knob("H2O_TPU_WATCHDOG_DISPATCH_BUDGET_MS", "int", 60_000,
      "an MRTask driver dispatch in flight longer than this trips the "
      "mrtask-stall detector (parallel/mrtask.py in-flight table)")
_knob("H2O_TPU_WATCHDOG_QUEUE_BUDGET_MS", "int", 10_000,
      "a serving batcher whose OLDEST queued request has waited longer "
      "than this trips the queue-stall detector (worker wedged or "
      "paused under live traffic)")
_knob("H2O_TPU_WATCHDOG_THRASH_OPS", "int", 16,
      "Cleaner spill AND rehydrate counters both advancing more than "
      "this within one watchdog interval trips the cleaner-thrash "
      "detector (evict/reload churn — the memory death spiral)")
_knob("H2O_TPU_HEALTH_HEADROOM_PCT", "int", 5,
      "GET /3/Health reports cleaner-headroom degradation when free HBM "
      "under the resolved budget (Cleaner live bytes + the serving "
      "reservation ledger both debited) falls below this percent")
_knob("H2O_TPU_HEALTH_QUEUE_PCT", "int", 80,
      "GET /3/Health reports serving-queue-saturation when any served "
      "model's live queue depth reaches this percent of its bounded "
      "capacity (the router should spray elsewhere BEFORE 429s start)")
_knob("H2O_TPU_HEALTH_BURN_MAX", "int", 10,
      "GET /3/Health reports slo-burn degradation when any declared "
      "SLO's burn rate exceeds this multiple of its error budget "
      "(burn 1.0 = exactly consuming the budget)")

# -- workload manager (h2o_tpu/workload/) ------------------------------------
_knob("H2O_TPU_TENANT", "str", "",
      "tenant this process submits work as (workload/tenants.py); the "
      "client attaches it as the X-H2O-TPU-Tenant request header, the "
      "server scopes each request's jobs/quota to it; empty = the "
      "'default' tenant (legacy single-tenant callers)")
_knob("H2O_TPU_WORKLOAD_SLOTS", "int", 0,
      "concurrent managed jobs the workload manager dispatches "
      "(workload/manager.py); excess submissions queue and drain under "
      "weighted fair-share, and preempted jobs auto-resume when a slot "
      "frees; 0 = unmanaged (every submit dispatches immediately — the "
      "legacy single-tenant behavior, no queueing, no auto-resume)")
_knob("H2O_TPU_WORKLOAD_SEED", "int", 42,
      "seed for the fair-share dispatch lottery (splitmix64, the PR 8 "
      "router construction) — same seed + same submission sequence = "
      "same dispatch order")
_knob("H2O_TPU_WORKLOAD_AGING", "int", 8,
      "starvation bound for the fair-share lottery: an entry that loses "
      "this many consecutive drawings is force-dispatched next, so the "
      "worst-case queue delay is bounded deterministically")
_knob("H2O_TPU_WORKLOAD_QUOTA", "str", "",
      "per-tenant HBM quota fractions as comma-separated "
      "'<tenant>=<frac>' pairs (e.g. 'team-a=0.5,team-b=0.25'); each "
      "fraction is taken of backend/memory.py base_hbm_limit_bytes() "
      "and debited through the one reservation ledger; unlisted tenants "
      "are unlimited; ignored entirely when no HBM budget resolves")
_knob("H2O_TPU_WORKLOAD_TICK_MS", "int", 1000,
      "workload maintenance cadence: how often the manager re-pumps the "
      "queue, re-admits parked (preempted) jobs and evaluates the "
      "SLO/health shed policy while managed work exists")
_knob("H2O_TPU_WORKLOAD_SHED_BURN", "int", 0,
      "shed policy trigger: when slo.worst_burn exceeds this multiple "
      "(or /3/Health degrades with cleaner-headroom / "
      "serving-queue-saturation), the highest-pressure tenant's lowest-"
      "priority running job is preempted at its next boundary; 0 = "
      "shed only on typed health degradation, never on burn alone")
_knob("H2O_TPU_WORKLOAD_RETRY_S", "int", 5,
      "seconds a shed (load-shed, not priority-preempted) job stays "
      "parked before re-admission is considered; also the Retry-After "
      "hint on 429 quota rejections")
_knob("H2O_TPU_WORKLOAD_DISPATCH_SLOTS", "int", 0,
      "concurrent MRTask driver dispatches allowed across tenants "
      "(workload/fairshare.py gate at parallel/mrtask.py _dispatch); "
      "waiters wake in weighted-fair order (lowest virtual time first); "
      "0 = ungated (the single-tenant default)")

# -- security ---------------------------------------------------------------
_knob("H2O_TPU_ALLOW_WIRE_UDF", "bool", True,
      "allow python: UDF references uploaded over the wire to execute")

# -- external systems -------------------------------------------------------
_knob("H2O_TPU_WEBHDFS_URL", "str", "",
      "explicit WebHDFS endpoint for hdfs:// persist")
_knob("H2O_TPU_WEBHDFS_PORT", "int", 9870,
      "WebHDFS port when hdfs:// URIs carry none")
_knob("H2O_TPU_HDFS_USER", "str", "",
      "user.name forwarded to WebHDFS (default: $USER)")
_knob("H2O_TPU_HIVE_JDBC", "str", "",
      "Hive JDBC endpoint for ImportHiveTable")

# -- bench.py ---------------------------------------------------------------
_knob("H2O_TPU_BENCH_ROWS", "int", 11_000_000,
      "rows for the HIGGS-shaped bench frame")
_knob("H2O_TPU_BENCH_TREES", "int", 100,
      "trees for the bench GBM legs")
_knob("H2O_TPU_BENCH_SORT_ROWS", "int", 100_000_000,
      "rows for the sort/merge bench legs")
_knob("H2O_TPU_BENCH_AIRLINES_ROWS", "int", 116_000_000,
      "rows for the airlines train-to-AUC leg")
_knob("H2O_TPU_BENCH_BINNED_ROWS", "int", 8_000_000,
      "rows for the binned-store stacked-vs-binned leg")
_knob("H2O_TPU_BENCH_WORKLOADS", "str",
      "gbm,glm,cod,gam,rulefit,sort,merge,binned,serving,serving_wire,"
      "recovery,cold_start,sharded,airlines,workload",
      "comma list of bench workloads to run")
_knob("H2O_TPU_BENCH_WORKLOAD_TENANTS", "int", 3,
      "tenants for the multi-tenant workload bench leg (each runs "
      "ingest + train + score under the managed scheduler)")
_knob("H2O_TPU_BENCH_WORKLOAD_ROWS", "int", 40_000,
      "rows per tenant frame in the workload bench leg")
_knob("H2O_TPU_BENCH_SHARDED_ROWS", "int", 400_000,
      "rows for the sharded leg (same GBM at 1 vs N row shards, each in "
      "its own subprocess; per-shard peak matrix bytes + psum payload + "
      "wall land in the sidecar)")
_knob("H2O_TPU_BENCH_RECOVERY_ROWS", "int", 500_000,
      "rows for the recovery leg (checkpoint overhead + resume-to-parity)")
_knob("H2O_TPU_BENCH_COLDSTART_ROWS", "int", 60_000,
      "rows for the cold_start leg's subprocess GBM train+score (first "
      "process cold vs second process on a warmed persistent compile "
      "cache)")
_knob("H2O_TPU_BENCH_SERVING_REQS", "int", 4000,
      "single-row requests issued by the concurrent serving bench leg")
_knob("H2O_TPU_BENCH_SERVING_THREADS", "int", 16,
      "concurrent client threads for the serving bench leg")
_knob("H2O_TPU_BENCH_WIRE_REQS", "int", 600,
      "sequential single-row HTTP requests per wire mode (pooled / "
      "per-request) in the serving_wire bench leg")
_knob("H2O_TPU_BENCH_SKIP_CADENCE", "bool", False,
      "skip the score_tree_interval=10 GBM cadence leg")
_knob("H2O_TPU_BENCH_SIDECAR", "str", "",
      "path of the crash-proof per-workload JSONL sidecar "
      "(default: BENCH_partial.jsonl next to bench.py)")
_knob("H2O_TPU_BENCH_GATE_BANDS", "str", "",
      "tolerance-band overrides for tools/bench_gate.py as "
      "'metric=frac' pairs, comma-separated, optionally leg-scoped "
      "('wall=0.4,peak=0.5,gbm.wall=0.6'); empty = the gate's documented "
      "defaults (wall +25%, peak bytes +25%, AUC drop 0.02)")

# -- test harness -----------------------------------------------------------
_knob("H2O_TPU_TEST_CACHE", "str", "",
      "opt-in persistent XLA compile cache dir for the test suite")
_knob("H2O_TPU_KEY_STRICT", "bool", False,
      "fail tests on leaked KVStore keys instead of reaping them")


def _lookup(name: str) -> Knob:
    try:
        return KNOBS[name]
    except KeyError:
        raise KeyError(
            f"unregistered knob {name!r} — declare it in "
            f"h2o_tpu/utils/knobs.py (graftlint rule unregistered-knob "
            f"enforces the same statically)") from None


def raw(name: str, default=None):
    """``os.environ.get`` semantics with the registration check: the raw
    string when set, else ``default`` untouched (NOT the registered
    default — this is the drop-in target for graftlint --fix rewrites)."""
    _lookup(name)
    return os.environ.get(name, default)


def get_str(name: str, default=_MISSING) -> str:
    """SET wins, even when set to the empty string — an exported-but-empty
    string knob means "nothing" (e.g. H2O_TPU_BENCH_WORKLOADS= runs no
    legs), not "give me the default"."""
    k = _lookup(name)
    v = os.environ.get(name)
    if v is not None:
        return v
    return k.default if default is _MISSING else default


def get_int(name: str, default=_MISSING) -> int:
    """Unset OR empty falls back to the default (there is no useful int
    reading of ""); sites that need set-but-empty to mean 0/disabled read
    through ``raw`` and keep their own coercion."""
    k = _lookup(name)
    v = os.environ.get(name)
    if v not in (None, ""):
        return int(v)
    d = k.default if default is _MISSING else default
    return d if d is None else int(d)


def get_bool(name: str, default=_MISSING) -> bool:
    k = _lookup(name)
    v = os.environ.get(name)
    if v is None or v.strip() == "":
        d = k.default if default is _MISSING else default
        return bool(d)
    return v.strip().lower() not in _FALSY


def describe() -> str:
    """Human-readable registry dump (the `printHelp` analog for env knobs)."""
    lines = []
    for k in sorted(KNOBS.values(), key=lambda k: k.name):
        lines.append(f"{k.name}  [{k.kind}, default {k.default!r}]")
        lines.append(f"    {k.doc}")
    return "\n".join(lines)
