"""TwoDimTable — the tabular-results container every H2O surface renders.

Analog of `water/util/TwoDimTable.java` (part of the 15,873-LoC util layer):
a named table with typed columns, row headers, pretty console rendering, and
pandas conversion. Model summaries, variable importances, scoring history and
grid summaries are all published in this shape in the reference; ours mirrors
the API (`table_header`, `col_header`, `cell_values`, `as_data_frame`)."""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np


class TwoDimTable:
    def __init__(self, table_header: str = "", description: str = "",
                 col_header: Sequence[str] = (),
                 col_types: Sequence[str] | None = None,
                 row_headers: Sequence[str] | None = None,
                 cell_values: Sequence[Sequence[Any]] | None = None):
        self.table_header = table_header
        self.description = description
        self.col_header = list(col_header)
        self.col_types = list(col_types) if col_types else \
            ["string"] * len(self.col_header)
        self.row_headers = list(row_headers) if row_headers else None
        self.cell_values = [list(r) for r in (cell_values or [])]

    # -- construction helpers -------------------------------------------------
    @staticmethod
    def from_dict(name: str, cols: dict) -> "TwoDimTable":
        """Column dict -> table (columns in insertion order)."""
        headers = list(cols)
        n = len(next(iter(cols.values()))) if cols else 0
        rows = [[cols[h][i] for h in headers] for i in range(n)]
        types = ["double" if isinstance(cols[h][0], (int, float, np.floating))
                 else "string" for h in headers] if n else None
        return TwoDimTable(table_header=name, col_header=headers,
                           col_types=types, cell_values=rows)

    @property
    def nrow(self) -> int:
        return len(self.cell_values)

    @property
    def ncol(self) -> int:
        return len(self.col_header)

    def __getitem__(self, rc):
        r, c = rc
        if isinstance(c, str):
            c = self.col_header.index(c)
        return self.cell_values[r][c]

    # -- rendering ------------------------------------------------------------
    def _fmt(self, v) -> str:
        if v is None:
            return ""
        if isinstance(v, (float, np.floating)):
            return f"{v:.5f}" if abs(v) < 1e6 else f"{v:.3e}"
        return str(v)

    def __repr__(self) -> str:
        headers = ([""] if self.row_headers else []) + self.col_header
        rows = []
        for i, r in enumerate(self.cell_values):
            lead = [self.row_headers[i]] if self.row_headers else []
            rows.append(lead + [self._fmt(v) for v in r])
        widths = [max(len(h), *(len(row[j]) for row in rows)) if rows else len(h)
                  for j, h in enumerate(headers)]
        out = [self.table_header] if self.table_header else []
        if self.description:
            out.append(self.description)
        out.append("  ".join(h.ljust(w) for h, w in zip(headers, widths)))
        out.append("  ".join("-" * w for w in widths))
        for row in rows:
            out.append("  ".join(v.ljust(w) for v, w in zip(row, widths)))
        return "\n".join(out)

    def as_data_frame(self):
        import pandas as pd

        df = pd.DataFrame(self.cell_values, columns=self.col_header)
        if self.row_headers:
            df.insert(0, "", self.row_headers)
        return df

    def to_json(self) -> dict:
        return {"name": self.table_header, "description": self.description,
                "columns": self.col_header, "data": self.cell_values}
