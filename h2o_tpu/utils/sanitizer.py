"""Runtime lock sanitizer — the dynamic twin of graftlint's interprocedural
concurrency rules (tools/graftlint/concurrency.py).

The static ``lock-order-cycle`` rule flags *possible* inversions; this
module catches *observed* ones the moment they happen, on the real
serving/training/Cleaner workload, behind one knob::

    H2O_TPU_SANITIZE=locks            # instrumented locks + order checking
    H2O_TPU_SANITIZE=locks,guards     # ... plus @guarded_by assertions

Every lock the concurrency-audited modules create goes through
:func:`make_lock`. With sanitizing OFF (the default) it returns a plain
``threading.Lock``/``RLock`` — zero wrapper, zero overhead (the <2%
disabled-cost contract is structural, not measured-and-hoped). With
``locks`` on it returns a :class:`SanitizedLock` that

- records the per-thread acquisition stack,
- maintains the process-global lock-order graph (edge A→B when B is
  acquired while A is held),
- and raises the typed :class:`LockOrderViolation` the moment an
  acquisition would invert an already-observed order — at the acquiring
  call site, with both orders and where the first was established, BEFORE
  the process can deadlock.

Observations are keyed by lock *name* (the ``make_lock`` argument, e.g.
``"Cleaner._lock"``), so all instances of a class's lock share one node —
exactly the static rule's granularity. Re-acquiring the same named lock
(RLock re-entry, or two instances of the same class) never reports.

``@guarded_by("_lock")`` is the assertion decorator the fixed call sites
adopt: with ``guards`` on, entering the method without holding
``self._lock`` raises the typed :class:`GuardViolation`; off, the
decorator is a pass-through wrapper whose only cost is one cached env
check. It asserts only when the attribute actually is a SanitizedLock
(plain locks cannot report their holder).

Wired through the standard drill surfaces: the ``sanitizer.trip``
failpoint fires inside the order check (arm ``raise`` to drill the
violation-handling path deterministically in CI), every real violation
bumps the ``sanitizer.violation.count`` metric and lands a typed
``sanitizer`` timeline event before raising.

The mode is read from the env on every :func:`enabled` call but cached on
the raw string (the knobs/failpoints dynamic-read contract: monkeypatching
tests work, steady state costs one ``os.environ.get``). NOTE: the mode is
consulted at lock *construction* — a module singleton built before the
env flips keeps its plain lock; tests build fresh objects (or swap
``obj._lock``) after setting the knob.
"""

from __future__ import annotations

import threading

from . import knobs


class LockOrderViolation(RuntimeError):
    """An OBSERVED lock-order inversion: this thread holds ``holding`` and
    tried to acquire ``acquiring``, but the opposite order was already
    observed (``established`` names the thread/site that recorded it).
    Raised before blocking — the deadlock candidate is reported, not
    entered."""

    def __init__(self, acquiring: str, holding: str, established: str):
        self.acquiring = acquiring
        self.holding = holding
        self.established = established
        super().__init__(
            f"lock-order inversion: acquiring '{acquiring}' while holding "
            f"'{holding}', but the order {acquiring} -> {holding} was "
            f"already observed ({established}); pick one global order "
            f"(graftlint rule lock-order-cycle is the static twin)")


class GuardViolation(AssertionError):
    """A ``@guarded_by`` method entered without its lock held."""

    def __init__(self, what: str, lock_attr: str):
        super().__init__(
            f"{what} requires {lock_attr} held by the calling thread "
            f"(@guarded_by contract)")


# ---------------------------------------------------------------------------
# mode (dynamic read, cached on the raw knob string)
# ---------------------------------------------------------------------------
_mode_cache: tuple[str | None, frozenset] = (None, frozenset())


def _modes() -> frozenset:
    global _mode_cache
    raw = knobs.raw("H2O_TPU_SANITIZE")  # registration check + env read
    if raw == _mode_cache[0]:
        return _mode_cache[1]
    modes = frozenset(m.strip() for m in (raw or "").split(",") if m.strip())
    unknown = modes - {"locks", "guards"}
    if unknown:
        raise ValueError(
            f"unknown H2O_TPU_SANITIZE mode(s) {sorted(unknown)} — "
            f"'locks' and/or 'guards'")
    _mode_cache = (raw, modes)
    return modes


def enabled(mode: str) -> bool:
    """Is a sanitize mode ('locks' / 'guards') currently on?"""
    return mode in _modes()


# ---------------------------------------------------------------------------
# the order graph (process-global, name-keyed)
# ---------------------------------------------------------------------------
_GRAPH_LOCK = threading.Lock()
#: name -> {successor name -> "thread/site that established the edge"}
_EDGES: dict[str, dict[str, str]] = {}
_TLS = threading.local()  # .held: list[str] of lock names, outermost first


def _held() -> list:
    held = getattr(_TLS, "held", None)
    if held is None:
        held = _TLS.held = []
    return held


def reset_order_graph() -> None:
    """Drop every observed edge (test isolation)."""
    with _GRAPH_LOCK:
        _EDGES.clear()


def order_graph() -> dict:
    """Copy of the observed order graph ({name: [successors]}) —
    observability / test pins."""
    with _GRAPH_LOCK:
        return {a: sorted(bs) for a, bs in _EDGES.items()}


def _reachable(frm: str, to: str) -> bool:
    """Path frm -> ... -> to in the observed graph (caller holds
    _GRAPH_LOCK)."""
    seen = {frm}
    stack = [frm]
    while stack:
        cur = stack.pop()
        if cur == to:
            return True
        for nxt in _EDGES.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


class SanitizedLock:
    """Lock/RLock wrapper recording per-thread acquisition stacks and the
    global order graph; raises :class:`LockOrderViolation` on an observed
    inversion BEFORE blocking on the inner lock."""

    __slots__ = ("name", "_inner", "_rlock", "_owner", "_count")

    def __init__(self, name: str, *, rlock: bool = False):
        self.name = name
        self._rlock = rlock
        self._inner = threading.RLock() if rlock else threading.Lock()
        self._owner: int | None = None
        self._count = 0

    # -- order bookkeeping ----------------------------------------------------
    def _pre_acquire(self) -> None:
        from . import failpoints

        failpoints.hit("sanitizer.trip")
        held = _held()
        if not held or self.name in held:
            return  # re-entry of the same named node never re-orders
        prev = held[-1]
        me = f"thread '{threading.current_thread().name}'"
        with _GRAPH_LOCK:
            if self.name in _EDGES and _reachable(self.name, prev):
                est = _EDGES.get(self.name, {}).get(
                    prev, "a transitive chain of observed acquisitions")
                violation = LockOrderViolation(self.name, prev, est)
            else:
                _EDGES.setdefault(prev, {}).setdefault(
                    self.name, f"{me} holding '{prev}'")
                return
        # emit outside the graph lock, then raise the typed error
        from . import telemetry, timeline

        telemetry.inc("sanitizer.violation.count")
        timeline.record("sanitizer", "lock_order",
                        acquiring=self.name, holding=prev)
        # the inversion IS the diagnosis — bundle the thread dump + order
        # graph while the offending threads are still in flight (no-op
        # unless H2O_TPU_FLIGHT_DIR is set). The dump runs on a DETACHED
        # thread: this thread still HOLDS the application locks of the
        # inversion, and bundling acquires foreign locks (Cleaner ledger,
        # telemetry) + fsyncs — doing that inline could deadlock the very
        # tool built to catch deadlocks, and would record synthetic
        # crash-path-only edges into the order graph
        from . import flightrec

        flightrec.dump_async("lock-order-violation", violation)
        raise violation

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking and not self._rlock \
                and self._owner == threading.get_ident():
            # a plain Lock re-acquired by its holder never returns — the
            # one deadlock that needs no second thread
            raise LockOrderViolation(
                self.name, self.name,
                "self-deadlock: non-reentrant lock re-acquired by its "
                "own holder")
        if blocking:
            self._pre_acquire()
        got = self._inner.acquire(blocking, timeout)
        if got:
            tid = threading.get_ident()
            if self._owner == tid and self._rlock:
                self._count += 1
            else:
                self._owner = tid
                self._count = 1
                _held().append(self.name)
        return got

    def release(self) -> None:
        tid = threading.get_ident()
        if self._owner is not None and self._owner != tid:
            # threading.Lock legally allows acquire-in-T1/release-in-T2
            # handoffs, but the sanitizer's per-thread stacks cannot model
            # them (the name would rot on T1's held stack and fabricate
            # violations later). Refuse LOUDLY instead of corrupting the
            # very diagnostics this mode exists for — the inner lock is
            # released first so the refusal never deadlocks the program.
            self._inner.release()
            raise RuntimeError(
                f"SanitizedLock '{self.name}' released by thread "
                f"'{threading.current_thread().name}' but acquired by "
                f"another thread — cross-thread lock handoff is not "
                f"supported under H2O_TPU_SANITIZE=locks (use an Event/"
                f"Condition for handoffs, or leave this lock plain)")
        if self._owner == tid:
            self._count -= 1
            if self._count == 0:
                self._owner = None
                held = _held()
                if held and held[-1] == self.name:
                    held.pop()
                elif self.name in held:  # out-of-order release
                    held.remove(self.name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._owner is not None

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()


def make_lock(name: str, *, rlock: bool = False):
    """The one lock factory the concurrency-audited modules use: a plain
    ``threading.Lock``/``RLock`` when sanitizing is off (zero overhead),
    a :class:`SanitizedLock` under ``H2O_TPU_SANITIZE=locks``."""
    if enabled("locks"):
        return SanitizedLock(name, rlock=rlock)
    return threading.RLock() if rlock else threading.Lock()


# ---------------------------------------------------------------------------
# @guarded_by — the assertion the fixed call sites adopt
# ---------------------------------------------------------------------------
def guarded_by(lock_attr: str = "_lock"):
    """Assert (under ``H2O_TPU_SANITIZE=guards``) that ``self.<lock_attr>``
    is held by the calling thread. Asserts only when the attribute is a
    SanitizedLock — a plain lock cannot report its holder, so with
    sanitizing off this is a pass-through whose cost is one cached env
    read."""
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            if "guards" in _modes():
                lock = getattr(self, lock_attr, None)
                if isinstance(lock, SanitizedLock) \
                        and not lock.held_by_me():
                    from . import telemetry, timeline

                    telemetry.inc("sanitizer.violation.count")
                    timeline.record("sanitizer", "guard",
                                    method=fn.__qualname__,
                                    lock=lock_attr)
                    raise GuardViolation(fn.__qualname__, lock_attr)
            return fn(self, *args, **kwargs)
        return wrapper
    return deco
