"""Runtime sanitizers — the dynamic twins of graftlint's interprocedural
rules (tools/graftlint/concurrency.py + tools/graftlint/dataflow.py).

The static rules flag *possible* hazards; this module catches *observed*
ones the moment they happen, on the real serving/training/Cleaner
workload, behind one knob::

    H2O_TPU_SANITIZE=locks            # instrumented locks + order checking
    H2O_TPU_SANITIZE=locks,guards     # ... plus @guarded_by assertions
    H2O_TPU_SANITIZE=transfers        # jax transfer guards over hot sections
    H2O_TPU_SANITIZE=recompiles       # steady-state compiles raise typed

``transfers`` is rule ``host-transfer-in-hot-path``'s twin:
:func:`transfer_scope` wraps the hot sections that rule names (train
chunk dispatch, MRTask dispatch, serving score path, Cleaner sweep) in a
``jax.transfer_guard_device_to_host("disallow")`` — an implicit
device→host conversion inside one raises the typed
:class:`TransferGuardViolation` naming the section (explicit
``jax.device_get`` stays allowed: it is the sanctioned spelling the
static rule pushes toward). The steady-state serving score path adds the
host→device guard too (``host_to_device=True``): after warmup every
staging transfer there is explicit by construction. NOTE the CPU backend
exposes device buffers as host memory, so device→host never trips there
— the live CPU drill goes through the host→device direction, and the
``sanitizer.transfer`` failpoint drills the violation path (typed error +
flight bundle) on any backend.

``recompiles`` is rule ``recompile-hazard``'s twin: the compilemeter's
:func:`~h2o_tpu.utils.compilemeter.no_compile_scope` (armed only under
this mode) raises the typed :class:`SteadyStateCompileError` on any
UNCACHED compile inside a declared-steady section — the GBM chunk loop
after its first boundary (model_base post-setup) and the serving score
path after registration warmup. Persistent-cache replays do not count
(they cost no XLA wall); the bucket-miss fallback the serving stats
gauge tracks is exactly what raises here.

Both violations feed the PR 13 flight recorder (one diagnostics bundle
per violation when ``H2O_TPU_FLIGHT_DIR`` is set) next to the metric and
timeline breadcrumbs the lock sanitizer already leaves.

Every lock the concurrency-audited modules create goes through
:func:`make_lock`. With sanitizing OFF (the default) it returns a plain
``threading.Lock``/``RLock`` — zero wrapper, zero overhead (the <2%
disabled-cost contract is structural, not measured-and-hoped). With
``locks`` on it returns a :class:`SanitizedLock` that

- records the per-thread acquisition stack,
- maintains the process-global lock-order graph (edge A→B when B is
  acquired while A is held),
- and raises the typed :class:`LockOrderViolation` the moment an
  acquisition would invert an already-observed order — at the acquiring
  call site, with both orders and where the first was established, BEFORE
  the process can deadlock.

Observations are keyed by lock *name* (the ``make_lock`` argument, e.g.
``"Cleaner._lock"``), so all instances of a class's lock share one node —
exactly the static rule's granularity. Re-acquiring the same named lock
(RLock re-entry, or two instances of the same class) never reports.

``@guarded_by("_lock")`` is the assertion decorator the fixed call sites
adopt: with ``guards`` on, entering the method without holding
``self._lock`` raises the typed :class:`GuardViolation`; off, the
decorator is a pass-through wrapper whose only cost is one cached env
check. It asserts only when the attribute actually is a SanitizedLock
(plain locks cannot report their holder).

Wired through the standard drill surfaces: the ``sanitizer.trip``
failpoint fires inside the order check (arm ``raise`` to drill the
violation-handling path deterministically in CI), every real violation
bumps the ``sanitizer.violation.count`` metric and lands a typed
``sanitizer`` timeline event before raising.

The mode is read from the env on every :func:`enabled` call but cached on
the raw string (the knobs/failpoints dynamic-read contract: monkeypatching
tests work, steady state costs one ``os.environ.get``). NOTE: the mode is
consulted at lock *construction* — a module singleton built before the
env flips keeps its plain lock; tests build fresh objects (or swap
``obj._lock``) after setting the knob.
"""

from __future__ import annotations

import contextlib
import threading

from . import knobs


class LockOrderViolation(RuntimeError):
    """An OBSERVED lock-order inversion: this thread holds ``holding`` and
    tried to acquire ``acquiring``, but the opposite order was already
    observed (``established`` names the thread/site that recorded it).
    Raised before blocking — the deadlock candidate is reported, not
    entered."""

    def __init__(self, acquiring: str, holding: str, established: str):
        self.acquiring = acquiring
        self.holding = holding
        self.established = established
        super().__init__(
            f"lock-order inversion: acquiring '{acquiring}' while holding "
            f"'{holding}', but the order {acquiring} -> {holding} was "
            f"already observed ({established}); pick one global order "
            f"(graftlint rule lock-order-cycle is the static twin)")


class GuardViolation(AssertionError):
    """A ``@guarded_by`` method entered without its lock held."""

    def __init__(self, what: str, lock_attr: str):
        super().__init__(
            f"{what} requires {lock_attr} held by the calling thread "
            f"(@guarded_by contract)")


class TransferGuardViolation(RuntimeError):
    """An implicit transfer OBSERVED inside a guarded hot section under
    ``H2O_TPU_SANITIZE=transfers`` — the runtime twin of graftlint's
    ``host-transfer-in-hot-path``. Carries the section so a flight bundle
    / log line names the hot path, not just the jax frame."""

    def __init__(self, section: str, detail: str = ""):
        self.section = section
        super().__init__(
            f"implicit transfer inside hot section '{section}' "
            f"(H2O_TPU_SANITIZE=transfers)"
            + (f": {detail}" if detail else "")
            + " — hot paths stage data with explicit jax.device_put and "
              "read results with explicit jax.device_get (graftlint rule "
              "host-transfer-in-hot-path is the static twin)")


class SteadyStateCompileError(RuntimeError):
    """An UNCACHED XLA compile observed inside a declared-steady section
    under ``H2O_TPU_SANITIZE=recompiles`` — the runtime twin of graftlint's
    ``recompile-hazard``. Raised at the dispatching call site (the compile
    completed; jax state stays healthy — the error names the cache-key
    churn, it does not corrupt the backend)."""

    def __init__(self, section: str):
        self.section = section
        super().__init__(
            f"uncached XLA compile inside steady-state section "
            f"'{section}' (H2O_TPU_SANITIZE=recompiles) — every steady-"
            f"state cache key was declared stable at the warmup boundary "
            f"(model_base post-setup / serving post-registration); a "
            f"compile here is a shape/static-arg that escaped warmup "
            f"(graftlint rule recompile-hazard is the static twin)")


# ---------------------------------------------------------------------------
# mode (dynamic read, cached on the raw knob string)
# ---------------------------------------------------------------------------
_mode_cache: tuple[str | None, frozenset] = (None, frozenset())


def _modes() -> frozenset:
    global _mode_cache
    raw = knobs.raw("H2O_TPU_SANITIZE")  # registration check + env read
    if raw == _mode_cache[0]:
        return _mode_cache[1]
    modes = frozenset(m.strip() for m in (raw or "").split(",") if m.strip())
    unknown = modes - {"locks", "guards", "transfers", "recompiles"}
    if unknown:
        raise ValueError(
            f"unknown H2O_TPU_SANITIZE mode(s) {sorted(unknown)} — "
            f"any of 'locks', 'guards', 'transfers', 'recompiles'")
    _mode_cache = (raw, modes)
    return modes


def enabled(mode: str) -> bool:
    """Is a sanitize mode ('locks' / 'guards') currently on?"""
    return mode in _modes()


# ---------------------------------------------------------------------------
# the order graph (process-global, name-keyed)
# ---------------------------------------------------------------------------
_GRAPH_LOCK = threading.Lock()
#: name -> {successor name -> "thread/site that established the edge"}
_EDGES: dict[str, dict[str, str]] = {}
_TLS = threading.local()  # .held: list[str] of lock names, outermost first


def _held() -> list:
    held = getattr(_TLS, "held", None)
    if held is None:
        held = _TLS.held = []
    return held


def reset_order_graph() -> None:
    """Drop every observed edge (test isolation)."""
    with _GRAPH_LOCK:
        _EDGES.clear()


def order_graph() -> dict:
    """Copy of the observed order graph ({name: [successors]}) —
    observability / test pins."""
    with _GRAPH_LOCK:
        return {a: sorted(bs) for a, bs in _EDGES.items()}


def _reachable(frm: str, to: str) -> bool:
    """Path frm -> ... -> to in the observed graph (caller holds
    _GRAPH_LOCK)."""
    seen = {frm}
    stack = [frm]
    while stack:
        cur = stack.pop()
        if cur == to:
            return True
        for nxt in _EDGES.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


class SanitizedLock:
    """Lock/RLock wrapper recording per-thread acquisition stacks and the
    global order graph; raises :class:`LockOrderViolation` on an observed
    inversion BEFORE blocking on the inner lock."""

    __slots__ = ("name", "_inner", "_rlock", "_owner", "_count")

    def __init__(self, name: str, *, rlock: bool = False):
        self.name = name
        self._rlock = rlock
        self._inner = threading.RLock() if rlock else threading.Lock()
        self._owner: int | None = None
        self._count = 0

    # -- order bookkeeping ----------------------------------------------------
    def _pre_acquire(self) -> None:
        from . import failpoints

        failpoints.hit("sanitizer.trip")
        held = _held()
        if not held or self.name in held:
            return  # re-entry of the same named node never re-orders
        prev = held[-1]
        me = f"thread '{threading.current_thread().name}'"
        with _GRAPH_LOCK:
            if self.name in _EDGES and _reachable(self.name, prev):
                est = _EDGES.get(self.name, {}).get(
                    prev, "a transitive chain of observed acquisitions")
                violation = LockOrderViolation(self.name, prev, est)
            else:
                _EDGES.setdefault(prev, {}).setdefault(
                    self.name, f"{me} holding '{prev}'")
                return
        # emit outside the graph lock, then raise the typed error
        from . import telemetry, timeline

        telemetry.inc("sanitizer.violation.count")
        timeline.record("sanitizer", "lock_order",
                        acquiring=self.name, holding=prev)
        # the inversion IS the diagnosis — bundle the thread dump + order
        # graph while the offending threads are still in flight (no-op
        # unless H2O_TPU_FLIGHT_DIR is set). The dump runs on a DETACHED
        # thread: this thread still HOLDS the application locks of the
        # inversion, and bundling acquires foreign locks (Cleaner ledger,
        # telemetry) + fsyncs — doing that inline could deadlock the very
        # tool built to catch deadlocks, and would record synthetic
        # crash-path-only edges into the order graph
        from . import flightrec

        flightrec.dump_async("lock-order-violation", violation)
        raise violation

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking and not self._rlock \
                and self._owner == threading.get_ident():
            # a plain Lock re-acquired by its holder never returns — the
            # one deadlock that needs no second thread
            raise LockOrderViolation(
                self.name, self.name,
                "self-deadlock: non-reentrant lock re-acquired by its "
                "own holder")
        if blocking:
            self._pre_acquire()
        got = self._inner.acquire(blocking, timeout)
        if got:
            tid = threading.get_ident()
            if self._owner == tid and self._rlock:
                self._count += 1
            else:
                self._owner = tid
                self._count = 1
                _held().append(self.name)
        return got

    def release(self) -> None:
        tid = threading.get_ident()
        if self._owner is not None and self._owner != tid:
            # threading.Lock legally allows acquire-in-T1/release-in-T2
            # handoffs, but the sanitizer's per-thread stacks cannot model
            # them (the name would rot on T1's held stack and fabricate
            # violations later). Refuse LOUDLY instead of corrupting the
            # very diagnostics this mode exists for — the inner lock is
            # released first so the refusal never deadlocks the program.
            self._inner.release()
            raise RuntimeError(
                f"SanitizedLock '{self.name}' released by thread "
                f"'{threading.current_thread().name}' but acquired by "
                f"another thread — cross-thread lock handoff is not "
                f"supported under H2O_TPU_SANITIZE=locks (use an Event/"
                f"Condition for handoffs, or leave this lock plain)")
        if self._owner == tid:
            self._count -= 1
            if self._count == 0:
                self._owner = None
                held = _held()
                if held and held[-1] == self.name:
                    held.pop()
                elif self.name in held:  # out-of-order release
                    held.remove(self.name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._owner is not None

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()


def make_lock(name: str, *, rlock: bool = False):
    """The one lock factory the concurrency-audited modules use: a plain
    ``threading.Lock``/``RLock`` when sanitizing is off (zero overhead),
    a :class:`SanitizedLock` under ``H2O_TPU_SANITIZE=locks``."""
    if enabled("locks"):
        return SanitizedLock(name, rlock=rlock)
    return threading.RLock() if rlock else threading.Lock()


# ---------------------------------------------------------------------------
# transfer guard — H2O_TPU_SANITIZE=transfers (rule 20's runtime twin)
# ---------------------------------------------------------------------------
def _emit_violation(what: str, violation, **detail) -> None:
    """Shared breadcrumb trail for the typed sanitizer violations: metric,
    timeline event, and an async flight bundle (async because the caller
    may hold application locks — the lock-order path's rationale)."""
    from . import flightrec, telemetry, timeline

    telemetry.inc("sanitizer.violation.count")
    timeline.record("sanitizer", what, **detail)
    flightrec.dump_async(f"{what}-violation", violation)


@contextlib.contextmanager
def transfer_scope(section: str, *, host_to_device: bool = False):
    """Scope a jax transfer guard over one hot section (no-op unless
    ``H2O_TPU_SANITIZE=transfers``): implicit device→host conversions
    inside raise the typed :class:`TransferGuardViolation` naming the
    section. ``host_to_device=True`` additionally disallows implicit
    host→device staging — ONLY for sections whose staging is explicit by
    construction and which never trace (tracing materializes constants
    host→device; the steady-state serving score path qualifies, the
    chunk-0 train dispatch does not).

    The ``sanitizer.transfer`` failpoint fires on entry so CI can drill
    the violation path (typed error + flight bundle) deterministically on
    backends where the guard itself cannot trip (CPU arrays are host
    memory — device→host is free there, and real TPU hardware is where
    the d2h guard earns its keep)."""
    if not enabled("transfers"):
        yield
        return
    from . import failpoints

    try:
        failpoints.hit("sanitizer.transfer")
    except failpoints.InjectedFault as e:
        v = TransferGuardViolation(section, f"drill: {e}")
        _emit_violation("transfer", v, section=section, drill=True)
        raise v from e
    import jax

    guards = [jax.transfer_guard_device_to_host("disallow")]
    if host_to_device:
        guards.append(jax.transfer_guard_host_to_device("disallow"))
    try:
        with contextlib.ExitStack() as stack:
            for g in guards:
                stack.enter_context(g)
            yield
    except TransferGuardViolation:
        raise  # an inner (nested) scope already typed + bundled it
    except Exception as e:
        msg = str(e)
        if "transfer" in msg.lower() and "isallow" in msg:
            v = TransferGuardViolation(section, msg.splitlines()[0])
            _emit_violation("transfer", v, section=section)
            raise v from e
        raise


# ---------------------------------------------------------------------------
# @guarded_by — the assertion the fixed call sites adopt
# ---------------------------------------------------------------------------
def guarded_by(lock_attr: str = "_lock"):
    """Assert (under ``H2O_TPU_SANITIZE=guards``) that ``self.<lock_attr>``
    is held by the calling thread. Asserts only when the attribute is a
    SanitizedLock — a plain lock cannot report its holder, so with
    sanitizing off this is a pass-through whose cost is one cached env
    read."""
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            if "guards" in _modes():
                lock = getattr(self, lock_attr, None)
                if isinstance(lock, SanitizedLock) \
                        and not lock.held_by_me():
                    from . import telemetry, timeline

                    telemetry.inc("sanitizer.violation.count")
                    timeline.record("sanitizer", "guard",
                                    method=fn.__qualname__,
                                    lock=lock_attr)
                    raise GuardViolation(fn.__qualname__, lock_attr)
            return fn(self, *args, **kwargs)
        return wrapper
    return deco
