"""Explicit device-sync points for wall-clock measurement.

jax dispatch is asynchronous: `model = GBM(p).train_model()` returns as soon
as the programs are ENQUEUED, so `time.time() - t0` around it measures
dispatch, not compute — the exact hazard graftlint's `timing-without-sync`
rule pins. The honest sync is to block on the arrays the work actually
produced; `device_arrays` collects every `jax.Array` reachable from an
object (dicts/lists/tuples and h2o_tpu-owned instances, bounded depth, cycle
safe) so timed legs can write

    jax.block_until_ready(device_arrays(model))

before reading the clock. Collection reads ``__dict__`` directly — it never
calls properties, so it cannot trigger a Cleaner rehydrate of a spilled Vec
(a spilled column is host-side by definition: nothing to wait on).
"""

from __future__ import annotations


def device_arrays(obj, max_depth: int = 5) -> list:
    """Every jax.Array reachable from ``obj`` through containers and
    h2o_tpu-owned instances (depth-bounded, cycle-safe)."""
    import jax

    out: list = []
    seen: set[int] = set()

    def walk(o, depth: int) -> None:
        if depth < 0 or id(o) in seen:
            return
        seen.add(id(o))
        if isinstance(o, jax.Array):
            out.append(o)
        elif isinstance(o, dict):
            for v in o.values():
                walk(v, depth - 1)
        elif isinstance(o, (list, tuple)):
            for v in o:
                walk(v, depth - 1)
        elif (getattr(type(o), "__module__", "").startswith("h2o_tpu")
              and hasattr(o, "__dict__")):
            for v in vars(o).values():
                walk(v, depth - 1)

    walk(obj, max_depth)
    return out
