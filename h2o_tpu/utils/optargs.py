"""OptArgs — the unified flag system (`water/H2O.OptArgs` analog).

The reference parses one flat class of CLI flags reflectively
(`water/H2O.java:343-474,581-588`) and prints them with `printHelp`
(`water/H2O.java:69`). This is the same design over a dataclass: every
launcher/runtime knob lives HERE with its type, default, env-var spelling
and help line; `parse()` resolves CLI > environment > default and then
EXPORTS the resolved values back into the process environment — the ~20
existing `os.environ.get("H2O_TPU_*")` consumers scattered through the
runtime keep working unchanged, with this class as the single documented
surface over them (the `H2O.ARGS` global role).

Per-model hyperparameters are NOT flags — they are Parameters dataclasses,
schema-exposed (same rule as the reference).

Usage:
    python -m h2o_tpu.deploy_entry --help
    python -m h2o_tpu.deploy_entry --port 54321 --name my_cloud
    ARGS = optargs.parse(sys.argv[1:])
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Optional


def _flag(default, env: str | None, help_: str):
    return field(default=default,
                 metadata={"env": env, "help": help_})


@dataclass
class OptArgs:
    """Every launcher/runtime flag. CLI spelling is ``--<field-name>``
    (underscores or dashes both accepted, like the reference's `-name`)."""

    # -- identity / networking (`OptArgs.name/port/ip/flatfile`) -----------
    name: str = _flag("h2o_tpu", None,
                      "cloud name reported by /3/Cloud")
    port: int = _flag(54321, "H2O_TPU_REST_PORT",
                      "REST API port")
    ip: str = _flag("0.0.0.0", None,
                    "bind address for the REST server")
    baseport: int = _flag(0, None,
                          "first port to probe when `port` is taken "
                          "(0 = fail instead of scanning)")
    flatfile: str = _flag("", None,
                          "path to a flatfile of cluster nodes "
                          "(multi-host boot; one IPv4/IPv6[:port] per line)")
    driver: str = _flag("", "H2O_TPU_DRIVER",
                        "python module to run as the multi-host driver "
                        "instead of serving REST")
    assisted_clustering: bool = _flag(False, "H2O_TPU_ASSISTED_CLUSTERING",
                                      "start the clustering sidecar API and "
                                      "wait for a flatfile POST before "
                                      "touching any JAX backend")
    assisted_clustering_api_port: int = _flag(
        8080, "H2O_TPU_ASSISTED_CLUSTERING_API_PORT",
        "port for the assisted-clustering sidecar API")

    # -- storage / memory (`OptArgs.ice_root`, Cleaner knobs) --------------
    ice_root: str = _flag("", "H2O_TPU_ICE_DIR",
                          "spill directory for the HBM Cleaner "
                          "(default: a temp dir)")
    hbm_limit_bytes: int = _flag(0, "H2O_TPU_HBM_LIMIT_BYTES",
                                 "soft HBM budget before the Cleaner spills "
                                 "LRU vecs (0 = backend default)")
    max_frame_bytes: int = _flag(0, "H2O_TPU_MAX_FRAME_BYTES",
                                 "refuse parses whose frame would exceed "
                                 "this (FrameSizeMonitor; 0 = no cap)")
    nps_dir: str = _flag("", "H2O_TPU_NPS_DIR",
                         "NodePersistentStorage root directory")

    # -- security ----------------------------------------------------------
    hash_login: str = _flag("", None,
                            "realm file of user:sha256 lines for REST "
                            "basic auth")
    ldap_login: str = _flag("", None,
                            "LDAP URL for REST basic auth (ldap://host:389/"
                            "dn-pattern)")
    kerberos_login: bool = _flag(False, None,
                                 "accept SPNEGO/Kerberos on the REST plane")
    pam_login: bool = _flag(False, None,
                            "authenticate REST basic auth against PAM")
    ssl_certfile: str = _flag("", None, "TLS certificate for the REST port")
    ssl_keyfile: str = _flag("", None, "TLS private key for the REST port")
    allow_wire_udf: bool = _flag(False, "H2O_TPU_ALLOW_WIRE_UDF",
                                 "allow python: UDF references uploaded "
                                 "over the wire to execute")

    # -- engine knobs (sys.ai.h2o.* expert-prop analog) --------------------
    compile_cache: str = _flag("", "H2O_TPU_COMPILE_CACHE",
                               "persistent XLA compile cache dir "
                               "('0' disables; empty = backend default)")
    exact_bin_rows: int = _flag(16384, "H2O_TPU_EXACT_BIN_ROWS",
                                "rows at or below which tree binning uses "
                                "exact small-data cut points")
    clear_caches_every: int = _flag(0, "H2O_TPU_CLEAR_CACHES_EVERY",
                                    "drop live XLA executables every N "
                                    "models (long-running-server hygiene; "
                                    "0 = never)")
    pdp_batch_rows: int = _flag(2_000_000, "H2O_TPU_PDP_BATCH_ROWS",
                                "row budget per batched partial-dependence "
                                "predict")

    # -- external systems --------------------------------------------------
    webhdfs_url: str = _flag("", "H2O_TPU_WEBHDFS_URL",
                             "WebHDFS endpoint for hdfs:// persist")
    webhdfs_port: int = _flag(9870, "H2O_TPU_WEBHDFS_PORT",
                              "WebHDFS port when hdfs:// URIs carry none")
    hdfs_user: str = _flag("", "H2O_TPU_HDFS_USER",
                           "user.name forwarded to WebHDFS")
    hive_jdbc: str = _flag("", "H2O_TPU_HIVE_JDBC",
                           "Hive JDBC endpoint for ImportHiveTable")

    # -- logging -----------------------------------------------------------
    log_level: str = _flag("INFO", None,
                           "TRACE|DEBUG|INFO|WARN|ERRR|FATA")

    def export_env(self) -> None:
        """Write every env-backed resolved value back into os.environ so the
        scattered runtime consumers observe the flag values (the H2O.ARGS
        global, realized through the environment)."""
        for f in dataclasses.fields(self):
            env = f.metadata.get("env")
            if not env:
                continue
            v = getattr(self, f.name)
            if isinstance(v, bool):
                if v:
                    os.environ[env] = "1"
                else:
                    os.environ.pop(env, None)
            elif v not in ("", None) and v != f.default:
                os.environ[env] = str(v)


def _coerce(f: dataclasses.Field, raw: str):
    if f.type in ("int", int):
        return int(raw)
    if f.type in ("bool", bool):
        return str(raw).lower() in ("1", "true", "yes", "on")
    return raw


def parse(argv: list[str] | None = None) -> OptArgs:
    """CLI > environment > default, reflectively over the dataclass
    (`water/H2O.java:581-588` parseArguments)."""
    args = OptArgs()
    # environment layer
    for f in dataclasses.fields(OptArgs):
        env = f.metadata.get("env")
        if env and os.environ.get(env) not in (None, ""):
            try:
                setattr(args, f.name, _coerce(f, os.environ[env]))
            except ValueError:
                raise SystemExit(
                    f"bad value for {env}: {os.environ[env]!r}")
    # CLI layer
    fields = {f.name: f for f in dataclasses.fields(OptArgs)}
    argv = list(argv or [])
    i = 0
    while i < len(argv):
        tok = argv[i]
        if tok in ("--help", "-help", "-h"):
            print(help_text())
            raise SystemExit(0)
        name = tok.lstrip("-").replace("-", "_")
        f = fields.get(name)
        if f is None:
            raise SystemExit(f"unknown flag {tok!r} (see --help)")
        if f.type in ("bool", bool):
            # `--flag` or `--flag true/false`
            if i + 1 < len(argv) and argv[i + 1].lower() in (
                    "true", "false", "1", "0"):
                setattr(args, name, _coerce(f, argv[i + 1]))
                i += 2
            else:
                setattr(args, name, True)
                i += 1
            continue
        if i + 1 >= len(argv):
            raise SystemExit(f"flag {tok!r} needs a value")
        try:
            setattr(args, name, _coerce(f, argv[i + 1]))
        except ValueError:
            raise SystemExit(f"bad value for {tok}: {argv[i + 1]!r}")
        i += 2
    args.export_env()
    return args


def help_text() -> str:
    """`printHelp` analog — generated from the dataclass metadata."""
    lines = ["usage: python -m h2o_tpu.deploy_entry [flags]", "",
             "Flags (CLI > environment > default):", ""]
    for f in dataclasses.fields(OptArgs):
        env = f.metadata.get("env")
        default = f.default
        spec = f"  --{f.name.replace('_', '-'):<32}"
        lines.append(spec + f.metadata["help"])
        detail = f"        default: {default!r}"
        if env:
            detail += f"   env: {env}"
        lines.append(detail)
    return "\n".join(lines)


#: the resolved flags for this process (the `H2O.ARGS` global); deploy_entry
#: re-parses with the real argv
ARGS = OptArgs()
