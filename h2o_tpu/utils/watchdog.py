"""Watchdog supervisor — one thread that notices what stopped moving.

Every hang this repo has shipped so far was only diagnosable after the
fact, by a human reading `/3/Timeline` — a wedged MRTask dispatch, a
Cleaner thrashing between spill and rehydrate, a serving queue whose
worker died. The watchdog turns those into PROACTIVE typed events while
the process still lives: one sweep thread (interval
``H2O_TPU_WATCHDOG_MS``, sanitizer-lock-disciplined) runs four
detectors, each reading state the subsystems already publish:

1. **hung-job** — a RUNNING `backend/jobs.py` Job whose progress
   heartbeat (``Job.beat()``, fed by ``update``/``check_cancelled`` at
   every chunk/epoch boundary) is older than
   ``H2O_TPU_WATCHDOG_JOB_BUDGET_MS``.
2. **mrtask-stall** — a driver dispatch in flight (the
   ``parallel/mrtask.py`` in-flight table) past
   ``H2O_TPU_WATCHDOG_DISPATCH_BUDGET_MS``.
3. **cleaner-thrash** — spill AND rehydrate counters both advancing more
   than ``H2O_TPU_WATCHDOG_THRASH_OPS`` within one interval (the memory
   death spiral: evict, reload, evict again).
4. **queue-stall** — a serving MicroBatcher whose oldest queued request
   has waited past ``H2O_TPU_WATCHDOG_QUEUE_BUDGET_MS`` (worker wedged
   or paused while traffic queues).

Every trip lands a typed ``watchdog`` timeline event, bumps
``watchdog.trip.count``, sets the per-detector gauge (Prometheus-visible
— the autoscaler/rollback loops read the same registry), and writes a
proactive flight-recorder bundle (``watchdog-<detector>``) with the full
thread dump — while the guarded work CONTINUES: a watchdog observes, it
never kills. Per-subject cooldown stops a persistent condition from
rotating the flight dir every sweep.

The registered ``watchdog.trip`` failpoint drills every detector: armed,
each detector's evaluation consumes one hit and reports a forced finding
— CI exercises all four trip paths (event + gauge + bundle) in one sweep
with nothing actually wrong.
"""

from __future__ import annotations

import threading
import time

from . import failpoints, knobs, telemetry, timeline

#: detector name -> gauge metric, in fixed evaluation order (the
#: failpoint drill's @K semantics depend on this order being stable)
DETECTORS = (
    ("hung-job", "watchdog.hung_jobs"),
    ("mrtask-stall", "watchdog.stalled_dispatch"),
    ("cleaner-thrash", "watchdog.cleaner_thrash"),
    ("queue-stall", "watchdog.queue_stall"),
)


def _ms(name: str) -> float:
    return max(knobs.get_int(name), 1) / 1000.0


def stale_running_jobs(budget_s: float | None = None) -> list[dict]:
    """RUNNING jobs whose progress heartbeat (``Job.last_beat``) is older
    than ``budget_s`` (default: the H2O_TPU_WATCHDOG_JOB_BUDGET_MS knob)
    — the ONE hung-job rule, shared by the watchdog's detector and the
    /3/Health job check (which must work with the watchdog disarmed)."""
    import sys

    jobs_mod = sys.modules.get("h2o_tpu.backend.jobs")
    if jobs_mod is None:
        return []
    from ..backend.kvstore import STORE

    if budget_s is None:
        budget_s = _ms("H2O_TPU_WATCHDOG_JOB_BUDGET_MS")
    now = time.time()
    out = []
    for job in STORE.values(jobs_mod.Job):
        if not job.is_running() or not job.start_time:
            continue
        stale = now - job.last_beat
        if stale > budget_s:
            out.append({"subject": str(job.key),
                        "desc": job.description,
                        "stale_s": round(stale, 3),
                        "budget_s": budget_s})
    return out


class Watchdog:
    """One supervisor instance; the process singleton lives behind
    :func:`ensure_started` (server boot arms it when the interval knob is
    set), tests drive private instances via :meth:`sweep`."""

    #: sweeps a (detector, subject) pair stays quiet after tripping — a
    #: wedged job must not write a bundle per sweep
    COOLDOWN_SWEEPS = 30

    def __init__(self, interval_s: float | None = None):
        from . import sanitizer

        # resolved HERE (not in start) so the field is written once,
        # before the sweep thread can read it
        self.interval_s = (interval_s if interval_s is not None
                           else knobs.get_int("H2O_TPU_WATCHDOG_MS")
                           / 1000.0)
        self._lock = sanitizer.make_lock("Watchdog._state")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._sweeps = 0
        #: (detector, subject) -> sweep number of the last trip
        self._tripped: dict[tuple, int] = {}
        #: last-seen Cleaner counter values for the thrash delta
        self._spill0 = self._rehydrate0 = None
        #: recent trips for /3/Health: detector -> (wall stamp, detail)
        self.last_trips: dict[str, tuple[float, dict]] = {}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Watchdog":
        with self._lock:
            if self._thread is not None:
                return self
            # the supervisor outlives any request context and roots its
            # own events — carrying a creator's trace would fabricate
            # causality
            self._thread = threading.Thread(  # graftlint: disable=thread-without-trace-context
                target=self._run, daemon=True, name="h2o-watchdog")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None:
            # join OUTSIDE the state lock (the sweep takes it)
            t.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sweep()
            except Exception as e:  # noqa: BLE001 — the supervisor must
                from . import log   # outlive anything it observes

                log.err(f"watchdog sweep failed: {e!r}")

    # -- one sweep -----------------------------------------------------------
    def sweep(self) -> dict:
        """Run every detector once; returns {detector: [findings]} (tests
        drive this directly — no thread required)."""
        with self._lock:
            self._sweeps += 1
            sweep_no = self._sweeps
        checks = {"hung-job": self._hung_jobs,
                  "mrtask-stall": self._stalled_dispatch,
                  "cleaner-thrash": self._cleaner_thrash,
                  "queue-stall": self._queue_stall}
        out: dict[str, list] = {}
        for detector, gauge in DETECTORS:
            forced = False
            try:
                # the drill seam: armed `watchdog.trip` turns this
                # detector's evaluation into a forced positive (*4 drills
                # all four in one sweep, @K exactly one)
                failpoints.hit("watchdog.trip")
            except failpoints.InjectedFault as e:
                forced = True
                findings = [{"subject": "drill", "forced": True,
                             "hit": e.hit_no}]
            if not forced:
                try:
                    findings = checks[detector]()
                except Exception as e:  # noqa: BLE001 — a sick subsystem
                    findings = []       # must not kill the other detectors
                    from . import log

                    log.err(f"watchdog {detector} check failed: {e!r}")
            out[detector] = findings
            telemetry.set_gauge(gauge, float(len(findings)))
            for f in findings:
                self._trip(detector, f, sweep_no)
        return out

    def _trip(self, detector: str, finding: dict, sweep_no: int) -> None:
        key = (detector, finding.get("subject"))
        with self._lock:
            last = self._tripped.get(key)
            if last is not None and sweep_no - last < self.COOLDOWN_SWEEPS:
                return
            self._tripped[key] = sweep_no
            self.last_trips[detector] = (time.time(), dict(finding))
        telemetry.inc("watchdog.trip.count")
        timeline.record("watchdog", detector, **finding)
        from . import flightrec, log

        log.warn(f"watchdog tripped: {detector} {finding}")
        # proactive bundle, job continues — dump() never raises; the
        # watchdog thread holds no application locks here so the inline
        # (non-async) write is safe and keeps bundle ordering deterministic
        flightrec.dump(f"watchdog-{detector}")

    def recent_trips(self, max_age_s: float | None = None) -> dict:
        """{detector: {age_s, ...finding}} of trips newer than
        ``max_age_s`` (default: 10 sweep intervals) — the /3/Health
        degradation source; an old trip ages out to 'recovered'."""
        if max_age_s is None:
            max_age_s = (self.interval_s or
                         knobs.get_int("H2O_TPU_WATCHDOG_MS") / 1000.0) * 10
        now = time.time()
        with self._lock:
            items = dict(self.last_trips)
        return {d: {"age_s": round(now - ts, 3), **f}
                for d, (ts, f) in items.items()
                if now - ts <= max_age_s}

    # -- detectors -----------------------------------------------------------
    def _hung_jobs(self) -> list[dict]:
        return stale_running_jobs()

    def _stalled_dispatch(self) -> list[dict]:
        import sys

        mr = sys.modules.get("h2o_tpu.parallel.mrtask")
        if mr is None:
            return []
        budget_s = _ms("H2O_TPU_WATCHDOG_DISPATCH_BUDGET_MS")
        now = time.monotonic()
        out = []
        for tid, (t0, fn) in list(mr.inflight_dispatches().items()):
            if now - t0 > budget_s:
                out.append({"subject": f"thread-{tid}", "fn": fn,
                            "in_flight_s": round(now - t0, 3),
                            "budget_s": budget_s})
        return out

    def _cleaner_thrash(self) -> list[dict]:
        spill = telemetry.value("cleaner.spill.count")
        rehydrate = telemetry.value("cleaner.rehydrate.count")
        s0, r0 = self._spill0, self._rehydrate0
        self._spill0, self._rehydrate0 = spill, rehydrate
        if s0 is None:
            return []
        threshold = max(knobs.get_int("H2O_TPU_WATCHDOG_THRASH_OPS"), 1)
        churn = min(spill - s0, rehydrate - r0)
        if churn > threshold:
            return [{"subject": "cleaner", "spills": spill - s0,
                     "rehydrates": rehydrate - r0,
                     "threshold": threshold}]
        return []

    def _queue_stall(self) -> list[dict]:
        import sys

        rt_mod = sys.modules.get("h2o_tpu.serving.runtime")
        rt = getattr(rt_mod, "_RUNTIME", None) if rt_mod else None
        if rt is None:
            return []
        budget_s = _ms("H2O_TPU_WATCHDOG_QUEUE_BUDGET_MS")
        out = []
        with rt._lock:
            models = dict(rt._models)
        for mid, served in models.items():
            for rep in served.replicas.replicas:
                wait = rep.batcher.oldest_wait_s()
                if wait is not None and wait > budget_s:
                    out.append({"subject": f"{mid}#r{rep.idx}",
                                "oldest_wait_s": round(wait, 3),
                                "depth": rep.batcher.depth,
                                "budget_s": budget_s})
        return out


# ---------------------------------------------------------------------------
# process singleton
# ---------------------------------------------------------------------------
_DOG: Watchdog | None = None
_DOG_LOCK = threading.Lock()


def ensure_started() -> Watchdog | None:
    """Arm the process watchdog when ``H2O_TPU_WATCHDOG_MS`` > 0 (server
    boot calls this; idempotent). Returns the running instance or None
    while disarmed."""
    global _DOG
    if knobs.get_int("H2O_TPU_WATCHDOG_MS") <= 0:
        return _DOG
    with _DOG_LOCK:
        if _DOG is None:
            _DOG = Watchdog().start()
        return _DOG


def instance() -> Watchdog | None:
    """The running process watchdog, if armed (health checks read it)."""
    return _DOG


def stop() -> None:
    """Tear down the singleton (tests)."""
    global _DOG
    with _DOG_LOCK:
        dog, _DOG = _DOG, None
    if dog is not None:
        dog.stop()
