"""Kerberos SPNEGO (HTTP Negotiate) authentication — the
`h2o-ext-krbstandalone` / Jetty `SpnegoLoginService` analog.

The HTTP layer (RFC 4559): an unauthenticated request gets
``401 WWW-Authenticate: Negotiate``; the client answers with
``Authorization: Negotiate <base64 GSS token>``; the server feeds the token
to an acceptor and admits the request when a principal comes back.

``SpnegoAuth`` owns that protocol. Token verification is a seam:

- by default, ``gss_accept_sec_context`` through ctypes on
  ``libgssapi_krb5`` with acceptor credentials from the keytab named by
  ``KRB5_KTNAME`` (how the reference's `-spnego_login` deployments work);
- or any ``verify_token(bytes) -> principal | None`` callable (tests drive
  the full HTTP handshake through a stub; a GSSAPI-SDK verifier plugs in
  the same way).

Wire into the server with ``H2OServer(negotiate_auth=SpnegoAuth(...))``.
"""

from __future__ import annotations

import base64
import ctypes
import ctypes.util
import os


class _GssBuffer(ctypes.Structure):
    _fields_ = [("length", ctypes.c_size_t), ("value", ctypes.c_void_p)]


class _GssOID(ctypes.Structure):
    _fields_ = [("length", ctypes.c_uint32), ("elements", ctypes.c_void_p)]


def _load_gssapi():
    name = ctypes.util.find_library("gssapi_krb5") or "libgssapi_krb5.so.2"
    return ctypes.CDLL(name)


def gss_verify_token(token: bytes) -> str | None:
    """Accept one SPNEGO/Kerberos token with the host's GSSAPI library;
    returns the initiator principal or None. Acceptor credentials come from
    the environment (KRB5_KTNAME keytab), exactly like the JVM's
    ``sun.security.jgss`` acceptor."""
    lib = _load_gssapi()
    minor = ctypes.c_uint32(0)
    ctx = ctypes.c_void_p(None)
    in_buf = _GssBuffer(len(token), ctypes.cast(
        ctypes.create_string_buffer(token, len(token)), ctypes.c_void_p))
    out_buf = _GssBuffer(0, None)
    src_name = ctypes.c_void_p(None)
    major = lib.gss_accept_sec_context(
        ctypes.byref(minor), ctypes.byref(ctx),
        None,                      # acceptor cred: default (keytab)
        ctypes.byref(in_buf),
        None,                      # channel bindings
        ctypes.byref(src_name),
        None,                      # mech type out
        ctypes.byref(out_buf),
        None, None, None)          # flags, time, delegated cred
    try:
        if major != 0:             # GSS_S_COMPLETE only (no multi-leg)
            return None
        name_buf = _GssBuffer(0, None)
        if lib.gss_display_name(ctypes.byref(minor), src_name,
                                ctypes.byref(name_buf), None) != 0:
            return None
        principal = ctypes.string_at(name_buf.value,
                                     name_buf.length).decode()
        lib.gss_release_buffer(ctypes.byref(minor), ctypes.byref(name_buf))
        return principal
    finally:
        if out_buf.value:
            lib.gss_release_buffer(ctypes.byref(minor),
                                   ctypes.byref(out_buf))
        if src_name.value:
            lib.gss_release_name(ctypes.byref(minor),
                                 ctypes.byref(src_name))
        if ctx.value:
            lib.gss_delete_sec_context(ctypes.byref(minor),
                                       ctypes.byref(ctx), None)


class SpnegoAuth:
    """HTTP Negotiate acceptor for the REST server.

    ``check_header(authorization) -> principal | None`` consumes the raw
    Authorization header. ``challenge`` is what a 401 must advertise."""

    challenge = "Negotiate"

    def __init__(self, verify_token=None, require_keytab: bool = True):
        if verify_token is None:
            if require_keytab and not os.environ.get("KRB5_KTNAME"):
                raise ValueError(
                    "SPNEGO needs acceptor credentials: set KRB5_KTNAME to "
                    "the service keytab (or pass verify_token=...)")
            verify_token = gss_verify_token
        self.verify_token = verify_token

    def check_header(self, authorization: str | None) -> str | None:
        if not authorization or not authorization.startswith("Negotiate "):
            return None
        try:
            token = base64.b64decode(authorization[len("Negotiate "):],
                                     validate=True)
        except Exception:
            return None
        if not token:
            return None
        try:
            return self.verify_token(token)
        except Exception:
            return None
