"""Process-global XLA compile counter — the serving warmup contract's meter.

The serving runtime promises "zero recompiles in steady state": every bucket
executable is AOT-compiled at model registration (`serving/scorer.py`), so a
compile observed during request serving is a bug (a shape that escaped the
buckets, a donated-buffer retrace, ...). That promise is only assertable if
compiles are *countable*, which jax exposes through `jax.monitoring`: the
dispatch layer records one `/jax/core/compile/backend_compile_duration`
event per program that reaches the backend compiler (cache hits do NOT
fire it — a replay from the persistent compile cache is not a compile).

`count()` returns the monotone process-wide total; callers measure deltas
around the region they care about::

    before = compilemeter.count()
    ...serve traffic...
    assert compilemeter.count() - before == 0

The listener is registered once per process (jax.monitoring offers no
unregister, so install() is idempotent by module flag) and costs one dict
lookup per monitoring event — nothing on the request path.
"""

from __future__ import annotations

import contextlib
import threading

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_installed = False
_count = 0


def _listener(name: str, secs: float, **kw) -> None:
    global _count
    if name == _COMPILE_EVENT:
        with _lock:
            _count += 1
        # every backend compile is also a registry counter and a timeline
        # event, so /3/Metrics and the bench sidecar deltas carry compile
        # counts per leg and cold-start cost is visible in /3/Timeline
        # (compiles are rare by contract — recording one is not hot-path)
        from . import telemetry, timeline

        telemetry.inc("xla.compile.count")
        timeline.record("compile", "backend_compile",
                        secs=round(float(secs), 4))


def install() -> None:
    """Register the monitoring listener (idempotent, lazy jax import)."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    import jax

    jax.monitoring.register_event_duration_secs_listener(_listener)


def count() -> int:
    """Total XLA backend compiles observed in this process so far.

    Process-global by nature: steady-state serving accounting must NOT
    diff this around individual device calls (a concurrent registration
    or training job would be blamed on the serving path) — the stats
    ``recompiles`` gauge counts the scorer's own bucket-miss fallbacks
    instead (`serving/scorer.py`). This counter is for delta assertions
    in controlled regions: warmup cost reporting and the zero-recompile
    tests."""
    install()
    with _lock:
        return _count


class CompileScope:
    """Compile-count delta over one region — the context-LOCAL reading the
    global counter's docstring warns against misusing: a scope pins its own
    start, so two concurrent scopes each see every compile in their window
    (attribution of a shared backend is inherently shared; per-cause
    blame stays with `serving/scorer.py`'s own bucket-miss gauge)."""

    __slots__ = ("start", "_end")

    def __init__(self, start: int):
        self.start = start
        self._end: int | None = None

    @property
    def compiles(self) -> int:
        """Compiles observed since the scope opened (frozen at exit)."""
        return (count() if self._end is None else self._end) - self.start


@contextlib.contextmanager
def scoped():
    """``with compilemeter.scoped() as sc: ... ; sc.compiles`` — the delta
    pattern made first-class (bench legs, per-train cold-start metering),
    mirroring PR 4's bucket-miss fix: read a scope, not the global."""
    sc = CompileScope(count())
    try:
        yield sc
    finally:
        sc._end = count()
