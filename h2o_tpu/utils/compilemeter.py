"""Process-global XLA compile counter — the serving warmup contract's meter.

The serving runtime promises "zero recompiles in steady state": every bucket
executable is AOT-compiled at model registration (`serving/scorer.py`), so a
compile observed during request serving is a bug (a shape that escaped the
buckets, a donated-buffer retrace, ...). That promise is only assertable if
compiles are *countable*, which jax exposes through `jax.monitoring`: the
dispatch layer records one `/jax/core/compile/backend_compile_duration`
event per program that reaches the compile path.

One version-measured caveat (jax 0.4.x): that event wraps
``compiler.compile_or_get_cached``, so it fires for PERSISTENT-CACHE HITS
too — a program replayed from the `utils/compile_cache.py` disk cache
counts as a "compile" even though no XLA compilation ran. The cache layer
emits its own `/jax/compilation_cache/cache_hits` event per replay, so
this module tracks both and exposes the number that actually costs wall:

- ``count()``       — programs through the compile path (builds + replays);
- ``cache_hits()``  — persistent-cache replays among them;
- ``uncached_count()`` — real XLA compilations (count − cache_hits), the
  cold-start acceptance metric of the bench ``cold_start`` leg.

`count()` deltas remain the right meter where NO compile activity at all
is the contract (serving steady state: both numbers are zero). Callers
measure deltas around the region they care about::

    before = compilemeter.count()
    ...serve traffic...
    assert compilemeter.count() - before == 0

The listeners are registered once per process (jax.monitoring offers no
unregister, so install() is idempotent by module flag) and cost one dict
lookup per monitoring event — nothing on the request path.
"""

from __future__ import annotations

import contextlib
import threading

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

_lock = threading.Lock()
_installed = False
_count = 0
_cache_hits = 0


#: thread-local steady-state guard (H2O_TPU_SANITIZE=recompiles): jax
#: compiles synchronously on the dispatching thread, so a per-thread
#: scope stack attributes every compile event to the section that
#: dispatched it — concurrent training/registration on OTHER threads
#: never trips a serving scope (the blame problem the global counter's
#: docstring warns about, solved by construction)
_STEADY = threading.local()


def _steady_stack() -> list:
    stack = getattr(_STEADY, "stack", None)
    if stack is None:
        stack = _STEADY.stack = []
    return stack


def _listener(name: str, secs: float, **kw) -> None:
    global _count
    if name == _COMPILE_EVENT:
        with _lock:
            _count += 1
        # every backend compile is also a registry counter and a timeline
        # event, so /3/Metrics and the bench sidecar deltas carry compile
        # counts per leg and cold-start cost is visible in /3/Timeline
        # (compiles are rare by contract — recording one is not hot-path)
        from . import telemetry, timeline

        telemetry.inc("xla.compile.count")
        timeline.record("compile", "backend_compile",
                        secs=round(float(secs), 4))
        stack = _steady_stack()
        if stack:
            # a persistent-cache replay fires its cache_hits event INSIDE
            # compile_or_get_cached, i.e. BEFORE this duration event on
            # the same thread — consuming one pending hit pairs them, so
            # replays (zero XLA wall) never raise
            if getattr(_STEADY, "hits", 0) > 0:
                _STEADY.hits -= 1
                return
            from . import sanitizer

            err = sanitizer.SteadyStateCompileError(stack[-1])
            sanitizer._emit_violation("steady_compile", err,
                                      section=stack[-1])
            raise err


def _event_listener(name: str, **kw) -> None:
    global _cache_hits
    if name == _CACHE_HIT_EVENT:
        with _lock:
            _cache_hits += 1
        if _steady_stack():
            _STEADY.hits = getattr(_STEADY, "hits", 0) + 1


def install() -> None:
    """Register the monitoring listeners (idempotent, lazy jax import)."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    import jax

    jax.monitoring.register_event_duration_secs_listener(_listener)
    jax.monitoring.register_event_listener(_event_listener)


def count() -> int:
    """Total programs through the XLA compile path in this process so far
    (real compilations AND persistent-cache replays — see module doc).

    Process-global by nature: steady-state serving accounting must NOT
    diff this around individual device calls (a concurrent registration
    or training job would be blamed on the serving path) — the stats
    ``recompiles`` gauge counts the scorer's own bucket-miss fallbacks
    instead (`serving/scorer.py`). This counter is for delta assertions
    in controlled regions: warmup cost reporting and the zero-recompile
    tests."""
    install()
    with _lock:
        return _count


def cache_hits() -> int:
    """Persistent compile-cache replays observed so far."""
    install()
    with _lock:
        return _cache_hits


def uncached_count() -> int:
    """Real XLA compilations so far: ``count() − cache_hits()``, floored
    at 0 (the floor defends against event-ordering races; the two events
    fire from the same dispatch stack, so in practice it never engages)."""
    install()
    with _lock:
        return max(_count - _cache_hits, 0)


class CompileScope:
    """Compile-count delta over one region — the context-LOCAL reading the
    global counter's docstring warns against misusing: a scope pins its own
    start, so two concurrent scopes each see every compile in their window
    (attribution of a shared backend is inherently shared; per-cause
    blame stays with `serving/scorer.py`'s own bucket-miss gauge)."""

    __slots__ = ("start", "start_hits", "_end", "_end_hits")

    def __init__(self, start: int, start_hits: int):
        self.start = start
        self.start_hits = start_hits
        self._end: int | None = None
        self._end_hits: int | None = None

    @property
    def compiles(self) -> int:
        """Programs through the compile path since the scope opened
        (frozen at exit) — builds plus persistent-cache replays."""
        return (count() if self._end is None else self._end) - self.start

    @property
    def hits(self) -> int:
        """Persistent-cache replays in the window."""
        return ((cache_hits() if self._end_hits is None else self._end_hits)
                - self.start_hits)

    @property
    def uncached(self) -> int:
        """Real XLA compilations in the window (compiles − hits, ≥ 0)."""
        return max(self.compiles - self.hits, 0)


@contextlib.contextmanager
def scoped():
    """``with compilemeter.scoped() as sc: ... ; sc.compiles`` — the delta
    pattern made first-class (bench legs, per-train cold-start metering),
    mirroring PR 4's bucket-miss fix: read a scope, not the global."""
    sc = CompileScope(count(), cache_hits())
    try:
        yield sc
    finally:
        sc._end = count()
        sc._end_hits = cache_hits()


@contextlib.contextmanager
def no_compile_scope(section: str):
    """Declare the enclosed dispatches steady-state: under
    ``H2O_TPU_SANITIZE=recompiles`` any UNCACHED compile inside raises the
    typed :class:`~h2o_tpu.utils.sanitizer.SteadyStateCompileError` at the
    dispatching call site, naming ``section``. Persistent-cache replays
    are paired off against their cache-hit events and never raise (they
    cost no XLA wall). Thread-local: a concurrent registration or
    training job compiling on another thread is its own business.

    No-op (one cached env read) when the mode is off — the hot-path
    wiring sites (GBM chunk dispatch, serving _score_bucket) pay nothing
    in production."""
    from . import sanitizer

    if not sanitizer.enabled("recompiles"):
        yield
        return
    install()
    stack = _steady_stack()
    if not stack:
        _STEADY.hits = 0
    stack.append(section)
    try:
        yield
    finally:
        stack.pop()
