"""LDAP simple-bind authentication — the `-ldap_login` analog.

The reference authenticates REST users against LDAP through Jetty's JAAS
`LdapLoginModule` (`h2o-security/`, `water/webserver/jetty9/`). Here the
LDAPv3 simple-bind exchange is spoken directly over a socket in ~60 lines of
BER (RFC 4511 §4.2): one BindRequest, one BindResponse, resultCode 0 means
the directory accepted the credentials. No SDK, no SASL — exactly the subset
`-ldap_login` uses in practice (user DN template + password).
"""

from __future__ import annotations

import os
import socket


def _ber_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def _ber(tag: int, payload: bytes) -> bytes:
    return bytes([tag]) + _ber_len(len(payload)) + payload


def bind_request(message_id: int, dn: str, password: str) -> bytes:
    """LDAPMessage { messageID, BindRequest { version=3, name, simple } }."""
    bind = (_ber(0x02, bytes([3]))              # version INTEGER 3
            + _ber(0x04, dn.encode())           # name LDAPDN
            + _ber(0x80, password.encode()))    # simple [0] OCTET STRING
    msg = (_ber(0x02, bytes([message_id]))
           + _ber(0x60, bind))                  # [APPLICATION 0] BindRequest
    return _ber(0x30, msg)


def _read_ber_len(buf: bytes, pos: int) -> tuple[int, int]:
    """Decode one BER length at ``pos``; returns (length, next_pos)."""
    first = buf[pos]
    pos += 1
    if first < 0x80:
        return first, pos
    n = first & 0x7F
    return int.from_bytes(buf[pos:pos + n], "big"), pos + n


def parse_bind_response(data: bytes) -> int:
    """Extract resultCode from the BindResponse (0 = success). Explicit
    checks, NOT assert — `python -O` strips asserts, and a misparsed
    non-BindResponse must never read as success."""
    if not data or data[0] != 0x30:
        raise ValueError("not an LDAPMessage")
    _, pos = _read_ber_len(data, 1)
    if data[pos] != 0x02:                 # messageID
        raise ValueError("missing messageID")
    mlen, pos = _read_ber_len(data, pos + 1)
    pos += mlen
    if data[pos] != 0x61:                 # [APPLICATION 1] BindResponse
        raise ValueError(f"not a BindResponse (tag 0x{data[pos]:02x})")
    _, pos = _read_ber_len(data, pos + 1)
    if data[pos] not in (0x0A, 0x02):     # resultCode ENUMERATED
        raise ValueError("missing resultCode")
    rlen, pos = _read_ber_len(data, pos + 1)
    return int.from_bytes(data[pos:pos + rlen], "big")


def ldap_bind(host: str, port: int, dn: str, password: str,
              timeout: float = 5.0, use_tls: bool = False,
              ssl_context=None) -> bool:
    """One simple bind; True iff the directory returns resultCode 0.
    Anonymous binds (empty password) are rejected up front — RFC 4513 §5.1.2
    (unauthenticated bind) would otherwise 'succeed' for any user.
    ``use_tls`` speaks ldaps (the credential travels encrypted — the
    LdapLoginModule ldaps:// role); plain 389 is for lab directories only."""
    if not password:
        return False
    with socket.create_connection((host, port), timeout=timeout) as raw:
        if use_tls:
            import ssl

            ctx = ssl_context or ssl.create_default_context()
            s = ctx.wrap_socket(raw, server_hostname=host)
        else:
            s = raw
        try:
            s.sendall(bind_request(1, dn, password))
            data = _recv_ber_message(s)
        finally:
            if use_tls:
                s.close()
    try:
        return parse_bind_response(data) == 0
    except (ValueError, IndexError):
        return False


def _recv_ber_message(s: socket.socket) -> bytes:
    """Read one complete BER message (a WAN peer may flush the header and
    body in separate segments — recv until the declared length arrives)."""
    buf = b""
    while True:
        # need tag + length octets first
        need = 2
        if len(buf) >= 2 and buf[1] & 0x80:
            need = 2 + (buf[1] & 0x7F)
        if len(buf) >= need:
            blen, body_pos = _read_ber_len(buf, 1)
            total = body_pos + blen
            if len(buf) >= total:
                return buf[:total]
        chunk = s.recv(4096)
        if not chunk:
            return buf
        buf += chunk


def escape_dn_value(value: str) -> str:
    """RFC 4514 escaping of one DN attribute value — an attacker-controlled
    username must not rewrite the bind DN ('admin,ou=service' injection)."""
    out = []
    for i, ch in enumerate(value):
        if ch in ',+"\\<>;=':
            out.append("\\" + ch)
        elif ch in "# " and i in (0, len(value) - 1):
            out.append("\\" + ch)
        elif ord(ch) < 0x20:
            out.append(f"\\{ord(ch):02x}")
        else:
            out.append(ch)
    return "".join(out)


class LdapAuth:
    """Server-side auth hook: Basic credentials verified by LDAP bind.

    ``dn_template`` turns a username into a bind DN, e.g.
    ``"uid={},ou=people,dc=example,dc=org"`` (the LdapLoginModule
    userDnTemplate role). ``use_tls`` speaks ldaps (default port 636).
    Successful verdicts are cached for ``cache_ttl_s`` against a salted
    credential hash — h2o-py polls jobs sub-second, and a bind storm per
    request would both stall on directory hiccups and trip lockout policies
    (the Jetty session-auth role in the reference)."""

    def __init__(self, host: str, port: int | None = None,
                 dn_template: str = "uid={}", use_tls: bool = False,
                 ssl_context=None, cache_ttl_s: float = 300.0):
        self.host = host
        self.port = port if port is not None else (636 if use_tls else 389)
        self.dn_template = dn_template
        self.use_tls = use_tls
        self.ssl_context = ssl_context
        self.cache_ttl_s = cache_ttl_s
        self._cache: dict = {}
        self._salt = os.urandom(16)
        self._lock = __import__("threading").Lock()

    def _fingerprint(self, user: str, password: str) -> bytes:
        import hashlib

        return hashlib.sha256(
            self._salt + user.encode() + b"\0" + password.encode()).digest()

    def __call__(self, user: str, password: str) -> bool:
        import time

        fp = self._fingerprint(user, password)
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(fp)
            if hit is not None and now - hit < self.cache_ttl_s:
                return True
        # str.replace, NOT .format: a username containing '{' must not be a
        # format spec, and DN metacharacters are escaped (injection)
        dn = self.dn_template.replace("{}", escape_dn_value(user))
        try:
            ok = ldap_bind(self.host, self.port, dn, password,
                           use_tls=self.use_tls, ssl_context=self.ssl_context)
        except OSError:
            return False
        if ok:
            with self._lock:
                self._cache[fp] = now
                if len(self._cache) > 1024:  # bound the verdict cache
                    self._cache.clear()
        return ok
