"""Deterministic fault injection — the failpoint registry.

H2O-3's credibility came from surviving node loss mid-job; this repo's
fault-tolerance layer (auto-checkpoints in `models/model_base.py`, typed
retry in `utils/retry.py`) can only be held to the bit-parity standard if
every failure mode is EXERCISABLE on the CPU mesh, on demand, at an exact
iteration — not awaited in production. Failpoints are that lever: named
sites instrumented through the stack (parser, Cleaner spill/rehydrate,
MRTask dispatch, serving batcher, REST server, the training chunk loops)
call :func:`hit`, a no-op until a spec arms the site.

Mirrors `utils/knobs.py` deliberately: every site is DECLARED here with a
docstring, accessors raise ``KeyError`` for undeclared names, and
graftlint's ``unregistered-failpoint`` rule fails the build on any literal
site name missing from this registry (the linter parses this file's AST —
no import needed).

Activation — ``H2O_TPU_FAILPOINTS=site:spec,site2:spec`` (env, re-read on
every hit so tests can arm/disarm mid-process), or programmatically via
:func:`arm`/:func:`disarm`. Spec grammar::

    spec     := action [ "(" arg ")" ] [ "*" N | "@" K ]
    action   := "raise" | "sleep" | "http"
    raise    — raise an injected fault; arg picks the kind:
               fault (default) | oom (RESOURCE_EXHAUSTED-shaped) |
               preempt (simulated TPU preemption) | conn (ConnectionResetError)
    sleep    — inject latency; arg = milliseconds
    http     — raise InjectedHTTPError; arg = status code (the REST
               handler maps it to that reply, with Retry-After on 429/503)
    *N       — fire on the first N hits only (default: every hit)
    @K       — fire on exactly the K-th hit (1-based) — the kill-at-every-
               checkpoint-boundary driver

Examples: ``parser.parse:raise``, ``mrtask.dispatch:raise(conn)*2``,
``train.gbm.chunk:raise(preempt)@3``, ``serving.batch:sleep(50)``,
``rest.route:http(429)*2``.

Determinism contract: hit counters are per-site and monotonic from the
moment a site is armed (``reset()`` zeroes them); two runs arming the same
spec and hitting the site in the same order inject identically. No
randomness anywhere in this module.
"""

from __future__ import annotations

import dataclasses
import os
import re
import threading
import time


# ---------------------------------------------------------------------------
# injected exception types
# ---------------------------------------------------------------------------
class InjectedFault(RuntimeError):
    """Base class of every failpoint-raised error; carries the site and the
    1-based hit number so tests can pin exactly which injection fired."""

    def __init__(self, site: str, hit_no: int, detail: str = ""):
        self.site = site
        self.hit_no = hit_no
        super().__init__(
            f"injected fault at failpoint '{site}' (hit {hit_no})"
            + (f": {detail}" if detail else ""))


class InjectedOOM(InjectedFault):
    """Shaped like an XLA device OOM: the message contains
    RESOURCE_EXHAUSTED, which is the marker the Cleaner's rehydrate
    retry path keys off (`frame/vec.py`)."""

    def __init__(self, site: str, hit_no: int):
        super().__init__(site, hit_no, "RESOURCE_EXHAUSTED: out of memory")


class InjectedPreemption(InjectedFault):
    """Simulated TPU preemption / SIGTERM mid-train — the driver the
    kill-resume bit-parity tests use (a real kill is just this exception
    that nobody catches)."""


class InjectedHTTPError(InjectedFault):
    """REST-layer injection: the server handler replies with ``status``
    instead of routing; 429/503 carry ``retry_after_s`` so client retry
    paths can be exercised against a live flaky server."""

    def __init__(self, site: str, hit_no: int, status: int,
                 retry_after_s: float = 0.05):
        self.status = int(status)
        self.retry_after_s = retry_after_s
        super().__init__(site, hit_no, f"HTTP {status}")


_KINDS = {
    "fault": InjectedFault,
    "oom": InjectedOOM,
    "preempt": InjectedPreemption,
}


# ---------------------------------------------------------------------------
# site registry (the knobs.py discipline)
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Failpoint:
    name: str
    doc: str


FAILPOINTS: dict[str, Failpoint] = {}


def _failpoint(name: str, doc: str) -> None:
    FAILPOINTS[name] = Failpoint(name, doc)


_failpoint("parser.parse",
           "io/parser.py parse_file entry — a corrupt/unreadable ingest")
_failpoint("cleaner.spill",
           "backend/memory.py Cleaner._spill_locked — ice write failure "
           "under memory pressure")
_failpoint("cleaner.rehydrate",
           "frame/vec.py spilled-Vec reload device_put — inject oom to "
           "exercise the sweep-and-retry path")
_failpoint("mrtask.dispatch",
           "parallel/mrtask.py mr_reduce/mr_map driver dispatch")
_failpoint("serving.batch",
           "serving/batcher.py worker, before the compiled scorer runs — "
           "a device fault fanned out to every coalesced request")
_failpoint("serving.place",
           "serving/control.py placement, before bucket scorers compile — "
           "raise(oom) drills the placement-OOM admission path (typed "
           "429 + Retry-After, co-registered models unaffected)")
_failpoint("serving.replica",
           "serving/control.py replica score path, per device call — "
           "raise@K kills the replica executing the K-th call: it is "
           "marked dead, drained, and dispatch routes around it")
_failpoint("rest.route",
           "api/server.py request routing — http(code) specs make the "
           "server reply that status (429/503 with Retry-After), raise "
           "specs surface as 500s")
_failpoint("train.gbm.chunk",
           "models/gbm.py boosting chunk-loop top (GBM and DRF) — "
           "raise(preempt)@K kills the job before chunk K trains")
_failpoint("train.dl.epoch",
           "models/deeplearning.py epoch boundary — preemption mid-SGD")
_failpoint("train.checkpoint",
           "model_base auto-checkpoint, fires after each successful "
           "recovery-state write — kill exactly between checkpoints")
_failpoint("persist.checkpoint",
           "backend/persist.py atomic state write, between temp-write and "
           "rename — a crash here must leave the previous state intact")
_failpoint("persist.shard",
           "backend/persist.py shard-aware checkpoint, before EACH "
           "per-device shard-state write — raise@K kills the coordinator "
           "mid-shard-fanout; the uncommitted generation must be invisible "
           "to resume (manifest commits last)")
_failpoint("io.remote",
           "io/hdfs.py + io/cloud.py remote-read request wrappers — "
           "raise(conn)*N exercises the typed retry without a network")
_failpoint("client.request",
           "api/client.py H2OConnection._send — client-side transport "
           "fault before the wire")
_failpoint("sanitizer.trip",
           "utils/sanitizer.py SanitizedLock order check (fires on every "
           "cross-lock acquisition while H2O_TPU_SANITIZE=locks) — arm "
           "raise to drill the violation-handling path without a real "
           "inversion")
_failpoint("sanitizer.transfer",
           "utils/sanitizer.py transfer_scope entry (fires on every hot-"
           "section entry while H2O_TPU_SANITIZE=transfers) — arm raise "
           "to drill the typed TransferGuardViolation + flight-recorder "
           "seam on backends where the jax guard itself cannot trip (CPU "
           "arrays are host memory, so device->host is free there)")
_failpoint("watchdog.trip",
           "utils/watchdog.py detector evaluation (hit once per detector "
           "per sweep, in DETECTORS order: hung-job, mrtask-stall, "
           "cleaner-thrash, queue-stall) — arm raise*4 to force-trip all "
           "four detectors in one sweep (each writes its typed timeline "
           "event + gauge + flight bundle with nothing actually wrong), "
           "raise@K to drill exactly the K-th detector")
_failpoint("flightrec.dump",
           "utils/flightrec.py drill site, polled at the GBM/DRF chunk "
           "boundary and the serving batch worker (flightrec.maybe_drill) "
           "— arm raise@K to force a flight-recorder bundle at an exact "
           "iteration without a real crash; the injected fault is "
           "consumed by the recorder, the job continues")
_failpoint("workload.preempt",
           "workload preemption poll at every training chunk/epoch "
           "boundary (model_base._recovery_tick) — raise@K preempts the "
           "job exactly before boundary K trains: state force-"
           "checkpointed, HBM released through the ledger, job parked "
           "PREEMPTED; resume_training replays to a bit-equal model")


# ---------------------------------------------------------------------------
# spec parsing / arming
# ---------------------------------------------------------------------------
_SPEC_RE = re.compile(
    r"^(?P<action>raise|sleep|http)"
    r"(?:\((?P<arg>[^)]*)\))?"
    r"(?:\*(?P<times>\d+)|@(?P<at>\d+))?$")


@dataclasses.dataclass
class _Armed:
    site: str
    action: str             # raise | sleep | http
    arg: str                # kind / ms / status
    times: int | None       # fire on first N hits
    at: int | None          # fire on exactly the K-th hit
    spec: str               # original text (repr / observability)
    count: int = 0          # hits seen since armed (armed or not fired)

    def should_fire(self, n: int) -> bool:
        if self.at is not None:
            return n == self.at
        if self.times is not None:
            return n <= self.times
        return True


def _parse_spec(site: str, spec: str) -> _Armed:
    m = _SPEC_RE.match(spec.strip())
    if not m:
        raise ValueError(
            f"bad failpoint spec {spec!r} for site '{site}' — grammar: "
            f"action[(arg)][*N|@K], action in raise|sleep|http "
            f"(h2o_tpu/utils/failpoints.py)")
    action, arg = m.group("action"), (m.group("arg") or "").strip()
    if action == "raise":
        if arg and arg not in _KINDS and arg != "conn":
            raise ValueError(
                f"unknown raise kind {arg!r} for failpoint '{site}' — "
                f"one of {sorted(_KINDS) + ['conn']}")
    elif action == "sleep":
        if not arg or not arg.isdigit():
            raise ValueError(
                f"sleep spec for '{site}' needs integer milliseconds, "
                f"got {arg!r}")
    elif action == "http":
        if not arg.isdigit() or not 100 <= int(arg) <= 599:
            raise ValueError(
                f"http spec for '{site}' needs a status code, got {arg!r}")
    return _Armed(site=site, action=action, arg=arg,
                  times=int(m.group("times")) if m.group("times") else None,
                  at=int(m.group("at")) if m.group("at") else None,
                  spec=spec.strip())


def _lookup(name: str) -> Failpoint:
    try:
        return FAILPOINTS[name]
    except KeyError:
        raise KeyError(
            f"unregistered failpoint {name!r} — declare it in "
            f"h2o_tpu/utils/failpoints.py (graftlint rule "
            f"unregistered-failpoint enforces the same statically)") from None


_lock = threading.Lock()
_armed: dict[str, _Armed] = {}       # programmatic + env-derived, merged
_env_cache: str | None = None        # last-parsed H2O_TPU_FAILPOINTS value
_env_sites: dict[str, _Armed] = {}   # the env-derived subset


def _sync_env() -> None:
    """Re-parse H2O_TPU_FAILPOINTS when its value changed (reads are
    dynamic, like knobs — monkeypatching tests keep working). Counters of
    unchanged site:spec pairs survive the re-parse, so appending a second
    site mid-run never resets the first one's determinism."""
    global _env_cache
    raw = os.environ.get("H2O_TPU_FAILPOINTS", "")
    if raw == _env_cache:
        return
    with _lock:
        if raw == _env_cache:
            return
        fresh: dict[str, _Armed] = {}
        for pair in filter(None, (p.strip() for p in raw.split(","))):
            site, _, spec = pair.partition(":")
            site = site.strip()
            _lookup(site)
            armed = _parse_spec(site, spec)
            prev = _env_sites.get(site)
            if prev is not None and prev.spec == armed.spec:
                armed.count = prev.count
            fresh[site] = armed
        for site in list(_armed):
            if site in _env_sites and _armed[site] is _env_sites[site]:
                del _armed[site]     # env-owned entry: env now rules again
        _env_sites.clear()
        _env_sites.update(fresh)
        for site, armed in fresh.items():
            _armed.setdefault(site, armed)
        _env_cache = raw


def arm(name: str, spec: str) -> None:
    """Programmatically arm a site (tests); overrides any env spec."""
    _lookup(name)
    armed = _parse_spec(name, spec)
    with _lock:
        _armed[name] = armed


def disarm(name: str) -> None:
    _lookup(name)
    with _lock:
        _armed.pop(name, None)
        _env_sites.pop(name, None)


def reset() -> None:
    """Disarm every site and zero every counter (test teardown)."""
    global _env_cache
    with _lock:
        _armed.clear()
        _env_sites.clear()
        _env_cache = None


def is_armed(name: str) -> bool:
    _lookup(name)
    _sync_env()
    return name in _armed


def hits(name: str) -> int:
    """Hits seen at an armed site since arming (0 when disarmed)."""
    _lookup(name)
    _sync_env()
    with _lock:
        a = _armed.get(name)
        return a.count if a else 0


def active() -> dict[str, str]:
    """{site: spec} of every armed site (observability / /3/Cloud debug)."""
    _sync_env()
    with _lock:
        return {k: v.spec for k, v in _armed.items()}


def hit(name: str) -> None:
    """The instrumented-site call. No-op (one env read + two dict lookups)
    unless the site is armed; armed sites count the hit, then inject per
    spec. Undeclared names raise KeyError whether or not anything is armed
    — same contract as the knobs accessors."""
    if name not in FAILPOINTS:
        _lookup(name)
    _sync_env()
    if not _armed:
        return
    with _lock:
        armed = _armed.get(name)
        if armed is None:
            return
        armed.count += 1
        n = armed.count
        fire = armed.should_fire(n)
    if not fire:
        return
    from . import telemetry, timeline

    telemetry.inc("failpoint.fired.count")
    timeline.record("failpoint", name, hit=n, action=armed.action,
                    spec=armed.spec)
    if armed.action == "sleep":
        time.sleep(int(armed.arg) / 1000.0)
        return
    if armed.action == "http":
        raise InjectedHTTPError(name, n, int(armed.arg))
    kind = armed.arg or "fault"
    if kind == "conn":
        raise ConnectionResetError(
            f"injected connection reset at failpoint '{name}' (hit {n})")
    raise _KINDS[kind](name, n)
