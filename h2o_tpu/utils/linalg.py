"""Linear-algebra utilities — `hex/util/LinearAlgebraUtils.java` analog.

`to_eigen_vec` is the ToEigenVec transform behind the reference's
``categorical_encoding="Eigen"`` (`hex/util/LinearAlgebraUtils.toEigen`,
used by Aggregator and tree algos): replace a categorical column with the
per-level loading of the dominant eigenvector of the centered one-hot
covariance — one numeric column instead of a k-wide one-hot block.

For a one-hot indicator with level frequencies p, the centered covariance is
``C = diag(p) − p pᵀ`` (k×k, tiny) — eigen-decomposed on host; no n-sized
work beyond one counts pass."""

from __future__ import annotations

import numpy as np

from ..frame.frame import Frame
from ..frame.vec import T_NUM, Vec


def to_eigen_vec(v: Vec) -> Vec:
    """Categorical Vec -> numeric Vec of per-level eigen loadings."""
    if not v.is_categorical():
        return v
    host = v.to_numpy()
    k = len(v.domain)
    ok = ~np.isnan(host)
    counts = np.bincount(host[ok].astype(np.int64), minlength=k).astype(np.float64)
    n = max(counts.sum(), 1.0)
    p = counts / n
    C = np.diag(p) - np.outer(p, p)
    vals, vecs = np.linalg.eigh(C)
    v1 = vecs[:, -1]  # dominant eigenvector
    if v1[np.argmax(np.abs(v1))] < 0:  # deterministic sign
        v1 = -v1
    out = np.full(host.shape, np.nan, dtype=np.float32)
    out[ok] = v1[host[ok].astype(np.int64)]
    return Vec.from_numpy(out, type=T_NUM)


def _eigen_loadings(v: Vec) -> np.ndarray:
    host = v.to_numpy()
    k = len(v.domain)
    ok = ~np.isnan(host)
    counts = np.bincount(host[ok].astype(np.int64), minlength=k).astype(np.float64)
    n = max(counts.sum(), 1.0)
    p = counts / n
    C = np.diag(p) - np.outer(p, p)
    _, vecs = np.linalg.eigh(C)
    v1 = vecs[:, -1]
    if v1[np.argmax(np.abs(v1))] < 0:
        v1 = -v1
    return v1.astype(np.float32)


#: wire-name spellings accepted for each frozen scheme
_ENC_CANON = {
    "eigen": "Eigen", "onehotexplicit": "OneHotExplicit",
    "one_hot_explicit": "OneHotExplicit", "binary": "Binary",
    "labelencoder": "LabelEncoder", "label_encoder": "LabelEncoder",
    "enumlimited": "EnumLimited", "enum_limited": "EnumLimited",
    "sortbyresponse": "SortByResponse", "sort_by_response": "SortByResponse",
}


def build_encoding_state(fr: Frame, encoding: str,
                         skip: list[str] | None = None,
                         response: str | None = None,
                         weights: str | None = None,
                         max_levels: int = 10) -> dict | None:
    """Freeze a categorical_encoding transform on the training frame so the
    IDENTICAL mapping replays at score time (levels matched by name, unseen
    levels → NA). Returns None for AUTO/Enum/OneHotInternal (builders one-hot
    internally via DataInfo).

    Schemes (`hex/Model.Parameters.CategoricalEncodingScheme` +
    `water/util/FrameUtils.java` encoder drivers):
    - Eigen: per-level dominant-eigenvector loading (ToEigenVec).
    - OneHotExplicit: k indicator columns ``name.level``.
    - Binary: ⌈log2⌉ bit columns ``name:k`` of (code+1); NA → all zeros
      (`FrameUtils.CategoricalBinaryEncoder`, val = isNA ? 0 : 1+code).
    - LabelEncoder: the integer level code as one numeric column.
    - EnumLimited: columns with card > max_levels keep their ``max_levels``
      most frequent levels, the rest collapse into a trailing ``other``
      level; column renamed ``name.top_N_levels``
      (`FrameUtils.CategoricalEnumLimitedEncoder` + `CreateInteractions`).
    - SortByResponse: levels reordered by weighted mean response ascending,
      column stays categorical with the permuted domain
      (`hex/ModelBuilder.java:1650` MeanResponsePerLevelTask + ReorderTask).
    """
    skip = set(skip or [])
    enc = _ENC_CANON.get((encoding or "AUTO").lower())
    if enc is None:
        return None
    if enc == "SortByResponse" and (response is None
                                    or response not in fr.names):
        return None  # unsupervised: nothing to sort by (reference gates the
        #              scheme on needsResponse() && isSupervised())
    cols = {}
    for name in fr.names:
        v = fr.vec(name)
        if not v.is_categorical() or name in skip:
            continue
        card = len(v.domain)
        if enc == "EnumLimited":
            if card <= max_levels:
                continue  # reference leaves small columns untouched
            host = v.to_numpy()
            ok = ~np.isnan(host)
            counts = np.bincount(host[ok].astype(np.int64), minlength=card)
            top = np.sort(np.argsort(-counts, kind="stable")[:max_levels])
            cols[name] = {"domain": list(v.domain),
                          "keep": [v.domain[i] for i in top],
                          "max_levels": int(max_levels)}
            continue
        cols[name] = {"domain": list(v.domain)}
        if enc == "Eigen":
            cols[name]["loadings"] = _eigen_loadings(v)
        elif enc == "Binary":
            # 1 + floor(log2(card-1+1)): enough bits for val = 1+max_code
            cols[name]["nbits"] = 1 + int(np.floor(np.log2(max(card, 1))))
        elif enc == "SortByResponse":
            host = v.to_numpy()
            y = fr.vec(response).to_numpy().astype(np.float64)
            w = (fr.vec(weights).to_numpy().astype(np.float64)
                 if weights and weights in fr.names
                 else np.ones_like(y))
            ok = ~(np.isnan(host) | np.isnan(y) | np.isnan(w))
            c = host[ok].astype(np.int64)
            wsum = np.bincount(c, weights=w[ok], minlength=card)
            ysum = np.bincount(c, weights=(w * y)[ok], minlength=card)
            mean = np.where(wsum > 0, ysum / np.maximum(wsum, 1e-300),
                            np.inf)  # empty levels sort last
            order = np.argsort(mean, kind="stable")
            cols[name] = {"domain": [v.domain[i] for i in order]}
    if not cols:
        return None
    return {"encoding": enc, "columns": cols}


def apply_encoding_state(fr: Frame, state: dict) -> Frame:
    """Replay a frozen encoding on any frame (train or score time)."""
    from ..frame.vec import T_CAT

    enc = state["encoding"]
    names, vecs = [], []
    for name in fr.names:
        v = fr.vec(name)
        spec = state["columns"].get(name)
        if spec is None or not v.is_categorical():
            names.append(name)
            vecs.append(v)
            continue
        host = v.to_numpy()
        if enc == "EnumLimited":
            # frozen top-k + catch-all: kept levels keep their rank order,
            # every other TRAINING-DOMAIN level (and any unseen level) maps
            # to the trailing "other" code
            keep = spec["keep"]
            new_dom = list(keep) + ["other"]
            lut = {lvl: i for i, lvl in enumerate(keep)}
            other = len(keep)
            out = np.full(host.shape, np.nan, dtype=np.float32)
            ok = ~np.isnan(host)
            out[ok] = [lut.get((v.domain or [])[int(c)], other)
                       for c in host[ok]]
            names.append(f"{name}.top_{spec['max_levels']}_levels")
            vecs.append(Vec.from_numpy(out, type=T_CAT, domain=new_dom))
            continue
        # remap this frame's codes onto the TRAINING domain by level name
        lut = {lvl: i for i, lvl in enumerate(spec["domain"])}
        codes = np.full(host.shape, np.nan, dtype=np.float32)
        ok = ~np.isnan(host)
        codes[ok] = [lut.get((v.domain or [])[int(c)], np.nan)
                     for c in host[ok]]
        if enc == "Eigen":
            load = np.asarray(spec["loadings"])
            out = np.full(host.shape, np.nan, dtype=np.float32)
            okc = ~np.isnan(codes)
            out[okc] = load[codes[okc].astype(np.int64)]
            names.append(name)
            vecs.append(Vec.from_numpy(out, type=T_NUM))
        elif enc == "LabelEncoder":
            names.append(name)
            vecs.append(Vec.from_numpy(codes, type=T_NUM))
        elif enc == "SortByResponse":
            names.append(name)
            vecs.append(Vec.from_numpy(codes, type=T_CAT,
                                       domain=list(spec["domain"])))
        elif enc == "Binary":
            # val = NA ? 0 : 1+code, little-endian bits across name:k
            # (`FrameUtils.CategoricalBinaryEncoder.BinaryConverter`)
            val = np.where(np.isnan(codes), 0,
                           codes + 1).astype(np.int64)
            for k in range(int(spec["nbits"])):
                names.append(f"{name}:{k}")
                vecs.append(Vec.from_numpy(
                    ((val >> k) & 1).astype(np.float32)))
        else:  # OneHotExplicit
            for j, lvl in enumerate(spec["domain"]):
                col = np.where(np.isnan(codes), np.nan,
                               (codes == j).astype(np.float32))
                names.append(f"{name}.{lvl}")
                vecs.append(Vec.from_numpy(col.astype(np.float32)))
    return Frame(names, vecs)


def apply_categorical_encoding(fr: Frame, encoding: str,
                               skip: list[str] | None = None) -> Frame:
    """One-shot frame transform (state built and applied on the same frame)."""
    state = build_encoding_state(fr, encoding, skip)
    return fr if state is None else apply_encoding_state(fr, state)
