"""Linear-algebra utilities — `hex/util/LinearAlgebraUtils.java` analog.

`to_eigen_vec` is the ToEigenVec transform behind the reference's
``categorical_encoding="Eigen"`` (`hex/util/LinearAlgebraUtils.toEigen`,
used by Aggregator and tree algos): replace a categorical column with the
per-level loading of the dominant eigenvector of the centered one-hot
covariance — one numeric column instead of a k-wide one-hot block.

For a one-hot indicator with level frequencies p, the centered covariance is
``C = diag(p) − p pᵀ`` (k×k, tiny) — eigen-decomposed on host; no n-sized
work beyond one counts pass."""

from __future__ import annotations

import numpy as np

from ..frame.frame import Frame
from ..frame.vec import T_NUM, Vec


def to_eigen_vec(v: Vec) -> Vec:
    """Categorical Vec -> numeric Vec of per-level eigen loadings."""
    if not v.is_categorical():
        return v
    host = v.to_numpy()
    k = len(v.domain)
    ok = ~np.isnan(host)
    counts = np.bincount(host[ok].astype(np.int64), minlength=k).astype(np.float64)
    n = max(counts.sum(), 1.0)
    p = counts / n
    C = np.diag(p) - np.outer(p, p)
    vals, vecs = np.linalg.eigh(C)
    v1 = vecs[:, -1]  # dominant eigenvector
    if v1[np.argmax(np.abs(v1))] < 0:  # deterministic sign
        v1 = -v1
    out = np.full(host.shape, np.nan, dtype=np.float32)
    out[ok] = v1[host[ok].astype(np.int64)]
    return Vec.from_numpy(out, type=T_NUM)


def _eigen_loadings(v: Vec) -> np.ndarray:
    host = v.to_numpy()
    k = len(v.domain)
    ok = ~np.isnan(host)
    counts = np.bincount(host[ok].astype(np.int64), minlength=k).astype(np.float64)
    n = max(counts.sum(), 1.0)
    p = counts / n
    C = np.diag(p) - np.outer(p, p)
    _, vecs = np.linalg.eigh(C)
    v1 = vecs[:, -1]
    if v1[np.argmax(np.abs(v1))] < 0:
        v1 = -v1
    return v1.astype(np.float32)


def build_encoding_state(fr: Frame, encoding: str,
                         skip: list[str] | None = None) -> dict | None:
    """Freeze a categorical_encoding transform on the training frame so the
    IDENTICAL mapping replays at score time (levels matched by name, unseen
    levels → NA). Returns None for AUTO/Enum (builders one-hot internally via
    DataInfo)."""
    skip = set(skip or [])
    enc = (encoding or "AUTO").lower()
    if enc not in ("eigen", "onehotexplicit", "one_hot_explicit"):
        return None
    cols = {}
    for name in fr.names:
        v = fr.vec(name)
        if v.is_categorical() and name not in skip:
            cols[name] = {"domain": list(v.domain)}
            if enc == "eigen":
                cols[name]["loadings"] = _eigen_loadings(v)
    if not cols:
        return None
    return {"encoding": "Eigen" if enc == "eigen" else "OneHotExplicit",
            "columns": cols}


def apply_encoding_state(fr: Frame, state: dict) -> Frame:
    """Replay a frozen encoding on any frame (train or score time)."""
    enc = state["encoding"]
    names, vecs = [], []
    for name in fr.names:
        v = fr.vec(name)
        spec = state["columns"].get(name)
        if spec is None or not v.is_categorical():
            names.append(name)
            vecs.append(v)
            continue
        host = v.to_numpy()
        # remap this frame's codes onto the TRAINING domain by level name
        lut = {lvl: i for i, lvl in enumerate(spec["domain"])}
        codes = np.full(host.shape, np.nan, dtype=np.float32)
        ok = ~np.isnan(host)
        codes[ok] = [lut.get((v.domain or [])[int(c)], np.nan)
                     for c in host[ok]]
        if enc == "Eigen":
            load = np.asarray(spec["loadings"])
            out = np.full(host.shape, np.nan, dtype=np.float32)
            okc = ~np.isnan(codes)
            out[okc] = load[codes[okc].astype(np.int64)]
            names.append(name)
            vecs.append(Vec.from_numpy(out, type=T_NUM))
        else:  # OneHotExplicit
            for j, lvl in enumerate(spec["domain"]):
                col = np.where(np.isnan(codes), np.nan,
                               (codes == j).astype(np.float32))
                names.append(f"{name}.{lvl}")
                vecs.append(Vec.from_numpy(col.astype(np.float32)))
    return Frame(names, vecs)


def apply_categorical_encoding(fr: Frame, encoding: str,
                               skip: list[str] | None = None) -> Frame:
    """One-shot frame transform (state built and applied on the same frame)."""
    state = build_encoding_state(fr, encoding, skip)
    return fr if state is None else apply_encoding_state(fr, state)
