"""Device microbenchmarks — `water/init/{Linpack,MemoryBandwidth,NetworkBench}`
analogs, re-targeted at what actually bounds a TPU node: MXU matmul throughput
(Linpack), HBM streaming bandwidth (MemoryBandwidth), and all-reduce bandwidth
over the mesh axis (NetworkBench rode the node-to-node sockets; here the
collective rides ICI — SURVEY.md §2.5 mapping). Served at `/3/NetworkTest`
like the reference's `water/api/NetworkTestHandler`.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def _timeit(fn, warmup: int = 1, iters: int = 3) -> float:
    for _ in range(warmup):
        jax.block_until_ready(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def linpack_gflops(n: int = 2048) -> float:
    """MXU matmul throughput in GFLOP/s (`water/init/Linpack.java` solved an
    LU system; on TPU the representative FLOP engine is the matmul)."""
    a = jnp.ones((n, n), jnp.bfloat16)
    b = jnp.ones((n, n), jnp.bfloat16)
    mm = jax.jit(lambda x, y: (x @ y).astype(jnp.float32).sum())
    sec = _timeit(lambda: mm(a, b))
    return 2.0 * n * n * n / sec / 1e9


def memory_bandwidth_gbs(mb: int = 256) -> float:
    """HBM streaming bandwidth in GB/s (`water/init/MemoryBandwidth.java`):
    one read + one write stream via an on-device copy-scale kernel."""
    n = mb * 1024 * 1024 // 4
    x = jnp.ones((n,), jnp.float32)
    k = jax.jit(lambda v: v * 1.0000001)
    sec = _timeit(lambda: k(x))
    return 2.0 * n * 4 / sec / 1e9


def collective_bandwidth_gbs(mb: int = 64) -> dict:
    """All-reduce bandwidth across the device mesh (`water/init/NetworkBench`
    measured point-to-point sockets; the TPU data plane is the psum over
    ICI). Returns bytes/s per message size; single-device meshes report the
    degenerate (no-transfer) case."""
    from ..parallel import mesh as meshmod
    from jax.sharding import PartitionSpec as P

    mesh = meshmod.default_mesh()
    ndev = int(np.prod(list(mesh.shape.values())))
    axis = list(mesh.shape.keys())[0]
    n = mb * 1024 * 1024 // 4

    def allreduce(x):
        return jax.lax.psum(x, axis)

    fn = jax.jit(
        meshmod.shard_map(allreduce, mesh=mesh, in_specs=P(axis),
                          out_specs=P()))
    x = jnp.ones((max(n // max(ndev, 1), 1) * ndev,), jnp.float32)
    sec = _timeit(lambda: fn(x))
    # ring all-reduce moves 2(k-1)/k of the payload per device
    payload = x.nbytes * (2 * (ndev - 1) / max(ndev, 1) if ndev > 1 else 1.0)
    return {"devices": ndev, "message_mb": mb,
            "gbytes_per_sec": payload / sec / 1e9,
            "microseconds": sec * 1e6}


def network_test() -> dict:
    """`/3/NetworkTest` payload: the three microbenchmarks in one sweep."""
    return {
        "linpack_gflops": linpack_gflops(1024),
        "memory_bandwidth_gbs": memory_bandwidth_gbs(64),
        "collective": collective_bandwidth_gbs(16),
    }
