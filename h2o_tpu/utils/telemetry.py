"""Unified telemetry — process-global metrics registry + span tracing.

The reference treats observability as a first-class surface: `water/
TimeLine.java`'s always-on event ring, `WaterMeter` CPU/I-O counters and
MRTask's per-phase `.profile()`. This module is the one registry those
analogs report into, with the `utils/knobs.py` discipline applied to
metrics: every metric is DECLARED here with a kind and a one-line doc,
accessors raise ``KeyError`` on undeclared names, and graftlint's
``unregistered-metric`` rule fails the build on any literal metric-name
emit missing from this registry (AST-parsed — the linter never imports
jax).

Three metric kinds:

- **counter** — monotone total (``inc``). The fast path is lock-free:
  each thread accumulates into its own shard of a per-metric dict (a
  thread only ever writes its own key, so there is no cross-thread
  read-modify-write to lose), and readers sum an atomic ``dict()`` copy.
- **gauge** — last-set value (``set_gauge``), optionally tracking the
  process-lifetime peak (the HBM watermark).
- **histogram** — bounded ring of observations (``observe``) plus
  sharded count/sum totals; snapshots report p50/p95/p99/max over the
  ring window (``H2O_TPU_METRICS_HIST_WINDOW``) and exact count/sum.

Span tracing (``span("mrtask.dispatch", ...)``): context managers that
nest, carry one trace id through a job (contextvars — a REST request's
span and the training spans under it share the id), time themselves and
optional sub-``phase``s, and land in the timeline ring (`/3/Timeline`) as
typed ``span`` events. When ``H2O_TPU_TRACE_DIR`` is set every span is
ALSO appended to a per-process chrome-tracing file
(``trace_<pid>.trace.json``) loadable in Perfetto / chrome://tracing, so
a whole training run can be opened in a trace viewer.

Causality does not stop at process or thread boundaries:

- **wire propagation** — trace ids are 32-hex (W3C trace-context shaped);
  :func:`current_traceparent` renders the innermost open span as a
  ``traceparent`` header value (the client wire attaches it), and
  :func:`remote_context` adopts an incoming header so the server's
  request span nests under the REMOTE parent with the same trace id —
  one Perfetto session shows client→REST→job→train-chunk under one id.
- **thread propagation** — contextvars do not cross ``threading.Thread``
  or executor submits, so a worker thread's spans silently orphan into
  fresh trace ids. :func:`carry_context` wraps a callable with the
  context captured AT WRAP TIME (the submitting thread's open span);
  every span-bearing module that spawns threads routes targets through
  it (graftlint rule ``thread-without-trace-context`` pins that).
- **span sinks** — a root span opened with ``sink=`` collects its whole
  finished subtree (bounded, closed at root exit) — the raw material of
  the tail-based slow-request capture (`utils/slowtrace.py`).

Recording is always-on (the reference's ring never turns off) and cheap:
a disabled registry (``H2O_TPU_METRICS_ENABLED=0``) still validates names
but skips the writes. Span durations measure HOST wall between enter and
exit — jax dispatch is async, so a span around an un-synced device call
measures dispatch, not compute (the drained-compute bench contract is
unaffected: `model_base.train` blocks before its timer reads).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import itertools
import json
import os
import threading
import time
from collections import deque

from . import knobs, timeline

_MISSING = object()


# ---------------------------------------------------------------------------
# registry (the knobs.py discipline, applied to metrics)
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Metric:
    name: str
    kind: str           # "counter" | "gauge" | "histogram"
    doc: str


METRICS: dict[str, Metric] = {}


class _Counter:
    __slots__ = ("shards",)

    def __init__(self):
        #: thread-id -> that thread's accumulated total. A thread only
        #: writes ITS OWN key, so `d[tid] = d.get(tid, 0) + n` races with
        #: nobody; `dict(d)` (one C-level call) gives readers an atomic
        #: copy to sum.
        self.shards: dict[int, float] = {}

    def value(self) -> float:
        return sum(dict(self.shards).values())


class _Gauge:
    __slots__ = ("value", "peak", "track_peak")

    def __init__(self, track_peak: bool = False):
        self.value = 0.0
        self.peak = 0.0
        self.track_peak = track_peak


class _Hist:
    __slots__ = ("ring", "count", "sum")

    def __init__(self, window: int):
        self.ring: deque = deque(maxlen=window)  # deque append is atomic
        self.count = _Counter()
        self.sum = _Counter()


_COUNTERS: dict[str, _Counter] = {}
_GAUGES: dict[str, _Gauge] = {}
_HISTS: dict[str, _Hist] = {}


def _hist_window() -> int:
    return max(knobs.get_int("H2O_TPU_METRICS_HIST_WINDOW"), 16)


def _counter(name: str, doc: str) -> None:
    METRICS[name] = Metric(name, "counter", doc)
    _COUNTERS[name] = _Counter()


def _gauge(name: str, doc: str, track_peak: bool = False) -> None:
    METRICS[name] = Metric(name, "gauge", doc)
    _GAUGES[name] = _Gauge(track_peak=track_peak)


def _histogram(name: str, doc: str) -> None:
    METRICS[name] = Metric(name, "histogram", doc)
    _HISTS[name] = _Hist(_hist_window())


# -- MRTask driver (parallel/mrtask.py — DrJAX-style per-stage accounting) --
_counter("mrtask.dispatch.count",
         "mr_reduce/mr_map driver dispatches")
_counter("mrtask.program.build.count",
         "driver-program cache misses (a fresh shard_map trace + compile)")
_counter("mrtask.payload.in.bytes",
         "bytes of input operands handed to MRTask dispatches")
_counter("mrtask.payload.out.bytes",
         "bytes of result leaves returned by MRTask dispatches")
_histogram("mrtask.dispatch.seconds",
           "host wall per driver dispatch (build + async dispatch; device "
           "compute drains at the caller's sync point)")

# -- training loops ----------------------------------------------------------
_counter("train.count", "completed training jobs")
_histogram("train.seconds",
           "drained train wall per job (the run_time_ms source — "
           "block_until_ready runs before the clock is read)")
_counter("train.chunk.count", "GBM/DRF boosting-chunk iterations")
_histogram("train.chunk.seconds",
           "wall per boosting chunk (train_fn dispatch + scoring + "
           "history, the score_tree_interval boundary)")
_counter("train.epoch.count", "DeepLearning epochs completed")
_histogram("train.epoch.seconds",
           "wall between DL epoch boundaries (async dispatch wall)")
_counter("train.checkpoint.count",
         "auto-recovery checkpoint writes (backend/persist.py)")
_histogram("train.checkpoint.seconds",
           "wall per auto-recovery checkpoint write (the preemption "
           "insurance premium, measured)")
_histogram("train.hist.kernel",
           "drained wall of one sampled level-histogram accumulation "
           "(backend/kernels/hist.py), observed once per GBM/DRF training "
           "job from the in-boundary phase sample; the backend "
           "(pallas/xla) rides the train.gbm.phases span/timeline detail")
_gauge("gbm.pipeline.overlap_ratio",
       "fraction of the h2d + collective wall the pipelined GBM level "
       "program hides under local accumulation, from the once-per-process "
       "train.gbm.pipeline stage sample (engine.sample_pipeline_phases); "
       "~0 on a single-shard CPU mesh where both hidden stages are "
       "already negligible")
_histogram("train.compile.seconds",
           "drained wall of the AOT lower+compile of the tree train step "
           "at build setup (near-zero when the persistent compile cache "
           "replays it — the cold-start meter)")

# -- HBM Cleaner (backend/memory.py) -----------------------------------------
_gauge("cleaner.hbm.live.bytes",
       "device-resident bytes the Cleaner ledger currently tracks",
       track_peak=True)
_gauge("cleaner.hbm.limit.bytes",
       "resolved Cleaner HBM budget (0 while unlimited/unresolved)")
_counter("cleaner.spill.count", "Vec device buffers spilled to ice")
_counter("cleaner.spill.bytes", "bytes spilled to ice")
_counter("cleaner.rehydrate.count", "spilled Vecs reloaded to device")
_counter("cleaner.rehydrate.bytes", "bytes reloaded from ice")
_counter("cleaner.emergency_sweep.count",
         "spill-everything sweeps triggered by device OOM")

# -- parser ------------------------------------------------------------------
_counter("parser.parse.count", "frames parsed (io/parser.py parse_file)")
_counter("parser.rows.count", "rows ingested by the parser")
_histogram("parser.parse.seconds", "wall per parse_file call")

# -- fault tolerance ---------------------------------------------------------
_counter("failpoint.fired.count",
         "armed failpoint injections that actually fired")
_counter("retry.attempt.count",
         "retries scheduled by utils/retry.py (transient failures seen)")

# -- serving (h2o_tpu/serving/ — the global face of per-model stats.py) ------
_counter("serving.request.count", "scoring requests across all models")
_counter("serving.request.rows", "rows scored across all models")
_histogram("serving.request.seconds",
           "end-to-end request latency (encode + queue + score)")
_counter("serving.batch.count", "micro-batcher device calls")
_counter("serving.batch.rows", "rows through micro-batched device calls")
_counter("serving.rejected.count", "requests rejected by backpressure (429)")
_counter("serving.timeout.count", "requests expired while queued (408)")
_counter("serving.recompile.count",
         "steady-state scorer bucket-miss recompiles (contract: 0)")

# -- serving control plane (serving/control.py + router.py) ------------------
_counter("serving.admission.rejected.count",
         "registrations/re-placements refused by the fleet HBM quota "
         "(REST: 429 + Retry-After)")
_counter("serving.placement.evicted.count",
         "cold placements evicted under quota pressure (lazily re-placed "
         "on first hit)")
_counter("serving.replica.dead.count",
         "replica scorers marked dead after a score-path fault")
_counter("serving.replica.reroute.count",
         "requests re-dispatched around a dying replica (contract: no "
         "request fails because its replica died under it)")
_counter("serving.route.count", "requests scored through a routed endpoint")
_counter("serving.route.shadow.rows",
         "rows shadow-scored off the response path")
_counter("serving.route.shadow.dropped.count",
         "shadow jobs dropped because the shadow queue was full (the "
         "response path never blocks on shadow work)")
_histogram("serving.route.divergence",
           "per-row |prediction delta| between the serving variant and a "
           "shadow variant over identical rows (canary drift monitor)")

# -- REST control plane ------------------------------------------------------
_counter("rest.request.count", "REST requests routed")
_counter("rest.error.count", "REST requests answered with a 5xx")
_histogram("rest.request.seconds", "wall per routed REST request")

# -- concurrency sanitizer (utils/sanitizer.py) ------------------------------
_counter("sanitizer.violation.count",
         "lock-order inversions observed + @guarded_by assertion "
         "failures raised by the runtime sanitizer (contract: 0 — every "
         "count is a typed error somewhere)")

# -- XLA ---------------------------------------------------------------------
_counter("xla.compile.count",
         "XLA backend compiles observed since utils/compilemeter.py "
         "installed its jax.monitoring listener")

# -- fleet observability plane (utils/programs.py / fleetobs.py / -----------
# -- flightrec.py + the profiler capture surface) ----------------------------
_counter("programs.registered.count",
         "compiled executables registered in the program cost registry "
         "(utils/programs.py — one per (program, signature))")
_counter("profiler.capture.count",
         "jax.profiler device-trace captures completed (span-scoped "
         "H2O_TPU_PROFILE_DIR sessions + POST /3/Profiler/capture)")
_counter("fleet.scrape.count",
         "peer-process metric scrapes attempted by the fleet collector "
         "(utils/fleetobs.py; failures count too — they carry an error "
         "in the merged view)")
_histogram("fleet.scrape.seconds",
           "wall per full fleet collection (every peer + spool read + "
           "merge) behind GET /3/Metrics?fleet=1")
_counter("flight.dump.count",
         "flight-recorder diagnostic bundles written to "
         "H2O_TPU_FLIGHT_DIR (utils/flightrec.py; contract: every count "
         "is a terminal event somewhere)")

# -- causal observability plane (utils/slo.py / watchdog.py / ---------------
# -- slowtrace.py / health.py) ----------------------------------------------
_counter("watchdog.trip.count",
         "watchdog detector trips (utils/watchdog.py — hung job, stalled "
         "MRTask dispatch, Cleaner thrash, serving queue stall; each trip "
         "also lands a typed timeline event + a proactive flight bundle)")
_gauge("watchdog.hung_jobs",
       "running jobs with no progress heartbeat within "
       "H2O_TPU_WATCHDOG_JOB_BUDGET_MS, as of the last watchdog sweep")
_gauge("watchdog.stalled_dispatch",
       "MRTask driver dispatches in flight longer than "
       "H2O_TPU_WATCHDOG_DISPATCH_BUDGET_MS, as of the last sweep")
_gauge("watchdog.cleaner_thrash",
       "1 while the Cleaner spilled AND rehydrated more than "
       "H2O_TPU_WATCHDOG_THRASH_OPS times within one watchdog interval "
       "(spill/reload churn — the memory death spiral), else 0")
_gauge("watchdog.queue_stall",
       "serving batchers whose oldest queued request has waited past "
       "H2O_TPU_WATCHDOG_QUEUE_BUDGET_MS, as of the last sweep")
_gauge("slo.worst_burn",
       "max burn rate across every declared SLO (utils/slo.py): 1.0 = "
       "exactly consuming the error budget, >1 = burning faster — the "
       "autoscaling/rollback loops' one-number health signal; refreshed "
       "by every GET /3/Metrics and /3/Health serve (slo.burn_snapshot), "
       "so both poll surfaces read a current value")
_counter("slowtrace.captured.count",
         "requests whose full span tree was persisted by the tail-based "
         "slow-request capture (utils/slowtrace.py — SLO p99 breachers "
         "only, behind GET /3/SlowTraces)")
_counter("health.poll.count",
         "GET /3/Health evaluations (excluded from the timeline ring "
         "like the PR 6 monitoring polls — a 1s readiness poller must "
         "not cycle the event ring)")

# -- workload manager (h2o_tpu/workload/ — tenants, lanes, preemption) -------
_counter("workload.submitted.count",
         "jobs submitted through the workload manager (all tenants; the "
         "per-tenant split rides the h2o_tpu_tenant_* Prometheus lines)")
_counter("workload.rejected.count",
         "submissions rejected by tenant quota admission (REST surfaces "
         "them as 429 + Retry-After)")
_counter("workload.dispatch.count",
         "queue entries handed a slot by the fair-share lottery "
         "(includes force-dispatches from the aging starvation bound)")
_counter("workload.preempt.count",
         "running jobs preempted at a chunk/epoch boundary (priority "
         "arrival, serving pressure, or the shed policy) — state force-"
         "checkpointed, HBM reservation released")
_counter("workload.resume.count",
         "parked (preempted) jobs re-admitted and resumed from their "
         "boundary checkpoint")
_counter("workload.shed.count",
         "shed-policy preemptions specifically (SLO burn / typed health "
         "degradation picked the victim tenant)")
_counter("workload.requeue.count",
         "managed jobs requeued by a watchdog hung-job/trip signal "
         "instead of paging (the PR 15 watchdog feeding the scheduler)")
_gauge("workload.running", "managed jobs currently holding a slot")
_gauge("workload.queue.depth", "managed jobs waiting for a slot")
_gauge("workload.parked",
       "preempted jobs parked host-side awaiting re-admission")
_histogram("workload.queue.wait.seconds",
           "queue wait per managed dispatch (submission or re-admission "
           "to slot grant) — backs the workload.wait SLO burn")


def _lookup(name: str) -> Metric:
    try:
        return METRICS[name]
    except KeyError:
        raise KeyError(
            f"unregistered metric {name!r} — declare it in "
            f"h2o_tpu/utils/telemetry.py (graftlint rule "
            f"unregistered-metric enforces the same statically)") from None


def _enabled() -> bool:
    return knobs.get_bool("H2O_TPU_METRICS_ENABLED")


def enabled() -> bool:
    """Public master-switch read — gates optional instrumentation work
    whose COST exists even when the emits are skipped (e.g. the GBM
    sampled phase profile dispatches real device work)."""
    return _enabled()


# ---------------------------------------------------------------------------
# emit accessors — the lint-checked surface
# ---------------------------------------------------------------------------
def inc(name: str, n: float = 1) -> None:
    """Add ``n`` to a declared counter (lock-free per-thread shard)."""
    c = _COUNTERS.get(name)
    if c is None:
        _lookup(name)
        raise KeyError(f"metric {name!r} is a {METRICS[name].kind}, not a "
                       f"counter — use the matching accessor")
    if not _enabled():
        return
    tid = threading.get_ident()
    c.shards[tid] = c.shards.get(tid, 0) + n


def set_gauge(name: str, value: float) -> None:
    g = _GAUGES.get(name)
    if g is None:
        _lookup(name)
        raise KeyError(f"metric {name!r} is a {METRICS[name].kind}, not a "
                       f"gauge — use the matching accessor")
    if not _enabled():
        return
    g.value = value
    if g.track_peak and value > g.peak:
        g.peak = value


def observe(name: str, value: float) -> None:
    h = _HISTS.get(name)
    if h is None:
        _lookup(name)
        raise KeyError(f"metric {name!r} is a {METRICS[name].kind}, not a "
                       f"histogram — use the matching accessor")
    if not _enabled():
        return
    h.ring.append(value)
    tid = threading.get_ident()
    h.count.shards[tid] = h.count.shards.get(tid, 0) + 1
    h.sum.shards[tid] = h.sum.shards.get(tid, 0) + value


def value(name: str) -> float:
    """Current counter total or gauge value (histograms: use snapshot)."""
    m = _lookup(name)
    if m.kind == "counter":
        return _COUNTERS[name].value()
    if m.kind == "gauge":
        return _GAUGES[name].value
    return _HISTS[name].count.value()


def hist_values(name: str) -> list:
    """The recent-window ring of a declared histogram, oldest first — the
    raw observations behind the snapshot percentiles. `utils/slo.py`
    computes rolling latency-breach fractions off these SAME rings instead
    of keeping a second latency window."""
    m = _lookup(name)
    if m.kind != "histogram":
        raise KeyError(f"metric {name!r} is a {m.kind}, not a histogram")
    return list(_HISTS[name].ring)


# ---------------------------------------------------------------------------
# snapshots — the /3/Metrics payload and the bench sidecar delta
# ---------------------------------------------------------------------------
def _percentiles(vals: list) -> dict:
    if not vals:
        return {"p50": None, "p95": None, "p99": None, "max": None}
    s = sorted(vals)
    n = len(s)

    def pct(q):
        return s[min(int(q * (n - 1) + 0.5), n - 1)]

    return {"p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99),
            "max": s[-1]}


def snapshot() -> dict:
    """Full typed registry state: {name: {kind, value|...}}. Installs the
    compile-count listener opportunistically (jax may not be up yet in a
    bare control-plane process — then compiles simply read 0)."""
    try:
        from . import compilemeter

        compilemeter.install()
    except Exception:  # pragma: no cover - no jax in a stripped process
        pass
    out: dict[str, dict] = {}
    for name, m in METRICS.items():
        if m.kind == "counter":
            out[name] = {"kind": "counter", "value": _COUNTERS[name].value()}
        elif m.kind == "gauge":
            g = _GAUGES[name]
            rec = {"kind": "gauge", "value": g.value}
            if g.track_peak:
                rec["peak"] = g.peak
            out[name] = rec
        else:
            h = _HISTS[name]
            vals = list(h.ring)
            out[name] = {"kind": "histogram", "count": h.count.value(),
                         "sum": round(h.sum.value(), 6),
                         "window": len(vals), **{
                             k: (None if v is None else round(v, 6))
                             for k, v in _percentiles(vals).items()}}
    return out


def snapshot_delta(before: dict, after: dict | None = None) -> dict:
    """What happened between two snapshots, compact: counters report the
    delta (zero deltas dropped), gauges the after-value (+ peak when
    tracked), histograms the count/sum delta. This is the per-leg record
    bench.py embeds in its fsync'd JSONL sidecar."""
    after = snapshot() if after is None else after
    out: dict[str, dict] = {}
    for name, rec in after.items():
        prev = before.get(name, {})
        if rec["kind"] == "counter":
            d = rec["value"] - prev.get("value", 0)
            if d:
                out[name] = {"delta": d}
        elif rec["kind"] == "gauge":
            g = {"value": rec["value"]}
            if "peak" in rec:
                g["peak"] = rec["peak"]
            out[name] = g
        else:
            dc = rec["count"] - prev.get("count", 0)
            if dc:
                out[name] = {"count": dc,
                             "sum_s": round(rec["sum"]
                                            - prev.get("sum", 0.0), 6),
                             "p99": rec["p99"]}
    return out


#: extra exposition sources: callables returning pre-formatted Prometheus
#: text lines. The registry itself stays label-free (fleet totals); a
#: subsystem with a natural label dimension (serving's per-model stats
#: windows) registers a provider instead of a second metrics registry.
_PROM_PROVIDERS: list = []


def add_prometheus_provider(fn) -> None:
    """Register a ``() -> list[str]`` of exposition lines appended to
    :func:`prometheus` output. Idempotent per callable."""
    if fn not in _PROM_PROVIDERS:
        _PROM_PROVIDERS.append(fn)


def prom_label_escape(label) -> str:
    """Prometheus label-value escaping (backslash, quote, newline) — the
    ONE implementation every labelled provider shares: label values are
    externally chosen (client model ids, backend device names) and one bad
    value must not make the whole scrape unparseable."""
    return (str(label).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def prometheus() -> str:
    """Prometheus text exposition (format 0.0.4) of the whole registry —
    dots become underscores, everything is prefixed ``h2o_tpu_``."""
    lines = []
    for name, m in sorted(METRICS.items()):
        pname = "h2o_tpu_" + name.replace(".", "_").replace("-", "_")
        lines.append(f"# HELP {pname} {m.doc}")
        if m.kind == "counter":
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {_COUNTERS[name].value():g}")
        elif m.kind == "gauge":
            g = _GAUGES[name]
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {g.value:g}")
            if g.track_peak:
                lines.append(f"# HELP {pname}_peak process-lifetime peak "
                             f"of {pname}")
                lines.append(f"# TYPE {pname}_peak gauge")
                lines.append(f"{pname}_peak {g.peak:g}")
        else:
            h = _HISTS[name]
            vals = list(h.ring)
            pc = _percentiles(vals)
            lines.append(f"# TYPE {pname} summary")
            for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
                if pc[key] is not None:
                    lines.append(f'{pname}{{quantile="{q}"}} {pc[key]:g}')
            lines.append(f"{pname}_sum {h.sum.value():g}")
            lines.append(f"{pname}_count {h.count.value():g}")
    for provider in list(_PROM_PROVIDERS):
        try:
            lines.extend(provider())
        except Exception:  # pragma: no cover — a sick provider must not
            pass           # take down the whole scrape
    return "\n".join(lines) + "\n"


def describe() -> str:
    """Human-readable registry dump (the knobs.describe analog)."""
    out = []
    for m in sorted(METRICS.values(), key=lambda m: m.name):
        out.append(f"{m.name}  [{m.kind}]")
        out.append(f"    {m.doc}")
    return "\n".join(out)


def reset() -> None:
    """Zero every metric (test isolation — production never calls this)."""
    for c in _COUNTERS.values():
        c.shards.clear()
    for g in _GAUGES.values():
        g.value = 0.0
        g.peak = 0.0
    for name in _HISTS:
        _HISTS[name] = _Hist(_hist_window())


# ---------------------------------------------------------------------------
# span tracing
# ---------------------------------------------------------------------------
#: (trace_id, span_id, sink) of the innermost open span in this context.
#: trace_id is 32 lowercase hex (W3C trace-context shaped, wire-portable);
#: span_id is a process-local int for local spans or the 16-hex string of
#: a REMOTE parent adopted from a traceparent header; sink is the span
#: tree collector of the enclosing captured request (None outside one).
_CTX: contextvars.ContextVar = contextvars.ContextVar("h2o_tpu_trace",
                                                      default=None)
_IDS = itertools.count(1)


def _mint_trace_id() -> str:
    import uuid

    return uuid.uuid4().hex


class SpanSink:
    """Bounded collector of finished span records under one root span.

    Every span whose context inherits the sink appends its record on exit
    — including spans from worker threads that adopted the context via
    :func:`carry_context`. The root CLOSES the sink when it exits, so a
    long-lived descendant (a background training job rooted under a REST
    request) cannot grow a dead request's tree forever; ``cap`` bounds
    the live tree the same way the timeline ring is bounded."""

    __slots__ = ("items", "cap", "closed")

    def __init__(self, cap: int = 512):
        self.items: list[dict] = []
        self.cap = cap
        self.closed = False

    def add(self, rec: dict) -> None:
        # list.append is atomic under the GIL; a dropped record past the
        # cap/close loses detail, never correctness
        if not self.closed and len(self.items) < self.cap:
            self.items.append(rec)

    def close(self) -> list[dict]:
        self.closed = True
        return self.items


# -- cross-boundary propagation ---------------------------------------------
_TRACEPARENT_RE = None  # compiled lazily (re import stays top-level-free)


def _traceparent_parse(header):
    """(trace_id, parent_span) from a W3C-style ``traceparent`` header, or
    None when absent/malformed — a bad header must degrade to a fresh
    trace, never 400 the request."""
    global _TRACEPARENT_RE
    if not header or not isinstance(header, str):
        return None
    if _TRACEPARENT_RE is None:
        import re

        _TRACEPARENT_RE = re.compile(
            r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None or m.group(1) == "ff" or set(m.group(2)) == {"0"} \
            or set(m.group(3)) == {"0"}:
        return None
    return m.group(2), m.group(3)


def current_traceparent() -> str | None:
    """The innermost open span as a ``traceparent`` header value
    (``00-<trace32>-<span16>-01``), or None outside any span — what
    `api/client.py`'s ``_send`` attaches to every request so the server
    side can root its request span under the caller's."""
    cur = _CTX.get()
    if cur is None:
        return None
    trace, span_id = cur[0], cur[1]
    if isinstance(span_id, int):
        span_hex = f"{span_id & 0xFFFFFFFFFFFFFFFF:016x}"
    else:                       # re-forwarding an adopted remote parent
        span_hex = str(span_id)[-16:].rjust(16, "0")
    # legacy/foreign trace ids normalize to 32 hex by hashing — the header
    # must always parse on the far side
    if len(trace) != 32 or not all(c in "0123456789abcdef" for c in trace):
        import hashlib

        trace = hashlib.sha256(trace.encode()).hexdigest()[:32]
    return f"00-{trace}-{span_hex}-01"


@contextlib.contextmanager
def remote_context(traceparent: str | None):
    """Adopt an incoming ``traceparent`` for the duration of the block:
    spans opened inside reuse the REMOTE trace id and record the remote
    span as their parent — the server half of wire propagation. A
    missing/malformed header makes this a no-op (fresh local trace)."""
    parsed = _traceparent_parse(traceparent)
    if parsed is None:
        yield None
        return
    trace, parent = parsed
    token = _CTX.set((trace, parent, None))
    try:
        yield trace
    finally:
        _CTX.reset(token)


def carry_context(fn):
    """Bind ``fn`` to the CURRENT span context (captured at wrap time) so
    running it on another thread keeps the trace id and parent linkage —
    ``Thread(target=carry_context(run))`` / ``ex.submit(carry_context(f),
    x)``. Contextvars do not cross thread starts or executor submits;
    without this, worker-thread spans mint orphan trace ids (the
    shadow-scorer/MicroBatcher hole this helper closes — graftlint rule
    ``thread-without-trace-context`` enforces adoption)."""
    import functools

    captured = _CTX.get()

    @functools.wraps(fn)
    def _carried(*args, **kwargs):
        token = _CTX.set(captured)
        try:
            return fn(*args, **kwargs)
        finally:
            _CTX.reset(token)

    return _carried


class Span:
    __slots__ = ("name", "metric", "attrs", "trace_id", "span_id",
                 "parent_id", "phases", "t0_ns")

    def __init__(self, name, metric, attrs, trace_id, span_id, parent_id):
        self.name = name
        self.metric = metric
        self.attrs = attrs
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id          # int (local) | str (remote)
        self.phases: dict[str, float] = {}
        self.t0_ns = 0

    @contextlib.contextmanager
    def phase(self, phase_name: str):
        """Sub-phase accounting inside the span (MRProfile's setup/map/
        reduce split) — totals land on the span's timeline event."""
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            dt = (time.perf_counter_ns() - t0) / 1e9
            self.phases[phase_name] = self.phases.get(phase_name, 0.0) + dt


@contextlib.contextmanager
def span(name: str, metric: str | None = None, ring: bool = True,
         sink: SpanSink | None = None, **attrs):
    """Open a traced span: nests (contextvars), shares the enclosing trace
    id or mints a 32-hex one, records a typed ``span`` timeline event on
    exit (plus the chrome-trace line when ``H2O_TPU_TRACE_DIR`` is set),
    and observes ``metric`` (a declared histogram) with its duration.
    ``attrs`` are small JSON-able labels; keep them cheap — this runs on
    hot-path boundaries.

    ``ring=False`` keeps the span OUT of the timeline ring (trace file
    and sink still see it) — for per-request spans whose rate would cycle
    the 4096-event ring the way monitoring polls would. ``sink=`` makes
    this span a capture root: its whole finished subtree (across
    carry_context'd threads) accumulates into the sink, which closes at
    root exit; children inherit the enclosing sink automatically."""
    if metric is not None and metric not in _HISTS:
        _lookup(metric)  # typed KeyError for undeclared / non-histogram
        raise KeyError(f"span metric {metric!r} must be a histogram")
    parent = _CTX.get()
    span_id = next(_IDS)
    trace_id = parent[0] if parent else _mint_trace_id()
    root_sink = sink
    if sink is None and parent is not None and len(parent) > 2:
        sink = parent[2]
    sp = Span(name, metric, attrs, trace_id, span_id,
              parent[1] if parent else None)
    token = _CTX.set((trace_id, span_id, sink))
    # while a device-profiler session is live, mirror the span stack into
    # jax TraceAnnotations so XLA ops nest under the SAME names in
    # Perfetto (train.gbm.chunk wraps its device ops) — one global read
    # when no capture is running, nothing on the steady-state span path
    ann = None
    if _PROFILE_ACTIVE[0]:
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
        except Exception:  # pragma: no cover — profiler backend quirk
            ann = None
    sp.t0_ns = time.perf_counter_ns()
    try:
        yield sp
    finally:
        dur_ns = time.perf_counter_ns() - sp.t0_ns
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:  # pragma: no cover
                pass
        _CTX.reset(token)
        if _enabled():
            detail = dict(sp.attrs)
            detail["trace"] = sp.trace_id
            detail["span"] = sp.span_id
            if sp.parent_id is not None:
                detail["parent"] = sp.parent_id
            for k, v in sp.phases.items():
                detail[f"{k}_s"] = round(v, 6)
            if ring:
                timeline.record("span", name,
                                dur_us=dur_ns // 1000, **detail)
            if sp.metric is not None:
                observe(sp.metric, dur_ns / 1e9)
            if sink is not None:
                sink.add({"name": name, "dur_us": dur_ns // 1000,
                          "t0_us": sp.t0_ns // 1000,
                          "tid": threading.get_ident(), **detail})
            _trace_emit(sp, dur_ns)
        if root_sink is not None:
            items = root_sink.close()
            # a NESTED capture root (a serving.score request inside a
            # rest.request capture) must not sever the enclosing tree:
            # fold the finished subtree into the parent's sink so the
            # outer slow-trace still carries the inner latency detail
            outer = parent[2] if (parent is not None and len(parent) > 2) \
                else None
            if outer is not None and outer is not root_sink:
                for rec in items:
                    outer.add(rec)


def trace_id() -> str | None:
    """Trace id of the innermost open span (None outside any span)."""
    cur = _CTX.get()
    return cur[0] if cur else None


class Lap:
    """Boundary-to-boundary timer whose clock math lives HERE (one audited
    site) instead of inside a training loop: ``tick()`` observes the wall
    since the previous tick into a declared histogram + timeline event.
    First tick only starts the clock. Durations are async-dispatch wall
    unless the loop syncs — same caveat as spans."""

    __slots__ = ("metric", "what", "_t0")

    def __init__(self, metric: str | None = None, what: str | None = None):
        if metric is not None and metric not in _HISTS:
            _lookup(metric)
            raise KeyError(f"lap metric {metric!r} must be a histogram")
        self.metric = metric
        self.what = what
        self._t0: float | None = None

    def tick(self, **detail) -> float | None:
        now = time.perf_counter()
        dt = None
        if self._t0 is not None:
            dt = now - self._t0
            if _enabled():
                if self.metric is not None:
                    observe(self.metric, dt)
                if self.what is not None:
                    timeline.record("lap", self.what,
                                    dur_us=int(dt * 1e6), **detail)
        self._t0 = now
        return dt


def lap(metric: str | None = None, what: str | None = None) -> Lap:
    return Lap(metric=metric, what=what)


# ---------------------------------------------------------------------------
# chrome-tracing / Perfetto export
# ---------------------------------------------------------------------------
_TRACE_LOCK = threading.Lock()
_TRACE_FILE = None        # open handle once the dir knob resolves
_TRACE_DIR_SEEN = None    # knob value the handle was opened for


def trace_path() -> str | None:
    """Path of this process's chrome-trace file (None when export is off)."""
    d = knobs.get_str("H2O_TPU_TRACE_DIR")
    if not d:
        return None
    return os.path.join(d, f"trace_{os.getpid()}.trace.json")


def _trace_emit(sp: Span, dur_ns: int) -> None:
    global _TRACE_FILE, _TRACE_DIR_SEEN
    d = knobs.get_str("H2O_TPU_TRACE_DIR")
    if not d:
        return
    ev = {"name": sp.name, "ph": "X", "ts": sp.t0_ns // 1000,
          "dur": max(dur_ns // 1000, 1), "pid": os.getpid(),
          "tid": threading.get_ident(),
          "args": {**{k: v for k, v in sp.attrs.items()},
                   "trace": sp.trace_id,
                   **{f"{k}_s": round(v, 6) for k, v in sp.phases.items()}}}
    line = json.dumps(ev)
    with _TRACE_LOCK:
        if _TRACE_FILE is None or _TRACE_DIR_SEEN != d:
            os.makedirs(d, exist_ok=True)
            if _TRACE_FILE is not None:
                try:
                    _TRACE_FILE.close()
                except OSError:  # pragma: no cover
                    pass
            _TRACE_FILE = open(trace_path(), "a")
            _TRACE_DIR_SEEN = d
        # chrome's JSON Array Format: "[" then comma-separated events; the
        # closing "]" is explicitly optional, so an append-only stream
        # stays loadable after a crash (read_trace normalizes). ONE write
        # call per event, leader + record + newline fused, and the whole
        # emit serialized under _TRACE_LOCK: concurrent span exits from N
        # threads can never interleave partial JSON lines, and a reader
        # sees whole lines (plus at most one torn tail mid-flush, which
        # read_trace drops)
        ldr = "[\n" if _TRACE_FILE.tell() == 0 else ",\n"
        _TRACE_FILE.write(ldr + line)
        _TRACE_FILE.flush()


def read_trace(path: str) -> list[dict]:
    """Load a chrome-trace export back as a list of event dicts.

    Normalizes what the streaming writer legitimately leaves: the missing
    closing bracket, a trailing comma, and — when read while a writer is
    mid-flush or after a crash tore the tail — an incomplete final record,
    which is dropped rather than failing the whole load (the flight
    recorder and the fleet trace merge both read live files)."""
    with open(path) as f:
        text = f.read().rstrip().rstrip(",")
    if not text:
        return []
    try:
        return json.loads(text if text.endswith("]") else text + "\n]")
    except json.JSONDecodeError:
        pass
    # torn tail: every complete record is one ",\n"-led line — reparse
    # line-wise and drop whatever the crash/in-flight write left behind
    out = []
    for ln in text.lstrip("[").split(",\n"):
        ln = ln.strip().rstrip(",").rstrip("]").strip()
        if not ln:
            continue
        try:
            out.append(json.loads(ln))
        except json.JSONDecodeError:
            continue
    return out


# ---------------------------------------------------------------------------
# on-demand device profiling (jax.profiler, span-scoped)
# ---------------------------------------------------------------------------
# The ONLY sanctioned jax.profiler capture site (with fleetobs.py) —
# graftlint rule 19 `unscoped-profiler-capture` pins that: a start_trace
# grown elsewhere would skip the span annotations and could leak a
# never-stopped session. One session per process (jax's own limit); the
# span stack mirrors into TraceAnnotations while a session is live, so
# XLA ops nest under train.gbm.chunk / mrtask.dispatch in Perfetto.

#: one-element list so span()'s hot-path read is a plain load (no lock);
#: flipped only under _PROFILE_LOCK
_PROFILE_ACTIVE = [False]
_PROFILE_LOCK = threading.Lock()
_PROFILE_SEQ = itertools.count(1)


def profile_dir() -> str | None:
    """H2O_TPU_PROFILE_DIR when set — arms span-scoped device capture."""
    return knobs.get_str("H2O_TPU_PROFILE_DIR") or None


@contextlib.contextmanager
def device_profile(what: str, out_dir: str | None = None):
    """Span-scoped ``jax.profiler`` capture around the caller's region.

    Yields the capture directory (``<dir>/<what>_<pid>_<n>``), or None
    when profiling is not armed (no ``H2O_TPU_PROFILE_DIR`` and no
    explicit ``out_dir``) or another session already runs in this process
    — the caller's region executes unchanged either way. stop_trace is
    guaranteed on exit, which is the whole point of scoping captures."""
    d = out_dir or profile_dir()
    if not d:
        yield None
        return
    with _PROFILE_LOCK:
        busy = _PROFILE_ACTIVE[0]
        if not busy:
            _PROFILE_ACTIVE[0] = True
    if busy:
        # NEVER yield while holding _PROFILE_LOCK: the caller's body runs
        # at the yield point, and anything there touching the lock (the
        # capture() busy-vs-failed diagnosis does) would self-deadlock
        yield None
        return
    path = os.path.join(
        d, f"{what.replace('/', '_')}_{os.getpid()}_{next(_PROFILE_SEQ)}")
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.profiler.start_trace(path)
    except Exception as e:  # pragma: no cover — backend without profiler
        from . import log

        log.err(f"jax.profiler start_trace failed: {e!r}")
        with _PROFILE_LOCK:
            _PROFILE_ACTIVE[0] = False
        yield None
        return
    try:
        yield path
    finally:
        try:
            # a stop failure (backend trace-collection error) must never
            # replace the caller's in-flight exception — the flight
            # recorder would bundle the profiler's error instead of the
            # crash that matters
            try:
                jax.profiler.stop_trace()
            except Exception:  # pragma: no cover — backend quirk
                pass
            else:
                inc("profiler.capture.count")
                timeline.record("profiler", what, dir=path)
        finally:
            with _PROFILE_LOCK:
                _PROFILE_ACTIVE[0] = False


def capture(ms: int, out_dir: str | None = None) -> str:
    """Bounded LIVE capture — the ``POST /3/Profiler/capture?ms=N`` body:
    profile whatever this process is doing for ``ms`` milliseconds and
    return the capture directory. Runs on the calling (REST handler)
    thread; concurrent training/serving work is what gets captured.
    Raises ValueError when a session is already live (REST: 400)."""
    import tempfile

    ms = int(ms)
    if not 0 < ms <= 60_000:
        raise ValueError(f"capture ms must be in (0, 60000], got {ms}")
    d = out_dir or profile_dir() or tempfile.mkdtemp(
        prefix="h2o_tpu_profile_")
    with device_profile("capture", out_dir=d) as path:
        if path is None:
            # device_profile yields None for BOTH "busy" and "start_trace
            # failed" — tell the operator which one this was (a 400 'busy'
            # on a process where no session ever started sends them
            # hunting a phantom concurrent capture)
            with _PROFILE_LOCK:
                busy = _PROFILE_ACTIVE[0]
            if busy:
                raise ValueError(
                    "a profiler session is already live in this process "
                    "— one capture at a time (jax.profiler limit)")
            raise RuntimeError(
                "jax.profiler failed to start a session on this backend "
                "— see the server log for the start_trace error")
        time.sleep(ms / 1000.0)
    return path
