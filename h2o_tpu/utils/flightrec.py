"""Crash flight recorder — atomic diagnostics bundles on terminal events.

The reference's operational story for "a node just died" is the log
bundle: `/3/Logs` + JStack + Timeline pulled from every node and zipped.
This repo's equivalent must survive the PROCESS dying — so instead of a
pull surface, typed terminal events push one atomic bundle to disk
(``H2O_TPU_FLIGHT_DIR``) at the moment of failure, while the process
still can. A bundle is one JSON file containing everything a post-mortem
needs and nothing it has to reconstruct:

- the metrics registry snapshot and the typed timeline ring (the last
  N events BEFORE the crash — the reference TimeLine's whole purpose);
- the log ring (every level, not just what reached stderr);
- an all-thread stack dump (the JStack parity piece);
- the Cleaner's per-device ledger + limits (was it memory?);
- the program cost registry (WHAT was running, and how big);
- every registered knob's effective value + armed failpoints (the
  configuration that reproduced this).

Triggers wired through the stack (each a no-op unless the knob is set):

- device OOM that survives the Cleaner's emergency sweep
  (`frame/vec.py _rehydrate_put` — the "we really are out of HBM" path);
- :class:`~h2o_tpu.utils.sanitizer.LockOrderViolation` raised by the
  runtime sanitizer (the inversion IS the diagnosis — record it);
- unhandled training crash (`models/model_base.py` train root) and a
  serving batch-worker fault (`serving/batcher.py`);
- the ``flightrec.dump`` drill failpoint (:func:`maybe_drill`, polled at
  the GBM chunk boundary and the serving batch worker) — CI's way to
  exercise the whole bundle path at an exact iteration.

Writes are atomic (temp + fsync + rename — a reader or a second crash
never sees a torn bundle), reentrancy-guarded (a crash INSIDE the
recorder must not recurse), and bounded (``H2O_TPU_FLIGHT_MAX_BUNDLES``
rotates the oldest out). ``GET /3/Flight`` lists bundles;
``GET /3/Flight/{name}`` serves one.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback

from . import failpoints, knobs, log, telemetry, timeline

_SCHEMA_VERSION = 1

_LOCK = threading.Lock()
_SEQ = 0
#: per-thread reentrancy guard — a fault raised while bundling (e.g. a
#: sick metrics provider) must not trigger a second bundle of itself
_IN_DUMP = threading.local()


def flight_dir() -> str | None:
    return knobs.get_str("H2O_TPU_FLIGHT_DIR") or None


def enabled() -> bool:
    return flight_dir() is not None


def _thread_dump() -> list[dict]:
    """All-thread stacks with names — `Thread.getAllStackTraces` parity."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append({
            "thread_id": tid,
            "name": names.get(tid, "?"),
            "stack": [ln.rstrip() for ln in
                      traceback.format_stack(frame, limit=40)],
        })
    return out


def _cleaner_state() -> dict:
    """The Cleaner's per-device ledger — lazily imported and failure-proof
    (the recorder may fire before any frame ever touched the backend)."""
    mem = sys.modules.get("h2o_tpu.backend.memory")
    if mem is None:
        return {"note": "backend.memory not loaded"}
    try:
        c = mem.CLEANER
        return {"tracked_bytes": c.tracked_bytes(),
                "device_bytes": {str(k): v
                                 for k, v in c.device_bytes().items()},
                "device_peak_bytes": {str(k): v for k, v in
                                      c.device_peak_bytes().items()},
                "limit_bytes": c.limit_bytes(),
                "reserved_bytes": mem.reserved_bytes()}
    except Exception as e:  # noqa: BLE001 — diagnostics must not crash
        return {"error": repr(e)}


def _knob_state() -> dict:
    """Effective value of every registered knob: the env value when set,
    the registered default otherwise (``set`` lists which is which)."""
    vals = {}
    set_names = []
    for name, k in sorted(knobs.KNOBS.items()):
        env = os.environ.get(name)
        if env is not None:
            vals[name] = env
            set_names.append(name)
        else:
            vals[name] = k.default
    return {"values": vals, "set_in_env": set_names}


def _bundle(reason: str, error: BaseException | None) -> dict:
    from . import programs

    b = {
        "schema_version": _SCHEMA_VERSION,
        "reason": reason,
        "ts_ms": int(time.time() * 1000),
        "pid": os.getpid(),
        "error": None if error is None else {
            "type": type(error).__name__,
            "message": str(error),
            "traceback": traceback.format_exception(
                type(error), error, error.__traceback__),
        },
        "metrics": telemetry.snapshot(),
        "timeline": timeline.snapshot(limit=1024),
        "logs": log.get_records(limit=512),
        "threads": _thread_dump(),
        "cleaner": _cleaner_state(),
        "programs": programs.snapshot(),
        "knobs": _knob_state(),
        "failpoints": failpoints.active(),
    }
    return b


def _reap(d: str) -> None:
    keep = max(knobs.get_int("H2O_TPU_FLIGHT_MAX_BUNDLES"), 1)
    names = sorted(n for n in os.listdir(d)
                   if n.startswith("flight_") and n.endswith(".json"))
    for n in names[:-keep] if len(names) > keep else []:
        try:
            os.remove(os.path.join(d, n))
        except OSError:  # pragma: no cover — concurrent reap
            pass


def dump(reason: str, error: BaseException | None = None) -> str | None:
    """Write one bundle; returns its path, or None when the recorder is
    disarmed (no ``H2O_TPU_FLIGHT_DIR``) or reentered. NEVER raises — the
    recorder rides failure paths, and a recorder fault must not mask the
    real error the caller is about to surface."""
    d = flight_dir()
    if d is None or getattr(_IN_DUMP, "active", False):
        return None
    _IN_DUMP.active = True
    try:
        global _SEQ
        with _LOCK:
            _SEQ += 1
            seq = _SEQ
        safe_reason = "".join(c if (c.isalnum() or c in "-_") else "-"
                              for c in reason)
        name = (f"flight_{int(time.time() * 1000)}_{os.getpid()}_"
                f"{seq}_{safe_reason}.json")
        path = os.path.join(d, name)
        os.makedirs(d, exist_ok=True)
        data = json.dumps(_bundle(reason, error),
                          default=repr).encode()
        # atomic write inlined (not persist.atomic_write_bytes: that hits
        # the persist.checkpoint failpoint — a recorder drill must not
        # perturb the checkpoint kill-windows' deterministic hit counts,
        # and a dump triggered BY a persist fault must not re-enter
        # failpoints at all)
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _reap(d)
        telemetry.inc("flight.dump.count")
        timeline.record("flight", reason, path=path)
        log.err(f"flight recorder: wrote {path} ({reason})")
        return path
    except Exception as e:  # noqa: BLE001 — see docstring
        try:
            log.err(f"flight recorder FAILED for {reason!r}: {e!r}")
        except Exception:  # pragma: no cover
            pass
        return None
    finally:
        _IN_DUMP.active = False


#: in-flight async dump threads — joined (bounded) at interpreter exit so
#: a violation that escalates straight to process shutdown still gets its
#: bundle on disk before teardown kills daemon threads
_ASYNC_DUMPS: list = []
_ATEXIT_ARMED = False


def _drain_async() -> None:
    for t in list(_ASYNC_DUMPS):
        t.join(timeout=5.0)


def dump_async(reason: str, error: BaseException | None = None):
    """Write a bundle from a DETACHED thread — for callers that cannot
    block or hold application locks through the bundle's fsync (the lock
    sanitizer's violation path: the violating thread still HOLDS the
    inverted locks, and bundling acquires foreign subsystem locks).
    The thread is tracked and joined at interpreter exit, so the bundle
    survives even when the triggering error takes the process down.
    Returns the thread (None when the recorder is disarmed)."""
    global _ATEXIT_ARMED
    if not enabled():
        return None
    # joined via _ASYNC_DUMPS in the atexit _drain_async hook — the lint
    # can't see a join that walks a list, so: (the caller must NOT join
    # inline; it holds the very locks the bundle collection acquires).
    # No trace context either: a bundle is a process-terminal diagnostic,
    # not part of any request's causality.
    t = threading.Thread(target=dump, args=(reason, error),  # graftlint: disable=unjoined-thread,thread-without-trace-context
                         name="flightrec-dump", daemon=True)
    with _LOCK:
        if not _ATEXIT_ARMED:
            import atexit

            atexit.register(_drain_async)
            _ATEXIT_ARMED = True
        _ASYNC_DUMPS[:] = [x for x in _ASYNC_DUMPS if x.is_alive()]
        _ASYNC_DUMPS.append(t)
    t.start()
    return t


def maybe_drill() -> str | None:
    """The ``flightrec.dump`` failpoint's consumption site: polled at the
    GBM/DRF chunk boundary and the serving batch worker; an armed hit
    becomes a bundle (reason ``drill``) and the caller continues — the
    one injected fault the registry documents as NOT propagating."""
    try:
        failpoints.hit("flightrec.dump")
    except failpoints.InjectedFault as e:
        return dump("drill", e)
    return None


def list_bundles(d: str | None = None) -> list[dict]:
    """The ``GET /3/Flight`` listing: newest last."""
    d = d or flight_dir()
    if d is None or not os.path.isdir(d):
        return []
    out = []
    for n in sorted(os.listdir(d)):
        if not (n.startswith("flight_") and n.endswith(".json")):
            continue
        parts = n[len("flight_"):-len(".json")].split("_", 3)
        st = os.stat(os.path.join(d, n))
        out.append({"name": n, "bytes": st.st_size,
                    "ts_ms": int(parts[0]) if parts[0].isdigit() else None,
                    "pid": int(parts[1]) if len(parts) > 1
                    and parts[1].isdigit() else None,
                    "reason": parts[3] if len(parts) > 3 else None})
    return out


def read_bundle(name: str, d: str | None = None) -> dict:
    """One bundle's content (``GET /3/Flight/{name}``). The name is
    validated against the listing pattern — no path traversal."""
    d = d or flight_dir()
    if d is None:
        raise KeyError("flight recorder is not armed (H2O_TPU_FLIGHT_DIR)")
    if (os.path.basename(name) != name or not name.startswith("flight_")
            or not name.endswith(".json")):
        raise KeyError(f"no such flight bundle: {name!r}")
    path = os.path.join(d, name)
    if not os.path.isfile(path):
        raise KeyError(f"no such flight bundle: {name!r}")
    with open(path) as f:
        return json.load(f)
