"""Per-task phase profiling — analog of MRTask's `.profile()`
(`water/MRTask.java:190-194,321-383` MRProfile: setup/RPC/map/reduce/block
times and payload sizes per distributed task).

Usage mirrors the reference's opt-in profile flag::

    with task_profile("gbm.histogram") as prof:
        with prof.phase("map"):
            ...device dispatch...
        with prof.phase("reduce"):
            ...

Each phase lands in the process timeline ring (utils/timeline.py → served at
`/3/Timeline`) and in the returned record, so the jax profiler covers device
internals while this covers the host-side task anatomy."""

from __future__ import annotations

import contextlib
import threading
import time

from . import timeline

#: process-lifetime aggregation by task name — the `/3/Profiler` payload's
#: task view (`water/api/ProfilerHandler` aggregates stack samples; the
#: TPU-native equivalent aggregates per-task phase wall)
_AGG_LOCK = threading.Lock()
_AGG: dict[str, dict] = {}


def _aggregate(prof: "TaskProfile") -> None:
    with _AGG_LOCK:
        rec = _AGG.setdefault(prof.name,
                              {"count": 0, "total_s": 0.0, "phases": {}})
        rec["count"] += 1
        rec["total_s"] += prof.t_total
        for k, v in prof.phases.items():
            rec["phases"][k] = rec["phases"].get(k, 0.0) + v


def aggregate_snapshot() -> list[dict]:
    """Per-task totals, heaviest first — what `/3/Profiler` serves next to
    the stack samples."""
    with _AGG_LOCK:
        out = [{"task": name, "count": rec["count"],
                "total_s": round(rec["total_s"], 6),
                "mean_s": round(rec["total_s"] / rec["count"], 6),
                "phases": {k: round(v, 6)
                           for k, v in sorted(rec["phases"].items())}}
               for name, rec in _AGG.items()]
    return sorted(out, key=lambda r: -r["total_s"])


def clear_aggregate() -> None:
    with _AGG_LOCK:
        _AGG.clear()


class TaskProfile:
    def __init__(self, name: str):
        self.name = name
        self.phases: dict[str, float] = {}
        self.t_start = time.perf_counter()
        self.t_total = 0.0

    @contextlib.contextmanager
    def phase(self, phase_name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.phases[phase_name] = self.phases.get(phase_name, 0.0) + dt

    def summary(self) -> dict:
        return {"task": self.name, "total_s": self.t_total, **{
            f"{k}_s": round(v, 6) for k, v in self.phases.items()}}


@contextlib.contextmanager
def task_profile(name: str):
    prof = TaskProfile(name)
    try:
        yield prof
    finally:
        prof.t_total = time.perf_counter() - prof.t_start
        timeline.record("task", name,
                        **{f"{k}_s": round(v, 6)
                           for k, v in prof.phases.items()},
                        total_s=round(prof.t_total, 6))
        _aggregate(prof)
