"""Per-task phase profiling — analog of MRTask's `.profile()`
(`water/MRTask.java:190-194,321-383` MRProfile: setup/RPC/map/reduce/block
times and payload sizes per distributed task).

Usage mirrors the reference's opt-in profile flag::

    with task_profile("gbm.histogram") as prof:
        with prof.phase("map"):
            ...device dispatch...
        with prof.phase("reduce"):
            ...

Each phase lands in the process timeline ring (utils/timeline.py → served at
`/3/Timeline`) and in the returned record, so the jax profiler covers device
internals while this covers the host-side task anatomy."""

from __future__ import annotations

import contextlib
import time

from . import timeline


class TaskProfile:
    def __init__(self, name: str):
        self.name = name
        self.phases: dict[str, float] = {}
        self.t_start = time.perf_counter()
        self.t_total = 0.0

    @contextlib.contextmanager
    def phase(self, phase_name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.phases[phase_name] = self.phases.get(phase_name, 0.0) + dt

    def summary(self) -> dict:
        return {"task": self.name, "total_s": self.t_total, **{
            f"{k}_s": round(v, 6) for k, v in self.phases.items()}}


@contextlib.contextmanager
def task_profile(name: str):
    prof = TaskProfile(name)
    try:
        yield prof
    finally:
        prof.t_total = time.perf_counter() - prof.t_start
        timeline.record("task", name,
                        **{f"{k}_s": round(v, 6)
                           for k, v in prof.phases.items()},
                        total_s=round(prof.t_total, 6))
