"""Program cost registry — per-program XLA cost/memory accounting.

The reference answers "where did the cluster's cycles go" with WaterMeter
and per-task `MRTask.profile()`; the TPU-native equivalent question is
"what does each COMPILED PROGRAM cost" — XLA knows (the compiler emits a
per-executable cost model and a memory assignment), but until this module
those numbers evaporated the moment an executable left the compile path.

Every compiled executable that passes through the repo's jit/AOT choke
points registers here under a STABLE program id:

- ``models/gbm.py _aot_train_step`` — the tree chunk step (the engine's
  ``_TRAIN_FN_CACHE`` programs reach XLA through this AOT site);
- ``parallel/mrtask.py _dispatch`` — every DrJAX-style driver program
  (rollups, binning, generic mr_reduce/mr_map), via :func:`tracked`;
- ``models/glm.py _make_irls_kernel`` — the GLM IRLS step (jit and
  shard_map shapes), via :func:`tracked`;
- ``serving/scorer.py CompiledScorer.warmup`` — one entry per bucket
  executable;
- ``models/tree/engine.py`` phase samples — the standalone kernels-layer
  replays.

Each record pairs the STATIC cost (``cost_analysis()``: flops, bytes
accessed; ``memory_analysis()``: argument/output/temp/generated-code
bytes) with MEASURED dispatch walls (a bounded per-program ring fed by
:class:`Tracked` dispatches or explicit :func:`note_wall` calls) to derive
achieved-FLOPs and a roofline fraction per program. Caveat the README
spells out: dispatch walls are HOST walls — async dispatch means they are
an upper bound on queue-insert cost, not device compute, unless the caller
drains (the tree chunk loop and bench legs do); and on the CPU mesh there
is no meaningful peak-FLOPs figure, so ``roofline_fraction`` is ``null``
off-TPU by design.

:func:`tracked` wraps a jitted callable: the first dispatch per argument
signature AOT-lowers and compiles (the SAME single compile the jit
dispatch would have paid — the jitted twin's own cache is never populated),
registers the executable's analyses, and dispatches the compiled object;
any mismatch (tracers, re-sharded inputs, executable input rejection)
falls back to the jitted twin permanently for that signature, so behavior
can only ever degrade to exactly the pre-registry dispatch. Results are
bit-identical either way: both paths execute the XLA program lowered from
the same arguments.

Surfaced as ``GET /3/Programs`` (JSON + Prometheus families via the
telemetry provider hook), embedded per-leg in the bench sidecar
(``record["programs"]``), and included in every flight-recorder bundle.
"""

from __future__ import annotations

import hashlib
import threading
import time
import weakref
from collections import deque

from . import telemetry

#: measured dispatch walls kept per program (host seconds)
_WALL_WINDOW = 128

_LOCK = threading.Lock()
_REGISTRY: dict[str, "ProgramRecord"] = {}

#: live Tracked instances, weakly held — a Tracked's lifetime belongs to
#: its caller (mrtask caches them on the map function so the gc reclaims
#: program + closure together); the jobs.py CLEAR_CACHES_EVERY sweep
#: calls :func:`clear_compiled` over whatever is still alive so
#: directly-held executables honor the same long-server hygiene bound as
#: the AOT caches
_TRACKED: "weakref.WeakSet" = weakref.WeakSet()


class ProgramRecord:
    __slots__ = ("pid", "kind", "name", "labels", "flops", "bytes_accessed",
                 "memory", "registered_ms", "dispatch_count", "walls")

    def __init__(self, pid, kind, name, labels, flops, bytes_accessed,
                 memory):
        self.pid = pid
        self.kind = kind            # "train" | "dispatch" | "serving" | "kernel"
        self.name = name
        self.labels = labels
        self.flops = flops
        self.bytes_accessed = bytes_accessed
        self.memory = memory
        self.registered_ms = int(time.time() * 1000)
        self.dispatch_count = 0
        self.walls: deque = deque(maxlen=_WALL_WINDOW)


def _cost_of(compiled) -> tuple[float, float]:
    """(flops, bytes accessed) from an executable's XLA cost model; a
    backend that reports neither yields (0, 0) rather than failing the
    registration (accounting must never gate a train)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # pragma: no cover — backend without a cost model
        return 0.0, 0.0
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return 0.0, 0.0
    return (float(ca.get("flops", 0.0) or 0.0),
            float(ca.get("bytes accessed", 0.0) or 0.0))


def _memory_of(compiled) -> dict:
    """Memory assignment of the executable: the figures the real-TPU HBM
    budget planning needs next to the Cleaner's runtime ledger."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # pragma: no cover
        return {}
    if ma is None:
        return {}
    out = {}
    for field, key in (("argument_size_in_bytes", "argument_bytes"),
                       ("output_size_in_bytes", "output_bytes"),
                       ("temp_size_in_bytes", "temp_bytes"),
                       ("alias_size_in_bytes", "alias_bytes"),
                       ("generated_code_size_in_bytes",
                        "generated_code_bytes")):
        v = getattr(ma, field, None)
        if v is not None:
            out[key] = int(v)
    return out


def _stable_pid(kind: str, name: str, sig, labels: dict) -> str:
    """Readable + stable program id: same program shape in two processes
    (or two runs) gets the same id — no ``id()``s, no pointers. The short
    hash disambiguates programs that share name and leading shape."""
    mat = repr((kind, name, sig, tuple(sorted(labels.items()))))
    h = hashlib.sha1(mat.encode()).hexdigest()[:8]
    shape = ""
    if sig:
        first = sig[0]
        if isinstance(first, tuple) and first and isinstance(first[0], tuple):
            shape = "x".join(str(d) for d in first[0])
    base = f"{name}[{shape}]" if shape else name
    return f"{base}#{h}"


def register_compiled(name: str, compiled, kind: str, sig=None,
                      wall_metric: str | None = None, **labels) -> str:
    """Register one compiled executable's analyses; idempotent per id
    (re-registration refreshes the static figures, keeps the wall ring).
    ``wall_metric`` names the DECLARED telemetry histogram whose walls
    already time this program's dispatches (the /3/Programs join)."""
    flops, nbytes = _cost_of(compiled)
    memory = _memory_of(compiled)
    if wall_metric is not None:
        labels["wall_metric"] = wall_metric
    pid = _stable_pid(kind, name, sig, labels)
    with _LOCK:
        rec = _REGISTRY.get(pid)
        if rec is None:
            rec = ProgramRecord(pid, kind, name, dict(labels), flops,
                                nbytes, memory)
            _REGISTRY[pid] = rec
        else:
            rec.flops, rec.bytes_accessed, rec.memory = flops, nbytes, memory
    telemetry.inc("programs.registered.count")
    return pid


def note_wall(pid: str, seconds: float) -> None:
    """Record one measured dispatch wall for a registered program (host
    wall — see the module caveat on async dispatch)."""
    with _LOCK:
        rec = _REGISTRY.get(pid)
        if rec is None:
            return
        rec.dispatch_count += 1
        rec.walls.append(seconds)


class Tracked:
    """Per-signature AOT dispatch wrapper over a jitted callable — the
    instrumentation shape of ``gbm._aot_train_step`` made reusable. One
    compile per signature either way; the compiled object additionally
    yields its cost/memory analyses and a measured dispatch wall."""

    __slots__ = ("name", "kind", "labels", "_jitted", "_compiled", "_pids",
                 "__weakref__")

    def __init__(self, name: str, jitted, kind: str, **labels):
        self.name = name
        self.kind = kind
        self.labels = labels
        self._jitted = jitted
        #: sig -> compiled executable, or False once a signature fell back
        self._compiled: dict = {}
        self._pids: dict = {}
        with _LOCK:
            _TRACKED.add(self)

    # AOT passthrough so a Tracked can stand wherever the jitted stood
    def lower(self, *args, **kw):
        return self._jitted.lower(*args, **kw)

    @staticmethod
    def _sig_of(a):
        shape = getattr(a, "shape", None)
        if shape is None:
            return (type(a).__name__,)
        sharding = getattr(a, "sharding", None)
        try:
            hash(sharding)
        except TypeError:  # pragma: no cover — unhashable sharding
            sharding = str(sharding)
        return (tuple(shape), str(getattr(a, "dtype", "?")), sharding)

    def _concrete(self, args) -> bool:
        from jax.core import Tracer

        return not any(isinstance(a, Tracer) for a in args)

    def clear(self) -> None:
        """Drop the compiled executables (long-server cache hygiene); the
        next dispatch per signature recompiles and re-registers."""
        self._compiled.clear()

    def __call__(self, *args):
        if not self._concrete(args):
            # under an enclosing trace the wrapper steps aside entirely
            return self._jitted(*args)
        key = tuple(self._sig_of(a) for a in args)
        ent = self._compiled.get(key)
        if ent is None:
            try:
                ent = self._jitted.lower(*args).compile()
                sig = tuple((s[0], s[1]) for s in key
                            if isinstance(s, tuple) and len(s) == 3)
                self._pids[key] = register_compiled(
                    self.name, ent, self.kind, sig=sig, **self.labels)
            except Exception:
                ent = False
            self._compiled[key] = ent
        if ent is False:
            return self._jitted(*args)
        t0 = time.perf_counter()
        try:
            out = ent(*args)
        except (TypeError, ValueError) as e:
            # executable input REJECTION — a compiled object refuses
            # mismatched dtypes/shapes/pytrees with TypeError and
            # mismatched shardings with ValueError (measured on this
            # jax) — permanently degrade this signature to the jitted
            # twin, exactly the pre-registry dispatch. Genuine device
            # runtime faults (XlaRuntimeError: OOM, INTERNAL, failed
            # collectives) do NOT match and surface unchanged: silently
            # re-running a whole program on a faulting device would
            # double time-to-failure and bury the real traceback.
            from . import log

            log.warn(f"program {self.name}: compiled executable rejected "
                     f"its inputs ({e!r:.200}) — signature degrades to "
                     f"jit dispatch")
            self._compiled[key] = False
            return self._jitted(*args)
        pid = self._pids.get(key)
        if pid is not None:
            note_wall(pid, time.perf_counter() - t0)
        return out


def tracked(name: str, jitted, kind: str, **labels) -> Tracked:
    return Tracked(name, jitted, kind, **labels)


def clear_compiled() -> None:
    """Drop every Tracked executable (the jobs.py CLEAR_CACHES_EVERY sweep
    — directly-held compiled objects are invisible to jax.clear_caches).
    Registry records (pure numbers) survive; only executables drop."""
    with _LOCK:
        live = list(_TRACKED)
    for t in live:
        t.clear()


def device_peak_flops() -> float | None:
    """Per-chip peak dense-matmul FLOP/s for the roofline denominator —
    bf16/MXU peaks from the published TPU specs (the units real-TPU
    campaign numbers are quoted in). None off-TPU: a CPU mesh has no
    honest single figure, so /3/Programs reports roofline as null there
    (README caveats this explicitly)."""
    try:
        import jax

        dev = jax.devices()[0]
        if getattr(dev, "platform", "") != "tpu":
            return None
        kind = (getattr(dev, "device_kind", "") or "").lower()
    except Exception:  # pragma: no cover — no backend yet
        return None
    table = {"v4": 275e12, "v5 lite": 197e12, "v5litepod": 197e12,
             "v5e": 197e12, "v5p": 459e12, "v6 lite": 918e12,
             "v6e": 918e12}
    for k, v in table.items():
        if k in kind:
            return v
    return None


def snapshot() -> dict:
    """{pid: record} — the /3/Programs payload. Static cost + memory
    figures, measured wall quantiles (telemetry's nearest-rank formula —
    ONE quantile definition across /3/Metrics and /3/Programs), achieved
    FLOP/s (flops / p50 wall) and the roofline fraction against
    :func:`device_peak_flops`."""
    peak = device_peak_flops()
    out: dict[str, dict] = {}
    with _LOCK:
        recs = list(_REGISTRY.values())
    for rec in recs:
        walls = list(rec.walls)
        pcts = telemetry._percentiles(walls)
        p50 = pcts["p50"]
        achieved = (rec.flops / p50) if (p50 and rec.flops) else None
        entry = {
            "kind": rec.kind, "name": rec.name, "labels": rec.labels,
            "flops": rec.flops, "bytes_accessed": rec.bytes_accessed,
            "memory": rec.memory, "registered_ms": rec.registered_ms,
            "dispatch_count": rec.dispatch_count,
            "wall": {"count": len(walls),
                     "p50_s": p50, "p95_s": pcts["p95"],
                     "total_s": round(sum(walls), 6) if walls else 0.0},
            "achieved_flops_per_s": achieved,
            "roofline_fraction": ((achieved / peak)
                                  if (achieved and peak) else None),
        }
        out[rec.pid] = entry
    return out


def ids() -> set:
    with _LOCK:
        return set(_REGISTRY)


def snapshot_delta(before_ids: set) -> dict:
    """Programs registered since ``before_ids`` (the bench sidecar's
    per-leg program-cost block): static figures only, compact."""
    out = {}
    for pid, rec in snapshot().items():
        if pid in before_ids:
            continue
        out[pid] = {"kind": rec["kind"], "flops": rec["flops"],
                    "bytes_accessed": rec["bytes_accessed"],
                    "memory": rec["memory"],
                    "dispatch_count": rec["dispatch_count"]}
    return out


def prometheus_lines() -> list:
    """Per-program Prometheus families (telemetry provider hook — the
    registry proper stays label-free, like serving's per-model stats)."""
    lines = []
    snap = snapshot()
    if not snap:
        return lines
    esc = telemetry.prom_label_escape
    lines.append("# HELP h2o_tpu_program_flops XLA cost-model flops per "
                 "dispatch of a registered program")
    lines.append("# TYPE h2o_tpu_program_flops gauge")
    for pid, rec in snap.items():
        lbl = f'program="{esc(pid)}",kind="{esc(rec["kind"])}"'
        lines.append(f'h2o_tpu_program_flops{{{lbl}}} {rec["flops"]:g}')
    lines.append("# HELP h2o_tpu_program_bytes_accessed XLA cost-model "
                 "bytes accessed per dispatch")
    lines.append("# TYPE h2o_tpu_program_bytes_accessed gauge")
    for pid, rec in snap.items():
        lbl = f'program="{esc(pid)}",kind="{esc(rec["kind"])}"'
        lines.append(f'h2o_tpu_program_bytes_accessed{{{lbl}}} '
                     f'{rec["bytes_accessed"]:g}')
    lines.append("# HELP h2o_tpu_program_dispatch_count measured dispatches "
                 "through the registry's tracked paths")
    lines.append("# TYPE h2o_tpu_program_dispatch_count counter")
    for pid, rec in snap.items():
        lbl = f'program="{esc(pid)}",kind="{esc(rec["kind"])}"'
        lines.append(f'h2o_tpu_program_dispatch_count{{{lbl}}} '
                     f'{rec["dispatch_count"]:g}')
    return lines


telemetry.add_prometheus_provider(prometheus_lines)


def reset() -> None:
    """Drop every record and tracked executable (test isolation)."""
    clear_compiled()
    with _LOCK:
        _REGISTRY.clear()
