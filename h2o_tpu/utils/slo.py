"""SLO registry — declared latency/error objectives + rolling burn rates.

The serving-for-millions loop (ROADMAP: load-driven autoscaling,
promote/rollback gates) needs ONE number per surface answering "are we
inside our objective, and how fast are we spending the error budget" —
not a dashboard of raw percentiles a human has to interpret. This module
is that number's registry, with the `utils/knobs.py` discipline applied
to objectives: every SLO is DECLARED here with a doc, a p99 latency
target and an error budget; accessors raise ``KeyError`` on undeclared
names, and per-deployment overrides ride ``H2O_TPU_SLO`` without code
edits.

Burn semantics (the classic multi-window burn-rate shape, collapsed to
one rolling window sized ``H2O_TPU_SLO_WINDOW_S``):

- **latency burn** — the fraction of recent SLO-relevant requests
  breaching the p99 target, divided by the allowed 1%: burn 1.0 =
  exactly at budget, 10 = paging territory. Computed from the per-SLO
  note window (the same samples the error burn uses), because the raw
  telemetry histogram rings also hold monitoring/health polls — a 1 Hz
  readiness prober's 5 ms samples would dilute the breach fraction and
  mask a real user-facing breach. When an SLO declares a backing
  ``hist`` and its note window is empty (a process that serves traffic
  through a path that only feeds the telemetry ring), the EXISTING
  histogram ring (``telemetry.hist_values``) is the fallback.
- **error burn** — windowed error fraction / declared error budget,
  from the same bounded per-SLO ring of ``(wall stamp, dur, error?)``
  samples fed by :func:`note` at the request boundaries (REST
  `_route`, the serving score path).

``burn_snapshot()`` is the `GET /3/Health` payload's ``slo`` block and
sets the ``slo.worst_burn`` gauge (Prometheus-scraped — the autoscaler's
poll target); ``objective()`` hands `utils/slowtrace.py` the per-request
p99 threshold that decides which span trees are worth persisting.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

from . import knobs, telemetry


@dataclasses.dataclass(frozen=True)
class SLO:
    name: str
    doc: str
    p99_ms: float           # latency objective the tail must stay under
    error_budget: float     # allowed error fraction over the window
    hist: str | None        # telemetry histogram ring backing latency burn


SLOS: dict[str, SLO] = {}

#: allowed tail fraction the p99 objective implies — breach_fraction is
#: divided by this to get the latency burn rate
_P99_ALLOWANCE = 0.01


def declare(name: str, doc: str, p99_ms: float, error_budget: float,
            hist: str | None = None) -> None:
    """Register an objective (idempotent by name — re-declaration
    replaces, which is how per-model serving SLOs refresh on
    re-registration). ``hist`` names a DECLARED telemetry histogram whose
    ring backs the latency burn; None keeps latency burn off and leaves
    only the error budget."""
    if hist is not None:
        telemetry.hist_values(hist)     # KeyError on undeclared/non-hist
    SLOS[name] = SLO(name, doc, float(p99_ms), float(error_budget), hist)


declare("rest.request",
        "REST control-plane requests (api/server.py _route; monitoring "
        "polls included — they ride the same handler)",
        p99_ms=2500.0, error_budget=0.02, hist="rest.request.seconds")
declare("serving.score",
        "online scoring requests end to end — encode + queue + device "
        "call (serving/runtime.py score path)",
        p99_ms=250.0, error_budget=0.01, hist="serving.request.seconds")
declare("workload.wait",
        "managed-job queue wait — submission (or re-admission after "
        "preemption) to slot grant under the workload manager's "
        "fair-share dispatch (workload/manager.py)",
        p99_ms=60_000.0, error_budget=0.05,
        hist="workload.queue.wait.seconds")


def _lookup(name: str) -> SLO:
    try:
        return SLOS[name]
    except KeyError:
        raise KeyError(
            f"undeclared SLO {name!r} — declare it in h2o_tpu/utils/slo.py "
            f"(or slo.declare() for per-model objectives)") from None


def _overrides() -> dict:
    """``H2O_TPU_SLO`` = comma list of ``<slo>.<field>=<value>`` pairs
    (fields: p99_ms, error_budget) — parsed per read so tests/operators
    can retune a live process. Unknown names/fields raise the registry's
    typed KeyError/ValueError loudly; a silently ignored override is an
    SLO nobody is actually holding."""
    raw = knobs.get_str("H2O_TPU_SLO")
    out: dict[str, dict] = {}
    for tok in filter(None, (t.strip() for t in raw.split(","))):
        key, _, val = tok.partition("=")
        name, _, field = key.rpartition(".")
        if field not in ("p99_ms", "error_budget"):
            raise ValueError(
                f"bad H2O_TPU_SLO entry {tok!r} — grammar: "
                f"<slo>.p99_ms=<ms> | <slo>.error_budget=<frac>")
        _lookup(name)
        out.setdefault(name, {})[field] = float(val)
    return out


def objective(name: str) -> SLO:
    """The EFFECTIVE objective: the declared SLO with any ``H2O_TPU_SLO``
    override applied. KeyError on undeclared names."""
    s = _lookup(name)
    ov = _overrides().get(name)
    if not ov:
        return s
    return dataclasses.replace(s, **ov)


# ---------------------------------------------------------------------------
# rolling error windows (latency rides the telemetry hist rings)
# ---------------------------------------------------------------------------
class _Window:
    __slots__ = ("ring", "lock")

    def __init__(self):
        #: (wall stamp, duration s, error?) per noted request
        self.ring: deque = deque(maxlen=4096)
        self.lock = threading.Lock()


_WINDOWS: dict[str, _Window] = {}
_WINDOWS_LOCK = threading.Lock()


def _window(name: str) -> _Window:
    w = _WINDOWS.get(name)
    if w is None:
        with _WINDOWS_LOCK:
            w = _WINDOWS.setdefault(name, _Window())
    return w


def note(name: str, dur_s: float, error: bool = False) -> None:
    """One finished request against SLO ``name`` — the request boundaries
    (REST route, serving score) call this. The window backs BOTH burns:
    it holds exactly the SLO-relevant requests, unlike the raw telemetry
    rings which also see monitoring/health polls. Honors the metrics
    master switch."""
    _lookup(name)
    if not telemetry.enabled():
        return
    w = _window(name)
    with w.lock:
        w.ring.append((time.time(), float(dur_s), bool(error)))


def window_s() -> float:
    return max(float(knobs.get_int("H2O_TPU_SLO_WINDOW_S")), 1.0)


def _recent(name: str) -> list[tuple]:
    w = _WINDOWS.get(name)
    if w is None:
        return []
    horizon = time.time() - window_s()
    with w.lock:
        return [rec for rec in w.ring if rec[0] >= horizon]


def _error_burn(recent: list[tuple], budget: float) -> dict:
    n = len(recent)
    frac = (sum(1 for (_, _, e) in recent if e) / n) if n else 0.0
    return {"window": n, "error_fraction": round(frac, 6),
            "burn": round(frac / budget, 4) if budget > 0 else None}


def _latency_burn(s: SLO, recent: list[tuple]) -> dict:
    thr = s.p99_ms / 1000.0
    if recent:
        # the note window holds exactly the SLO-relevant requests — the
        # raw telemetry ring would let monitor-poll samples dilute the
        # breach fraction (a 1 Hz health prober masking a real breach)
        n = len(recent)
        breach = sum(1 for (_, d, _) in recent if d > thr) / n
        src = "window"
    elif s.hist is not None:
        vals = telemetry.hist_values(s.hist)
        n = len(vals)
        breach = (sum(1 for v in vals if v > thr) / n) if n else 0.0
        src = s.hist
    else:
        n, breach, src = 0, 0.0, "window"
    return {"window": n, "p99_target_ms": s.p99_ms, "source": src,
            "breach_fraction": round(breach, 6),
            "burn": round(breach / _P99_ALLOWANCE, 4)}


def burn_snapshot() -> dict:
    """{slo: {objective, latency, errors, burn}} for every declared SLO —
    the `/3/Health` ``slo`` block. ``burn`` is the max of the latency and
    error burns; the overall max lands on the ``slo.worst_burn`` gauge."""
    out: dict[str, dict] = {}
    worst = 0.0
    for name in sorted(SLOS):
        s = objective(name)
        recent = _recent(name)
        lat = _latency_burn(s, recent)
        err = _error_burn(recent, s.error_budget)
        burn = max(lat["burn"] or 0.0, err["burn"] or 0.0)
        worst = max(worst, burn)
        out[name] = {"doc": s.doc, "p99_ms": s.p99_ms,
                     "error_budget": s.error_budget,
                     "latency": lat, "errors": err,
                     "burn": round(burn, 4)}
    telemetry.set_gauge("slo.worst_burn", worst)
    return out


def describe() -> str:
    """Human-readable registry dump (the knobs.describe analog)."""
    lines = []
    for s in sorted(SLOS.values(), key=lambda s: s.name):
        lines.append(f"{s.name}  [p99 {s.p99_ms:g} ms, error budget "
                     f"{s.error_budget:g}]")
        lines.append(f"    {s.doc}")
    return "\n".join(lines)


def reset() -> None:
    """Drop every error window (test isolation)."""
    with _WINDOWS_LOCK:
        _WINDOWS.clear()
