"""Micro-batching scheduler — one device call for many concurrent requests.

The serving hot path is many small independent requests against one model;
dispatching each alone wastes the accelerator (a 1-row call costs the same
fixed overhead as a 64-row call). The `MicroBatcher` is the classic
dynamic-batching loop (TF-Serving's BatchingSession shape): requests enter
a BOUNDED queue; a single worker thread drains it, coalescing up to
``max_batch`` rows — holding the first request open at most ``max_wait_us``
to let concurrent arrivals join — concatenates them into one matrix, makes
ONE scorer call, and fans the rows back out.

The three failure modes are explicit, typed, and never hang:

- **backpressure**: a submit against a full queue raises `QueueFullError`
  immediately (the REST layer maps it to 429 + Retry-After from the drain
  estimate). Load sheds at the door, not by stacking latency.
- **deadlines**: every request carries one; if it expires while still
  queued, the submitter (or the worker, whichever looks first) flips it to
  TIMED_OUT under the lock and `DeadlineExceededError` is raised. A
  request already RUNNING is past the point of no return — its result is
  returned even if slightly late, because the compute is spent either way.
- **shutdown**: stop() fails all queued requests with
  `ServingShutdownError` rather than stranding their waiters.

State transitions (WAITING → RUNNING | TIMED_OUT, RUNNING → DONE) happen
only under the queue lock, so the worker and a timing-out submitter can
never both claim a request.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from .errors import (DeadlineExceededError, QueueFullError,
                     ServingShutdownError)

_WAITING, _RUNNING, _DONE, _TIMED_OUT, _FAILED = range(5)


class _Pending:
    __slots__ = ("rows", "n", "deadline", "state", "event", "result",
                 "error", "enqueued")

    def __init__(self, rows: np.ndarray, deadline: float | None):
        self.rows = rows
        self.n = rows.shape[0]
        self.deadline = deadline        # absolute time.monotonic() stamp
        self.state = _WAITING
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.enqueued = time.monotonic()  # queue-stall watchdog probe


class MicroBatcher:
    def __init__(self, model_id: str, score_fn, stats, *,
                 max_batch: int, max_wait_us: int, queue_depth: int,
                 recompile_probe=None):
        self.model_id = model_id
        self._score = score_fn          # (N, F) np -> (N, ...) np
        self._stats = stats
        #: cumulative steady-state-compile count owned by the SCORER
        #: (bucket-miss fallbacks) — a process-global compile-counter
        #: delta here would blame concurrent training/registration
        #: compiles on this model
        self._recompiles = recompile_probe or (lambda: 0)
        self.max_batch = max(int(max_batch), 1)
        self.max_wait_s = max(int(max_wait_us), 0) / 1e6
        self.queue_depth = max(int(queue_depth), 1)
        self._q: deque[_Pending] = deque()
        self._cv = threading.Condition()
        self._paused = False
        self._stopped = False
        from ..utils import telemetry

        # the worker adopts the REGISTERING thread's span context: any
        # span it opens shares the registration trace instead of minting
        # per-batch orphan ids (telemetry.carry_context — the
        # thread-without-trace-context contract). Per-REQUEST attribution
        # is deliberately NOT attempted here: one batch serves N
        # coalesced requests, so there is no single request context a
        # batch could honestly adopt — the request-side serving.submit
        # span (runtime.score_rows) owns the queue+device wall instead
        self._worker = threading.Thread(
            target=telemetry.carry_context(self._run), daemon=True,
            name=f"h2o-serving-batch[{model_id}]")
        self._worker.start()

    # -- request side --------------------------------------------------------
    @property
    def depth(self) -> int:
        with self._cv:
            return len(self._q)

    def oldest_wait_s(self) -> float | None:
        """Age of the oldest still-WAITING queued request (None when the
        queue is empty) — the watchdog's queue-stall probe: a wedged or
        paused worker shows up as this number growing past budget."""
        now = time.monotonic()
        with self._cv:
            for req in self._q:
                if req.state == _WAITING:
                    return now - req.enqueued
        return None

    def submit(self, rows: np.ndarray, deadline_s: float | None):
        """Block until the batch worker scores these rows; returns the
        (n, ...) result slice. Raises the typed errors documented above."""
        now = time.monotonic()
        req = _Pending(rows, None if deadline_s is None else now + deadline_s)
        with self._cv:
            if self._stopped:
                raise ServingShutdownError(
                    f"serving model '{self.model_id}' is shut down")
            if len(self._q) >= self.queue_depth:
                self._stats.observe_rejected()
                raise QueueFullError(self.model_id, len(self._q),
                                     self._retry_after_locked())
            self._q.append(req)
            self._cv.notify_all()
        timeout = None if req.deadline is None else req.deadline - now
        if not req.event.wait(timeout):
            with self._cv:
                if req.state == _WAITING:       # still queued: reclaim it
                    req.state = _TIMED_OUT
                    try:
                        self._q.remove(req)
                    except ValueError:
                        pass
                    self._stats.observe_timeout()
                    raise DeadlineExceededError(self.model_id,
                                                (timeout or 0.0) * 1000.0)
            # RUNNING: the batch is on the device — wait it out
            req.event.wait()
        if req.state == _TIMED_OUT:
            raise DeadlineExceededError(self.model_id, (timeout or 0) * 1e3)
        if req.error is not None:
            raise req.error
        return req.result

    def _retry_after_locked(self) -> float:
        queued_rows = sum(r.n for r in self._q)
        rate = self._stats.recent_rows_per_s()
        if rate <= 0:
            return 0.1
        return min(max(queued_rows / rate, 0.05), 30.0)

    # -- control -------------------------------------------------------------
    def pause(self) -> None:
        """Hold the worker before its next batch (tests use this to build
        deterministic queue states; requests keep queueing)."""
        with self._cv:
            self._paused = True

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._paused = False
            dangling = list(self._q)
            self._q.clear()
            self._cv.notify_all()
        err = ServingShutdownError(
            f"serving model '{self.model_id}' is shut down")
        for req in dangling:
            req.state = _FAILED
            req.error = err
            req.event.set()
        self._worker.join(timeout=5.0)

    # -- worker --------------------------------------------------------------
    def _take_batch(self) -> list[_Pending] | None:
        """Block until work is available (and not paused), coalesce up to
        max_batch rows, claim the survivors as RUNNING. Returns None on
        shutdown — decided UNDER the queue lock, so the worker never
        consults `_stopped` unguarded (graftlint unguarded-shared-field:
        the old `_run` re-read it outside the cv after an empty take)."""
        with self._cv:
            while True:
                while not self._stopped and (self._paused or not self._q):
                    self._cv.wait()
                if self._stopped:
                    return None
                if self.max_wait_s:
                    # hold the door open for concurrent arrivals — but
                    # never past the first queued request's deadline
                    end = time.monotonic() + self.max_wait_s
                    while not self._stopped and not self._paused and \
                            sum(r.n for r in self._q) < self.max_batch:
                        left = end - time.monotonic()
                        dl = min((r.deadline for r in self._q
                                  if r.deadline is not None),
                                 default=None)
                        if dl is not None:
                            left = min(left, dl - time.monotonic())
                        if left <= 0:
                            break
                        self._cv.wait(left)
                if not self._paused or self._stopped:
                    break
                # pause() landed mid-hold: keep holding — "before its
                # next batch" means nothing dispatches while paused
            batch: list[_Pending] = []
            rows = 0
            now = time.monotonic()
            while self._q:
                req = self._q[0]
                if req.state != _WAITING:       # timed out and reclaimed
                    self._q.popleft()
                    continue
                if req.deadline is not None and now > req.deadline:
                    self._q.popleft()           # expired while queued: the
                    req.state = _TIMED_OUT      # submitter's own wait() has
                    req.event.set()             # fired or is about to
                    self._stats.observe_timeout()
                    continue
                if batch and rows + req.n > self.max_batch:
                    break
                self._q.popleft()
                req.state = _RUNNING
                batch.append(req)
                rows += req.n
            return batch

    def _run(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:       # stop decided under the queue lock
                return
            if not batch:           # spurious wake / all takers expired
                continue
            X = (batch[0].rows if len(batch) == 1
                 else np.concatenate([r.rows for r in batch], axis=0))
            recompiles_before = self._recompiles()
            try:
                from ..utils import failpoints, flightrec

                # flight-recorder drill window on the serving path (the
                # armed flightrec.dump failpoint writes a bundle here and
                # the batch proceeds)
                flightrec.maybe_drill()
                failpoints.hit("serving.batch")
                out = self._score(X)
            except Exception as e:  # noqa: BLE001 — fan the failure out
                # a scoring-path fault is the serving tier's terminal
                # event: bundle it (no-op unless H2O_TPU_FLIGHT_DIR set),
                # then fail the coalesced requests as before
                from ..utils import flightrec as _fr

                _fr.dump("serving-crash", e)
                for req in batch:
                    req.state = _FAILED
                    req.error = e
                    req.event.set()
                continue
            self._stats.observe_batch(
                len(batch), X.shape[0],
                recompiles=self._recompiles() - recompiles_before)
            i = 0
            for req in batch:
                req.result = out[i:i + req.n]
                i += req.n
                req.state = _DONE
                req.event.set()
