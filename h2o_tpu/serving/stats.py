"""Per-model serving observability — the numbers behind GET /3/Serving/stats.

One `ServingStats` per registered model, fed from two sides:

- the request path records end-to-end latency per request (encode + queue
  wait + scoring), via ``observe_request``;
- the batch worker records each device call's occupancy and any XLA
  compiles it observed (`utils/compilemeter.py` delta — steady state must
  record zero), via ``observe_batch``.

Latencies and batch completions live in bounded ring buffers
(``H2O_TPU_SERVING_STATS_WINDOW``), so the percentiles and the rows/s
throughput describe *recent* traffic and the memory footprint is fixed no
matter how long the server lives. Counters (requests, rows, rejected,
timeouts, recompiles) are monotone totals.

Everything is guarded by one lock: request threads and the batch worker
mutate concurrently, and a torn snapshot would misreport the very tail
latencies the endpoint exists to expose.

Every per-model observation ALSO feeds the process-global telemetry
registry (`utils/telemetry.py` ``serving.*`` counters/histogram), so
`GET /3/Metrics` and the Prometheus exposition carry fleet-wide serving
totals without a second instrumentation layer — the per-model snapshots
here remain the `GET /3/Serving/stats` payload.
"""

from __future__ import annotations

import time
from collections import deque

import numpy as np

from ..utils import telemetry
from ..utils.sanitizer import guarded_by, make_lock


class ServingStats:
    def __init__(self, window: int = 2048):
        self._lock = make_lock("ServingStats._lock")
        self.window = max(int(window), 16)
        self._lat_s: deque = deque(maxlen=self.window)
        #: (completion wall-stamp, rows) per scored batch — throughput window
        self._batches: deque = deque(maxlen=self.window)
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.batch_rows = 0
        self.rejected = 0
        self.timeouts = 0
        self.recompiles = 0
        self.started_at = time.time()

    # -- request path --------------------------------------------------------
    def observe_request(self, latency_s: float, rows: int) -> None:
        with self._lock:
            self.requests += 1
            self.rows += rows
            self._lat_s.append(latency_s)
        telemetry.inc("serving.request.count")
        telemetry.inc("serving.request.rows", rows)
        telemetry.observe("serving.request.seconds", latency_s)

    def observe_rejected(self) -> None:
        with self._lock:
            self.rejected += 1
        telemetry.inc("serving.rejected.count")

    def observe_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1
        telemetry.inc("serving.timeout.count")

    # -- batch worker --------------------------------------------------------
    def observe_batch(self, n_requests: int, n_rows: int,
                      recompiles: int = 0) -> None:
        with self._lock:
            self.batches += 1
            self.batch_rows += n_rows
            self.recompiles += recompiles
            self._batches.append((time.time(), n_rows))
        telemetry.inc("serving.batch.count")
        telemetry.inc("serving.batch.rows", n_rows)
        if recompiles:
            telemetry.inc("serving.recompile.count", recompiles)

    def recent_rows_per_s(self) -> float:
        """Scoring throughput over the batch window (0.0 when idle)."""
        with self._lock:
            return self._rows_per_s_locked()

    @guarded_by("_lock")
    def _rows_per_s_locked(self) -> float:
        if len(self._batches) < 2:
            return 0.0
        t0 = self._batches[0][0]
        span = self._batches[-1][0] - t0
        if span <= 0:
            return 0.0
        return sum(r for _, r in self._batches) / span

    # -- snapshot ------------------------------------------------------------
    def snapshot(self, queue_depth: int = 0) -> dict:
        with self._lock:
            lat = np.asarray(self._lat_s, dtype=np.float64)
            p50 = p95 = p99 = None
            if lat.size:
                p50, p95, p99 = (round(float(v) * 1000.0, 3) for v in
                                 np.percentile(lat, (50, 95, 99)))
            occupancy = (self.batch_rows / self.batches
                         if self.batches else None)
            return {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "rejected": self.rejected,
                "timeouts": self.timeouts,
                "recompiles": self.recompiles,
                "queue_depth": queue_depth,
                "mean_batch_occupancy": (None if occupancy is None
                                         else round(occupancy, 3)),
                "latency_ms": {"p50": p50, "p95": p95, "p99": p99,
                               "window": int(lat.size)},
                "rows_per_s": round(self._rows_per_s_locked(), 1),
                "uptime_s": round(time.time() - self.started_at, 1),
            }
