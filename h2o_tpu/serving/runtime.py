"""The serving runtime: model registry + request path + stats surface.

`ServingRuntime` owns one `ServedModel` per registered id. Registration
(`register_model` / `register_mojo`) builds the three cooperating parts:

- a shared `RowEncoder` (`mojo/easy.py`) for dict→row conversion — engine
  models encode against ``output.names``/``output.domains``, MOJOs against
  the wrapper's feature metadata, so BOTH surfaces speak the same row-dict
  dialect as EasyPredictModelWrapper;
- a shape-bucketed scorer (`scorer.py`) — AOT-compiled jit buckets for
  engine models, the numpy MOJO scorer for MOJO files — warmed up HERE, so
  a registration that returns has already paid every compile;
- a `MicroBatcher` (`batcher.py`) + `ServingStats` (`stats.py`).

The request path (`score`) is: encode rows (caller's thread — encoding is
host work and parallelizes across request threads), submit to the batcher,
format the scored rows into the typed per-category prediction dicts the
REST layer returns verbatim.

Since the control plane (PR 8), registration rides admission: the
`ControlPlane` (`control.py`) estimates the model's HBM cost, checks it
against the fleet quota (evicting cold placements if that makes it fit),
and reserves the bytes in `backend/memory.py`'s ledger; a model may place
N replica scorers across mesh devices (`ReplicaSet` — least-loaded
dispatch, dead replicas routed around), and `cold`-priority models that
lost their placement lazily re-place on first hit.

A module-level singleton (`get_runtime`) backs the REST routes; tests
build private instances.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..utils import knobs, slowtrace, telemetry
from .control import (ControlPlane, Replica, ReplicaSet,
                      estimate_model_bytes, replica_devices)
from .errors import AdmissionError, ModelNotRegisteredError
from .scorer import CompiledScorer, HostScorer, bucket_sizes
from .stats import ServingStats


def _cfg(overrides: dict | None) -> dict:
    """Effective serving config: knobs, then per-registration overrides."""
    o = overrides or {}
    return {
        "buckets": bucket_sizes(o.get("buckets")
                                if o.get("buckets") else None),
        "max_batch": int(o.get("max_batch")
                         or knobs.get_int("H2O_TPU_SERVING_MAX_BATCH")),
        "max_wait_us": int(o["max_wait_us"] if o.get("max_wait_us")
                           is not None
                           else knobs.get_int("H2O_TPU_SERVING_MAX_WAIT_US")),
        "queue_depth": int(o.get("queue_depth")
                           or knobs.get_int("H2O_TPU_SERVING_QUEUE_DEPTH")),
        "deadline_ms": int(o["deadline_ms"] if o.get("deadline_ms")
                           is not None
                           else knobs.get_int("H2O_TPU_SERVING_DEADLINE_MS")),
        "stats_window": int(o.get("stats_window")
                            or knobs.get_int("H2O_TPU_SERVING_STATS_WINDOW")),
        "priority": str(o.get("priority")
                        or knobs.get_str("H2O_TPU_SERVING_PRIORITY")),
        "replicas": int(o.get("replicas")
                        or knobs.get_int("H2O_TPU_SERVING_REPLICAS")),
    }


class ServedModel:
    def __init__(self, model_id: str, make_scorer, encoder, category: str,
                 response_domain, cfg: dict, source: str):
        self.model_id = model_id
        #: ``device -> scorer`` factory — kept so a cold model that lost
        #: its placement can rebuild and re-warm its scorers on first hit
        self.make_scorer = make_scorer
        self.encoder = encoder
        self.category = category
        self.response_domain = response_domain
        self.cfg = cfg
        self.source = source
        self.registered_at = time.time()
        self.stats = ServingStats(window=cfg["stats_window"])
        self._place_lock = threading.Lock()
        self._control: ControlPlane | None = None  # set by _install
        devices = replica_devices(cfg["replicas"])
        self.replicas = ReplicaSet([
            Replica(i, dev, make_scorer(dev), self.stats, cfg, model_id)
            for i, dev in enumerate(devices)])

    @property
    def scorer(self):
        """Replica 0's scorer (the single-replica surface tests/info use)."""
        return self.replicas.replicas[0].scorer

    @property
    def batcher(self):
        """Replica 0's batcher — the pre-replica surface (pause/depth in
        the PR 4 tests); multi-replica control goes through `replicas`."""
        return self.replicas.replicas[0].batcher

    @property
    def depth(self) -> int:
        return self.replicas.depth

    def warmup(self) -> int:
        return sum(r.scorer.warmup() for r in self.replicas.replicas)

    # -- placement (control-plane hooks) -------------------------------------
    def deplace(self) -> None:
        """Drop every replica's compiled executables (cold eviction)."""
        for r in self.replicas.replicas:
            r.scorer.evict()

    def ensure_placed(self) -> None:
        """Lazy re-placement of an evicted cold model on first hit: re-admit
        under the quota (429 if the fleet is hot-crowded) and re-pay the
        bucket compiles. No-op (one placed-flag read) while placed."""
        control = self._control
        if control is None:
            return
        pl = control.placement(self.model_id)
        if pl is None or pl.placed:
            # None: unregistered under a stale handle — nothing to place
            return
        with self._place_lock:
            pl = control.placement(self.model_id)
            if pl is None or pl.placed:
                return                     # raced another request's re-place
            control.admit(self.model_id, pl.cost_bytes,
                          self.cfg["priority"], self.cfg["replicas"])
            self.warmup()

    # -- request path --------------------------------------------------------
    def score_rows(self, rows: list, deadline_ms=None,
                   slo: bool = True) -> list:
        if not rows:
            return []
        if not slo:
            # shadow/background scoring: droppable-by-definition work
            # must not feed the serving.score SLO window or the
            # slow-trace ring — a slow shadow candidate flipping
            # /3/Health to slo-burn would page on a signal no
            # user-facing request produced
            return self._score_impl(rows, deadline_ms)
        # the serving.score SLO boundary: latency feeds the existing
        # serving.request.seconds ring (observe_request below), the error
        # flag feeds the SLO window (a 429/408/scoring fault unwinds as a
        # typed exception and counts), and a request breaching the
        # serving.score p99 target persists its span tree behind
        # GET /3/SlowTraces (the span is ring=False — request-rate spans
        # must not cycle the timeline ring)
        with slowtrace.request("serving.score", self.model_id,
                               model=self.model_id, rows=len(rows)):
            return self._score_impl(rows, deadline_ms)

    def _score_impl(self, rows: list, deadline_ms) -> list:
        t0 = time.perf_counter()
        self.ensure_placed()
        if self._control is not None:
            self._control.note_hit(self.model_id)
        # child spans on the CALLER thread split a slow request's
        # tree into encode vs queue+device wall (the submit span
        # covers queueing, coalescing AND the batch's device call —
        # the batch worker serves N coalesced requests at once, so
        # per-request attribution finer than this is structurally
        # impossible); ring=False — request-rate spans must not
        # cycle the timeline ring
        with telemetry.span("serving.encode", ring=False):
            X = self.encoder.encode(rows)
        if deadline_ms is None:
            deadline_ms = self.cfg["deadline_ms"]
        deadline_s = None if not deadline_ms else float(deadline_ms) / 1e3
        with telemetry.span("serving.submit", ring=False,
                            rows=int(X.shape[0])):
            out = self.replicas.submit(X, deadline_s)
        preds = self._format(np.asarray(out))
        self.stats.observe_request(time.perf_counter() - t0, len(rows))
        return preds

    def _format(self, out: np.ndarray) -> list:
        """(n,)/(n, 1+K) raw scores → typed per-row prediction dicts (the
        EasyPredictModelWrapper prediction classes' wire shape)."""
        cat = (self.category or "").lower()
        if out.ndim == 2 and cat in ("binomial", "multinomial", "ordinal"):
            dom = (self.response_domain
                   or [str(i) for i in range(out.shape[1] - 1)])
            return [{"label": dom[int(r[0])], "labelIndex": int(r[0]),
                     "classProbabilities": [float(p) for p in r[1:]]}
                    for r in out]
        if cat == "clustering":
            flat = out[:, 0] if out.ndim == 2 else out
            return [{"cluster": int(v)} for v in flat]
        if cat == "regression" or out.ndim == 1:
            flat = out[:, -1] if out.ndim == 2 else out
            return [{"value": float(v)} for v in flat]
        return [{"values": [float(v) for v in r]} for r in np.atleast_2d(out)]

    def info(self) -> dict:
        out = {
            "model_id": self.model_id,
            "source": self.source,
            "category": self.category,
            "features": list(self.encoder.features),
            "n_features": len(self.encoder.features),
            "buckets": list(self.scorer.buckets),
            "max_batch": self.batcher.max_batch,
            "max_wait_us": int(self.batcher.max_wait_s * 1e6),
            "queue_depth": self.batcher.queue_depth,
            "deadline_ms": self.cfg["deadline_ms"],
            "warmup_compiles": self.scorer.warmup_compiles,
            "replicas": self.replicas.info(),
        }
        if self._control is not None:
            pl = self._control.placement(self.model_id)
            if pl is not None:
                out["placement"] = pl.info()
        return out

    def shutdown(self) -> None:
        self.replicas.stop()


class ServingRuntime:
    def __init__(self):
        from .router import Router

        from ..utils.sanitizer import make_lock

        self._models: dict[str, ServedModel] = {}
        self._lock = make_lock("ServingRuntime._lock")
        self.control = ControlPlane()
        self.control.deplacer = self._deplace
        self.router = Router(self)

    # -- registration --------------------------------------------------------
    def register_model(self, model, model_id: str | None = None,
                       overrides: dict | None = None,
                       strict_levels: bool = False) -> dict:
        """Register an in-STORE engine model: jit bucket scorers over its
        ``score_raw`` matrix path — admitted under the fleet quota, placed
        (one replica per configured device), warmed up before this
        returns."""
        from ..mojo.easy import RowEncoder

        model_id = model_id or model.key
        cfg = _cfg(overrides)
        # validate the scorer contract BEFORE admission reserves anything
        # (CompiledScorer's constructor refuses adapt_frame-overriders and
        # frozen encodings) — build replica 0 eagerly, the rest on install
        probe = CompiledScorer(model, buckets=cfg["buckets"])
        del probe
        encoder = RowEncoder(
            model.output.names,
            [model.output.domains.get(n) for n in model.output.names],
            convert_unknown=not strict_levels, dtype=np.float32)
        cost = estimate_model_bytes(model, cfg["buckets"],
                                    len(model.output.names),
                                    replicas=cfg["replicas"])
        return self._admit_and_install(
            model_id, cost, cfg,
            lambda dev: CompiledScorer(model, buckets=cfg["buckets"],
                                       device=dev),
            encoder, model.output.model_category,
            model.output.response_domain, source=f"model:{model.key}")

    def register_mojo(self, path_or_model, model_id: str | None = None,
                      overrides: dict | None = None,
                      strict_levels: bool = False) -> dict:
        """Register a standalone MOJO (zip path, exploded dir, or loaded
        `MojoModel`) through its numpy scorer."""
        from ..mojo.easy import EasyPredictModelWrapper

        wrapper = EasyPredictModelWrapper(
            path_or_model,
            convert_unknown_categorical_levels_to_na=not strict_levels)
        m = wrapper.model
        model_id = model_id or f"mojo_{m.algo}_{id(m) & 0xffff:04x}"
        cfg = _cfg(overrides)
        nf = len(wrapper._features)
        cost = estimate_model_bytes(m, cfg["buckets"], nf,
                                    replicas=cfg["replicas"])
        return self._admit_and_install(
            model_id, cost, cfg,
            lambda dev: HostScorer(m, nf, buckets=cfg["buckets"]),
            wrapper.encoder, m.category, wrapper._resp_domain,
            source=(path_or_model if isinstance(path_or_model, str)
                    else f"mojo:{m.algo}"))

    def _admit_and_install(self, model_id, cost, cfg, make_scorer, encoder,
                           category, response_domain, source) -> dict:
        """Admission → placement → warmup → install, with the OOM seam:
        a device OOM during placement (real, or the `serving.place`
        failpoint's ``raise(oom)``) unwinds the reservation and surfaces
        as the SAME typed 429 an over-quota registration gets — the
        co-registered fleet never notices."""
        served = None
        prior = self.control.placement(model_id)
        try:
            self.control.admit(model_id, cost, cfg["priority"],
                               cfg["replicas"])
            served = ServedModel(model_id, make_scorer, encoder, category,
                                 response_domain, cfg, source=source)
            served._control = self.control
            served.warmup()
        except Exception as e:
            if served is not None:
                served.shutdown()          # no leaked batcher threads
            if prior is not None:
                # a failed RE-registration must not strip the still-
                # installed prior registration of its placement/reservation
                self.control.restore(prior)
            else:
                self.control.release(model_id)
            if isinstance(e, AdmissionError):
                raise
            if "RESOURCE_EXHAUSTED" in str(e):
                telemetry.inc("serving.admission.rejected.count")
                budget = self.control.budget_bytes()
                raise AdmissionError(model_id, cost, budget or 0,
                                     self.control.placed_bytes()) from e
            raise
        return self._install(served)

    def _deplace(self, model_id: str) -> None:
        """ControlPlane eviction hook: drop a cold model's executables."""
        with self._lock:
            served = self._models.get(model_id)
        if served is not None:
            served.deplace()

    def _install(self, served: ServedModel) -> dict:
        with self._lock:
            old = self._models.get(served.model_id)
            self._models[served.model_id] = served
        if old is not None:  # re-registration replaces atomically
            old.shutdown()
        return served.info()

    def unregister(self, model_id: str) -> None:
        with self._lock:
            served = self._models.pop(model_id, None)
        if served is None:
            raise ModelNotRegisteredError(model_id)
        self.control.release(model_id)
        served.shutdown()

    # -- lookup / request path ----------------------------------------------
    def model(self, model_id: str) -> ServedModel:
        with self._lock:
            served = self._models.get(model_id)
        if served is None:
            raise ModelNotRegisteredError(model_id)
        return served

    def model_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def score(self, model_id: str, rows: list, deadline_ms=None,
              slo: bool = True) -> list:
        return self.model(model_id).score_rows(rows, deadline_ms=deadline_ms,
                                               slo=slo)

    def stats(self, model_id: str | None = None) -> dict:
        if model_id is not None:
            served = self.model(model_id)
            return served.stats.snapshot(queue_depth=served.depth)
        with self._lock:
            models = dict(self._models)
        return {mid: s.stats.snapshot(queue_depth=s.depth)
                for mid, s in models.items()}

    def control_snapshot(self) -> dict:
        """`GET /3/Serving/control` payload: quota, placements, routes."""
        snap = self.control.snapshot()
        snap["models"] = self.model_ids()
        snap["routes"] = sorted(self.router.endpoints())
        return snap

    def shutdown(self) -> None:
        self.router.shutdown()
        with self._lock:
            models = list(self._models.values())
            self._models.clear()
        for served in models:
            self.control.release(served.model_id)
            served.shutdown()


_RUNTIME: ServingRuntime | None = None
_RUNTIME_LOCK = threading.Lock()


def get_runtime() -> ServingRuntime:
    """The process singleton behind the REST Serving routes."""
    global _RUNTIME
    with _RUNTIME_LOCK:
        if _RUNTIME is None:
            _RUNTIME = ServingRuntime()
        return _RUNTIME


def _prometheus_model_lines() -> list[str]:
    """Per-model label dimension for the Prometheus exposition: the
    fleet-total ``h2o_tpu_serving_*`` series stay in the registry (one
    accounting); these ``{model="..."}``-labelled families are generated
    straight from the per-model stats windows of the SINGLETON runtime (a
    test's private runtime must not leak into the process scrape)."""
    rt = _RUNTIME
    if rt is None:
        return []
    lines: list[str] = []

    esc = telemetry.prom_label_escape  # serving ids are client-chosen

    snaps = sorted((esc(mid), s) for mid, s in rt.stats().items())
    if not snaps:
        return []

    def fam(metric: str, kind: str, doc: str):
        lines.append(f"# HELP h2o_tpu_serving_model_{metric} {doc}")
        lines.append(f"# TYPE h2o_tpu_serving_model_{metric} {kind}")

    fam("requests", "counter", "per-model scoring requests")
    for mid, s in snaps:
        lines.append(f'h2o_tpu_serving_model_requests{{model="{mid}"}} '
                     f'{s["requests"]:g}')
    fam("rows", "counter", "per-model rows scored")
    for mid, s in snaps:
        lines.append(f'h2o_tpu_serving_model_rows{{model="{mid}"}} '
                     f'{s["rows"]:g}')
    fam("rejected", "counter", "per-model backpressure rejections (429)")
    for mid, s in snaps:
        lines.append(f'h2o_tpu_serving_model_rejected{{model="{mid}"}} '
                     f'{s["rejected"]:g}')
    fam("timeouts", "counter", "per-model queued-deadline expiries (408)")
    for mid, s in snaps:
        lines.append(f'h2o_tpu_serving_model_timeouts{{model="{mid}"}} '
                     f'{s["timeouts"]:g}')
    fam("queue_depth", "gauge", "per-model live batcher queue depth")
    for mid, s in snaps:
        lines.append(f'h2o_tpu_serving_model_queue_depth{{model="{mid}"}} '
                     f'{s["queue_depth"]:g}')
    fam("latency_ms", "summary",
        "per-model recent-window request latency percentiles")
    for mid, s in snaps:
        for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            v = s["latency_ms"][key]
            if v is not None:
                lines.append(f'h2o_tpu_serving_model_latency_ms'
                             f'{{model="{mid}",quantile="{q}"}} {v:g}')
    fam("rows_per_s", "gauge",
        "per-model recent scoring throughput (batch window)")
    for mid, s in snaps:
        lines.append(f'h2o_tpu_serving_model_rows_per_s{{model="{mid}"}} '
                     f'{s["rows_per_s"]:g}')
    return lines


telemetry.add_prometheus_provider(_prometheus_model_lines)


__all__ = ["ServingRuntime", "ServedModel", "get_runtime"]
