"""The serving runtime: model registry + request path + stats surface.

`ServingRuntime` owns one `ServedModel` per registered id. Registration
(`register_model` / `register_mojo`) builds the three cooperating parts:

- a shared `RowEncoder` (`mojo/easy.py`) for dict→row conversion — engine
  models encode against ``output.names``/``output.domains``, MOJOs against
  the wrapper's feature metadata, so BOTH surfaces speak the same row-dict
  dialect as EasyPredictModelWrapper;
- a shape-bucketed scorer (`scorer.py`) — AOT-compiled jit buckets for
  engine models, the numpy MOJO scorer for MOJO files — warmed up HERE, so
  a registration that returns has already paid every compile;
- a `MicroBatcher` (`batcher.py`) + `ServingStats` (`stats.py`).

The request path (`score`) is: encode rows (caller's thread — encoding is
host work and parallelizes across request threads), submit to the batcher,
format the scored rows into the typed per-category prediction dicts the
REST layer returns verbatim.

A module-level singleton (`get_runtime`) backs the REST routes; tests
build private instances.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..utils import knobs
from .batcher import MicroBatcher
from .errors import ModelNotRegisteredError
from .scorer import CompiledScorer, HostScorer, bucket_sizes
from .stats import ServingStats


def _cfg(overrides: dict | None) -> dict:
    """Effective serving config: knobs, then per-registration overrides."""
    o = overrides or {}
    return {
        "buckets": bucket_sizes(o.get("buckets")
                                if o.get("buckets") else None),
        "max_batch": int(o.get("max_batch")
                         or knobs.get_int("H2O_TPU_SERVING_MAX_BATCH")),
        "max_wait_us": int(o["max_wait_us"] if o.get("max_wait_us")
                           is not None
                           else knobs.get_int("H2O_TPU_SERVING_MAX_WAIT_US")),
        "queue_depth": int(o.get("queue_depth")
                           or knobs.get_int("H2O_TPU_SERVING_QUEUE_DEPTH")),
        "deadline_ms": int(o["deadline_ms"] if o.get("deadline_ms")
                           is not None
                           else knobs.get_int("H2O_TPU_SERVING_DEADLINE_MS")),
        "stats_window": int(o.get("stats_window")
                            or knobs.get_int("H2O_TPU_SERVING_STATS_WINDOW")),
    }


class ServedModel:
    def __init__(self, model_id: str, scorer, encoder, category: str,
                 response_domain, cfg: dict, source: str):
        self.model_id = model_id
        self.scorer = scorer
        self.encoder = encoder
        self.category = category
        self.response_domain = response_domain
        self.cfg = cfg
        self.source = source
        self.registered_at = time.time()
        self.stats = ServingStats(window=cfg["stats_window"])
        self.batcher = MicroBatcher(
            model_id, scorer.score, self.stats,
            max_batch=min(cfg["max_batch"], max(scorer.buckets)),
            max_wait_us=cfg["max_wait_us"],
            queue_depth=cfg["queue_depth"],
            recompile_probe=lambda: scorer.fallback_compiles)

    # -- request path --------------------------------------------------------
    def score_rows(self, rows: list, deadline_ms=None) -> list:
        if not rows:
            return []
        t0 = time.perf_counter()
        X = self.encoder.encode(rows)
        if deadline_ms is None:
            deadline_ms = self.cfg["deadline_ms"]
        deadline_s = None if not deadline_ms else float(deadline_ms) / 1e3
        out = self.batcher.submit(X, deadline_s)
        preds = self._format(np.asarray(out))
        self.stats.observe_request(time.perf_counter() - t0, len(rows))
        return preds

    def _format(self, out: np.ndarray) -> list:
        """(n,)/(n, 1+K) raw scores → typed per-row prediction dicts (the
        EasyPredictModelWrapper prediction classes' wire shape)."""
        cat = (self.category or "").lower()
        if out.ndim == 2 and cat in ("binomial", "multinomial", "ordinal"):
            dom = (self.response_domain
                   or [str(i) for i in range(out.shape[1] - 1)])
            return [{"label": dom[int(r[0])], "labelIndex": int(r[0]),
                     "classProbabilities": [float(p) for p in r[1:]]}
                    for r in out]
        if cat == "clustering":
            flat = out[:, 0] if out.ndim == 2 else out
            return [{"cluster": int(v)} for v in flat]
        if cat == "regression" or out.ndim == 1:
            flat = out[:, -1] if out.ndim == 2 else out
            return [{"value": float(v)} for v in flat]
        return [{"values": [float(v) for v in r]} for r in np.atleast_2d(out)]

    def info(self) -> dict:
        return {
            "model_id": self.model_id,
            "source": self.source,
            "category": self.category,
            "features": list(self.encoder.features),
            "n_features": len(self.encoder.features),
            "buckets": list(self.scorer.buckets),
            "max_batch": self.batcher.max_batch,
            "max_wait_us": int(self.batcher.max_wait_s * 1e6),
            "queue_depth": self.batcher.queue_depth,
            "deadline_ms": self.cfg["deadline_ms"],
            "warmup_compiles": self.scorer.warmup_compiles,
        }

    def shutdown(self) -> None:
        self.batcher.stop()


class ServingRuntime:
    def __init__(self):
        self._models: dict[str, ServedModel] = {}
        self._lock = threading.Lock()

    # -- registration --------------------------------------------------------
    def register_model(self, model, model_id: str | None = None,
                       overrides: dict | None = None,
                       strict_levels: bool = False) -> dict:
        """Register an in-STORE engine model: jit bucket scorers over its
        ``score_raw`` matrix path, warmed up before this returns."""
        from ..mojo.easy import RowEncoder

        model_id = model_id or model.key
        cfg = _cfg(overrides)
        scorer = CompiledScorer(model, buckets=cfg["buckets"])
        scorer.warmup()
        encoder = RowEncoder(
            model.output.names,
            [model.output.domains.get(n) for n in model.output.names],
            convert_unknown=not strict_levels, dtype=np.float32)
        return self._install(ServedModel(
            model_id, scorer, encoder, model.output.model_category,
            model.output.response_domain, cfg, source=f"model:{model.key}"))

    def register_mojo(self, path_or_model, model_id: str | None = None,
                      overrides: dict | None = None,
                      strict_levels: bool = False) -> dict:
        """Register a standalone MOJO (zip path, exploded dir, or loaded
        `MojoModel`) through its numpy scorer."""
        from ..mojo.easy import EasyPredictModelWrapper

        wrapper = EasyPredictModelWrapper(
            path_or_model,
            convert_unknown_categorical_levels_to_na=not strict_levels)
        m = wrapper.model
        model_id = model_id or f"mojo_{m.algo}_{id(m) & 0xffff:04x}"
        cfg = _cfg(overrides)
        scorer = HostScorer(m, len(wrapper._features), buckets=cfg["buckets"])
        scorer.warmup()
        return self._install(ServedModel(
            model_id, scorer, wrapper.encoder, m.category,
            wrapper._resp_domain, cfg,
            source=(path_or_model if isinstance(path_or_model, str)
                    else f"mojo:{m.algo}")))

    def _install(self, served: ServedModel) -> dict:
        with self._lock:
            old = self._models.get(served.model_id)
            self._models[served.model_id] = served
        if old is not None:  # re-registration replaces atomically
            old.shutdown()
        return served.info()

    def unregister(self, model_id: str) -> None:
        with self._lock:
            served = self._models.pop(model_id, None)
        if served is None:
            raise ModelNotRegisteredError(model_id)
        served.shutdown()

    # -- lookup / request path ----------------------------------------------
    def model(self, model_id: str) -> ServedModel:
        with self._lock:
            served = self._models.get(model_id)
        if served is None:
            raise ModelNotRegisteredError(model_id)
        return served

    def model_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def score(self, model_id: str, rows: list, deadline_ms=None) -> list:
        return self.model(model_id).score_rows(rows, deadline_ms=deadline_ms)

    def stats(self, model_id: str | None = None) -> dict:
        if model_id is not None:
            served = self.model(model_id)
            return served.stats.snapshot(queue_depth=served.batcher.depth)
        with self._lock:
            models = dict(self._models)
        return {mid: s.stats.snapshot(queue_depth=s.batcher.depth)
                for mid, s in models.items()}

    def shutdown(self) -> None:
        with self._lock:
            models = list(self._models.values())
            self._models.clear()
        for served in models:
            served.shutdown()


_RUNTIME: ServingRuntime | None = None
_RUNTIME_LOCK = threading.Lock()


def get_runtime() -> ServingRuntime:
    """The process singleton behind the REST Serving routes."""
    global _RUNTIME
    with _RUNTIME_LOCK:
        if _RUNTIME is None:
            _RUNTIME = ServingRuntime()
        return _RUNTIME


__all__ = ["ServingRuntime", "ServedModel", "get_runtime"]
