"""Shape-bucketed scorers — the no-recompile contract of the serving runtime.

Every request shape that reaches XLA is a potential compile, and a compile
on the request path is a multi-second latency cliff. The fix is the same
one the DrJAX-style training driver uses for its batched map/reduce: pin
the shape set up front. A `CompiledScorer` AOT-compiles ONE executable per
configured bucket size (``H2O_TPU_SERVING_BUCKETS``, a power-of-two ladder)
at registration — `jit(...).lower(ShapeDtypeStruct).compile()` — and at
request time pads each micro-batch up to the smallest bucket that fits
(chunking through the largest bucket for oversized batches). A compiled
executable *cannot* retrace: steady-state serving performs zero compiles by
construction, and `utils/compilemeter.py` makes that assertable.

Padded rows are zero-filled and sliced off the output. Scoring is row-wise
(trees route rows independently, GLM/KMeans are per-row dots), so padding
can't perturb real rows — the parity tests pin batched-vs-single-row
BIT-equality across every bucket size and model category.

`HostScorer` is the same bucket/pad/mask surface over a host (numpy) batch
scorer — the standalone MOJO readers (`mojo/reader.py`) — so registered
MOJO files serve through the identical runtime with a trivially-zero
compile count.
"""

from __future__ import annotations

import contextlib

import numpy as np

from ..utils import compilemeter, knobs
from .errors import UnsupportedModelError


def bucket_sizes(override=None) -> tuple[int, ...]:
    """The configured bucket ladder, ascending and deduplicated."""
    if override is None:
        spec = knobs.get_str("H2O_TPU_SERVING_BUCKETS")
        sizes = [int(t) for t in spec.split(",") if t.strip()]
    else:
        sizes = [int(b) for b in override]
    sizes = sorted({b for b in sizes if b > 0})
    if not sizes:
        raise ValueError("serving bucket list is empty "
                         "(H2O_TPU_SERVING_BUCKETS)")
    return tuple(sizes)


class _BucketedScorer:
    """Shared pad/chunk/mask logic over a per-bucket batch scorer."""

    def __init__(self, n_features: int, buckets, dtype, device=None):
        self.n_features = int(n_features)
        self.buckets = bucket_sizes(buckets)
        self.dtype = dtype
        #: optional pinned placement (replica scorers compile one
        #: executable set per mesh device; None = backend default)
        self.device = device
        self.warmup_compiles = 0
        #: cumulative bucket-miss fallbacks — each one IS a steady-state
        #: compile. This, not a global-counter delta, feeds the stats
        #: `recompiles` gauge: the process compile counter also ticks for
        #: concurrent training/registration work that is not this model's
        #: fault (a false zero-recompile violation otherwise).
        self.fallback_compiles = 0

    # subclasses: score exactly one padded (b, F) bucket -> np.ndarray
    def _score_bucket(self, Xp: np.ndarray, b: int) -> np.ndarray:
        raise NotImplementedError

    def warmup(self) -> int:
        return 0

    def evict(self) -> int:
        """Drop placed executables (cold-priority eviction); returns the
        compiles a re-placement will cost (0 for host scorers)."""
        return 0

    @property
    def placed(self) -> bool:
        return True

    def _bucket_of(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def score(self, X: np.ndarray) -> np.ndarray:
        """(N, F) rows → (N, ...) predictions; N is unconstrained — batches
        beyond the largest bucket chunk through it."""
        X = np.asarray(X, dtype=self.dtype)
        if X.ndim != 2 or X.shape[1] != self.n_features:
            raise ValueError(f"expected (N, {self.n_features}) rows, got "
                             f"{X.shape}")
        outs = []
        i, n = 0, X.shape[0]
        while i < n:
            b = self._bucket_of(n - i)
            take = min(n - i, b)
            if take == b:
                Xp = X[i:i + b]
            else:
                Xp = np.zeros((b, self.n_features), dtype=self.dtype)
                Xp[:take] = X[i:i + take]
            out = np.asarray(self._score_bucket(Xp, b))
            outs.append(out[:take])
            i += take
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)


class CompiledScorer(_BucketedScorer):
    """Engine models: jit of ``model.score_raw`` AOT-compiled per bucket.

    ``device`` pins every executable (and every padded input) to one mesh
    device — the replica-placement lever: N replicas of a model are N
    CompiledScorers on N devices, and the executables CANNOT silently
    migrate (a compiled object rejects mismatched shardings loudly)."""

    def __init__(self, model, buckets=None, device=None):
        import jax

        from ..models.model_base import Model

        # a model that reshapes frames on the way in (GAM's spline basis,
        # DL's DataInfo pipeline, ...) but never declared a matrix-level
        # twin would silently score garbage — refuse it loudly instead
        if type(model).score_raw is Model.score_raw and \
                type(model).adapt_frame is not Model.adapt_frame:
            raise UnsupportedModelError(
                f"{type(model).__name__} overrides adapt_frame without a "
                f"score_raw matrix path — register its MOJO instead")
        # a frozen categorical_encoding renames/expands the columns before
        # base adapt_frame ever sees them (pre_adapt's encoding replay):
        # output.names are the ENCODED names, so the serving row encoder
        # would NaN every client cell and serve imputed garbage with a 200
        if getattr(model.output, "encoding_state", None) is not None:
            raise UnsupportedModelError(
                f"{type(model).__name__} was trained with a frozen "
                f"categorical_encoding — its raw-matrix path needs the "
                f"Frame-side encoding replay; register its MOJO instead")
        super().__init__(len(model.output.names), buckets, np.float32,
                         device=device)
        self._jit = jax.jit(model.score_raw)
        self._compiled: dict[int, object] = {}
        #: program-registry identity (utils/programs.py) — stable across
        #: processes serving the same model
        self._program_name = (f"serving.score."
                              f"{type(model).__name__.lower()}")
        self._model_key = str(getattr(model, "key", "?"))

    def warmup(self) -> int:
        """Compile every bucket and prime it with one scored batch of
        zeros; returns (and records) the XLA compiles that cost. After
        this, `_score_bucket` never compiles — the executables are frozen.
        """
        import contextlib

        import jax
        import jax.numpy as jnp

        before = compilemeter.count()
        pin = (jax.default_device(self.device) if self.device is not None
               else contextlib.nullcontext())
        from ..utils import programs

        with pin:
            for b in self.buckets:
                spec = jax.ShapeDtypeStruct((b, self.n_features),
                                            jnp.float32)
                # warmup IS the declared compile window: one AOT lower+
                # compile per registered bucket, counted and frozen — the
                # steady-state scorer never compiles again (the recompile
                # sanitizer arms only AFTER this boundary)
                self._compiled[b] = self._jit.lower(spec).compile()  # graftlint: disable=recompile-hazard
                # one real execution per bucket: surfaces runtime-only
                # errors (bad gather bounds, NaN traps) at registration,
                # not under load
                self._score_bucket(
                    np.zeros((b, self.n_features), np.float32), b)
                # one cost-registry entry per bucket executable — the
                # serving face of /3/Programs (what does a scored batch
                # COST, statically, per bucket)
                programs.register_compiled(
                    self._program_name, self._compiled[b], "serving",
                    sig=(((b, self.n_features), "float32"),),
                    wall_metric="serving.request.seconds",
                    model=self._model_key, bucket=b)
        self.warmup_compiles = compilemeter.count() - before
        return self.warmup_compiles

    def evict(self) -> int:
        """Drop the compiled executables (the cold-priority eviction hook):
        the next warmup() re-pays the bucket compiles. Returns how many."""
        n = len(self._compiled)
        self._compiled.clear()
        return n

    @property
    def placed(self) -> bool:
        return bool(self._compiled)

    def _score_bucket(self, Xp: np.ndarray, b: int) -> np.ndarray:
        import jax

        from ..utils import sanitizer

        fn = self._compiled.get(b)
        aot = fn is not None
        # post-warmup the WHOLE score path is declared steady — a bucket-
        # miss landing on the jit fallback below is exactly the uncached
        # compile H2O_TPU_SANITIZE=recompiles raises typed on. With the
        # sanitizer off the miss keeps degrading to a counted compile.
        steady = bool(self._compiled)
        if fn is None:  # unreachable after warmup(); kept non-fatal so a
            fn = self._jit  # mis-sized bucket degrades to a counted compile
            self.fallback_compiles += 1
        # staging and result fetch are EXPLICIT transfers (device_put /
        # device_get), so the steady-state path runs silent under the full
        # transfer guard in both directions — any other implicit transfer
        # on the score path is a bug the sanitizer raises typed. The h2d
        # guard arms only on the AOT path: the jit fallback TRACES, and
        # tracing stages constants host->device legitimately.
        X = jax.device_put(Xp, self.device)
        with sanitizer.transfer_scope("serving.score",
                                      host_to_device=aot), \
                (compilemeter.no_compile_scope("serving.score") if steady
                 else contextlib.nullcontext()):
            out = fn(X)
            return np.asarray(jax.device_get(out))


class HostScorer(_BucketedScorer):
    """MOJO models: the numpy batch scorer behind the same bucket surface."""

    def __init__(self, mojo_model, n_features: int, buckets=None):
        super().__init__(n_features, buckets, np.float64)
        self._model = mojo_model

    def warmup(self) -> int:
        for b in self.buckets:
            self._score_bucket(np.zeros((b, self.n_features), np.float64), b)
        self.warmup_compiles = 0
        return 0

    def _score_bucket(self, Xp: np.ndarray, b: int) -> np.ndarray:
        return np.asarray(self._model.score(Xp))
