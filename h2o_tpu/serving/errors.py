"""Typed serving-runtime errors — the wire contract's failure surface.

Every failure mode a caller can act on gets its own type, because the REST
layer maps types to status codes (`api/server.py` Serving routes): an
overloaded queue is retryable-later (429 + Retry-After), an expired
deadline is a per-request timeout (408), an unknown model is a 404. A
generic exception would collapse all three into a 500 and the client could
only guess.
"""

from __future__ import annotations


class ServingError(Exception):
    """Base of every serving-runtime error."""


class ModelNotRegisteredError(ServingError, KeyError):
    def __init__(self, model_id: str):
        super().__init__(f"no serving model '{model_id}' — register it via "
                         f"POST /3/Serving/models/{model_id} first")
        self.model_id = model_id

    def __str__(self):  # KeyError would repr() the message
        return self.args[0]


class UnsupportedModelError(ServingError, TypeError):
    """The model has no raw-matrix scoring path (`Model.score_raw`)."""


class QueueFullError(ServingError):
    """Bounded request queue at capacity — backpressure, not failure.

    ``retry_after_s`` is the runtime's drain estimate (queued rows over the
    recent scoring throughput); the REST layer ships it as the standard
    Retry-After header so well-behaved clients back off instead of
    retry-storming."""

    def __init__(self, model_id: str, depth: int, retry_after_s: float):
        super().__init__(
            f"serving queue for '{model_id}' is full ({depth} pending "
            f"requests); retry in ~{retry_after_s:.2f}s")
        self.model_id = model_id
        self.retry_after_s = retry_after_s


class AdmissionError(ServingError):
    """The fleet HBM quota cannot place this model, even after evicting
    every cold placement — retryable-later, not a server fault.

    ``retry_after_s`` is a crude drain suggestion: quota pressure clears
    when a registration is dropped or a cold model ages out, both
    operator-timescale events, so the estimate is deliberately coarse."""

    def __init__(self, model_id: str, cost_bytes: int, budget_bytes: int,
                 used_bytes: int, retry_after_s: float = 5.0):
        super().__init__(
            f"serving quota cannot place '{model_id}': needs "
            f"{cost_bytes} B but only {max(budget_bytes - used_bytes, 0)} "
            f"of {budget_bytes} B remain (H2O_TPU_SERVING_QUOTA_FRACTION) "
            f"— unregister or demote a model to 'cold', or raise the "
            f"quota")
        self.model_id = model_id
        self.cost_bytes = cost_bytes
        self.budget_bytes = budget_bytes
        self.used_bytes = used_bytes
        self.retry_after_s = retry_after_s


class RouteNotFoundError(ServingError, KeyError):
    def __init__(self, endpoint: str):
        super().__init__(f"no serving route '{endpoint}' — create it via "
                         f"POST /3/Serving/routes/{endpoint} first")
        self.endpoint = endpoint

    def __str__(self):  # KeyError would repr() the message
        return self.args[0]


class DeadlineExceededError(ServingError, TimeoutError):
    """The request's deadline expired before its batch was scored."""

    def __init__(self, model_id: str, deadline_ms: float):
        super().__init__(
            f"serving request to '{model_id}' missed its {deadline_ms:.0f}ms "
            f"deadline while queued")
        self.model_id = model_id
        self.deadline_ms = deadline_ms


class ServingShutdownError(ServingError):
    """Submitted to a batcher that has been stopped/unregistered."""
