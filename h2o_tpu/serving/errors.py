"""Typed serving-runtime errors — the wire contract's failure surface.

Every failure mode a caller can act on gets its own type, because the REST
layer maps types to status codes (`api/server.py` Serving routes): an
overloaded queue is retryable-later (429 + Retry-After), an expired
deadline is a per-request timeout (408), an unknown model is a 404. A
generic exception would collapse all three into a 500 and the client could
only guess.
"""

from __future__ import annotations


class ServingError(Exception):
    """Base of every serving-runtime error."""


class ModelNotRegisteredError(ServingError, KeyError):
    def __init__(self, model_id: str):
        super().__init__(f"no serving model '{model_id}' — register it via "
                         f"POST /3/Serving/models/{model_id} first")
        self.model_id = model_id

    def __str__(self):  # KeyError would repr() the message
        return self.args[0]


class UnsupportedModelError(ServingError, TypeError):
    """The model has no raw-matrix scoring path (`Model.score_raw`)."""


class QueueFullError(ServingError):
    """Bounded request queue at capacity — backpressure, not failure.

    ``retry_after_s`` is the runtime's drain estimate (queued rows over the
    recent scoring throughput); the REST layer ships it as the standard
    Retry-After header so well-behaved clients back off instead of
    retry-storming."""

    def __init__(self, model_id: str, depth: int, retry_after_s: float):
        super().__init__(
            f"serving queue for '{model_id}' is full ({depth} pending "
            f"requests); retry in ~{retry_after_s:.2f}s")
        self.model_id = model_id
        self.retry_after_s = retry_after_s


class DeadlineExceededError(ServingError, TimeoutError):
    """The request's deadline expired before its batch was scored."""

    def __init__(self, model_id: str, deadline_ms: float):
        super().__init__(
            f"serving request to '{model_id}' missed its {deadline_ms:.0f}ms "
            f"deadline while queued")
        self.model_id = model_id
        self.deadline_ms = deadline_ms


class ServingShutdownError(ServingError):
    """Submitted to a batcher that has been stopped/unregistered."""
