"""Weighted + canary routing — one logical endpoint, many model variants.

The last piece of the control plane: clients score against a ROUTE
(`POST /3/Serving/routes/{endpoint}` maps an endpoint name onto weighted
variants — e.g. champion 0.99 / canary 0.01), and the router picks the
serving variant per request with a DETERMINISTIC seeded split: variant
choice is a pure function of ``(route seed, request ordinal)`` via a
splitmix64 hash, so a fixed seed replays the exact same variant sequence
(the split-count tests pin this) while the long-run fractions converge to
the weights. No RNG state, no lock on the choice itself.

**Shadow traffic**: a variant marked ``shadow`` scores the SAME rows as
the serving variant, off the response path — a single bounded background
worker drains shadow jobs, the response is returned before (and entirely
independent of) shadow scoring, and the shadow queue sheds load by
dropping jobs (counted) rather than ever blocking a caller. Shadow
results feed per-variant DIVERGENCE stats: per-row |prediction delta|
against the primary's predictions (histogram through the PR 6 registry +
a per-variant window surfaced in ``GET /3/Serving/routes``), plus a
disagreement counter for categorical label flips — the canary drift
monitor.

The router holds model IDs, not model objects: a variant resolves through
the runtime at request time, so re-registration (or a canary's cold
re-placement) is picked up with no route edit, and deleting a model makes
its routes fail loudly with the model-not-registered 404.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from ..utils import knobs, telemetry
from .errors import RouteNotFoundError


def _splitmix64(x: int) -> int:
    """splitmix64 finalizer — the standard 64-bit avalanche (same family
    the mesh RNG folding uses); pure integer math, no numpy RNG object."""
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return z ^ (z >> 31)


def _unit(seed: int, ordinal: int) -> float:
    """Deterministic uniform in [0, 1) for request ``ordinal`` under
    ``seed`` — the whole split policy, auditable in four lines."""
    h = _splitmix64((seed & 0xFFFFFFFFFFFFFFFF) ^
                    _splitmix64(ordinal & 0xFFFFFFFFFFFFFFFF))
    return (h >> 11) / float(1 << 53)


def _pred_scalar(pred: dict) -> float:
    """One comparable number per prediction dict: P(class 0) for
    classifiers (delta in probability space, not label space), the value
    for regression, the cluster index for clustering."""
    probs = pred.get("classProbabilities")
    if probs:
        return float(probs[0])
    if "value" in pred:
        return float(pred["value"])
    if "cluster" in pred:
        return float(pred["cluster"])
    vals = pred.get("values")
    return float(vals[0]) if vals else float("nan")


def _pred_label(pred: dict):
    return pred.get("label", pred.get("cluster"))


class Variant:
    __slots__ = ("model_id", "weight", "shadow", "requests", "rows",
                 "shadow_rows", "disagreements", "_deltas", "_lock")

    def __init__(self, model_id: str, weight: float, shadow: bool,
                 window: int = 1024):
        self.model_id = model_id
        self.weight = float(weight)
        self.shadow = bool(shadow)
        self.requests = 0               # times picked as the serving variant
        self.rows = 0
        self.shadow_rows = 0            # rows this variant shadow-scored
        self.disagreements = 0          # label flips vs the primary
        self._deltas: deque = deque(maxlen=window)
        self._lock = threading.Lock()

    def note_served(self, rows: int) -> None:
        with self._lock:
            self.requests += 1
            self.rows += rows

    def note_shadow(self, deltas: list[float], disagreements: int) -> None:
        with self._lock:
            self.shadow_rows += len(deltas)
            self.disagreements += disagreements
            self._deltas.extend(deltas)

    def stats(self) -> dict:
        with self._lock:
            deltas = np.asarray(self._deltas, dtype=np.float64)
            out = {
                "model_id": self.model_id, "weight": self.weight,
                "shadow": self.shadow, "requests": self.requests,
                "rows": self.rows, "shadow_rows": self.shadow_rows,
                "disagreements": self.disagreements,
            }
        if deltas.size:
            p50, p95, p99 = (float(v) for v in
                             np.percentile(deltas, (50, 95, 99)))
            out["divergence"] = {
                "window": int(deltas.size),
                "mean": round(float(deltas.mean()), 9),
                "p50": round(p50, 9), "p95": round(p95, 9),
                "p99": round(p99, 9), "max": round(float(deltas.max()), 9),
            }
        else:
            out["divergence"] = None
        return out


class Route:
    def __init__(self, endpoint: str, variants: list[Variant], seed: int):
        import math

        for v in variants:
            # a negative weight would push a cumulative edge past 1 and
            # starve a variant silently; a NaN poisons every comparison —
            # both must be a loud 400, not a quietly-wrong split
            if not math.isfinite(v.weight) or v.weight < 0:
                raise ValueError(
                    f"route '{endpoint}': variant '{v.model_id}' has "
                    f"invalid weight {v.weight!r} (must be finite and "
                    f">= 0)")
        if not any(v.weight > 0 for v in variants if not v.shadow):
            raise ValueError(
                f"route '{endpoint}' needs at least one serving variant "
                f"with weight > 0 (shadow variants never serve)")
        self.endpoint = endpoint
        self.variants = variants
        self.seed = int(seed)
        self.created_at = time.time()
        self._ordinal = 0
        self._lock = threading.Lock()
        total = sum(v.weight for v in variants if not v.shadow)
        #: cumulative weight edges over the serving (non-shadow) variants
        self._serving = [v for v in variants if not v.shadow]
        acc, self._edges = 0.0, []
        for v in self._serving:
            acc += v.weight / total
            self._edges.append(acc)
        self._edges[-1] = 1.0 + 1e-12   # float-sum slack: u<1 always lands

    def pick(self) -> tuple[Variant, int]:
        """The serving variant for the next request (deterministic in
        arrival order) and the request's ordinal."""
        with self._lock:
            ordinal = self._ordinal
            self._ordinal += 1
        u = _unit(self.seed, ordinal)
        for v, edge in zip(self._serving, self._edges):
            if u < edge:
                return v, ordinal
        return self._serving[-1], ordinal        # unreachable (slack edge)

    def shadows_for(self, primary: Variant) -> list[Variant]:
        """Every variant that shadow-scores this request: explicit shadow
        variants, never the one that just served (self-divergence is 0)."""
        return [v for v in self.variants
                if v.shadow and v.model_id != primary.model_id]

    def stats(self) -> dict:
        with self._lock:
            ordinal = self._ordinal
        return {"endpoint": self.endpoint, "seed": self.seed,
                "requests": ordinal,
                "variants": [v.stats() for v in self.variants]}


class Router:
    """Route table + the shadow worker, owned by one ServingRuntime."""

    #: bounded shadow backlog — beyond it, shadow jobs DROP (counted);
    #: shadow is observability, it must never become backpressure
    SHADOW_QUEUE = 256

    def __init__(self, runtime):
        self._runtime = runtime
        self._routes: dict[str, Route] = {}
        self._lock = threading.Lock()
        self._shadow_q: deque = deque()
        self._shadow_cv = threading.Condition()
        self._shadow_stop = False
        self._shadow_busy = False
        self._shadow_worker: threading.Thread | None = None

    # -- route table ---------------------------------------------------------
    def create_route(self, endpoint: str, variants: list[dict],
                     seed: int | None = None) -> dict:
        """Create/replace a route. ``variants`` are dicts with ``model_id``,
        ``weight`` (serving share; ignored for shadows) and optional
        ``shadow``. Every named model must already be registered."""
        if not variants:
            raise ValueError("a route needs at least one variant")
        built = []
        for v in variants:
            mid = v.get("model_id")
            if not mid:
                raise ValueError("every route variant needs a model_id")
            self._runtime.model(mid)        # 404s on unknown models NOW
            built.append(Variant(mid, float(v.get("weight", 0.0)),
                                 bool(v.get("shadow", False))))
        if seed is None:
            seed = knobs.get_int("H2O_TPU_SERVING_ROUTE_SEED")
        route = Route(endpoint, built, seed)
        with self._lock:
            self._routes[endpoint] = route
        return route.stats()

    def delete_route(self, endpoint: str) -> None:
        with self._lock:
            if self._routes.pop(endpoint, None) is None:
                raise RouteNotFoundError(endpoint)

    def route(self, endpoint: str) -> Route:
        with self._lock:
            r = self._routes.get(endpoint)
        if r is None:
            raise RouteNotFoundError(endpoint)
        return r

    def endpoints(self) -> list[str]:
        with self._lock:
            return list(self._routes)

    def stats(self, endpoint: str | None = None) -> dict:
        if endpoint is not None:
            return self.route(endpoint).stats()
        with self._lock:
            routes = list(self._routes.values())
        return {"routes": [r.stats() for r in routes]}

    # -- request path --------------------------------------------------------
    def score(self, endpoint: str, rows: list, deadline_ms=None) -> tuple:
        """Score ``rows`` through the endpoint's picked variant; returns
        ``(predictions, variant_model_id)``. Shadow scoring of the same
        rows is enqueued AFTER the primary result exists and cannot touch
        it — the bit-parity contract is structural, not best-effort."""
        route = self.route(endpoint)
        variant, _ = route.pick()
        preds = self._runtime.score(variant.model_id, rows,
                                    deadline_ms=deadline_ms)
        variant.note_served(len(rows))
        telemetry.inc("serving.route.count")
        shadows = route.shadows_for(variant)
        if shadows and knobs.get_bool("H2O_TPU_SERVING_SHADOW"):
            self._enqueue_shadow(route, variant, shadows, rows, preds)
        return preds, variant.model_id

    # -- shadow path ---------------------------------------------------------
    def _enqueue_shadow(self, route, primary, shadows, rows, preds) -> None:
        with self._shadow_cv:
            if self._shadow_stop:
                return
            if len(self._shadow_q) >= self.SHADOW_QUEUE:
                telemetry.inc("serving.route.shadow.dropped.count")
                return
            # primary predictions ride along BY VALUE: the shadow compare
            # can never reach back into the response. The span context is
            # carried PER JOB (captured here, in the request thread): the
            # long-lived worker must not pin its FIRST request's context
            # forever and attribute every later shadow score to a
            # long-dead trace
            job = telemetry.carry_context(self._score_one_shadow)
            self._shadow_q.append((job, primary, shadows,
                                   list(rows), list(preds)))
            if self._shadow_worker is None:
                # the worker's own (span-free) loop still adopts a
                # context for the rule-24 contract; real causality rides
                # the per-job wrappers above
                self._shadow_worker = threading.Thread(
                    target=telemetry.carry_context(self._shadow_run),
                    daemon=True, name="h2o-serving-shadow")
                self._shadow_worker.start()
            self._shadow_cv.notify()

    def _score_one_shadow(self, primary, shadows, rows, preds) -> None:
        base = [_pred_scalar(p) for p in preds]
        base_labels = [_pred_label(p) for p in preds]
        for v in shadows:
            try:
                # slo=False: shadow work is droppable by definition — it
                # must not feed the serving.score SLO window, flip
                # /3/Health to slo-burn, or pollute the slow-trace ring
                sh = self._runtime.score(v.model_id, rows, slo=False)
            except Exception:   # model gone / overloaded: shadow work
                continue        # is droppable by definition
            deltas = [abs(_pred_scalar(p) - b)
                      for p, b in zip(sh, base)]
            dis = sum(1 for p, lb in zip(sh, base_labels)
                      if _pred_label(p) != lb)
            v.note_shadow(deltas, dis)
            telemetry.inc("serving.route.shadow.rows", len(deltas))
            for d in deltas:
                telemetry.observe("serving.route.divergence", d)

    def _shadow_run(self) -> None:
        while True:
            with self._shadow_cv:
                self._shadow_busy = False
                self._shadow_cv.notify_all()     # drain_shadow waiters
                while not self._shadow_q and not self._shadow_stop:
                    self._shadow_cv.wait()
                if self._shadow_stop and not self._shadow_q:
                    return
                job, primary, shadows, rows, preds = \
                    self._shadow_q.popleft()
                self._shadow_busy = True
            job(primary, shadows, rows, preds)

    def drain_shadow(self, timeout_s: float = 10.0) -> bool:
        """Block until the shadow queue is empty AND the worker is idle
        (tests pin divergence stats after this; True on drained)."""
        deadline = time.monotonic() + timeout_s
        with self._shadow_cv:
            while self._shadow_q or self._shadow_busy:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._shadow_cv.wait(left)
            return True

    def shutdown(self) -> None:
        with self._shadow_cv:
            self._shadow_stop = True
            self._shadow_q.clear()
            self._shadow_cv.notify_all()
            # read the handle under the cv: _enqueue_shadow can't create a
            # worker after _shadow_stop lands, so this is the last word
            w = self._shadow_worker
        if w is not None:
            w.join(timeout=5.0)


__all__ = ["Router", "Route", "Variant"]
