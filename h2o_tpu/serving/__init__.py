"""Online scoring runtime + serving control plane.

The inference-stack counterpart of the batched training driver: per-model
shape-bucketed AOT-compiled scorers (zero steady-state XLA compiles),
a bounded micro-batching scheduler with deadlines and backpressure, and a
stats surface — wired to REST as ``POST /3/Serving/models/{id}``,
``POST /3/Serving/score`` and ``GET /3/Serving/stats`` (`api/server.py`).

Since PR 8 the runtime is fleet-operable: `control.py` adds HBM placement
with admission quotas and hot/cold priority classes, replica scorers
across mesh devices with least-loaded dispatch, and `router.py` adds
weighted + canary endpoint routing with shadow traffic and divergence
stats (``/3/Serving/routes``).

See `runtime.py` for the architecture overview; README "Online scoring" /
"Serving control plane" for the operator-facing contract and knobs.
"""

from .control import ControlPlane, ReplicaSet, estimate_model_bytes
from .errors import (AdmissionError, DeadlineExceededError,
                     ModelNotRegisteredError, QueueFullError,
                     RouteNotFoundError, ServingError, ServingShutdownError,
                     UnsupportedModelError)
from .router import Router
from .runtime import ServedModel, ServingRuntime, get_runtime

__all__ = [
    "ServingRuntime", "ServedModel", "get_runtime",
    "ControlPlane", "ReplicaSet", "Router", "estimate_model_bytes",
    "ServingError", "ModelNotRegisteredError", "UnsupportedModelError",
    "QueueFullError", "DeadlineExceededError", "ServingShutdownError",
    "AdmissionError", "RouteNotFoundError",
]
