"""Online scoring runtime — micro-batched, shape-bucketed, backpressured.

The inference-stack counterpart of the batched training driver: per-model
shape-bucketed AOT-compiled scorers (zero steady-state XLA compiles),
a bounded micro-batching scheduler with deadlines and backpressure, and a
stats surface — wired to REST as ``POST /3/Serving/models/{id}``,
``POST /3/Serving/score`` and ``GET /3/Serving/stats`` (`api/server.py`).

See `runtime.py` for the architecture overview; README "Online scoring"
for the operator-facing contract and knobs.
"""

from .errors import (DeadlineExceededError, ModelNotRegisteredError,
                     QueueFullError, ServingError, ServingShutdownError,
                     UnsupportedModelError)
from .runtime import ServedModel, ServingRuntime, get_runtime

__all__ = [
    "ServingRuntime", "ServedModel", "get_runtime",
    "ServingError", "ModelNotRegisteredError", "UnsupportedModelError",
    "QueueFullError", "DeadlineExceededError", "ServingShutdownError",
]
