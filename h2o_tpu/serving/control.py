"""Serving control plane — placement, admission quotas, replica dispatch.

PR 4's runtime made ONE model fast; this module makes a FLEET operable.
Three cooperating pieces, the classic model-server control plane (Clipper's
adaptive model selection, TF-Serving's version manager) rebuilt on this
repo's substrate:

- **placement + admission** (`ControlPlane`): every registration carries an
  HBM cost estimate (`estimate_model_bytes` — the model's device-resident
  parameter arrays plus each compiled bucket's padded input/output working
  set, × replicas). The fleet may reserve at most
  ``H2O_TPU_SERVING_QUOTA_FRACTION`` of the resolved Cleaner HBM budget
  (`backend/memory.py` — the SAME accounting training planners read;
  placed bytes are reserved there, so frames yield residency to serving and
  vice versa, no second ledger). Two priority classes: ``hot`` pins
  residency for the life of the registration; ``cold`` placements are
  evicted (compiled executables dropped, reservation released) under quota
  pressure and lazily re-placed — paying their bucket compiles again — on
  first hit. A registration that cannot fit even after evicting every cold
  placement is refused with the typed :class:`AdmissionError` (REST: 429 +
  Retry-After), and co-registered models keep scoring untouched.

- **replica scorers** (`Replica`/`ReplicaSet`): a model may place N
  replicas, each a `CompiledScorer` pinned to its own mesh device with its
  own `MicroBatcher` lane. Dispatch is least-loaded by LIVE batcher queue
  depth (the occupancy state the batcher already keeps). A replica whose
  score path faults (the `serving.replica` failpoint stands in for a real
  device loss) is marked dead at the point of failure: no new request is
  routed to it, its queued work drains, and the requests in the poisoned
  batch are transparently re-dispatched to a healthy replica (scoring is
  read-only — replay is safe).

Failpoints: ``serving.place`` fires before placement compiles (arm
``raise(oom)`` to drill the placement-OOM admission path), and
``serving.replica`` fires per replica device call (arm ``raise@K`` to kill
the replica executing the K-th call).
"""

from __future__ import annotations

import threading
import time

from ..utils import failpoints, knobs, telemetry
from .batcher import MicroBatcher
from .errors import (AdmissionError, DeadlineExceededError, QueueFullError,
                     ServingShutdownError)


def quota_fraction() -> float:
    """The serving fleet's share of the resolved HBM budget (knob)."""
    try:
        frac = float(knobs.get_str("H2O_TPU_SERVING_QUOTA_FRACTION"))
    except ValueError:
        frac = 0.35
    return min(max(frac, 0.0), 1.0)


def estimate_model_bytes(model, buckets, n_features: int,
                         replicas: int = 1) -> int:
    """Per-model HBM cost estimate at registration: the model's
    device-resident parameter arrays (walked exactly like the bench sync
    contract — `utils/blocking.device_arrays`) plus each compiled bucket's
    padded f32 input + output working set, everything × replicas (each
    replica holds its own executables and runs its own padded batches)."""
    from ..utils.blocking import device_arrays

    params = sum(a.size * a.dtype.itemsize for a in device_arrays(model))
    # per bucket: one (b, F) f32 input + a same-order output/scratch bound
    working = sum(2 * b * max(int(n_features), 1) * 4 for b in buckets)
    return (params + working) * max(int(replicas), 1)


# ---------------------------------------------------------------------------
# placement + admission
# ---------------------------------------------------------------------------
class Placement:
    __slots__ = ("model_id", "priority", "replicas", "cost_bytes",
                 "placed", "last_hit", "evictions")

    def __init__(self, model_id: str, priority: str, replicas: int,
                 cost_bytes: int):
        self.model_id = model_id
        self.priority = priority        # "hot" | "cold"
        self.replicas = replicas
        self.cost_bytes = int(cost_bytes)
        self.placed = True
        self.last_hit = time.monotonic()
        self.evictions = 0              # observability: times deplaced

    def info(self) -> dict:
        return {"priority": self.priority, "replicas": self.replicas,
                "cost_bytes": self.cost_bytes, "placed": self.placed,
                "evictions": self.evictions}


class ControlPlane:
    """Fleet-wide placement ledger + admission gate.

    ``deplacer`` (set by the runtime) is called OUTSIDE the ledger lock
    with a model_id whose cold placement lost its residency — it drops the
    model's compiled executables so the freed estimate is real."""

    def __init__(self):
        from ..utils.sanitizer import make_lock

        self._lock = make_lock("ControlPlane._lock")
        self._placements: dict[str, Placement] = {}
        self.deplacer = None            # callable(model_id) -> None

    # -- budget ---------------------------------------------------------------
    def budget_bytes(self) -> int | None:
        """Fleet quota: fraction × the PRE-reservation Cleaner budget
        (`memory.base_hbm_limit_bytes`); None = no resolvable budget
        (CPU without ``H2O_TPU_HBM_LIMIT_BYTES``) = admission is open."""
        from ..backend import memory

        base = memory.base_hbm_limit_bytes()
        if base is None:
            return None
        return int(base * quota_fraction())

    def placed_bytes(self) -> int:
        with self._lock:
            return sum(p.cost_bytes for p in self._placements.values()
                       if p.placed)

    # -- admission ------------------------------------------------------------
    def admit(self, model_id: str, cost_bytes: int, priority: str,
              replicas: int = 1) -> Placement:
        """Place (or re-place) ``model_id`` under the fleet quota, evicting
        cold placements LRU-by-last-hit if that makes it fit; raises the
        typed :class:`AdmissionError` when it cannot."""
        failpoints.hit("serving.place")
        if priority not in ("hot", "cold"):
            raise ValueError(f"unknown serving priority {priority!r} — "
                             f"'hot' or 'cold'")
        budget = self.budget_bytes()
        from ..backend import memory

        evict: list[str] = []
        with self._lock:
            prior = self._placements.get(model_id)
            used = sum(p.cost_bytes for p in self._placements.values()
                       if p.placed and p.model_id != model_id)
            if budget is not None and used + cost_bytes > budget:
                # cold placements yield, coldest hit first — never the
                # model being admitted, never a hot pin
                colds = sorted(
                    (p for p in self._placements.values()
                     if p.placed and p.priority == "cold"
                     and p.model_id != model_id),
                    key=lambda p: p.last_hit)
                for p in colds:
                    if used + cost_bytes <= budget:
                        break
                    p.placed = False
                    p.evictions += 1
                    used -= p.cost_bytes
                    evict.append(p.model_id)
                if used + cost_bytes > budget:
                    for mid in evict:    # roll back: admission failed, the
                        pl = self._placements[mid]   # colds keep serving
                        pl.placed = True
                        pl.evictions -= 1
                    telemetry.inc("serving.admission.rejected.count")
                    # serving placement is the hottest arrival there is:
                    # ask the workload tier to preempt its weakest
                    # running training job at the next chunk boundary so
                    # the client's Retry-After retry finds the HBM free.
                    # No-op (one module-global read) without a manager.
                    from .. import workload as _workload

                    _workload.note_serving_pressure()
                    raise AdmissionError(model_id, cost_bytes, budget, used)
            pl = Placement(model_id, priority, replicas, cost_bytes)
            if prior is not None:
                pl.evictions = prior.evictions
            self._placements[model_id] = pl
        for mid in evict:
            telemetry.inc("serving.placement.evicted.count")
            memory.release_bytes(f"serving:{mid}")
            if self.deplacer is not None:
                self.deplacer(mid)
        memory.reserve_bytes(f"serving:{model_id}", cost_bytes)
        return pl

    def release(self, model_id: str) -> None:
        from ..backend import memory

        with self._lock:
            self._placements.pop(model_id, None)
        memory.release_bytes(f"serving:{model_id}")

    def restore(self, placement: Placement) -> None:
        """Reinstate a placement after a failed REPLACEMENT attempt: the
        prior registration is still installed and serving, so its ledger
        entry and reservation must survive the failed admit/warmup of its
        would-be successor."""
        from ..backend import memory

        with self._lock:
            self._placements[placement.model_id] = placement
        memory.reserve_bytes(f"serving:{placement.model_id}",
                             placement.cost_bytes)

    def note_hit(self, model_id: str) -> None:
        with self._lock:
            p = self._placements.get(model_id)
            if p is not None:
                p.last_hit = time.monotonic()

    def placement(self, model_id: str) -> Placement | None:
        with self._lock:
            return self._placements.get(model_id)

    def snapshot(self) -> dict:
        budget = self.budget_bytes()
        with self._lock:
            placements = {mid: p.info()
                          for mid, p in sorted(self._placements.items())}
            used = sum(p.cost_bytes for p in self._placements.values()
                       if p.placed)
        return {"budget_bytes": budget, "placed_bytes": used,
                "quota_fraction": quota_fraction(),
                "placements": placements}


# ---------------------------------------------------------------------------
# replica scorers — least-loaded dispatch with death detection
# ---------------------------------------------------------------------------
class Replica:
    """One scorer lane: a (possibly device-pinned) scorer + its batcher.

    The score wrapper is the death detector: ANY exception on the batch
    score path (a real device loss, or the ``serving.replica`` failpoint
    standing in for one) marks the replica dead at the point of failure —
    shape/encoding errors can't reach here, the encoder and bucket pads
    fix the matrix shape before submit."""

    def __init__(self, idx: int, device, scorer, stats, cfg: dict,
                 model_id: str):
        self.idx = idx
        self.device = device
        self.scorer = scorer
        # death is a monotone flip read by request threads while the batch
        # worker writes it — an Event makes the handoff a synchronized
        # publication instead of a bare bool (graftlint
        # unguarded-shared-field GL14-replica-dead); the small lock makes
        # the first-observation COUNT atomic too (Event has no test-and-set)
        self._dead = threading.Event()
        self._death_note = threading.Lock()
        self.batcher = MicroBatcher(
            f"{model_id}#r{idx}", self._score, stats,
            max_batch=min(cfg["max_batch"], max(scorer.buckets)),
            max_wait_us=cfg["max_wait_us"],
            queue_depth=cfg["queue_depth"],
            recompile_probe=lambda: scorer.fallback_compiles)

    @property
    def dead(self) -> bool:
        return self._dead.is_set()

    def mark_dead(self) -> None:
        """Idempotent death flip; counts the FIRST observation only
        (test-and-set under the lock — two concurrently-failing batches
        must report ONE death)."""
        with self._death_note:
            if self._dead.is_set():
                return
            self._dead.set()
        telemetry.inc("serving.replica.dead.count")

    def _score(self, X):
        try:
            failpoints.hit("serving.replica")
            return self.scorer.score(X)
        except Exception:
            self.mark_dead()
            raise

    def info(self) -> dict:
        return {"replica": self.idx,
                "device": str(self.device) if self.device is not None
                else "default",
                "queue_depth": self.batcher.depth,
                "dead": self.dead}


class ReplicaSet:
    """N replica lanes behind one submit surface.

    Dispatch picks the healthy replica with the smallest live queue depth
    (ties break toward the lowest index — deterministic). A submit that
    fails because its replica died mid-batch is re-dispatched once per
    remaining healthy replica; typed backpressure (queue full, deadline)
    propagates untouched — those are the CALLER's signals, not faults."""

    def __init__(self, replicas: list[Replica]):
        self.replicas = replicas

    @property
    def depth(self) -> int:
        return sum(r.batcher.depth for r in self.replicas if not r.dead)

    def healthy(self) -> list[Replica]:
        return [r for r in self.replicas if not r.dead]

    def pick(self) -> Replica | None:
        alive = self.healthy()
        if not alive:
            return None
        return min(alive, key=lambda r: (r.batcher.depth, r.idx))

    def submit(self, X, deadline_s):
        tried: set[int] = set()
        last: Exception | None = None
        while True:
            alive = [r for r in self.healthy() if r.idx not in tried]
            if not alive:
                if last is not None:
                    raise last
                raise ServingShutdownError(
                    "every replica of this serving model is dead")
            rep = min(alive, key=lambda r: (r.batcher.depth, r.idx))
            try:
                return rep.batcher.submit(X, deadline_s)
            except (QueueFullError, DeadlineExceededError):
                raise                     # backpressure, not replica death
            except Exception as e:        # noqa: BLE001 — reroute decision
                if not rep.dead:
                    raise                 # a non-death fault: surface it
                tried.add(rep.idx)
                last = e
                telemetry.inc("serving.replica.reroute.count")

    def pause(self) -> None:
        for r in self.replicas:
            r.batcher.pause()

    def resume(self) -> None:
        for r in self.replicas:
            r.batcher.resume()

    def stop(self) -> None:
        for r in self.replicas:
            r.batcher.stop()

    def info(self) -> list[dict]:
        return [r.info() for r in self.replicas]


def replica_devices(n: int) -> list:
    """Round-robin device placement for ``n`` replicas. A single replica
    keeps the backend-default placement (None) — today's single-model path
    byte-for-byte; multi-replica sets pin one device each so two replicas
    never contend for the same chip."""
    if n <= 1:
        return [None]
    import jax

    devs = jax.devices()
    return [devs[i % len(devs)] for i in range(n)]
