"""h2o_tpu — a TPU-native distributed ML platform with the capabilities of H2O-3.

From-scratch JAX/XLA/Pallas design (see SURVEY.md for the blueprint): frames are
row-sharded JAX arrays over a device mesh, the MRTask compute driver is
shard_map + XLA collectives, and algorithms (GBM/DRF, GLM, KMeans, PCA, ...) run
their hot loops on the MXU.
"""

from .backend.jobs import Job, JobCancelled, JobTimeoutError
from .backend.kvstore import STORE, Keyed, KVStore, make_key
from .frame.frame import Frame
from .frame.vec import Vec
from .parallel import mesh
from .parallel.mesh import default_mesh, make_mesh, use_mesh
from .parallel.mrtask import mr_map, mr_reduce

__version__ = "0.1.0"


def resume_training(recovery_dir: str):
    """Restart a killed training job from its auto-recovery dir (lazy
    import — the models package is heavy and most sessions never resume)."""
    from .models.model_base import resume_training as _resume

    return _resume(recovery_dir)


__all__ = [
    "Frame", "Vec", "Job", "JobCancelled", "JobTimeoutError", "STORE",
    "Keyed", "KVStore",
    "make_key", "mesh", "default_mesh", "make_mesh", "use_mesh",
    "mr_map", "mr_reduce", "resume_training", "__version__",
]
